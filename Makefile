# The runnable test matrix (ref Makefile:3-27 build/test vs sbuild/stest;
# .github/workflows/ci.yml encodes the same legs for CI).
#
#   make test          sim suite, compiled C executor core (the default)
#   make test-nonative sim suite again with MADSIM_NO_NATIVE=1 (pure-Python
#                      loop; schedules must be byte-identical)
#   make test-real     real-mode legs only (asyncio + real sockets + grpcio
#                      wire + real fs/signal/process)
#   make test-procs    forked-process sweep smoke (fail-fast, jax guard)
#   make explore-smoke the explore pipeline end to end on a tiny budget
#                      (CPU backend, fixed campaign seed: find -> triage
#                      -> shrink against the amnesia raft target)
#   make oracle-smoke  the history-oracle pipeline end to end (seeded
#                      etcd bug -> linearizability checker -> triage ->
#                      shrink -> cross-path history byte identity)
#   make differential-smoke
#                      host<->device differential gate: matched
#                      (spec, seed) grids incl. every gray-failure
#                      family, outcome distributions within tolerances,
#                      both tiers' histories checked by one spec
#   make wire-smoke    heavy-traffic wire gate, both tiers: the sim-tier
#                      Kafka leg (concurrent genuine-protocol clients
#                      against the sim broker under a latency burst,
#                      LogSpec-checked history, live-vs-replay byte
#                      identity, differential-fuzz sweep) plus the async
#                      serving core's load rig at small scale (worker
#                      processes, kafka+s3+etcd wires, chaos mid-run,
#                      oracle-checked histories, async-vs-legacy
#                      transcript parity — docs/wire.md)
#   make multichip-smoke
#                      sharded checked-sweep pipeline on the CPU host
#                      mesh: device-count curve + a small sharded
#                      campaign, summary/report bytes asserted
#                      identical across mesh sizes
#   make stream-smoke  persistent streaming sweep service
#                      (docs/streaming.md): stream == chunked report
#                      bytes, refill-schedule invariance, v9
#                      interrupt/resume, zero-compile warmed stream
#   make obs-smoke     fleet telemetry (docs/observability.md): reports
#                      byte-equal with telemetry on/off, Perfetto trace
#                      with visible device/host overlap + stream refill
#                      cadence, run journal, live /metrics endpoint,
#                      device-side event-mix plane
#   make fleet-smoke   crash-safe fleet orchestrator (docs/fleet.md):
#                      shared corpus store across two processes ==
#                      solo bytes, strictly more fingerprints than
#                      either worker alone, kill -9 mid-append + lease
#                      reclaim, regression-replay gate
#   make steer-smoke   self-steering scheduler (docs/steering.md):
#                      bandit campaign report + decision trace replayed
#                      byte-identical (telemetry on/off), journaled
#                      steer_round mirror, and the adaptive-vs-uniform
#                      A/B at a matched device-event budget (>= 1.5x
#                      distinct fingerprints)
#   make stest         sim suite + determinism smoke gate (a fault-campaign
#                      sweep twice in two processes, traces byte-diffed;
#                      plus two campaign runs, JSONL reports byte-diffed;
#                      plus two history decodes, bytes diffed; plus the
#                      pipelined checked-sweep report across two
#                      processes x two worker-pool sizes AND two mesh
#                      sizes, byte-diffed)
#                      + explore-smoke + oracle-smoke + multichip-smoke
#                      + stream-smoke
#   make dryrun        multi-chip gate: 8-device mesh, sharded==unsharded
#                      and chunked==unsharded per-seed equality
#   make bench-smoke   the whole bench pipeline on tiny shapes (~1 min)
#   make test-all      every leg above, in order
#
# PYTEST_ARGS passes extra pytest flags to the suite legs, e.g.
#   make test PYTEST_ARGS="-k unix -v"

PY ?= python
PYTEST ?= $(PY) -m pytest
PYTEST_ARGS ?=

.PHONY: test test-nonative test-real test-procs stest determinism \
	explore-smoke oracle-smoke differential-smoke wire-smoke \
	multichip-smoke stream-smoke obs-smoke fleet-smoke steer-smoke \
	dryrun bench-smoke test-all

test:
	$(PYTEST) tests/ -q $(PYTEST_ARGS)

determinism:
	PY=$(PY) bash scripts/check_determinism.sh

# campaign seed 5 on purpose: tests/test_explore.py already runs the
# seed-1 campaign, so the gate explores a second mutation path instead
# of paying ~70 s to repeat the same deterministic computation
explore-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/explore_demo.py \
	  --rounds 6 --seeds-per-round 128 --campaign-seed 5 \
	  --assert-zero-recompile

# the history-oracle pipeline end to end (docs/oracle.md): seeded etcd
# stale-read bug -> WGL checker rejects -> history-flavor triage ->
# checker-verified shrink -> sweep/traced byte identity -> clean control;
# then the checked sweep once more through the on-device decode kernel
# (docs/oracle.md "Device-side checking")
oracle-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/oracle_demo.py
	JAX_PLATFORMS=cpu $(PY) scripts/checked_sweep_demo.py --seeds 96 \
		--chunk-size 32 --device-decode --report /dev/null

# host<->device differential gate (docs/faults.md "Gray failures"): a
# 200-seed matched-(spec, seed) grid per fault family — crash storm +
# asymmetric partitions + fsync-stall/power-fail + clock skew — outcome
# distributions within tolerances, election histories checked against
# one sequential spec on both tiers
differential-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/differential_demo.py

# the kafka wire under concurrent genuine-protocol load + fuzz
# (scripts/wire_load_demo.py docstring has the three determinism claims),
# then the async serving core's rig at small scale: worker processes x
# kafka+s3+etcd wires, gray failure mid-run, oracle-checked histories,
# replay identity, async-vs-legacy parity (scripts/wire_load.py --smoke)
wire-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/wire_load_demo.py
	$(PY) scripts/wire_load_demo.py --fuzz 12
	JAX_PLATFORMS=cpu $(PY) scripts/wire_load.py --smoke

# the sharded checked-sweep pipeline on the CPU host mesh
# (docs/multichip.md): device-count curve + small campaign, bytes
# asserted identical across mesh sizes
multichip-smoke:
	$(PY) scripts/multichip_campaign.py --smoke

# the persistent streaming sweep service (docs/streaming.md): stream ==
# chunked bytes, refill-schedule invariance, v9 interrupt/resume,
# zero-compile warmed stream
stream-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/stream_smoke.py

# the fleet telemetry subsystem (docs/observability.md): out-of-band
# reports, Perfetto trace artifact, journal, exposition, event mix
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/obs_smoke.py

# the crash-safe fleet orchestrator (docs/fleet.md): solo-vs-shared-store
# merged-report byte identity, two workers strictly beating either alone,
# kill -9 mid-append + lease reclaim, regression-replay gate
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/fleet_smoke.py

# the self-steering scheduler (docs/steering.md): replayed bandit
# campaign byte-identity (report + decision trace, telemetry on/off),
# the journal's steer_round mirror, and the matched-budget
# adaptive-vs-uniform fingerprint A/B
steer-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/steer_demo.py

stest: test determinism explore-smoke oracle-smoke differential-smoke \
	wire-smoke multichip-smoke stream-smoke obs-smoke fleet-smoke \
	steer-smoke

test-nonative:
	MADSIM_NO_NATIVE=1 $(PYTEST) tests/ -q $(PYTEST_ARGS)

test-real:
	$(PYTEST) tests/test_real.py tests/test_real_grpc.py \
	  tests/test_real_grpcio.py tests/test_real_etcd.py \
	  tests/test_real_kafka_s3.py tests/test_real_fs_signal.py \
	  tests/test_etcd_wire.py tests/test_s3_wire.py \
	  tests/test_kafka_wire.py tests/test_wire_differential.py \
	  -q $(PYTEST_ARGS)

test-procs:
	$(PYTEST) tests/test_builder.py -q -k procs $(PYTEST_ARGS)

dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench-smoke:
	$(PY) bench.py --smoke

test-all: test test-nonative test-real test-procs dryrun bench-smoke
	@echo "test matrix: ALL LEGS GREEN"
