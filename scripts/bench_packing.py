"""A/B: packed queue layout (occupancy in the time plane) vs the legacy
layout (explicit bool valid[Q] plane in the loop carry).

Round-4's verdict asked for a measured answer on state packing in the
bandwidth-bound 64k regime (docs/pallas_finding.md §4: 0.04 µs/seed/step,
the loop carry streams through HBM every event). The shipped round-5
packing drops the one redundant plane — valid[Q] duplicates
``time == INVALID_TIME`` — cutting Q bytes/seed of carry plus a leaf of
XLA carry bookkeeping, with bit-identical schedules by construction
(tests/test_engine.py::test_legacy_queue_layout_bit_identical).

Methodology per docs/pallas_finding.md §0: both layouts compile side by
side (EngineConfig.legacy_queue is a static jit arg), reps interleave
A/B/A/B in one process (the tunneled chip drifts ±30% over minutes),
fresh seeds per timed call, completion bounded by a scalar readback,
min-of-REPS reported with spread.

Run on the TPU:  python scripts/bench_packing.py
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp

from madsim_tpu.engine import core
from madsim_tpu.models import raft

BATCHES = (16384, 65536)
REPS = 5
SIM_SECONDS = 3.0

_seed_base = [1]


def fresh_seeds(n: int) -> jnp.ndarray:
    lo = _seed_base[0]
    _seed_base[0] += n
    return jnp.arange(lo, lo + n, dtype=jnp.int64)


def main() -> None:
    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    packed_cfg = raft.engine_config(cfg, time_limit_ns=int(SIM_SECONDS * 1e9))
    legacy_cfg = packed_cfg._replace(legacy_queue=1)
    wl = raft.workload(cfg)
    print(f"# devices: {jax.devices()}", file=sys.stderr)

    variants = {"packed": packed_cfg, "legacy": legacy_cfg}
    results = []
    for S in BATCHES:
        # warmup/compile each variant once, and verify bit-equality of the
        # two layouts on a shared seed batch before timing anything
        vseeds = fresh_seeds(S)
        finals = {}
        for name, ecfg in variants.items():
            finals[name] = core.run_sweep(wl, ecfg, vseeds)
            int(finals[name].ctr.sum())
        assert jnp.array_equal(finals["packed"].ctr, finals["legacy"].ctr)
        assert jnp.array_equal(finals["packed"].now_ns, finals["legacy"].now_ns)
        events = int(finals["packed"].ctr.sum())

        times = {name: [] for name in variants}
        for _rep in range(REPS):
            for name, ecfg in variants.items():
                seeds = fresh_seeds(S)
                t0 = time.perf_counter()
                final = core.run_sweep(wl, ecfg, seeds)
                int(final.ctr.sum())
                times[name].append(time.perf_counter() - t0)

        row = {"batch": S, "events_per_seed": round(events / S, 1)}
        for name, ts in times.items():
            best = min(ts)
            row[name] = {
                "s": round(best, 3),
                "seeds_per_sec": round(S / best, 1),
                "spread": round((max(ts) - best) / best, 3),
            }
        row["packed_over_legacy"] = round(
            min(times["packed"]) / min(times["legacy"]), 3
        )
        row["bit_exact"] = True
        results.append(row)
        print(json.dumps(row))

    print(json.dumps({"summary": results}), file=sys.stderr)


if __name__ == "__main__":
    main()
