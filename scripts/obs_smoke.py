"""Observability smoke (docs/observability.md, `make obs-smoke`).

End-to-end assertions of the fleet telemetry subsystem on the CPU
backend, small enough for `make stest`, producing ONE Perfetto-loadable
trace file as the run's artifact:

1. out-of-band: the pipelined checked sweep and the streaming checked
   sweep each produce byte-equal report dicts with telemetry on vs off
   (the process-level byte diff lives in scripts/check_determinism.sh);
2. trace spans: the saved Chrome-trace JSON has named "device" and
   "host" tracks, the device sweep of chunk N visibly OVERLAPS the host
   decode/check of chunk N-1 (interval intersection asserted), and the
   stream pool's occupancy rides along as counter samples (the refill
   cadence view);
3. journal: the run's JSONL stream has run_start/run_end plus per-chunk
   and per-flush events, all carrying the same run ID;
4. exposition: the opt-in localhost HTTP endpoint serves the registry
   in Prometheus text format while the sweep runs;
5. event mix: a raft sweep with the opt-in device-side event-mix plane
   enabled lands per-kind counters in `engine_events_by_kind_total`,
   and the default-config report stays free of the "event_mix" key.

Usage: python scripts/obs_smoke.py [out_dir]   (default ./obs_smoke_out)
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _spans(events, track_tid):
    return [
        e for e in events
        if e.get("ph") == "X" and e.get("tid") == track_tid
    ]


def _overlaps(a, b) -> bool:
    return max(a["ts"], b["ts"]) < min(a["ts"] + a["dur"], b["ts"] + b["dur"])


def main() -> int:
    from madsim_tpu import obs
    from madsim_tpu.engine.checkpoint import run_sweep_pipelined
    from madsim_tpu.models import etcd, raft
    from madsim_tpu.obs import read_journal
    from madsim_tpu.oracle.screen import checked_sweep

    out_dir = sys.argv[1] if len(sys.argv) > 1 else "obs_smoke_out"
    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, "trace.json")
    journal_path = os.path.join(out_dir, "journal.jsonl")
    for p in (trace_path, journal_path):
        if os.path.exists(p):
            os.remove(p)

    cfg = etcd.EtcdConfig(hist_slots=128, bug_stale_read=True)
    ecfg = etcd.engine_config(cfg, time_limit_ns=1_000_000_000,
                              max_steps=6_000)
    wl = etcd.workload(cfg)
    spec = etcd.history_spec()
    seeds = jnp.arange(128, dtype=jnp.int64)
    kw = dict(chunk_size=32, workers=0)

    # warm both drivers' programs so the traced region shows steady-state
    # pipelining, not one giant compile span
    checked_sweep(wl, ecfg, seeds, spec, etcd.sweep_summary, **kw)
    checked_sweep(wl, ecfg, seeds, spec, etcd.sweep_summary,
                  driver="stream", **kw)

    telem = obs.Telemetry(journal=journal_path, trace=trace_path,
                          http_port=0)
    run_id = telem.run_id

    # -- leg 1: pipelined chunked checked sweep (device/host overlap) --
    piped = checked_sweep(wl, ecfg, seeds, spec, etcd.sweep_summary,
                          telemetry=telem, **kw)
    piped_off = checked_sweep(wl, ecfg, seeds, spec, etcd.sweep_summary,
                              **kw)
    assert piped == piped_off, "telemetry changed the pipelined report"
    print(f"pipelined report out-of-band: OK "
          f"({piped['hist_violations']} violations)")

    # -- leg 2: streaming checked sweep (refill cadence) ---------------
    streamed = checked_sweep(wl, ecfg, seeds, spec, etcd.sweep_summary,
                             driver="stream", telemetry=telem, **kw)
    streamed_off = checked_sweep(wl, ecfg, seeds, spec, etcd.sweep_summary,
                                 driver="stream", **kw)
    assert streamed == streamed_off, "telemetry changed the stream report"
    print("stream report out-of-band: OK")

    # -- leg 3: the opt-in device-side event-mix plane -----------------
    rcfg = raft.RaftConfig(num_nodes=3, crashes=1, event_mix=True)
    recfg = raft.engine_config(rcfg, time_limit_ns=500_000_000)
    mixed = run_sweep_pipelined(
        raft.workload(rcfg), recfg, jnp.arange(64, dtype=jnp.int64),
        raft.sweep_summary, chunk_size=32, telemetry=telem,
    )
    assert "event_mix" in mixed and len(mixed["event_mix"]) == raft.N_KINDS
    assert sum(mixed["event_mix"]) > 0, "event-mix plane counted nothing"
    plain = run_sweep_pipelined(
        raft.workload(raft.RaftConfig(num_nodes=3, crashes=1)),
        raft.engine_config(raft.RaftConfig(num_nodes=3, crashes=1),
                           time_limit_ns=500_000_000),
        jnp.arange(64, dtype=jnp.int64), raft.sweep_summary, chunk_size=32,
    )
    assert "event_mix" not in plain, "default report grew an event_mix key"
    by_kind = telem.registry.get("engine_events_by_kind_total", kind="0")
    assert by_kind and by_kind > 0, "event-mix counters missing from registry"
    print(f"event-mix plane: OK (mix={mixed['event_mix']})")

    # -- leg 4: live Prometheus exposition -----------------------------
    body = urllib.request.urlopen(telem.server.url, timeout=5).read().decode()
    for needle in ("sweep_chunk_seconds_bucket", "stream_rounds_total",
                   "oracle_screened_total", "engine_events_by_kind_total"):
        assert needle in body, f"exposition missing {needle}"
    print(f"exposition endpoint: OK ({telem.server.url}, "
          f"{len(body.splitlines())} lines)")

    telem.close()

    # -- leg 5: the trace artifact -------------------------------------
    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    tracks = {
        e["args"]["name"]: e["tid"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "device" in tracks and "host" in tracks, f"tracks: {tracks}"
    dev = _spans(events, tracks["device"])
    host = _spans(events, tracks["host"])
    assert dev and host, f"empty tracks: {len(dev)} device, {len(host)} host"
    overlapped = sum(
        1 for h in host if any(_overlaps(h, d) for d in dev)
    )
    assert overlapped > 0, "no device/host phase overlap visible in trace"
    occ_samples = [
        e for e in events
        if e.get("ph") == "C" and e.get("name") == "stream occupancy"
    ]
    assert len(occ_samples) >= 2, "no refill-cadence counter samples"
    rounds = [e for e in dev if e["name"].startswith("round ")]
    assert rounds, "no stream round spans on the device track"
    print(
        f"trace: OK ({len(dev)} device spans, {len(host)} host spans, "
        f"{overlapped} host spans overlap device work, "
        f"{len(occ_samples)} occupancy samples) -> {trace_path}"
    )

    # -- leg 6: the run journal ----------------------------------------
    recs = read_journal(journal_path)
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end", kinds[:3]
    assert "chunk" in kinds and "flush" in kinds, sorted(set(kinds))
    assert all(r["run"] == run_id for r in recs), "run ID drifted"
    print(f"journal: OK ({len(recs)} events, run {run_id}) "
          f"-> {journal_path}")

    print("obs smoke: ALL OK "
          f"(backend={jax.default_backend()}); load {trace_path} in "
          "https://ui.perfetto.dev to see the overlap")
    return 0


if __name__ == "__main__":
    sys.exit(main())
