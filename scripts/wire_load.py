#!/usr/bin/env python
"""The async-core wire load rig (docs/wire.md "Async serving core").

Three modes against one sim-backed cluster (Kafka binary wire + S3 REST
wire + framed etcd wire, all multiplexed by ``serve.core``):

  (default)       full load: worker PROCESSES running >=1k genuine-
                  protocol asyncio clients, gray failure injected
                  mid-run (asymmetric partition during a consumer-group
                  rebalance; fsync stall under S3 multipart), histories
                  checked against LogSpec/S3Spec/KVSpec, the Kafka and
                  S3 transcripts replayed through fresh engines byte
                  for byte, SLO report from the server-side histograms.

  --smoke         the same rig at small scale (<~60 s) plus an in-
                  process async-vs-legacy transcript parity check —
                  the `make wire-smoke` leg.

  --determinism   a seeded SEQUENTIAL transcript (injected clocks, one
                  op at a time): the report carries per-wire response
                  hashes and op counts and nothing else, so two
                  processes x {--server async, --server legacy} x
                  {--telemetry on/off} must all emit byte-identical
                  reports — the check_determinism.sh wire-load leg.

Exit 0 iff every gate in the chosen mode holds.
"""

import argparse
import asyncio
import hashlib
import json
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from madsim_tpu.serve import loadgen  # noqa: E402


# ---------------------------------------------------------------------------
# determinism mode: seeded sequential transcripts, injected clocks


class _Counter:
    """A deterministic ms clock: strictly increasing, process-independent."""

    def __init__(self, start: int = 1_000_000):
        self.t = start

    def __call__(self) -> int:
        self.t += 1
        return self.t


async def _det_kafka(addr, seed: int) -> int:
    from madsim_tpu.kafka.probe import ProbeClient, RealTransport

    rng = random.Random(seed * 31 + 1)
    c = ProbeClient(await RealTransport.connect(addr))
    try:
        await c.api_versions()
        await c.create_topics([("det", 4)])
        await c.metadata(["det"])
        offsets = [0, 0, 0, 0]
        n = 0
        for _ in range(40):
            part = rng.randrange(4)
            kind = rng.randrange(3)
            if kind == 0:
                await c.produce(
                    "det", part,
                    [(1_000_000 + n, b"k%d" % rng.randrange(16),
                      b"v%d" % rng.randrange(1 << 20))],
                )
            elif kind == 1:
                err, _high, rows = await c.fetch("det", part, offsets[part])
                if not err and rows:
                    offsets[part] = rows[-1][0] + 1
            else:
                await c.list_offsets("det", part, -1)
            n += 1
        return n + 3
    finally:
        c.close()


async def _det_s3(addr, seed: int) -> int:
    rng = random.Random(seed * 31 + 2)
    c = loadgen._HttpClient(*addr)
    await c.connect()
    try:
        await c.request("PUT", "/det")
        n = 1
        for i in range(30):
            key = "k%d" % rng.randrange(8)
            kind = rng.randrange(4)
            if kind == 0:
                await c.request(
                    "PUT", f"/det/{key}", b"b%d" % rng.randrange(1 << 20)
                )
                n += 1
            elif kind == 1:
                await c.request("GET", f"/det/{key}")
                n += 1
            elif kind == 2:
                await c.request("DELETE", f"/det/{key}")
                n += 1
            else:
                ok = await loadgen._s3_multipart(
                    c, key, b"m%d" % rng.randrange(1 << 20)
                )
                # 4 requests when the lifecycle completes; count them
                # via the recorder, not here
                n += 4 if ok else 0
        return n
    finally:
        c.close()


async def _det_etcd(addr, seed: int):
    from madsim_tpu.real import etcd as retcd

    rng = random.Random(seed * 31 + 3)
    client = await retcd.Client.connect([f"{addr[0]}:{addr[1]}"])
    h = hashlib.sha256()
    n = 0
    for _ in range(30):
        key = b"k%d" % rng.randrange(8)
        kind = rng.randrange(3)
        if kind == 0:
            rsp = await client.put(key, b"v%d" % rng.randrange(1 << 20))
            h.update(b"put:%d;" % rsp.header().revision())
        elif kind == 1:
            rsp = await client.get(key)
            kvs = [(kv.key, kv.value) for kv in rsp.kvs()]
            h.update(b"get:%d:%r;" % (rsp.count(), kvs))
        else:
            rsp = await client.delete(key)
            h.update(b"del;")
        n += 1
    return n, h.hexdigest()


async def _determinism_async(server_kind: str, seed: int,
                             telemetry: bool) -> dict:
    cluster = loadgen.Cluster(
        server_kind=server_kind,
        kafka_clock=_Counter(), s3_clock=_Counter(),
        telemetry=telemetry,
        kafka_advertised=("127.0.0.1", 9092),
    )
    addrs = await cluster.start()
    try:
        kafka_n = await _det_kafka(addrs["kafka"], seed)
        s3_n = await _det_s3(addrs["s3"], seed)
        etcd_n, etcd_hash = await _det_etcd(addrs["etcd"], seed)

        kh = hashlib.sha256()
        for req, clk, rsp in cluster.kafka.wire.recorder:
            kh.update(req)
            kh.update(rsp if rsp is not None else b"\x00")
            kh.update(b"%d" % clk)
        sh = hashlib.sha256()
        for req, clk, (status, body, headers) in cluster.s3.rest.recorder:
            sh.update(
                f"{req.method} {req.path} {status} {clk} "
                f"{sorted(headers.items())}".encode()
            )
            sh.update(body)
        # the replay gate runs here too: determinism mode must satisfy
        # the same live-vs-replay contract as the full rig
        _, kafka_replay_ok = cluster.replay_kafka()
        _, s3_replay_ok = cluster.replay_s3()
        return {
            "seed": seed,
            "kafka": {
                "frames": len(cluster.kafka.wire.recorder),
                "client_ops": kafka_n,
                "sha256": kh.hexdigest(),
                "replay_ok": kafka_replay_ok,
            },
            "s3": {
                "requests": len(cluster.s3.rest.recorder),
                "client_ops": s3_n,
                "sha256": sh.hexdigest(),
                "replay_ok": s3_replay_ok,
            },
            "etcd": {"ops": etcd_n, "sha256": etcd_hash},
        }
    finally:
        await cluster.stop()


def run_determinism(args) -> int:
    report = asyncio.run(
        _determinism_async(args.server, args.seed, args.telemetry)
    )
    blob = json.dumps(report, sort_keys=True, indent=1) + "\n"
    if args.report:
        with open(args.report, "w") as f:
            f.write(blob)
    sys.stdout.write(blob)
    ok = report["kafka"]["replay_ok"] and report["s3"]["replay_ok"]
    print(f"wire_load determinism [{args.server}]: "
          f"{'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# full / smoke modes


def _gate(report: dict, min_clients: int) -> list:
    failures = []
    if report["clients"] < min_clients:
        failures.append(
            f"clients {report['clients']} < {min_clients}"
        )
    if not report["histories_ok"]:
        failures.append(f"history check failed: {report['history_checks']}")
    if not report["replay_ok"]:
        failures.append(f"replay mismatch: {report['replay']}")
    if report["missing_workers"]:
        failures.append(f"{report['missing_workers']} worker(s) missing")
    if report["fatals"]:
        failures.append(f"worker fatals: {report['fatals']}")
    total = report["total_ops"]
    if total and report["stats"]["errors"] > total * 0.25:
        failures.append(
            f"error rate {report['stats']['errors']}/{total} above 25%"
        )
    return failures


def run_full(args) -> int:
    cfg = dict(loadgen.DEFAULT_SCENARIO)
    if args.clients:
        scale = args.clients / loadgen.total_clients(cfg)
        for k in ("kafka_producers", "s3_clients", "etcd_clients"):
            cfg[k] = max(1, int(cfg[k] * scale))
    if args.run_secs:
        cfg["run_secs"] = args.run_secs
    cfg["seed"] = args.seed
    report = loadgen.run_load(cfg, server_kind=args.server)
    failures = _gate(report, min_clients=args.min_clients)
    report["gate_failures"] = failures
    blob = json.dumps(report, sort_keys=True, indent=1) + "\n"
    if args.report:
        with open(args.report, "w") as f:
            f.write(blob)
    sys.stdout.write(blob)
    print(f"wire_load [{report['clients']} clients, "
          f"{report['total_ops']} ops, "
          f"{report['throughput_ops_s']} ops/s, "
          f"peak {report['peak_open_conns']} conns]: "
          f"{'OK' if not failures else 'FAILED: ' + '; '.join(failures)}")
    return 0 if not failures else 1


def run_smoke(args) -> int:
    # leg 1: the concurrent rig at small scale through the async core
    cfg = dict(loadgen.SMOKE_SCENARIO, seed=args.seed)
    report = loadgen.run_load(cfg, server_kind="async")
    failures = _gate(report, min_clients=loadgen.total_clients(cfg) // 2)
    print(f"smoke load [{report['clients']} clients, "
          f"{report['total_ops']} ops]: "
          f"{'OK' if not failures else 'FAILED: ' + '; '.join(failures)}")

    # leg 2: adapter parity — the async core and the legacy thread-of-
    # control servers must produce the SAME seeded sequential transcript
    a = asyncio.run(_determinism_async("async", args.seed, True))
    b = asyncio.run(_determinism_async("legacy", args.seed, False))
    parity = a == b
    print(f"smoke parity [async vs legacy, telemetry on vs off]: "
          f"{'OK' if parity else 'FAILED'}")
    if not parity:
        for wire in ("kafka", "s3", "etcd"):
            if a[wire] != b[wire]:
                print(f"  {wire}: async={a[wire]} legacy={b[wire]}")
    return 0 if not failures and parity else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--determinism", action="store_true")
    ap.add_argument("--server", choices=("async", "legacy"),
                    default="async")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--telemetry", action="store_true",
                    help="determinism mode: serve with telemetry on "
                         "(report bytes must not change)")
    ap.add_argument("--report", default="")
    ap.add_argument("--clients", type=int, default=0,
                    help="scale the client mix to ~N total clients")
    ap.add_argument("--min-clients", type=int, default=1000)
    ap.add_argument("--run-secs", type=float, default=0.0)
    args = ap.parse_args()
    if args.determinism:
        return run_determinism(args)
    if args.smoke:
        return run_smoke(args)
    return run_full(args)


if __name__ == "__main__":
    sys.exit(main())
