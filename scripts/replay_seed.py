"""Replay a device-found failure seed: CPU trace + host-tier reproduction.

Usage:
    python scripts/replay_seed.py SEED [--host-seeds N] [--volatile]
    python scripts/replay_seed.py SEED --model etcd --history [--stale-bug]

Runs the flagship Raft sweep config for one seed on the CPU backend with
full event tracing (bit-exact vs the TPU sweep), prints the dispatched
event log and the extracted fault plan, then replays the plan against the
host-tier example (examples/raft_host.py) scanning N host seeds for a
reproduction — the workflow a user follows when a TPU sweep reports a
violation seed (the analogue of the reference's "run with
MADSIM_TEST_SEED={seed} to reproduce", runtime/mod.rs:205-210; attach pdb
inside raft_host handlers to step through the reproduction).

``--model etcd`` replays the etcd oracle configuration instead;
``--history`` additionally dumps the seed's decoded operation history
(madsim_tpu/oracle) alongside the event trace and prints the
linearizability checker's verdict. ``--stale-bug`` seeds the
``bug_stale_read`` defect the history oracle exists to catch.
"""

from __future__ import annotations

import argparse
import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
sys.path.insert(0, os.path.join(_repo, "examples"))


def _print_trace(model_mod, trace, max_events: int) -> None:
    import numpy as np

    kind_names = {
        getattr(model_mod, name): name[2:]
        for name in dir(model_mod)
        if name.startswith("K_")
    }
    fired = np.asarray(trace["fired"])
    times = np.asarray(trace["time_ns"])
    kinds = np.asarray(trace["kind"])
    pays = np.asarray(trace["pay"])
    idx = np.nonzero(fired)[0]
    print(f"--- first {min(max_events, idx.size)} of {idx.size} dispatched events ---")
    for i in idx[:max_events]:
        name = kind_names.get(int(kinds[i]), str(int(kinds[i])))
        print(f"  t={times[i] / 1e9:9.6f}s {name:<9} pay={[int(x) for x in pays[i][:4]]}")


def _main_etcd(args) -> None:
    from madsim_tpu import replay
    from madsim_tpu.engine import core
    from madsim_tpu.explore.targets import oracle_demo_faults, stale_etcd_target
    from madsim_tpu.models import etcd
    from madsim_tpu.oracle import KVSpec, check_history, history_bytes

    # the exact (config, faults) the oracle pipeline sweeps
    # (scripts/oracle_demo.py, explore.stale_etcd_target), so a seed the
    # demo reports reproduces here verbatim
    target = stale_etcd_target(bug_stale_read=args.stale_bug)
    workload, ecfg = target.build(oracle_demo_faults())
    final, trace = core.run_traced(workload, ecfg, args.seed)
    w = final.wstate
    print(
        f"seed={args.seed} events={int(final.ctr)} "
        f"sim_time={int(final.now_ns) / 1e9:.3f}s puts={int(w.puts)} "
        f"gets={int(w.gets)} violation={bool(w.violation)}"
    )
    _print_trace(etcd, trace, args.events)
    plan = replay.extract_fault_schedule(trace, etcd.K_FAULT)
    print(f"--- fault schedule ({len(plan)} events) ---")
    for t, action, node in plan:
        print(f"  t={t / 1e9:9.6f}s {action:<9} node={node}")
    if args.history:
        hist = replay.extract_history(final)
        print(
            f"--- op history ({len(hist.ops)} ops, {hist.rows} rows, "
            f"overflow={hist.overflow}) ---"
        )
        for op in hist.ops:
            print(f"  {op.describe()}")
        result = check_history(hist, KVSpec())
        if result.ok:
            print(f"history: LINEARIZABLE ({result.states} states explored)")
        else:
            print(f"history: NOT linearizable — {result.reason}")
        sys.stdout.write(f"({len(history_bytes(hist))} canonical bytes)\n")


def _main_raft(args) -> None:
    import raft_host
    from madsim_tpu import replay
    from madsim_tpu.engine import core
    from madsim_tpu.models import raft

    if args.volatile:
        cfg, ecfg = replay.amnesia_raft_config()
    else:
        cfg = raft.RaftConfig(num_nodes=5, crashes=1)
        ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)

    final, trace = core.run_traced(raft.workload(cfg), ecfg, args.seed)
    w = final.wstate
    print(
        f"seed={args.seed} events={int(final.ctr)} "
        f"sim_time={int(final.now_ns) / 1e9:.3f}s "
        f"elections={int(w.elections)} violation={bool(w.violation)}"
    )
    _print_trace(raft, trace, args.events)

    plan = replay.extract_fault_schedule(trace, raft.K_FAULT)
    print(f"--- fault schedule ({len(plan)} events) ---")
    for t, action, node in plan:
        print(f"  t={t / 1e9:9.6f}s {action:<9} node={node}")

    if not plan:
        print("no faults in this seed's schedule; nothing to replay on host")
        return
    print(f"--- host-tier replay (scanning {args.host_seeds} host seeds) ---")
    result = replay.replay_on_host(
        lambda hs, p: raft_host.run_seed_with_plan(
            hs, p, n=cfg.num_nodes, sim_seconds=3.0
        ),
        plan,
        host_seeds=range(args.host_seeds),
    )
    if result is None:
        print("no host-tier reproduction in the scanned seeds "
              "(within-tier CPU trace above is the bit-exact artifact)")
    else:
        print(
            f"REPRODUCED on host_seed={result['host_seed']}: "
            f"violations={result['violations']} "
            f"elections={result['leaders_elected']} msgs={result['msgs']}"
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("seed", type=int)
    ap.add_argument("--model", choices=("raft", "etcd"), default="raft")
    ap.add_argument("--host-seeds", type=int, default=10)
    ap.add_argument(
        "--volatile", action="store_true",
        help="amnesia config (crash wipes durable state — the host example's semantics)",
    )
    ap.add_argument(
        "--history", action="store_true",
        help="dump the decoded op history + linearizability verdict (etcd model)",
    )
    ap.add_argument(
        "--stale-bug", action="store_true",
        help="seed the etcd stale-read bug the history oracle catches",
    )
    ap.add_argument("--events", type=int, default=30, help="trace lines to print")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.model == "etcd":
        if args.volatile:
            ap.error("--volatile is the raft amnesia config (default model)")
        _main_etcd(args)
    else:
        if args.history:
            ap.error(
                "--history needs a history-recording workload; the raft "
                "model records none (use --model etcd)"
            )
        if args.stale_bug:
            ap.error("--stale-bug seeds the etcd defect (use --model etcd)")
        _main_raft(args)


if __name__ == "__main__":
    main()
