"""Replay a device-found failure seed: CPU trace + host-tier reproduction.

Usage:
    python scripts/replay_seed.py SEED [--host-seeds N] [--volatile]

Runs the flagship Raft sweep config for one seed on the CPU backend with
full event tracing (bit-exact vs the TPU sweep), prints the dispatched
event log and the extracted fault plan, then replays the plan against the
host-tier example (examples/raft_host.py) scanning N host seeds for a
reproduction — the workflow a user follows when a TPU sweep reports a
violation seed (the analogue of the reference's "run with
MADSIM_TEST_SEED={seed} to reproduce", runtime/mod.rs:205-210; attach pdb
inside raft_host handlers to step through the reproduction).
"""

from __future__ import annotations

import argparse
import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
sys.path.insert(0, os.path.join(_repo, "examples"))

def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("seed", type=int)
    ap.add_argument("--host-seeds", type=int, default=10)
    ap.add_argument(
        "--volatile", action="store_true",
        help="amnesia config (crash wipes durable state — the host example's semantics)",
    )
    ap.add_argument("--events", type=int, default=30, help="trace lines to print")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import raft_host
    from madsim_tpu import replay
    from madsim_tpu.engine import core
    from madsim_tpu.models import raft

    if args.volatile:
        cfg, ecfg = replay.amnesia_raft_config()
    else:
        cfg = raft.RaftConfig(num_nodes=5, crashes=1)
        ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)

    # event-kind names from the model's own constants (never drifts)
    kind_names = {
        getattr(raft, name): name[2:] for name in dir(raft) if name.startswith("K_")
    }

    final, trace = core.run_traced(raft.workload(cfg), ecfg, args.seed)
    w = final.wstate
    print(
        f"seed={args.seed} events={int(final.ctr)} "
        f"sim_time={int(final.now_ns) / 1e9:.3f}s "
        f"elections={int(w.elections)} violation={bool(w.violation)}"
    )

    fired = np.asarray(trace["fired"])
    times = np.asarray(trace["time_ns"])
    kinds = np.asarray(trace["kind"])
    pays = np.asarray(trace["pay"])
    idx = np.nonzero(fired)[0]
    print(f"--- first {min(args.events, idx.size)} of {idx.size} dispatched events ---")
    for i in idx[: args.events]:
        name = kind_names.get(int(kinds[i]), str(int(kinds[i])))
        print(f"  t={times[i] / 1e9:9.6f}s {name:<9} pay={[int(x) for x in pays[i][:4]]}")

    plan = replay.extract_fault_schedule(trace, raft.K_FAULT)
    print(f"--- fault schedule ({len(plan)} events) ---")
    for t, action, node in plan:
        print(f"  t={t / 1e9:9.6f}s {action:<9} node={node}")

    if not plan:
        print("no faults in this seed's schedule; nothing to replay on host")
        return
    print(f"--- host-tier replay (scanning {args.host_seeds} host seeds) ---")
    result = replay.replay_on_host(
        lambda hs, p: raft_host.run_seed_with_plan(
            hs, p, n=cfg.num_nodes, sim_seconds=3.0
        ),
        plan,
        host_seeds=range(args.host_seeds),
    )
    if result is None:
        print("no host-tier reproduction in the scanned seeds "
              "(within-tier CPU trace above is the bit-exact artifact)")
    else:
        print(
            f"REPRODUCED on host_seed={result['host_seed']}: "
            f"violations={result['violations']} "
            f"elections={result['leaders_elected']} msgs={result['msgs']}"
        )


if __name__ == "__main__":
    main()
