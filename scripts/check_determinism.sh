#!/usr/bin/env bash
# Determinism smoke gate (wired into `make stest`, see docs/faults.md):
# run a small fault-campaign sweep + traced replays twice with the same
# seeds, in two SEPARATE processes (fresh jit caches, fresh process
# state), and byte-diff the dumped traces. Any drift in the schedule
# derivation, the engine loop, or the fault interpreter fails the gate.
# A second leg runs a tiny explore campaign twice the same way and
# byte-diffs the JSONL reports (docs/explore.md determinism contract).
# The dump also decodes etcd operation histories (madsim_tpu/oracle) on
# both the sweep and the traced-replay path, so the same (spec, seed)
# must yield byte-identical canonical history bytes across the two
# processes AND across the two paths (docs/oracle.md contract).
# A decode leg re-runs the checked sweep with canonical rows sourced
# from the on-device decode kernel and byte-diffs against the
# host-decode reports (docs/oracle.md device-side checking contract).
# A telemetry leg re-runs the streaming checked sweep and the campaign
# under a full obs.Telemetry handle and byte-diffs against the
# uninstrumented reports (docs/observability.md out-of-band contract).
# A fleet leg runs the leased-unit orchestrator over a shared corpus
# store with 1 and 2 workers, twice each, and byte-diffs the merged
# report across all four runs (docs/fleet.md merge contract).
# A steering leg runs the pinned bandit campaign across 2 processes x
# telemetry {on,off} and byte-diffs BOTH the campaign report and the
# decision trace across all four runs (docs/steering.md determinism
# contract: every scheduling decision a pure function of recorded
# outcomes + the campaign seed, telemetry strictly out-of-band).
# A serving-core leg runs the seeded wire_load determinism transcript
# (kafka + S3 + framed etcd, injected clocks) across two processes x
# {async core, legacy servers} x {telemetry on, off} and byte-diffs the
# four reports (docs/wire.md "Async serving core" contract).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

dump() {
  "${PY:-python}" - "$1" <<'EOF'
import sys

import jax
import jax.numpy as jnp
import numpy as np

from madsim_tpu.engine import core
from madsim_tpu.engine.faults import FaultSpec
from madsim_tpu.models import raft

spec = FaultSpec(
    crashes=2, crash_window_ns=1_500_000_000,
    partitions=2, part_window_ns=1_500_000_000,
    spikes=1, losses=1, pauses=1,
)
cfg = raft.RaftConfig(num_nodes=4, commands=4, faults=spec)
ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
wl = raft.workload(cfg)

blobs = {}
# a small sweep: every per-seed counter and latched flag
final = core.run_sweep(wl, ecfg, jnp.arange(256, dtype=jnp.int64))
for i, leaf in enumerate(jax.tree.leaves(final)):
    if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
        leaf = jax.random.key_data(leaf)
    blobs[f"sweep.{i}"] = np.asarray(leaf)
# two traced replays: the full dispatched event schedule, byte for byte
for seed in (0, 7):
    _, trace = core.run_traced(wl, ecfg, seed)
    for k in sorted(trace):
        blobs[f"trace{seed}.{k}"] = np.asarray(trace[k])

# history leg (madsim_tpu/oracle): decoded op histories for one etcd
# (spec, seed) set — canonical bytes from the sweep path, asserted
# in-process equal to the traced-replay path's (cross-path identity),
# then byte-diffed across the two processes by the npz cmp below. The
# (config, faults) pair is the oracle pipeline's own (clean control),
# so this gate covers exactly what oracle_demo/replay_seed run.
from madsim_tpu.explore.targets import oracle_demo_faults, stale_etcd_target
from madsim_tpu.oracle import decode_seed, history_bytes

wl2, ecfg2 = stale_etcd_target(bug_stale_read=False).build(oracle_demo_faults())
hfinal = core.run_sweep(wl2, ecfg2, jnp.arange(16, dtype=jnp.int64))
for seed in (0, 5, 11):
    sweep_b = history_bytes(decode_seed(hfinal, seed))
    tfinal, _ = core.run_traced(wl2, ecfg2, seed)
    assert history_bytes(decode_seed(tfinal)) == sweep_b, (
        f"history path divergence at seed {seed}: sweep lane != traced replay"
    )
    blobs[f"hist{seed}"] = np.frombuffer(sweep_b, dtype=np.uint8)

np.savez(sys.argv[1], **blobs)
print(f"wrote {len(blobs)} arrays -> {sys.argv[1]}")
EOF
}

dump "$out/a.npz"
dump "$out/b.npz"

# npz member timestamps are zeroed by numpy, so the archives themselves
# must be byte-identical when every array is
if cmp -s "$out/a.npz" "$out/b.npz"; then
  echo "determinism gate: OK (two processes, byte-identical traces + histories)"

  # explore leg: two campaign runs of one campaign seed, in two
  # separate processes, must emit byte-identical JSONL reports (no
  # shrink — this leg checks the campaign loop + coverage accounting,
  # cheaply). The demo exits nonzero when its tiny budget finds no
  # violation — expected here; only a MISSING report means the campaign
  # crashed.
  for r in a b; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/explore_demo.py \
      --rounds 2 --seeds-per-round 64 --campaign-seed 0 --no-shrink \
      --report "$out/$r.jsonl" >"$out/$r.log" 2>&1 || true
  done
  if [ -s "$out/a.jsonl" ] && cmp -s "$out/a.jsonl" "$out/b.jsonl"; then
    echo "determinism gate: OK (two campaign runs, byte-identical reports)"
  else
    echo "determinism gate: FAILED — campaign reports differ or are empty" >&2
    diff "$out/a.jsonl" "$out/b.jsonl" >&2 || true
    echo "--- explore_demo run logs ---" >&2
    cat "$out/a.log" "$out/b.log" >&2 || true
    exit 1
  fi

  # pipelined checked-sweep leg (docs/oracle.md "Screening and
  # pipelining"): the screened+pooled checked-sweep report must be
  # byte-identical across two processes x two worker-pool sizes —
  # pipelining overlap, the device screen, and the process-pool fan-out
  # may change wall-clock only, never a report byte.
  for w in 0 2; do
    for r in a b; do
      JAX_PLATFORMS=cpu "${PY:-python}" scripts/checked_sweep_demo.py \
        --seeds 96 --chunk-size 32 --workers "$w" \
        --report "$out/cs_${r}_w${w}.json" >"$out/cs_${r}_w${w}.log" 2>&1
    done
  done
  if [ -s "$out/cs_a_w0.json" ] \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_w0.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_a_w2.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_w2.json"; then
    echo "determinism gate: OK (checked sweep, 2 processes x 2 pool sizes, byte-identical)"
  else
    echo "determinism gate: FAILED — checked-sweep reports differ or are empty" >&2
    for f in "$out"/cs_*.json; do echo "--- $f"; cat "$f"; done >&2 || true
    cat "$out"/cs_*.log >&2 || true
    exit 1
  fi

  # sharded leg (docs/multichip.md): the SAME checked-sweep report must
  # be byte-identical across two processes x two MESH sizes — sharding
  # the sweep/screen/summary over a device mesh may change wall-clock
  # and chunk boundaries, never a report byte. Compared against the
  # unsharded w0 report above, so all three drivers (plain, pooled,
  # sharded) are pinned to one byte string.
  # JAX_PLATFORMS=cpu like every other leg: the m1 run sees >=1 device
  # on any backend so the CPU-mesh re-exec is a no-op, and an
  # accelerator-backend report here would turn the diff against the
  # CPU-pinned w0 reference into a cross-backend assertion
  for m in 1 2; do
    for r in a b; do
      JAX_PLATFORMS=cpu "${PY:-python}" scripts/checked_sweep_demo.py \
        --seeds 96 --chunk-size 32 --workers 0 --mesh "$m" \
        --report "$out/cs_${r}_m${m}.json" >"$out/cs_${r}_m${m}.log" 2>&1
    done
  done
  if [ -s "$out/cs_a_m1.json" ] \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_a_m1.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_m1.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_a_m2.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_m2.json"; then
    echo "determinism gate: OK (sharded checked sweep, 2 processes x 2 mesh sizes == unsharded, byte-identical)"
  else
    echo "determinism gate: FAILED — sharded checked-sweep reports differ from unsharded or are empty" >&2
    for f in "$out"/cs_*_m*.json; do echo "--- $f"; cat "$f"; done >&2 || true
    cat "$out"/cs_*_m*.log >&2 || true
    exit 1
  fi

  # streaming leg (docs/streaming.md): the SAME checked-sweep report
  # must be byte-identical across two processes x two DRIVERS — the
  # persistent lane pool retires and refills lanes on a schedule the
  # chunked driver never sees, but per-seed results are bit-identical
  # and the virtual-chunk flush reproduces the chunked report byte for
  # byte. Compared against the unsharded w0 reference above, so the
  # stream driver joins the one pinned byte string.
  for r in a b; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/checked_sweep_demo.py \
      --seeds 96 --chunk-size 32 --workers 0 --driver stream \
      --report "$out/cs_${r}_stream.json" >"$out/cs_${r}_stream.log" 2>&1
  done
  if [ -s "$out/cs_a_stream.json" ] \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_a_stream.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_stream.json"; then
    echo "determinism gate: OK (streaming checked sweep, 2 processes x 2 drivers, byte-identical)"
  else
    echo "determinism gate: FAILED — streaming checked-sweep reports differ from chunked or are empty" >&2
    for f in "$out"/cs_*stream*.json; do echo "--- $f"; cat "$f"; done >&2 || true
    cat "$out"/cs_*stream*.log >&2 || true
    exit 1
  fi

  # decode leg (docs/oracle.md "Device-side checking"): the SAME
  # checked-sweep report must be byte-identical across two processes x
  # two DECODE PATHS — canonical history rows sourced from the jitted
  # on-device decode kernel vs per-row host Python. Compared against
  # the unsharded w0 (host-decode) reference above, so the device
  # kernel joins the one pinned byte string: same dedup keys, same
  # rebuilt histories, same verdicts, bit for bit.
  for r in a b; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/checked_sweep_demo.py \
      --seeds 96 --chunk-size 32 --workers 0 --device-decode \
      --report "$out/cs_${r}_dd.json" >"$out/cs_${r}_dd.log" 2>&1
  done
  if [ -s "$out/cs_a_dd.json" ] \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_a_dd.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_dd.json"; then
    echo "determinism gate: OK (device-decode checked sweep, 2 processes x 2 decode paths, byte-identical)"
  else
    echo "determinism gate: FAILED — device-decode checked-sweep reports differ from host-decode or are empty" >&2
    for f in "$out"/cs_*_dd.json; do echo "--- $f"; cat "$f"; done >&2 || true
    cat "$out"/cs_*_dd.log >&2 || true
    exit 1
  fi

  # telemetry leg (docs/observability.md): telemetry must be strictly
  # OUT-OF-BAND — the checked-sweep report (streaming driver, the most
  # instrumented path) and the campaign JSONL must be byte-identical
  # with a full obs.Telemetry handle (metrics + journal + trace) vs
  # none, across two processes. Journal/trace files carry wall clocks
  # and run IDs BY DESIGN and are excluded from the diff; the reports
  # never embed them.
  for r in a b; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/checked_sweep_demo.py \
      --seeds 96 --chunk-size 32 --workers 0 --driver stream \
      --telemetry-dir "$out/obs_cs_$r" \
      --report "$out/cs_${r}_telem.json" >"$out/cs_${r}_telem.log" 2>&1
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/explore_demo.py \
      --rounds 2 --seeds-per-round 64 --campaign-seed 0 --no-shrink \
      --telemetry-dir "$out/obs_ex_$r" \
      --report "$out/${r}_telem.jsonl" >"$out/${r}_telem.log" 2>&1 || true
  done
  if [ -s "$out/cs_a_telem.json" ] \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_a_telem.json" \
    && cmp -s "$out/cs_a_w0.json" "$out/cs_b_telem.json" \
    && [ -s "$out/a_telem.jsonl" ] \
    && cmp -s "$out/a.jsonl" "$out/a_telem.jsonl" \
    && cmp -s "$out/a.jsonl" "$out/b_telem.jsonl" \
    && [ -s "$out/obs_cs_a/journal.jsonl" ] \
    && [ -s "$out/obs_cs_a/trace.json" ]; then
    echo "determinism gate: OK (telemetry on/off x 2 processes, byte-identical reports)"
  else
    echo "determinism gate: FAILED — telemetry changed report bytes (or wrote no journal/trace)" >&2
    diff "$out/cs_a_w0.json" "$out/cs_a_telem.json" >&2 || true
    diff "$out/a.jsonl" "$out/a_telem.jsonl" >&2 || true
    cat "$out"/cs_*_telem.log "$out"/?_telem.log >&2 || true
    exit 1
  fi

  # wire leg (docs/wire.md): the Kafka-binary-wire load report and the
  # wire differential-fuzz report must each be byte-identical across two
  # processes; each load run ALSO asserts the second path in-process —
  # the live sim serve vs a recorded-(frame, clock) replay through a
  # fresh broker must agree byte for byte (replay_ok in the report).
  # || true: a demo failure must fall through to the diagnostic branch
  # below (set -e would otherwise abort with the logs unprinted)
  for r in wa wb; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/wire_load_demo.py \
      --report "$out/$r.json" >"$out/$r.log" 2>&1 || true
  done
  for r in wfa wfb; do
    "${PY:-python}" scripts/wire_load_demo.py --fuzz 8 \
      --report "$out/$r.json" >"$out/$r.log" 2>&1 || true
  done
  if [ -s "$out/wa.json" ] && cmp -s "$out/wa.json" "$out/wb.json" \
    && [ -s "$out/wfa.json" ] && cmp -s "$out/wfa.json" "$out/wfb.json"; then
    echo "determinism gate: OK (wire load + fuzz, 2 processes x 2 paths, byte-identical)"
  else
    echo "determinism gate: FAILED — wire load/fuzz reports differ or are empty" >&2
    diff "$out/wa.json" "$out/wb.json" >&2 || true
    diff "$out/wfa.json" "$out/wfb.json" >&2 || true
    cat "$out"/w*.log >&2 || true
    exit 1
  fi

  # serving-core leg (docs/wire.md "Async serving core"): the seeded
  # sequential wire_load determinism report — per-wire response hashes
  # over the kafka binary, S3 REST and framed etcd wires, with injected
  # clocks and a pinned advertised address — must be byte-identical
  # across two processes x {async core, legacy thread-per-connection}
  # x {telemetry on, off}. One pinned byte string means the core is a
  # transport change only, and its metrics are strictly out-of-band.
  # Each run also asserts live-vs-replay transcript identity in-process
  # (replay_ok gates its exit code).
  "${PY:-python}" scripts/wire_load.py --determinism --server async \
    --report "$out/sa.json" >"$out/sa.log" 2>&1 || true
  "${PY:-python}" scripts/wire_load.py --determinism --server async \
    --report "$out/sb.json" >"$out/sb.log" 2>&1 || true
  "${PY:-python}" scripts/wire_load.py --determinism --server legacy \
    --report "$out/sl.json" >"$out/sl.log" 2>&1 || true
  "${PY:-python}" scripts/wire_load.py --determinism --server async \
    --telemetry --report "$out/st.json" >"$out/st.log" 2>&1 || true
  if [ -s "$out/sa.json" ] && cmp -s "$out/sa.json" "$out/sb.json" \
    && cmp -s "$out/sa.json" "$out/sl.json" \
    && cmp -s "$out/sa.json" "$out/st.json"; then
    echo "determinism gate: OK (serving core, 2 processes x 2 servers x telemetry on/off, byte-identical)"
  else
    echo "determinism gate: FAILED — serving-core wire reports differ or are empty" >&2
    diff "$out/sa.json" "$out/sb.json" >&2 || true
    diff "$out/sa.json" "$out/sl.json" >&2 || true
    diff "$out/sa.json" "$out/st.json" >&2 || true
    cat "$out"/s?.log >&2 || true
    exit 1
  fi

  # differential leg: the host<->device differential report
  # (docs/faults.md gray failures) must be byte-identical across two
  # processes — a small matched grid here; the full 200-seed tolerance
  # gate runs as `make differential-smoke`. Tolerance verdicts on this
  # tiny grid are not the point (|| true); only the report bytes are.
  for r in da db; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/differential_demo.py \
      --seeds 32 --sim-seconds 1.5 --specs 2 \
      --report "$out/$r.json" >"$out/$r.log" 2>&1 || true
  done
  if [ -s "$out/da.json" ] && cmp -s "$out/da.json" "$out/db.json"; then
    echo "determinism gate: OK (two differential runs, byte-identical reports)"
  else
    echo "determinism gate: FAILED — differential reports differ or are empty" >&2
    diff "$out/da.json" "$out/db.json" >&2 || true
    echo "--- differential_demo run logs ---" >&2
    cat "$out/da.log" "$out/db.log" >&2 || true
    exit 1
  fi

  # fleet leg (docs/fleet.md): the merged fleet corpus report must be
  # byte-identical across two driver processes x two worker counts —
  # how many workers leased which units, in what order, and whether a
  # lease ever expired may change wall-clock only, never a merged byte
  # (min-combine over the record union is partition-invariant).
  for w in 1 2; do
    for r in a b; do
      JAX_PLATFORMS=cpu "${PY:-python}" scripts/fleet_smoke.py \
        --merged-only --workers "$w" \
        --report "$out/fleet_${r}_w${w}.jsonl" \
        >"$out/fleet_${r}_w${w}.log" 2>&1
    done
  done
  if [ -s "$out/fleet_a_w1.jsonl" ] \
    && cmp -s "$out/fleet_a_w1.jsonl" "$out/fleet_b_w1.jsonl" \
    && cmp -s "$out/fleet_a_w1.jsonl" "$out/fleet_a_w2.jsonl" \
    && cmp -s "$out/fleet_a_w1.jsonl" "$out/fleet_b_w2.jsonl"; then
    echo "determinism gate: OK (fleet merged corpus, 2 processes x 2 worker counts, byte-identical)"
  else
    echo "determinism gate: FAILED — fleet merged reports differ or are empty" >&2
    for f in "$out"/fleet_*.jsonl; do echo "--- $f"; cat "$f"; done >&2 || true
    cat "$out"/fleet_*.log >&2 || true
    exit 1
  fi

  # steering leg (docs/steering.md): the pinned bandit campaign — the
  # UCB family scheduler driving the streaming service — must emit a
  # byte-identical campaign report AND decision trace across 2 driver
  # processes x telemetry {on,off}. The trace is the scheduler's whole
  # decision sequence (cold plays, UCB picks, escalations, kills), so
  # one diff pins every allocation choice, not just the sweep results.
  for r in a b; do
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/steer_demo.py \
      --policy bandit --budget 30000 \
      --report "$out/steer_$r.jsonl" --trace "$out/steer_$r.trace.jsonl" \
      >"$out/steer_$r.log" 2>&1
    JAX_PLATFORMS=cpu "${PY:-python}" scripts/steer_demo.py \
      --policy bandit --budget 30000 --telemetry-dir "$out/obs_steer_$r" \
      --report "$out/steer_${r}_telem.jsonl" \
      --trace "$out/steer_${r}_telem.trace.jsonl" \
      >"$out/steer_${r}_telem.log" 2>&1
  done
  if [ -s "$out/steer_a.jsonl" ] && [ -s "$out/steer_a.trace.jsonl" ] \
    && cmp -s "$out/steer_a.jsonl" "$out/steer_b.jsonl" \
    && cmp -s "$out/steer_a.jsonl" "$out/steer_a_telem.jsonl" \
    && cmp -s "$out/steer_a.jsonl" "$out/steer_b_telem.jsonl" \
    && cmp -s "$out/steer_a.trace.jsonl" "$out/steer_b.trace.jsonl" \
    && cmp -s "$out/steer_a.trace.jsonl" "$out/steer_a_telem.trace.jsonl" \
    && cmp -s "$out/steer_a.trace.jsonl" "$out/steer_b_telem.trace.jsonl" \
    && [ -s "$out/obs_steer_a/bandit.journal.jsonl" ]; then
    echo "determinism gate: OK (steered campaign, 2 processes x telemetry on/off, byte-identical report + decision trace)"
  else
    echo "determinism gate: FAILED — steered campaign report/trace differ or are empty" >&2
    diff "$out/steer_a.jsonl" "$out/steer_b.jsonl" >&2 || true
    diff "$out/steer_a.trace.jsonl" "$out/steer_a_telem.trace.jsonl" >&2 || true
    cat "$out"/steer_*.log >&2 || true
    exit 1
  fi
else
  echo "determinism gate: FAILED — traces differ between identical runs" >&2
  "${PY:-python}" - "$out/a.npz" "$out/b.npz" <<'EOF' >&2
import sys

import numpy as np

a, b = (np.load(p) for p in sys.argv[1:3])
for k in sorted(set(a.files) | set(b.files)):
    if k not in a.files or k not in b.files:
        print(f"  {k}: only in one run")
    elif not np.array_equal(a[k], b[k]):
        print(f"  {k}: differs")
EOF
  exit 1
fi
