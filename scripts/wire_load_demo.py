#!/usr/bin/env python
"""Heavy-traffic wire load gate: N concurrent Kafka-binary-protocol
clients (producers + a consumer group with a mid-run joiner) hammer the
sim-backed broker through the GENUINE wire (kafka/wire.py over Endpoint
pipes) while a FaultSpec latency burst degrades the simulated network —
and the whole run is a determinism statement three ways:

1. the outcome/throughput REPORT is a pure function of the seed: the
   gate (scripts/check_determinism.sh) runs this script twice in two
   processes and byte-diffs the reports;
2. the wire server itself is a pure function of (frame sequence, clock):
   every recorded (request, clock) pair is re-fed through a FRESH broker
   in-process and the responses must be byte-identical (the second
   path), with both paths' transcript digests in the report;
3. the wire-driven operation history (oracle.HostRecorder rows around
   every produce/fetch) must satisfy the kafka ordered-log spec
   (oracle.specs.LogSpec) — protocol-level load with a Jepsen-style
   check on top.

``--fuzz N`` instead runs N seeds of the kafka differential fuzz
(kafka/fuzz.py, loopback codec) and reports per-seed digests — the
fuzz half of the gate's wire leg.

Usage:
    python scripts/wire_load_demo.py [--seed 0] [--report out.json]
    python scripts/wire_load_demo.py --fuzz 12 --report fuzz.json
"""

import argparse
import asyncio
import hashlib
import json
import sys

sys.path.insert(0, ".")

BROKER = "10.0.0.1:9092"
TOPIC = "load"
GROUP = "load-group"


def run_load(args) -> dict:
    import madsim_tpu as ms
    from madsim_tpu import faults as hfaults
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.kafka import wire
    from madsim_tpu.kafka.broker import Broker
    from madsim_tpu.kafka.probe import ProbeClient, SimTransport
    from madsim_tpu.oracle import HostRecorder, check_history
    from madsim_tpu.oracle.history import OP_FETCH, OP_PRODUCE
    from madsim_tpu.oracle.specs import LogSpec

    spec = FaultSpec(
        spikes=2,
        spike_window_ns=2_000_000_000,
        spike_dur_lo_ns=200_000_000,
        spike_dur_hi_ns=600_000_000,
        spike_lat_lo_ns=20_000_000,
        spike_lat_hi_ns=80_000_000,
    )
    total = args.producers * args.records
    rt = ms.Runtime(seed=args.seed)

    async def main():
        h = ms.current_handle()
        server = wire.SimWireServer()
        broker_node = (
            h.create_node().name("broker").ip("10.0.0.1")
            .init(lambda: server.serve(BROKER)).build()
        )
        client_node = h.create_node().name("clients").ip("10.0.0.2").build()
        await ms.sleep(0.05)
        server.wire.recorder = transcript = []

        schedule = hfaults.compile_host(spec, num_nodes=1, seed=args.seed)
        ms.spawn(hfaults.apply_schedule(schedule, [broker_node], spec=spec))

        rec = HostRecorder()
        produced = [0] * args.producers
        consumed = {}  # consumer -> unique records fetched
        state = {"producing": args.producers}

        async def setup():
            c = ProbeClient(await SimTransport.connect(BROKER))
            out = await c.create_topics([(TOPIC, args.partitions)])
            assert out[0][1] == 0, out
            c.close()

        async def producer(i: int):
            c = ProbeClient(await SimTransport.connect(BROKER))
            for r in range(args.records):
                seq = i * args.records + r
                p = seq % args.partitions
                now = h.time.now_time_ns() // 1_000_000
                opid = rec.invoke(client=i, op=OP_PRODUCE, key=p, inp=seq)
                err, off = await c.produce(
                    TOPIC, p,
                    [(now, b"p%d" % i, b"r%d" % seq)],
                )
                assert err == 0, (i, r, err)
                rec.complete(client=i, opid=opid, out=off + 1)
                produced[i] += 1
                await ms.sleep(0.002)
            state["producing"] -= 1
            c.close()

        async def consumer(i: int, member_id: str = "", late: bool = False):
            if late:
                await ms.sleep(0.4)  # joins mid-run: a live rebalance
            cid = args.producers + i  # history client ids after producers
            c = ProbeClient(await SimTransport.connect(BROKER))
            member, gen, assignment = await c.group_session(
                GROUP, [TOPIC], member_id=member_id
            )
            positions = {}
            seen = 0
            while True:
                progressed = False
                for topic, p in assignment:
                    offset = positions.get(p, 0)
                    opid = rec.invoke(client=cid, op=OP_FETCH, key=p,
                                      inp=offset)
                    err, high, rows = await c.fetch(topic, p, offset)
                    assert err == 0
                    rec.complete(client=cid, opid=opid, out=len(rows))
                    if rows:
                        positions[p] = rows[-1][0] + 1
                        seen += len(rows)
                        progressed = True
                hb = await c.heartbeat(GROUP, gen, member)
                if hb == wire.ERR_REBALANCE_IN_PROGRESS:
                    member, gen, assignment = await c.group_session(
                        GROUP, [TOPIC], member_id=member
                    )
                    # keep per-(client, partition) fetches contiguous for
                    # the LogSpec structural check: carried partitions
                    # continue, newly adopted ones restart from 0
                    positions = {p: positions.get(p, 0)
                                 for _t, p in assignment}
                elif hb == 0:
                    await c.offset_commit(
                        GROUP, gen, member,
                        [(TOPIC, p, off) for p, off in sorted(
                            positions.items())],
                    )
                if state["producing"] == 0 and not progressed:
                    caught_up = True
                    for _topic, p in assignment:
                        err, _ts, high = await c.list_offsets(TOPIC, p, -1)
                        if positions.get(p, 0) < high:
                            caught_up = False
                    if caught_up:
                        break
                await ms.sleep(0.01)
            consumed[f"c{i}"] = seen
            if late:
                await c.leave_group(GROUP, member)
            c.close()

        await client_node.spawn(setup())
        tasks = [client_node.spawn(producer(i))
                 for i in range(args.producers)]
        tasks += [client_node.spawn(consumer(i))
                  for i in range(args.consumers)]
        tasks += [client_node.spawn(consumer(args.consumers, late=True))]
        for t in tasks:
            await t

        # per-partition final highs via one more wire client
        c = ProbeClient(await SimTransport.connect(BROKER))
        highs = {}
        for p in range(args.partitions):
            err, _ts, high = await c.list_offsets(TOPIC, p, -1)
            assert err == 0
            highs[str(p)] = high
        committed = await c.offset_fetch(
            GROUP, [(TOPIC, p) for p in range(args.partitions)]
        )
        c.close()

        result = check_history(rec.history(), LogSpec())
        assert result.ok, f"LogSpec violation under load: {result.reason}"

        # path 2: replay every recorded (frame, clock) pair through a
        # FRESH broker — the wire server is pure, so every response byte
        # must reproduce
        clock_feed = [now for _req, now, _rsp in transcript]
        replay = wire.KafkaWire(
            Broker(), clock_ms=lambda: clock_feed.pop(0),
            advertised=server.bound_addr,
        )
        live = hashlib.sha256()
        replayed = hashlib.sha256()
        for req, _now, rsp in transcript:
            got = replay.handle_frame(req)
            assert got == rsp, "wire replay diverged from the live serve"
            live.update(req + (rsp or b"\x00"))
            replayed.update(req + (got or b"\x00"))

        return {
            "seed": args.seed,
            "producers": args.producers,
            "consumers": args.consumers + 1,
            "partitions": args.partitions,
            "records": total,
            "produced": produced,
            "consumed": dict(sorted(consumed.items())),
            "highs": highs,
            "committed": [[t, p, o] for t, p, o in committed],
            "history_ops": len(rec.history().ops),
            "history_ok": bool(result.ok),
            "fault_events": len(schedule),
            "elapsed_virtual_ns": h.time.now_time_ns(),
            "frames": len(transcript),
            "transcript_sha256": live.hexdigest(),
            "replay_sha256": replayed.hexdigest(),
            "replay_ok": live.hexdigest() == replayed.hexdigest(),
        }

    report = rt.block_on(main())
    assert sum(report["highs"].values()) == total
    return report


def run_fuzz(args) -> dict:
    from madsim_tpu.kafka import fuzz as kfuzz
    from madsim_tpu.kafka.probe import LoopbackTransport, ProbeClient
    from madsim_tpu.kafka.wire import KafkaWire

    async def main():
        digests = {}
        for seed in range(args.fuzz):
            client = ProbeClient(LoopbackTransport(KafkaWire()))
            digests[str(seed)] = await kfuzz.fuzz_seed(seed, client, ops=30)
        return digests

    return {"fuzz_seeds": args.fuzz, "digests": asyncio.run(main())}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--producers", type=int, default=3)
    ap.add_argument("--consumers", type=int, default=2,
                    help="steady group members (one more joins mid-run)")
    ap.add_argument("--partitions", type=int, default=3)
    ap.add_argument("--records", type=int, default=16,
                    help="records per producer")
    ap.add_argument("--fuzz", type=int, default=0,
                    help="run N differential-fuzz seeds instead of the load")
    ap.add_argument("--report", default=None)
    args = ap.parse_args()

    report = run_fuzz(args) if args.fuzz else run_load(args)
    text = json.dumps(report, sort_keys=True, indent=2) + "\n"
    if args.report:
        with open(args.report, "w") as f:
            f.write(text)
    sys.stdout.write(text)
    if not args.fuzz:
        ok = report["replay_ok"] and report["history_ok"]
        print(f"wire load gate: {'OK' if ok else 'FAILED'} "
              f"({report['frames']} frames, {report['records']} records, "
              f"{report['fault_events']} fault events)")
        return 0 if ok else 1
    print(f"wire fuzz: OK ({args.fuzz} seeds)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
