"""Screened + pipelined checked-sweep demo (and determinism-gate leg).

Runs the etcd history workload (seeded ``bug_stale_read`` by default)
through ``oracle.screen.checked_sweep``: chunked sweep with the
on-device suspect screen folded behind each chunk, host-side decode +
WGL checking of chunk N overlapped with the device sweep of chunk N+1,
optionally fanned over a process pool.

The report written by ``--report`` is deterministic BY CONTRACT: it is
a pure function of (config, seed range) — no wall times, no paths, keys
sorted — and the worker-pool size must not change a byte of it
(``check_histories`` orders results by lane and each verdict is a pure
function of one history). ``scripts/check_determinism.sh`` runs this
twice x two pool sizes and byte-diffs the four reports. Timing goes to
stderr, where the gate ignores it.

Usage: python scripts/checked_sweep_demo.py [--seeds N] [--chunk-size C]
           [--workers W] [--clean] [--report PATH] [--mesh N]
           [--driver chunked|stream] [--telemetry-dir DIR]
           [--device-decode]

``--device-decode`` sources canonical history rows from the jitted
on-device decode kernel (``oracle.history.canon_sweep``) instead of
per-row host Python — the report must be byte-identical either way;
the gate's decode leg runs 2 processes x {device, host} and diffs all
four.

``--telemetry-dir DIR`` runs the identical pipeline under a full
``obs.Telemetry`` handle (metrics + journal + trace spans written to
DIR) — the report must be byte-identical to an uninstrumented run; the
gate's telemetry leg runs 2 processes x telemetry {on, off} and diffs
all four.

``--driver stream`` routes the identical pipeline through the
persistent streaming lane pool (``engine.stream.stream_sweep``,
docs/streaming.md); the report must be byte-identical to the chunked
driver's — the gate's streaming leg runs 2 processes x 2 drivers and
diffs all four.

``--mesh N`` runs the identical pipeline sharded over an N-device mesh
(re-execing under the forced CPU host mesh when needed) — the report
must be byte-identical to the unsharded one; the determinism gate runs
this across 2 processes x 2 mesh sizes and diffs all four.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=512)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--chunk-size", type=int, default=128)
    ap.add_argument("--workers", type=int, default=0)
    ap.add_argument(
        "--clean", action="store_true",
        help="default config (no seeded bug): the checker must stay quiet",
    )
    ap.add_argument("--report", default=None)
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard the pipeline over an N-device mesh")
    ap.add_argument(
        "--driver", choices=("chunked", "stream"), default="chunked",
        help="sweep driver; the report bytes must not depend on this "
        "(the streaming leg of check_determinism.sh diffs the two)",
    )
    ap.add_argument(
        "--telemetry-dir", default=None,
        help="run under a full obs.Telemetry handle (metrics + journal + "
        "trace written HERE); the report bytes must not depend on this "
        "(the telemetry leg of check_determinism.sh diffs on vs off)",
    )
    ap.add_argument(
        "--device-decode", action="store_true",
        help="source canonical history rows from the on-device decode "
        "kernel instead of per-row host Python; the report bytes must "
        "not depend on this (the decode leg of check_determinism.sh "
        "diffs the two)",
    )
    args = ap.parse_args()

    mesh = None
    if args.mesh:
        from madsim_tpu._cpu_mesh_env import reexec_with_cpu_mesh

        reexec_with_cpu_mesh(args.mesh)
        from madsim_tpu import parallel

        mesh = parallel.seed_mesh(jax.devices()[: args.mesh])

    from madsim_tpu.models import etcd
    from madsim_tpu.oracle.screen import checked_sweep

    cfg = etcd.EtcdConfig(
        hist_slots=256, bug_stale_read=not args.clean
    )
    ecfg = etcd.engine_config(
        cfg, time_limit_ns=2_000_000_000, max_steps=20_000
    )
    wl = etcd.workload(cfg)
    seeds = jnp.arange(
        args.seed0, args.seed0 + args.seeds, dtype=jnp.int64
    )

    telem = None
    if args.telemetry_dir:
        from madsim_tpu import obs

        os.makedirs(args.telemetry_dir, exist_ok=True)
        telem = obs.Telemetry(
            journal=os.path.join(args.telemetry_dir, "journal.jsonl"),
            trace=os.path.join(args.telemetry_dir, "trace.json"),
        )

    t0 = time.perf_counter()
    totals = checked_sweep(
        wl, ecfg, seeds, etcd.history_spec(), etcd.sweep_summary,
        chunk_size=args.chunk_size, workers=args.workers, mesh=mesh,
        driver=args.driver, telemetry=telem,
        device_decode=args.device_decode,
    )
    wall = time.perf_counter() - t0
    if telem is not None:
        telem.close()

    report = {
        "metric": "etcd_checked_sweep",
        "config": "clean" if args.clean else "bug_stale_read",
        "seed_range": [args.seed0, args.seed0 + args.seeds],
        "chunk_size": args.chunk_size,
        "totals": totals,
    }
    if args.report:
        with open(args.report, "w") as f:
            f.write(json.dumps(report, sort_keys=True) + "\n")
    else:
        print(json.dumps(report, sort_keys=True))
    print(
        f"checked {args.seeds} seeds in {wall:.2f}s "
        f"({args.seeds / wall:.1f} seeds/s end-to-end; "
        f"{totals['hist_suspects']} suspects, "
        f"{totals['hist_violations']} violations, "
        f"workers={args.workers}, backend={jax.default_backend()})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
