"""End-to-end explore demo: bland spec -> campaign -> triage -> shrink.

Usage:
    python scripts/explore_demo.py [--rounds N] [--seeds-per-round N]
        [--campaign-seed N] [--report PATH] [--ckpt-dir DIR] [--no-shrink]

Runs the full find->triage->shrink loop against the amnesia Raft target
on whatever backend JAX selects (CPU by default outside a TPU VM):
starting from a bland one-crash ``FaultSpec``, the coverage-guided
campaign mutates its way to a violating ``(spec, seed)``, triage assigns
the failure a stable fingerprint, and the shrinker emits a minimal
``FixedFaults`` schedule re-verified by bit-exact ``run_traced`` replay.

``--report`` writes the campaign's JSONL report — deterministic bytes
per campaign seed (the determinism gate runs this script twice and
byte-diffs; keep wall-clock and environment facts OUT of that file).
The human-readable summary on stdout is NOT part of that contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--seeds-per-round", type=int, default=128)
    ap.add_argument("--campaign-seed", type=int, default=1)
    ap.add_argument("--report", type=str, default=None, help="JSONL report path")
    ap.add_argument("--ckpt-dir", type=str, default=None,
                    help="per-round sweep checkpoints (resumable campaigns)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="stop after the campaign (the cheap determinism leg)")
    ap.add_argument("--assert-zero-recompile", action="store_true",
                    help="warm the envelope program with a one-round "
                         "campaign, then FAIL unless the full campaign "
                         "runs with 0 XLA compilations (the spec-as-data "
                         "contract, docs/faults.md)")
    ap.add_argument("--telemetry-dir", type=str, default=None,
                    help="run the campaign under a full obs.Telemetry "
                         "handle (metrics + journal written here); must "
                         "not change a report byte (docs/observability.md)")
    args = ap.parse_args()

    import time

    from madsim_tpu import explore
    from madsim_tpu.engine.compiles import count_compiles
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.models._common import coverage_bit_count

    t0 = time.perf_counter()
    target = explore.amnesia_raft_target()
    bland = FaultSpec(
        crashes=1,
        crash_window_ns=2_000_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    ccfg = explore.CampaignConfig(
        rounds=args.rounds,
        seeds_per_round=args.seeds_per_round,
        campaign_seed=args.campaign_seed,
        stop_after_failures=1,
    )
    if args.assert_zero_recompile:
        # one round of the same campaign compiles every program the full
        # run needs (envelope-keyed sweep, summary, pipeline glue) —
        # every later candidate is data, not a new jit key
        explore.run_campaign(target, bland, ccfg._replace(rounds=1))
    telem = None
    if args.telemetry_dir:
        from madsim_tpu import obs

        os.makedirs(args.telemetry_dir, exist_ok=True)
        telem = obs.Telemetry(
            journal=os.path.join(args.telemetry_dir, "journal.jsonl"),
        )
    with count_compiles() as compiles:
        result = explore.run_campaign(
            target, bland, ccfg, report_path=args.report,
            ckpt_dir=args.ckpt_dir, telemetry=telem,
        )
    if telem is not None:
        telem.close()
    out = {
        "metric": "explore_demo",
        "rounds_run": len(result.records),
        "corpus_size": len(result.corpus),
        "coverage_bits": coverage_bit_count(result.coverage_map),
        "failures_found": len(result.failures),
        # XLA compilations the campaign itself performed (0 after the
        # --assert-zero-recompile warm-up; without the warm-up the first
        # round's compiles land here — engine/compiles.py)
        "compiles_in_campaign": compiles.count,
    }
    if args.assert_zero_recompile and compiles.count != 0:
        print(
            f"explore demo: campaign recompiled {compiles.count}x after "
            "warm-up — the spec-as-data zero-recompile contract is broken",
            file=sys.stderr,
        )
        sys.exit(1)
    if result.failures:
        spec, seed = result.failures[0]
        # triage each seed under the spec it was found with (failures can
        # span rounds — and thus specs — when stop_after_failures > 1)
        buckets: dict = {}
        for fspec, fseed in result.failures:
            for fp, fails in explore.triage(target, fspec, [fseed]).items():
                buckets.setdefault(fp, []).extend(fails)
        out["fingerprints"] = explore.fingerprint_counts(buckets)
        if not args.no_shrink:
            sr = explore.shrink(target, spec, seed)
            assert sr is not None, "shrink lost the failure it was given"
            out["shrunk"] = {
                "seed": sr.seed,
                "fingerprint": sr.fingerprint,
                "schedule": [list(e) for e in sr.schedule],
                "events_before": sr.original_len,
                "events_after": len(sr.schedule),
                "replays": sr.tests,
            }
    out["wall_s"] = round(time.perf_counter() - t0, 2)
    print(json.dumps(out, sort_keys=True))
    if not result.failures:
        print("explore demo: campaign found no violating seed in budget",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
