"""Million-seed MadRaft sweep — the scale demonstration beyond bench.py.

Runs 2**20 = 1,048,576 seeds of BASELINE config #3 (5-node Raft election +
replication with crash/restart injection, 3 virtual seconds each) as 16k
chunks of one compiled program, merging per-chunk summaries on host
(constant device memory — the pattern that extends indefinitely; see
engine.core.run_sweep_chunked). Prints one JSON line.

Any total works: a ragged final chunk is padded to the full chunk size
(the padded lanes' counts are trimmed out of its summary inside one
jitted program), so every chunk still reuses the single compiled sweep.

Usage: python scripts/sweep_million.py [total_seeds] [ckpt_dir]

With ``ckpt_dir`` the sweep is preemption-safe: per-chunk summaries are
checkpointed (engine.checkpoint.run_sweep_chunked_resumable) and a
restarted run skips completed chunks.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from madsim_tpu.engine import core
from madsim_tpu.models import raft
from madsim_tpu.models._common import merge_summaries

CHUNK = 16384


def main() -> None:
    total = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000)
    wl = raft.workload(cfg)

    # compile once outside the timed region — at the batch shape the
    # timed loop will actually run (a sub-chunk total compiles and runs
    # at its own exact shape; see `mult` below)
    warm_n = CHUNK if total > CHUNK else total
    warm = core.run_sweep(wl, ecfg, jnp.arange(warm_n, dtype=jnp.int64))
    raft.sweep_summary(warm)

    ckpt_dir = sys.argv[2] if len(sys.argv) > 2 else None
    chunks_preloaded = 0
    t0 = time.perf_counter()
    if ckpt_dir:
        import glob
        import os

        from madsim_tpu.engine.checkpoint import run_sweep_chunked_resumable

        chunks_preloaded = len(glob.glob(os.path.join(ckpt_dir, "chunk_*.json")))
        seeds = jnp.arange(1 << 30, (1 << 30) + total, dtype=jnp.int64)
        # clamp the chunk granule to the total so a sub-chunk run is not
        # padded up to a full 16k-lane sweep (mirrors `mult` below)
        totals = run_sweep_chunked_resumable(
            wl, ecfg, seeds, raft.sweep_summary, ckpt_dir,
            chunk_size=min(CHUNK, total),
        )
    else:
        totals = {}
        # pad a ragged FINAL chunk to the compiled 16k shape only when an
        # earlier full chunk already paid for that program; a sub-chunk
        # total compiles its own exact shape instead of simulating (and
        # discarding) up to 16x padded lanes
        mult = CHUNK if total > CHUNK else 1
        for lo in range(1 << 30, (1 << 30) + total, CHUNK):
            k = min(CHUNK, (1 << 30) + total - lo)
            # run_in_chunks trims the padded lanes before returning;
            # calling it per chunk keeps the constant-memory per-chunk
            # summary merge this script exists to demonstrate
            final = core.run_in_chunks(
                lambda c: core.run_sweep(wl, ecfg, c),
                jnp.arange(lo, lo + k, dtype=jnp.int64),
                CHUNK,
                multiple=mult,
            )
            merge_summaries(totals, raft.sweep_summary(final))
    wall = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "metric": "madraft_million_seed_sweep",
                "seeds": total,
                "chunk_size": CHUNK,
                "wall_s": round(wall, 2),
                "seeds_per_sec": round(total / wall, 1),
                "events_per_sec": round(totals["events_total"] / wall, 1),
                "sim_sec_per_wall_sec": round(
                    totals["sim_ns_total"] / wall / 1e9, 1
                ),
                "violations": totals["violations"],
                "elections_total": totals["elections_total"],
                # provenance: throughput above is only a device
                # measurement when every chunk was computed this run
                "chunks_loaded_from_checkpoint": chunks_preloaded,
                "chunks_computed": -(-total // CHUNK) - chunks_preloaded,
                "backend": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
