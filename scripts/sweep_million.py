"""Million-seed MadRaft sweep — the scale demonstration beyond bench.py.

Runs 2**20 = 1,048,576 seeds of BASELINE config #3 (5-node Raft election +
replication with crash/restart injection, 3 virtual seconds each) as 16k
chunks of one compiled program, merging per-chunk summaries on host
(constant device memory — the pattern that extends indefinitely; see
engine.core.run_sweep_chunked). Prints one JSON line.

Any total works: a ragged final chunk is padded to the full chunk size
and its summary is computed through the LIMIT-MASKED reduction
(models/_common.make_sweep_summary ``limit=``), so the ragged tail
reuses both the compiled sweep program AND the compiled summary program
— zero recompiles in the timed region, which the summary line proves by
counting ``Finished XLA compilation`` events (``jax.log_compiles``)
while the timed loop runs.

Usage: python scripts/sweep_million.py [total_seeds] [ckpt_dir] [--mesh [N]]

Progress goes to stderr as an obs-registry heartbeat (seeds done,
seeds/s, ETA) every ``MADSIM_HB_SECONDS`` (default 5; 0 disables) —
stdout stays the single machine-readable JSON line.

With ``ckpt_dir`` the sweep is preemption-safe: per-chunk summaries are
checkpointed (engine.checkpoint.run_sweep_chunked_resumable) and a
restarted run skips completed chunks.

``--mesh`` (optionally ``--mesh N`` for an N-device mesh) runs every
chunk sharded over the device mesh (``parallel.run_sweep_sharded``) —
the same chunk granule spans all devices, summaries merge identically,
and the per-chunk checkpoint files are mesh-free, so a sweep can be
interrupted under one device count and finished under another. When the
process sees fewer devices than requested it re-execs itself under the
forced CPU host mesh (madsim_tpu._cpu_mesh_env).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from madsim_tpu import obs
from madsim_tpu.engine import core
from madsim_tpu.engine.compiles import count_compiles
from madsim_tpu.models import raft
from madsim_tpu.models._common import merge_summaries

# env-overridable so smoke runs can exercise the multi-chunk + ragged
# paths without paying for 16k-lane compiles
CHUNK = int(os.environ.get("MADSIM_SWEEP_CHUNK", 16384))
# heartbeat cadence (stderr; stdout stays the one JSON line). 0 disables.
HB_SECONDS = float(os.environ.get("MADSIM_HB_SECONDS", 5.0))


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("total", type=int, nargs="?", default=1 << 20)
    ap.add_argument("ckpt_dir", nargs="?", default=None)
    ap.add_argument("--mesh", type=int, nargs="?", const=8, default=None,
                    help="shard each chunk over an N-device mesh "
                         "(bare --mesh picks 8)")
    ns = ap.parse_args()
    total = ns.total
    mesh = None
    n_dev = 0
    if ns.mesh is not None:
        n_dev = ns.mesh
        from madsim_tpu._cpu_mesh_env import reexec_with_cpu_mesh

        reexec_with_cpu_mesh(n_dev)
        from madsim_tpu import parallel

        mesh = parallel.seed_mesh(jax.devices()[:n_dev])
        if CHUNK % n_dev or total % n_dev:
            raise SystemExit(
                f"chunk {CHUNK} and total {total} must divide the "
                f"{n_dev}-device mesh"
            )
    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000)
    wl = raft.workload(cfg)

    def run_chunk(seed_chunk):
        if mesh is None:
            return core.run_sweep(wl, ecfg, seed_chunk)
        from madsim_tpu import parallel

        return parallel.run_sweep_sharded(wl, ecfg, seed_chunk, mesh)

    base = 1 << 30
    tail = total % CHUNK if total > CHUNK else 0

    # compile once outside the timed region — at the batch shape the
    # timed loop will actually run (a sub-chunk total compiles and runs
    # at its own exact shape), including the limit-masked summary
    # program a ragged tail will hit
    # ... the warm seed range sits just below ``base`` so the offset
    # arange (an eager iota+add) is compiled here too, not in the loop
    warm_n = CHUNK if total > CHUNK else total
    warm = run_chunk(jnp.arange(base - warm_n, base, dtype=jnp.int64))
    raft.sweep_summary(warm)
    if tail:
        raft.sweep_summary(warm, limit=tail)

    # progress heartbeat driven by the obs registry (seeds done, seeds/s,
    # ETA), replacing ad-hoc perf_counter prints: the chunk drivers count
    # ``sweep_seeds_done_total`` as each chunk lands, and a daemon ticker
    # reads it back every HB_SECONDS — the same series a Prometheus
    # scrape would see (obs.Telemetry(http_port=...))
    telem = obs.Telemetry()
    hb = obs.Heartbeat(telem.registry, total, prefix="sweep")
    hb_stop = None
    if HB_SECONDS > 0:
        import threading

        hb_stop = threading.Event()

        def _beat():
            while not hb_stop.wait(HB_SECONDS):
                hb.tick()

        threading.Thread(target=_beat, daemon=True, name="hb").start()

    ckpt_dir = ns.ckpt_dir
    chunks_preloaded = 0
    try:
        with count_compiles() as compiles:
            t0 = time.perf_counter()
            if ckpt_dir:
                import glob

                from madsim_tpu.engine.checkpoint import (
                    run_sweep_chunked_resumable,
                )

                chunks_preloaded = len(
                    glob.glob(os.path.join(ckpt_dir, "chunk_*.json"))
                )
                seeds = jnp.arange(base, base + total, dtype=jnp.int64)
                # clamp the chunk granule to the total so a sub-chunk run
                # is not padded up to a full 16k-lane sweep
                totals = run_sweep_chunked_resumable(
                    wl, ecfg, seeds, raft.sweep_summary, ckpt_dir,
                    chunk_size=min(CHUNK, total), run_chunk=run_chunk,
                    telemetry=telem,
                )
            else:
                totals = {}
                for lo in range(base, base + total, CHUNK):
                    k = min(CHUNK, base + total - lo)
                    if k < CHUNK and total > CHUNK:
                        # ragged tail: extend the contiguous seed range
                        # to the compiled chunk shape (value-identical to
                        # core._pad_seeds' max+1+i filler) and mask the
                        # padded lanes inside the one compiled summary
                        # program — no trim program, no recompile, not
                        # even an eager pad op
                        final = run_chunk(
                            jnp.arange(lo, lo + CHUNK, dtype=jnp.int64)
                        )
                        merge_summaries(
                            totals, raft.sweep_summary(final, limit=k)
                        )
                    else:
                        final = run_chunk(
                            jnp.arange(lo, lo + k, dtype=jnp.int64)
                        )
                        merge_summaries(totals, raft.sweep_summary(final))
                    telem.count(
                        "sweep_seeds_done_total", k,
                        help="seeds retired across all chunks",
                    )
            wall = time.perf_counter() - t0
    finally:
        if hb_stop is not None:
            hb_stop.set()
    hb.tick(force=True)

    print(
        json.dumps(
            {
                "metric": "madraft_million_seed_sweep",
                "seeds": total,
                "chunk_size": CHUNK,
                "wall_s": round(wall, 2),
                "seeds_per_sec": round(total / wall, 1),
                "events_per_sec": round(totals["events_total"] / wall, 1),
                "sim_sec_per_wall_sec": round(
                    totals["sim_ns_total"] / wall / 1e9, 1
                ),
                "violations": totals["violations"],
                "elections_total": totals["elections_total"],
                # provenance: throughput above is only a device
                # measurement when every chunk was computed this run
                "chunks_loaded_from_checkpoint": chunks_preloaded,
                "chunks_computed": -(-total // CHUNK) - chunks_preloaded,
                # program reuse, measured: XLA compilations during the
                # timed loop (0 = the warm-up paid for everything,
                # ragged tail included)
                "compiles_in_timed_region": compiles.count,
                "mesh_devices": n_dev,
                "backend": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
