"""Real-mode RPC bench — parity with the reference's criterion bench
(madsim/benches/rpc.rs:11-56: empty-RPC latency + throughput at payload
sizes 16 B..1 MiB over real loopback).

Runs over BOTH real transports (UDP datagrams and framed TCP) so the
numbers bound the transport choice. Prints one JSON line.

    python scripts/bench_rpc.py
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu import real
from madsim_tpu.net.rpc import Request

SIZES = [16, 256, 4096, 65536, 1 << 20]
LAT_ITERS = 2000
THR_ITERS = 200


class Empty(Request):
    pass


class Payload(Request):
    def __init__(self, data: bytes):
        self.data = data


async def _bench_endpoint(make_endpoint) -> dict:
    server = await make_endpoint(("127.0.0.1", 0))

    async def on_empty(req):
        return None

    async def on_payload(req):
        return len(req.data)

    server.add_rpc_handler(Empty, on_empty)
    server.add_rpc_handler(Payload, on_payload)
    client = await make_endpoint(("127.0.0.1", 0))
    addr = server.local_addr()

    # empty-RPC round-trip latency (rpc.rs:11-27)
    for _ in range(50):
        await client.call(addr, Empty())
    t0 = time.perf_counter()
    for _ in range(LAT_ITERS):
        await client.call(addr, Empty())
    lat_us = (time.perf_counter() - t0) / LAT_ITERS * 1e6

    # throughput by payload size (rpc.rs:29-54)
    thr = {}
    for size in SIZES:
        if size > 60000 and make_endpoint is real.Endpoint.bind:
            thr[str(size)] = None  # above the UDP datagram ceiling
            continue
        blob = b"x" * size
        n = max(20, THR_ITERS // max(1, size // 4096))
        for _ in range(5):
            await client.call(addr, Payload(blob))
        t0 = time.perf_counter()
        for _ in range(n):
            await client.call(addr, Payload(blob))
        dt = time.perf_counter() - t0
        thr[str(size)] = round(n * size / dt / 1e6, 1)  # MB/s

    server.close()
    client.close()
    return {"empty_rpc_latency_us": round(lat_us, 1), "throughput_mb_s": thr}


def main() -> None:
    rt = real.Runtime()

    async def run():
        return {
            "udp": await _bench_endpoint(real.Endpoint.bind),
            "tcp": await _bench_endpoint(real.TcpEndpoint.bind),
        }

    out = rt.block_on(run())
    out["metric"] = "real_mode_rpc"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
