"""Self-steering scheduler smoke: adaptive-vs-uniform A/B + replay gate.

Usage:
    python scripts/steer_demo.py                       # the full drill
    python scripts/steer_demo.py --policy bandit --budget 30000 \
        --report PATH --trace PATH [--telemetry-dir DIR]   # one pinned run

The full drill (``make steer-smoke``) checks the ISSUE-20 "Done" bar on
the raft-amnesia steering gate (``explore.targets.steer_gate``), all
in one process (the runs share the warmed stream program):

1. the bandit campaign runs TWICE — once with telemetry journaling on,
   once fully off — and the campaign report AND the decision trace must
   be byte-identical (replay determinism + telemetry out-of-band-ness
   in one shot);
2. the decision trace is asserted present and structurally complete:
   cold + UCB decisions, absorbed outcomes in submission order, at
   least one budget escalation and one early-kill at the pinned config;
3. the run journal carries one ``steer_round`` event per decision and
   per outcome (the trace's out-of-band mirror);
4. the uniform grid runs at the SAME deterministic device-event budget
   (the matched-compute baseline: same loop, same families, round-robin
   policy) and the bandit must find >= 1.5x its distinct triage
   fingerprints — the coverage-guided allocation actually buying bugs.

``--policy/--report/--trace`` is the check_determinism.sh steering leg:
one pinned campaign, report + trace written for the gate to byte-diff
across 2 driver processes x telemetry {on,off}.

Exit code 0 = every assertion held. Stdout's last line is a JSON
summary (machine-readable); progress goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the pinned drill config. 10 families, 2 of them crash-bearing: the
# uniform grid burns ~80% of the budget on amnesia-blind duds, while
# the bandit early-kills them and pours the freed budget into escalated
# (8x seeds) crash candidates — which is what reaches the rare third
# fingerprint (n0 lives deep in the violating-seed tail; see
# docs/steering.md "What the A/B measures").
FAMILIES = (0x001, 0x002, 0x003, 0x004, 0x008,
            0x010, 0x020, 0x040, 0x080, 0x100)
SEEDS_PER_ROUND = 16
MAX_RECORDED = 8
ESCALATE_SEEDS = 8
KILL_PLAYS = 1  # kill a family after one barren play: max pruning
BUDGET_EVENTS = 45_000
CAMPAIGN_SEED = 7


def _cfgs(policy: str, budget: int):
    from madsim_tpu.explore import CampaignConfig, SteerConfig

    ccfg = CampaignConfig(
        rounds=999, seeds_per_round=SEEDS_PER_ROUND,
        campaign_seed=CAMPAIGN_SEED, max_recorded_seeds=MAX_RECORDED,
        scheduler=policy,
    )
    scfg = SteerConfig(
        scheduler=policy, families=FAMILIES,
        escalate_seeds=ESCALATE_SEEDS, kill_plays=KILL_PLAYS,
        budget_events=budget,
    )
    return ccfg, scfg


def _run(policy: str, budget: int, report: str, trace: str,
         telemetry_dir: str | None):
    from madsim_tpu.explore import run_steered
    from madsim_tpu.explore.targets import steer_gate

    target, base = steer_gate(smoke=True)
    ccfg, scfg = _cfgs(policy, budget)
    telemetry = None
    if telemetry_dir is not None:
        from madsim_tpu.obs import Telemetry

        telemetry = Telemetry(
            journal=os.path.join(telemetry_dir, f"{policy}.journal.jsonl")
        )
    try:
        return run_steered(
            target, base, ccfg, scfg,
            report_path=report, trace_path=trace, telemetry=telemetry,
        )
    finally:
        if telemetry is not None:
            telemetry.close()


def single(args) -> None:
    """One pinned run — the determinism gate's steering leg."""
    res = _run(args.policy, args.budget, args.report, args.trace,
               args.telemetry_dir)
    print(json.dumps({
        "policy": args.policy,
        "decisions": len(res.records),
        "fingerprints": res.fingerprints,
        "spent_events": res.spent_events,
    }, sort_keys=True))


def drill(args) -> None:
    outdir = args.outdir or tempfile.mkdtemp(prefix="steer_smoke_")
    os.makedirs(outdir, exist_ok=True)
    p = lambda n: os.path.join(outdir, n)  # noqa: E731
    summary: dict = {}

    # leg 1+2+3: bandit twice (journal on / telemetry off), byte-diffed
    print("[steer-smoke] bandit run (journal on)", file=sys.stderr)
    res = _run("bandit", args.budget, p("bandit.jsonl"),
               p("bandit.trace.jsonl"), outdir)
    print("[steer-smoke] bandit replay (telemetry off)", file=sys.stderr)
    _run("bandit", args.budget, p("replay.jsonl"),
         p("replay.trace.jsonl"), None)
    report = open(p("bandit.jsonl"), "rb").read()
    trace = open(p("bandit.trace.jsonl"), "rb").read()
    assert report == open(p("replay.jsonl"), "rb").read(), \
        "bandit campaign report bytes diverged on replay"
    assert trace == open(p("replay.trace.jsonl"), "rb").read(), \
        "bandit decision-trace bytes diverged on replay"

    recs = [json.loads(ln) for ln in trace.splitlines()[1:]]
    kinds = [r["kind"] for r in recs]
    decides = [r for r in recs if r["kind"] == "decide"]
    outcomes = [r for r in recs if r["kind"] == "outcome"]
    assert decides and outcomes, "decision trace is empty"
    assert [r["i"] for r in outcomes] == list(range(len(outcomes))), \
        "outcomes not absorbed in submission order"
    assert any(r["why"] == "ucb" for r in decides), "bandit never exploited"
    assert "escalate" in kinds, "no family escalated at the pinned config"
    assert "kill" in kinds, "no family early-killed at the pinned config"
    summary["decisions"] = len(decides)
    summary["kills"] = kinds.count("kill")
    summary["escalations"] = kinds.count("escalate")

    journal = [
        r for r in _read_journal(p("bandit.journal.jsonl"))
        if r.get("kind") == "steer_round"
    ]
    assert len(journal) == len(decides) + len(outcomes), (
        f"journal carries {len(journal)} steer_round events, trace has "
        f"{len(decides)}+{len(outcomes)}"
    )

    # leg 4: the matched-budget uniform grid
    print("[steer-smoke] uniform baseline", file=sys.stderr)
    uni = _run("uniform", args.budget, p("uniform.jsonl"),
               p("uniform.trace.jsonl"), None)
    bandit_fps = [json.loads(ln) for ln in report.splitlines()[1:]]
    bandit_fps = sorted(
        {fp for r in bandit_fps for fp in r["fresh_fingerprints"]}
    )
    summary["bandit_fps"] = bandit_fps
    summary["uniform_fps"] = uni.fingerprints
    assert bandit_fps, "bandit found no fingerprints; drill is vacuous"
    assert 2 * len(bandit_fps) >= 3 * len(uni.fingerprints), (
        f"adaptive/uniform fingerprint ratio below 1.5x: "
        f"{bandit_fps} vs {uni.fingerprints}"
    )

    summary["ok"] = True
    print(json.dumps(summary, sort_keys=True))
    print(f"[steer-smoke] OK ({outdir})", file=sys.stderr)


def _read_journal(path: str):
    from madsim_tpu.obs import read_journal

    return read_journal(path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--policy", choices=("bandit", "uniform"), default=None)
    ap.add_argument("--budget", type=int, default=BUDGET_EVENTS)
    ap.add_argument("--report", type=str, default=None)
    ap.add_argument("--trace", type=str, default=None)
    ap.add_argument("--telemetry-dir", type=str, default=None)
    ap.add_argument("--outdir", type=str, default=None)
    args = ap.parse_args()
    if args.policy is not None:
        if not (args.report and args.trace):
            ap.error("--policy needs --report and --trace")
        single(args)
    else:
        drill(args)


if __name__ == "__main__":
    main()
