"""Million-seed sharded campaign + device-count curve — the multichip
publication artifact (MULTICHIP_r06 direction; docs/multichip.md).

Two phases, both through the sharded pipelined checked-sweep driver
(``parallel.run_sweep_sharded_pipelined``):

1. **curve** — one fixed-spec checked sweep (sweep + on-device screen +
   WGL checking) at each device count in ``--devices``, same seed range,
   compiles excluded; prints aggregate seeds/s, events/s and
   time-to-first-bug per count and ASSERTS the merged summary bytes are
   identical across every mesh size (the invariance contract).
2. **campaign** — a genuine coverage-guided fault campaign (seeded
   FaultSpec mutations, retain-on-new-bits, election-history screening
   + checking) over ``--campaign-seeds`` total seeds at the largest
   device count: a million seeds as ONE unit of work. ``--campaign-invariance``
   additionally re-runs a small campaign at two device counts and
   byte-compares the JSONL reports.

Runs anywhere: when the process sees fewer devices than requested it
re-execs itself under the forced CPU host mesh
(``madsim_tpu._cpu_mesh_env``), the same environment the multichip
dryrun gate and the pytest suite use. ``--smoke`` shrinks every knob to
a ~1-minute CI gate (``make multichip-smoke``).

Wall-clock metrics go to stdout JSON; the byte-compared artifacts
(checked-sweep totals, campaign JSONL) never contain times or paths.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _target(kind: str, smoke: bool):
    from madsim_tpu.explore.targets import (
        amnesia_gate,
        oracle_demo_faults,
        stale_etcd_target,
    )

    if kind == "raft":
        return amnesia_gate(smoke)
    t = stale_etcd_target(
        time_limit_ns=500_000_000 if smoke else 2_000_000_000,
        max_steps=6_000 if smoke else 20_000,
        hist_slots=128 if smoke else 256,
    )
    return t, oracle_demo_faults()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated device counts for the curve")
    ap.add_argument("--curve-target", choices=("raft", "etcd"), default="raft")
    ap.add_argument("--curve-seeds", type=int, default=4096)
    ap.add_argument("--chunk-per-device", type=int, default=512)
    ap.add_argument("--workers", type=int, default=0,
                    help="history-checker process-pool size")
    ap.add_argument("--campaign-seeds", type=int, default=0,
                    help="total seeds of the big sharded campaign "
                         "(rounds x seeds-per-round; 0 = skip)")
    ap.add_argument("--seeds-per-round", type=int, default=65536)
    ap.add_argument("--campaign-ckpt-dir", default=None)
    ap.add_argument("--campaign-invariance", action="store_true",
                    help="re-run a small campaign at the smallest and "
                         "largest device counts and byte-compare reports")
    ap.add_argument("--report", default=None)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    counts = tuple(int(x) for x in args.devices.split(","))
    if args.smoke:
        counts = tuple(c for c in counts if c <= 2) or (1, 2)
        args.curve_seeds = min(args.curve_seeds, 512)
        args.chunk_per_device = min(args.chunk_per_device, 128)
        args.campaign_invariance = True

    from madsim_tpu._cpu_mesh_env import reexec_with_cpu_mesh

    reexec_with_cpu_mesh(max(counts))

    import jax

    from madsim_tpu.explore import (
        CampaignConfig,
        checked_sweep_curve,
        sharded_campaign,
    )

    target, base = _target(args.curve_target, args.smoke)
    curve = checked_sweep_curve(
        target, base, device_counts=counts, seeds_total=args.curve_seeds,
        chunk_per_device=args.chunk_per_device, workers=args.workers,
    )
    assert curve["bytes_invariant"], (
        "sharded checked-sweep summary bytes differ across mesh sizes"
    )
    out = {"backend": jax.default_backend(), "curve": curve}

    if args.campaign_seeds:
        ctarget, cbase = _target("raft", args.smoke)
        rounds = -(-args.campaign_seeds // args.seeds_per_round)
        ccfg = CampaignConfig(
            rounds=rounds,
            seeds_per_round=args.seeds_per_round,
            chunk_size=args.chunk_per_device * max(counts),
            check_workers=args.workers,
        )
        out["campaign"] = sharded_campaign(
            ctarget, cbase, ccfg, max(counts),
            ckpt_dir=args.campaign_ckpt_dir,
        )

    if args.campaign_invariance:
        lo_hi = (min(counts), max(counts))
        ctarget, cbase = _target("raft", True)
        ccfg = CampaignConfig(
            rounds=2, seeds_per_round=256,
            chunk_size=128 * max(lo_hi), check_workers=args.workers,
        )
        shas = {}
        with tempfile.TemporaryDirectory() as d:
            for nd in lo_hi:
                p = os.path.join(d, f"campaign_{nd}.jsonl")
                res = sharded_campaign(ctarget, cbase, ccfg, nd, report_path=p)
                shas[nd] = res["report_sha256"]
        assert len(set(shas.values())) == 1, (
            f"campaign report bytes differ across mesh sizes: {shas}"
        )
        out["campaign_invariance"] = {
            "device_counts": list(lo_hi),
            "report_sha256": next(iter(shas.values())),
            "bytes_invariant": True,
        }

    blob = json.dumps(out, sort_keys=True)
    if args.report:
        with open(args.report, "w") as f:
            f.write(blob + "\n")
    print(blob)
    return 0


if __name__ == "__main__":
    sys.exit(main())
