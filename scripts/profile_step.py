"""Phase-level profiling of the engine step on the current backend.

Methodology (docs/pallas_finding.md §0 — naive timing lies on this
setup): every phase runs ITERS times inside ONE on-device fori_loop with
per-iteration input variation (the tunneled device memoizes same-input
executions), every output leaf is folded into the loop carry (so nothing
dead-code-eliminates), and completion is bounded by a host readback of
that scalar (``block_until_ready`` under-reports through the tunnel).
The ~100 ms fixed dispatch+readback cost is measured and subtracted.

Run on TPU:  python scripts/profile_step.py [S]
"""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu.engine import core, queue as equeue
from madsim_tpu.engine.rng import event_bits
from madsim_tpu.models import raft

S = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
ITERS = 256

cfg = raft.RaftConfig(num_nodes=5, crashes=1)
ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000)
wl = raft.workload(cfg)

state = jax.jit(partial(core.init_sweep, wl, ecfg))(jnp.arange(S, dtype=jnp.int64))
# a few real steps so queues/wstate have representative content
warm = jax.jit(partial(core.step_batch, wl, ecfg))
for _ in range(8):
    state = warm(state)
jax.block_until_ready(state)


def _fold(acc, out):
    """Fold every output leaf into the int64 carry (defeats DCE)."""
    for leaf in jax.tree.leaves(out):
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            leaf = jax.random.key_data(leaf)
        acc = acc + jnp.sum(leaf.astype(jnp.int64))
    return acc


def timeit(name, body, n=ITERS, reps=3):
    """body(i, acc) -> acc, looped on-device; prints per-iter ms.

    Two loop lengths (n and 4n) and the difference quotient, so the
    ~90 ms (and noisy) per-call dispatch+readback cost cancels exactly
    instead of being subtracted as a separately-measured constant."""

    def make(k):
        @jax.jit
        def run(salt):
            return jax.lax.fori_loop(0, k, body, salt.astype(jnp.int64))

        return run

    run_n, run_4n = make(n), make(4 * n)
    int(run_n(jnp.int64(0)))  # compile
    int(run_4n(jnp.int64(0)))
    t_n = t_4n = float("inf")
    for r in range(1, reps + 1):
        t0 = time.perf_counter()
        int(run_n(jnp.int64(2 * r)))  # fresh salt → not memoizable
        t_n = min(t_n, time.perf_counter() - t0)
        t0 = time.perf_counter()
        int(run_4n(jnp.int64(2 * r + 1)))
        t_4n = min(t_4n, time.perf_counter() - t0)
    per = (t_4n - t_n) / (3 * n)
    print(f"{name:28s} {per * 1e3:8.3f} ms")
    return per

step = partial(core.step_batch, wl, ecfg)


def step_body(i, acc):
    # chain a salted state so every iteration differs
    s = state._replace(ctr=state.ctr + (acc % 7).astype(jnp.int32))
    return _fold(acc, step(s))


timeit("step_batch (full)", step_body)


def rng_body(i, acc):
    bits = jax.vmap(lambda k, c: event_bits(k, c, wl.num_rand + 2))(
        state.key, state.ctr + i.astype(jnp.int32)
    )
    return _fold(acc, bits)


timeit("event_bits", rng_body)

rand0 = jax.vmap(lambda k, c: event_bits(k, c, wl.num_rand + 2))(state.key, state.ctr)


def pop_body(i, acc):
    tie = rand0[:, 1] + i.astype(jnp.uint32)
    out = jax.vmap(lambda q, t: equeue.pop_min(q, tie_u32=t))(state.queue, tie)
    return _fold(acc, out)


timeit("pop_min (tie-break)", pop_body)

_, _, kind0, pay0, _ = jax.vmap(lambda q, t: equeue.pop_min(q, tie_u32=t))(
    state.queue, rand0[:, 1]
)


def handler_body(i, acc):
    rand = rand0[:, 2:] ^ i.astype(jnp.uint32)
    out = jax.vmap(wl.handle)(state.wstate, state.now_ns, kind0, pay0, rand)
    return _fold(acc, out)


timeit("handler (6-way switch)", handler_body)

_, emits0 = jax.vmap(wl.handle)(state.wstate, state.now_ns, kind0, pay0, rand0[:, 2:])


def push_body(i, acc):
    times = emits0.times + i
    out = jax.vmap(
        lambda q, t, k, p, e: equeue.push_many(q, t, k, p, e)
    )(state.queue, times, emits0.kinds, emits0.pays, emits0.enables)
    return _fold(acc, out)


timeit("push_many (rank-select)", push_body)

print(f"\nbatch={S}, iters={ITERS}, backend={jax.default_backend()}")
