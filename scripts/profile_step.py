"""Phase-level profiling of the engine step on the current backend.

Times each component of step_batch in isolation (jitted, vmapped over the
same seed batch) plus the full step, so the dominant cost is measurable
rather than guessed. Run on TPU:  python scripts/profile_step.py [S]
"""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu.engine import core, queue as equeue
from madsim_tpu.engine.rng import event_bits
from madsim_tpu.models import raft

S = int(sys.argv[1]) if len(sys.argv) > 1 else 16384

cfg = raft.RaftConfig(num_nodes=5, crashes=1)
ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000)
wl = raft.workload(cfg)

seeds = jnp.arange(S, dtype=jnp.int64)
state = jax.jit(partial(core.init_sweep, wl, ecfg))(seeds)
jax.block_until_ready(state)


def timeit(name, fn, *args, n=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:28s} {dt*1e3:8.3f} ms")
    return out


# full step
step = jax.jit(partial(core.step_batch, wl, ecfg))
timeit("step_batch (full)", step, state)

# rng only — the engine draws num_rand + 2 words per event (rand[0] clock
# jitter, rand[1] pop tie-break, rand[2:] handler draws; engine/core.py
# _pop_event)
rng = jax.jit(jax.vmap(lambda k, c: event_bits(k, c, wl.num_rand + 2)))
rand0 = timeit("event_bits", rng, state.key, state.ctr)

# pop only (with the tie-break draw, as the real step does)
pop = jax.jit(jax.vmap(lambda q, t: equeue.pop_min(q, tie_u32=t)))
timeit("pop_min (tie-break)", pop, state.queue, rand0[:, 1])

# handler only (all six branches under vmapped switch)
_, _, kind0, pay0, _ = jax.vmap(lambda q, t: equeue.pop_min(q, tie_u32=t))(
    state.queue, rand0[:, 1]
)


def handler_only(wstate, now, kind, pay, rand):
    return wl.handle(wstate, now, kind, pay, rand)


h = jax.jit(jax.vmap(handler_only))
wstate2, emits = timeit(
    "handler (6-way switch)", h, state.wstate, state.now_ns, kind0, pay0, rand0[:, 2:]
)

# each branch alone, forced kind
for k, nm in [(0, "election"), (1, "heartbeat"), (2, "msg"), (3, "crash"), (5, "cmd")]:
    hk = jax.jit(
        jax.vmap(
            lambda wstate, now, pay, rand, _k=k: wl.handle(
                wstate, now, jnp.int32(_k), pay, rand
            )
        )
    )
    timeit(f"handler kind={nm}", hk, state.wstate, state.now_ns, pay0, rand0[:, 2:])

# push only
pm = jax.jit(
    jax.vmap(lambda q, e: equeue.push_many(q, e.times, e.kinds, e.pays, e.enables))
)
timeit("push_many (rank-select)", pm, state.queue, emits)

# select tree only (the done-mask select over wstate)
sel = jax.jit(
    jax.vmap(
        lambda p, a, b: jax.tree.map(lambda x, y: jnp.where(p, x, y), a, b)
    )
)
timeit("wstate select tree", sel, state.done, wstate2, state.wstate)

print(f"\nbatch={S}, backend={jax.default_backend()}")
