"""Streaming sweep service smoke (docs/streaming.md, `make stream-smoke`).

End-to-end assertions of the persistent lane pool's contracts on the
CPU backend, small enough for `make stest`:

1. streaming == chunked: `stream_sweep` totals byte-equal to
   `run_sweep_pipelined` over the same (seeds, chunk_size), on the
   screened etcd checked sweep (screen + WGL host work riding along);
2. refill-schedule invariance: a permuted `queue_order` (lanes retire
   and refill in a completely different order) changes nothing;
3. interrupt/resume: stopping after a few rounds into a v9 stream
   snapshot and resuming reproduces the uninterrupted totals exactly;
4. zero-compile: a warmed stream over a fresh seed range performs 0 XLA
   compilations (`engine/compiles.count_compiles`), and occupancy stays
   high (the whole point of continuous refill);
5. telemetry rides along out-of-band: the first leg runs under an
   `obs.Telemetry` handle and its registry drives the progress heartbeat
   (seeds done, seeds/s, occupancy, ETA on stderr) — with the report
   bytes still equal to the uninstrumented chunked run.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    from madsim_tpu import obs
    from madsim_tpu.engine.checkpoint import run_sweep_pipelined
    from madsim_tpu.engine.compiles import count_compiles
    from madsim_tpu.engine.stream import stream_sweep
    from madsim_tpu.models import etcd
    from madsim_tpu.oracle.screen import history_host_work, screen_sweep

    cfg = etcd.EtcdConfig(hist_slots=128, bug_stale_read=True)
    ecfg = etcd.engine_config(cfg, time_limit_ns=1_000_000_000, max_steps=6_000)
    wl = etcd.workload(cfg)
    spec = etcd.history_spec()
    screen = lambda final: screen_sweep(final, spec)  # noqa: E731
    hw = history_host_work(spec)
    seeds = jnp.arange(96, dtype=jnp.int64)
    kw = dict(chunk_size=32, host_work=hw, screen=screen)

    t0 = time.perf_counter()
    chunked = run_sweep_pipelined(wl, ecfg, seeds, etcd.sweep_summary, **kw)
    stats: dict = {}
    # the obs-registry heartbeat (satellite of docs/observability.md):
    # the stream driver counts stream_seeds_done_total / sets
    # stream_occupancy as it runs, and the heartbeat prints from those
    # series — the telemetry must NOT change the report (asserted below)
    telem = obs.Telemetry()
    hb = obs.Heartbeat(telem.registry, len(seeds), prefix="stream")
    streamed = stream_sweep(
        wl, ecfg, seeds, etcd.sweep_summary, pool_size=32, round_steps=256,
        stats=stats, telemetry=telem, **kw,
    )
    hb_line = hb.tick(force=True)
    assert hb_line is not None and f"{len(seeds)}/{len(seeds)}" in hb_line, (
        f"heartbeat did not see the registry's seed count: {hb_line!r}"
    )
    assert streamed == chunked, (
        f"stream totals diverge from chunked:\n{streamed}\nvs\n{chunked}"
    )
    print(
        f"stream == chunked: OK ({streamed['hist_violations']} violations, "
        f"{streamed['hist_unique']}/{streamed['hist_suspects']} unique "
        f"suspects, occupancy {stats['occupancy_mean']:.3f} over "
        f"{stats['rounds']} rounds, telemetry out-of-band)"
    )

    order = np.random.default_rng(7).permutation(len(seeds))
    permuted = stream_sweep(
        wl, ecfg, seeds, etcd.sweep_summary, pool_size=32, round_steps=256,
        queue_order=order, **kw,
    )
    assert permuted == chunked, "permuted refill schedule changed the report"
    print("refill-schedule invariance: OK (permuted queue, same bytes)")

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "stream.npz")
        partial = stream_sweep(
            wl, ecfg, seeds, etcd.sweep_summary, pool_size=32,
            round_steps=256, ckpt_path=path, stop_after_rounds=2, **kw,
        )
        assert os.path.exists(path), "no v9 stream snapshot written"
        resumed = stream_sweep(
            wl, ecfg, seeds, etcd.sweep_summary, pool_size=32,
            round_steps=256, resume_from=path, **kw,
        )
    assert resumed == chunked, "interrupt/resume changed the totals"
    print("interrupt/resume via v9 snapshot: OK (bit-identical totals)")

    fresh = jnp.arange(1000, 1000 + 96, dtype=jnp.int64)
    with count_compiles() as c:
        warm_stats: dict = {}
        stream_sweep(
            wl, ecfg, fresh, etcd.sweep_summary, pool_size=32,
            round_steps=256, stats=warm_stats, **kw,
        )
    assert c.count == 0, f"{c.count} XLA compilations in a warmed stream"
    assert warm_stats["occupancy_mean"] > 0.5, (
        f"pool occupancy collapsed: {warm_stats['occupancy_mean']:.3f}"
    )
    print(
        f"warmed stream: OK (0 XLA compiles, occupancy "
        f"{warm_stats['occupancy_mean']:.3f})"
    )
    print(
        f"stream smoke: ALL OK in {time.perf_counter() - t0:.1f}s "
        f"(backend={jax.default_backend()})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
