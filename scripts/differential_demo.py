"""The host↔device differential gate (docs/faults.md "Gray failures").

Runs `examples/raft_host.py` and the device raft model (amnesia mode)
over a matched `(spec, seed)` grid — one FaultSpec compiles to the
identical fault schedule on both tiers — for a baseline crash storm plus
one spec per gray-failure family (asymmetric partitions, fsync-stall +
power-fail, clock skew), then asserts:

- outcome distributions (election / no-leader / violation rates) agree
  within the documented per-mille tolerances;
- each tier's recorded election history passes/fails
  `oracle.specs.ElectionSpec` exactly when that tier's own online
  violation latch fired (the checker cross-validates the latches);
- the JSON report is canonical (sorted keys, integers only) — the
  determinism gate (`scripts/check_determinism.sh`) byte-diffs it
  across two processes.

Run on CPU:  JAX_PLATFORMS=cpu python scripts/differential_demo.py
(`make differential-smoke` wires it into `make stest`.)
Exit code: 0 iff every spec's tolerance verdict passed.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu.explore.differential import (  # noqa: E402
    DifferentialConfig,
    gate_specs,
    run_differential,
)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=200)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--sim-seconds", type=float, default=2.0)
    ap.add_argument(
        "--specs", type=int, default=0,
        help="run only the first N gate specs (0 = all four)",
    )
    ap.add_argument("--report", default=None, help="write the JSON report here")
    args = ap.parse_args()

    dcfg = DifferentialConfig(
        seeds=args.seeds, seed0=args.seed0, sim_seconds=args.sim_seconds
    )
    specs = gate_specs()
    if args.specs:
        specs = specs[: args.specs]
    report = run_differential(specs, dcfg, report_path=args.report)

    for rec in report["specs"]:
        fams = {
            k: rec["spec"][k]
            for k in ("crashes", "aparts", "fsync_stalls", "power_fails", "skews")
            if rec["spec"].get(k)
        }
        line = {
            "spec": fams,
            "device": rec["device"],
            "host": rec["host"],
            "deltas": rec["deltas"],
            "pass": rec["pass"],
        }
        print(json.dumps(line, sort_keys=True))
    verdict = "PASS" if report["pass"] else "FAIL"
    print(
        f"differential gate: {verdict} "
        f"({len(report['specs'])} specs x {dcfg.seeds} matched seeds)"
    )
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
