"""History-oracle demo: sweep -> check -> triage -> shrink -> byte-compare.

The end-to-end acceptance path of madsim_tpu/oracle (docs/oracle.md),
sized to run in under a minute on the CPU backend (`make oracle-smoke`):

1. sweep the seeded etcd stale-read bug config over a pinned seed range
   and decode every lane's recorded operation history;
2. the WGL linearizability checker rejects at least one seed — with NO
   model-specific probe involved (the online invariant latches all stay
   quiet on this bug, which is the point);
3. triage fingerprints the failure under the ``history`` flavor;
4. the shrinker ddmin-reduces the fault schedule to a minimal
   ``(FixedFaults, seed)`` the checker STILL rejects (every candidate
   re-verified through the checker, not the probe);
5. the sweep-extracted history bytes for that seed equal the bit-exact
   CPU ``run_traced`` replay's — the cross-path determinism contract;
6. the matching clean config checks linearizable across the whole
   pinned range (no false positives).

Exit code 0 iff all six hold.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=32, help="pinned sweep size")
    ap.add_argument("--shrink-tests", type=int, default=8)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import explore
    from madsim_tpu.engine import core as ecore
    from madsim_tpu.explore.targets import oracle_demo_faults
    from madsim_tpu.oracle import (
        check_history,
        decode_seed,
        decode_sweep,
        history_bytes,
    )

    t0 = time.time()
    spec = oracle_demo_faults()
    seeds = jnp.arange(args.seeds, dtype=jnp.int64)

    # 1-2. find: the checker rejects seeds of the seeded-bug sweep
    target = explore.stale_etcd_target()
    workload, ecfg = target.build(spec)
    final = ecore.run_sweep(workload, ecfg, seeds)
    vio = np.asarray(target.violating(final))
    print(f"[{time.time()-t0:5.1f}s] bug sweep: {vio.size}/{args.seeds} "
          f"seeds non-linearizable {[int(x) for x in vio[:8]]}")
    if vio.size == 0:
        print("FAIL: checker never fired on the seeded bug", file=sys.stderr)
        return 1
    online = int(np.asarray(final.wstate.violation).sum())
    if online:
        print("FAIL: online latches saw the stale-read bug — the demo's "
              "premise (probe-invisible defect) broke", file=sys.stderr)
        return 1
    seed = int(vio[0])

    # 3. triage: the history fingerprint flavor
    failure = explore.triage_seed(target, spec, seed, history=True)
    if failure is None or ":history:" not in failure.fingerprint:
        print(f"FAIL: triage lost the failure ({failure})", file=sys.stderr)
        return 1
    print(f"[{time.time()-t0:5.1f}s] triage: seed {seed} -> "
          f"{failure.fingerprint} (op #{failure.step})")

    # 4. shrink: minimal FixedFaults, every candidate checker-verified
    sr = explore.shrink(
        target, spec, seed, max_tests=args.shrink_tests, history=True
    )
    if sr is None or sr.fingerprint != failure.fingerprint:
        print(f"FAIL: shrink lost the fingerprint ({sr})", file=sys.stderr)
        return 1
    again = explore.triage_seed(target, sr.spec, sr.seed, history=True)
    if again is None or again.fingerprint != failure.fingerprint:
        print("FAIL: minimal triple does not reproduce", file=sys.stderr)
        return 1
    print(f"[{time.time()-t0:5.1f}s] shrink: {sr.original_len} -> "
          f"{len(sr.schedule)} fault events ({sr.tests} replays)")

    # 5. cross-path byte identity: sweep lane vs CPU traced replay
    lane = int(np.nonzero(np.asarray(final.seed) == seed)[0][0])
    sweep_bytes = history_bytes(decode_seed(final, lane))
    traced_final, _ = ecore.run_traced(workload, ecfg, seed)
    traced_bytes = history_bytes(decode_seed(traced_final))
    if sweep_bytes != traced_bytes:
        print("FAIL: sweep-extracted history != traced-replay history",
              file=sys.stderr)
        return 1
    print(f"[{time.time()-t0:5.1f}s] byte identity: sweep lane == traced "
          f"replay ({len(sweep_bytes)} bytes)")

    # 6. clean control: no false positives over the same pinned range
    clean = explore.stale_etcd_target(bug_stale_read=False)
    cw, ce = clean.build(spec)
    cfinal = ecore.run_sweep(cw, ce, seeds)
    bad = []
    for h in decode_sweep(cfinal):
        r = check_history(h, clean.hist_spec)
        if not r.ok:
            bad.append((h.seed, r.reason))
    if bad:
        print(f"FAIL: clean config flagged {bad[:3]}", file=sys.stderr)
        return 1
    print(f"[{time.time()-t0:5.1f}s] clean sweep: all {args.seeds} seeds "
          "linearizable")
    print("oracle demo: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
