"""A/B: Pallas pop-min kernel vs the XLA path, honest methodology.

Runs both implementations of the batched pop decision over identical
queue states, asserts bit-identical results (slots AND found flags — the
kernel must be a drop-in for replay parity), then times each with fresh
inputs per call and a forced scalar readback (the tunneled device
memoizes same-input executions and `block_until_ready` under-reports, so
naive timing produces fantasy numbers — see docs/pallas_finding.md).

    python scripts/bench_pallas.py [S ...]   (default 16384 65536)
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu.engine import core, pallas_queue as pq
from madsim_tpu.models import raft

SIZES = [int(a) for a in sys.argv[1:]] or [16384, 65536]

cfg = raft.RaftConfig(num_nodes=5, crashes=1)
ecfg = raft.engine_config(cfg)
wl = raft.workload(cfg)
on_tpu = jax.default_backend() == "tpu"


def fresh_inputs(s, offset, warm_steps=16):
    """A materialized queue batch with realistic occupancy + a tie draw."""
    state = jax.jit(partial(core.init_sweep, wl, ecfg))(
        jnp.arange(offset, offset + s, dtype=jnp.int64)
    )
    step = jax.jit(partial(core.step_batch, wl, ecfg))
    for _ in range(warm_steps):
        state = step(state)
    tie = jax.random.bits(jax.random.key(offset), (s,), dtype=jnp.uint32)
    jax.block_until_ready(state)
    return state.queue, tie


ITERS = 512  # on-device repetitions per timed call: a single dispatch
# through the tunnel costs ~100 ms wall regardless of work, so the op
# must be amortized inside one program to be measurable


def looped(fn):
    """fn repeated ITERS times on-device with varying tie draws; returns a
    jitted callable whose scalar output forces everything to run."""

    @jax.jit
    def run(q, ties):
        def body(i, acc):
            slot, found = fn(q, ties[i])
            return acc + jnp.sum(slot) + jnp.sum(found)

        return jax.lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.int64))

    return run


def timed(run, inputs_list):
    best = float("inf")
    for q, ties in inputs_list:
        t0 = time.perf_counter()
        int(run(q, ties))  # host readback = real completion
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    pallas = partial(pq.pop_min_pallas, interpret=not on_tpu)
    for s in SIZES:
        # parity first: the kernel must pick bit-identical slots
        q, tie = fresh_inputs(s, offset=7 * s)
        sx, fx = pq.pop_min_xla(q, tie)
        sp, fp = pallas(q, tie)
        assert jnp.array_equal(sx, sp) and jnp.array_equal(fx, fp), (
            f"kernel diverged from XLA path at S={s}"
        )

        def with_ties(i):
            q, _ = fresh_inputs(s, offset=(i + 1) * 100 * s)
            ties = jax.random.bits(jax.random.key(i), (ITERS, s), dtype=jnp.uint32)
            return q, ties

        inputs = [with_ties(i) for i in range(3)]
        run_xla, run_pal = looped(pq.pop_min_xla), looped(pallas)
        int(run_xla(*inputs[0]))  # compile
        int(run_pal(*inputs[0]))
        t_xla = timed(run_xla, inputs[1:]) / ITERS
        t_pal = timed(run_pal, inputs[1:]) / ITERS
        print(
            f"S={s:6d}  xla={t_xla * 1e6:8.1f} us/op  "
            f"pallas={t_pal * 1e6:8.1f} us/op  "
            f"pallas/xla={t_pal / t_xla:5.2f}x  (parity: identical)"
        )
    print(f"backend={jax.default_backend()} (pallas interpret={not on_tpu}, "
          f"iters={ITERS})")


if __name__ == "__main__":
    main()
