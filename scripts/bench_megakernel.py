"""A/B: VMEM-resident megakernel vs the flat XLA sweep loop.

Measures the round-3 headroom claim (docs/pallas_finding.md §3): the XLA
driver's ~65 MB loop carry round-trips HBM every event at a 16k batch —
does keeping each seed-tile's state resident in VMEM across many steps
buy the projected ≲2.7×?

Methodology (same rules as scripts/bench_pallas.py — see
docs/pallas_finding.md §0): fresh inputs per timed call (the tunneled
device memoizes same-input executions), completion bounded by a scalar
readback, many steps amortized inside one program (~100 ms fixed
dispatch+readback latency per call), compile excluded by a warmup call
per shape.

Run on the TPU:  python scripts/bench_megakernel.py
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp

from madsim_tpu.engine import core
from madsim_tpu.engine import megakernel as mk

STEPS = 512
BATCHES = (4096, 16384, 65536)
# >=512-seed tiles exceed the 16 MB scoped-VMEM budget (the compiler
# stages the kernel's in+out tuples, ~2x the tile state); 64 measured best
TILES = (64, 128, 256)
REPS = 5

_seed_base = [0]


def fresh_seeds(n: int) -> jnp.ndarray:
    lo = _seed_base[0]
    _seed_base[0] += n
    return jnp.arange(lo, lo + n, dtype=jnp.int64)


def readback(state) -> int:
    return int(jnp.sum(state.ctr)) + int(jnp.sum(state.wstate.acc))


def timed(fn, s0):
    t0 = time.perf_counter()
    out = fn(s0)
    rb = readback(out)
    return time.perf_counter() - t0, rb


def main() -> None:
    wl = mk.probe_workload()
    cfg = mk.probe_config(max_steps=STEPS)
    print(f"# devices: {jax.devices()}", file=sys.stderr)

    results = []
    for S in BATCHES:
        xla = lambda s0: jax.block_until_ready(core._drive(wl, cfg, s0))  # noqa: E731

        # one fixed verification batch per size: EVERY tile that gets
        # timed must first reproduce the XLA driver's final state
        # bit-exactly on it (a tile-size-dependent miscompile must not
        # publish a timing as verified); the comparison doubles as the
        # warmup/compile call
        s_verify = core._init(wl, cfg, fresh_seeds(S))
        ref = core._drive(wl, cfg, s_verify)

        # contenders, then INTERLEAVED reps — the tunneled device drifts
        # ±30% over minutes, so only alternating measurements in one
        # process compare fairly (min-of-reps)
        contenders = {"xla": xla}
        for tile in TILES:
            if S % tile:
                continue
            mega = lambda s0, t=tile: mk.run_megasweep(  # noqa: E731
                s0, steps=STEPS, time_limit=cfg.time_limit_ns, tile=t
            )
            try:
                got = mega(s_verify)
            except Exception as e:  # e.g. a tile too big for scoped VMEM
                print(json.dumps({"batch": S, "tile": tile,
                                  "skipped": str(e).splitlines()[0][:120]}),
                      file=sys.stderr)
                continue
            leaves = jax.tree.leaves(
                jax.tree.map(lambda a, b: bool(jnp.array_equal(a, b)), ref, got)
            )
            assert all(leaves), f"megakernel diverged at S={S} tile={tile}"
            contenders[f"mega{tile}"] = mega
        if len(contenders) == 1:
            print(json.dumps({"batch": S,
                              "skipped": "no megakernel tile compiled"}),
                  file=sys.stderr)
            continue
        s0 = core._init(wl, cfg, fresh_seeds(S))
        timed(xla, s0)  # warmup
        times = {name: [] for name in contenders}
        for _ in range(REPS):
            for name, fn in contenders.items():
                s0 = core._init(wl, cfg, fresh_seeds(S))
                dt, _ = timed(fn, s0)
                times[name].append(dt)
        xla_us = min(times["xla"]) / STEPS * 1e6
        tile_rows = {
            int(name[4:]): min(ts) / STEPS * 1e6
            for name, ts in times.items() if name.startswith("mega")
        }

        best_tile = min(tile_rows, key=tile_rows.get)
        row = {
            "batch": S,
            "steps": STEPS,
            "xla_us_per_step": round(xla_us, 1),
            "mega_us_per_step": {str(t): round(v, 1) for t, v in tile_rows.items()},
            "best_tile": best_tile,
            "mega_over_xla": round(tile_rows[best_tile] / xla_us, 2),
            "bit_exact": True,
        }
        results.append(row)
        print(json.dumps(row))

    print(json.dumps({"summary": results}), file=sys.stderr)


if __name__ == "__main__":
    main()
