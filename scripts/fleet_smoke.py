"""Fleet orchestrator smoke: shared store, kill -9 reclaim, replay gate.

Usage:
    python scripts/fleet_smoke.py [--outdir DIR]          # the full drill
    python scripts/fleet_smoke.py --merged-only --workers N --report PATH
    (internal) python scripts/fleet_smoke.py --worker --store DIR ...

The full drill (``make fleet-smoke``) checks the ISSUE-16 "Done" bar
end to end, every leg in a SEPARATE process:

1. a solo worker sweeps the whole unit plan into a fresh store and
   writes the merged fleet report — the reference bytes;
2. two independent workers share a second store (the first capped to
   half the units, so each genuinely runs only part of the plan): the
   merged report must be BYTE-IDENTICAL to the solo run, and the merged
   distinct-fingerprint count must be STRICTLY greater than what either
   worker found alone;
3. the kill drill: a worker on a third store is killed by ``os._exit``
   mid-append after one unit (torn final record, no done marker, a
   lease left to expire); a second worker quarantines nothing (torn
   tails drop), reclaims the dead worker's unit, re-runs it, and the
   merged report STILL matches the solo bytes;
4. the regression gate replays every stored bug bit-exactly inside each
   later worker's startup (their JSON output carries the verdict).

``--merged-only`` is the check_determinism.sh fleet leg: run the plan
on a fresh store with N workers and write the merged report — the gate
byte-diffs it across 2 driver processes x 2 worker counts.

Exit code 0 = every assertion held. Stdout's last line is a JSON
summary (machine-readable); progress goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# one pinned drill config (campaign seed 7 on purpose: its unit plan
# spreads the two raft-amnesia fingerprints across the two halves of a
# 4-unit plan — units 0-1 reach only n0, units 2-3 only n1 — which is
# what makes the strictly-more-than-either-alone assertion meaningful)
UNITS = 4
CFG = dict(seeds_per_round=24, batch=2, chunk_size=24,
           campaign_seed=7, max_recorded_seeds=4)
TARGET_KW = dict(time_limit_ns=1_500_000_000, max_steps=15_000, hist_slots=0)
SHRINK_TESTS = 24


def _build():
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.explore import CampaignConfig, amnesia_raft_target

    target = amnesia_raft_target(**TARGET_KW)
    base = FaultSpec(
        crashes=3, crash_window_ns=1_200_000_000,
        restart_lo_ns=50_000_000, restart_hi_ns=300_000_000,
    )
    return target, base, CampaignConfig(**CFG)


def worker_main(args) -> None:
    """One fleet worker process (the internal --worker mode)."""
    from madsim_tpu.explore import CorpusStore, run_worker

    target, base, ccfg = _build()
    store = CorpusStore(args.store, worker=args.name, ttl_s=args.ttl)
    res = run_worker(
        target, base, ccfg, store, args.units,
        max_units=args.max_units, shrink_tests=SHRINK_TESTS,
        skip_gate=args.skip_gate,
        _crash_after_units=args.crash_after,
    )
    reader = CorpusStore(args.store, worker=f"{args.name}-read")
    _, stats = reader.read_records()
    out = {
        "worker": args.name,
        "units": res["units"],
        "fingerprints": res["fingerprints"],
        "gate": res["gate"],
        "stats": {
            "lines": stats.lines,
            "quarantined": stats.quarantined,
            "truncated_logs": stats.truncated_logs,
        },
    }
    print(json.dumps(out, sort_keys=True))


def report_main(args) -> None:
    """Write the merged fleet report (the internal --report-only mode —
    import-only, no sweeps, so the drivers stay light)."""
    from madsim_tpu.explore import CorpusStore, write_merged

    write_merged(CorpusStore(args.store, worker="report"), args.report)


def _spawn(store: str, name: str, *extra: str) -> subprocess.Popen:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--store", store, "--name", name, "--units", str(UNITS), *extra,
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env
    )


def _run_worker_proc(store: str, name: str, *extra: str) -> dict:
    p = _spawn(store, name, *extra)
    out, _ = p.communicate(timeout=900)
    if p.returncode != 0:
        raise SystemExit(f"worker {name} failed rc={p.returncode}")
    print(f"[fleet-smoke] worker {name} done", file=sys.stderr)
    return json.loads(out.strip().splitlines()[-1])


def _write_report(store: str, path: str) -> str:
    subprocess.run(
        [
            sys.executable, os.path.abspath(__file__), "--report-only",
            "--store", store, "--report", path,
        ],
        check=True, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    return open(path).read()


def merged_only(args) -> None:
    """The determinism-leg mode: N workers over a fresh store, merged
    report to --report. Bytes must not depend on N (the gate diffs)."""
    with tempfile.TemporaryDirectory() as d:
        store = os.path.join(d, "store")
        half = -(-UNITS // args.workers)
        for i in range(args.workers):
            extra = [] if i == args.workers - 1 else ["--max-units", str(half)]
            _run_worker_proc(
                store, f"w{i}", *extra, *(["--skip-gate"] if i else [])
            )
        _write_report(store, args.report)


def drill(args) -> None:
    outdir = args.outdir or tempfile.mkdtemp(prefix="fleet_smoke_")
    os.makedirs(outdir, exist_ok=True)
    summary: dict = {}

    # leg 1: solo reference
    s_solo = os.path.join(outdir, "solo")
    solo = _run_worker_proc(s_solo, "solo")
    ref = _write_report(s_solo, os.path.join(outdir, "merged_solo.jsonl"))
    assert solo["units"] == list(range(UNITS)), solo
    assert solo["fingerprints"], "solo run found no bugs; drill is vacuous"
    summary["solo_fps"] = solo["fingerprints"]

    # leg 2: two independent processes share one store; merged bytes
    # identical to solo, fingerprint union strictly above either share
    s_shared = os.path.join(outdir, "shared")
    wa = _run_worker_proc(s_shared, "wa", "--max-units", str(UNITS // 2))
    wb = _run_worker_proc(s_shared, "wb")
    shared = _write_report(
        s_shared, os.path.join(outdir, "merged_shared.jsonl")
    )
    assert shared == ref, "shared-store merged bytes diverged from solo"
    merged_fps = sorted(
        json.loads(ln)["key"] for ln in ref.splitlines()
        if json.loads(ln).get("kind") == "bug"
    )
    assert len(merged_fps) > len(wa["fingerprints"]), (merged_fps, wa)
    assert len(merged_fps) > len(wb["fingerprints"]), (merged_fps, wb)
    assert set(wa["fingerprints"]) | set(wb["fingerprints"]) == set(merged_fps)
    # worker B's startup gate replayed worker A's stored bugs bit-exactly
    assert wa["gate"]["ok"] and wa["gate"]["checked"] == 0, wa["gate"]
    assert wb["gate"]["ok"] and wb["gate"]["checked"] >= 1, wb["gate"]
    summary["wa_fps"] = wa["fingerprints"]
    summary["wb_fps"] = wb["fingerprints"]
    summary["merged_fps"] = merged_fps
    summary["gate_checked"] = wb["gate"]["checked"]

    # leg 3: kill -9 mid-append + reclaim
    s_kill = os.path.join(outdir, "kill")
    p = _spawn(s_kill, "victim", "--crash-after", "1", "--ttl", "1")
    p.communicate(timeout=900)
    assert p.returncode == 137, f"victim exited {p.returncode}, wanted 137"
    print("[fleet-smoke] victim killed mid-append", file=sys.stderr)
    rec = _run_worker_proc(s_kill, "reclaimer", "--ttl", "1")
    killed = _write_report(s_kill, os.path.join(outdir, "merged_kill.jsonl"))
    assert killed == ref, "kill-and-reclaim merged bytes diverged from solo"
    # the victim's torn final record was dropped, not quarantined, and
    # its unleased units (everything it never finished) were re-run
    assert rec["stats"]["truncated_logs"] >= 1, rec["stats"]
    assert rec["stats"]["quarantined"] == 0, rec["stats"]
    assert rec["gate"]["ok"], rec["gate"]
    summary["reclaimer_units"] = rec["units"]
    summary["reclaimer_gate"] = rec["gate"]

    summary["merged_bytes"] = len(ref)
    summary["ok"] = True
    print(json.dumps(summary, sort_keys=True))
    print(f"[fleet-smoke] OK ({outdir})", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--report-only", action="store_true")
    ap.add_argument("--merged-only", action="store_true")
    ap.add_argument("--store", type=str)
    ap.add_argument("--name", type=str, default=None)
    ap.add_argument("--units", type=int, default=UNITS)
    ap.add_argument("--max-units", type=int, default=None)
    ap.add_argument("--crash-after", type=int, default=None)
    ap.add_argument("--ttl", type=float, default=30.0)
    ap.add_argument("--skip-gate", action="store_true")
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--report", type=str, default=None)
    ap.add_argument("--outdir", type=str, default=None)
    args = ap.parse_args()
    if args.worker:
        worker_main(args)
    elif args.report_only:
        report_main(args)
    elif args.merged_only:
        merged_only(args)
    else:
        drill(args)


if __name__ == "__main__":
    main()
