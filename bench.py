"""Benchmark: MadRaft seed-sweep throughput, TPU engine vs host-tier CPU.

Prints ONE JSON line whose headline is the largest-batch MadRaft sweep
(BASELINE.md config #3: 5-node Raft election + replication with
crash/restart fault injection, 3 virtual seconds per seed), with:

- ``batch_curve``: seeds/sec at 4k/16k/64k (throughput scales with the
  lockstep batch; per-batch compile and run times reported separately);
- ``sweep_100k``: BASELINE config #5's pod-scale artifact — 131,072
  seeds run as 16,384-seed chunks of one compiled program, per-chunk
  summaries merged on host (constant device memory);
- ``recovery_e2e``: config #5's determinism half — a sweep interrupted
  at 300 steps, checkpointed to .npz, restored, resumed, and verified
  bit-identical to the uninterrupted run;
- ``cross_backend``: the hardware bit-parity contract, self-verified —
  a 4096-seed sweep on the TPU vs the same seeds on the CPU backend,
  every EngineState leaf compared, plus one CPU traced replay against
  its TPU sweep lane;
- ``kafka``: BASELINE config #4 as a second workload line (10k-seed
  broker crash/restart sweep with the acked-loss checker quiet);
- ``etcd``: BASELINE config #2 (8k-seed 3-node KV + lease sweep with
  partition injection, revision/lease checkers quiet);
- honest baseline framing: ``vs_baseline`` divides by THIS REPO's
  single-threaded Python host executor running the same workload — the
  reference publishes no numbers (BASELINE.md) and its Rust toolchain is
  not in this image, so ``baseline.reference_note`` records the honest
  order-of-magnitude estimate instead of a fake ratio.

Timing methodology per docs/pallas_finding.md §0: fresh seed ranges per
timed run (the tunneled device memoizes same-input executions), a scalar
host readback to bound completion, and — because the tunneled chip drifts
±30% across minutes and the host tier ±15% with machine load — every
timed figure is the MIN of ``REPS`` interleaved repetitions (rep-outer,
case-inner, exactly like scripts/bench_megakernel.py), with the
max-over-min spread reported per point. The headline ``value`` is the
chunked 131k sweep (the production pattern and the most drift-resistant
number: ~3 s of device work per rep), not a single-shot curve point.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time as walltime

import jax
import jax.numpy as jnp
import numpy as np

SIM_SECONDS = 3.0
# 48 seeds keeps the host-tier measurement under ~0.5 s now that the
# compiled executor core runs >100 seeds/s (was 8 when it ran at ~37/s —
# flagged as too thin for the vs_baseline denominator)
HOST_SEEDS = 48
# 32,768 brackets the occupancy knee: r05 measured 45.1k seeds/s at
# 16,384 and 33.9k at 65,536 with nothing in between, so the cliff's
# location was a guess; each point now also reports its loop-carry HBM
# footprint (core.state_bytes_per_seed) so the knee is attributable
CURVE = (4096, 16384, 32768, 65536)
# 131,072 seeds — the "100k-seed" artifact — as 16k chunks of one
# compiled program: per-lane step cost cliffs ~9x above ~16k seeds
# (see core.run_sweep_chunked), so chunking IS the fast path
BIG_TOTAL = 131072
BIG_CHUNK = 16384
# min-of-REPS interleaved repetitions per timed figure (drift discipline;
# see module docstring)
REPS = 3
# seed-batch size for the recovery and cross-backend parity phases
PARITY_SEEDS = 4096
# checked-sweep leg (sweep + on-device screen + WGL check, end to end):
# the etcd history workload at 131k seeds through the pipelined driver,
# vs a naive decode-and-check-every-seed loop measured in the same run
CHECKED_TOTAL = 131072
CHECKED_CHUNK = None  # None = auto-pick the occupancy knee
CHECKED_SIM_SECONDS = 2.0  # hist_slots=256 is sized for a 2 s horizon
CHECKED_REPS = 2  # interleaved checked/unchecked reps (full-scale leg)
NAIVE_SEEDS = 4096
CHECK_WORKERS = 8
# pipelined-recovery leg: 2 chunks, interrupted mid-chunk-0
PIPE_SEEDS = 2048
PIPE_CHUNK = 1024
# campaign leg (explore-candidate throughput): K mutated candidates per
# measured batch, serial compile-per-candidate (the pre-refactor explore
# path) vs ONE batched (candidate x seed) spec-as-data grid — the
# compile-bound regime the spec-as-data refactor targets, so the figure
# of merit is end-to-end candidates/s including compiles
CAMPAIGN_K = 16
CAMPAIGN_SEEDS = 256
CAMPAIGN_REPS = 2
CAMPAIGN_SIM_SECONDS = 1.5
# streaming leg (persistent lane pool vs fixed-shape chunks): etcd
# under the gray-failure FaultSpec retires lanes at genuinely different
# ages (measured max/mean step spread ~1.46 per chunk — crashes starve
# some seeds of events while partition retries feed others), which is
# the straggler pattern a fixed-shape chunk drags on; the pool is
# HALF the chunk so the drain tail (the last pool-full of stragglers,
# the only stretch a stream cannot refill) stays small relative to the
# smallest curve point; round_steps can exceed the mean lane age
# (~161) because the round exits early once a refill quorum retires,
# so a large value just amortizes round dispatch
STREAM_CURVE = (4096, 16384, 32768, 65536)
STREAM_CHUNK = 1024
STREAM_POOL = 512
STREAM_ROUND_STEPS = 256
STREAM_REPS = 2
STREAM_SIM_SECONDS = 3.0
STREAM_MAX_STEPS = 2_000
# telemetry leg (obs overhead on the streaming checked-sweep path):
# the SAME stream_sweep-driven checked sweep with telemetry off
# (telemetry=None — the true zero-instrumentation baseline) vs on
# (full-fat handle: metrics + journal + trace spans), interleaved
# on/off reps per pallas_finding §0; the gate is ≤3% overhead, and the
# two legs' report dicts must be equal (the out-of-band contract,
# checked here on every bench run, byte-level in check_determinism.sh)
TELEM_SEEDS = 16384
TELEM_CHUNK = 1024
TELEM_REPS = 3
TELEM_SIM_SECONDS = 2.0
TELEM_OVERHEAD_GATE = 0.03
# steering leg (the self-steering scheduler A/B, docs/steering.md
# "What the A/B measures"): bandit vs uniform at the SAME deterministic
# device-event budget on two targets — the raft amnesia gate (10
# families, 2 crash-bearing: the uniform grid burns ~80% of its budget
# on amnesia-blind duds) and the partitioned stale-read etcd gate (its
# single reachable fingerprint saturates both policies, so its win
# metric is coverage bits, not fingerprints). One rep per cell: the
# figure of merit is fingerprints-at-matched-budget, a deterministic
# count, not a wall-clock rate (wall is reported for context only)
STEER_FAMILIES = (0x001, 0x002, 0x003, 0x004, 0x008,
                  0x010, 0x020, 0x040, 0x080, 0x100)
STEER_SEEDS_PER_ROUND = 16
STEER_ESCALATE_SEEDS = 8
STEER_KILL_PLAYS = 1
STEER_CAMPAIGN_SEED = 7
STEER_RAFT_BUDGET = 45_000
STEER_ETCD_BUDGET = 12_000
# wire-load leg (the serve/ async core under >=1k genuine-protocol
# clients; docs/wire.md "Async serving core"): one full-scale run for
# the SLO/oracle/replay gates + WIRE_REPS smaller reps for the
# throughput spread gate. Runs in SUBPROCESSES (scripts/wire_load.py):
# this process holds jax, and the rig forks worker processes — the
# parent of those forks must stay jax-free (thread-after-fork hazard)
WIRE_REP_CLIENTS = 264
WIRE_REP_SECS = 8.0
WIRE_REPS = 3

_seed_cursor = [1]


def _fresh(n: int) -> jnp.ndarray:
    lo = _seed_cursor[0]
    _seed_cursor[0] += n
    return jnp.arange(lo, lo + n, dtype=jnp.int64)


def _spread(times) -> float:
    """Max-over-min dispersion of a rep list: 0.0 = perfectly stable."""
    return round((max(times) - min(times)) / min(times), 3) if times else 0.0


# a timed figure is only comparable round-over-round when its rep
# dispersion is small; the kafka/etcd legs gate on the 3 FASTEST reps
# (the min is the figure, so extra reps tighten it — raw max/min spread
# can only grow with more reps) staying within this bound
SPREAD_GATE = 0.10
MAX_EXTRA_ROUNDS = 6


def _spread_best3(times) -> float:
    """Dispersion of the three fastest reps — the stability of the
    min-of-reps figure itself, immune to a single slow outlier."""
    return _spread(sorted(times)[:3])


def bench_host() -> dict:
    """Host-tier executor: one full simulation per seed (seeds/sec),
    min of REPS passes (the host number swings ±15% with machine load)."""
    sys.path.insert(0, __file__.rsplit("/", 1)[0] + "/examples")
    from raft_host import run_seed

    times = []
    for rep in range(REPS):
        t0 = walltime.perf_counter()
        for seed in range(HOST_SEEDS):
            run_seed(
                rep * HOST_SEEDS + seed, n=5, crashes=1, sim_seconds=SIM_SECONDS
            )
        times.append(walltime.perf_counter() - t0)
    return {
        "seeds_per_sec": round(HOST_SEEDS / min(times), 2),
        "reps": REPS,
        "spread": _spread(times),
    }


def bench_curve(wl, ecfg, raft):
    """seeds/sec at each batch size: REPS interleaved timed runs per size
    (rep-outer, size-inner, so a drift window hits every size equally),
    min taken per size; compile time split out per size. Each point
    carries its loop-carry HBM footprint so the occupancy knee
    (ROADMAP item 3) is attributable to a measured byte count.

    The AUTO-PICKED chunk size (``core.pick_chunk_size`` — what the
    chunked/pipelined drivers actually sweep at) is measured as its own
    curve point next to the raw sizes and flagged ``auto_chunk``, so
    the occupancy-cliff fix is visible in the curve itself round over
    round: the auto point must sit at or left of the knee."""
    from madsim_tpu.engine import core

    per_seed = core.state_bytes_per_seed(wl, ecfg)
    auto = core.pick_chunk_size(wl, ecfg)
    sizes = tuple(sorted(set(CURVE) | {auto}))
    compile_s = {}
    summaries = {}
    for s in sizes:
        t0 = walltime.perf_counter()
        warm = core.run_sweep(wl, ecfg, _fresh(s))
        int(warm.ctr.sum())
        compile_s[s] = walltime.perf_counter() - t0
    times = {s: [] for s in sizes}
    for _rep in range(REPS):
        for s in sizes:
            t0 = walltime.perf_counter()
            final = core.run_sweep(wl, ecfg, _fresh(s))
            int(final.ctr.sum())
            t = walltime.perf_counter() - t0
            # keep the summary PAIRED with its own rep's time: each rep
            # sweeps fresh seeds, so event totals differ slightly per rep
            if not times[s] or t < min(times[s]):
                summaries[s] = raft.sweep_summary(final)
            times[s].append(t)
    curve = []
    for s in sizes:
        best = min(times[s])
        summary = summaries[s]
        curve.append(
            {
                "seeds": s,
                "auto_chunk": s == auto,
                "seeds_per_sec": round(s / best, 1),
                "events_per_sec": round(summary["events_total"] / best, 1),
                "sim_sec_per_wall_sec": round(
                    summary["sim_ns_total"] / best / 1e9, 1
                ),
                "compile_plus_first_run_s": round(compile_s[s], 2),
                "run_s": round(best, 3),
                "reps": REPS,
                "spread": _spread(times[s]),
                "violations": summary["violations"],
                "hbm_bytes": s * per_seed,
            }
        )
    return curve


def bench_100k(wl, ecfg, raft):
    """BASELINE config #5 scale: pod-scale sweep as 16k chunks of one
    compiled program, summaries merged on host per chunk — constant
    device memory, the pattern that extends to millions of seeds (each
    chunk is also the checkpoint/restart granule). Min of REPS full
    passes; this is the headline figure."""
    from madsim_tpu.engine import core
    from madsim_tpu.models._common import merge_summaries

    times = []
    best_totals = None
    for _rep in range(REPS):
        t0 = walltime.perf_counter()
        totals = {}
        for _ in range(BIG_TOTAL // BIG_CHUNK):
            final = core.run_sweep(wl, ecfg, _fresh(BIG_CHUNK))
            merge_summaries(totals, raft.sweep_summary(final))
        wall = walltime.perf_counter() - t0
        if not times or wall < min(times):
            best_totals = totals
        times.append(wall)
        assert totals["violations"] == 0, totals
    wall = min(times)
    return {
        "seeds": BIG_TOTAL,
        "chunk_size": BIG_CHUNK,
        "wall_s": round(wall, 2),
        "seeds_per_sec": round(BIG_TOTAL / wall, 1),
        "events_per_sec": round(best_totals["events_total"] / wall, 1),
        "reps": REPS,
        "spread": _spread(times),
        "violations": best_totals["violations"],
    }


def bench_recovery(wl, raft_mod):
    """Config #5 determinism half: interrupt → checkpoint → restore →
    resume ≡ uninterrupted, bit for bit."""
    from madsim_tpu.engine import checkpoint, core

    cfg = raft_mod.RaftConfig(num_nodes=5, crashes=1)
    full_ecfg = raft_mod.engine_config(cfg, time_limit_ns=int(SIM_SECONDS * 1e9))
    part_ecfg = raft_mod.engine_config(
        cfg, time_limit_ns=int(SIM_SECONDS * 1e9), max_steps=300
    )
    seeds = _fresh(PARITY_SEEDS)
    straight = core.run_sweep(wl, full_ecfg, seeds)
    partial = core.run_sweep(wl, part_ecfg, seeds)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "mid.npz")
        checkpoint.save_sweep(partial, path)
        restored = checkpoint.load_sweep(path, like=partial)
    resumed = checkpoint.resume_sweep(wl, full_ecfg, restored)
    identical = all(
        bool(jnp.array_equal(a, b))
        for a, b in zip(
            jax.tree.leaves(
                (straight.ctr, straight.now_ns, straight.wstate.elections)
            ),
            jax.tree.leaves(
                (resumed.ctr, resumed.now_ns, resumed.wstate.elections)
            ),
        )
    )
    return {"seeds": PARITY_SEEDS, "interrupted_at_step": 300,
            "bit_identical": identical}


def bench_checked_sweep() -> dict:
    """END-TO-END checked throughput — the quantity this round makes
    the optimized one: seeds/s through sweep PLUS history validation.

    The pipelined leg runs the etcd history workload (clean config,
    hist_slots=256) at CHECKED_TOTAL seeds through
    ``oracle.screen.checked_sweep``: chunked sweep, on-device suspect
    screen folded behind each chunk, host-side decode + process-pool
    WGL checking of chunk N interleaved with the device rounds of chunk
    N+1 (budgeted incremental polling). Its UNCHECKED TWIN — the same
    pipelined sweep + summary with no screen, no decode, no checker —
    runs in the same process at the same seed count, interleaved
    rep-outer/case-inner so load drift hits both legs alike; the ratio
    ``checked_over_unchecked`` is the full price of history validation
    (acceptance: <= 2x at this scale on CPU). The naive baseline —
    sweep, decode EVERY lane, check serially, no overlap — is measured
    on a smaller seed count; rates compare directly since both are
    per-seed-linear."""
    from madsim_tpu.engine import core
    from madsim_tpu.engine.checkpoint import run_sweep_pipelined
    from madsim_tpu.models import etcd
    from madsim_tpu.oracle import check_histories, decode_sweep
    from madsim_tpu.oracle.screen import checked_sweep

    cfg = etcd.EtcdConfig(hist_slots=256)
    ecfg = etcd.engine_config(
        cfg, time_limit_ns=int(CHECKED_SIM_SECONDS * 1e9)
    )
    wl = etcd.workload(cfg)
    spec = etcd.history_spec()
    chunk = CHECKED_CHUNK or core.pick_chunk_size(wl, ecfg)
    total = max(CHECKED_TOTAL, 2 * chunk)

    # warm every program untimed — ALL legs: the pipeline's sweep/
    # screen/summary/pool at the chunk shape, the unchecked twin
    # (shares the sweep/summary programs — run once anyway so its
    # driver path holds no first-call surprises), AND the naive leg's
    # sweep at NAIVE_SEEDS (a compile inside nwall would hand the
    # pipeline a fake speedup) plus one decode+check rep
    checked_sweep(
        wl, ecfg, _fresh(chunk), spec, etcd.sweep_summary,
        chunk_size=chunk, workers=CHECK_WORKERS,
    )
    run_sweep_pipelined(
        wl, ecfg, _fresh(chunk), etcd.sweep_summary, chunk_size=chunk
    )
    warm_naive = core.run_sweep(wl, ecfg, _fresh(NAIVE_SEEDS))
    check_histories(decode_sweep(warm_naive), spec)

    cwalls, uwalls = [], []
    totals = None
    for _rep in range(CHECKED_REPS):
        t0 = walltime.perf_counter()
        totals = checked_sweep(
            wl, ecfg, _fresh(total), spec, etcd.sweep_summary,
            chunk_size=chunk, workers=CHECK_WORKERS,
        )
        cwalls.append(walltime.perf_counter() - t0)
        t0 = walltime.perf_counter()
        run_sweep_pipelined(
            wl, ecfg, _fresh(total), etcd.sweep_summary, chunk_size=chunk
        )
        uwalls.append(walltime.perf_counter() - t0)
    wall, uwall = min(cwalls), min(uwalls)

    t0 = walltime.perf_counter()
    nfinal = core.run_sweep(wl, ecfg, _fresh(NAIVE_SEEDS))
    hists = decode_sweep(nfinal)
    naive_bad = sum(
        1 for r in check_histories(hists, spec) if not r.ok
    )
    nwall = walltime.perf_counter() - t0

    rate, urate, nrate = total / wall, total / uwall, NAIVE_SEEDS / nwall
    return {
        "seeds": total,
        "chunk_size": chunk,
        "workers": CHECK_WORKERS,
        "reps": CHECKED_REPS,
        "wall_s": round(wall, 2),
        "seeds_per_sec": round(rate, 1),
        "spread": _spread(cwalls),
        "suspects": totals["hist_suspects"],
        "hist_violations": totals["hist_violations"],
        "hist_overflow_seeds": totals["hist_overflow_seeds"],
        "budget_exceeded": totals.get("budget_exceeded", 0),
        "unchecked": {
            "seeds": total,
            "wall_s": round(uwall, 2),
            "seeds_per_sec": round(urate, 1),
            "spread": _spread(uwalls),
        },
        "checked_over_unchecked": round(wall / uwall, 2),
        "naive": {
            "seeds": NAIVE_SEEDS,
            "wall_s": round(nwall, 2),
            "seeds_per_sec": round(nrate, 1),
            "hist_violations": naive_bad,
        },
        "speedup_vs_naive": round(rate / nrate, 1),
    }


def bench_recovery_pipelined() -> dict:
    """The pipelined half of config #5's determinism story: interrupt a
    checked sweep MID-CHUNK, checkpoint the in-flight chunk state with
    its chunk metadata (format v7 ``inflight``), restore, resume with
    overlap enabled — the merged checked-sweep report must be
    bit-identical to the uninterrupted pipelined run."""
    from madsim_tpu.engine import checkpoint, core
    from madsim_tpu.models import etcd
    from madsim_tpu.oracle.screen import checked_sweep

    cfg = etcd.EtcdConfig(hist_slots=256)
    full = etcd.engine_config(
        cfg, time_limit_ns=int(CHECKED_SIM_SECONDS * 1e9)
    )
    short = etcd.engine_config(
        cfg, time_limit_ns=int(CHECKED_SIM_SECONDS * 1e9), max_steps=300
    )
    wl = etcd.workload(cfg)
    spec = etcd.history_spec()
    seeds = _fresh(PIPE_SEEDS)
    straight = checked_sweep(
        wl, full, seeds, spec, etcd.sweep_summary, chunk_size=PIPE_CHUNK
    )
    partial = core.run_sweep(wl, short, seeds[:PIPE_CHUNK])
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "mid.npz")
        checkpoint.save_sweep(
            partial, path, inflight={"lo": 0, "k": PIPE_CHUNK}
        )
        restored = checkpoint.load_sweep(path, like=partial)
        inflight = checkpoint.load_inflight(path)
    resumed = checked_sweep(
        wl, full, seeds, spec, etcd.sweep_summary, chunk_size=PIPE_CHUNK,
        resume_from=(restored, inflight),
    )
    return {
        "pipelined_seeds": PIPE_SEEDS,
        "pipelined_interrupted_at_step": 300,
        "pipelined_bit_identical": resumed == straight,
    }


def bench_campaign() -> dict:
    """Explore-candidate throughput, serial vs batched grid.

    Per rep (interleaved A/B, docs/pallas_finding.md §0): leg A sweeps
    ``CAMPAIGN_K`` FRESH mutated candidates the pre-refactor way — every
    candidate a new jit cache key, so every candidate pays the sweep
    compile (the production regime a coverage-guided campaign used to
    live in); leg B stacks the same-count fresh candidates into one
    (candidate x seed) spec-as-data grid over the warmed envelope
    program. Fresh candidates every rep keep leg A honestly
    compile-bound and leg B honestly data-bound; compiles are COUNTED in
    both timed regions (engine/compiles.py), so the speedup is
    attributable, not asserted."""
    import random

    from madsim_tpu import explore
    from madsim_tpu.engine.compiles import count_compiles
    from madsim_tpu.engine.faults import FaultSpec

    target = explore.amnesia_raft_target(
        time_limit_ns=int(CAMPAIGN_SIM_SECONDS * 1e9), max_steps=15_000
    )
    base = FaultSpec(
        crashes=3,
        crash_window_ns=1_200_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    env = explore.target_envelope(target, base)
    rng = random.Random(0xBE7C)
    seen = set()

    def fresh_candidates():
        # distinct across the whole bench: a repeated spec would hit the
        # serial leg's jit cache and understate its per-candidate compile
        out = []
        while len(out) < CAMPAIGN_K:
            spec = explore.mutate_spec(base, rng, 2)
            if spec not in seen:
                seen.add(spec)
                out.append(spec)
        return out

    def ccfg_at(seed0: int) -> explore.CampaignConfig:
        return explore.CampaignConfig(
            seeds_per_round=CAMPAIGN_SEEDS, seed0=seed0
        )

    # warm the grid's programs (envelope sweep, lane slice, summary)
    # outside every timed region; the serial leg has nothing to warm —
    # paying the compiler per candidate IS that leg
    explore.sweep_candidate_grid(
        target, fresh_candidates(), ccfg_at(int(_fresh(CAMPAIGN_SEEDS)[0])),
        env,
    )

    serial_times, grid_times = [], []
    serial_compiles = grid_compiles = 0
    for _ in range(CAMPAIGN_REPS):
        cand_a, cand_b = fresh_candidates(), fresh_candidates()
        s0a = int(_fresh(CAMPAIGN_SEEDS)[0])
        s0b = int(_fresh(CAMPAIGN_SEEDS)[0])
        with count_compiles() as c:
            t0 = walltime.perf_counter()
            for spec in cand_a:
                explore.campaign._sweep_candidate(
                    target, spec, ccfg_at(s0a), None
                )
            serial_times.append(walltime.perf_counter() - t0)
        serial_compiles += c.count
        with count_compiles() as c:
            t0 = walltime.perf_counter()
            explore.sweep_candidate_grid(target, cand_b, ccfg_at(s0b), env)
            grid_times.append(walltime.perf_counter() - t0)
        grid_compiles += c.count

    rate_serial = CAMPAIGN_K / min(serial_times)
    rate_grid = CAMPAIGN_K / min(grid_times)
    return {
        "candidates": CAMPAIGN_K,
        "seeds_per_candidate": CAMPAIGN_SEEDS,
        "reps": CAMPAIGN_REPS,
        "serial_per_candidate": {
            "candidates_per_sec": round(rate_serial, 2),
            "compiles_in_timed_region": serial_compiles,
            "spread": _spread(serial_times),
        },
        "batched_grid": {
            "candidates_per_sec": round(rate_grid, 2),
            "compiles_in_timed_region": grid_compiles,
            "spread": _spread(grid_times),
        },
        "speedup_vs_serial": round(rate_grid / rate_serial, 1),
    }


def bench_streaming() -> dict:
    """Streaming vs chunked seeds/s across the batch curve (ROADMAP
    item 1, docs/streaming.md): the SAME etcd history sweep through
    ``run_sweep_pipelined`` (fixed-shape chunks — each chunk drags to
    its slowest lane) and ``engine.stream.stream_sweep`` (a
    constant-occupancy lane pool continuously refilled from the work
    queue), interleaved A/B reps per pallas_finding §0 (rep-outer,
    driver-inner, fresh seed ranges, min-of-reps). The gray-failure
    FaultSpec makes lanes retire at genuinely different ages (crashes
    starve some seeds of events while partition retries feed others) —
    exactly the straggler pattern fixed-shape chunking pays for. Every
    rep asserts the two drivers' totals are identical (the byte
    contract) and that the warmed stream region performs 0 XLA
    compilations."""
    from madsim_tpu.engine.checkpoint import run_sweep_pipelined
    from madsim_tpu.engine.compiles import count_compiles
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.engine.stream import stream_sweep
    from madsim_tpu.models import etcd

    cfg = etcd.EtcdConfig(
        hist_slots=64,
        bug_stale_read=True,
        faults=FaultSpec(
            crashes=2, partitions=2, spikes=1, losses=1, pauses=1
        ),
    )
    ecfg = etcd.engine_config(
        cfg, time_limit_ns=int(STREAM_SIM_SECONDS * 1e9),
        max_steps=STREAM_MAX_STEPS,
    )
    wl = etcd.workload(cfg)
    sizes = STREAM_CURVE
    chunk = min(STREAM_CHUNK, min(sizes))
    pool = min(STREAM_POOL, chunk)
    kw = dict(chunk_size=chunk)

    # warm both drivers' programs (the [chunk]/[pool]-shaped
    # round/refill/summary programs serve every curve point) on a
    # 2-chunk batch so the refill and merge paths are hot before any
    # timed region
    warm = _fresh(2 * chunk)
    run_sweep_pipelined(wl, ecfg, warm, etcd.sweep_summary, **kw)
    stream_sweep(
        wl, ecfg, warm, etcd.sweep_summary, pool_size=pool,
        round_steps=STREAM_ROUND_STEPS, **kw,
    )

    times_c = {s: [] for s in sizes}
    times_s = {s: [] for s in sizes}
    occs = {s: 0.0 for s in sizes}
    stream_compiles = 0
    for _rep in range(STREAM_REPS):
        for s in sizes:
            seeds = _fresh(s)  # same seeds for both drivers: the totals
            #                    equality below is then a real byte check
            t0 = walltime.perf_counter()
            chunked = run_sweep_pipelined(
                wl, ecfg, seeds, etcd.sweep_summary, **kw
            )
            times_c[s].append(walltime.perf_counter() - t0)
            stats: dict = {}
            with count_compiles() as c:
                t0 = walltime.perf_counter()
                streamed = stream_sweep(
                    wl, ecfg, seeds, etcd.sweep_summary, pool_size=pool,
                    round_steps=STREAM_ROUND_STEPS, stats=stats, **kw,
                )
                dt = walltime.perf_counter() - t0
            stream_compiles += c.count
            assert streamed == chunked, (
                f"driver totals diverge at {s} seeds"
            )
            if not times_s[s] or dt < min(times_s[s]):
                occs[s] = stats["occupancy_mean"]
            times_s[s].append(dt)
    assert stream_compiles == 0, (
        f"{stream_compiles} XLA compilations in the warmed stream region"
    )

    curve = []
    for s in sizes:
        rate_c = s / min(times_c[s])
        rate_s = s / min(times_s[s])
        curve.append(
            {
                "seeds": s,
                "chunked_seeds_per_sec": round(rate_c, 1),
                "stream_seeds_per_sec": round(rate_s, 1),
                "speedup": round(rate_s / rate_c, 2),
                "occupancy_mean": round(occs[s], 3),
                "totals_identical": True,
                "spread_chunked": _spread(times_c[s]),
                "spread_stream": _spread(times_s[s]),
            }
        )
    return {
        "workload": (
            "etcd bug_stale_read + gray-failure FaultSpec "
            "(straggler-heavy retirement, step spread ~1.46x)"
        ),
        "chunk_size": chunk,
        "pool_size": pool,
        "round_steps": STREAM_ROUND_STEPS,
        "reps": STREAM_REPS,
        "compiles_in_warmed_region": stream_compiles,
        "curve": curve,
    }


def bench_telemetry() -> dict:
    """Telemetry overhead on the streaming checked-sweep path.

    Per rep (interleaved on/off, docs/pallas_finding.md §0): leg OFF
    runs ``checked_sweep(driver="stream")`` with ``telemetry=None`` —
    every recorder is behind an ``if telemetry is not None`` guard, so
    this is the genuine uninstrumented baseline; leg ON runs the same
    seeds with a full-fat ``obs.Telemetry`` (metrics registry + JSONL
    journal + trace spans — the most expensive configuration a user can
    enable). Every rep asserts the two report dicts are EQUAL (the
    out-of-band contract; the determinism gate byte-diffs the same
    thing across processes). The figure is min-of-reps wall per leg;
    ``overhead`` is on/off − 1, gated ≤ TELEM_OVERHEAD_GATE."""
    import tempfile as _tmp

    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.models import etcd
    from madsim_tpu.obs import Telemetry
    from madsim_tpu.oracle.screen import checked_sweep

    cfg = etcd.EtcdConfig(
        hist_slots=64,
        faults=FaultSpec(crashes=2, partitions=2, spikes=1),
    )
    ecfg = etcd.engine_config(
        cfg, time_limit_ns=int(TELEM_SIM_SECONDS * 1e9),
        max_steps=STREAM_MAX_STEPS,
    )
    wl = etcd.workload(cfg)
    spec = etcd.history_spec()
    kw = dict(
        chunk_size=TELEM_CHUNK, workers=0, driver="stream",
    )

    # warm both legs' programs (identical programs — telemetry never
    # changes a traced computation, only wall-clock-side bookkeeping)
    checked_sweep(wl, ecfg, _fresh(TELEM_CHUNK), spec,
                  etcd.sweep_summary, **kw)

    times_off, times_on = [], []
    with _tmp.TemporaryDirectory() as d:
        for rep in range(TELEM_REPS):
            seeds = _fresh(TELEM_SEEDS)  # same seeds both legs: the
            #                              equality below is a real check
            t0 = walltime.perf_counter()
            off = checked_sweep(wl, ecfg, seeds, spec,
                                etcd.sweep_summary, **kw)
            times_off.append(walltime.perf_counter() - t0)
            telem = Telemetry(
                journal=os.path.join(d, f"rep{rep}.jsonl"),
                trace=os.path.join(d, f"rep{rep}.trace.json"),
            )
            t0 = walltime.perf_counter()
            on = checked_sweep(wl, ecfg, seeds, spec,
                               etcd.sweep_summary, telemetry=telem, **kw)
            times_on.append(walltime.perf_counter() - t0)
            telem.close()
            assert on == off, "telemetry changed the report — OUT-OF-BAND BROKEN"
        snapshot = telem.registry.snapshot()
    overhead = min(times_on) / min(times_off) - 1
    return {
        "seeds": TELEM_SEEDS,
        "chunk_size": TELEM_CHUNK,
        "reps": TELEM_REPS,
        "off_seeds_per_sec": round(TELEM_SEEDS / min(times_off), 1),
        "on_seeds_per_sec": round(TELEM_SEEDS / min(times_on), 1),
        "overhead": round(overhead, 4),
        "overhead_ok": overhead <= TELEM_OVERHEAD_GATE,
        "gate": TELEM_OVERHEAD_GATE,
        "reports_identical": True,
        "spread_off": _spread(times_off),
        "spread_on": _spread(times_on),
        # a few sanity series from the last ON rep, proving the
        # instrumentation actually fired while the reports stayed equal
        "sample_metrics": {
            k: snapshot.get(k)
            for k in ("stream_rounds_total", "stream_seeds_done_total",
                      "oracle_screened_total")
            if k in snapshot
        },
    }


def _leaf_np(a):
    """Host array for comparison; typed PRNG keys via their raw words."""
    if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
        a = jax.random.key_data(a)
    return np.asarray(a)


def bench_cross_backend(wl, ecfg):
    """THE framework contract, machine-checked on hardware every round:
    a TPU sweep and a CPU sweep of the same seeds are bit-identical on
    every EngineState leaf, and the single-seed traced replay (the
    debugging path, engine/core.run_traced) lands on the same final
    state as the batched sweep lane. Ref analogue: determinism checking
    as a first-class harness feature (madsim/src/sim/runtime/mod.rs:
    178-202). Skipped (reported as such) when no second backend exists
    — e.g. the whole process is already CPU-only."""
    from madsim_tpu.engine import core

    if jax.default_backend() == "cpu":
        return {"skipped": "single-backend process (cpu only)"}
    cpu = jax.devices("cpu")[0]
    seeds = _fresh(PARITY_SEEDS)
    dev_final = core.run_sweep(wl, ecfg, seeds)
    with jax.default_device(cpu):
        cpu_final = core.run_sweep(wl, ecfg, jax.device_put(seeds, cpu))
    dev_leaves, _ = jax.tree.flatten(dev_final)
    cpu_leaves, _ = jax.tree.flatten(cpu_final)
    leaves_equal = all(
        np.array_equal(_leaf_np(a), _leaf_np(b))
        for a, b in zip(dev_leaves, cpu_leaves)
    )

    # traced replay of one seed on CPU == that seed's sweep lane on TPU
    replay_seed = int(np.asarray(seeds)[0])
    with jax.default_device(cpu):
        traced_final, _ = core.run_traced(wl, ecfg, replay_seed)
    lane = jax.tree.map(lambda a: a[0], dev_final)
    t_leaves, _ = jax.tree.flatten(traced_final)
    l_leaves, _ = jax.tree.flatten(lane)
    replay_equal = all(
        np.array_equal(_leaf_np(a), _leaf_np(b))
        for a, b in zip(t_leaves, l_leaves)
    )
    return {
        "seeds": int(seeds.shape[0]),
        "leaves": len(dev_leaves),
        "leaves_equal": leaves_equal,
        "traced_replay_seed": replay_seed,
        "traced_replay_equal": replay_equal,
    }


def bench_secondary_models():
    """BASELINE configs #4 (kafka broker crash/restart sweep) and #2
    (etcd 3-node KV + lease with partition injection), checkers quiet.

    The two legs INTERLEAVE their reps (rep-outer, model-inner — the
    scripts/bench_packing.py A/B discipline) instead of running
    back-to-back rep blocks: the tunneled chip drifts ±30% over minutes,
    so sequential blocks hand one model the drift window wholesale
    (measured spreads 0.29/0.42 on these legs vs 0.02-0.06 on the
    interleaved raft legs, VERDICT r05). Interleaving alone was not
    enough (r05 measured the same spreads WITH it), so two more
    disciplines apply: the first post-warm interleaved pass is a
    DISCARDED warm-up rep (it still pays allocator growth and device
    re-tunneling that the compile warm-up does not flush), and the legs
    gate on ``_spread_best3 < SPREAD_GATE`` — more interleaved rounds
    are taken (bounded by ``MAX_EXTRA_ROUNDS``) until the three fastest
    reps agree within 10%, so the min-of-reps figure is tight enough
    that a sharded-perf regression is actually detectable round over
    round. ``spread_ok`` records whether the gate was met.
    Returns ``(kafka_line, etcd_line)``."""
    from madsim_tpu.engine import core
    from madsim_tpu.models import etcd, kafka

    cases = {
        "kafka": (kafka, kafka.KafkaConfig(), 10240),
        "etcd": (etcd, etcd.EtcdConfig(), 8192),
    }
    built = {}
    for name, (mod, cfg, seeds) in cases.items():
        ecfg = mod.engine_config(cfg, time_limit_ns=int(SIM_SECONDS * 1e9))
        wl = mod.workload(cfg)
        warm = core.run_sweep(wl, ecfg, _fresh(seeds))  # compile/warm
        int(warm.ctr.sum())
        built[name] = (mod, wl, ecfg, seeds)

    times = {name: [] for name in cases}
    best_final = {}

    def one_round(discard: bool = False) -> None:
        for name, (mod, wl, ecfg, seeds) in built.items():
            t0 = walltime.perf_counter()
            final = core.run_sweep(wl, ecfg, _fresh(seeds))
            int(final.ctr.sum())
            t = walltime.perf_counter() - t0
            if discard:
                continue
            if not times[name] or t < min(times[name]):
                best_final[name] = final
            times[name].append(t)

    one_round(discard=True)  # warm-up discard (see docstring)
    for _rep in range(REPS):
        one_round()
    extra = 0
    while (
        max(_spread_best3(ts) for ts in times.values()) >= SPREAD_GATE
        and extra < MAX_EXTRA_ROUNDS
    ):
        one_round()
        extra += 1

    def line(name, extra_fields):
        mod, _wl, _ecfg, seeds = built[name]
        run_s = min(times[name])
        s = mod.sweep_summary(best_final[name])
        out = {
            "seeds": seeds,
            "seeds_per_sec": round(seeds / run_s, 1),
            "events_per_sec": round(s["events_total"] / run_s, 1),
            "reps": len(times[name]),
            "spread": _spread_best3(times[name]),
            "spread_all": _spread(times[name]),
            "spread_ok": _spread_best3(times[name]) < SPREAD_GATE,
            "violations": s["violations"],
        }
        out.update((k, s[src]) for k, src in extra_fields)
        return out

    return (
        line("kafka", (("broker_crashes", "crashes"), ("records_consumed", "fetched"))),
        line("etcd", (("partitions", "partitions"), ("lease_expiries", "expiries"))),
    )


def bench_carryover() -> dict:
    """The carry-over leg standalone (``--carryover``): re-run exactly
    the two measurements earlier rounds left flagged — the kafka/etcd
    interleaved spread gate (``spread_ok`` must hold round over round)
    and the auto-picked chunk-size batch-curve point (the auto pick
    must stay at or left of the occupancy knee) — without paying for
    the full pipeline. Recorded per round in ``BENCH_rNN.json``."""
    global CURVE
    from madsim_tpu.engine import core  # noqa: F401  (x64 setup)
    from madsim_tpu.models import raft

    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=int(SIM_SECONDS * 1e9))
    wl = raft.workload(cfg)
    saved = CURVE
    CURVE = ()  # bench_curve unions in the auto pick: one point, flagged
    try:
        curve = bench_curve(wl, ecfg, raft)
    finally:
        CURVE = saved
    kafka_line, etcd_line = bench_secondary_models()
    return {
        "auto_chunk_point": next(p for p in curve if p["auto_chunk"]),
        "kafka": kafka_line,
        "etcd": etcd_line,
        "spread_gate": SPREAD_GATE,
        "spread_ok": kafka_line["spread_ok"] and etcd_line["spread_ok"],
        "backend": jax.default_backend(),
    }


def bench_steering() -> dict:
    """The self-steering scheduler A/B (``--steering``): bandit vs
    uniform family allocation at a MATCHED deterministic device-event
    budget, per target. Both policies run the same loop (run_steered),
    same families, same seeds-per-round, same campaign seed — the only
    difference is the pick rule (UCB + kill/escalate vs round-robin),
    so every delta is attributable to allocation. Per cell: distinct
    triage fingerprints (the acceptance metric — bandit/uniform >= 1.5x
    on the raft gate), covered coverage bits, events spent until the
    first violating candidate (the time-to-first-bug analogue in the
    budget currency — deterministic, unlike wall), decision count, and
    wall seconds for context. The etcd cell runs its checker-backed
    triage (history=True) and is EXPECTED to tie on fingerprints: one
    reachable flavor saturates both policies, and its delta shows up in
    coverage bits instead — reported, not gated."""
    from madsim_tpu.explore import CampaignConfig, SteerConfig, run_steered
    from madsim_tpu.explore.targets import etcd_steer_gate, steer_gate

    def cell(target, base, policy, budget, history):
        ccfg = CampaignConfig(
            rounds=999, seeds_per_round=STEER_SEEDS_PER_ROUND,
            campaign_seed=STEER_CAMPAIGN_SEED, max_recorded_seeds=8,
            scheduler=policy,
        )
        scfg = SteerConfig(
            scheduler=policy, families=STEER_FAMILIES,
            escalate_seeds=STEER_ESCALATE_SEEDS,
            kill_plays=STEER_KILL_PLAYS, budget_events=budget,
        )
        t0 = walltime.perf_counter()
        res = run_steered(target, base, ccfg, scfg, history=history)
        wall = walltime.perf_counter() - t0
        events_to_first_bug = None
        spent = 0
        for r in res.records:
            spent += r.get("events_total", 0)
            if r.get("violations", 0) > 0:
                events_to_first_bug = spent
                break
        kinds = [d["kind"] for d in res.decisions]
        return {
            "fingerprints": len(res.fingerprints),
            "fingerprint_list": res.fingerprints,
            "coverage_bits": sum(int(w).bit_count() for w in res.coverage_map),
            "events_to_first_bug": events_to_first_bug,
            "spent_events": res.spent_events,
            "decisions": kinds.count("decide"),
            "kills": kinds.count("kill"),
            "escalations": kinds.count("escalate"),
            "wall_s": round(wall, 2),
        }

    def ab(name, target, base, budget, history):
        bandit = cell(target, base, "bandit", budget, history)
        uniform = cell(target, base, "uniform", budget, history)
        ratio = (
            round(bandit["fingerprints"] / uniform["fingerprints"], 2)
            if uniform["fingerprints"] else None
        )
        return {
            "target": name,
            "budget_events": budget,
            "bandit": bandit,
            "uniform": uniform,
            "fingerprint_ratio": ratio,
            "coverage_ratio": round(
                bandit["coverage_bits"] / uniform["coverage_bits"], 2
            ) if uniform["coverage_bits"] else None,
        }

    rt, rb = steer_gate(smoke=True)
    et, eb = etcd_steer_gate(smoke=True)
    raft = ab("raft-amnesia", rt, rb, STEER_RAFT_BUDGET, False)
    etcd = ab("etcd-stale", et, eb, STEER_ETCD_BUDGET, True)
    return {
        "families": len(STEER_FAMILIES),
        "seeds_per_round": STEER_SEEDS_PER_ROUND,
        "campaign_seed": STEER_CAMPAIGN_SEED,
        "raft": raft,
        "etcd": etcd,
        # the acceptance gate rides on the raft cell; etcd saturates
        "ratio_ok": (raft["fingerprint_ratio"] or 0) >= 1.5,
        "backend": jax.default_backend(),
    }


def bench_wire_load() -> dict:
    """The async serving core under production-scale load
    (``--wire-load``): >=1k concurrent genuine-protocol clients (Kafka
    producers + consumer groups, S3 REST incl. multipart, framed etcd)
    against one sim-backed cluster, gray failure injected mid-run,
    LogSpec/S3Spec/KVSpec-checked histories, kafka+s3 transcripts
    replayed byte for byte, p50/p99 from the server-side histograms.
    The spread gate runs over WIRE_REPS smaller reps on the dominant
    op's p50 (kafka Fetch): latency SLOs come from the server-side
    histograms and are scheduling-stable, whereas raw ops/s on a
    shared single-core box swings with wall-clock contention — it is
    reported (``throughput_spread``) but not gated."""
    import subprocess

    script = os.path.join(os.path.dirname(__file__), "scripts",
                          "wire_load.py")

    def run(extra):
        with tempfile.NamedTemporaryFile(suffix=".json") as f:
            proc = subprocess.run(
                [sys.executable, script, "--report", f.name, *extra],
                capture_output=True, text=True, timeout=900,
            )
            try:
                report = json.load(open(f.name))
            except (json.JSONDecodeError, OSError):
                report = {}
        return proc.returncode, report

    rc, full = run([])
    reps = []
    for _ in range(WIRE_REPS):
        rep_rc, rep = run([
            "--clients", str(WIRE_REP_CLIENTS),
            "--run-secs", str(WIRE_REP_SECS),
            "--min-clients", str(WIRE_REP_CLIENTS // 2),
        ])
        fetch = (rep.get("slo", {}).get("kafka_api_seconds", {})
                 .get("Fetch", {}))
        reps.append({
            "rc": rep_rc,
            "throughput_ops_s": rep.get("throughput_ops_s", 0.0),
            "total_ops": rep.get("total_ops", 0),
            "fetch_p50_ms": fetch.get("p50_ms", 0.0),
            "fetch_p99_ms": fetch.get("p99_ms", 0.0),
        })
    p50s = [r["fetch_p50_ms"] for r in reps if r["fetch_p50_ms"]]
    spread = _spread(p50s) if p50s else 1.0
    rates = [r["throughput_ops_s"] for r in reps if r["throughput_ops_s"]]
    throughput_spread = _spread(rates) if rates else 1.0

    def pcts(hist_name):
        legs = full.get("slo", {}).get(hist_name, {})
        return {
            k: {"count": v["count"], "p50_ms": v["p50_ms"],
                "p99_ms": v["p99_ms"]}
            for k, v in sorted(legs.items())
        }

    return {
        "rc": rc,
        "clients": full.get("clients", 0),
        "workers": full.get("workers", 0),
        "elapsed_s": full.get("elapsed_s", 0),
        "total_ops": full.get("total_ops", 0),
        "throughput_ops_s": full.get("throughput_ops_s", 0),
        "peak_open_conns": full.get("peak_open_conns", 0),
        "errors": full.get("stats", {}).get("errors", -1),
        "histories_ok": full.get("histories_ok", False),
        "replay_ok": full.get("replay_ok", False),
        "chaos": full.get("chaos", {}),
        "gate_failures": full.get("gate_failures", ["no report"]),
        "kafka_slo": pcts("kafka_api_seconds"),
        "s3_slo": pcts("s3_api_seconds"),
        "etcd_slo": pcts("etcd_api_seconds"),
        "rep_clients": WIRE_REP_CLIENTS,
        "reps": reps,
        "spread": spread,
        "throughput_spread": throughput_spread,
        "spread_gate": SPREAD_GATE,
        "spread_ok": spread < SPREAD_GATE and all(
            r["rc"] == 0 for r in reps
        ),
        "ok": rc == 0 and spread < SPREAD_GATE,
    }


def main() -> None:
    from madsim_tpu.engine import core  # noqa: F401  (x64 setup)
    from madsim_tpu.models import raft

    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=int(SIM_SECONDS * 1e9))
    wl = raft.workload(cfg)

    # host tier first: measured before device churn (GC/allocator
    # pressure from the TPU runs costs it ~2x)
    host = bench_host()
    host_rate = host["seeds_per_sec"]
    curve = bench_curve(wl, ecfg, raft)
    big = bench_100k(wl, ecfg, raft)
    recovery = bench_recovery(wl, raft)
    recovery.update(bench_recovery_pipelined())
    cross = bench_cross_backend(wl, ecfg)
    kafka_line, etcd_line = bench_secondary_models()
    checked = bench_checked_sweep()
    campaign = bench_campaign()
    streaming = bench_streaming()
    telemetry = bench_telemetry()

    # HEADLINE = the chunked 131k sweep: the production pattern, and —
    # at ~3 s of device work per rep — the only number the tunneled
    # chip's ±30% minute-scale drift cannot move (r03→r04: curve points
    # swung −15..−35% with no code change while this one stayed flat,
    # 44,192 → 44,214 seeds/s)
    print(
        json.dumps(
            {
                "metric": "madraft_sweep_seeds_per_sec",
                "value": big["seeds_per_sec"],
                "unit": "seeds/s",
                "vs_baseline": round(big["seeds_per_sec"] / host_rate, 1),
                "headline_note": (
                    f"chunked {BIG_TOTAL}-seed sweep ({BIG_CHUNK}-seed "
                    f"chunks), min of {REPS} full passes; spread "
                    f"{big['spread']}. Curve points below are min-of-"
                    f"{REPS} interleaved reps with per-point spread."
                ),
                "baseline": {
                    "name": (
                        "host-tier single-thread executor, compiled C core "
                        "(this repo, native/simloop.c), min of "
                        f"{REPS} passes"
                    ),
                    "seeds_per_sec": host_rate,
                    "spread": host["spread"],
                    "reference_note": (
                        "the Rust reference publishes no benchmark numbers "
                        "(BASELINE.md) and no Rust toolchain exists in this "
                        "image to measure it. Round 4 compiled the host "
                        "executor's hot loop (ready queue, timer heap, "
                        "futures, context swap) to C — 3.3x over the "
                        "round-3 pure-Python tier (37 -> ~120 seeds/s), "
                        "closing most of the 'compiled executor' gap; user "
                        "coroutine bodies still run in CPython, so read "
                        "vs_baseline as 'vs this repo's own host tier'"
                    ),
                },
                "events_per_sec": big["events_per_sec"],
                "batch_curve": curve,
                "auto_chunk": {
                    "chunk_size": core.pick_chunk_size(wl, ecfg),
                    "state_bytes_per_seed": core.state_bytes_per_seed(
                        wl, ecfg
                    ),
                },
                "sweep_100k": big,
                "checked_sweep": checked,
                "campaign": campaign,
                "streaming": streaming,
                "telemetry": telemetry,
                "recovery_e2e": recovery,
                "cross_backend": cross,
                "kafka": kafka_line,
                "etcd": etcd_line,
                "backend": jax.default_backend(),
            }
        )
    )


def _smoke() -> None:
    """Shrink every knob so the full pipeline (host tier, curve, chunked
    sweep, recovery, cross-backend parity, kafka, etcd) runs in ~a minute
    — the CI/Make smoke target. Numbers are meaningless; the exit code
    and the JSON shape are the point."""
    global CURVE, BIG_TOTAL, BIG_CHUNK, HOST_SEEDS, REPS, SIM_SECONDS
    global PARITY_SEEDS, CHECKED_TOTAL, CHECKED_CHUNK, CHECKED_SIM_SECONDS
    global CHECKED_REPS, NAIVE_SEEDS, CHECK_WORKERS, PIPE_SEEDS, PIPE_CHUNK
    global CAMPAIGN_K, CAMPAIGN_SEEDS, CAMPAIGN_REPS, CAMPAIGN_SIM_SECONDS
    global STREAM_CURVE, STREAM_CHUNK, STREAM_POOL, STREAM_REPS
    global STREAM_SIM_SECONDS, STREAM_ROUND_STEPS, STREAM_MAX_STEPS
    global TELEM_SEEDS, TELEM_CHUNK, TELEM_REPS, TELEM_SIM_SECONDS
    # shrink the auto-picked curve point too: the default 128 MiB budget
    # would land it at 16k lanes — ~45 s of CPU sweeps in a smoke run
    os.environ.setdefault("MADSIM_CHUNK_BUDGET_BYTES", str(8 << 20))
    CURVE = (64, 128)
    BIG_TOTAL = 256
    BIG_CHUNK = 128
    HOST_SEEDS = 2
    REPS = 2
    SIM_SECONDS = 0.5
    PARITY_SEEDS = 256
    CHECKED_TOTAL = 256
    CHECKED_CHUNK = 128
    CHECKED_SIM_SECONDS = 0.5
    CHECKED_REPS = 1
    NAIVE_SEEDS = 64
    CHECK_WORKERS = 2
    PIPE_SEEDS = 128
    PIPE_CHUNK = 64
    CAMPAIGN_K = 4
    CAMPAIGN_SEEDS = 32
    CAMPAIGN_REPS = 1
    CAMPAIGN_SIM_SECONDS = 0.5
    STREAM_CURVE = (64, 128)
    STREAM_CHUNK = 32
    STREAM_POOL = 16
    STREAM_ROUND_STEPS = 128
    STREAM_REPS = 1
    STREAM_SIM_SECONDS = 0.3
    STREAM_MAX_STEPS = 2_000
    TELEM_SEEDS = 128
    TELEM_CHUNK = 64
    TELEM_REPS = 2
    TELEM_SIM_SECONDS = 0.3


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        _smoke()
    if "--campaign" in sys.argv:
        # the campaign leg standalone (CPU is the compile-dominated
        # regime the ≥5x acceptance figure is measured in)
        print(json.dumps({"metric": "campaign_leg", **bench_campaign()}))
    elif "--streaming" in sys.argv:
        # the streaming leg standalone (the ≥1x-at-every-batch-size
        # acceptance figure, incl. the 65,536 sag point)
        print(json.dumps({"metric": "streaming_leg", **bench_streaming()}))
    elif "--telemetry" in sys.argv:
        # the telemetry-overhead leg standalone (the ≤3% gate on the
        # streaming checked-sweep path)
        print(json.dumps({"metric": "telemetry_leg", **bench_telemetry()}))
    elif "--checked" in sys.argv:
        # the checked-sweep leg standalone (checked vs its same-run
        # unchecked twin; the <=2x checked_over_unchecked acceptance
        # figure at CHECKED_TOTAL seeds)
        print(json.dumps({"metric": "checked_leg", **bench_checked_sweep()}))
    elif "--steering" in sys.argv:
        # the steering A/B standalone (bandit vs uniform at a matched
        # device-event budget; the >=1.5x fingerprint acceptance figure
        # on the raft gate, coverage-bit delta on the saturated etcd one)
        print(json.dumps({"metric": "steering_leg", **bench_steering()}))
    elif "--wire-load" in sys.argv:
        # the async-core serving leg standalone (>=1k-client SLO gate,
        # docs/wire.md; histories + replay checked in the subprocess)
        print(json.dumps({"metric": "wire_load_leg", **bench_wire_load()}))
    elif "--carryover" in sys.argv:
        # the flagged-legs re-run (kafka/etcd spread gate + auto_chunk
        # curve point) for the per-round BENCH_rNN.json record
        print(json.dumps({"metric": "carryover_leg", **bench_carryover()}))
    else:
        main()
