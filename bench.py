"""Benchmark: MadRaft seed-sweep throughput, TPU engine vs host-tier CPU.

Prints ONE JSON line:
    {"metric": "madraft_sweep_seeds_per_sec", "value": N, "unit": "seeds/s",
     "vs_baseline": M, ...}

The workload is BASELINE.md config #3 (5-node Raft election with
crash/restart fault injection, 3 virtual seconds per seed). The baseline is
the host tier — this framework's own Python deterministic executor running
the identical workload one seed at a time (the reference publishes no
numbers, so the stage-1 CPU engine is the measured baseline per
BASELINE.md). ``vs_baseline`` = device seeds/sec ÷ host seeds/sec.
"""

from __future__ import annotations

import json
import sys
import time as walltime


SIM_SECONDS = 3.0
HOST_SEEDS = 8
# large default batch: the lockstep engine amortizes per-op dispatch over
# the seed axis, so throughput grows with batch size
DEVICE_SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 16384


def bench_host() -> float:
    """Host-tier executor: one full simulation per seed (seeds/sec)."""
    sys.path.insert(0, __file__.rsplit("/", 1)[0] + "/examples")
    from raft_host import run_seed

    t0 = walltime.perf_counter()
    for seed in range(HOST_SEEDS):
        run_seed(seed, n=5, crashes=1, sim_seconds=SIM_SECONDS)
    return HOST_SEEDS / (walltime.perf_counter() - t0)


def bench_device() -> tuple:
    """TPU engine: lockstep sweep (seeds/sec, excluding compile)."""
    import jax
    import jax.numpy as jnp

    from madsim_tpu.engine import core
    from madsim_tpu.models import raft

    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=int(SIM_SECONDS * 1e9))
    wl = raft.workload(cfg)

    # warmup = compile; MUST use different seeds than the timed run (the
    # runtime memoizes same-input executions, which silently produces
    # fantasy numbers)
    warm = core.run_sweep(
        wl, ecfg, jnp.arange(DEVICE_SEEDS, 2 * DEVICE_SEEDS, dtype=jnp.int64)
    )
    int(warm.ctr.sum())  # force full materialization of the warmup
    seeds = jnp.arange(DEVICE_SEEDS, dtype=jnp.int64)
    t0 = walltime.perf_counter()
    final = core.run_sweep(wl, ecfg, seeds)
    # time to host readback — block_until_ready alone under-reports on
    # asynchronously tunneled devices
    int(final.ctr.sum())
    dt = walltime.perf_counter() - t0
    return DEVICE_SEEDS / dt, raft.sweep_summary(final), dt


def main() -> None:
    device_rate, summary, device_dt = bench_device()
    host_rate = bench_host()
    sim_ns_per_sec = summary["sim_ns_total"] / device_dt
    print(
        json.dumps(
            {
                "metric": "madraft_sweep_seeds_per_sec",
                "value": round(device_rate, 2),
                "unit": "seeds/s",
                "vs_baseline": round(device_rate / host_rate, 3),
                "baseline_host_seeds_per_sec": round(host_rate, 3),
                "device_seeds": DEVICE_SEEDS,
                "sim_seconds_per_wall_sec": round(sim_ns_per_sec / 1e9, 1),
                "events_per_sec": round(summary["events_total"] / device_dt, 1),
                "violations": summary["violations"],
                "backend": __import__("jax").default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
