"""Scale-out tier: sharded sweep == unsharded sweep, cross-backend parity.

These are the multi-chip guarantees of SURVEY.md §7 stage 7: sharding the
seed batch over a mesh must not change any seed's execution (pure DP), and
the integer-only engine must produce bit-identical results on every
backend (the CPU-replay contract).
"""

import jax
import jax.numpy as jnp
import pytest

from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.rng import prob_to_q32
from madsim_tpu.models import raft
from madsim_tpu import parallel

CFG = raft.RaftConfig(num_nodes=3, crashes=1, loss_q32=prob_to_q32(0.01))
ECFG = raft.engine_config(CFG, queue_capacity=32, time_limit_ns=1_000_000_000, max_steps=8_000)


def _cpu_devices(n):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices (XLA_FLAGS force_host_platform_device_count)")
    return devs[:n]


def test_sharded_sweep_matches_unsharded():
    wl = raft.workload(CFG)
    seeds = jnp.arange(16, dtype=jnp.int64)
    mesh = parallel.seed_mesh(_cpu_devices(8))
    sharded = parallel.run_sweep_sharded(wl, ECFG, seeds, mesh)

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        plain = ecore.run_sweep(wl, ECFG, seeds)

    for path, a in zip(jax.tree.leaves(sharded), jax.tree.leaves(plain)):
        if jnp.issubdtype(path.dtype, jnp.integer) or path.dtype == bool:
            assert jnp.array_equal(jax.device_get(path), jax.device_get(a))


def test_cross_backend_bit_exact():
    """CPU vs session-default backend: identical. NOTE: under pytest the
    conftest forces a CPU-only process, so this compares CPU to CPU and
    only proves the comparison machinery; the REAL hardware check runs
    in bench.py (bench_cross_backend, emitted as ``cross_backend`` in
    every BENCH_r*.json) where the default backend is the TPU."""
    wl = raft.workload(CFG)
    seeds = jnp.arange(8, dtype=jnp.int64)
    default = ecore.run_sweep(wl, ECFG, seeds)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        on_cpu = ecore.run_sweep(wl, ECFG, seeds)
    assert jnp.array_equal(jax.device_get(default.ctr), jax.device_get(on_cpu.ctr))
    assert jnp.array_equal(jax.device_get(default.now_ns), jax.device_get(on_cpu.now_ns))
    assert jnp.array_equal(
        jax.device_get(default.wstate.elections), jax.device_get(on_cpu.wstate.elections)
    )
    assert jnp.array_equal(
        jax.device_get(default.wstate.msgs_delivered),
        jax.device_get(on_cpu.wstate.msgs_delivered),
    )


def test_sharded_parity_kafka_and_etcd_models():
    """The sharded driver is model-agnostic: every newer device workload
    produces bit-identical results sharded vs unsharded."""
    from madsim_tpu.models import etcd, kafka, s3

    mesh = parallel.seed_mesh(_cpu_devices(8))
    cases = [
        (
            kafka.workload(kafka.KafkaConfig()),
            kafka.engine_config(
                kafka.KafkaConfig(), time_limit_ns=1_000_000_000, max_steps=8_000
            ),
        ),
        (
            etcd.workload(etcd.EtcdConfig()),
            etcd.engine_config(
                etcd.EtcdConfig(), time_limit_ns=1_000_000_000, max_steps=8_000
            ),
        ),
        (
            s3.workload(s3.S3Config()),
            s3.engine_config(
                s3.S3Config(), time_limit_ns=1_000_000_000, max_steps=8_000
            ),
        ),
    ]
    for wl, ecfg in cases:
        seeds = jnp.arange(16, dtype=jnp.int64)
        sharded = parallel.run_sweep_sharded(wl, ecfg, seeds, mesh)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            plain = ecore.run_sweep(wl, ecfg, seeds)
        assert jnp.array_equal(
            jax.device_get(sharded.ctr), jax.device_get(plain.ctr)
        )
        assert jnp.array_equal(
            jax.device_get(sharded.now_ns), jax.device_get(plain.now_ns)
        )


def test_sharded_chunked_matches_unsharded_with_ragged_tail():
    """Pod-scale composition: sharding over a mesh AND chunking the batch
    (with a ragged tail padded then trimmed) must be bit-identical per
    seed to one big single-device run_sweep."""
    wl = raft.workload(CFG)
    mesh = parallel.seed_mesh(_cpu_devices(8))
    seeds = jnp.arange(44, dtype=jnp.int64)  # 16+16+12: ragged tail
    chunked = parallel.run_sweep_sharded_chunked(
        wl, ECFG, seeds, mesh, chunk_per_device=2
    )
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        plain = ecore.run_sweep(wl, ECFG, seeds)
    for a, b in zip(jax.tree.leaves(chunked), jax.tree.leaves(plain)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        assert jnp.array_equal(jax.device_get(a), jax.device_get(b))

    # a batch smaller than one chunk and not divisible by the mesh is
    # padded to mesh divisibility (plain run_sweep_sharded would raise)
    small = jnp.arange(100, 112, dtype=jnp.int64)
    out = parallel.run_sweep_sharded_chunked(
        wl, ECFG, small, mesh, chunk_per_device=16384
    )
    with jax.default_device(cpu):
        plain_small = ecore.run_sweep(wl, ECFG, small)
    assert out.ctr.shape[0] == 12
    assert jnp.array_equal(
        jax.device_get(out.ctr), jax.device_get(plain_small.ctr)
    )


def test_mesh_size_must_divide_batch():
    wl = raft.workload(CFG)
    mesh = parallel.seed_mesh(_cpu_devices(8))
    with pytest.raises(Exception):
        parallel.run_sweep_sharded(wl, ECFG, jnp.arange(12, dtype=jnp.int64), mesh)
