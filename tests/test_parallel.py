"""Scale-out tier: sharded sweep == unsharded sweep, cross-backend parity.

These are the multi-chip guarantees of SURVEY.md §7 stage 7: sharding the
seed batch over a mesh must not change any seed's execution (pure DP), and
the integer-only engine must produce bit-identical results on every
backend (the CPU-replay contract).
"""

import jax
import jax.numpy as jnp
import pytest

from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.rng import prob_to_q32
from madsim_tpu.models import raft
from madsim_tpu import parallel

CFG = raft.RaftConfig(num_nodes=3, crashes=1, loss_q32=prob_to_q32(0.01))
ECFG = raft.engine_config(CFG, queue_capacity=32, time_limit_ns=1_000_000_000, max_steps=8_000)


def _cpu_devices(n):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices (XLA_FLAGS force_host_platform_device_count)")
    return devs[:n]


def test_sharded_sweep_matches_unsharded():
    wl = raft.workload(CFG)
    seeds = jnp.arange(16, dtype=jnp.int64)
    mesh = parallel.seed_mesh(_cpu_devices(8))
    sharded = parallel.run_sweep_sharded(wl, ECFG, seeds, mesh)

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        plain = ecore.run_sweep(wl, ECFG, seeds)

    for path, a in zip(jax.tree.leaves(sharded), jax.tree.leaves(plain)):
        if jnp.issubdtype(path.dtype, jnp.integer) or path.dtype == bool:
            assert jnp.array_equal(jax.device_get(path), jax.device_get(a))


def test_cross_backend_bit_exact():
    """CPU vs session-default backend: identical. NOTE: under pytest the
    conftest forces a CPU-only process, so this compares CPU to CPU and
    only proves the comparison machinery; the REAL hardware check runs
    in bench.py (bench_cross_backend, emitted as ``cross_backend`` in
    every BENCH_r*.json) where the default backend is the TPU."""
    wl = raft.workload(CFG)
    seeds = jnp.arange(8, dtype=jnp.int64)
    default = ecore.run_sweep(wl, ECFG, seeds)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        on_cpu = ecore.run_sweep(wl, ECFG, seeds)
    assert jnp.array_equal(jax.device_get(default.ctr), jax.device_get(on_cpu.ctr))
    assert jnp.array_equal(jax.device_get(default.now_ns), jax.device_get(on_cpu.now_ns))
    assert jnp.array_equal(
        jax.device_get(default.wstate.elections), jax.device_get(on_cpu.wstate.elections)
    )
    assert jnp.array_equal(
        jax.device_get(default.wstate.msgs_delivered),
        jax.device_get(on_cpu.wstate.msgs_delivered),
    )


def test_sharded_parity_kafka_and_etcd_models():
    """The sharded driver is model-agnostic: every newer device workload
    produces bit-identical results sharded vs unsharded."""
    from madsim_tpu.models import etcd, kafka, s3

    mesh = parallel.seed_mesh(_cpu_devices(8))
    cases = [
        (
            kafka.workload(kafka.KafkaConfig()),
            kafka.engine_config(
                kafka.KafkaConfig(), time_limit_ns=1_000_000_000, max_steps=8_000
            ),
        ),
        (
            etcd.workload(etcd.EtcdConfig()),
            etcd.engine_config(
                etcd.EtcdConfig(), time_limit_ns=1_000_000_000, max_steps=8_000
            ),
        ),
        (
            s3.workload(s3.S3Config()),
            s3.engine_config(
                s3.S3Config(), time_limit_ns=1_000_000_000, max_steps=8_000
            ),
        ),
    ]
    for wl, ecfg in cases:
        seeds = jnp.arange(16, dtype=jnp.int64)
        sharded = parallel.run_sweep_sharded(wl, ecfg, seeds, mesh)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            plain = ecore.run_sweep(wl, ecfg, seeds)
        assert jnp.array_equal(
            jax.device_get(sharded.ctr), jax.device_get(plain.ctr)
        )
        assert jnp.array_equal(
            jax.device_get(sharded.now_ns), jax.device_get(plain.now_ns)
        )


def test_sharded_chunked_matches_unsharded_with_ragged_tail():
    """Pod-scale composition: sharding over a mesh AND chunking the batch
    (with a ragged tail padded then trimmed) must be bit-identical per
    seed to one big single-device run_sweep."""
    wl = raft.workload(CFG)
    mesh = parallel.seed_mesh(_cpu_devices(8))
    seeds = jnp.arange(44, dtype=jnp.int64)  # 16+16+12: ragged tail
    chunked = parallel.run_sweep_sharded_chunked(
        wl, ECFG, seeds, mesh, chunk_per_device=2
    )
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        plain = ecore.run_sweep(wl, ECFG, seeds)
    for a, b in zip(jax.tree.leaves(chunked), jax.tree.leaves(plain)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        assert jnp.array_equal(jax.device_get(a), jax.device_get(b))

    # a batch smaller than one chunk and not divisible by the mesh is
    # padded to mesh divisibility (plain run_sweep_sharded would raise)
    small = jnp.arange(100, 112, dtype=jnp.int64)
    out = parallel.run_sweep_sharded_chunked(
        wl, ECFG, small, mesh, chunk_per_device=16384
    )
    with jax.default_device(cpu):
        plain_small = ecore.run_sweep(wl, ECFG, small)
    assert out.ctr.shape[0] == 12
    assert jnp.array_equal(
        jax.device_get(out.ctr), jax.device_get(plain_small.ctr)
    )


def test_mesh_size_must_divide_batch():
    wl = raft.workload(CFG)
    mesh = parallel.seed_mesh(_cpu_devices(8))
    with pytest.raises(Exception):
        parallel.run_sweep_sharded(wl, ECFG, jnp.arange(12, dtype=jnp.int64), mesh)


# ---------------------------------------------------------------------------
# The {1, 2, 4, 8}-device equality matrix (ROADMAP item 1): sharding the
# checked-sweep pipeline over the mesh must change NOTHING — per-seed
# state bit-equal to unsharded at thousands of seeds, and every report
# (summary totals, campaign JSONL, screen verdicts) byte-identical
# across mesh sizes even though the chunk boundaries differ.

MATRIX = (1, 2, 4, 8)
MATRIX_SEEDS = 4096


def _etcd_hist():
    """A cheap history-recording etcd workload for the matrix tests."""
    from madsim_tpu.models import etcd

    cfg = etcd.EtcdConfig(hist_slots=128)
    ecfg = etcd.engine_config(
        cfg, time_limit_ns=500_000_000, max_steps=6_000
    )
    return etcd, etcd.workload(cfg), ecfg, etcd.history_spec()


def test_mesh_matrix_per_seed_state_equality():
    """Every mesh size yields the bit-identical final state per seed at
    >= 4096 seeds (chunked + ragged boundaries differ per mesh size)."""
    devs = _cpu_devices(8)
    wl = raft.workload(CFG)
    seeds = jnp.arange(MATRIX_SEEDS, dtype=jnp.int64)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        plain = ecore.run_sweep(wl, ECFG, seeds)
    for n_dev in MATRIX:
        mesh = parallel.seed_mesh(devs[:n_dev])
        sharded = parallel.run_sweep_sharded_chunked(
            wl, ECFG, seeds, mesh, chunk_per_device=1024
        )
        for a, b in zip(jax.tree.leaves(sharded), jax.tree.leaves(plain)):
            if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
                a, b = jax.random.key_data(a), jax.random.key_data(b)
            assert jnp.array_equal(jax.device_get(a), jax.device_get(b)), (
                f"leaf mismatch at {n_dev} devices"
            )


def test_mesh_matrix_checked_sweep_report_bytes():
    """The merged checked-sweep summary (sweep + device screen + WGL
    checking) is byte-identical on 1, 2, 4 and 8 devices AND equal to
    the unsharded pipelined driver — with per-device chunking, so the
    chunk boundaries differ at every mesh size."""
    import json

    from madsim_tpu.oracle.screen import checked_sweep

    devs = _cpu_devices(8)
    _etcd, wl, ecfg, spec = _etcd_hist()
    seeds = jnp.arange(MATRIX_SEEDS, dtype=jnp.int64)
    ref = json.dumps(
        checked_sweep(
            wl, ecfg, seeds, spec, _etcd.sweep_summary, chunk_size=1024
        ),
        sort_keys=True,
    )
    for n_dev in MATRIX:
        mesh = parallel.seed_mesh(devs[:n_dev])
        blob = json.dumps(
            checked_sweep(
                wl, ecfg, seeds, spec, _etcd.sweep_summary,
                mesh=mesh, chunk_per_device=512,
            ),
            sort_keys=True,
        )
        assert blob == ref, f"report bytes differ at {n_dev} devices"


def test_mesh_screen_matches_unsharded():
    """The shard_map'd device screen produces the identical suspect mask
    as the single-device screen, per mesh size."""
    from madsim_tpu.oracle.screen import screen_sweep

    devs = _cpu_devices(8)
    _etcd, wl, ecfg, spec = _etcd_hist()
    seeds = jnp.arange(512, dtype=jnp.int64)
    plain = ecore.run_sweep(wl, ecfg, seeds)
    want = jax.device_get(screen_sweep(plain, spec, block=128))
    for n_dev in (2, 8):
        mesh = parallel.seed_mesh(devs[:n_dev])
        final = parallel.run_sweep_sharded(wl, ecfg, seeds, mesh)
        got = jax.device_get(screen_sweep(final, spec, block=128, mesh=mesh))
        assert jnp.array_equal(got, want), f"screen differs at {n_dev} devices"


def test_mesh_matrix_campaign_report_bytes(tmp_path):
    """One coverage-guided campaign (seeded mutations, history screening
    + checking) emits byte-identical JSONL reports on every mesh size."""
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.explore import CampaignConfig, run_campaign
    from madsim_tpu.explore.targets import amnesia_raft_target

    devs = _cpu_devices(8)
    target = amnesia_raft_target(
        time_limit_ns=1_000_000_000, max_steps=10_000, hist_slots=16
    )
    base = FaultSpec(
        crashes=2, crash_window_ns=800_000_000,
        restart_lo_ns=50_000_000, restart_hi_ns=300_000_000,
    )
    ccfg = CampaignConfig(rounds=2, seeds_per_round=256, chunk_size=128)
    blobs = {}
    for n_dev in MATRIX:
        path = tmp_path / f"campaign_{n_dev}.jsonl"
        run_campaign(
            target, base, ccfg, report_path=str(path),
            mesh=parallel.seed_mesh(devs[:n_dev]),
        )
        blobs[n_dev] = path.read_bytes()
    assert len(set(blobs.values())) == 1, (
        f"campaign report bytes differ across mesh sizes "
        f"{[len(b) for b in blobs.values()]}"
    )


def test_interrupt_on_8_resume_on_1_checkpoint_portability(tmp_path):
    """A checked sweep interrupted MID-CHUNK on an 8-device mesh resumes
    bit-identical on a single device (and vice versa): the v8 snapshot
    carries the mesh layout whose global chunk size the resuming mesh
    honors, and the state arrays themselves are layout-free."""
    import json

    from madsim_tpu.engine import checkpoint
    from madsim_tpu.models import etcd

    devs = _cpu_devices(8)
    _etcd, wl, ecfg, spec = _etcd_hist()
    short = etcd.engine_config(
        etcd.EtcdConfig(hist_slots=128),
        time_limit_ns=500_000_000, max_steps=300,
    )
    seeds = jnp.arange(1024, dtype=jnp.int64)
    mesh8 = parallel.seed_mesh(devs[:8])
    mesh1 = parallel.seed_mesh(devs[:1])

    straight = parallel.run_sweep_sharded_pipelined(
        wl, ecfg, seeds, _etcd.sweep_summary, mesh=mesh1, chunk_size=512
    )

    # interrupt chunk 0 mid-flight on the 8-device mesh
    partial = parallel.run_sweep_sharded(wl, short, seeds[:512], mesh8)
    path = str(tmp_path / "mid.npz")
    layout = parallel.mesh_layout(mesh8, 64)
    checkpoint.save_sweep(
        partial, path, inflight={"lo": 0, "k": 512}, mesh_layout=layout
    )
    got_layout = checkpoint.load_mesh_layout(path)
    assert got_layout == layout and got_layout["chunk_size"] == 512
    restored = checkpoint.load_sweep(path, like=partial)
    inflight = checkpoint.load_inflight(path)

    resumed = parallel.run_sweep_sharded_pipelined(
        wl, ecfg, seeds, _etcd.sweep_summary, mesh=mesh1,
        chunk_size=got_layout["chunk_size"],
        resume_from=(restored, inflight),
    )
    assert json.dumps(resumed, sort_keys=True) == json.dumps(
        straight, sort_keys=True
    )

    # and the mirror: interrupted unsharded, resumed on the full mesh
    resumed8 = parallel.run_sweep_sharded_pipelined(
        wl, ecfg, seeds, _etcd.sweep_summary, mesh=mesh8,
        chunk_size=got_layout["chunk_size"],
        resume_from=(restored, inflight),
    )
    assert json.dumps(resumed8, sort_keys=True) == json.dumps(
        straight, sort_keys=True
    )
