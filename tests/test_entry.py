"""CI coverage for the driver gate entry points (``__graft_entry__``).

Round 2's multichip gate went red because ``sharded_step``'s signature
changed and the dryrun's call site was never re-run before committing —
the test suite stayed green because nothing in tests/ imported
``__graft_entry__``. These tests exercise both driver entry points under
the same forced-8-CPU-device mesh the driver uses, so any future
signature or semantics drift breaks CI instead of the gate artifact.
"""

import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs_one_step():
    fn, example_args = graft.entry()
    out = jax.jit(fn)(*example_args)
    jax.block_until_ready(out)
    # a single lockstep step over a fresh 64-seed batch must leave live seeds
    assert int(out.done.sum()) < out.done.shape[0]


def test_dryrun_multichip_8():
    # conftest already forces an 8-CPU-device mesh in this process, so the
    # dryrun takes its in-process path (no subprocess re-exec) — the same
    # code the driver's gate executes.
    assert len(jax.devices()) >= 8
    graft.dryrun_multichip(8)
