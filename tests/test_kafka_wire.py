"""Genuine Kafka binary wire tests: the vendored probe client (or
kafka-python, when importable) driving ``kafka/wire.py`` — ApiVersions
negotiation, Metadata, Produce/Fetch with record-batch v2 + CRC32C,
ListOffsets, and the full consumer-group session
(FindCoordinator/Join/Sync/Heartbeat/OffsetCommit/OffsetFetch/Leave) —
over BOTH tiers: real TCP and the simulator's Endpoint pipes, where the
transcript must be byte-deterministic across runs of one seed."""

import asyncio
import hashlib

import pytest

import madsim_tpu as ms
from madsim_tpu.kafka import wire
from madsim_tpu.kafka.probe import (
    LoopbackTransport,
    ProbeClient,
    RealTransport,
    SimTransport,
)

# -- codec units ------------------------------------------------------------


def test_crc32c_vectors():
    # RFC 3720 test vector + the empty string
    assert wire.crc32c(b"") == 0
    assert wire.crc32c(b"123456789") == 0xE3069283


def test_varint_zigzag_roundtrip():
    for v in (0, 1, -1, 63, -64, 64, 300, -301, 2**31 - 1, -(2**31),
              2**62, -(2**62)):
        w = wire.Writer().varint(v)
        assert wire.Reader(w.done()).varint() == v, v


def test_record_batch_roundtrip_and_crc():
    records = [(1_000, b"k0", b"v0"), (1_007, None, b"v1"),
               (1_014, b"k2", None)]
    blob = wire.encode_record_batch(37, records)
    rows = wire.decode_record_batches(blob)
    assert rows == [(37, 1_000, b"k0", b"v0"), (38, 1_007, None, b"v1"),
                    (39, 1_014, b"k2", None)]
    # a flipped payload byte must fail the CRC32C check, not half-decode
    bad = bytearray(blob)
    bad[-1] ^= 0x01
    with pytest.raises(wire.WireError):
        wire.decode_record_batches(bytes(bad))


def test_frame_buffer_reassembles_arbitrary_chunking():
    frames = [b"alpha", b"", b"a much longer frame body " * 7]
    stream = b"".join(wire.frame(f) for f in frames)
    for chunk in (1, 2, 3, 5, len(stream)):
        buf = wire.FrameBuffer()
        got = []
        for i in range(0, len(stream), chunk):
            got.extend(buf.feed(stream[i:i + chunk]))
        assert got == frames, chunk


def test_unsupported_api_version_answers_apiversions_v0_error():
    """KIP-511: an out-of-range ApiVersions request still gets a v0 body
    with UNSUPPORTED_VERSION + the full matrix, so clients can downshift;
    any other API out of range (or an unknown key) drops the connection."""
    k = wire.KafkaWire()
    req = (wire.Writer().i16(wire.API_VERSIONS).i16(99).i32(7)
           .nullable_string("probe"))
    rsp = k.handle_frame(req.done())
    r = wire.Reader(rsp)
    assert r.i32() == 7  # correlation id
    assert r.i16() == wire.ERR_UNSUPPORTED_VERSION
    apis = {}
    r.array(lambda: apis.update({r.i16(): (r.i16(), r.i16())}))
    assert apis == {a: (lo, hi) for a, (lo, hi, _f) in
                    wire.SUPPORTED_APIS.items()}

    with pytest.raises(wire.WireError):
        k.handle_frame(wire.Writer().i16(wire.API_FETCH).i16(0).i32(1)
                       .nullable_string(None).done())
    with pytest.raises(wire.WireError):
        k.handle_frame(wire.Writer().i16(12345).i16(0).i32(1).done())


def test_produce_acks_zero_gets_no_response():
    async def main():
        k = wire.KafkaWire()
        c = ProbeClient(LoopbackTransport(k))
        await c.create_topics([("t", 1)])
        err, base = await c.produce("t", 0, [(5, None, b"x")], acks=0)
        assert (err, base) == (0, -1)
        err, _high, rows = await c.fetch("t", 0, 0)
        assert err == 0 and [r[3] for r in rows] == [b"x"]

    asyncio.run(main())


# -- the canonical session, shared by both tiers ----------------------------


async def run_probe_session(client: ProbeClient, recorder=None) -> dict:
    """ApiVersions -> Metadata -> CreateTopics -> Produce -> Fetch ->
    ListOffsets -> a full two-member consumer-group session with a
    mid-session rebalance. Returns the outcome summary; records a
    HostRecorder history checked against the kafka LogSpec when asked."""
    from madsim_tpu.oracle import HostRecorder, check_history
    from madsim_tpu.oracle.history import OP_FETCH, OP_PRODUCE
    from madsim_tpu.oracle.specs import LogSpec

    rec = recorder or HostRecorder(clock=lambda: 0)

    err, apis = await client.api_versions(ver=0)
    assert err == 0 and apis == {
        a: (lo, hi) for a, (lo, hi, _f) in wire.SUPPORTED_APIS.items()
    }
    err, apis3 = await client.api_versions(ver=3)  # the flexible form
    assert err == 0 and apis3 == apis

    out = await client.create_topics([("wt", 2)])
    assert out == [("wt", 0, None)]
    md = await client.metadata()
    assert md == {"wt": 2}

    produced = []
    for i in range(8):
        p = i % 2
        opid = rec.invoke(client=0, op=OP_PRODUCE, key=p, inp=i)
        err, off = await client.produce(
            "wt", p, [(1_000 + i, f"k{i}".encode(), f"v{i}".encode())]
        )
        assert err == 0
        rec.complete(client=0, opid=opid, out=off + 1)
        produced.append((p, off))

    # fetch both partitions from 0, contiguously (LogSpec structural)
    fetched = {}
    for p in (0, 1):
        offset = 0
        rows_all = []
        while True:
            opid = rec.invoke(client=1, op=OP_FETCH, key=p, inp=offset)
            err, high, rows = await client.fetch("wt", p, offset)
            assert err == 0
            rec.complete(client=1, opid=opid, out=len(rows))
            if not rows:
                break
            rows_all.extend(rows)
            offset = rows[-1][0] + 1
        assert [r[3] for r in rows_all] == [
            f"v{i}".encode() for i in range(8) if i % 2 == p
        ]
        fetched[p] = len(rows_all)

    result = check_history(rec.history(), LogSpec())
    assert result.ok, result.reason

    err, _ts, latest = await client.list_offsets("wt", 0, -1)
    assert err == 0 and latest == 4
    err, _ts, earliest = await client.list_offsets("wt", 0, -2)
    assert err == 0 and earliest == 0

    # consumer-group session with a mid-session rebalance
    m0, g0, a0 = await client.group_session("cg", ["wt"])
    assert len(a0) == 2
    assert await client.heartbeat("cg", g0, m0) == 0
    m1, g1, a1 = await client.group_session("cg", ["wt"])
    assert g1 == g0 + 1 and len(a1) == 1
    assert await client.heartbeat("cg", g0, m0) == wire.ERR_REBALANCE_IN_PROGRESS
    m0b, g0b, a0b = await client.group_session("cg", ["wt"], member_id=m0)
    assert m0b == m0 and g0b == g1 and len(a0b) == 1
    assert sorted(a0b + a1) == [("wt", 0), ("wt", 1)]

    commits = await client.offset_commit("cg", g0b, m0, [a0b[0] + (3,)])
    assert commits == [(a0b[0][0], a0b[0][1], 0)]
    stale = await client.offset_commit("cg", g0, m0, [a0b[0] + (1,)])
    assert stale[0][2] == wire.ERR_ILLEGAL_GENERATION
    got = await client.offset_fetch("cg", [a0b[0], a1[0]])
    assert (a0b[0][0], a0b[0][1], 3) in got
    assert (a1[0][0], a1[0][1], None) in got

    assert await client.leave_group("cg", m1) == 0
    assert await client.heartbeat("cg", g0b, m0) == wire.ERR_REBALANCE_IN_PROGRESS

    return {"produced": produced, "fetched": fetched,
            "group": [m0, m1, g0, g1]}


# -- real tier: genuine TCP --------------------------------------------------


def test_wire_session_over_real_tcp():
    from madsim_tpu import real

    async def main():
        server = wire.WireServer()
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            if task.done():
                task.result()
            await real.sleep(0.005)
        client = ProbeClient(await RealTransport.connect(server.bound_addr))
        out = await run_probe_session(client)
        assert out["fetched"] == {0: 4, 1: 4}
        client.close()
        task.abort()

    real.Runtime().block_on(main())


def test_wire_session_with_kafka_python_if_available():
    """The stock-client leg proper: kafka-python against the wire server
    (skipped when the library is absent — the vendored probe then holds
    the round-trip story, as the module docstring explains)."""
    kafka_lib = pytest.importorskip("kafka")
    from madsim_tpu import real

    async def main():
        server = wire.WireServer()
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            await real.sleep(0.005)
        host, port = server.bound_addr
        loop = asyncio.get_running_loop()

        def stock_roundtrip():
            admin = kafka_lib.KafkaAdminClient(
                bootstrap_servers=f"{host}:{port}"
            )
            from kafka.admin import NewTopic

            admin.create_topics([NewTopic("st", 2, 1)])
            prod = kafka_lib.KafkaProducer(bootstrap_servers=f"{host}:{port}")
            for i in range(4):
                prod.send("st", key=b"k%d" % i, value=b"v%d" % i,
                          partition=i % 2)
            prod.flush()
            cons = kafka_lib.KafkaConsumer(
                "st", bootstrap_servers=f"{host}:{port}",
                group_id="stock-grp", auto_offset_reset="earliest",
                consumer_timeout_ms=5000,
            )
            got = sorted(m.value for m in cons)
            cons.close()
            prod.close()
            admin.close()
            return got

        got = await loop.run_in_executor(None, stock_roundtrip)
        assert got == [b"v0", b"v1", b"v2", b"v3"]
        task.abort()

    real.Runtime().block_on(main())


# -- sim tier: Endpoint pipes + byte-deterministic transcripts ---------------

BROKER = "10.0.0.1:9092"


def _sim_session(seed: int) -> str:
    """One full probe session inside the simulator; returns the sha256
    of the server's (request, clock, response) transcript."""
    rt = ms.Runtime(seed=seed)

    async def main():
        h = ms.current_handle()
        server = wire.SimWireServer()
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: server.serve(BROKER)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)
        server.wire.recorder = transcript = []

        async def run():
            client = ProbeClient(await SimTransport.connect(BROKER))
            out = await run_probe_session(client)
            assert out["fetched"] == {0: 4, 1: 4}
            client.close()

        await node.spawn(run())
        acc = hashlib.sha256()
        for req, now, rsp in transcript:
            acc.update(req)
            acc.update(str(now).encode())
            acc.update(rsp if rsp is not None else b"\x00")
        return acc.hexdigest()

    return rt.block_on(main())


def test_wire_session_over_sim_pipes_transcript_deterministic():
    """The same genuine protocol session runs over the sim tier's
    Endpoint/connect1 pipes, and two runs of one seed produce
    byte-identical wire transcripts (the cross-process variant is the
    determinism gate's wire leg)."""
    d1 = _sim_session(1234)
    d2 = _sim_session(1234)
    assert d1 == d2
    assert d1 != _sim_session(1235)  # different schedule, different times


def test_wire_replay_of_recorded_transcript_is_byte_identical():
    """The purity contract the load gate leans on: re-feeding a recorded
    (frame, clock) transcript through a FRESH broker reproduces every
    response byte."""

    async def main():
        k = wire.KafkaWire(clock_ms=lambda: 4_200)
        k.recorder = transcript = []
        client = ProbeClient(LoopbackTransport(k))
        await run_probe_session(client)

        clock_feed = [now for _req, now, _rsp in transcript]
        replay = wire.KafkaWire(clock_ms=lambda: clock_feed.pop(0))
        for req, _now, rsp in transcript:
            assert replay.handle_frame(req) == rsp

    asyncio.run(main())


# -- the legacy A/B flag -----------------------------------------------------


def test_real_mode_legacy_codec_flag_roundtrip(monkeypatch):
    """MADSIM_KAFKA_LEGACY=1 swaps BOTH sides back to the pre-wire
    private framed codec (the A/B escape hatch, like the engine's
    legacy_queue); the client API is oblivious."""
    monkeypatch.setenv("MADSIM_KAFKA_LEGACY", "1")
    from madsim_tpu import real
    from madsim_tpu.kafka import NewTopic
    from madsim_tpu.real import kafka as rkafka

    async def main():
        broker = rkafka.SimBroker()
        task = real.spawn(broker.serve(("127.0.0.1", 0)))
        while broker.bound_addr is None:
            if task.done():
                task.result()
            await real.sleep(0.005)
        assert broker.wire_server is None  # the legacy dispatcher is up
        addr = "%s:%d" % broker.bound_addr
        config = rkafka.ClientConfig().set("bootstrap.servers", addr)
        admin = await config.create(rkafka.AdminClient)
        assert await admin.create_topics([NewTopic("lg", 1)]) == [None]
        producer = await config.create(rkafka.FutureProducer)
        assert await producer.send(
            rkafka.FutureRecord.to("lg").with_payload("old-school")
        ) == (0, 0)
        consumer = await config.create(rkafka.BaseConsumer)
        await consumer.subscribe(["lg"])
        msg = await consumer.poll(timeout_s=1.0)
        assert msg is not None and msg.payload == b"old-school"
        task.abort()

    real.Runtime().block_on(main())
