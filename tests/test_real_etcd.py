"""Real-mode etcd: the unchanged client API against the EtcdService state
machine served over real TCP — the dual-mode property of
madsim-etcd-client/src/lib.rs (sim and production share one surface)."""

import pytest

from madsim_tpu import real
from madsim_tpu.real import etcd


async def _start_server(timeout_rate: float = 0.0):
    server = etcd.Server(etcd.EtcdService(), timeout_rate)
    task = real.spawn(server.serve(("127.0.0.1", 0)))
    while server.bound_addr is None:
        if task.done():
            task.result()  # surface the bind failure instead of spinning
        await real.sleep(0.005)
    host, port = server.bound_addr
    return server, task, f"{host}:{port}"


def test_real_etcd_kv_txn_roundtrip():
    async def main():
        _server, task, addr = await _start_server()
        client = await etcd.Client.connect(addr)

        # put / get / delete over real sockets
        await client.put("k1", "v1")
        resp = await client.get("k1")
        assert resp.kvs()[0].value_str() == "v1"
        assert resp.header().revision() >= 1

        await client.put("k1", "v2")
        resp = await client.get("k1")
        assert resp.kvs()[0].value_str() == "v2"

        prefix_opts = etcd.GetOptions().with_prefix()
        await client.put("k2", "x")
        resp = await client.get("k", prefix_opts)
        assert {kv.key_str() for kv in resp.kvs()} == {"k1", "k2"}

        dresp = await client.delete("k2")
        assert dresp.deleted() == 1

        # txn: compare-and-swap goes through the real wire
        txn = (
            etcd.Txn()
            .when([etcd.Compare.value("k1", etcd.CompareOp.EQUAL, "v2")])
            .and_then([etcd.TxnOp.put("k1", "v3")])
            .or_else([etcd.TxnOp.put("k1", "wrong")])
        )
        tresp = await client.txn(txn)
        assert tresp.succeeded()
        assert (await client.get("k1")).kvs()[0].value_str() == "v3"

        # dump/load snapshot across the wire (keys are base64 in the dump)
        import base64, json

        dump = await client.dump()
        keys = {e["key"] for e in json.loads(dump)["kv"]}
        assert base64.b64encode(b"k1").decode() in keys
        task.abort()

    real.Runtime().block_on(main())


def test_real_etcd_watch_stream():
    async def main():
        _server, task, addr = await _start_server()
        client = await etcd.Client.connect(addr)

        watch = await client.watch_client().watch("w", prefix=True)

        async def writer():
            await real.sleep(0.02)
            await client.put("w/a", "1")
            await client.put("w/b", "2")

        w = real.spawn(writer())
        ev1 = await watch.next()
        ev2 = await watch.next()
        assert ev1.kv.key_str() == "w/a" and ev1.kv.value_str() == "1"
        assert ev2.kv.key_str() == "w/b" and ev2.kv.value_str() == "2"
        assert ev1.type == etcd.EventType.PUT
        await w
        watch.cancel()
        task.abort()

    real.Runtime().block_on(main())


def test_real_etcd_election_campaign_blocks_until_resign():
    """campaign() parks on the server's watcher (asyncio futures in real
    mode) until the current leader resigns."""

    async def main():
        _server, task, addr = await _start_server()
        c1 = await etcd.Client.connect(addr)
        c2 = await etcd.Client.connect(addr)

        lease1 = await c1.lease_client().grant(60)
        lease2 = await c2.lease_client().grant(60)

        el1 = c1.election_client()
        el2 = c2.election_client()
        r1 = await el1.campaign("pres", "node1", lease1.id())
        leader = await el2.leader("pres")
        assert leader.kv().value_str() == "node1"

        # second campaigner blocks until the first resigns
        acquired = []

        async def second():
            r2 = await el2.campaign("pres", "node2", lease2.id())
            acquired.append(r2)

        t2 = real.spawn(second())
        await real.sleep(0.05)
        assert not acquired  # still parked
        await el1.resign(r1.leader())
        await t2
        assert acquired
        leader = await el1.leader("pres")
        assert leader.kv().value_str() == "node2"
        task.abort()

    real.Runtime().block_on(main())


def test_real_etcd_timeout_rate_maps_to_unavailable():
    """timeout_rate=1.0: every request stalls then fails Unavailable — the
    fault knob works outside the simulator too (on wall-clock delays)."""

    async def main():
        server = etcd.Server(etcd.EtcdService(), timeout_rate=1.0)
        # shrink the injected 5-15 s stall so the test stays fast
        server._uniform = lambda a, b: 0.01
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            await real.sleep(0.005)
        host, port = server.bound_addr
        client = await etcd.Client.connect(f"{host}:{port}")
        from madsim_tpu.grpc.status import Code, Status

        with pytest.raises(Status) as e:
            await client.put("k", "v")
        assert e.value.code == Code.UNAVAILABLE
        task.abort()

    real.Runtime().block_on(main())
