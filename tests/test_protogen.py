"""`.proto` ingestion: a real proto file drives sim clients/servers.

The madsim-tonic-build analogue (ref prost.rs:599-680 generates sim stubs
next to real ones from one proto): compile_protos parses services and
streaming kinds with protoc, produces REAL protobuf message classes, and
wires implement()/client() into the simulator's gRPC shim — all four
streaming modes over a simulated cluster.
"""

import os
import tempfile

import pytest

import madsim_tpu as ms
from madsim_tpu import grpc

PROTO = """
syntax = "proto3";
package echotest;

message EchoRequest { string text = 1; int32 n = 2; }
message EchoReply   { string text = 1; }

service Echo {
  rpc Say (EchoRequest) returns (EchoReply);
  rpc Fan (EchoRequest) returns (stream EchoReply);
  rpc Sum (stream EchoRequest) returns (EchoReply);
  rpc Chat (stream EchoRequest) returns (stream EchoReply);
}
"""


def _compile():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "echotest.proto")
        with open(path, "w") as f:
            f.write(PROTO)
        return grpc.compile_protos(path)


def test_descriptor_parsing():
    pkg = _compile()
    assert "echotest.Echo" in pkg.services
    assert pkg.services["echotest.Echo"].methods == {
        "say": "unary",
        "fan": "server_streaming",
        "sum": "client_streaming",
        "chat": "bidi_streaming",
    }
    # message classes are real protobufs that round-trip bytes
    req = pkg.messages["echotest.EchoRequest"](text="hi", n=3)
    cls = pkg.messages["echotest.EchoRequest"]
    assert cls.FromString(req.SerializeToString()).text == "hi"


def test_proto_service_all_modes_in_sim():
    pkg = _compile()
    EchoRequest = pkg.messages["echotest.EchoRequest"]
    EchoReply = pkg.messages["echotest.EchoReply"]

    @pkg.implement("echotest.Echo")
    class Echo:
        async def say(self, request):
            msg = request.message
            return EchoReply(text=f"say:{msg.text}")

        async def fan(self, request):
            msg = request.message
            for i in range(msg.n):
                yield EchoReply(text=f"fan{i}:{msg.text}")

        async def sum(self, stream):
            texts = [m.text async for m in stream]
            return EchoReply(text="+".join(texts))

        async def chat(self, stream):
            async for m in stream:
                yield EchoReply(text=f"re:{m.text}")

    rt = ms.Runtime(seed=21)

    async def main():
        h = ms.current_handle()
        addr = "10.0.0.1:700"

        async def serve():
            await grpc.Server.builder().add_service(Echo()).serve(addr)

        h.create_node().name("server").ip("10.0.0.1").init(lambda: serve()).build()
        client_node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            channel = await grpc.Endpoint.from_static(f"http://{addr}").connect()
            c = pkg.client("echotest.Echo", channel)
            r = await c.say(EchoRequest(text="x"))
            assert r.into_inner().text == "say:x"
            stream = await c.fan(EchoRequest(text="y", n=3))
            assert [m.text async for m in stream] == [
                "fan0:y", "fan1:y", "fan2:y",
            ]
            r = await c.sum([EchoRequest(text=t) for t in "abc"])
            assert r.into_inner().text == "a+b+c"
            stream = await c.chat([EchoRequest(text=t) for t in ("u", "v")])
            assert [m.text async for m in stream] == ["re:u", "re:v"]

        await client_node.spawn(run())

    rt.block_on(main())


def test_unknown_service_and_missing_method_error():
    pkg = _compile()
    with pytest.raises(grpc.ProtogenError, match="unknown service"):
        pkg.client("echotest.Nope", channel=None)
    with pytest.raises(grpc.ProtogenError, match="missing rpc method"):

        @pkg.implement("echotest.Echo")
        class Incomplete:
            async def say(self, request):
                return None


def test_modified_proto_same_filename_errors_not_stale():
    """Recompiling a *changed* proto under the same filename must raise,
    not silently hand back the first compile's message classes (the
    descriptor pool can't hold two versions of one file anyway)."""
    pkg = _compile()  # seeds the module cache with echotest.proto
    assert "n" in {
        f.name
        for f in pkg.messages["echotest.EchoRequest"].DESCRIPTOR.fields
    }
    changed = PROTO.replace("int32 n = 2;", "int32 n = 2; bool extra = 3;")
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "echotest.proto")
        with open(path, "w") as f:
            f.write(changed)
        with pytest.raises(grpc.ProtogenError, match="changed since"):
            grpc.compile_protos(path)
    # an unchanged recompile still reuses the cached module quietly
    pkg2 = _compile()
    assert pkg2.messages["echotest.EchoRequest"] is pkg.messages[
        "echotest.EchoRequest"
    ]


def test_bad_proto_reports_protoc_error():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bad.proto")
        with open(path, "w") as f:
            f.write('syntax = "proto3";\nmessage Broken {')
        with pytest.raises(grpc.ProtogenError, match="protoc failed"):
            grpc.compile_protos(path)
    with pytest.raises(grpc.ProtogenError, match="no such proto"):
        grpc.compile_protos("/nonexistent/x.proto")
