"""Real-mode twin: the sim API surface over asyncio + real sockets
(the analogue of the reference's std tree, madsim/src/std/)."""

import time as walltime

import pytest

from madsim_tpu import real
from madsim_tpu.net.rpc import Request


class Ping(Request):
    def __init__(self, value: int):
        self.value = value


def test_real_endpoint_tag_matching_loopback():
    rt = real.Runtime()

    async def main():
        server = await real.Endpoint.bind(("127.0.0.1", 0))
        client = await real.Endpoint.bind(("127.0.0.1", 0))
        addr = server.local_addr()

        async def serve():
            data, src = await server.recv_from(7)
            assert data == b"ping"
            await server.send_to(src, 8, b"pong")

        t = real.spawn(serve())
        await client.send_to(addr, 7, b"ping")
        data, _src = await client.recv_from(8)
        assert data == b"pong"
        await t
        server.close()
        client.close()

    rt.block_on(main())


def test_real_rpc_roundtrip():
    rt = real.Runtime()

    async def main():
        server = await real.Endpoint.bind(("127.0.0.1", 0))

        async def handler(req: Ping) -> int:
            return req.value * 2

        server.add_rpc_handler(Ping, handler)
        client = await real.Endpoint.bind(("127.0.0.1", 0))
        for i in range(5):
            assert await client.call(server.local_addr(), Ping(i)) == 2 * i
        # call_timeout against a dead port times out
        with pytest.raises(real.time.TimeoutError):
            await client.call_timeout(("127.0.0.1", 1), Ping(1), 0.2)
        server.close()
        client.close()

    rt.block_on(main())


def test_real_time_is_wall_time():
    rt = real.Runtime()

    async def main():
        t0 = walltime.monotonic()
        await real.sleep(0.05)
        assert walltime.monotonic() - t0 >= 0.045
        iv = real.interval(0.02)
        await iv.tick()  # immediate
        t1 = walltime.monotonic()
        await iv.tick()
        assert walltime.monotonic() - t1 >= 0.01

    rt.block_on(main())


def test_real_spawn_and_abort():
    rt = real.Runtime()

    async def main():
        hits = []

        async def worker():
            while True:
                await real.sleep(0.01)
                hits.append(1)

        h = real.spawn(worker())
        await real.sleep(0.05)
        h.abort()
        await real.sleep(0.03)
        n = len(hits)
        await real.sleep(0.03)
        assert len(hits) == n and n >= 2

    rt.block_on(main())


# -- framed TCP transport (reference std/net/tcp.rs parity) ------------------


def test_tcp_endpoint_tag_matching_loopback():
    rt = real.Runtime()

    async def main():
        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        client = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        addr = server.local_addr()

        async def serve():
            data, src = await server.recv_from(7)
            assert data == b"ping"
            await server.send_to(src, 8, b"pong")

        t = real.spawn(serve())
        await client.send_to(addr, 7, b"ping")
        data, _src = await client.recv_from(8)
        assert data == b"pong"
        await t
        server.close()
        client.close()

    rt.block_on(main())


def test_tcp_rpc_concurrent_clients():
    rt = real.Runtime()

    async def main():
        import asyncio

        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))

        async def handler(req: Ping) -> int:
            await real.sleep(0.005)  # overlap the in-flight requests
            return req.value * 3

        server.add_rpc_handler(Ping, handler)
        clients = [await real.TcpEndpoint.bind(("127.0.0.1", 0)) for _ in range(5)]

        async def one(i, c):
            return await c.call(server.local_addr(), Ping(i))

        results = await asyncio.gather(
            *(one(i, c) for i, c in enumerate(clients) for _ in range(3))
        )
        assert results == [i * 3 for i in range(5) for _ in range(3)]
        for c in clients:
            c.close()
        server.close()

    rt.block_on(main())


def test_tcp_large_payload_beyond_udp_limit():
    """Length-delimited framing has no datagram size cliff: 1 MiB payloads
    round-trip (UDP tops out near 64 KiB)."""
    rt = real.Runtime()

    async def main():
        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        client = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        blob = bytes(range(256)) * 4096  # 1 MiB

        async def serve():
            data, src = await server.recv_from(1)
            await server.send_to(src, 2, data[::-1])

        t = real.spawn(serve())
        await client.send_to(server.local_addr(), 1, blob)
        data, _ = await client.recv_from(2)
        assert data == blob[::-1]
        await t
        server.close()
        client.close()

    rt.block_on(main())


def test_tcp_reconnect_after_server_restart():
    """A cached connection that dies is evicted and redialed: the client
    keeps working across a server endpoint restart on the same port."""
    rt = real.Runtime()

    async def main():
        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        addr = server.local_addr()

        async def echo(ep):
            while True:
                data, src = await ep.recv_from(5)
                await ep.send_to(src, 6, data)

        t1 = real.spawn(echo(server))
        client = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        await client.send_to(addr, 5, b"one")
        data, _ = await client.recv_from(6)
        assert data == b"one"

        t1.abort()
        server.close()
        await real.sleep(0.1)  # client reader sees EOF, evicts the conn

        server2 = await real.TcpEndpoint.bind(addr)
        t2 = real.spawn(echo(server2))
        await client.send_to(addr, 5, b"two")
        data, _ = await client.recv_from(6)
        assert data == b"two"
        t2.abort()
        server2.close()
        client.close()

    rt.block_on(main())


# -- restricted codec (the pickle-RCE fix) -----------------------------------


def test_codec_roundtrip_structures():
    from madsim_tpu.real import codec

    cases = [
        None, True, False, 0, -1, 2**64 - 1, -(2**70), 3.5, "héllo", b"\x00\xff",
        (1, "a", b"b"), [1, [2, [3]]], {"k": (1, 2), 5: None},
        (2**63, Ping(7), b""),
    ]
    for obj in cases:
        out = codec.loads(codec.dumps(obj))
        if isinstance(obj, Ping):
            assert isinstance(out, Ping) and out.value == obj.value
        elif isinstance(obj, tuple) and any(isinstance(x, Ping) for x in obj):
            assert out[0] == obj[0] and out[1].value == obj[1].value
        else:
            assert out == obj and type(out) is type(obj)


def test_codec_refuses_unregistered_types():
    """The security property: a frame naming a class that is not a
    registered Request cannot materialize it (no import, no code run)."""
    import struct as _struct

    from madsim_tpu.real import codec

    class NotRegistered:
        pass

    with pytest.raises(codec.CodecError):
        codec.dumps(NotRegistered())

    # hand-craft a hostile frame claiming to be os.system-adjacent
    name = b"os::system"
    frame = b"O" + _struct.pack(">I", len(name)) + name + b"d" + _struct.pack(">I", 0)
    with pytest.raises(codec.CodecError):
        codec.loads(frame)

    # truncated and trailing-garbage frames are rejected, not crashes
    good = codec.dumps((1, b"x"))
    with pytest.raises(codec.CodecError):
        codec.loads(good[:-1])
    with pytest.raises(codec.CodecError):
        codec.loads(good + b"Z")


def test_codec_rejects_slots_classes_loudly():
    """A __slots__ class can't round-trip through the instance-dict
    protocol; the failure must be a CodecError at register/encode time,
    not a raw AttributeError escaping dumps."""
    from madsim_tpu.real import codec

    class Slotted:
        __slots__ = ("x",)

    with pytest.raises(codec.CodecError, match="__dict__"):
        codec.register(Slotted)

    # a slots class that slipped past registration (e.g. a Request
    # subclass) still fails as a codec-level error on encode
    codec._EXTRA_TYPES[f"{Slotted.__module__}::{Slotted.__qualname__}"] = Slotted
    try:
        s = Slotted()
        s.x = 1
        with pytest.raises(codec.CodecError, match="__dict__"):
            codec.dumps(s)
    finally:
        del codec._EXTRA_TYPES[f"{Slotted.__module__}::{Slotted.__qualname__}"]


def test_udp_endpoint_drops_hostile_frames():
    """A malformed/hostile datagram is dropped like line noise; the
    endpoint keeps serving."""
    rt = real.Runtime()

    async def main():
        server = await real.Endpoint.bind(("127.0.0.1", 0))
        client = await real.Endpoint.bind(("127.0.0.1", 0))
        import socket as _socket

        raw = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        raw.sendto(b"\x80\x04pickle-bomb", server.local_addr())
        raw.sendto(b"O\x00\x00\x00\x09os::evil" + b"d\x00\x00\x00\x00", server.local_addr())
        raw.close()

        async def serve():
            data, src = await server.recv_from(9)
            await server.send_to(src, 10, data)

        t = real.spawn(serve())
        await client.send_to(server.local_addr(), 9, b"still-alive")
        data, _ = await client.recv_from(10)
        assert data == b"still-alive"
        await t
        server.close()
        client.close()

    rt.block_on(main())


def test_rpc_unencodable_response_fails_caller_loudly():
    """A handler returning an unregistered class must raise RpcError at
    the caller, not hang it forever on a response that can never arrive."""
    from madsim_tpu.real.net import RpcError

    class Opaque:  # not a Request, not registered
        pass

    rt = real.Runtime()

    async def main():
        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))

        async def handler(req: Ping):
            return Opaque()

        server.add_rpc_handler(Ping, handler)
        client = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        with pytest.raises(RpcError):
            await real.timeout(2.0, client.call(server.local_addr(), Ping(1)))
        server.close()
        client.close()

    rt.block_on(main())


def test_tcp_hello_claimed_host_is_ignored():
    """Connection keys use the TCP-observed peer IP: a hello claiming
    another node's host cannot capture that node's traffic, and replies
    still reach the real dialer."""
    rt = real.Runtime()

    async def main():
        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))

        async def serve():
            data, src = await server.recv_from(3)
            # src host is the observed 127.0.0.1, never the claimed one
            assert src[0] == "127.0.0.1"
            await server.send_to(src, 4, b"ack")

        t = real.spawn(serve())
        client = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        client._local = ("10.99.99.99", client._local[1])  # lie about host
        await client.send_to(server.local_addr(), 3, b"hi")
        data, _ = await client.recv_from(4)
        assert data == b"ack"
        await t
        server.close()
        client.close()

    rt.block_on(main())


def test_tcp_oversized_frame_fails_at_sender():
    rt = real.Runtime()

    async def main():
        server = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        client = await real.TcpEndpoint.bind(("127.0.0.1", 0))
        with pytest.raises(ValueError):
            await client.send_to(server.local_addr(), 1, bytes(70 * 1024 * 1024))
        server.close()
        client.close()

    rt.block_on(main())


def test_codec_hostile_bytes_always_raise_codec_error():
    from madsim_tpu.real import codec

    hostile = [
        b"s\x00\x00\x00\x01\xff",  # invalid UTF-8 string
        b"d\x00\x00\x00\x01l\x00\x00\x00\x00N",  # unhashable dict key
        b"",
        b"\x99",
    ]
    for frame in hostile:
        with pytest.raises(codec.CodecError):
            codec.loads(frame)
