"""Real-mode twin: the sim API surface over asyncio + real sockets
(the analogue of the reference's std tree, madsim/src/std/)."""

import time as walltime

import pytest

from madsim_tpu import real
from madsim_tpu.net.rpc import Request


class Ping(Request):
    def __init__(self, value: int):
        self.value = value


def test_real_endpoint_tag_matching_loopback():
    rt = real.Runtime()

    async def main():
        server = await real.Endpoint.bind(("127.0.0.1", 0))
        client = await real.Endpoint.bind(("127.0.0.1", 0))
        addr = server.local_addr()

        async def serve():
            data, src = await server.recv_from(7)
            assert data == b"ping"
            await server.send_to(src, 8, b"pong")

        t = real.spawn(serve())
        await client.send_to(addr, 7, b"ping")
        data, _src = await client.recv_from(8)
        assert data == b"pong"
        await t
        server.close()
        client.close()

    rt.block_on(main())


def test_real_rpc_roundtrip():
    rt = real.Runtime()

    async def main():
        server = await real.Endpoint.bind(("127.0.0.1", 0))

        async def handler(req: Ping) -> int:
            return req.value * 2

        server.add_rpc_handler(Ping, handler)
        client = await real.Endpoint.bind(("127.0.0.1", 0))
        for i in range(5):
            assert await client.call(server.local_addr(), Ping(i)) == 2 * i
        # call_timeout against a dead port times out
        with pytest.raises(real.time.TimeoutError):
            await client.call_timeout(("127.0.0.1", 1), Ping(1), 0.2)
        server.close()
        client.close()

    rt.block_on(main())


def test_real_time_is_wall_time():
    rt = real.Runtime()

    async def main():
        t0 = walltime.monotonic()
        await real.sleep(0.05)
        assert walltime.monotonic() - t0 >= 0.045
        iv = real.interval(0.02)
        await iv.tick()  # immediate
        t1 = walltime.monotonic()
        await iv.tick()
        assert walltime.monotonic() - t1 >= 0.01

    rt.block_on(main())


def test_real_spawn_and_abort():
    rt = real.Runtime()

    async def main():
        hits = []

        async def worker():
            while True:
                await real.sleep(0.01)
                hits.append(1)

        h = real.spawn(worker())
        await real.sleep(0.05)
        h.abort()
        await real.sleep(0.03)
        n = len(hits)
        await real.sleep(0.03)
        assert len(hits) == n and n >= 2

    rt.block_on(main())
