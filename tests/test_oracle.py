"""History oracle (madsim_tpu/oracle): recording plane + WGL checker.

Covers the subsystem's contracts bottom-up: the checker on hand-written
histories (known-linearizable and known-not), the engine's history
buffer (in-step append, sticky no-wrap overflow), validation that the
checker FIRES on a seeded etcd bug and stays clean on the default
config over pinned seed ranges, cross-path byte identity (device-sweep
lane vs bit-exact CPU traced replay), the explore wiring (history
triage flavor + checker-verified shrink), the etcd/kafka ``viol_kind``
flavor parity, and the host-tier recorder shim.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import explore, replay
from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.faults import FaultSpec
from madsim_tpu.models import etcd, kafka
from madsim_tpu.oracle import (
    History,
    HostRecorder,
    KVSpec,
    LogSpec,
    Op,
    check_history,
    decode_seed,
    decode_sweep,
    first_bad_prefix,
    history_bytes,
    violating_seeds,
)
from madsim_tpu.oracle.history import (
    OP_FETCH,
    OP_GET,
    OP_PRODUCE,
    OP_PUT,
)

SEEDS = jnp.arange(16, dtype=jnp.int64)

ETCD_CLEAN = etcd.EtcdConfig(hist_slots=256)
ETCD_BUG = etcd.EtcdConfig(hist_slots=256, bug_stale_read=True)


def _ecfg(cfg):
    return etcd.engine_config(cfg, time_limit_ns=2_000_000_000, max_steps=20_000)


def _op(c, o, k, inp, out, t0, t1, opid=0):
    return Op(c, o, k, inp, out, t0, t1, opid)


def _hist(*ops):
    return History(seed=0, ops=tuple(ops), overflow=False, rows=2 * len(ops))


# -- the checker on hand-written histories -----------------------------------


def test_checker_accepts_linearizable_register_history():
    """Concurrent put/get interleavings with a consistent witness order."""
    h = _hist(
        _op(0, OP_PUT, 1, 5, 5, 0, 100, 0),
        _op(1, OP_GET, 1, 0, -1, 10, 50, 0),  # read-before-put is open interval
        _op(1, OP_GET, 1, 0, 5, 60, 150, 1),  # concurrent with put: may see it
        _op(0, OP_GET, 1, 0, 5, 200, 250, 1),
    )
    r = check_history(h, KVSpec())
    assert r.ok and r.decided and r.bad_index == -1


def test_checker_rejects_stale_read():
    """A read strictly after an acked overwrite must not see the old value."""
    h = _hist(
        _op(0, OP_PUT, 1, 5, 5, 0, 100, 0),
        _op(0, OP_PUT, 1, 7, 7, 150, 250, 1),
        _op(1, OP_GET, 1, 0, 5, 300, 400, 0),
    )
    r = check_history(h, KVSpec())
    assert not r.ok and r.bad_index == 2
    assert "get" in r.reason


def test_checker_rejects_phantom_read():
    """A read of a value nobody ever wrote has no explanation."""
    h = _hist(_op(1, OP_GET, 3, 0, 42, 10, 20, 0))
    r = check_history(h, KVSpec())
    assert not r.ok and r.bad_index == 0


def test_open_ops_are_optional():
    """A PUT whose ack was lost may have happened (a later read observes
    it) or not (no read does) — both histories are linearizable."""
    observed = _hist(
        _op(0, OP_PUT, 1, 5, 0, 0, -1, 0),
        _op(1, OP_GET, 1, 0, 5, 300, 400, 0),
    )
    silent = _hist(
        _op(0, OP_PUT, 1, 5, 0, 0, -1, 0),
        _op(1, OP_GET, 1, 0, -1, 300, 400, 0),
    )
    assert check_history(observed, KVSpec()).ok
    assert check_history(silent, KVSpec()).ok


def test_keys_check_independently():
    """Locality: a violation on key 2 never implicates ops on key 1, and
    the reported bad op is the earliest-invoked one across partitions."""
    h = _hist(
        _op(0, OP_PUT, 1, 5, 5, 0, 100, 0),
        _op(0, OP_PUT, 2, 6, 6, 120, 200, 1),
        _op(1, OP_GET, 2, 0, 9, 300, 400, 0),  # phantom on key 2
        _op(1, OP_GET, 1, 0, 5, 500, 600, 1),  # fine on key 1
    )
    r = check_history(h, KVSpec())
    assert not r.ok
    assert r.bad_op.key == 2 and r.bad_index == 2


def test_first_bad_prefix_locates_the_op():
    ops = (
        _op(0, OP_PUT, 1, 5, 5, 0, 100, 0),
        _op(1, OP_GET, 1, 0, 5, 200, 300, 0),
        _op(1, OP_GET, 1, 0, 8, 400, 500, 1),  # first inexplicable op
        _op(1, OP_GET, 1, 0, 5, 600, 700, 2),
    )
    assert first_bad_prefix(ops, KVSpec()) == 3
    assert first_bad_prefix(ops[:2], KVSpec()) == -1


def test_first_bad_prefix_is_partition_aware():
    """A linearizable multi-key history must never be rejected by
    cross-key state mixing, and a bad op's prefix length is its global
    index + 1 even with other keys' ops interleaved before it."""
    ok_ops = (
        _op(0, OP_PUT, 1, 5, 5, 0, 100, 0),
        _op(0, OP_PUT, 2, 7, 7, 150, 250, 1),
        _op(1, OP_GET, 1, 0, 5, 300, 400, 0),
    )
    assert first_bad_prefix(ok_ops, KVSpec()) == -1
    mixed = ok_ops + (_op(1, OP_GET, 2, 0, 9, 500, 600, 1),)  # phantom k2
    assert first_bad_prefix(mixed, KVSpec()) == 4


def test_log_spec_rejects_overread_and_broken_contiguity():
    """LogSpec: a fetch serving records beyond every linearizable append
    count fails the search; an offset gap fails the structural pass."""
    overread = _hist(
        _op(0, OP_PRODUCE, 0, 0, 0, 0, 50, 0),
        _op(4, OP_FETCH, 0, 0, 3, 100, 200, 0),  # 3 records, 1 produce
    )
    r = check_history(overread, LogSpec())
    assert not r.ok and r.bad_op.op == OP_FETCH
    gap = _hist(
        _op(0, OP_PRODUCE, 0, 0, 0, 0, 50, 0),
        _op(0, OP_PRODUCE, 0, 1, 1, 60, 110, 1),
        _op(4, OP_FETCH, 0, 0, 1, 100, 200, 0),
        _op(4, OP_FETCH, 0, 2, 1, 300, 400, 1),  # skipped offset 1
    )
    r2 = check_history(gap, LogSpec())
    assert not r2.ok and "contiguity" in r2.reason


# -- the engine recording plane ----------------------------------------------


def test_history_overflow_latches_and_prefix_is_untouched():
    """Satellite contract: overfilling a tiny buffer latches the sticky
    per-seed flag (surfaced in the chunk summary like queue overflow),
    never wraps — the recorded prefix is row-for-row the big buffer's."""
    tiny_cfg = ETCD_CLEAN._replace(hist_slots=8)
    ecfg = _ecfg(ETCD_CLEAN)
    big = ecore.run_sweep(etcd.workload(ETCD_CLEAN), ecfg, SEEDS)
    tiny = ecore.run_sweep(etcd.workload(tiny_cfg), _ecfg(tiny_cfg), SEEDS)
    assert (np.asarray(big.hist_len) > 8).all(), "fixture must overfill"
    assert np.asarray(tiny.hist_overflow).all()
    assert not np.asarray(big.hist_overflow).any()
    assert (np.asarray(tiny.hist_len) == 8).all()
    # untouched prefix: the first 8 rows match the unconstrained run
    np.testing.assert_array_equal(
        np.asarray(tiny.hist_rec), np.asarray(big.hist_rec)[:, :8, :]
    )
    np.testing.assert_array_equal(
        np.asarray(tiny.hist_t), np.asarray(big.hist_t)[:, :8]
    )
    # the flag reaches the chunk summary (models/_common engine fields)
    assert etcd.sweep_summary(tiny)["hist_overflow_seeds"] == len(SEEDS)
    assert etcd.sweep_summary(big)["hist_overflow_seeds"] == 0


def test_recording_does_not_change_schedules():
    """The history plane is pure instrumentation: the same config with
    recording off dispatches the identical event schedule."""
    off_cfg = ETCD_CLEAN._replace(hist_slots=0)
    _, t_on = ecore.run_traced(etcd.workload(ETCD_CLEAN), _ecfg(ETCD_CLEAN), 3)
    _, t_off = ecore.run_traced(etcd.workload(off_cfg), _ecfg(off_cfg), 3)
    for k in ("time_ns", "kind", "pay", "fired"):
        np.testing.assert_array_equal(np.asarray(t_on[k]), np.asarray(t_off[k]))


def test_sweep_and_traced_histories_are_byte_identical():
    """The cross-path determinism contract: one (spec, seed) decodes to
    identical canonical bytes from a sweep lane and from the bit-exact
    CPU traced replay."""
    wl, ecfg = etcd.workload(ETCD_CLEAN), _ecfg(ETCD_CLEAN)
    final = ecore.run_sweep(wl, ecfg, SEEDS)
    for lane in (0, 7, 11):
        traced_final, _ = ecore.run_traced(wl, ecfg, int(SEEDS[lane]))
        assert history_bytes(decode_seed(traced_final)) == history_bytes(
            decode_seed(final, lane)
        )


# -- validation: fires on the seeded bug, clean on the default ---------------


def test_checker_fires_on_etcd_stale_read_bug():
    """The oracle's reason to exist: a defect no online latch can see
    (revision/lease bookkeeping intact) is caught from the history
    alone, over a pinned seed range."""
    final = ecore.run_sweep(etcd.workload(ETCD_BUG), _ecfg(ETCD_BUG), SEEDS)
    vio = violating_seeds(final, KVSpec())
    assert vio.size >= 1, "checker never fired on bug_stale_read"
    assert not np.asarray(final.wstate.violation).any(), (
        "online latches saw the bug — it no longer validates the oracle"
    )
    # replay.py surfaces the same set
    np.testing.assert_array_equal(
        replay.history_violation_seeds(final, KVSpec()), vio
    )


def test_default_configs_check_linearizable():
    """No false positives: etcd (KV register) and kafka (ordered log)
    histories over pinned seed ranges all pass their specs."""
    efinal = ecore.run_sweep(etcd.workload(ETCD_CLEAN), _ecfg(ETCD_CLEAN), SEEDS)
    assert violating_seeds(efinal, KVSpec()).size == 0
    kcfg = kafka.KafkaConfig(hist_slots=512)
    kecfg = kafka.engine_config(kcfg, time_limit_ns=2_000_000_000, max_steps=20_000)
    kfinal = ecore.run_sweep(kafka.workload(kcfg), kecfg, SEEDS)
    assert not np.asarray(kfinal.hist_overflow).any()
    assert violating_seeds(kfinal, LogSpec()).size == 0
    # histories are non-trivial (ops actually completed)
    assert all(
        any(o.complete for o in h.ops) for h in decode_sweep(kfinal)
    )


# -- explore wiring: history triage flavor + checker-verified shrink ---------


def test_history_triage_and_shrink_close_the_loop(tmp_path):
    """End-to-end: the seeded-bug sweep yields a seed the checker
    rejects; triage fingerprints it under the history flavor; shrink
    emits a minimal FixedFaults triple every candidate of which was
    re-verified through the checker; the minimal triple reproduces."""
    from madsim_tpu.explore.targets import oracle_demo_faults

    target = explore.stale_etcd_target()
    spec = oracle_demo_faults()
    wl, ecfg = target.build(spec)
    final = ecore.run_sweep(wl, ecfg, jnp.arange(8, dtype=jnp.int64))
    vio = np.asarray(target.violating(final))
    assert vio.size >= 1
    seed = int(vio[0])

    f = explore.triage_seed(target, spec, seed, history=True)
    assert f is not None
    assert f.flavor == explore.HISTORY_FLAVOR
    assert f.fingerprint == "etcd-stale:history:get"
    # deterministic across reruns
    assert explore.triage_seed(target, spec, seed, history=True) == f

    sr = explore.shrink(target, spec, seed, max_tests=6, history=True)
    assert sr is not None and sr.fingerprint == f.fingerprint
    assert len(sr.schedule) <= sr.original_len
    again = explore.triage_seed(target, sr.spec, sr.seed, history=True)
    assert again is not None and again.fingerprint == f.fingerprint


def test_probe_triage_requires_spec_and_recording():
    target = explore.amnesia_raft_target()
    with pytest.raises(ValueError, match="hist_spec"):
        explore.triage_seed(target, FaultSpec(), 0, history=True)


# -- viol_kind flavor parity (etcd + kafka, like raft) -----------------------


def test_etcd_viol_kind_flavors():
    """bug_rev_regress latches V_REV; the traced probe channel carries
    the flavor so triage fingerprints are no longer flavor-less."""
    cfg = etcd.EtcdConfig(bug_rev_regress=True)
    ecfg = etcd.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(etcd.workload(cfg), ecfg, jnp.arange(48, dtype=jnp.int64))
    vio = np.asarray(final.wstate.violation)
    vk = np.asarray(final.wstate.viol_kind)
    assert vio.any(), "rev-regress fixture found no violation"
    assert (vk[vio] != 0).all() and ((vk[vio] & etcd.V_REV) != 0).any()
    assert (vk[~vio] == 0).all()
    seed = int(np.asarray(final.seed)[vio][0])
    _, trace = ecore.run_traced(etcd.workload(cfg), ecfg, seed)
    probe = np.asarray(trace["probe"])
    fired = np.asarray(trace["fired"])
    hits = np.nonzero(fired & (probe != 0))[0]
    assert hits.size > 0 and probe[hits[0]] & etcd.V_REV


def test_kafka_viol_kind_flavors():
    cfg = kafka.KafkaConfig(bug_ack_on_append=True, crashes=2)
    ecfg = kafka.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(kafka.workload(cfg), ecfg, jnp.arange(48, dtype=jnp.int64))
    vio = np.asarray(final.wstate.violation)
    vk = np.asarray(final.wstate.viol_kind)
    assert vio.any(), "ack-loss fixture found no violation"
    assert (vk[vio] != 0).all() and ((vk[vio] & kafka.V_ACK_LOSS) != 0).any()
    assert (vk[~vio] == 0).all()


# -- the host-tier recorder shim ---------------------------------------------


def test_host_recorder_matches_device_format():
    """The client-shim yields the same History structure the device
    decoder produces, checkable by the same spec — including open ops
    and the canonical byte encoding."""
    t = [0]

    def clock():
        t[0] += 10
        return t[0]

    rec = HostRecorder(clock=clock)
    a = rec.invoke(client=0, op=OP_PUT, key=3, inp=42)
    rec.complete(client=0, opid=a, out=42)
    b = rec.invoke(client=1, op=OP_GET, key=3, inp=0)
    rec.complete(client=1, opid=b, out=42)
    rec.invoke(client=1, op=OP_GET, key=4, inp=0)  # never completes
    h = rec.history(seed=9)
    assert [o.complete for o in h.ops] == [True, True, False]
    assert check_history(h, KVSpec()).ok
    assert history_bytes(h) == history_bytes(rec.history(seed=9))
    # shim-usage bugs raise at the offending call, not from the decoder:
    # unknown id, and double-completion of an already-closed op
    with pytest.raises(ValueError):
        rec.complete(client=2, opid=0, out=1)
    with pytest.raises(ValueError):
        rec.complete(client=0, opid=a, out=42)
