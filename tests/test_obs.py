"""The fleet telemetry subsystem (madsim_tpu/obs, docs/observability.md).

Direct unit coverage for the substrate the drivers instrument against:
the metrics registry and its Prometheus rendering, the JSONL run
journal, the opt-in HTTP exposition endpoint, the ``Telemetry`` handle's
recorder surface, the obs-registry heartbeat, the host-tier
``RuntimeMetrics`` shim joined to the exposition path, and the Chrome-
trace JSON shape of both exporters (``tracing.Tracer`` for one seed's
polls, ``tracing.SpanTracer`` for fleet driver phases). The end-to-end
out-of-band property (report bytes identical with telemetry on/off)
lives in scripts/obs_smoke.py and the determinism gate; here each piece
is pinned in isolation.
"""

import io
import json
import urllib.request

import pytest

import madsim_tpu as ms
from madsim_tpu import obs, tracing
from madsim_tpu.obs import metrics as obsm


# -- metrics registry -------------------------------------------------------


def test_counter_labels_and_monotonicity():
    c = obsm.Counter("frames_total", "frames", labels=("api",))
    c.inc(api="Produce")
    c.inc(2, api="Produce")
    c.inc(api="Fetch")
    assert c.get(api="Produce") == 3
    assert c.get(api="Fetch") == 1
    assert c.get(api="Metadata") == 0
    assert c.series() == [(("Fetch",), 1), (("Produce",), 3)]
    with pytest.raises(ValueError):
        c.inc(-1, api="Produce")
    with pytest.raises(ValueError):
        c.inc(bogus_label="x")


def test_gauge_set_inc():
    g = obsm.Gauge("depth")
    g.set(7)
    assert g.get() == 7
    g.inc(-2)
    assert g.get() == 5  # gauges may go down; counters may not


def test_histogram_buckets_cumulative():
    h = obsm.Histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    count, total = h.get()
    assert count == 5
    assert total == pytest.approx(56.05)
    ((key, row),) = h.series()
    assert key == ()
    # per-bucket (non-cumulative) counts + the +Inf bucket + the sum
    assert row == [1.0, 2.0, 1.0, 1.0, pytest.approx(56.05)]
    with pytest.raises(ValueError):
        obsm.Histogram("bad", buckets=(1.0, 0.1))


def test_registry_idempotent_and_kind_checked():
    r = obsm.Registry()
    assert r.counter("a") is r.counter("a")
    with pytest.raises(ValueError):
        r.gauge("a")
    assert r.get("missing") is None
    r.counter("a").inc(4)
    assert r.get("a") == 4


def test_registry_callback_gauge_and_snapshot():
    r = obsm.Registry()
    r.counter("done_total", "finished").inc(3)
    r.callback_gauge("live_tasks", lambda: 11, help="census")
    r.callback_gauge(
        "by_node", lambda: {"n1": 2, "n2": 1}, help="per node", label="node"
    )
    r.callback_gauge("broken", lambda: 1 / 0)  # must not break collection
    snap = r.snapshot()
    assert snap["done_total"] == 3
    assert snap["live_tasks"] == 11
    assert snap["by_node"] == {"node=n1": 2, "node=n2": 1}
    assert "broken" not in snap
    with pytest.raises(ValueError):
        r.callback_gauge("done_total", lambda: 0)


def test_render_prometheus_text_shape():
    r = obsm.Registry()
    r.counter("frames_total", "frames served", labels=("api",)).inc(
        5, api="Produce"
    )
    r.gauge("occupancy", "pool occupancy").set(0.75)
    r.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.5)
    text = obs.render_prometheus(r)
    assert "# HELP frames_total frames served" in text
    assert "# TYPE frames_total counter" in text
    assert 'frames_total{api="Produce"} 5' in text
    assert "occupancy 0.75" in text
    # histogram buckets render CUMULATIVE with the +Inf cap
    assert 'lat_seconds_bucket{le="0.1"} 0' in text
    assert 'lat_seconds_bucket{le="1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_sum 0.5" in text
    assert "lat_seconds_count 1" in text


# -- run journal ------------------------------------------------------------


def test_journal_roundtrip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    j = obs.Journal(path, run_id="cafe" * 4)
    j.write("chunk", lo=0, k=32)
    j.write("flush", lo=0, wall_s=0.25)
    j.close()
    j.write("late", x=1)  # post-close writes are dropped, not crashes
    recs = obs.read_journal(path)
    assert [r["kind"] for r in recs] == ["run_start", "chunk", "flush",
                                        "run_end"]
    assert all(r["run"] == "cafe" * 4 for r in recs)
    assert all("ts" in r for r in recs)
    assert recs[1]["lo"] == 0 and recs[1]["k"] == 32
    # every line is standalone JSON (append-only, crash-durable)
    with open(path) as f:
        for line in f:
            json.loads(line)


def test_journal_torn_final_line_every_offset(tmp_path):
    # the crash-recovery contract: a writer killed mid-append leaves a
    # torn partial FINAL line; read_journal must return the valid prefix
    # with .truncated set — at EVERY byte offset of the last record
    path = str(tmp_path / "run.jsonl")
    j = obs.Journal(path, run_id="dead" * 4)
    j.write("chunk", lo=0, k=32)
    j.write("flush", lo=0, wall_s=0.25, note="padding so the torn line "
            "has structure worth truncating through")
    j.close()
    data = open(path, "rb").read()
    last_start = data.rstrip(b"\n").rfind(b"\n") + 1
    last_len = len(data) - last_start  # includes the trailing newline
    whole = obs.read_journal(path)
    assert not whole.truncated and len(whole) == 4
    for off in range(last_len + 1):
        with open(path, "wb") as f:
            f.write(data[: last_start + off])
        recs = obs.read_journal(path)
        if off in (0, last_len - 1, last_len):
            # clean cuts: the record absent, or complete (a cut that
            # drops only the trailing newline still parses whole)
            assert not recs.truncated
            assert len(recs) == (3 if off == 0 else 4)
        else:
            assert recs.truncated, f"offset {off} not flagged"
            assert recs == whole[:3]
    # a malformed line with more data AFTER it is corruption, not a torn
    # tail — that still raises
    with open(path, "wb") as f:
        f.write(data[: last_start + 5] + b"\n" + data[last_start:])
    with pytest.raises(json.JSONDecodeError):
        obs.read_journal(path)


def test_new_run_id_unique_hex():
    ids = {obs.new_run_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)


# -- exposition endpoint ----------------------------------------------------


def test_http_metrics_endpoint():
    r = obsm.Registry()
    r.counter("hits_total").inc(2)
    server = obs.start_http_server(r, port=0)
    try:
        body = urllib.request.urlopen(server.url, timeout=5).read().decode()
        assert "hits_total 2" in body
        r.counter("hits_total").inc()
        body = urllib.request.urlopen(server.url, timeout=5).read().decode()
        assert "hits_total 3" in body  # live: renders at scrape time
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://{server.host}:{server.port}/nope", timeout=5
            )
    finally:
        server.close()


# -- the Telemetry handle ---------------------------------------------------


def test_telemetry_recorders(tmp_path):
    t = obs.Telemetry(journal=str(tmp_path / "j.jsonl"),
                      trace=str(tmp_path / "t.json"))
    t.count("chunks_total", help="chunks")
    t.count("chunks_total", 2)
    t.gauge("occupancy", 0.9)
    t.observe("chunk_seconds", 0.5)
    t.event("chunk", lo=0)
    with t.span("phase", track="device", lo=0):
        pass
    t.sample("occupancy", pool=0.9)
    t.event_mix({"event_mix": [3, 0, 7]})
    t.event_mix({})  # reports without the plane are a no-op
    assert t.registry.get("chunks_total") == 3
    assert t.registry.get("occupancy") == 0.9
    assert t.registry.get("engine_events_by_kind_total", kind="0") == 3
    assert t.registry.get("engine_events_by_kind_total", kind="2") == 7
    t.close()
    kinds = [r["kind"] for r in obs.read_journal(str(tmp_path / "j.jsonl"))]
    assert kinds == ["run_start", "chunk", "run_end"]
    trace = json.loads((tmp_path / "t.json").read_text())
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])


def test_telemetry_planes_off_are_noops():
    t = obs.Telemetry()  # metrics only: no journal, trace, or server
    t.event("chunk", lo=0)
    t.sample("occupancy", pool=1.0)
    with t.span("phase"):
        pass
    t.count("ok_total")
    assert t.journal is None and t.tracer is None and t.server is None
    t.close()


def test_heartbeat_reads_registry():
    r = obsm.Registry()
    out = io.StringIO()
    hb = obs.Heartbeat(r, total_seeds=1000, prefix="sweep", out=out)
    r.counter("sweep_seeds_done_total").inc(250)
    r.gauge("sweep_occupancy").set(0.875)
    line = hb.tick(force=True)
    assert "250/1000 seeds" in line
    assert "occ 0.875" in line
    assert "ETA" in line
    assert out.getvalue().strip() == line
    # min_interval throttling: a second immediate tick is suppressed
    hb2 = obs.Heartbeat(r, 1000, prefix="sweep", out=out,
                        min_interval_s=3600)
    assert hb2.tick(force=True) is not None
    assert hb2.tick() is None


# -- RuntimeMetrics shim joined to the exposition path ----------------------


def test_runtime_metrics_shim_exposed():
    rt = ms.Runtime(seed=9)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("svc").build()

        async def forever():
            await ms.sleep(1000.0)

        node.spawn(forever())
        await ms.sleep(0.1)
        # census mid-sim, while the task is live
        m = h.metrics()
        assert m.num_nodes() >= 1
        assert m.num_tasks() >= 1
        by_node = m.num_tasks_by_node()
        assert any("svc" in str(k) for k in by_node)
        r = obsm.Registry()
        obs.bind_runtime_metrics(r, m)
        text = obs.render_prometheus(r)
        assert "madsim_runtime_nodes" in text
        assert "madsim_runtime_tasks" in text
        assert 'madsim_runtime_tasks_by_node{node="' in text
        snap = r.snapshot()
        assert snap["madsim_runtime_tasks"] == m.num_tasks()

    rt.block_on(main())


# -- Chrome-trace JSON golden shape -----------------------------------------

# every event the exporters may emit must carry exactly these keys —
# the contract chrome://tracing and Perfetto parse against
_REQUIRED = {
    "X": {"name", "ph", "pid", "tid", "ts", "dur"},
    "M": {"name", "ph", "pid", "args"},
    "i": {"name", "ph", "pid", "tid", "ts", "s"},
    "C": {"name", "ph", "pid", "ts", "args"},
}


def _check_shape(events):
    assert events, "no trace events"
    for e in events:
        need = _REQUIRED[e["ph"]]
        missing = need - set(e)
        assert not missing, f"{e['ph']} event missing {missing}: {e}"
        if e["ph"] == "X":
            assert e["dur"] > 0 and e["ts"] >= 0


def test_tracer_golden_shape(tmp_path):
    rt = ms.Runtime(seed=41)
    tracer = tracing.Tracer().install(rt)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("golden").build()

        async def work():
            await ms.sleep(0.2)

        await node.spawn(work())

    rt.block_on(main())
    path = tmp_path / "trace.json"
    tracer.save(str(path))
    data = json.loads(path.read_text())
    assert set(data) == {"traceEvents"}
    _check_shape(data["traceEvents"])
    polls = [e for e in data["traceEvents"] if e.get("cat") == "poll"]
    assert polls and all(e["ph"] == "X" for e in polls)
    meta = [e for e in data["traceEvents"] if e["ph"] == "M"]
    assert any(e["args"]["name"] == "golden" for e in meta)


def test_span_tracer_golden_shape(tmp_path):
    st = tracing.SpanTracer()
    with st.span("device chunk lo=0", track="device", args={"k": 32}):
        with st.span("host flush lo=0", track="host"):
            pass
    st.complete("round 1", 10.0, 5.0, track="device")
    st.instant("snapshot", track="host")
    st.counter("stream occupancy", occupancy=0.875, queue=96)
    path = tmp_path / "spans.json"
    st.save(str(path))
    data = json.loads(path.read_text())
    events = data["traceEvents"]
    _check_shape(events)
    # named tracks announced via thread_name metadata (numbered in
    # first-RECORD order: the nested host span completes before the
    # device span that encloses it)
    tracks = {
        e["args"]["name"]: e["tid"]
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert set(tracks) == {"device", "host"}
    by_name = {e["name"]: e for e in events if e["ph"] == "X"}
    assert by_name["device chunk lo=0"]["tid"] == tracks["device"]
    assert by_name["host flush lo=0"]["tid"] == tracks["host"]
    assert by_name["device chunk lo=0"]["args"] == {"k": 32}
    assert by_name["round 1"]["ts"] == 10.0
    assert by_name["round 1"]["dur"] == 5.0
    # the nested host span's window sits inside the device span's
    dev, host = by_name["device chunk lo=0"], by_name["host flush lo=0"]
    assert dev["ts"] <= host["ts"]
    assert host["ts"] + host["dur"] <= dev["ts"] + dev["dur"] + 1e-6
    (c,) = [e for e in events if e["ph"] == "C"]
    assert c["args"] == {"occupancy": 0.875, "queue": 96.0}
