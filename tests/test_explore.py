"""Coverage-guided exploration: find -> triage -> shrink (madsim_tpu/explore).

Ground truth first: the flagship Raft safety detector must demonstrably
FIRE (the round-5 VERDICT's named gap) — a pinned amnesia sweep yields
violating seeds and a bit-exact CPU ``run_traced`` confirms each one.
On top of that fixture, the explore acceptance: a campaign starting from
a bland ``FaultSpec`` discovers a violating ``(spec, seed)``, triage
assigns it a stable fingerprint, and the shrinker emits a minimal
``FixedFaults`` schedule that still reproduces under bit-exact replay —
all deterministic per campaign seed (byte-identical JSONL reports).
"""

import json
import random

import jax.numpy as jnp
import numpy as np

from madsim_tpu import explore, replay
from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.faults import FaultSpec, FixedFaults
from madsim_tpu.models import raft
from madsim_tpu.models._common import coverage_bit_count, merge_summaries

CFG, ECFG = replay.amnesia_raft_config()

# the demo campaign: a bland one-crash spec the loop must escalate
BLAND = FaultSpec(
    crashes=1,
    crash_window_ns=2_000_000_000,
    restart_lo_ns=50_000_000,
    restart_hi_ns=300_000_000,
)
CCFG = explore.CampaignConfig(
    rounds=6, seeds_per_round=128, campaign_seed=1, stop_after_failures=1
)


# -- ground truth: the safety detector fires --------------------------------


def test_amnesia_detector_demonstrably_fires():
    """Tier-1 proof the flagship detector works: the pinned amnesia
    config over a pinned seed range yields >= 1 violating seed, and a
    bit-exact CPU trace confirms the violation with its flavor — the
    explore subsystem's ground-truth fixture."""
    final = ecore.run_sweep(
        raft.workload(CFG), ECFG, jnp.arange(160, dtype=jnp.int64)
    )
    vio = replay.violation_seeds(final)
    assert vio.size >= 1, "amnesia sweep found no violations"
    seed = int(vio[0])
    single, trace = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    assert bool(single.wstate.violation)
    assert int(single.wstate.viol_kind) & raft.V_ELECTION
    # the traced probe channel pinpoints the first violating event
    probe = np.asarray(trace["probe"])
    fired = np.asarray(trace["fired"])
    hits = np.nonzero(fired & (probe != 0))[0]
    assert hits.size > 0 and probe[hits[0]] & raft.V_ELECTION


# -- the coverage signal -----------------------------------------------------


def test_coverage_bitmap_chunking_invariant():
    """The chunk summary's coverage union is the same whether a sweep
    runs as one batch or as chunks merged through ``merge_summaries``
    (seeds are independent; coverage is a per-seed OR)."""
    seeds = jnp.arange(96, dtype=jnp.int64)
    whole = raft.sweep_summary(ecore.run_sweep(raft.workload(CFG), ECFG, seeds))
    totals = {}
    for lo in (0, 32, 64):
        final = ecore.run_sweep(raft.workload(CFG), ECFG, seeds[lo : lo + 32])
        merge_summaries(totals, raft.sweep_summary(final))
    assert totals["coverage_map"] == whole["coverage_map"]
    assert coverage_bit_count(whole["coverage_map"]) > 0
    assert len(whole["coverage_map"]) == (raft.cover_bits(CFG) + 31) // 32


def test_mutations_are_deterministic_and_bounded():
    a = explore.mutate_spec(BLAND, random.Random(42))
    b = explore.mutate_spec(BLAND, random.Random(42))
    assert a == b, "same rng state must yield the same candidate"
    for _ in range(200):
        s = explore.mutate_spec(BLAND, random.Random(_))
        for f in ("crashes", "partitions", "spikes", "losses", "pauses"):
            assert 0 <= getattr(s, f) <= 6
        assert s.restart_lo_ns < s.restart_hi_ns
        # every candidate round-trips through the JSONL encoding
        assert explore.spec_from_dict(explore.spec_to_dict(s)) == s
    fixed = FixedFaults(events=((5, "crash", 0), (9, "restart", 0)))
    assert explore.spec_from_dict(explore.spec_to_dict(fixed)) == fixed


# -- the acceptance loop: find -> triage -> shrink ---------------------------


def test_campaign_finds_triages_and_shrinks_the_amnesia_bug(tmp_path):
    """End-to-end on CPU: the coverage-guided campaign escalates a bland
    spec into a violating (spec, seed); triage fingerprints it; the
    shrinker's minimal FixedFaults schedule still reproduces the SAME
    fingerprint under bit-exact run_traced replay."""
    target = explore.amnesia_raft_target()
    report = tmp_path / "campaign.jsonl"
    result = explore.run_campaign(
        target, BLAND, CCFG, report_path=str(report)
    )
    # 1. find: the loop discovered a violating (spec, seed) and stopped
    assert result.failures, "campaign never found a violating seed"
    spec, seed = result.failures[0]
    assert spec != BLAND, "the bland base spec itself should stay quiet"
    # coverage guidance did the driving: the corpus grew beyond the base
    assert len(result.corpus) >= 2
    assert coverage_bit_count(result.coverage_map) > 0

    # 2. triage: every red seed lands in a bucket with a stable key
    buckets = explore.triage(target, spec, [s for _, s in result.failures])
    assert sum(len(v) for v in buckets.values()) == len(result.failures)
    fp = explore.triage_seed(target, spec, seed).fingerprint
    assert fp in buckets
    assert explore.triage_seed(target, spec, seed).fingerprint == fp  # stable

    # 3. shrink: minimal schedule, re-verified, still the same failure
    sr = explore.shrink(target, spec, seed, max_tests=32)
    assert sr is not None
    assert sr.fingerprint == fp
    assert len(sr.schedule) <= sr.original_len
    assert sr.schedule == tuple(sorted(sr.schedule))
    # the minimal triple reproduces standalone (fresh replay, literal
    # schedule — no draws left anywhere in the fault path)
    again = explore.triage_seed(target, sr.spec, sr.seed)
    assert again is not None and again.fingerprint == fp

    # the report is well-formed JSONL: header + one record per round
    lines = [json.loads(l) for l in report.read_text().splitlines()]
    assert lines[0]["target"] == target.name
    assert len(lines) == 1 + len(result.records)
    assert lines[-1]["violating_seeds"], "last round holds the discovery"


def test_campaign_report_is_byte_deterministic(tmp_path):
    """Two runs of one campaign seed produce byte-identical JSONL (the
    in-process half of scripts/check_determinism.sh's two-process gate)."""
    target = explore.amnesia_raft_target()
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    ccfg = CCFG._replace(rounds=3, stop_after_failures=0)
    ra = explore.run_campaign(target, BLAND, ccfg, report_path=str(a))
    rb = explore.run_campaign(target, BLAND, ccfg, report_path=str(b))
    assert a.read_bytes() == b.read_bytes()
    assert ra.records == rb.records


def test_campaign_resumes_through_checkpoints(tmp_path):
    """With ckpt_dir set, a rerun skips every completed chunk (the
    engine/checkpoint.py machinery) and reproduces the identical result."""
    target = explore.amnesia_raft_target()
    ccfg = CCFG._replace(rounds=2, stop_after_failures=0, seeds_per_round=64)
    ck = tmp_path / "ck"
    r1 = explore.run_campaign(target, BLAND, ccfg, ckpt_dir=str(ck))
    # the pipelined driver's chunk files (pchunk_*: their summaries
    # carry host-phase results, so they are not interchangeable with
    # run_sweep_chunked_resumable's chunk_* files)
    files = sorted(p.name for p in (ck / "round_0000").glob("pchunk_*.json"))
    assert files, "no per-chunk checkpoints written"
    r2 = explore.run_campaign(target, BLAND, ccfg, ckpt_dir=str(ck))
    assert r1.records == r2.records
    assert r1.coverage_map == r2.coverage_map
