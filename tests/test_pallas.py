"""Pallas pop-min kernel: bit-exact parity with the XLA path.

The kernel (engine/pallas_queue.py) exists as measured evidence that the
XLA path saturates the queue ops (docs/pallas_finding.md); parity is the
property that makes the A/B meaningful — and would let it substitute
without breaking replay. CI runs it in interpret mode (no TPU); the
compiled path is exercised by scripts/bench_pallas.py on hardware.
"""

from functools import partial

import jax
import jax.numpy as jnp

from madsim_tpu.engine import core, pallas_queue as pq
from madsim_tpu.models import raft


def _queue_batch(n_seeds, steps=12):
    cfg = raft.RaftConfig(num_nodes=5, crashes=1)
    ecfg = raft.engine_config(cfg)
    wl = raft.workload(cfg)
    state = jax.jit(partial(core.init_sweep, wl, ecfg))(
        jnp.arange(n_seeds, dtype=jnp.int64)
    )
    step = jax.jit(partial(core.step_batch, wl, ecfg))
    for _ in range(steps):
        state = step(state)
    return state.queue


def test_pallas_pop_min_matches_xla_bit_exactly():
    q = _queue_batch(256)
    tie = jax.random.bits(jax.random.key(3), (256,), dtype=jnp.uint32)
    sx, fx = pq.pop_min_xla(q, tie)
    sp, fp = pq.pop_min_pallas(q, tie, interpret=True)
    assert jnp.array_equal(sx, sp)
    assert jnp.array_equal(fx, fp)
    assert bool(fx.all())  # queues had content — the test is not vacuous


def test_pallas_pop_min_empty_queues_report_not_found():
    from madsim_tpu.engine import queue as equeue

    empty = jax.vmap(lambda _: equeue.make(58, 8))(jnp.arange(128))
    tie = jnp.zeros((128,), jnp.uint32)
    slot, found = pq.pop_min_pallas(empty, tie, interpret=True)
    assert not bool(found.any())
    sx, fx = pq.pop_min_xla(empty, tie)
    assert not bool(fx.any())
