"""Seed-sweep Builder + @sim_test decorator tests
(mirrors ref sim/runtime/builder.rs behavior)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.builder import Builder


def test_builder_runs_count_seeds():
    seeds = []

    async def test_body():
        seeds.append(ms.current_handle().seed)

    Builder(seed=100, count=5).run(test_body)
    assert seeds == [100, 101, 102, 103, 104]


def test_builder_env_parsing(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_SEED", "77")
    monkeypatch.setenv("MADSIM_TEST_NUM", "3")
    monkeypatch.setenv("MADSIM_TEST_JOBS", "2")
    b = Builder.from_env()
    assert b.seed == 77
    assert b.count == 3
    assert b.jobs == 2


def test_builder_prints_failing_seed(capsys):
    async def failing():
        if ms.current_handle().seed == 202:
            raise AssertionError("seed-specific failure")

    with pytest.raises(AssertionError):
        Builder(seed=200, count=5).run(failing)
    err = capsys.readouterr().err
    assert "MADSIM_TEST_SEED=202" in err


def test_builder_parallel_jobs():
    seeds = []
    import threading

    lock = threading.Lock()

    async def body():
        with lock:
            seeds.append(ms.current_handle().seed)

    Builder(seed=300, count=8, jobs=4).run(body)
    assert sorted(seeds) == list(range(300, 308))


def test_sim_test_decorator():
    ran = []

    @ms.sim_test(seed=42, count=2)
    async def my_test():
        ran.append(ms.current_handle().seed)

    my_test()
    assert ran == [42, 43]


def test_sim_test_check_determinism():
    @ms.sim_test(seed=1, check_determinism=True)
    async def my_test():
        import random

        await ms.sleep(random.uniform(0.01, 0.1))

    my_test()


def test_builder_time_limit():
    from madsim_tpu.task import TimeLimitError

    async def forever():
        await ms.sleep(1e6)

    with pytest.raises(TimeLimitError):
        Builder(seed=1, time_limit=10.0).run(forever)


def test_config_toml_roundtrip():
    from madsim_tpu.config import Config

    cfg = Config.from_toml(
        """
[net]
packet_loss_rate = 0.1
send_latency = [0.002, 0.02]
"""
    )
    assert cfg.net.packet_loss_rate == 0.1
    assert cfg.net.send_latency == (0.002, 0.02)
    assert cfg.hash() == Config.from_toml(
        "[net]\npacket_loss_rate = 0.1\nsend_latency = [0.002, 0.02]\n"
    ).hash()
    assert cfg.hash() != Config().hash()


def test_procs_sweep_matches_sequential():
    """The fork-based process sweep must produce the same per-seed results
    as the sequential sweep (total per-seed isolation, same schedules)."""
    from madsim_tpu.builder import Builder

    async def wl():
        import madsim_tpu as ms

        total = 0
        for _ in range(5):
            await ms.sleep(0.01)
            total += ms.rand.gen_range(0, 100)
        return total

    seq = Builder(seed=100, count=6).run(wl)
    par = Builder(seed=100, count=6, procs=3).run(wl)
    assert seq == par


def test_procs_sweep_failure_prints_repro_and_raises(capfd):
    from madsim_tpu.builder import Builder, SimSweepError

    async def boom():
        import madsim_tpu as ms

        await ms.sleep(0.01)
        if ms.rand.gen_range(0, 3) == 1:
            raise AssertionError("bad seed")

    with pytest.raises(SimSweepError) as e:
        Builder(seed=100, count=8, procs=2).run(boom)
    assert "AssertionError" in str(e.value)
    err = capfd.readouterr().err
    assert "MADSIM_TEST_SEED=" in err


def test_procs_sweep_large_result_volume_no_deadlock():
    """The parent drains the result queue while children run — a sweep
    whose queued results exceed the OS pipe capacity must not deadlock
    (regression: join-before-drain hung once ~64KB of results queued)."""
    from madsim_tpu.builder import Builder

    async def wl():
        import madsim_tpu as ms

        await ms.sleep(0.001)
        return "x" * 500  # ~500B/seed * 400 seeds >> pipe capacity

    out = Builder(seed=0, count=400, procs=2).run(wl)
    assert out == "x" * 500


def test_procs_sweep_device_tier_raises_named_error():
    """A workload touching JAX (or the engine) under procs=N must fail
    fast with ProcsDeviceTierError in the child — surfaced through the
    sweep failure path — instead of hanging in inherited JAX state."""
    import jax  # ensure jax is imported in the parent before the fork

    from madsim_tpu.builder import Builder, SimSweepError

    assert jax is not None

    async def device_wl():
        import jax.numpy as jnp  # resolves to the child's poisoned module

        return jnp.zeros(4)

    with pytest.raises(SimSweepError) as e:
        Builder(seed=0, count=2, procs=2).run(device_wl)
    assert "ProcsDeviceTierError" in str(e.value)

    async def engine_wl():
        from madsim_tpu.engine import core  # pre-fork module, real jax refs

        from madsim_tpu.models import raft

        cfg = raft.RaftConfig(num_nodes=3)
        core.run_sweep(raft.workload(cfg), raft.engine_config(cfg), [0, 1])

    with pytest.raises(SimSweepError) as e:
        Builder(seed=0, count=2, procs=2).run(engine_wl)
    assert "ProcsDeviceTierError" in str(e.value)


def test_procs_sweep_fresh_jax_import_also_blocked():
    """Even when the PARENT never imported jax, a child's fresh
    ``import jax`` must raise the named error (meta-path finder), not
    initialize the real backend N times concurrently."""
    import subprocess
    import sys

    script = (
        "import sys\n"
        "sys.path.insert(0, '/root/repo')\n"
        "from madsim_tpu.builder import Builder, SimSweepError\n"
        "assert 'jax' not in sys.modules\n"
        "async def wl():\n"
        "    import jax\n"
        "    return jax.numpy.zeros(2)\n"
        "try:\n"
        "    Builder(seed=0, count=2, procs=2).run(wl)\n"
        "    print('NO-ERROR')\n"
        "except SimSweepError as e:\n"
        "    print('named' if 'ProcsDeviceTierError' in str(e) else 'other')\n"
    )
    env = {
        k: v for k, v in dict(**__import__("os").environ).items()
        if not k.startswith(("JAX_", "XLA_"))
    }
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120, env=env,
    )
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip().endswith("named"), (r.stdout, r.stderr)


def test_procs_sweep_unpicklable_result_degrades_to_none():
    """A result that cannot cross the process boundary degrades to None
    for that seed (probed eagerly — Queue.put pickles lazily in a feeder
    thread, so a put-side try/except can't catch it)."""
    from madsim_tpu.builder import Builder

    async def wl():
        import madsim_tpu as ms

        await ms.sleep(0.001)
        if ms.rand is not None:  # the LAST seed returns the lambda
            pass
        return (lambda: 1)  # unpicklable

    out = Builder(seed=0, count=4, procs=2).run(wl)
    assert out is None
