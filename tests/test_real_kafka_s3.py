"""Real-mode Kafka and S3: the unchanged client APIs against the broker /
service state machines over real TCP sockets — completing the dual-mode
story for all four ecosystem shims (madsim-rdkafka/src/lib.rs:3-12,
madsim-aws-sdk-s3/src/lib.rs:3-10)."""

import pytest

from madsim_tpu import real
from madsim_tpu.real import kafka, s3


# -- kafka ------------------------------------------------------------------


async def _start_broker():
    broker = kafka.SimBroker()
    task = real.spawn(broker.serve(("127.0.0.1", 0)))
    while broker.bound_addr is None:
        if task.done():
            task.result()  # surface the bind failure instead of spinning
        await real.sleep(0.005)
    host, port = broker.bound_addr
    return broker, task, f"{host}:{port}"


def test_real_kafka_produce_fetch_roundtrip():
    async def main():
        _broker, task, addr = await _start_broker()
        config = kafka.ClientConfig().set("bootstrap.servers", addr)

        admin = await config.create(kafka.AdminClient)
        from madsim_tpu.kafka import NewTopic

        errs = await admin.create_topics([NewTopic("t", 2)])
        assert errs == [None]

        # FutureProducer: per-record send returns (partition, offset)
        producer = await config.create(kafka.FutureProducer)
        for i in range(6):
            p, off = await producer.send(
                kafka.FutureRecord.to("t").with_key(f"k{i}").with_payload(f"v{i}")
            )
            assert p in (0, 1)

        # BaseConsumer: assign from the beginning and read everything back
        consumer = await config.create(kafka.BaseConsumer)
        await consumer.subscribe(["t"])
        got = []
        for _ in range(6):
            msg = await consumer.poll(timeout_s=1.0)
            assert msg is not None
            got.append((msg.key, msg.payload))
        assert len(got) == 6
        assert {k for k, _ in got} == {f"k{i}".encode() for i in range(6)}

        # watermarks reflect the produced records
        low0, high0 = await consumer.fetch_watermarks("t", 0)
        low1, high1 = await consumer.fetch_watermarks("t", 1)
        assert low0 == low1 == 0
        assert high0 + high1 == 6

        # empty poll times out on the wall clock (fast)
        assert await consumer.poll(timeout_s=0.05) is None
        task.abort()

    real.Runtime().block_on(main())


def test_real_kafka_consumer_groups_over_real_sockets():
    """Consumer groups flow through the SAME SimBroker dispatcher the
    real-mode twin serves, so group membership, range assignment,
    rebalance, and committed-offset resume all work over real TCP."""
    async def main():
        _broker, task, addr = await _start_broker()
        from madsim_tpu.kafka import NewTopic

        config = kafka.ClientConfig().set("bootstrap.servers", addr)
        admin = await config.create(kafka.AdminClient)
        await admin.create_topics([NewTopic("gt", 2)])
        producer = await config.create(kafka.FutureProducer)
        for i in range(6):
            await producer.send(
                kafka.FutureRecord.to("gt").with_payload(f"m{i}")
            )

        def gcfg():
            return (kafka.ClientConfig()
                    .set("bootstrap.servers", addr)
                    .set("group.id", "realgrp")
                    .set("enable.auto.commit", "false"))

        a = await gcfg().create(kafka.BaseConsumer)
        b = await gcfg().create(kafka.BaseConsumer)
        await a.subscribe(["gt"])
        await b.subscribe(["gt"])
        got = []
        first = await a.poll(timeout_s=0.05)  # adopts the 2-member gen
        if first:
            got.append(first.payload.decode())
        assert len(a._assignments) == 1 and len(b._assignments) == 1
        # drain until complete, bounded by attempts rather than a tight
        # wall-clock budget (this box can stall polls under suite load)
        for _ in range(60):
            if len(got) == 6:
                break
            for c in (a, b):
                m = await c.poll(timeout_s=0.2)
                if m:
                    got.append(m.payload.decode())
        assert sorted(got) == [f"m{i}" for i in range(6)]

        # commit + leave; a successor resumes where the group left off
        await a.commit()
        await b.commit()
        await a.unsubscribe()
        await b.unsubscribe()
        c2 = await gcfg().create(kafka.BaseConsumer)
        await c2.subscribe(["gt"])
        assert await c2.poll(timeout_s=0.1) is None  # all committed
        task.abort()

    real.Runtime().block_on(main())


def test_real_kafka_broker_error_maps_to_kafka_error():
    async def main():
        _broker, task, addr = await _start_broker()
        config = kafka.ClientConfig().set("bootstrap.servers", addr)
        consumer = await config.create(kafka.BaseConsumer)
        with pytest.raises(kafka.KafkaError):
            await consumer.fetch_watermarks("missing-topic", 0)
        task.abort()

    real.Runtime().block_on(main())


# -- s3 ---------------------------------------------------------------------


async def _start_s3():
    server = s3.SimServer()
    task = real.spawn(server.serve(("127.0.0.1", 0)))
    while server.bound_addr is None:
        if task.done():
            task.result()  # surface the bind failure instead of spinning
        await real.sleep(0.005)
    host, port = server.bound_addr
    return server, task, f"{host}:{port}"


def test_real_s3_object_crud_and_multipart():
    async def main():
        _server, task, addr = await _start_s3()
        client = s3.Client.from_addr(addr)

        await client.create_bucket().bucket("b").send()
        await client.put_object().bucket("b").key("k").body(b"hello").send()
        out = await client.get_object().bucket("b").key("k").send()
        body = await out.body.collect()
        assert body.into_bytes() == b"hello"

        # list-v2
        out = await client.list_objects_v2().bucket("b").send()
        assert [o.key() for o in out.contents()] == ["k"]

        # multipart lifecycle
        mp = await client.create_multipart_upload().bucket("b").key("big").send()
        etags = []
        for i, part in enumerate((b"aa", b"bb", b"cc"), start=1):
            r = (
                await client.upload_part().bucket("b").upload_id(mp.upload_id())
                .part_number(i).body(part).send()
            )
            etags.append((i, r.e_tag()))
        completed = s3.CompletedMultipartUpload.builder()
        for i, etag in etags:
            completed = completed.parts(
                s3.CompletedPart.builder().part_number(i).e_tag(etag).build()
            )
        await (
            client.complete_multipart_upload().bucket("b").key("big")
            .upload_id(mp.upload_id()).multipart_upload(completed.build()).send()
        )
        out = await client.get_object().bucket("b").key("big").send()
        assert (await out.body.collect()).into_bytes() == b"aabbcc"

        # error mapping: missing key -> S3Error with a code
        with pytest.raises(s3.S3Error) as e:
            await client.get_object().bucket("b").key("nope").send()
        assert e.value.code == "NoSuchKey"
        task.abort()

    real.Runtime().block_on(main())
