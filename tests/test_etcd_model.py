"""etcd device workload: healthy sweeps are quiet, partitions really expire
leases, both injected bugs are caught, and traced CPU replay matches.

BASELINE.md config #2: 3-node KV + lease with net-partition injection.
"""

import jax
import jax.numpy as jnp
import numpy as np

from madsim_tpu.engine import core as ecore
from madsim_tpu.models import etcd

CFG = etcd.EtcdConfig()
ECFG = etcd.engine_config(CFG, time_limit_ns=5_000_000_000, max_steps=40_000)


def test_healthy_sweep_quiet_and_progresses():
    final = ecore.run_sweep(etcd.workload(CFG), ECFG, jnp.arange(256, dtype=jnp.int64))
    s = etcd.sweep_summary(final)
    assert s["violations"] == 0, s
    assert s["puts"] > 0 and s["gets"] > 0 and s["keepalives"] > 0
    assert s["partitions"] > 0  # the fault plan fired
    # partitions block keepalives long enough to expire leases somewhere
    # in the batch (part_hi 2s > ttl 1s)
    assert s["expiries"] > 0 and s["keys_expired"] > 0
    assert s["overflow_seeds"] == 0
    assert s["queue_high_water"] <= ECFG.queue_capacity
    # sent counts attempts, delivered counts link-test passes
    assert s["msgs_sent"] >= s["msgs_delivered"] > 0


def test_skip_expiry_bug_is_caught():
    """bug_skip_expiry leaves expired-lease keys in the store; the GET-side
    checker must catch a stale read at some seed, and the seed replays."""
    cfg = CFG._replace(bug_skip_expiry=True)
    final = ecore.run_sweep(
        etcd.workload(cfg), etcd.engine_config(cfg, time_limit_ns=5_000_000_000,
                                               max_steps=40_000),
        jnp.arange(512, dtype=jnp.int64),
    )
    s = etcd.sweep_summary(final)
    assert s["expiry_seeds"] > 0, f"checker failed to catch the bug: {s}"
    bad = np.asarray(final.seed)[np.asarray(final.wstate.vio_expiry)]
    seed = int(bad[0])
    with jax.default_device(jax.devices("cpu")[0]):
        replayed, _ = ecore.run_traced(
            etcd.workload(cfg),
            etcd.engine_config(cfg, time_limit_ns=5_000_000_000, max_steps=40_000),
            seed,
        )
    assert bool(replayed.wstate.vio_expiry)


def test_rev_regress_bug_is_caught():
    """bug_rev_regress decrements the revision at expiry; the client-side
    monotonicity checker must catch it."""
    cfg = CFG._replace(bug_rev_regress=True)
    final = ecore.run_sweep(
        etcd.workload(cfg), etcd.engine_config(cfg, time_limit_ns=5_000_000_000,
                                               max_steps=40_000),
        jnp.arange(512, dtype=jnp.int64),
    )
    s = etcd.sweep_summary(final)
    assert s["rev_regress_seeds"] > 0, f"checker failed to catch the bug: {s}"


def test_lease_state_is_consistent_at_end():
    final = ecore.run_sweep(etcd.workload(CFG), ECFG, jnp.arange(128, dtype=jnp.int64))
    w = final.wstate
    present = np.asarray(w.kv_present)  # [S, K]
    kv_lease = np.asarray(w.kv_lease)  # [S, K]
    lease_on = np.asarray(w.lease_on)  # [S, NC]
    # every present key with an attached lease points at a live lease
    # (expiry deletes attached keys; rejected PUTs never attach dead ones)
    attached = present & (kv_lease >= 0)
    s_idx, k_idx = np.nonzero(attached)
    assert lease_on[s_idx, kv_lease[s_idx, k_idx]].all()
    # revision accounting: the revision only grows
    assert (np.asarray(w.rev) >= 0).all()
    assert (np.asarray(w.seen_rev) <= np.asarray(w.rev)[:, None]).all()
    # mod-revision accounting: every present key was written at a real
    # revision no later than the current one
    mod_rev = np.asarray(w.kv_mod_rev)
    rev = np.asarray(w.rev)
    p_s, p_k = np.nonzero(present)
    assert (mod_rev[p_s, p_k] >= 1).all()
    assert (mod_rev[p_s, p_k] <= rev[p_s]).all()
    # partition refcounts all returned to zero (every window healed)
    assert (np.asarray(w.fstate.part_in_cnt) == 0).all()
    assert (np.asarray(w.fstate.part_out_cnt) == 0).all()


def test_traced_replay_matches_sweep():
    wl = etcd.workload(CFG)
    seeds = jnp.arange(5, dtype=jnp.int64)
    final = ecore.run_sweep(wl, ECFG, seeds)
    for i in range(5):
        single, _ = ecore.run_traced(wl, ECFG, int(seeds[i]))
        assert int(single.ctr) == int(final.ctr[i])
        assert int(single.now_ns) == int(final.now_ns[i])
        assert int(single.wstate.rev) == int(final.wstate.rev[i])
        assert int(single.wstate.expiries) == int(final.wstate.expiries[i])
        assert bool(single.wstate.violation) == bool(final.wstate.violation[i])
