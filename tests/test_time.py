"""Virtual time semantics (mirrors ref sim/time/mod.rs:232-280 tests)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.time import MissedTickBehavior


def test_sleep_advances_virtual_clock():
    rt = ms.Runtime(seed=1)

    async def main():
        t0 = ms.time.now_instant()
        await ms.sleep(1.0)
        dt = ms.time.now_instant() - t0
        assert 1.0 <= dt < 1.001  # epsilon + poll jitter only

    rt.block_on(main())


def test_sim_time_compression_is_instant():
    # 1000 simulated seconds must run instantly in wall time
    import time as walltime

    rt = ms.Runtime(seed=2)

    async def main():
        await ms.sleep(1000.0)

    start = walltime.monotonic()
    rt.block_on(main())
    assert walltime.monotonic() - start < 2.0


def test_min_sleep_is_1ms():
    rt = ms.Runtime(seed=3)

    async def main():
        t0 = ms.time.now_instant()
        await ms.sleep(0.0)
        assert ms.time.now_instant() - t0 >= 0.001

    rt.block_on(main())


def test_sleep_until_and_ordering():
    rt = ms.Runtime(seed=4)
    order = []

    async def waiter(name, dur):
        await ms.sleep(dur)
        order.append(name)

    async def main():
        hs = [
            ms.spawn(waiter("c", 3.0)),
            ms.spawn(waiter("a", 1.0)),
            ms.spawn(waiter("b", 2.0)),
        ]
        for h in hs:
            await h

    rt.block_on(main())
    assert order == ["a", "b", "c"]


def test_timeout_elapsed_and_ok():
    rt = ms.Runtime(seed=5)

    async def main():
        with pytest.raises(ms.TimeoutError):
            await ms.timeout(1.0, ms.sleep(10.0))
        result = await ms.timeout(10.0, value_after(1.0))
        assert result == 42

    async def value_after(d):
        await ms.sleep(d)
        return 42

    rt.block_on(main())


def test_interval_burst_and_delay():
    rt = ms.Runtime(seed=6)

    async def main():
        iv = ms.interval(1.0)
        t0 = ms.time.now_instant()
        await iv.tick()  # immediate first tick
        assert ms.time.now_instant() - t0 < 0.01
        await iv.tick()
        assert 1.0 <= ms.time.now_instant() - t0 < 1.01

        iv2 = ms.interval(1.0)
        iv2.missed_tick_behavior = MissedTickBehavior.SKIP
        await iv2.tick()
        await ms.sleep(2.5)  # miss two ticks
        await iv2.tick()  # skip should land on the next multiple

    rt.block_on(main())


def test_system_time_randomized_around_2022():
    seen = set()
    for seed in range(3):
        rt = ms.Runtime(seed=seed)

        async def main():
            return ms.time.now()

        wall = rt.block_on(main())
        assert 1_640_000_000 < wall < 1_680_000_000  # within a year of 2022
        seen.add(int(wall))
    assert len(seen) > 1  # base time differs by seed


def test_instant_same_seed_deterministic():
    def run(seed):
        rt = ms.Runtime(seed=seed)

        async def main():
            await ms.sleep(1.5)
            return (ms.time.now_instant().ns, ms.time.now())

        return rt.block_on(main())

    assert run(7) == run(7)
    assert run(7) != run(8)


def test_manual_advance_fires_timers():
    rt = ms.Runtime(seed=9)

    async def main():
        h = ms.spawn(sleeper())
        ms.time.advance(10.0)
        assert await h == "woke"

    async def sleeper():
        await ms.sleep(5.0)
        return "woke"

    rt.block_on(main())


def test_timeout_tie_inner_wins():
    """tokio's Timeout polls the inner future BEFORE the deadline, so a
    result landing exactly on the deadline instant is returned, not
    timed out — both Sleep timers here are created at the same virtual
    instant with the same duration."""
    rt = ms.Runtime(seed=61)

    async def inner():
        await ms.sleep(1.0)
        return "made it"

    async def main():
        assert await ms.timeout(1.0, inner()) == "made it"

    rt.block_on(main())


def test_timeout_expiry_closes_coroutine_deterministically():
    """On expiry the timed coroutine is dropped: its finally blocks run
    before TimeoutError reaches the awaiter (RAII analogue), not at some
    later GC point."""
    rt = ms.Runtime(seed=62)
    cleaned = []

    async def inner():
        try:
            await ms.sleep(100.0)
        finally:
            cleaned.append(True)

    async def main():
        with pytest.raises(ms.TimeoutError):
            await ms.timeout(0.5, inner())
        assert cleaned == [True]

    rt.block_on(main())


def test_timeout_propagates_inner_exception_to_awaiter():
    """An exception raised by the timed coroutine propagates to the
    awaiter (inline polling, time/mod.rs:183-196) — it must not abort
    the simulation as a task panic."""
    rt = ms.Runtime(seed=63)

    async def inner():
        await ms.sleep(0.01)
        raise ValueError("boom")

    async def main():
        with pytest.raises(ValueError, match="boom"):
            await ms.timeout(5.0, inner())

    rt.block_on(main())
