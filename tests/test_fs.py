"""Filesystem sim tests (mirrors ref sim/fs.rs:259-296)."""

import pytest

import madsim_tpu as ms
from madsim_tpu import fs


def test_file_write_read():
    rt = ms.Runtime(seed=1)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("db").build()

        async def body():
            f = await fs.File.create("/data/log")
            await f.write_all(b"hello ")
            await f.write_all(b"world")
            await f.sync_all()
            assert await fs.read("/data/log") == b"hello world"
            await f.write_all_at(b"WORLD", 6)
            await f.sync_all()
            assert await fs.read("/data/log") == b"hello WORLD"
            meta = await fs.metadata("/data/log")
            assert meta.len() == 11

        await node.spawn(body())

    rt.block_on(main())


def test_file_not_found():
    rt = ms.Runtime(seed=2)

    async def main():
        node = ms.current_handle().create_node().build()

        async def body():
            with pytest.raises(FileNotFoundError):
                await fs.File.open("/missing")

        await node.spawn(body())

    rt.block_on(main())


def test_fs_is_per_node():
    rt = ms.Runtime(seed=3)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().build()
        n2 = h.create_node().build()

        async def writer():
            await fs.write("/shared", b"n1-data")

        async def reader():
            with pytest.raises(FileNotFoundError):
                await fs.read("/shared")

        await n1.spawn(writer())
        await n2.spawn(reader())

    rt.block_on(main())


def test_power_fail_drops_unsynced_writes():
    rt = ms.Runtime(seed=4)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("crashy").build()

        async def write_phase():
            f = await fs.File.create("/wal")
            await f.write_all(b"synced")
            await f.sync_all()
            await f.write_all(b"+unsynced")
            # no sync before crash

        await node.spawn(write_phase())
        h.restart(node)  # triggers FsSim.power_fail via reset_node

        async def read_phase():
            return await fs.read("/wal")

        await ms.sleep(0.1)
        assert await node.spawn(read_phase()) == b"synced"

    rt.block_on(main())


def test_set_len_and_read_at():
    rt = ms.Runtime(seed=5)

    async def main():
        node = ms.current_handle().create_node().build()

        async def body():
            f = await fs.File.create("/f")
            await f.write_all(b"0123456789")
            assert await f.read_at(4, 3) == b"3456"
            await f.set_len(5)
            assert await f.read_all() == b"01234"
            await f.set_len(8)
            assert await f.read_all() == b"01234\x00\x00\x00"

        await node.spawn(body())

    rt.block_on(main())


def test_remove_file():
    rt = ms.Runtime(seed=6)

    async def main():
        node = ms.current_handle().create_node().build()

        async def body():
            await fs.write("/tmp1", b"x")
            await fs.remove_file("/tmp1")
            with pytest.raises(FileNotFoundError):
                await fs.read("/tmp1")

        await node.spawn(body())

    rt.block_on(main())


def test_unsynced_create_vanishes_on_power_fail():
    rt = ms.Runtime(seed=7)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def create_unsynced():
            f = await fs.File.create("/ephemeral")
            await f.write_all(b"gone")
            # no sync

        await node.spawn(create_unsynced())
        h.restart(node)
        await ms.sleep(0.1)

        async def check():
            with pytest.raises(FileNotFoundError):
                await fs.read("/ephemeral")

        await node.spawn(check())

    rt.block_on(main())


def test_create_over_existing_preserves_synced_until_sync():
    rt = ms.Runtime(seed=8)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def phase1():
            await fs.write("/cfg", b"durable")
            f = await fs.File.create("/cfg")  # truncate, buffered
            await f.write_all(b"partial")
            # crash before sync

        await node.spawn(phase1())
        h.restart(node)
        await ms.sleep(0.1)

        async def phase2():
            return await fs.read("/cfg")

        assert await node.spawn(phase2()) == b"durable"

    rt.block_on(main())


def test_unsynced_remove_resurrected_on_power_fail():
    rt = ms.Runtime(seed=9)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def phase1():
            await fs.write("/keep", b"data")
            await fs.remove_file("/keep")  # buffered unlink
            with pytest.raises(FileNotFoundError):
                await fs.read("/keep")

        await node.spawn(phase1())
        h.restart(node)
        await ms.sleep(0.1)

        async def phase2():
            return await fs.read("/keep")

        assert await node.spawn(phase2()) == b"data"

    rt.block_on(main())


def test_durable_remove_survives_power_fail():
    rt = ms.Runtime(seed=10)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def phase1():
            await fs.write("/gone", b"data")
            await fs.remove_file("/gone", durable=True)

        await node.spawn(phase1())
        h.restart(node)
        await ms.sleep(0.1)

        async def phase2():
            with pytest.raises(FileNotFoundError):
                await fs.read("/gone")

        await node.spawn(phase2())

    rt.block_on(main())


# -- slow-disk windows + schedule-driven power fail (gray failures) ----------


def test_fsync_stall_defers_durability():
    """Inside a slow-disk window sync_all returns but nothing becomes
    durable: a power fail drops the 'synced' data; closing the window
    applies the pending sync."""
    rt = ms.Runtime(seed=11)

    async def main():
        h = ms.current_handle()
        fssim = h.simulator(fs.FsSim)
        node = h.create_node().name("graydisk").build()

        async def phase1():
            await fs.write("/wal", b"durable")

        await node.spawn(phase1())
        fssim.stall_fsync(node.id)

        async def phase2():
            f = await fs.File.open("/wal")
            await f.write_all(b"+lied")
            await f.sync_all()  # the disk lies: defers
            assert await f.read_all() == b"durable+lied"

        await node.spawn(phase2())
        fssim.power_fail(node.id)

        async def phase3():
            assert await fs.read("/wal") == b"durable"
            f = await fs.File.open("/wal")
            await f.write_all(b"+caught")
            await f.sync_all()  # still stalled: defers again

        await node.spawn(phase3())
        fssim.unstall_fsync(node.id)  # the disk catches up
        fssim.power_fail(node.id)

        async def phase4():
            assert await fs.read("/wal") == b"durable+caught"

        await node.spawn(phase4())

    rt.block_on(main())


def test_fault_schedule_drives_power_fail_machinery():
    """Satellite acceptance: a LITERAL fault schedule (FixedFaults wire
    format — identical on both tiers for any seed, tests/test_faults.py)
    drives fsync_stall -> power_fail -> restart -> fsync_ok through
    apply_schedule, and the node's storage shows exactly the power-fail
    semantics: unsynced writes dropped, never-synced files vanished,
    unsynced removals resurrected."""
    from madsim_tpu import faults as hfaults
    from madsim_tpu.engine import faults as efaults

    fixed = efaults.FixedFaults(
        events=(
            (200_000_000, "fsync_stall", 0),
            (500_000_000, "power_fail", 0),
            (700_000_000, "restart", 0),
            (900_000_000, "fsync_ok", 0),
        )
    )
    # the literal compiles seed-independently and identically on both
    # tiers; the device half of these semantics is the raft durability
    # plane (tests/test_faults.py::test_power_fail_drops_unsynced_raft_writes)
    assert hfaults.compile_host(fixed, 1, 3) == sorted(
        (t, a, v) for t, a, v in fixed.events
    )
    rt = ms.Runtime(seed=12)
    observed = {}

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("victim").build()

        async def workload():
            # before the stall: one durable file, one durably-removed
            await fs.write("/keep", b"base")
            await fs.write("/zombie", b"boo")
            await ms.sleep(0.3)  # now inside the stall window
            f = await fs.File.open("/keep")
            await f.write_all(b"+lost")
            await f.sync_all()  # deferred: will be dropped
            await fs.write("/fresh", b"never-durable")
            await fs.remove_file("/zombie")  # unsynced removal
            # the power fail at 0.5 s kills this task with the node

        node.spawn(workload())  # runs concurrently with the supervisor
        await hfaults.apply_schedule(
            [(t, a, v) for t, a, v in fixed.events], [node]
        )

        async def inspect():
            observed["keep"] = await fs.read("/keep")
            observed["zombie"] = await fs.read("/zombie")
            try:
                await fs.read("/fresh")
                observed["fresh_gone"] = False
            except FileNotFoundError:
                observed["fresh_gone"] = True

        await node.spawn(inspect())

    rt.block_on(main())
    assert observed["keep"] == b"base", "unsynced write dropped"
    assert observed["zombie"] == b"boo", "unsynced removal resurrected"
    assert observed["fresh_gone"], "never-synced file vanished"


def test_recreate_supersedes_deferred_durable_unlink():
    """A durable unlink deferred by a stall window must NOT outlive a
    re-creation of the path: create + sync after the deferred removal,
    and the window's close keeps the new file (regression: the stale
    remove_requested flag used to delete it at unstall)."""
    rt = ms.Runtime(seed=13)

    async def main():
        h = ms.current_handle()
        fssim = h.simulator(fs.FsSim)
        node = h.create_node().build()

        async def phase1():
            await fs.write("/x", b"old")

        await node.spawn(phase1())
        fssim.stall_fsync(node.id)

        async def phase2():
            await fs.remove_file("/x", durable=True)  # deferred unlink
            f = await fs.File.create("/x")  # re-creation supersedes it
            await f.write_all(b"new")
            await f.sync_all()  # deferred data sync

        await node.spawn(phase2())
        fssim.unstall_fsync(node.id)
        fssim.power_fail(node.id)

        async def phase3():
            assert await fs.read("/x") == b"new"

        await node.spawn(phase3())

    rt.block_on(main())
