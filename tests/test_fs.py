"""Filesystem sim tests (mirrors ref sim/fs.rs:259-296)."""

import pytest

import madsim_tpu as ms
from madsim_tpu import fs


def test_file_write_read():
    rt = ms.Runtime(seed=1)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("db").build()

        async def body():
            f = await fs.File.create("/data/log")
            await f.write_all(b"hello ")
            await f.write_all(b"world")
            await f.sync_all()
            assert await fs.read("/data/log") == b"hello world"
            await f.write_all_at(b"WORLD", 6)
            await f.sync_all()
            assert await fs.read("/data/log") == b"hello WORLD"
            meta = await fs.metadata("/data/log")
            assert meta.len() == 11

        await node.spawn(body())

    rt.block_on(main())


def test_file_not_found():
    rt = ms.Runtime(seed=2)

    async def main():
        node = ms.current_handle().create_node().build()

        async def body():
            with pytest.raises(FileNotFoundError):
                await fs.File.open("/missing")

        await node.spawn(body())

    rt.block_on(main())


def test_fs_is_per_node():
    rt = ms.Runtime(seed=3)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().build()
        n2 = h.create_node().build()

        async def writer():
            await fs.write("/shared", b"n1-data")

        async def reader():
            with pytest.raises(FileNotFoundError):
                await fs.read("/shared")

        await n1.spawn(writer())
        await n2.spawn(reader())

    rt.block_on(main())


def test_power_fail_drops_unsynced_writes():
    rt = ms.Runtime(seed=4)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("crashy").build()

        async def write_phase():
            f = await fs.File.create("/wal")
            await f.write_all(b"synced")
            await f.sync_all()
            await f.write_all(b"+unsynced")
            # no sync before crash

        await node.spawn(write_phase())
        h.restart(node)  # triggers FsSim.power_fail via reset_node

        async def read_phase():
            return await fs.read("/wal")

        await ms.sleep(0.1)
        assert await node.spawn(read_phase()) == b"synced"

    rt.block_on(main())


def test_set_len_and_read_at():
    rt = ms.Runtime(seed=5)

    async def main():
        node = ms.current_handle().create_node().build()

        async def body():
            f = await fs.File.create("/f")
            await f.write_all(b"0123456789")
            assert await f.read_at(4, 3) == b"3456"
            await f.set_len(5)
            assert await f.read_all() == b"01234"
            await f.set_len(8)
            assert await f.read_all() == b"01234\x00\x00\x00"

        await node.spawn(body())

    rt.block_on(main())


def test_remove_file():
    rt = ms.Runtime(seed=6)

    async def main():
        node = ms.current_handle().create_node().build()

        async def body():
            await fs.write("/tmp1", b"x")
            await fs.remove_file("/tmp1")
            with pytest.raises(FileNotFoundError):
                await fs.read("/tmp1")

        await node.spawn(body())

    rt.block_on(main())


def test_unsynced_create_vanishes_on_power_fail():
    rt = ms.Runtime(seed=7)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def create_unsynced():
            f = await fs.File.create("/ephemeral")
            await f.write_all(b"gone")
            # no sync

        await node.spawn(create_unsynced())
        h.restart(node)
        await ms.sleep(0.1)

        async def check():
            with pytest.raises(FileNotFoundError):
                await fs.read("/ephemeral")

        await node.spawn(check())

    rt.block_on(main())


def test_create_over_existing_preserves_synced_until_sync():
    rt = ms.Runtime(seed=8)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def phase1():
            await fs.write("/cfg", b"durable")
            f = await fs.File.create("/cfg")  # truncate, buffered
            await f.write_all(b"partial")
            # crash before sync

        await node.spawn(phase1())
        h.restart(node)
        await ms.sleep(0.1)

        async def phase2():
            return await fs.read("/cfg")

        assert await node.spawn(phase2()) == b"durable"

    rt.block_on(main())


def test_unsynced_remove_resurrected_on_power_fail():
    rt = ms.Runtime(seed=9)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def phase1():
            await fs.write("/keep", b"data")
            await fs.remove_file("/keep")  # buffered unlink
            with pytest.raises(FileNotFoundError):
                await fs.read("/keep")

        await node.spawn(phase1())
        h.restart(node)
        await ms.sleep(0.1)

        async def phase2():
            return await fs.read("/keep")

        assert await node.spawn(phase2()) == b"data"

    rt.block_on(main())


def test_durable_remove_survives_power_fail():
    rt = ms.Runtime(seed=10)

    async def main():
        h = ms.current_handle()
        node = h.create_node().build()

        async def phase1():
            await fs.write("/gone", b"data")
            await fs.remove_file("/gone", durable=True)

        await node.spawn(phase1())
        h.restart(node)
        await ms.sleep(0.1)

        async def phase2():
            with pytest.raises(FileNotFoundError):
                await fs.read("/gone")

        await node.spawn(phase2())

    rt.block_on(main())
