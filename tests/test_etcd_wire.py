"""etcd v3 gRPC wire tests: a STOCK gRPC client (plain multicallables —
exactly what etcd's generated stubs expand to, same wire bytes) driving
the framework's EtcdService over genuine gRPC (madsim_tpu/etcd/wire.py).
The analogue of madsim-etcd-client's std mode speaking real etcd gRPC."""

import pytest

grpcio = pytest.importorskip("grpc")

from grpc import aio as grpc_aio  # noqa: E402

from madsim_tpu import real  # noqa: E402
from madsim_tpu.etcd import wire  # noqa: E402


async def _start():
    server = wire.WireServer()
    task = real.spawn(server.serve(("127.0.0.1", 0)))
    while server.bound_addr is None:
        if task.done():
            task.result()
        await real.sleep(0.005)
    host, port = server.bound_addr
    return server, task, f"{host}:{port}"


def _mc(ch, m, service, method, req_cls, rsp_cls):
    return ch.unary_unary(
        f"/etcdserverpb.{service}/{method}",
        request_serializer=req_cls.SerializeToString,
        response_deserializer=rsp_cls.FromString,
    )


def _msgs():
    pkg = wire.wire_pkg()
    return {n.rsplit(".", 1)[-1]: c for n, c in pkg.messages.items()}


def test_wire_kv_put_range_delete():
    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            rng = _mc(ch, m, "KV", "Range", m["RangeRequest"], m["RangeResponse"])
            dele = _mc(ch, m, "KV", "DeleteRange",
                       m["DeleteRangeRequest"], m["DeleteRangeResponse"])

            r = await put(m["PutRequest"](key=b"foo", value=b"bar"))
            assert r.header.revision == 1

            # single key
            r = await rng(m["RangeRequest"](key=b"foo"))
            assert len(r.kvs) == 1 and r.kvs[0].value == b"bar"
            assert r.kvs[0].create_revision == 1 and r.kvs[0].version == 1

            # overwrite bumps version + mod_revision
            await put(m["PutRequest"](key=b"foo", value=b"baz"))
            r = await rng(m["RangeRequest"](key=b"foo"))
            assert r.kvs[0].version == 2 and r.kvs[0].mod_revision == 2

            # prefix range, range_end computed the way stock clients do
            for k in (b"k1", b"k2", b"k3", b"z"):
                await put(m["PutRequest"](key=k, value=b"v" + k))
            r = await rng(m["RangeRequest"](key=b"k", range_end=b"l"))
            assert [kv.key for kv in r.kvs] == [b"k1", b"k2", b"k3"]
            assert r.count == 3 and not r.more

            # limit + more flag
            r = await rng(m["RangeRequest"](key=b"k", range_end=b"l", limit=2))
            assert len(r.kvs) == 2 and r.more and r.count == 3

            # count_only
            r = await rng(m["RangeRequest"](key=b"k", range_end=b"l",
                                            count_only=True))
            assert not r.kvs and r.count == 3

            # from-key convention: range_end = "\0" means every key >= key
            r = await rng(m["RangeRequest"](key=b"k3", range_end=b"\x00"))
            assert [kv.key for kv in r.kvs] == [b"k3", b"z"]

            # delete with prev_kv
            r = await dele(m["DeleteRangeRequest"](key=b"k1", prev_kv=True))
            assert r.deleted == 1 and r.prev_kvs[0].value == b"vk1"
            r = await rng(m["RangeRequest"](key=b"k1"))
            assert not r.kvs
        task.abort()

    real.Runtime().block_on(main())


def test_wire_txn_and_compact():
    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            txn = _mc(ch, m, "KV", "Txn", m["TxnRequest"], m["TxnResponse"])
            compact = _mc(ch, m, "KV", "Compact",
                          m["CompactionRequest"], m["CompactionResponse"])
            rng = _mc(ch, m, "KV", "Range", m["RangeRequest"], m["RangeResponse"])

            await put(m["PutRequest"](key=b"cas", value=b"old"))

            def cmp_value(key, val):
                c = m["Compare"](key=key, value=val)
                c.result = m["Compare"].CompareResult.EQUAL
                c.target = m["Compare"].CompareTarget.VALUE
                return c

            # success branch: compare holds -> put new
            req = m["TxnRequest"](
                compare=[cmp_value(b"cas", b"old")],
                success=[m["RequestOp"](
                    request_put=m["PutRequest"](key=b"cas", value=b"new")
                )],
                failure=[m["RequestOp"](
                    request_range=m["RangeRequest"](key=b"cas")
                )],
            )
            r = await txn(req)
            assert r.succeeded
            assert r.responses[0].WhichOneof("response") == "response_put"
            got = await rng(m["RangeRequest"](key=b"cas"))
            assert got.kvs[0].value == b"new"

            # failure branch: stale compare -> the range op runs instead
            r = await txn(req)
            assert not r.succeeded
            assert r.responses[0].WhichOneof("response") == "response_range"
            assert r.responses[0].response_range.kvs[0].value == b"new"

            # compact at the current revision succeeds; future errors
            await compact(m["CompactionRequest"](revision=r.header.revision))
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await compact(m["CompactionRequest"](revision=10_000))
            assert e.value.code() == grpcio.StatusCode.OUT_OF_RANGE
        task.abort()

    real.Runtime().block_on(main())


def test_wire_range_sort_and_txn_range_semantics():
    """The etcd behaviors a stock client leans on: descending limited
    queries sort BEFORE limiting ('latest N'), from-key ranges work
    inside Txn branches with one revision per DeleteRange, and range
    compares (etcd >= 3.3) evaluate over the whole range."""
    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            rng = _mc(ch, m, "KV", "Range", m["RangeRequest"], m["RangeResponse"])
            txn = _mc(ch, m, "KV", "Txn", m["TxnRequest"], m["TxnResponse"])

            for k in (b"a", b"b", b"c"):
                await put(m["PutRequest"](key=k, value=b"v" + k))

            # descending + limit: the LATEST page, not the oldest
            r = await rng(m["RangeRequest"](
                key=b"a", range_end=b"d", limit=1,
                sort_order=m["RangeRequest"].SortOrder.DESCEND,
            ))
            assert [kv.key for kv in r.kvs] == [b"c"] and r.more

            # sort by MOD descending = most recently written first
            await put(m["PutRequest"](key=b"a", value=b"rewritten"))
            r = await rng(m["RangeRequest"](
                key=b"a", range_end=b"d", limit=1,
                sort_order=m["RangeRequest"].SortOrder.DESCEND,
                sort_target=m["RangeRequest"].SortTarget.MOD,
            ))
            assert [kv.key for kv in r.kvs] == [b"a"]

            # keys_only holds on the from-key convention too
            r = await rng(m["RangeRequest"](key=b"b", range_end=b"\x00",
                                            keys_only=True))
            assert [kv.key for kv in r.kvs] == [b"b", b"c"]
            assert all(kv.value == b"" for kv in r.kvs)

            # range compare: "no key in [x, y) exists" holds vacuously,
            # fails once one exists
            def no_key_in(key, range_end):
                c = m["Compare"](key=key, range_end=range_end, version=0)
                c.result = m["Compare"].CompareResult.EQUAL
                c.target = m["Compare"].CompareTarget.VERSION
                return c

            req = m["TxnRequest"](
                compare=[no_key_in(b"x", b"y")],
                success=[m["RequestOp"](
                    request_put=m["PutRequest"](key=b"x1", value=b"claimed")
                )],
            )
            r = await txn(req)
            assert r.succeeded  # empty range: vacuous
            await put(m["PutRequest"](key=b"x2", value=b"taken"))
            r = await txn(m["TxnRequest"](compare=[no_key_in(b"x", b"y")]))
            assert not r.succeeded  # x1/x2 exist now

            # txn ranges honor limit/more/sort exactly like the top level
            r = await txn(m["TxnRequest"](success=[m["RequestOp"](
                request_range=m["RangeRequest"](
                    key=b"a", range_end=b"d", limit=1,
                    sort_order=m["RangeRequest"].SortOrder.DESCEND,
                )
            )]))
            nested = r.responses[0].response_range
            assert [kv.key for kv in nested.kvs] == [b"c"] and nested.more

            # an empty RequestOp is rejected, not run as a vacuous txn
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await txn(m["TxnRequest"](success=[m["RequestOp"]()]))
            assert e.value.code() == grpcio.StatusCode.INVALID_ARGUMENT

            # historical reads fail loudly (no MVCC history kept)
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await rng(m["RangeRequest"](key=b"a", revision=1))
            assert e.value.code() == grpcio.StatusCode.UNIMPLEMENTED

            # count_only is never "truncated"
            r = await rng(m["RangeRequest"](key=b"a", range_end=b"d",
                                            count_only=True, limit=1))
            assert not r.kvs and not r.more and r.count == 3

            # atomicity: an invalid op ANYWHERE in the request rejects the
            # whole txn BEFORE any op applies (earlier put must not leak)
            before_rev = (await rng(m["RangeRequest"](key=b"a"))).header.revision
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await txn(m["TxnRequest"](success=[
                    m["RequestOp"](request_put=m["PutRequest"](
                        key=b"leak", value=b"x"
                    )),
                    m["RequestOp"](request_range=m["RangeRequest"](
                        key=b"a", revision=1
                    )),
                ]))
            assert e.value.code() == grpcio.StatusCode.UNIMPLEMENTED
            r = await rng(m["RangeRequest"](key=b"leak"))
            assert not r.kvs  # the put never applied
            assert (await rng(m["RangeRequest"](key=b"a"))).header.revision == before_rev

            # from-key delete INSIDE a txn: works and is ONE revision
            before = (await rng(m["RangeRequest"](key=b"a"))).header.revision
            r = await txn(m["TxnRequest"](success=[m["RequestOp"](
                request_delete_range=m["DeleteRangeRequest"](
                    key=b"b", range_end=b"\x00"
                )
            )]))
            assert r.succeeded
            deleted = r.responses[0].response_delete_range.deleted
            assert deleted >= 3  # b, c, x1, x2 minus whatever sorts below b
            after = (await rng(m["RangeRequest"](key=b"a"))).header.revision
            assert after == before + 1  # one revision for the whole range
        task.abort()

    real.Runtime().block_on(main())


def test_wire_keepalive_expired_lease_replies_ttl_minus_one():
    """Real etcd answers keepalive for a gone lease with TTL=-1 on a LIVE
    stream (a stream error would read as a retryable transport failure)."""
    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            ka = ch.stream_stream(
                "/etcdserverpb.Lease/LeaseKeepAlive",
                request_serializer=m["LeaseKeepAliveRequest"].SerializeToString,
                response_deserializer=m["LeaseKeepAliveResponse"].FromString,
            )
            grant = _mc(ch, m, "Lease", "LeaseGrant",
                        m["LeaseGrantRequest"], m["LeaseGrantResponse"])
            g = await grant(m["LeaseGrantRequest"](TTL=30))

            # unknown lease then a live one, on ONE stream: -1 then 30
            call = ka(iter([
                m["LeaseKeepAliveRequest"](ID=999_999),
                m["LeaseKeepAliveRequest"](ID=g.ID),
            ]))
            got = [r.TTL async for r in call]
            assert got == [-1, 30]
        task.abort()

    real.Runtime().block_on(main())


def test_wire_lease_lifecycle():
    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            grant = _mc(ch, m, "Lease", "LeaseGrant",
                        m["LeaseGrantRequest"], m["LeaseGrantResponse"])
            revoke = _mc(ch, m, "Lease", "LeaseRevoke",
                         m["LeaseRevokeRequest"], m["LeaseRevokeResponse"])
            ttl_q = _mc(ch, m, "Lease", "LeaseTimeToLive",
                        m["LeaseTimeToLiveRequest"], m["LeaseTimeToLiveResponse"])
            leases = _mc(ch, m, "Lease", "LeaseLeases",
                         m["LeaseLeasesRequest"], m["LeaseLeasesResponse"])
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            rng = _mc(ch, m, "KV", "Range", m["RangeRequest"], m["RangeResponse"])

            g = await grant(m["LeaseGrantRequest"](TTL=30))
            lease_id = g.ID
            assert lease_id > 0 and g.TTL == 30

            await put(m["PutRequest"](key=b"ephemeral", value=b"x",
                                      lease=lease_id))
            t = await ttl_q(m["LeaseTimeToLiveRequest"](ID=lease_id, keys=True))
            assert t.grantedTTL == 30 and list(t.keys) == [b"ephemeral"]

            ls = await leases(m["LeaseLeasesRequest"]())
            assert [s.ID for s in ls.leases] == [lease_id]

            # bidi keepalive refreshes the TTL
            ka = ch.stream_stream(
                "/etcdserverpb.Lease/LeaseKeepAlive",
                request_serializer=m["LeaseKeepAliveRequest"].SerializeToString,
                response_deserializer=m["LeaseKeepAliveResponse"].FromString,
            )
            call = ka(iter([m["LeaseKeepAliveRequest"](ID=lease_id)]))
            async for rsp in call:
                assert rsp.ID == lease_id and rsp.TTL == 30
                break

            # revoke deletes attached keys
            await revoke(m["LeaseRevokeRequest"](ID=lease_id))
            r = await rng(m["RangeRequest"](key=b"ephemeral"))
            assert not r.kvs
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await revoke(m["LeaseRevokeRequest"](ID=lease_id))
            assert e.value.code() == grpcio.StatusCode.NOT_FOUND
        task.abort()

    real.Runtime().block_on(main())


def test_wire_watch_stream():
    """The Watch bidi service over genuine wire: create a range watch,
    observe PUT/DELETE events (with prev_kv) while unrelated keys are
    filtered out, cancel it, and see historical watches refused by name."""
    import asyncio

    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            dele = _mc(ch, m, "KV", "DeleteRange",
                       m["DeleteRangeRequest"], m["DeleteRangeResponse"])
            watch = ch.stream_stream(
                "/etcdserverpb.Watch/Watch",
                request_serializer=m["WatchRequest"].SerializeToString,
                response_deserializer=m["WatchResponse"].FromString,
            )

            req_q: asyncio.Queue = asyncio.Queue()

            async def reqs():
                while True:
                    r = await req_q.get()
                    if r is None:
                        return
                    yield r

            call = watch(reqs())
            it = call.__aiter__()

            # create a [w, x) range watch with prev_kv
            await req_q.put(m["WatchRequest"](
                create_request=m["WatchCreateRequest"](
                    key=b"w", range_end=b"x", prev_kv=True
                )
            ))
            r = await it.__anext__()
            assert r.created and not r.canceled
            wid = r.watch_id

            # in-range put arrives; out-of-range key never does
            await put(m["PutRequest"](key=b"zzz", value=b"ignored"))
            await put(m["PutRequest"](key=b"w1", value=b"a"))
            r = await it.__anext__()
            ev = r.events[0]
            assert r.watch_id == wid
            assert ev.type == m["Event"].EventType.PUT
            assert ev.kv.key == b"w1" and ev.kv.value == b"a"

            # overwrite carries prev_kv; delete arrives as DELETE
            await put(m["PutRequest"](key=b"w1", value=b"b"))
            r = await it.__anext__()
            assert r.events[0].kv.value == b"b"
            assert r.events[0].prev_kv.value == b"a"
            await dele(m["DeleteRangeRequest"](key=b"w1"))
            r = await it.__anext__()
            assert r.events[0].type == m["Event"].EventType.DELETE
            assert r.events[0].kv.key == b"w1"

            # cancel: acknowledged, then no more events for that watch
            await req_q.put(m["WatchRequest"](
                cancel_request=m["WatchCancelRequest"](watch_id=wid)
            ))
            r = await it.__anext__()
            assert r.canceled and r.watch_id == wid

            # historical watch refused by name (no MVCC history)
            await req_q.put(m["WatchRequest"](
                create_request=m["WatchCreateRequest"](key=b"h",
                                                       start_revision=1)
            ))
            r = await it.__anext__()
            assert r.canceled and "historical" in r.cancel_reason

            # duplicate explicit watch_id rejected, original still live
            await req_q.put(m["WatchRequest"](
                create_request=m["WatchCreateRequest"](key=b"d",
                                                       watch_id=77)
            ))
            r = await it.__anext__()
            assert r.created and r.watch_id == 77
            await req_q.put(m["WatchRequest"](
                create_request=m["WatchCreateRequest"](key=b"d",
                                                       watch_id=77)
            ))
            r = await it.__anext__()
            assert r.canceled and "duplicate" in r.cancel_reason
            await put(m["PutRequest"](key=b"d", value=b"once"))
            r = await it.__anext__()
            assert r.watch_id == 77 and len(r.events) == 1  # delivered ONCE

            await req_q.put(None)  # close our request side
        task.abort()

    real.Runtime().block_on(main())


def test_wire_watch_future_start_revision():
    """The canonical read-then-watch pattern: Range gives revision R, the
    client watches from start_revision=R+1 (servable without history —
    only past revisions are refused), and events BELOW the start are
    suppressed so the stream begins exactly where the read ended."""
    import asyncio

    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            rng = _mc(ch, m, "KV", "Range", m["RangeRequest"], m["RangeResponse"])
            watch = ch.stream_stream(
                "/etcdserverpb.Watch/Watch",
                request_serializer=m["WatchRequest"].SerializeToString,
                response_deserializer=m["WatchResponse"].FromString,
            )
            await put(m["PutRequest"](key=b"seen", value=b"already"))
            rev = (await rng(m["RangeRequest"](key=b"seen"))).header.revision

            req_q: asyncio.Queue = asyncio.Queue()

            async def reqs():
                while True:
                    r = await req_q.get()
                    if r is None:
                        return
                    yield r

            it = watch(reqs()).__aiter__()
            # watch from rev+3: the next TWO writes are below the start
            # and must be suppressed; the third is the first delivered
            await req_q.put(m["WatchRequest"](
                create_request=m["WatchCreateRequest"](
                    key=b"s", range_end=b"t", start_revision=rev + 3
                )
            ))
            r = await it.__anext__()
            assert r.created and not r.canceled
            await put(m["PutRequest"](key=b"s1", value=b"below1"))  # rev+1
            await put(m["PutRequest"](key=b"s2", value=b"below2"))  # rev+2
            await put(m["PutRequest"](key=b"s3", value=b"at-start"))  # rev+3
            ev = (await it.__anext__()).events[0]
            assert ev.kv.key == b"s3" and ev.kv.mod_revision == rev + 3

            # a PAST start_revision is still refused by name
            await req_q.put(m["WatchRequest"](
                create_request=m["WatchCreateRequest"](key=b"s",
                                                       start_revision=1)
            ))
            r = await it.__anext__()
            assert r.canceled and "historical" in r.cancel_reason
            await req_q.put(None)
        task.abort()

    real.Runtime().block_on(main())


def test_wire_maintenance_surface():
    """The Maintenance RPCs health tooling calls: Status (version/dbSize/
    revision), Alarm (always clear), Defragment (no-op ack), Hash, and a
    Snapshot stream whose reassembled blob restores the full state."""
    m = _msgs()

    async def main():
        server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            status = _mc(ch, m, "Maintenance", "Status",
                         m["StatusRequest"], m["StatusResponse"])
            alarm = _mc(ch, m, "Maintenance", "Alarm",
                        m["AlarmRequest"], m["AlarmResponse"])
            defrag = _mc(ch, m, "Maintenance", "Defragment",
                         m["DefragmentRequest"], m["DefragmentResponse"])
            hash_mc = _mc(ch, m, "Maintenance", "Hash",
                          m["HashRequest"], m["HashResponse"])

            await put(m["PutRequest"](key=b"snap", value=b"state"))
            s = await status(m["StatusRequest"]())
            assert s.version and s.dbSize > 0
            assert s.header.revision == 1

            a = await alarm(m["AlarmRequest"]())
            assert list(a.alarms) == []
            assert (await defrag(m["DefragmentRequest"]())).header.revision == 1
            # the hash is a function of KV state only: stable across
            # wall-clock time even with a live (decaying) lease...
            grant = _mc(ch, m, "Lease", "LeaseGrant",
                        m["LeaseGrantRequest"], m["LeaseGrantResponse"])
            await grant(m["LeaseGrantRequest"](TTL=60))
            h1 = (await hash_mc(m["HashRequest"]())).hash
            await real.sleep(1.2)  # the tick loop decays the lease
            assert (await hash_mc(m["HashRequest"]())).hash == h1
            # ...and changes when the KV store does
            await put(m["PutRequest"](key=b"snap2", value=b"more"))
            h2 = (await hash_mc(m["HashRequest"]())).hash
            assert h1 != h2

            # snapshot stream reassembles into a loadable dump
            snap = ch.unary_stream(
                "/etcdserverpb.Maintenance/Snapshot",
                request_serializer=m["SnapshotRequest"].SerializeToString,
                response_deserializer=m["SnapshotResponse"].FromString,
            )
            blob = b""
            async for part in snap(m["SnapshotRequest"]()):
                blob += part.blob
                last_remaining = part.remaining_bytes
            assert last_remaining == 0

            from madsim_tpu.etcd.service import EtcdService

            restored = EtcdService()
            restored.load(blob.decode())
            assert restored.kv[b"snap"].value == b"state"
            assert restored.kv[b"snap2"].value == b"more"
            assert restored.revision == 2
        task.abort()

    real.Runtime().block_on(main())


def test_wire_lease_expires_on_wall_clock():
    """The tick loop expires leases on real time: a TTL-1 lease's key is
    gone within ~2.5 s (ref: the sim's per-second tick task,
    service.rs:27-33, here on the wall clock)."""
    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            grant = _mc(ch, m, "Lease", "LeaseGrant",
                        m["LeaseGrantRequest"], m["LeaseGrantResponse"])
            put = _mc(ch, m, "KV", "Put", m["PutRequest"], m["PutResponse"])
            rng = _mc(ch, m, "KV", "Range", m["RangeRequest"], m["RangeResponse"])

            g = await grant(m["LeaseGrantRequest"](TTL=1))
            await put(m["PutRequest"](key=b"evanescent", value=b"x", lease=g.ID))
            assert (await rng(m["RangeRequest"](key=b"evanescent"))).kvs
            await real.sleep(2.5)
            assert not (await rng(m["RangeRequest"](key=b"evanescent"))).kvs
        task.abort()

    real.Runtime().block_on(main())


# -- election / lock (v3electionpb.Election, v3lockpb.Lock) ------------------

import shutil  # noqa: E402

needs_protoc = pytest.mark.skipif(
    shutil.which("protoc") is None,
    reason="protoc not installed (environmental — see BASELINE notes)",
)


@needs_protoc
def test_wire_election_campaign_proclaim_leader_resign():
    """The v3election service over genuine gRPC: campaign wins with a
    live lease, Leader observes the proclaimed value, a second candidate
    blocks until the first resigns, and Proclaim after resign fails by
    name (session expired)."""
    import asyncio

    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            grant = _mc(ch, m, "Lease", "LeaseGrant",
                        m["LeaseGrantRequest"], m["LeaseGrantResponse"])

            def emc(method, req_cls, rsp_cls):
                return ch.unary_unary(
                    f"/v3electionpb.Election/{method}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=rsp_cls.FromString,
                )

            campaign = emc("Campaign", m["CampaignRequest"],
                           m["CampaignResponse"])
            proclaim = emc("Proclaim", m["ProclaimRequest"],
                           m["ProclaimResponse"])
            leader_mc = emc("Leader", m["LeaderRequest"], m["LeaderResponse"])
            resign = emc("Resign", m["ResignRequest"], m["ResignResponse"])

            l1 = (await grant(m["LeaseGrantRequest"](TTL=60))).ID
            l2 = (await grant(m["LeaseGrantRequest"](TTL=60))).ID

            r1 = await campaign(m["CampaignRequest"](
                name=b"elec", lease=l1, value=b"alpha"
            ))
            key1 = r1.leader.key
            assert key1.startswith(b"elec/") and r1.leader.rev > 0

            # Leader sees the current value; Proclaim replaces it
            led = await leader_mc(m["LeaderRequest"](name=b"elec"))
            assert led.kv.key == key1 and led.kv.value == b"alpha"
            await proclaim(m["ProclaimRequest"](
                leader=r1.leader, value=b"alpha-2"
            ))
            led = await leader_mc(m["LeaderRequest"](name=b"elec"))
            assert led.kv.value == b"alpha-2"

            # a second candidate BLOCKS until the first resigns
            second = asyncio.ensure_future(campaign(m["CampaignRequest"](
                name=b"elec", lease=l2, value=b"beta"
            )))
            await real.sleep(0.1)
            assert not second.done()  # still parked behind the leader
            await resign(m["ResignRequest"](leader=r1.leader))
            r2 = await asyncio.wait_for(second, timeout=5)
            assert r2.leader.key != key1
            led = await leader_mc(m["LeaderRequest"](name=b"elec"))
            assert led.kv.value == b"beta"

            # proclaiming with the RESIGNED leader key fails by name
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await proclaim(m["ProclaimRequest"](
                    leader=r1.leader, value=b"zombie"
                ))
            assert e.value.code() == grpcio.StatusCode.FAILED_PRECONDITION

            # no-leader elections answer NOT_FOUND
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await leader_mc(m["LeaderRequest"](name=b"empty"))
            assert e.value.code() == grpcio.StatusCode.NOT_FOUND
        task.abort()

    real.Runtime().block_on(main())


@needs_protoc
def test_wire_lock_blocks_until_unlock_and_lease_expiry():
    """The v3lock service: Lock hands out the key immediately when free,
    a contender blocks until Unlock, and revoking the holder's lease
    releases the lock to the waiter (the session-expiry path)."""
    import asyncio

    m = _msgs()

    async def main():
        _server, task, addr = await _start()
        async with grpc_aio.insecure_channel(addr) as ch:
            grant = _mc(ch, m, "Lease", "LeaseGrant",
                        m["LeaseGrantRequest"], m["LeaseGrantResponse"])
            revoke = _mc(ch, m, "Lease", "LeaseRevoke",
                         m["LeaseRevokeRequest"], m["LeaseRevokeResponse"])

            def lmc(method, req_cls, rsp_cls):
                return ch.unary_unary(
                    f"/v3lockpb.Lock/{method}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=rsp_cls.FromString,
                )

            lock = lmc("Lock", m["LockRequest"], m["LockResponse"])
            unlock = lmc("Unlock", m["UnlockRequest"], m["UnlockResponse"])

            l1 = (await grant(m["LeaseGrantRequest"](TTL=60))).ID
            l2 = (await grant(m["LeaseGrantRequest"](TTL=60))).ID
            l3 = (await grant(m["LeaseGrantRequest"](TTL=60))).ID

            r1 = await lock(m["LockRequest"](name=b"mtx", lease=l1))
            assert r1.key.startswith(b"mtx/")

            waiter = asyncio.ensure_future(
                lock(m["LockRequest"](name=b"mtx", lease=l2))
            )
            await real.sleep(0.1)
            assert not waiter.done()
            await unlock(m["UnlockRequest"](key=r1.key))
            r2 = await asyncio.wait_for(waiter, timeout=5)
            assert r2.key != r1.key

            # lease revocation (session expiry) also releases the lock
            waiter3 = asyncio.ensure_future(
                lock(m["LockRequest"](name=b"mtx", lease=l3))
            )
            await real.sleep(0.1)
            assert not waiter3.done()
            await revoke(m["LeaseRevokeRequest"](ID=l2))
            r3 = await asyncio.wait_for(waiter3, timeout=5)
            assert r3.key.startswith(b"mtx/")
        task.abort()

    real.Runtime().block_on(main())


# -- the acquire recipe, protoc-free (pure EtcdService + asyncio) -----------
# The wire services above are thin shells around acquire_candidacy + the
# existing service primitives; these tests pin the recipe's semantics in
# environments without protoc (this container included).


def test_acquire_candidacy_blocks_and_hands_off_in_revision_order():
    import asyncio

    from madsim_tpu.etcd.service import DeleteOptions, EtcdService

    svc = EtcdService()

    async def main():
        svc.bus.future_factory = (
            lambda: asyncio.get_running_loop().create_future()
        )
        l1, _ = svc.lease_grant(60)
        l2, _ = svc.lease_grant(60)
        l3, _ = svc.lease_grant(60)

        key1 = await wire.acquire_candidacy(svc, b"e", b"one", l1)
        assert svc.election_leader(b"e").key == key1

        # two waiters queue up; handoff is oldest-candidacy-first
        w2 = asyncio.ensure_future(
            wire.acquire_candidacy(svc, b"e", b"two", l2)
        )
        await asyncio.sleep(0.01)
        w3 = asyncio.ensure_future(
            wire.acquire_candidacy(svc, b"e", b"three", l3)
        )
        await asyncio.sleep(0.01)
        assert not w2.done() and not w3.done()

        svc.delete(key1, DeleteOptions())  # resign
        key2 = await asyncio.wait_for(w2, timeout=5)
        assert svc.election_leader(b"e").key == key2
        assert not w3.done()  # strictly one handoff per release

        svc.lease_revoke(l2)  # session expiry releases too
        key3 = await asyncio.wait_for(w3, timeout=5)
        assert svc.election_leader(b"e").key == key3

    asyncio.run(main())


def test_acquire_candidacy_requires_live_lease():
    import asyncio

    from madsim_tpu.etcd.service import EtcdService
    from madsim_tpu.grpc.status import Status

    svc = EtcdService()

    async def main():
        with pytest.raises(Status):
            await wire.acquire_candidacy(svc, b"e", b"x", 424242)

    asyncio.run(main())
