"""The async serving core (madsim_tpu/serve): framing reassembly,
bounded-queue backpressure, lifecycle, and adapter parity.

The heavy end-to-end rig (>=1k concurrent clients, chaos mid-run) is
``scripts/wire_load.py`` / `make wire-smoke`; these tests pin the core's
unit contracts cheaply: framers are pure state machines, ``Conn`` is
driven through a fake transport (no sockets, no sleeps), and the parity
test replays a small seeded client mix against both the core-backed and
the legacy thread-of-control Kafka servers and byte-compares the
recorded transcripts.
"""

import asyncio
import random
import struct
import subprocess
import sys
import os

import pytest

from madsim_tpu.obs import Telemetry
from madsim_tpu.oracle import History, Op, S3Spec, check_history
from madsim_tpu.oracle.history import OP_DEL, OP_GET, OP_PUT
from madsim_tpu.oracle.specs import ABSENT
from madsim_tpu.serve import (
    AsyncWireServer,
    FramingError,
    PureFrameAdapter,
    WireAdapter,
)
from madsim_tpu.serve.framing import (
    HttpRequestFramer,
    LengthPrefixFramer,
    frame,
    render_http_response,
)


# -- framing: reassembly across arbitrary chunk boundaries -------------------


def test_length_prefix_reassembly_byte_by_byte():
    bodies = [b"", b"x", b"hello" * 100, bytes(range(256))]
    wire = b"".join(frame(b) for b in bodies)
    f = LengthPrefixFramer()
    out = []
    for i in range(len(wire)):
        out.extend(f.feed(wire[i : i + 1]))
    assert out == bodies
    assert f.pending() == 0

    # and the whole stream in one chunk
    f2 = LengthPrefixFramer()
    assert f2.feed(wire) == bodies


def test_length_prefix_rejects_insane_length():
    f = LengthPrefixFramer(max_frame=16)
    with pytest.raises(FramingError):
        f.feed(struct.pack(">I", 17) + b"x" * 17)


def test_http_framer_split_boundaries_and_keepalive():
    put = (
        b"PUT /b/k?uploadId=u-1&partNumber=2 HTTP/1.1\r\n"
        b"Content-Length: 11\r\nHost: x\r\n\r\nhello world"
    )
    get = b"GET /b/k HTTP/1.1\r\nContent-Length: 0\r\n\r\n"
    wire = put + get  # keep-alive: two requests on one stream
    # split at every position: the parse must come out identical
    for cut in range(0, len(wire), 7):
        f = HttpRequestFramer()
        reqs = f.feed(wire[:cut]) + f.feed(wire[cut:])
        assert [r.method for r in reqs] == ["PUT", "GET"]
        assert reqs[0].path == "/b/k"
        assert reqs[0].query == {"uploadId": "u-1", "partNumber": "2"}
        assert reqs[0].headers["content-length"] == "11"
        assert reqs[0].body == b"hello world"
        assert reqs[1].body == b""
        assert f.pending() == 0


def test_http_framer_rejects_garbage():
    with pytest.raises(FramingError):
        HttpRequestFramer().feed(b"NOTHTTP\r\n\r\n")
    with pytest.raises(FramingError):
        HttpRequestFramer().feed(
            b"PUT /k HTTP/1.1\r\nContent-Length: nope\r\n\r\n"
        )
    with pytest.raises(FramingError):
        HttpRequestFramer(max_body=8).feed(
            b"PUT /k HTTP/1.1\r\nContent-Length: 9\r\n\r\n"
        )


def test_render_http_response_head_advertises_but_omits_body():
    full = render_http_response(200, b"body!", {"ETag": '"e"'})
    head = render_http_response(200, b"body!", {"ETag": '"e"'},
                                head_only=True)
    assert full.endswith(b"body!")
    assert not head.endswith(b"body!")
    assert b"Content-Length: 5" in head  # real entity length, no body


# -- Conn: bounded write queue + pause bookkeeping (fake transport) ----------


class FakeTransport:
    def __init__(self):
        self.written = bytearray()
        self.reading = True
        self.closed = False
        self.aborted = False

    def get_extra_info(self, _key):
        return ("test-peer", 0)

    def write(self, data):
        self.written += data

    def pause_reading(self):
        self.reading = False

    def resume_reading(self):
        self.reading = True

    def close(self):
        self.closed = True

    def abort(self):
        self.aborted = True


def _proto_on_fake(telemetry=None, **srv_kw):
    from madsim_tpu.serve.core import _WireProtocol

    srv = AsyncWireServer(
        PureFrameAdapter(lambda b: b, name="t"),
        telemetry=telemetry, **srv_kw,
    )
    proto = _WireProtocol(srv, asyncio.get_running_loop())
    t = FakeTransport()
    proto.connection_made(t)
    return srv, proto, t


def test_conn_backpressure_pause_resume_and_drain():
    async def main():
        tel = Telemetry()
        srv, proto, t = _proto_on_fake(
            telemetry=tel, max_queue_bytes=200, read_pause_bytes=100
        )
        conn = proto.conn

        # writable transport: send writes straight through, no queue
        conn.send(b"a" * 10)
        assert bytes(t.written) == b"a" * 10 and not conn._q

        # transport pushes back: output queues; crossing read_pause_bytes
        # pauses the read side (write-backlog backpressure)
        proto.pause_writing()
        conn.send(b"b" * 60)
        assert t.reading and conn._q_bytes == 60
        conn.send(b"c" * 60)
        assert not t.reading  # 120 > read_pause_bytes
        assert tel.registry.get(
            "serve_backpressure_pauses_total", wire="t") == 1

        # drained() blocks until the transport resumes and we flush
        waiter = asyncio.ensure_future(conn.drained())
        await asyncio.sleep(0)
        assert not waiter.done()
        proto.resume_writing()
        await asyncio.wait_for(waiter, 1)
        assert bytes(t.written) == b"a" * 10 + b"b" * 60 + b"c" * 60
        assert t.reading and conn._q_bytes == 0
        assert srv.open_conns() == 1
    asyncio.run(main())


def test_conn_slow_client_evicted_at_queue_bound():
    async def main():
        tel = Telemetry()
        srv, proto, t = _proto_on_fake(
            telemetry=tel, max_queue_bytes=200, read_pause_bytes=100
        )
        conn = proto.conn
        proto.pause_writing()
        conn.send(b"x" * 150)
        assert not t.aborted
        conn.send(b"y" * 100)  # 250 > max_queue_bytes: evict, hard
        assert t.aborted
        assert tel.registry.get(
            "serve_slow_client_drops_total", wire="t") == 1

        proto.connection_lost(None)
        assert conn.closed and srv.open_conns() == 0
        with pytest.raises(BrokenPipeError):
            conn.send(b"late")
    asyncio.run(main())


def test_protocol_violation_aborts_connection():
    async def main():
        _srv, proto, t = _proto_on_fake()
        proto.data_received(struct.pack(">I", 0xFFFF_FFFF))
        assert t.aborted  # FramingError: dropped like a real wire
    asyncio.run(main())


def test_close_defers_until_queue_flushes():
    async def main():
        _srv, proto, t = _proto_on_fake()
        conn = proto.conn
        proto.pause_writing()
        conn.send(b"tail")
        conn.close()
        assert not t.closed  # queued output must reach the peer first
        proto.resume_writing()
        assert t.closed and bytes(t.written) == b"tail"
    asyncio.run(main())


# -- clean shutdown with in-flight async handlers (real sockets) -------------


class _SlowEcho(WireAdapter):
    """Answers each frame from a coroutine after a short sleep — the
    in-flight shape ``aclose`` must drain, in arrival order."""

    name = "slowecho"

    def new_framer(self):
        return LengthPrefixFramer()

    def on_frame(self, conn, body):
        async def run():
            await asyncio.sleep(0.02)
            return frame(b"echo:" + body)

        return run()


def test_aclose_drains_inflight_async_handlers_in_order():
    async def main():
        srv = AsyncWireServer(_SlowEcho())
        host, port = await srv.start(("127.0.0.1", 0))
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(frame(b"one") + frame(b"two"))
        await writer.drain()
        await asyncio.sleep(0.005)  # let the frames reach the server
        assert srv._inflight > 0  # handlers genuinely in flight
        await srv.aclose(drain_timeout=2.0)
        # both responses arrive, in order, then a clean EOF
        got = await asyncio.wait_for(reader.read(), 2)
        f = LengthPrefixFramer()
        assert f.feed(got) == [b"echo:one", b"echo:two"]
        writer.close()
    asyncio.run(main())


def test_inject_read_stall_blackholes_matched_conns_only():
    async def main():
        tel = Telemetry()
        srv = AsyncWireServer(PureFrameAdapter(lambda b: b, name="t"),
                              telemetry=tel)
        host, port = await srv.start(("127.0.0.1", 0))
        r1, w1 = await asyncio.open_connection(host, port)
        r2, w2 = await asyncio.open_connection(host, port)
        while srv.open_conns() < 2:
            await asyncio.sleep(0.001)
        ids = sorted(c.id for c in srv.connections())
        n = srv.inject_read_stall(0.05, match=lambda c: c.id == ids[0])
        assert n == 1
        assert tel.registry.get("serve_chaos_stalls_total", wire="t") == 1
        # the unmatched connection keeps round-tripping during the stall
        w2.write(frame(b"live"))
        assert (await asyncio.wait_for(r2.readexactly(8), 1))[4:] == b"live"
        # the stalled one answers only after the heal timer fires
        w1.write(frame(b"held"))
        read1 = asyncio.ensure_future(r1.readexactly(8))
        done, _ = await asyncio.wait([read1], timeout=0.02)
        assert not done  # blackholed while stalled
        assert (await asyncio.wait_for(read1, 1))[4:] == b"held"
        for w in (w1, w2):
            w.close()
        srv.close()
    asyncio.run(main())


# -- adapter parity: core-backed vs legacy servers, one seeded transcript ----


class _CounterClock:
    def __init__(self, start=1_000_000):
        self.t = start

    def __call__(self):
        self.t += 1
        return self.t


async def _kafka_transcript(server):
    """A small seeded probe mix; returns the server's recorded
    (request bytes, clock, response bytes) transcript."""
    from madsim_tpu.kafka.probe import ProbeClient, RealTransport

    await server.start(("127.0.0.1", 0))
    server.wire.recorder = []
    rng = random.Random(7)
    c = ProbeClient(await RealTransport.connect(server.bound_addr))
    try:
        await c.api_versions()
        await c.create_topics([("p", 2)])
        offsets = [0, 0]
        for _ in range(12):
            part = rng.randrange(2)
            if rng.randrange(2):
                await c.produce("p", part,
                                [(1_000, b"k", b"v%d" % rng.randrange(99))])
            else:
                err, _hi, rows = await c.fetch("p", part, offsets[part])
                if not err and rows:
                    offsets[part] = rows[-1][0] + 1
    finally:
        c.close()
        server.close()
    return server.wire.recorder


def test_kafka_adapter_parity_async_vs_legacy():
    """The serving core is a transport change, not a protocol change:
    with the clock injected and the advertised address pinned, the
    core-backed server and the legacy task-per-connection server record
    byte-identical transcripts for the same seeded client mix."""
    from madsim_tpu.kafka.wire import LegacyWireServer, WireServer

    adv = ("127.0.0.1", 9092)

    async def run_async():
        return await _kafka_transcript(
            WireServer(clock_ms=_CounterClock(), advertised=adv))

    async def run_legacy():
        return await _kafka_transcript(
            LegacyWireServer(clock_ms=_CounterClock(), advertised=adv))

    a = asyncio.run(run_async())
    b = asyncio.run(run_legacy())
    assert len(a) == len(b) >= 14
    assert a == b


# -- channel adapter: the pull-style (tx, rx) surface over the core ----------


def test_channel_adapter_runs_pull_style_handler():
    from madsim_tpu.real import codec
    from madsim_tpu.serve import ChannelAdapter
    from madsim_tpu.real import stream

    async def upper(tx, rx):
        while True:
            msg = await rx.recv()
            if msg is None:
                break
            await tx.send(str(msg).upper())
        tx.close()

    async def main():
        srv = AsyncWireServer(ChannelAdapter(upper, codec))
        addr = await srv.start(("127.0.0.1", 0))
        tx, rx = await stream.connect(addr)
        await tx.send("hello")
        assert await rx.recv() == "HELLO"
        await tx.send("again")
        assert await rx.recv() == "AGAIN"
        tx.close()
        assert await rx.recv() is None  # handler EOF propagates cleanly
        srv.close()
    asyncio.run(main())


# -- real/stream: closed-listener semantics the load rig leans on ------------


def test_stream_listener_close_drops_unclaimed_connections():
    from madsim_tpu.real import stream

    async def main():
        lis = await stream.StreamListener.bind(("127.0.0.1", 0))
        addr = lis.local_addr()
        # queued-but-unclaimed: accepted by the kernel, never accept1()d
        tx, rx = await stream.connect(addr)
        await asyncio.sleep(0.02)  # let the accept callback enqueue it
        lis.close()
        # the unclaimed client sees a reset/EOF instead of hanging
        with pytest.raises((ConnectionResetError, ConnectionError)):
            if await rx.recv() is None:
                raise ConnectionResetError("clean EOF counts as dropped")
        # and accept1 on a closed listener raises instead of blocking
        with pytest.raises(ConnectionAbortedError):
            await lis.accept1()
        tx.close()
    asyncio.run(main())


# -- S3Spec: the per-object register semantics the rig checks against --------


def _s3_hist(*ops):
    return History(seed=0, ops=tuple(ops), overflow=False,
                   rows=2 * len(ops))


def test_s3_spec_register_semantics():
    v1, v2 = 101, 202
    legal = _s3_hist(
        Op(0, OP_PUT, 5, v1, 0, 0, 1, 0),
        Op(1, OP_GET, 5, 0, v1, 2, 3, 0),
        Op(0, OP_PUT, 5, v2, 0, 4, 5, 1),
        Op(1, OP_GET, 5, 0, v2, 6, 7, 1),
        Op(0, OP_DEL, 5, 0, 0, 8, 9, 2),
        Op(1, OP_GET, 5, 0, ABSENT, 10, 11, 2),
    )
    assert check_history(legal, S3Spec()).ok

    # a lost PUT: the GET observes absence with no DELETE in between
    torn = _s3_hist(
        Op(0, OP_PUT, 5, v1, 0, 0, 1, 0),
        Op(1, OP_GET, 5, 0, ABSENT, 2, 3, 0),
    )
    r = check_history(torn, S3Spec())
    assert not r.ok

    # keys are independent partitions: a stale read on one key cannot
    # be excused by activity on another
    cross = _s3_hist(
        Op(0, OP_PUT, 5, v1, 0, 0, 1, 0),
        Op(0, OP_PUT, 6, v2, 0, 2, 3, 1),
        Op(1, OP_GET, 5, 0, v2, 4, 5, 0),
    )
    assert not check_history(cross, S3Spec()).ok
    assert S3Spec().partition_of(Op(1, OP_GET, 6, 0, 0, 0, 1, 0)) == 6


# -- the whole rig, small (slow: `make wire-smoke` drills this) --------------


@pytest.mark.slow
def test_wire_load_smoke_end_to_end():
    """SMOKE_SCENARIO through the load rig: concurrent worker processes,
    oracle-checked histories, live-vs-replay identity, async-vs-legacy
    parity — the subprocess keeps the forked workers jax-free."""
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "wire_load.py")
    proc = subprocess.run(
        [sys.executable, script, "--smoke"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "smoke parity [async vs legacy" in proc.stdout
