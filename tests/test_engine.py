"""TPU engine: queue ops, determinism, raft sweep behavior, CPU parity.

The determinism contract under test is SURVEY.md §7's invariant: one seed =
one bit-exact execution, independent of batch size or batch position —
the property that lets a TPU sweep find a failure and a CPU replay
reproduce it byte-identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.engine import core as ecore
from madsim_tpu.engine import net as enet
from madsim_tpu.engine import queue as equeue
from madsim_tpu.engine.core import EngineConfig
from madsim_tpu.engine.rng import bounded, coin, event_bits, prob_to_q32, seed_key
from madsim_tpu.models import raft


# -- queue -----------------------------------------------------------------


def test_queue_push_pop_min_order():
    q = equeue.make(8, 2)
    for t in [50, 10, 30]:
        q, ov = equeue.push(
            q,
            jnp.int64(t),
            jnp.int32(t),
            jnp.array([t, 0], jnp.int32),
            jnp.asarray(True),
        )
        assert not bool(ov)
    times = []
    for _ in range(4):
        q, t, kind, pay, found = equeue.pop_min(q)
        if bool(found):
            times.append(int(t))
            assert int(kind) == int(t)
    assert times == [10, 30, 50]
    assert int(equeue.size(q)) == 0


def test_queue_overflow_flag():
    q = equeue.make(2, 1)
    for i in range(3):
        q, ov = equeue.push(
            q, jnp.int64(i), jnp.int32(i), jnp.array([i], jnp.int32), jnp.asarray(True)
        )
    assert bool(ov)


def test_queue_disabled_push_is_noop():
    q = equeue.make(2, 1)
    q, ov = equeue.push(
        q, jnp.int64(1), jnp.int32(1), jnp.array([1], jnp.int32), jnp.asarray(False)
    )
    assert not bool(ov)
    assert int(equeue.size(q)) == 0


# -- rng -------------------------------------------------------------------


def test_event_bits_counter_based():
    k = seed_key(jnp.int64(42))
    a = event_bits(k, jnp.int32(7), 4)
    b = event_bits(k, jnp.int32(7), 4)
    c = event_bits(k, jnp.int32(8), 4)
    assert jnp.array_equal(a, b)
    assert not jnp.array_equal(a, c)


def test_bounded_range():
    k = seed_key(jnp.int64(1))
    draws = event_bits(k, jnp.int32(0), 256)
    vals = bounded(draws, 10, 20)
    assert int(vals.min()) >= 10 and int(vals.max()) < 20


def test_bounded_wide_spans_do_not_sign_wrap():
    """spans above 2**31 (5 s fault windows, day-scale spans) used to
    overflow the int64 product and wrap times negative; the half-width
    multiply must stay in range AND match exact integer arithmetic."""
    k = seed_key(jnp.int64(2))
    draws = event_bits(k, jnp.int32(0), 256)
    for lo, hi in ((0, 5_000_000_000), (0, 1 << 47), (-3, 4_000_000_000)):
        vals = bounded(draws, lo, hi)
        assert int(vals.min()) >= lo and int(vals.max()) < hi
        expect = [lo + ((int(d) * (hi - lo)) >> 32) for d in draws]
        assert [int(v) for v in vals] == expect


def test_coin_fixed_point():
    assert not bool(coin(jnp.uint32(0xFFFFFFFF), jnp.uint32(prob_to_q32(0.5))))
    assert bool(coin(jnp.uint32(0), jnp.uint32(prob_to_q32(0.001))))


# -- net model -------------------------------------------------------------


def test_route_latency_within_bounds():
    links = enet.make(3, loss_q32=0, lat_lo_ns=100, lat_hi_ns=200)
    k = seed_key(jnp.int64(5))
    u = event_bits(k, jnp.int32(0), 2)
    t, deliver = enet.route(links, jnp.int64(1000), jnp.int32(0), jnp.int32(1), u[0], u[1])
    assert bool(deliver)
    assert 1100 <= int(t) <= 1200


def test_clog_drops_messages():
    links = enet.make(3)
    links = enet.clog_link(links, jnp.int32(0), jnp.int32(1))
    k = seed_key(jnp.int64(5))
    u = event_bits(k, jnp.int32(0), 2)
    _, deliver = enet.route(links, jnp.int64(0), jnp.int32(0), jnp.int32(1), u[0], u[1])
    assert not bool(deliver)
    # reverse direction unaffected
    _, deliver_rev = enet.route(links, jnp.int64(0), jnp.int32(1), jnp.int32(0), u[0], u[1])
    assert bool(deliver_rev)
    links = enet.unclog_link(links, jnp.int32(0), jnp.int32(1))
    _, deliver2 = enet.route(links, jnp.int64(0), jnp.int32(0), jnp.int32(1), u[0], u[1])
    assert bool(deliver2)


def test_clog_node_blocks_both_directions():
    links = enet.clog_node(enet.make(4), jnp.int32(2))
    assert bool(links.clog[2, 0]) and bool(links.clog[0, 2])
    assert not bool(links.clog[0, 1])
    links = enet.unclog_node(links, jnp.int32(2))
    assert not bool(links.clog.any())


# -- raft sweep ------------------------------------------------------------


SMALL = raft.RaftConfig(crashes=1, loss_q32=prob_to_q32(0.01))
ECFG = raft.engine_config(SMALL, time_limit_ns=3_000_000_000, max_steps=20_000)


@pytest.fixture(scope="module")
def raft_final():
    wl = raft.workload(SMALL)
    seeds = jnp.arange(32, dtype=jnp.int64)
    return ecore.run_sweep(wl, ECFG, seeds)


def test_raft_sweep_elects_leaders(raft_final):
    s = raft.sweep_summary(raft_final)
    assert s["seeds"] == 32
    assert s["overflow_seeds"] == 0
    assert s["violations"] == 0
    # within 3 virtual seconds nearly every 150-300ms-timeout cluster elects
    assert s["no_leader_seeds"] == 0
    assert s["events_total"] > 32 * 50
    # sent counts attempts, delivered counts link-test passes
    assert s["msgs_sent"] >= s["msgs_delivered"] > 0


def test_workload_memoized_per_config():
    """Equal configs must yield the SAME Workload object: _drive's jit
    cache keys on the Workload's partials by identity, so an equal-but-
    distinct Workload silently recompiles the whole sweep (~16 s)."""
    from madsim_tpu.models import etcd, kafka, s3

    assert raft.workload(SMALL) is raft.workload(
        raft.RaftConfig(**SMALL._asdict())
    )
    for mod, cfg_cls in (
        (kafka, kafka.KafkaConfig),
        (etcd, etcd.EtcdConfig),
        (s3, s3.S3Config),
    ):
        assert mod.workload(cfg_cls()) is mod.workload(cfg_cls())
        # default-arg call normalizes to the same cache key
        assert mod.workload() is mod.workload(cfg_cls())
    # a different config still gets its own workload
    assert raft.workload(SMALL) is not raft.workload(
        raft.RaftConfig(**{**SMALL._asdict(), "crashes": SMALL.crashes + 1})
    )


def test_raft_all_seeds_terminate(raft_final):
    assert bool(jnp.all(raft_final.done))
    # terminated by time limit, not queue starvation: clock near the limit
    assert int(raft_final.now_ns.min()) > ECFG.time_limit_ns // 2


def test_raft_seeds_diverge(raft_final):
    # different seeds must explore different schedules (ref: 10 seeds ⇒ 10
    # distinct interleavings, task/mod.rs:964-988)
    assert len(np.unique(np.asarray(raft_final.ctr))) > 8
    assert len(np.unique(np.asarray(raft_final.wstate.elections))) > 1


def test_raft_same_seed_bit_exact(raft_final):
    wl = raft.workload(SMALL)
    again = ecore.run_sweep(wl, ECFG, jnp.arange(32, dtype=jnp.int64))
    for a, b in zip(jax.tree.leaves(raft_final), jax.tree.leaves(again)):
        if jnp.issubdtype(a.dtype, jnp.integer) or a.dtype == bool:
            assert jnp.array_equal(a, b)


def test_raft_batch_position_invariant():
    """Seed 7's outcome is identical whether run in a batch of 32 or alone —
    the property that makes CPU replay of a TPU-found failure valid."""
    wl = raft.workload(SMALL)
    batch = ecore.run_sweep(wl, ECFG, jnp.arange(32, dtype=jnp.int64))
    solo = ecore.run_sweep(wl, ECFG, jnp.array([7], dtype=jnp.int64))
    assert int(batch.ctr[7]) == int(solo.ctr[0])
    assert int(batch.now_ns[7]) == int(solo.now_ns[0])
    assert int(batch.wstate.elections[7]) == int(solo.wstate.elections[0])
    assert int(batch.wstate.msgs_delivered[7]) == int(solo.wstate.msgs_delivered[0])


def test_raft_traced_replay_matches_sweep():
    wl = raft.workload(SMALL)
    sweep = ecore.run_sweep(wl, ECFG, jnp.array([3], dtype=jnp.int64))
    final, trace = ecore.run_traced(wl, ECFG, 3)
    assert int(final.ctr) == int(sweep.ctr[0])
    assert int(final.now_ns) == int(sweep.now_ns[0])
    fired = np.asarray(trace["fired"])
    assert fired.sum() == int(final.ctr)
    # trace times are monotonically non-decreasing over fired events
    t = np.asarray(trace["time_ns"])[fired]
    assert (np.diff(t) >= 0).all()


def test_raft_crash_restart_in_plan():
    # with an aggressive fault plan the sweep still holds election safety
    cfg = raft.RaftConfig(crashes=4, crash_window_ns=2_000_000_000)
    wl = raft.workload(cfg)
    final = ecore.run_sweep(
        wl, raft.engine_config(cfg, time_limit_ns=3_000_000_000), jnp.arange(16, dtype=jnp.int64)
    )
    s = raft.sweep_summary(final)
    assert s["violations"] == 0
    assert s["overflow_seeds"] == 0


def test_raft_log_replication_commits():
    """With client commands in the plan, entries get replicated and
    committed on a majority, and the log-matching checker stays quiet."""
    cfg = raft.RaftConfig(num_nodes=3, crashes=1, commands=6,
                          cmd_window_ns=2_000_000_000)
    wl = raft.workload(cfg)
    final = ecore.run_sweep(
        wl,
        raft.engine_config(cfg, time_limit_ns=4_000_000_000, max_steps=40_000),
        jnp.arange(16, dtype=jnp.int64),
    )
    s = raft.sweep_summary(final)
    assert s["violations"] == 0
    assert s["overflow_seeds"] == 0
    assert s["log_overflow_seeds"] == 0
    # nearly all commands find a leader within 4 virtual seconds, and
    # committed entries replicate
    assert s["accepted_cmds"] >= 16 * 4
    assert s["commits_total"] >= s["accepted_cmds"]  # leader + follower commits
    w = final.wstate
    # every seed: all alive nodes' committed prefixes agree with the
    # recorded commit history (end-state cross-check of the online checker)
    import numpy as np

    log_term = np.asarray(w.log_term)
    commit = np.asarray(w.commit)
    chist_term = np.asarray(w.chist_term)
    chist_set = np.asarray(w.chist_set)
    for sd in range(log_term.shape[0]):
        for node in range(cfg.num_nodes):
            for idx in range(1, commit[sd, node] + 1):
                if chist_set[sd, idx]:
                    assert log_term[sd, node, idx] == chist_term[sd, idx], (sd, node, idx)


def test_raft_total_partition_no_leader():
    """Sanity-check the checker can see *absence* too: with 100% packet
    loss no election can ever complete."""
    cfg = raft.RaftConfig(crashes=0, loss_q32=prob_to_q32(1.0))
    wl = raft.workload(cfg)
    final = ecore.run_sweep(
        wl,
        raft.engine_config(cfg, time_limit_ns=1_000_000_000, max_steps=5_000),
        jnp.arange(4, dtype=jnp.int64),
    )
    s = raft.sweep_summary(final)
    assert s["no_leader_seeds"] == 4


# -- random tie-breaking (ref mpsc.rs:71-84 random-pop semantics) ----------


def test_pop_tie_break_varies_with_draw():
    """Equal-time events pop in different orders for different tie draws,
    and identically for the same draw (deterministic per seed+event)."""
    def fill():
        q = equeue.make(8, 1)
        for k in range(4):
            q, _ = equeue.push(
                q, jnp.int64(100), jnp.int32(k),
                jnp.array([k], jnp.int32), jnp.asarray(True),
            )
        return q

    def pop_order(tie_seq):
        q = fill()
        order = []
        for u in tie_seq:
            q, t, kind, pay, found = equeue.pop_min(q, tie_u32=jnp.uint32(u))
            assert bool(found) and int(t) == 100
            order.append(int(kind))
        return order

    a = pop_order([0x12345678, 0x9E3779B9, 0xDEADBEEF, 7])
    b = pop_order([0x12345678, 0x9E3779B9, 0xDEADBEEF, 7])
    assert a == b, "same draws must give the same order"
    assert sorted(a) == [0, 1, 2, 3], "all tied events must pop exactly once"
    orders = {tuple(pop_order([u, u + 1, u + 2, u + 3])) for u in range(12)}
    assert len(orders) > 1, "tie order must vary across draws"


def test_pop_tie_break_prefers_earlier_time():
    """The tie-break only applies within the minimum time bucket."""
    q = equeue.make(4, 1)
    for t, k in [(200, 0), (100, 1), (200, 2)]:
        q, _ = equeue.push(
            q, jnp.int64(t), jnp.int32(k), jnp.array([k], jnp.int32),
            jnp.asarray(True),
        )
    for u in (0, 1, 0xFFFFFFFF, 0x13572468):
        _, t, kind, _, found = equeue.pop_min(q, tie_u32=jnp.uint32(u))
        assert bool(found) and int(t) == 100 and int(kind) == 1


def test_same_timestamp_events_interleave_across_seeds():
    """Two events scheduled at the identical timestamp are dispatched in
    seed-dependent order — the device analogue of the reference's random
    ready-queue pop (schedule amplification across a sweep)."""
    from madsim_tpu.engine.core import Emits, Workload

    def init(key):
        w = jnp.zeros((2,), jnp.int32)  # dispatch log: order of kinds
        emits = Emits(
            times=jnp.array([1000, 1000], jnp.int64),
            kinds=jnp.array([1, 2], jnp.int32),
            pays=jnp.zeros((2, 1), jnp.int32),
            enables=jnp.ones((2,), bool),
        )
        return w, emits

    def handle(w, now, kind, pay, rand):
        slot = jnp.where(w[0] == 0, 0, 1)
        w = jnp.where(jnp.arange(2) == slot, kind, w)
        return w, Emits(
            times=jnp.zeros((1,), jnp.int64),
            kinds=jnp.zeros((1,), jnp.int32),
            pays=jnp.zeros((1, 1), jnp.int32),
            enables=jnp.zeros((1,), bool),
        )

    wl = Workload(init=init, handle=handle, num_rand=1, payload_slots=1, max_emits=1)
    cfg = EngineConfig(queue_capacity=4, time_limit_ns=10_000, max_steps=8,
                       cond_interval=1)
    final = ecore.run_sweep(wl, cfg, jnp.arange(64, dtype=jnp.int64))
    first = np.asarray(final.wstate)[:, 0]
    assert set(first.tolist()) == {1, 2}, (
        "across seeds both orders of the tied pair must occur"
    )


# -- queue-capacity bound (exact boundary) ----------------------------------


def _spawner_workload():
    """Synthetic growth workload: every handled event spawns two future
    events, so queue occupancy grows by exactly one per step — a ruler for
    the capacity boundary."""
    from madsim_tpu.engine.core import Emits, Workload

    def init(key):
        emits = Emits(
            times=jnp.array([100, 0], jnp.int64),
            kinds=jnp.zeros((2,), jnp.int32),
            pays=jnp.zeros((2, 1), jnp.int32),
            enables=jnp.array([True, False]),
        )
        return jnp.zeros(()), emits

    def handle(w, now, kind, pay, rand):
        emits = Emits(
            times=jnp.stack([now + 100, now + 200]),
            kinds=jnp.zeros((2,), jnp.int32),
            pays=jnp.zeros((2, 1), jnp.int32),
            enables=jnp.ones((2,), bool),
        )
        return w, emits

    return Workload(init=init, handle=handle, num_rand=1, payload_slots=1, max_emits=2)


def test_queue_fills_to_exact_capacity_without_overflow():
    """Occupancy can reach exactly queue_capacity with the overflow flag
    still clear: the bound is tight, not conservative."""
    cap = 8
    wl = _spawner_workload()
    cfg = EngineConfig(queue_capacity=cap, time_limit_ns=1 << 40,
                       max_steps=cap - 1, cond_interval=1)
    final = ecore.run_sweep(wl, cfg, jnp.arange(4, dtype=jnp.int64))
    assert (np.asarray(final.qmax) == cap).all()
    assert not np.asarray(final.overflow).any()


def test_queue_overflow_latches_exactly_past_capacity():
    """One step beyond the fill point the push exceeds capacity and the
    sticky overflow flag latches — at capacity+1 demand, not before."""
    cap = 8
    wl = _spawner_workload()
    cfg = EngineConfig(queue_capacity=cap, time_limit_ns=1 << 40,
                       max_steps=cap, cond_interval=1)
    final = ecore.run_sweep(wl, cfg, jnp.arange(4, dtype=jnp.int64))
    assert (np.asarray(final.qmax) == cap).all()  # never exceeds capacity
    assert np.asarray(final.overflow).all()


# -- raft client-command retry cap ------------------------------------------


def test_cmd_retry_cap_and_giveups_surfaced():
    """With a fully lossy network no leader ever emerges: every command
    retries to the cap, gives up (bounded K_CMD chains — no spinning until
    the time limit), and the give-ups are surfaced in the summary."""
    cfg = raft.RaftConfig(
        num_nodes=3, crashes=0, commands=4, loss_q32=prob_to_q32(1.0),
        cmd_max_retries=5, cmd_retry_ns=10_000_000,
        # every command must fire AND exhaust its retries inside the
        # 2 s time limit, or it can neither accept nor give up
        cmd_window_ns=1_000_000_000,
    )
    ecfg = raft.engine_config(cfg, time_limit_ns=2_000_000_000, max_steps=50_000)
    final = ecore.run_sweep(raft.workload(cfg), ecfg, jnp.arange(8, dtype=jnp.int64))
    s = raft.sweep_summary(final)
    assert s["accepted_cmds"] == 0
    assert s["cmd_giveups"] == 8 * cfg.commands  # every command capped out


def test_chunked_sweep_matches_unchunked_with_ragged_tail():
    """run_sweep_chunked splits a sweep into fixed-size program calls
    (padding + trimming a ragged final chunk) and must be bit-identical
    per seed to one big run_sweep."""
    cfg = raft.RaftConfig(num_nodes=3, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=500_000_000, max_steps=4_000)
    wl = raft.workload(cfg)
    seeds = jnp.arange(22, dtype=jnp.int64)  # 8+8+6: ragged tail
    whole = ecore.run_sweep(wl, ecfg, seeds)
    chunked = ecore.run_sweep_chunked(wl, ecfg, seeds, chunk_size=8)
    for a, b in zip(jax.tree.leaves(whole), jax.tree.leaves(chunked)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        assert jnp.array_equal(jax.device_get(a), jax.device_get(b))


def test_legacy_queue_layout_bit_identical():
    """The pre-round-5 queue layout (explicit valid plane,
    EngineConfig(legacy_queue=1)) and the packed layout (occupancy encoded
    in the time plane) must produce bit-identical schedules — the A/B in
    scripts/bench_packing.py measures a pure layout effect, nothing else."""
    cfg = raft.RaftConfig(num_nodes=3, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=500_000_000, max_steps=4_000)
    legacy_ecfg = ecfg._replace(legacy_queue=1)
    wl = raft.workload(cfg)
    seeds = jnp.arange(16, dtype=jnp.int64)
    packed = ecore.run_sweep(wl, ecfg, seeds)
    legacy = ecore.run_sweep(wl, legacy_ecfg, seeds)
    assert jnp.array_equal(packed.ctr, legacy.ctr)
    assert jnp.array_equal(packed.now_ns, legacy.now_ns)
    assert jnp.array_equal(packed.queue.time, legacy.queue.time)
    for a, b in zip(jax.tree.leaves(packed.wstate), jax.tree.leaves(legacy.wstate)):
        assert jnp.array_equal(jax.device_get(a), jax.device_get(b))
    # the legacy layout really does carry the extra plane
    assert hasattr(legacy.queue, "valid") and not hasattr(packed.queue, "valid")
    assert raft.sweep_summary(packed) == raft.sweep_summary(legacy)


def test_buggify_latency_spikes_amplify_and_stay_deterministic():
    """The device-tier buggify spike path (engine/net.py: loss-draw remix
    gates a 1-5 s latency spike, ref net/mod.rs:287-295): enabling it
    changes schedules for most seeds, amplifies elections (delayed
    heartbeats), keeps checkers quiet, and preserves traced-replay
    parity."""
    base = raft.RaftConfig(num_nodes=3, crashes=0)
    # 50%: rare enough to keep clusters mostly healthy, frequent enough
    # that consecutive delayed heartbeats open election-timeout gaps (a
    # lone 10% spike rarely does — heartbeats keep resetting the timer)
    spiky = base._replace(buggify_q32=prob_to_q32(0.50))
    # spiked (1-5 s) messages accumulate undelivered far beyond the
    # normal-latency queue sizing — give explicit headroom so the
    # assertions measure the spike model, not dropped-event artifacts
    ecfg = raft.engine_config(
        base, queue_capacity=128, time_limit_ns=2_000_000_000, max_steps=20_000
    )
    seeds = jnp.arange(64, dtype=jnp.int64)
    fb = ecore.run_sweep(raft.workload(base), ecfg, seeds)
    fs = ecore.run_sweep(raft.workload(spiky), ecfg, seeds)
    sb, ss = raft.sweep_summary(fb), raft.sweep_summary(fs)
    assert ss["violations"] == 0, ss
    assert ss["overflow_seeds"] == 0 and sb["overflow_seeds"] == 0
    # spikes perturb most seeds' schedules
    frac_changed = np.mean(np.asarray(fb.ctr) != np.asarray(fs.ctr))
    assert frac_changed > 0.5, frac_changed
    # 1-5 s heartbeat spikes against ~150-300 ms election timeouts force
    # re-elections across the batch
    assert ss["elections_total"] > sb["elections_total"], (sb, ss)
    # replay parity holds on the buggified config
    single, _ = ecore.run_traced(raft.workload(spiky), ecfg, int(seeds[3]))
    assert int(single.ctr) == int(fs.ctr[3])
    assert bool(single.wstate.violation) == bool(fs.wstate.violation[3])
