"""Spec-as-data fault campaigns (engine/faults.py FaultEnvelope/FaultParams).

The contract under test (docs/faults.md "Spec-as-data and the campaign
envelope"): a concrete spec compiled to runtime ``FaultParams`` and run
through the ONE program of its ``FaultEnvelope`` produces the
BIT-IDENTICAL ``(time_ns, action, victim)`` schedule — and therefore
bit-identical sweeps, campaign reports, differential outcomes and shrink
artifacts — as the static compile-per-spec path, while a warmed campaign
of mutated candidates performs ZERO XLA compilations.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import explore
from madsim_tpu.engine import core as ecore
from madsim_tpu.engine import faults as efaults
from madsim_tpu.engine.compiles import count_compiles
from madsim_tpu.models import etcd, raft
from madsim_tpu.replay import amnesia_raft_config

# one window pair in EVERY family (gray families included), all windows
# inside a 3 s horizon
FULL_SPEC = efaults.FaultSpec(
    crashes=2,
    crash_window_ns=1_500_000_000,
    restart_lo_ns=100_000_000,
    restart_hi_ns=400_000_000,
    partitions=2,
    part_window_ns=1_500_000_000,
    part_lo_ns=200_000_000,
    part_hi_ns=600_000_000,
    spikes=1,
    losses=1,
    pauses=1,
    aparts=2,
    apart_window_ns=1_200_000_000,
    fsync_stalls=1,
    power_fails=1,
    skews=1,
)

NODES = 5


def _padded_equals_dense(spec, envelope, num_nodes=NODES, seed=1234):
    key = jax.random.key(seed)
    td, ad, vd = efaults.schedule_events(spec, num_nodes, key)
    params = efaults.spec_to_params(spec, envelope, num_nodes)
    tp, ap, vp, en = efaults.schedule_events_padded(
        envelope, params, num_nodes, key
    )
    en = np.asarray(en)
    assert int(en.sum()) == int(td.shape[0])
    np.testing.assert_array_equal(np.asarray(tp)[en], np.asarray(td))
    np.testing.assert_array_equal(np.asarray(ap)[en], np.asarray(ad))
    np.testing.assert_array_equal(np.asarray(vp)[en], np.asarray(vd))


def test_bits_at_matches_jax_random_bits():
    # the padded derivation's RNG primitive: draw i of the partitionable
    # threefry stream as a pure function of (key, i), bit-for-bit what
    # jax.random.bits(key, (s,), uint32)[i] returns for any s
    for seed in (0, 7, 0xDEAD):
        key = jax.random.key(seed)
        ref = np.asarray(jax.random.bits(key, (257,), dtype=jnp.uint32))
        got = np.asarray(
            jax.vmap(lambda i, k=key: efaults.bits_at(k, i))(
                jnp.arange(257, dtype=jnp.uint32)
            )
        )
        np.testing.assert_array_equal(ref, got)


@pytest.mark.parametrize("family", efaults.FAMILIES)
def test_schedule_equivalence_per_family(family):
    # each family alone, padded into an envelope with headroom in EVERY
    # family: the enabled rows must be the dense derivation bit for bit
    spec = efaults.FaultSpec(**{family: 2})
    env = efaults.campaign_envelope(spec, mutation_cap=4)
    for seed in (0, 3, 99):
        _padded_equals_dense(spec, env, seed=seed)


def test_schedule_equivalence_full_spec():
    env = efaults.campaign_envelope(FULL_SPEC, mutation_cap=6)
    for seed in (0, 1, 42, 1 << 40):
        _padded_equals_dense(FULL_SPEC, env, seed=seed)


def test_schedule_equivalence_fixed_faults():
    fx = efaults.FixedFaults(
        events=(
            (100_000, "crash", 1),
            (200_000, "restart", 1),
            (200_000, "fsync_stall", 2),  # deliberate time tie
            (300_000, "skew_on", 0),
            (400_000, "part_in", 3),
        )
    )
    env = efaults.FaultEnvelope(fixed=12)
    _padded_equals_dense(fx, env)
    # and the whole emit stream through compile_device: enabled rows
    # compact to the front, so slots (and thus tie-breaks) match the
    # dense path exactly
    key = jax.random.key(5)
    params = efaults.spec_to_params(fx, env, NODES)
    dense = efaults.compile_device(fx, NODES, key, 7, 4)
    padded = efaults.compile_device(env, NODES, key, 7, 4, params=params)
    en = np.asarray(padded.enables)
    k = int(en.sum())
    assert k == len(fx.events) and en[:k].all(), "enabled rows not compacted"
    np.testing.assert_array_equal(
        np.asarray(padded.times)[:k], np.asarray(dense.times)
    )
    np.testing.assert_array_equal(
        np.asarray(padded.pays)[:k], np.asarray(dense.pays)
    )


def test_envelope_rejects_oversized_spec():
    env = efaults.campaign_envelope(efaults.FaultSpec(crashes=1))
    with pytest.raises(ValueError, match="envelope caps"):
        efaults.spec_to_params(efaults.FaultSpec(crashes=2), env, NODES)
    with pytest.raises(ValueError, match="fixed capacity"):
        efaults.spec_to_params(
            efaults.FixedFaults(events=((1, "crash", 0),)), env, NODES
        )


def test_envelope_static_gating():
    # gating is decided once per campaign envelope, not per candidate
    env = efaults.campaign_envelope(efaults.FaultSpec(skews=1))
    assert efaults.can_skew(env) and not efaults.can_stall(env)
    env = efaults.campaign_envelope(efaults.FaultSpec(fsync_stalls=1))
    assert efaults.can_stall(env) and not efaults.can_skew(env)
    assert not efaults.can_skew(efaults.FaultEnvelope())


def _raft_pair(spec, env, seeds):
    base_cfg, _ = amnesia_raft_config()
    kw = dict(time_limit_ns=1_500_000_000, max_steps=15_000)
    cfg_d = base_cfg._replace(faults=spec)
    dense = ecore.run_sweep(
        raft.workload(cfg_d), raft.engine_config(cfg_d, **kw), seeds
    )
    cfg_e = base_cfg._replace(faults=env)
    params = efaults.tile_params(
        efaults.spec_to_params(spec, env, base_cfg.num_nodes), len(seeds)
    )
    padded = ecore.run_sweep(
        raft.workload(cfg_e), raft.engine_config(cfg_e, **kw), seeds,
        params=params,
    )
    return raft.sweep_summary(dense), raft.sweep_summary(padded)


def test_sweep_summary_identical_raft():
    # end to end through the engine: the envelope sweep (durability
    # shadows ON for the whole campaign, FaultRt in the loop carry) must
    # reproduce the static path's summary exactly
    spec = FULL_SPEC._replace(aparts=1, crashes=3)
    env = efaults.campaign_envelope(spec, mutation_cap=6)
    seeds = np.arange(48, dtype=np.int64)
    s_dense, s_padded = _raft_pair(spec, env, seeds)
    assert s_dense == s_padded


def test_sweep_summary_identical_etcd():
    spec = efaults.FaultSpec(
        partitions=2, part_window_ns=1_200_000_000, part_group=(1, -1),
        skews=1,
    )
    env = efaults.campaign_envelope(spec, mutation_cap=4)
    cfg_d = etcd.EtcdConfig(faults=spec)
    cfg_e = etcd.EtcdConfig(faults=env)
    kw = dict(time_limit_ns=1_500_000_000, max_steps=15_000)
    seeds = np.arange(32, dtype=np.int64)
    dense = ecore.run_sweep(
        etcd.workload(cfg_d), etcd.engine_config(cfg_d, **kw), seeds
    )
    params = efaults.tile_params(
        efaults.spec_to_params(spec, env, cfg_e.num_nodes), len(seeds)
    )
    padded = ecore.run_sweep(
        etcd.workload(cfg_e), etcd.engine_config(cfg_e, **kw), seeds,
        params=params,
    )
    assert etcd.sweep_summary(dense) == etcd.sweep_summary(padded)


def test_run_traced_identical_through_envelope():
    # the shrink channel: a FixedFaults candidate replayed as params
    # through a width-8 envelope dispatches the identical event sequence
    target = explore.amnesia_raft_target(
        time_limit_ns=1_000_000_000, max_steps=8_000
    )
    fx = efaults.FixedFaults(
        events=((300_000_000, "crash", 0), (500_000_000, "restart", 0))
    )
    wl_d, ecfg_d = target.build(fx)
    _, trace_d = ecore.run_traced(wl_d, ecfg_d, 3)
    env = efaults.FaultEnvelope(fixed=8)
    wl_e, ecfg_e = target.build(env)
    _, trace_e = ecore.run_traced(
        wl_e, ecfg_e, 3,
        params=efaults.spec_to_params(fx, env, target.num_nodes),
    )
    for k in sorted(trace_d):
        np.testing.assert_array_equal(
            np.asarray(trace_d[k]), np.asarray(trace_e[k]), err_msg=k
        )


def _campaign_fixture():
    target = explore.amnesia_raft_target(
        time_limit_ns=1_000_000_000, max_steps=8_000
    )
    base = efaults.FaultSpec(
        crashes=2,
        crash_window_ns=800_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=200_000_000,
    )
    return target, base


def test_campaign_report_bytes_reproducible(tmp_path):
    # the hard byte-identity constraint: two runs of one campaign seed
    # write identical JSONL (the legacy compile-per-candidate A/B leg is
    # gone — spec-as-data is the only path)
    target, base = _campaign_fixture()
    ccfg = explore.CampaignConfig(
        rounds=3, seeds_per_round=32, campaign_seed=11
    )
    p_a = tmp_path / "a.jsonl"
    p_b = tmp_path / "b.jsonl"
    explore.run_campaign(target, base, ccfg, report_path=str(p_a))
    explore.run_campaign(target, base, ccfg, report_path=str(p_b))
    assert p_a.read_bytes() == p_b.read_bytes()
    assert not hasattr(explore, "use_legacy_spec_path")


def test_warmed_campaign_zero_compiles():
    # the acceptance contract: >= 16 mutated candidates, 0 XLA
    # compilations in the timed region once the envelope program is warm
    target, base = _campaign_fixture()
    ccfg = explore.CampaignConfig(
        rounds=17, seeds_per_round=32, campaign_seed=2
    )
    explore.run_campaign(target, base, ccfg._replace(rounds=1))  # warm
    with count_compiles() as c:
        result = explore.run_campaign(target, base, ccfg)
    assert len(result.records) == 17
    assert c.count == 0, f"{c.count} XLA compilations in a warmed campaign"


def test_grid_summaries_match_serial():
    # the batched (candidate x seed) grid returns the same per-candidate
    # summary dicts as serial spec-as-data sweeps of the same seed range
    target, base = _campaign_fixture()
    ccfg = explore.CampaignConfig(seeds_per_round=32)
    env = explore.target_envelope(target, base)
    rng = random.Random(3)
    specs = [base] + [explore.mutate_spec(base, rng) for _ in range(4)]
    grid = explore.sweep_candidate_grid(target, specs, ccfg, env)
    for spec, got in zip(specs, grid):
        want = explore.campaign._sweep_candidate(
            target, spec, ccfg, None, envelope=env
        )
        assert got == want


def test_warmed_grid_zero_compiles():
    target, base = _campaign_fixture()
    ccfg = explore.CampaignConfig(seeds_per_round=32)
    env = explore.target_envelope(target, base)
    rng = random.Random(4)

    def fresh(k):
        return [explore.mutate_spec(base, rng) for _ in range(k)]

    explore.sweep_candidate_grid(target, fresh(16), ccfg, env)  # warm
    with count_compiles() as c:
        explore.sweep_candidate_grid(target, fresh(16), ccfg, env)
    assert c.count == 0, f"{c.count} XLA compilations in a warmed grid"


def test_batched_campaign_runs_and_is_deterministic(tmp_path):
    # batch > 1 is a different (documented) trajectory but still a pure
    # function of the campaign seed
    target, base = _campaign_fixture()
    ccfg = explore.CampaignConfig(
        rounds=5, seeds_per_round=32, campaign_seed=6, batch=4
    )
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    ra = explore.run_campaign(target, base, ccfg, report_path=str(pa))
    rb = explore.run_campaign(target, base, ccfg, report_path=str(pb))
    assert len(ra.records) == 5
    assert pa.read_bytes() == pb.read_bytes()
    # round 0 of any batch mode is the base spec, retained
    assert ra.records[0]["spec"] == explore.spec_to_dict(base)
    assert ra.records[0]["retained"]


def test_differential_grid_matches_per_spec_outcomes():
    dcfg = explore.DifferentialConfig(seeds=16, sim_seconds=1.0)
    specs = explore.gate_specs()
    grid = explore.device_outcomes_grid(specs, dcfg)
    for spec, got in zip(specs, grid):
        assert got == explore.device_outcomes(spec, dcfg)


def test_shrink_deterministic_through_envelope():
    # ddmin re-verification through the fixed-width envelope is a pure
    # function of (spec, seed): two runs return the same minimal artifact
    target, base = _campaign_fixture()
    ccfg = explore.CampaignConfig(
        rounds=8, seeds_per_round=64, campaign_seed=1, stop_after_failures=1
    )
    result = explore.run_campaign(target, base, ccfg)
    if not result.failures:
        pytest.skip("tiny campaign budget found no failure on this config")
    spec, seed = result.failures[0]
    got = explore.shrink(target, spec, seed, max_tests=24)
    want = explore.shrink(target, spec, seed, max_tests=24)
    assert (got is None) == (want is None)
    if got is not None:
        assert got.schedule == want.schedule
        assert got.fingerprint == want.fingerprint
        assert got.tests == want.tests


def test_params_digest_distinguishes_candidates():
    from madsim_tpu.engine.checkpoint import params_digest

    env = efaults.campaign_envelope(FULL_SPEC, mutation_cap=6)
    a = efaults.spec_to_params(FULL_SPEC, env, NODES)
    b = efaults.spec_to_params(
        FULL_SPEC._replace(crashes=1), env, NODES
    )
    assert params_digest(a) == params_digest(a)
    assert params_digest(a) != params_digest(b)


def test_chunked_and_pipelined_params_match_flat():
    # the chunk drivers slice/edge-pad per-lane params exactly like the
    # seeds: a 3-chunk ragged sweep equals the one-shot sweep per lane
    target, base = _campaign_fixture()
    env = explore.target_envelope(target, base)
    wl, ecfg = target.build(env)
    n = 40  # 2 full 16-lane chunks + one ragged 8-lane tail
    seeds = np.arange(n, dtype=np.int64)
    params = efaults.tile_params(
        efaults.spec_to_params(base, env, target.num_nodes), n
    )
    flat = ecore.run_sweep(wl, ecfg, seeds, params=params)
    chunked = ecore.run_sweep_chunked(
        wl, ecfg, seeds, chunk_size=16, params=params
    )
    for a, b in zip(jax.tree.leaves(flat), jax.tree.leaves(chunked)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    from madsim_tpu.engine.checkpoint import run_sweep_pipelined

    piped = run_sweep_pipelined(
        wl, ecfg, seeds, target.summarize, chunk_size=16, params=params
    )
    whole = dict(target.summarize(flat))
    for k, v in whole.items():
        if k == "coverage_map":
            continue  # merged as a union; compare directly below
        if isinstance(v, (int, float)) and k != "seeds":
            assert piped[k] == v, k
    assert piped["coverage_map"] == whole["coverage_map"]
