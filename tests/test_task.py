"""Scheduler lifecycle battery (mirrors ref sim/task/mod.rs:787-1102 tests:
kill / restart / restart_on_panic / pause / resume / ctrl-c / abort / exit,
plus the randomized-schedule check: 10 seeds => multiple interleavings)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.futures import CancelledError
from madsim_tpu.task import DeadlockError, TimeLimitError


def test_spawn_and_join():
    rt = ms.Runtime(seed=1)

    async def child(x):
        await ms.sleep(0.01)
        return x * 2

    async def main():
        h = ms.spawn(child(21))
        return await h

    assert rt.block_on(main()) == 42


def test_join_propagates_exception():
    rt = ms.Runtime(seed=2)

    async def boom():
        raise ValueError("boom")

    async def main():
        h = ms.spawn(boom())
        with pytest.raises(ValueError):
            await h

    # a panic without restart_on_panic aborts the simulation (ref resume_unwind)
    with pytest.raises(ValueError):
        rt.block_on(main())


def test_abort_cancels_task():
    rt = ms.Runtime(seed=3)
    witness = []

    async def victim():
        try:
            await ms.sleep(100.0)
            witness.append("finished")
        finally:
            witness.append("cleanup")

    async def main():
        h = ms.spawn(victim())
        await ms.sleep(0.01)
        h.abort()
        with pytest.raises(CancelledError):
            await h

    rt.block_on(main())
    assert witness == ["cleanup"]  # finally ran, body did not complete


def test_kill_node_drops_tasks():
    rt = ms.Runtime(seed=4)
    ticks = []

    async def ticker():
        while True:
            await ms.sleep(1.0)
            ticks.append(ms.time.elapsed())

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("worker").build()
        node.spawn(ticker())
        await ms.sleep(3.5)
        h.kill(node)
        n = len(ticks)
        assert n == 3
        await ms.sleep(5.0)
        assert len(ticks) == n  # no more ticks after kill
        assert h.is_exit(node)

    rt.block_on(main())


def test_spawn_on_killed_node_fails():
    rt = ms.Runtime(seed=5)

    async def noop():
        pass

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("victim").build()
        h.kill(node)
        with pytest.raises(RuntimeError, match="killed"):
            node.spawn(noop())

    rt.block_on(main())


def test_restart_reruns_init():
    rt = ms.Runtime(seed=6)
    boots = []

    async def main():
        h = ms.current_handle()

        def init():
            async def body():
                boots.append(ms.time.elapsed())
                await ms.sleep(10_000.0)

            return body()

        node = h.create_node().name("svc").init(init).build()
        await ms.sleep(1.0)
        assert len(boots) == 1
        h.restart(node)
        await ms.sleep(1.0)
        assert len(boots) == 2

    rt.block_on(main())


def test_restart_on_panic():
    rt = ms.Runtime(seed=7)
    attempts = []

    async def main():
        h = ms.current_handle()

        def init():
            async def body():
                attempts.append(1)
                if len(attempts) < 3:
                    raise RuntimeError("flaky service crash")
                await ms.sleep(10_000.0)

            return body()

        h.create_node().name("flaky").init(init).restart_on_panic().build()
        await ms.sleep(60.0)  # restart backoff is 1-10s per attempt
        assert len(attempts) == 3

    rt.block_on(main())


def test_restart_on_panic_matching_filter():
    rt = ms.Runtime(seed=8)
    attempts = []

    async def main():
        h = ms.current_handle()

        def init():
            async def body():
                attempts.append(1)
                raise RuntimeError("unmatched kind of crash")

            return body()

        (
            h.create_node()
            .name("picky")
            .init(init)
            .restart_on_panic(matching="specific text")
            .build()
        )
        await ms.sleep(30.0)

    # crash text does not match the filter => panic propagates
    with pytest.raises(RuntimeError, match="unmatched"):
        rt.block_on(main())
    assert len(attempts) == 1


def test_pause_resume():
    rt = ms.Runtime(seed=9)
    ticks = []

    async def ticker():
        while True:
            await ms.sleep(1.0)
            ticks.append(1)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("pausable").build()
        node.spawn(ticker())
        await ms.sleep(2.5)
        assert len(ticks) == 2
        h.pause(node)
        await ms.sleep(5.0)
        assert len(ticks) == 2  # frozen while paused
        h.resume(node)
        await ms.sleep(2.1)
        assert len(ticks) >= 4

    rt.block_on(main())


def test_ctrl_c_with_handler():
    rt = ms.Runtime(seed=10)
    got = []

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("graceful").build()

        async def svc():
            from madsim_tpu.signal import ctrl_c

            await ctrl_c()
            got.append("sigint")

        node.spawn(svc())
        await ms.sleep(1.0)
        h.send_ctrl_c(node)
        await ms.sleep(1.0)
        assert got == ["sigint"]
        assert not h.is_exit(node)  # handler installed => node survives

    rt.block_on(main())


def test_ctrl_c_without_handler_kills():
    rt = ms.Runtime(seed=11)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("ungraceful").build()

        async def svc():
            await ms.sleep(10_000.0)

        node.spawn(svc())
        await ms.sleep(1.0)
        h.send_ctrl_c(node)
        assert h.is_exit(node)

    rt.block_on(main())


def test_randomized_schedule_distinct_interleavings():
    """10 seeds must produce more than one distinct interleaving
    (ref task/mod.rs:964-988)."""

    def run(seed):
        rt = ms.Runtime(seed=seed)
        order = []

        async def worker(i):
            for _ in range(3):
                await ms.sleep(0.001)
                order.append(i)

        async def main():
            hs = [ms.spawn(worker(i)) for i in range(4)]
            for h in hs:
                await h

        rt.block_on(main())
        return tuple(order)

    results = {run(seed) for seed in range(10)}
    assert len(results) > 1


def test_same_seed_same_interleaving():
    def run(seed):
        rt = ms.Runtime(seed=seed)
        order = []

        async def worker(i):
            for _ in range(5):
                await ms.sleep(0.001)
                order.append(i)

        async def main():
            hs = [ms.spawn(worker(i)) for i in range(4)]
            for h in hs:
                await h

        rt.block_on(main())
        return tuple(order)

    assert run(42) == run(42)


def test_deadlock_detection():
    rt = ms.Runtime(seed=12)

    async def main():
        from madsim_tpu.futures import Future

        await Future()  # never resolved, no timers pending

    with pytest.raises(DeadlockError):
        rt.block_on(main())


def test_time_limit():
    rt = ms.Runtime(seed=13)
    rt.set_time_limit(5.0)

    async def main():
        await ms.sleep(100.0)

    with pytest.raises(TimeLimitError):
        rt.block_on(main())


def test_exit_current_task_kills_node():
    rt = ms.Runtime(seed=14)
    after = []

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("exiter").build()

        async def svc():
            await ms.sleep(1.0)
            ms.exit_current_task()
            after.append("unreachable")

        node.spawn(svc())
        await ms.sleep(2.0)
        assert h.is_exit(node)
        assert after == []

    rt.block_on(main())


def test_metrics():
    rt = ms.Runtime(seed=15)

    async def main():
        h = ms.current_handle()
        m = h.metrics()
        assert m.num_nodes() >= 1

        async def sleeper():
            await ms.sleep(100.0)

        ms.spawn(sleeper())
        ms.spawn(sleeper())
        await ms.sleep(0.01)
        assert m.num_tasks() >= 2
        by_node = m.num_tasks_by_node()
        assert "main" in by_node

    rt.block_on(main())


def test_forbid_creating_system_thread():
    """OS threads inside a sim break determinism — blocked by default
    (ref task/mod.rs forbid_creating_system_thread; pthread interposition
    sim/task/mod.rs:761-785)."""
    import threading

    rt = ms.Runtime(seed=1)

    async def main():
        threading.Thread(target=lambda: None).start()

    with pytest.raises(RuntimeError, match="OS thread"):
        rt.block_on(main())


def test_allow_creating_system_thread():
    """set_allow_system_thread(True) opts back in (ref task/mod.rs
    allow_creating_system_thread) — the thread really runs."""
    import threading

    rt = ms.Runtime(seed=1)
    rt.set_allow_system_thread(True)
    ran = []

    async def main():
        done = threading.Event()

        def work():
            ran.append(1)
            done.set()

        threading.Thread(target=work).start()
        # wait on the REAL event (the sim clock doesn't drive OS threads);
        # bounded so a regression can't hang the suite
        assert done.wait(timeout=10.0)

    rt.block_on(main())
    assert ran == [1]
