"""Megakernel probe: in-kernel helpers match their int64/jax references,
and the full multi-step kernel reproduces the XLA engine bit-for-bit
(interpret mode — the TPU run is covered by scripts/bench_megakernel.py,
whose numbers are recorded in docs/pallas_finding.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.engine import core
from madsim_tpu.engine import megakernel as mk


def test_mulhi32_matches_int64_reference():
    rng = np.random.default_rng(7)
    xs = jnp.asarray(rng.integers(0, 1 << 32, size=256, dtype=np.uint64),
                     dtype=jnp.uint32)
    for c in (1, 5, 51, 19_000_001, 0x7FFFFFFF, 0xFFFFFFFF):
        ref = ((xs.astype(jnp.uint64) * c) >> 32).astype(jnp.uint32)
        got = mk._mulhi32(xs, c)
        assert jnp.array_equal(ref, got), c


def test_event_words_match_jax_random():
    """The in-kernel threefry must reproduce engine.rng.event_bits
    (fold_in + partitionable bits) word for word."""
    from madsim_tpu.engine.rng import event_bits, seed_key

    for seed in (0, 3, 123456):
        key = seed_key(jnp.asarray(seed, jnp.int64))
        kd = jax.random.key_data(key).astype(jnp.uint32)
        for ctr in (0, 1, 999):
            expect = event_bits(key, jnp.asarray(ctr, jnp.int32), 15)
            got = mk._event_words(
                kd[0].reshape(1, 1), kd[1].reshape(1, 1),
                jnp.full((1, 1), ctr, jnp.uint32), 15,
            )[0]
            assert jnp.array_equal(expect, got), (seed, ctr)


def test_split_join_roundtrip_and_order():
    ts = jnp.asarray(
        [0, 1, 50, 10_000_000_000, (1 << 62) - 1, int(mk.INVALID_TIME)],
        dtype=jnp.int64,
    )
    hi, lo = mk._split64(ts)
    assert jnp.array_equal(mk._join64(hi, lo), ts)
    # lexicographic signed order on the planes == int64 order
    for i in range(len(ts) - 1):
        a = bool(mk._gt64(hi[i + 1], lo[i + 1], hi[i], lo[i]))
        assert a == bool(ts[i + 1] > ts[i])


@pytest.mark.parametrize("steps,tile", [(40, 8), (17, 4)])
def test_megakernel_bit_exact_vs_xla(steps, tile):
    """Every EngineState leaf equal after `steps` events per seed."""
    wl = mk.probe_workload()
    cfg = mk.probe_config(max_steps=steps)
    seeds = jnp.arange(16, dtype=jnp.int64)
    s0 = core._init(wl, cfg, seeds)
    ref = core._drive(wl, cfg, s0)
    got = mk.run_megasweep(
        s0, steps=steps, time_limit=cfg.time_limit_ns, tile=tile,
        interpret=True,
    )
    eq = jax.tree.map(lambda a, b: bool(jnp.array_equal(a, b)), ref, got)
    assert all(jax.tree.leaves(eq)), eq


def test_megakernel_time_limit_semantics():
    """A reachable time limit must freeze seeds exactly like the XLA
    step's done/time_up masking (the budget-cut pop is still consumed)."""
    wl = mk.probe_workload()
    steps = 60
    cfg = core.EngineConfig(queue_capacity=mk._Q,
                            time_limit_ns=120_000_000,  # ~6-12 events in
                            max_steps=steps)
    seeds = jnp.arange(8, dtype=jnp.int64)
    s0 = core._init(wl, cfg, seeds)
    ref = core._drive(wl, cfg, s0)
    got = mk.run_megasweep(
        s0, steps=steps, time_limit=cfg.time_limit_ns, tile=8,
        interpret=True,
    )
    eq = jax.tree.map(lambda a, b: bool(jnp.array_equal(a, b)), ref, got)
    assert all(jax.tree.leaves(eq)), eq
    assert bool(jnp.any(got.done))  # the limit actually fired for some seeds
