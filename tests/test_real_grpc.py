"""Real-mode gRPC: the SAME service classes served over real TCP sockets
with no simulator — the analogue of madsim-tonic compiling to real tonic
without ``--cfg madsim`` (madsim-tonic/src/lib.rs:1-8)."""

import asyncio
from dataclasses import dataclass

import pytest

from madsim_tpu import real
from madsim_tpu.real import grpc


@real.codec.register
@dataclass
class HelloRequest:
    name: str
    delay_s: float = 0.0


@real.codec.register
@dataclass
class HelloReply:
    message: str


@grpc.service("helloworld.Greeter")
class Greeter:
    """Same shape as examples/greeter.py, but awaiting real wall-clock."""

    @grpc.unary
    async def say_hello(self, request: grpc.Request) -> HelloReply:
        msg: HelloRequest = request.message
        if msg.delay_s:
            await real.sleep(msg.delay_s)
        if msg.name == "error":
            raise grpc.Status.invalid_argument("invalid name: error")
        return HelloReply(message=f"Hello {msg.name}!")

    @grpc.server_streaming
    async def lots_of_replies(self, request: grpc.Request):
        msg: HelloRequest = request.message
        for i in range(3):
            yield HelloReply(message=f"{i}: Hello {msg.name}!")

    @grpc.client_streaming
    async def lots_of_greetings(self, stream: grpc.Streaming) -> HelloReply:
        names = []
        async for msg in stream:
            names.append(msg.name)
        return HelloReply(message=f"Hello {', '.join(names)}!")

    @grpc.bidi_streaming
    async def bidi_hello(self, stream: grpc.Streaming):
        async for msg in stream:
            yield HelloReply(message=f"Hello {msg.name}!")


async def _start_greeter():
    """Serve Greeter on an OS-assigned port; returns (serve_task, addr)."""
    router = grpc.Server.builder().add_service(Greeter())
    task = real.spawn(router.serve(("127.0.0.1", 0)))
    while router.bound_addr is None:
        if task.done():
            task.result()  # surface the bind failure instead of spinning
        await real.sleep(0.005)
    host, port = router.bound_addr
    return task, f"{host}:{port}"


def test_real_grpc_four_call_shapes():
    async def main():
        task, addr = await _start_greeter()
        channel = await grpc.Endpoint.from_static(f"http://{addr}").connect()
        client = grpc.ServiceClient(Greeter, channel)

        # unary
        reply = await client.say_hello(HelloRequest(name="world"))
        assert reply.into_inner().message == "Hello world!"

        # unary error -> Status with the right code
        with pytest.raises(grpc.Status) as e:
            await client.say_hello(HelloRequest(name="error"))
        assert e.value.code == grpc.Code.INVALID_ARGUMENT
        assert "invalid name" in e.value.message

        # server streaming
        stream = await client.lots_of_replies(HelloRequest(name="s"))
        got = [r.message async for r in stream]
        assert got == ["0: Hello s!", "1: Hello s!", "2: Hello s!"]

        # client streaming
        reply = await client.lots_of_greetings(
            [HelloRequest(name="a"), HelloRequest(name="b")]
        )
        assert reply.into_inner().message == "Hello a, b!"

        # bidi
        stream = await client.bidi_hello(
            [HelloRequest(name="x"), HelloRequest(name="y")]
        )
        got = [r.message async for r in stream]
        assert got == ["Hello x!", "Hello y!"]

        task.abort()

    real.Runtime().block_on(main())


def test_real_grpc_timeout_and_unavailable():
    async def main():
        task, addr = await _start_greeter()
        channel = await grpc.Endpoint.from_static(f"http://{addr}").connect()
        client = grpc.ServiceClient(Greeter, channel)

        # grpc-timeout: a 2 s handler against a 0.1 s deadline
        with pytest.raises(grpc.Status) as e:
            await client._grpc.unary(
                "/helloworld.Greeter/SayHello",
                grpc.Request(HelloRequest(name="slow", delay_s=2.0), timeout=0.1),
            )
        assert e.value.code == grpc.Code.CANCELLED
        task.abort()

        # nobody listening -> Unavailable from connect()
        with pytest.raises(grpc.Status) as e:
            await grpc.Endpoint.from_static("http://127.0.0.1:1").connect()
        assert e.value.code == grpc.Code.UNAVAILABLE

    real.Runtime().block_on(main())


def test_real_grpc_unimplemented_and_interceptor():
    async def main():
        task, addr = await _start_greeter()
        channel = await grpc.Endpoint.from_static(f"http://{addr}").connect()

        # unknown path -> Unimplemented from the router
        with pytest.raises(grpc.Status) as e:
            await grpc.Grpc(channel).unary("/helloworld.Greeter/Nope", grpc.Request(None))
        assert e.value.code == grpc.Code.UNIMPLEMENTED

        # interceptor sees (and may mutate) the outgoing request
        seen = []

        def icept(req: grpc.Request) -> grpc.Request:
            seen.append(req.message.name)
            return req

        client = grpc.ServiceClient.with_interceptor(Greeter, channel, icept)
        reply = await client.say_hello(HelloRequest(name="icept"))
        assert reply.into_inner().message == "Hello icept!"
        assert seen == ["icept"]

        # Grpc.with_interceptor must keep the real-mode subclass (its
        # asyncio spawn/timeout bindings), not fall back to the sim class
        g = grpc.Grpc(channel).with_interceptor(icept)
        assert type(g) is grpc.Grpc
        reply = await g.unary(
            "/helloworld.Greeter/SayHello",
            grpc.Request(HelloRequest(name="again"), timeout=5.0),
        )
        assert reply.into_inner().message == "Hello again!"
        task.abort()

    real.Runtime().block_on(main())


def test_real_grpc_unregistered_type_fails_loudly():
    """A message class not registered with the codec is a CLIENT-side
    CodecError, not silent corruption (wire types are declared, like the
    reference's serde derives)."""

    @dataclass
    class Secret:
        data: str

    async def main():
        task, addr = await _start_greeter()
        channel = await grpc.Endpoint.from_static(f"http://{addr}").connect()
        client = grpc.ServiceClient(Greeter, channel)
        with pytest.raises(real.codec.CodecError):
            await client.say_hello(Secret(data="x"))
        task.abort()

    real.Runtime().block_on(main())
