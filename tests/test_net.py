"""Network simulator tests (mirrors ref sim/net/endpoint.rs:365-585,
net/tcp/mod.rs:57-308, net/addr.rs:362-409, net/ipvs.rs:108-131)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.config import Config, NetConfig
from madsim_tpu.net import (
    Endpoint,
    NetSim,
    Request,
    ServiceAddr,
    TcpListener,
    TcpStream,
    UdpSocket,
    lookup_host,
)
from madsim_tpu.plugin import simulator


def two_nodes(h):
    n1 = h.create_node().name("n1").ip("10.0.1.1").build()
    n2 = h.create_node().name("n2").ip("10.0.1.2").build()
    return n1, n2


def test_endpoint_send_recv_across_nodes():
    rt = ms.Runtime(seed=1)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("10.0.1.2:100")
            data, src = await ep.recv_from(42)
            assert data == b"ping"
            await ep.send_to(src, 43, b"pong")

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)  # let the server bind
            await ep.send_to("10.0.1.2:100", 42, b"ping")
            data, src = await ep.recv_from(43)
            assert data == b"pong"
            assert src[0] == "10.0.1.2"
            return True

        n2.spawn(server())
        hc = n1.spawn(client())
        assert await hc

    rt.block_on(main())


def test_endpoint_localhost_loopback():
    rt = ms.Runtime(seed=2)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("solo").ip("10.0.9.1").build()

        async def body():
            a = await Endpoint.bind("127.0.0.1:200")
            b = await Endpoint.bind("0.0.0.0:0")
            await b.send_to("127.0.0.1:200", 7, b"local")
            data, _ = await a.recv_from(7)
            return data

        assert await node.spawn(body()) == b"local"

    rt.block_on(main())


def test_tag_matching_mailbox():
    rt = ms.Runtime(seed=3)

    async def main():
        h = ms.current_handle()
        node = h.create_node().ip("10.2.0.1").build()

        async def body():
            ep = await Endpoint.bind("10.2.0.1:300")
            tx = await Endpoint.bind("0.0.0.0:0")
            # send tags out of order; recv must match by tag
            await tx.send_to("10.2.0.1:300", 2, b"two")
            await tx.send_to("10.2.0.1:300", 1, b"one")
            d1, _ = await ep.recv_from(1)
            d2, _ = await ep.recv_from(2)
            return d1, d2

        assert await node.spawn(body()) == (b"one", b"two")

    rt.block_on(main())


def test_dns_and_lookup_host():
    rt = ms.Runtime(seed=4)

    async def main():
        net = simulator(NetSim)
        h = ms.current_handle()
        n1 = h.create_node().ip("10.3.0.1").build()
        net.add_dns_record("server.example", "10.3.0.1")

        async def body():
            addrs = await lookup_host("server.example:80")
            assert addrs == [("10.3.0.1", 80)]
            addrs = await lookup_host("localhost:1")
            assert addrs == [("127.0.0.1", 1)]

        await n1.spawn(body())

    rt.block_on(main())


def test_packet_loss_drops_messages():
    cfg = Config(net=NetConfig(packet_loss_rate=1.0))
    rt = ms.Runtime(seed=5, config=cfg)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("10.0.1.2:100")
            await ep.recv_from(1)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            await ep.send_to("10.0.1.2:100", 1, b"lost")

        hs = n2.spawn(server())
        await n1.spawn(client())
        with pytest.raises(ms.TimeoutError):
            await ms.timeout(10.0, hs)

    rt.block_on(main())


def test_clog_node_blocks_then_unclog_delivers():
    rt = ms.Runtime(seed=6)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)
        got = []

        async def server():
            ep = await Endpoint.bind("10.0.1.2:100")
            data, _ = await ep.recv_from(1)
            got.append(data)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            stream_s, stream_r = None, None
            # use a reliable channel so the clog delays rather than drops
            sender, receiver = await ep.connect1("10.0.1.2:200")
            await sender.send(b"queued")
            return receiver

        async def chan_server():
            ep = await Endpoint.bind("10.0.1.2:200")
            s, r, _src = await ep.accept1()
            msg = await r.recv()
            got.append(msg)

        n2.spawn(server())
        hcs = n2.spawn(chan_server())
        net.clog_node(n2.id)
        n1.spawn(client())
        await ms.sleep(5.0)
        assert got == []  # clogged: nothing arrives
        net.unclog_node(n2.id)
        await ms.timeout(30.0, hcs)
        assert got == [b"queued"]

    rt.block_on(main())


def test_clog_link_directional():
    rt = ms.Runtime(seed=7)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("10.0.1.2:100")
            while True:
                data, src = await ep.recv_from(1)
                await ep.send_to(src, 2, b"ack:" + data)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            await ep.send_to("10.0.1.2:100", 1, b"m1")
            data, _ = await ep.recv_from(2)
            assert data == b"ack:m1"
            # now clog only n1->n2; replies still flow but requests don't
            net.clog_link(n1.id, n2.id)
            await ep.send_to("10.0.1.2:100", 1, b"m2")
            try:
                await ms.timeout(5.0, ep.recv_from(2))
                raise AssertionError("request should have been dropped")
            except ms.TimeoutError:
                pass

        n2.spawn(server())
        await n1.spawn(client())

    rt.block_on(main())


def test_ipvs_round_robin():
    rt = ms.Runtime(seed=8)

    async def main():
        net = simulator(NetSim)
        ipvs = net.global_ipvs()
        svc = ServiceAddr.udp("10.99.0.1:80")
        ipvs.add_service(svc)
        ipvs.add_server(svc, "10.4.0.1:80")
        ipvs.add_server(svc, "10.4.0.2:80")

        h = ms.current_handle()
        b1 = h.create_node().ip("10.4.0.1").build()
        b2 = h.create_node().ip("10.4.0.2").build()
        client = h.create_node().ip("10.4.0.9").build()
        hits = {"b1": 0, "b2": 0}

        async def backend(name, ip):
            ep = await Endpoint.bind(f"{ip}:80")
            while True:
                await ep.recv_from(1)
                hits[name] += 1

        async def send_all():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            for _ in range(10):
                await ep.send_to("10.99.0.1:80", 1, b"x")
            await ms.sleep(1.0)

        b1.spawn(backend("b1", "10.4.0.1"))
        b2.spawn(backend("b2", "10.4.0.2"))
        await client.spawn(send_all())
        assert hits["b1"] == 5
        assert hits["b2"] == 5

    rt.block_on(main())


def test_rpc_call_and_handler():
    class Ping(Request):
        def __init__(self, n):
            self.n = n

    rt = ms.Runtime(seed=9)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("10.0.1.2:500")

            async def handle(req):
                return req.n + 1

            ep.add_rpc_handler(Ping, handle)
            await ms.sleep(10_000.0)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            rsp = await ep.call("10.0.1.2:500", Ping(41))
            assert rsp == 42
            rsp = await ep.call_timeout("10.0.1.2:500", Ping(1), 5.0)
            assert rsp == 2

        n2.spawn(server())
        await n1.spawn(client())

    rt.block_on(main())


def test_rpc_drop_hook():
    class Ping(Request):
        def __init__(self, n):
            self.n = n

    rt = ms.Runtime(seed=10)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)
        net.hook_rpc_req(lambda src, dst, tag, payload: True)  # drop all reqs

        async def server():
            ep = await Endpoint.bind("10.0.1.2:500")

            async def handle(req):
                return req.n

            ep.add_rpc_handler(Ping, handle)
            await ms.sleep(10_000.0)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            with pytest.raises(ms.TimeoutError):
                await ep.call_timeout("10.0.1.2:500", Ping(1), 5.0)

        n2.spawn(server())
        await n1.spawn(client())

    rt.block_on(main())


def test_tcp_echo():
    rt = ms.Runtime(seed=11)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            listener = await TcpListener.bind("10.0.1.2:700")
            stream, peer = await listener.accept()
            data = await stream.read_exact(5)
            await stream.write_all_flush(b"echo:" + data)

        async def client():
            await ms.sleep(0.1)
            stream = await TcpStream.connect("10.0.1.2:700")
            await stream.write_all_flush(b"hello")
            return await stream.read_exact(10)

        n2.spawn(server())
        assert await n1.spawn(client()) == b"echo:hello"

    rt.block_on(main())


def test_tcp_eof_on_close():
    rt = ms.Runtime(seed=12)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            listener = await TcpListener.bind("10.0.1.2:700")
            stream, _ = await listener.accept()
            await stream.write_all_flush(b"bye")
            stream.shutdown()

        async def client():
            await ms.sleep(0.1)
            stream = await TcpStream.connect("10.0.1.2:700")
            assert await stream.read_exact(3) == b"bye"
            assert await stream.read(10) == b""  # EOF

        n2.spawn(server())
        await n1.spawn(client())

    rt.block_on(main())


def test_tcp_connection_refused():
    rt = ms.Runtime(seed=13)

    async def main():
        h = ms.current_handle()
        n1, _n2 = two_nodes(h)

        async def client():
            with pytest.raises(ConnectionRefusedError):
                await TcpStream.connect("10.0.1.2:999")

        await n1.spawn(client())

    rt.block_on(main())


def test_kill_server_breaks_connection():
    rt = ms.Runtime(seed=14)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            listener = await TcpListener.bind("10.0.1.2:700")
            stream, _ = await listener.accept()
            await stream.write_all_flush(b"hi")
            await ms.sleep(10_000.0)

        async def client():
            await ms.sleep(0.1)
            stream = await TcpStream.connect("10.0.1.2:700")
            assert await stream.read_exact(2) == b"hi"
            await ms.sleep(1.0)  # server gets killed here
            with pytest.raises(ConnectionResetError):
                while True:
                    data = await stream.read(10)
                    if data == b"":
                        raise ConnectionResetError("eof")

        n2.spawn(server())
        hc = n1.spawn(client())
        await ms.sleep(0.5)
        h.kill(n2)
        await hc

    rt.block_on(main())


def test_udp_socket():
    rt = ms.Runtime(seed=15)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def server():
            sock = await UdpSocket.bind("10.0.1.2:800")
            data, src = await sock.recv_from()
            await sock.send_to(b"pong:" + data, src)

        async def client():
            sock = await UdpSocket.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            await sock.send_to(b"ping", "10.0.1.2:800")
            data, _ = await sock.recv_from()
            return data

        n2.spawn(server())
        assert await n1.spawn(client()) == b"pong:ping"

    rt.block_on(main())


def test_bind_ephemeral_and_conflict():
    rt = ms.Runtime(seed=16)

    async def main():
        h = ms.current_handle()
        node = h.create_node().ip("10.5.0.1").build()

        async def body():
            a = await Endpoint.bind("10.5.0.1:0")
            assert a.local_addr()[1] >= 32768
            b = await Endpoint.bind("10.5.0.1:9000")
            with pytest.raises(OSError, match="in use"):
                await Endpoint.bind("10.5.0.1:9000")
            b.close()
            await Endpoint.bind("10.5.0.1:9000")  # rebind after close

        await node.spawn(body())

    rt.block_on(main())


def test_reset_node_frees_ports():
    rt = ms.Runtime(seed=17)

    async def main():
        h = ms.current_handle()
        node = h.create_node().ip("10.6.0.1").build()

        async def body():
            await Endpoint.bind("10.6.0.1:9000")
            await ms.sleep(10_000.0)

        node.spawn(body())
        await ms.sleep(0.1)
        h.restart(node)

        async def rebind():
            await Endpoint.bind("10.6.0.1:9000")

        await ms.sleep(0.1)
        await node.spawn(rebind())

    rt.block_on(main())


def test_net_stat_counts_messages():
    rt = ms.Runtime(seed=18)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("10.0.1.2:100")
            while True:
                await ep.recv_from(1)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ms.sleep(0.1)
            for _ in range(3):
                await ep.send_to("10.0.1.2:100", 1, b"x")
            await ms.sleep(1.0)

        n2.spawn(server())
        await n1.spawn(client())
        assert net.stat().msg_count >= 3

    rt.block_on(main())


def test_auto_ip_no_collision_with_user_ips():
    rt = ms.Runtime(seed=19)

    async def main():
        h = ms.current_handle()
        # claim an address in the auto-assign range, then force auto-assign
        h.create_node().ip("10.200.0.2").build()
        auto = h.create_node().build()  # id 2 would auto-map into 10.200.x
        from madsim_tpu.plugin import simulator

        ip = simulator(NetSim).get_ip(auto.id)
        assert ip is not None and ip != "10.200.0.2"

    rt.block_on(main())


def test_finished_connections_unregister():
    rt = ms.Runtime(seed=20)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)

        async def server():
            listener = await TcpListener.bind("10.0.1.2:700")
            while True:
                stream, _ = await listener.accept()
                while await stream.read(100):
                    pass  # drain to EOF
                stream.close()

        async def client_once():
            stream = await TcpStream.connect("10.0.1.2:700")
            await stream.write_all_flush(b"x")
            stream.close()
            await ms.sleep(0.5)

        n2.spawn(server())
        await ms.sleep(0.1)
        for _ in range(10):
            await n1.spawn(client_once())
        await ms.sleep(2.0)
        # closed+drained pipes must not accumulate forever
        assert len(net._node_pipes[n1.id]) + len(net._node_pipes[n2.id]) < 30

    rt.block_on(main())


def test_tcp_partition_recovery():
    """Port of the reference's disconnect_and_recovery
    (net/tcp/mod.rs:102-180): a clogged server refuses connects; after
    unclogging a connection establishes; a mid-stream link partition
    delays (not drops) flushed writes, which arrive once the partition
    heals — the reliable-channel backoff-retry path."""
    rt = ms.Runtime(seed=21)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)  # n2 = server 10.0.1.2

        async def server():
            listener = await TcpListener.bind("10.0.1.2:900")
            stream, _peer = await listener.accept()
            await stream.write_all(b"hello world")
            await stream.flush()
            # phase 4: write during the link partition; delivery rides
            # the backoff retry after the scheduled unclog
            await ms.sleep(1.0)
            await stream.write_all(b"after heal")
            await stream.flush()

        async def client():
            # phase 2: server clogged — connect cannot complete
            with pytest.raises(ms.TimeoutError):
                await ms.timeout(1.0, TcpStream.connect("10.0.1.2:900"))
            # phase 3: unclogged — connect + first read succeed
            net.unclog_node(n2.id)
            stream = await TcpStream.connect("10.0.1.2:900")
            assert await stream.read_exact(11) == b"hello world"
            # phase 4: partition both directions; heal after 3 s
            net.clog_link(n1.id, n2.id)
            net.clog_link(n2.id, n1.id)

            async def heal():
                await ms.sleep(3.0)
                net.unclog_link(n1.id, n2.id)
                net.unclog_link(n2.id, n1.id)

            ms.spawn(heal())
            t0 = h.time.now_ns
            assert await stream.read_exact(10) == b"after heal"
            # the heal fires exactly 3 s after t0, so a correct run can
            # never deliver earlier
            assert h.time.now_ns - t0 >= int(3.0e9)

        n2.spawn(server())
        net.clog_node(n2.id)
        task = n1.spawn(client())
        await task

    rt.block_on(main())


def test_tcp_connect_through_ipvs():
    """TCP connects through a virtual service address, balanced to a
    real server (ref net/tcp/mod.rs:197-308 ipvs_load_balance)."""
    rt = ms.Runtime(seed=22)

    async def main():
        h = ms.current_handle()
        net = simulator(NetSim)
        n1, n2 = two_nodes(h)
        ipvs = net.global_ipvs()
        svc = ServiceAddr.tcp("10.99.0.5:1000")  # virtual service IP
        ipvs.add_service(svc)
        ipvs.add_server(svc, "10.0.1.2:1000")

        async def server():
            listener = await TcpListener.bind("10.0.1.2:1000")
            stream, _ = await listener.accept()
            await stream.write_all(b"via ipvs")
            await stream.flush()

        async def client():
            await ms.sleep(0.1)
            stream = await TcpStream.connect("10.99.0.5:1000")
            assert await stream.read_exact(8) == b"via ipvs"

        n2.spawn(server())
        await n1.spawn(client())

    rt.block_on(main())


def test_receiver_drop_message_not_lost():
    """A recv that times out drops its mailbox registration; a message
    arriving afterwards must be buffered for the NEXT recv, not swallowed
    by the dead one (ref endpoint.rs receiver_drop, endpoint.rs:46-81)."""
    rt = ms.Runtime(seed=23)

    async def main():
        h = ms.current_handle()
        n1, n2 = two_nodes(h)

        async def sender():
            ep = await Endpoint.bind("10.0.1.1:700")
            await ms.sleep(2.0)  # after the receiver's timeout expires
            await ep.send_to("10.0.1.2:700", 1, b"late")

        async def receiver():
            ep = await Endpoint.bind("10.0.1.2:700")
            with pytest.raises(ms.TimeoutError):
                await ms.timeout(1.0, ep.recv_from(1))
            # dead registration dropped; the message arrives (t≈2s)
            # while no receiver is waiting, then a fresh recv gets it
            await ms.sleep(2.0)
            data, src = await ep.recv_from(1)
            assert data == b"late"
            assert src[0] == "10.0.1.1"

        n1.spawn(sender())
        await n2.spawn(receiver())

    rt.block_on(main())


def test_mailbox_drop_resolved_recv_hands_message_to_live_waiter():
    """If a message already resolved into a receiver that is then
    dropped unconsumed, it goes to the next live waiter on the tag (not
    the undelivered queue, which would strand it while that waiter
    blocks); with no waiter it returns to the FRONT of the queue."""
    from madsim_tpu.net.endpoint import Mailbox

    mb = Mailbox()
    a = mb.recv(1)
    b = mb.recv(1)
    mb.deliver(1, b"m", ("10.0.1.1", 9))
    assert a.done() and not b.done()
    mb.drop_recv(1, a)  # a was aborted before consuming
    assert b.done() and b.result() == (b"m", ("10.0.1.1", 9))

    # no live waiter: requeued at the front, ahead of later arrivals
    c = mb.recv(2)
    mb.deliver(2, b"first", ("10.0.1.1", 9))
    mb.deliver(2, b"second", ("10.0.1.1", 9))
    mb.drop_recv(2, c)
    assert mb.recv(2).result()[0] == b"first"
    assert mb.recv(2).result()[0] == b"second"
