"""Determinism core tests (mirrors ref sim/rand.rs:262-331 and the
determinism-check driver runtime/mod.rs:178-202)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.rand import GlobalRng, NondeterminismError, mix64


def test_global_rng_reproducible():
    a = GlobalRng(seed=123)
    b = GlobalRng(seed=123)
    assert [a.next_u64() for _ in range(10)] == [b.next_u64() for _ in range(10)]
    c = GlobalRng(seed=124)
    assert a.next_u64() != c.next_u64()


def test_gen_range_bounds():
    rng = GlobalRng(seed=1)
    for _ in range(1000):
        v = rng.gen_range(10, 20)
        assert 10 <= v < 20
    with pytest.raises(ValueError):
        rng.gen_range(5, 5)


def test_mix64_stable():
    assert mix64(0) == mix64(0)
    assert mix64(1) != mix64(2)


def test_stdlib_random_interposed_deterministic():
    """random.random() inside the sim is seeded (the getrandom analogue,
    ref rand.rs:197-241)."""

    def run(seed):
        rt = ms.Runtime(seed=seed)

        async def main():
            import random
            import uuid

            return (
                random.random(),
                random.randint(0, 1000),
                str(uuid.uuid4()),
            )

        return rt.block_on(main())

    assert run(1) == run(1)
    assert run(1) != run(2)


def test_stdlib_time_interposed():
    rt = ms.Runtime(seed=3)

    async def main():
        import time as stdtime

        t0 = stdtime.monotonic()
        await ms.sleep(5.0)
        return stdtime.monotonic() - t0

    dt = rt.block_on(main())
    assert 5.0 <= dt < 5.01  # virtual, not wall time


def test_datetime_interposed():
    """datetime.datetime.now()/date.today() inside the sim read the virtual
    clock (the clock_gettime analogue, ref sim/time/system_time.rs:4-113);
    outside, the real classes are restored."""
    import datetime

    real_cls = datetime.datetime

    def run(seed):
        rt = ms.Runtime(seed=seed)

        async def main():
            import datetime as dt

            a = dt.datetime.now()
            await ms.sleep(90.0)
            b = dt.datetime.now()
            assert 90.0 <= (b - a).total_seconds() < 90.01  # virtual time
            assert dt.date.today() == a.date()
            return a.isoformat(), dt.datetime.utcnow().isoformat()

        return rt.block_on(main())

    assert run(11) == run(11)
    assert run(11) != run(12)
    assert datetime.datetime is real_cls  # restored outside the sim


def test_datetime_pre_import_alias_rebound():
    """An alias bound by ``from datetime import datetime`` BEFORE the sim
    starts must still read the virtual clock inside the sim (the install
    scan rebinds module attributes holding the real classes, freezegun-
    style) and be the real class again afterwards. Modeled as the real
    flow: a module imported — with its import-time aliases — before any
    Runtime exists (earlier sims in this process notwithstanding: a NEW
    sys.modules entry is always scanned)."""
    import sys
    import types

    import datetime as real_dt

    # simulate `import user_mod` where user_mod.py did
    # `from datetime import datetime, date` at import time
    user_mod = types.ModuleType("fake_user_mod_alias_test")
    user_mod.pre_datetime = real_dt.datetime
    user_mod.pre_date = real_dt.date
    sys.modules[user_mod.__name__] = user_mod

    def run(seed):
        rt = ms.Runtime(seed=seed)

        async def main():
            a = user_mod.pre_datetime.now()
            await ms.sleep(120.0)
            b = user_mod.pre_datetime.now()
            assert 120.0 <= (b - a).total_seconds() < 120.01  # virtual
            # compare against b (same instant) — a is 120 virtual seconds
            # earlier and could sit on the far side of midnight
            assert user_mod.pre_date.today() == b.date()
            return a.isoformat()

        return rt.block_on(main())

    try:
        assert run(31) == run(31)  # deterministic
        assert run(31) != run(32)  # seed-dependent (randomized base time)
        # restored after the sim: the alias is the real class again
        assert user_mod.pre_datetime is real_dt.datetime
        assert user_mod.pre_date is real_dt.date
    finally:
        del sys.modules[user_mod.__name__]


def test_datetime_isinstance_inside_sim():
    """The swapped classes must not change isinstance/issubclass dispatch:
    a sim datetime is an instance of datetime.date (datetime ⊂ date), and
    objects created before the swap are instances of the swapped classes
    (freezegun-style delegating metaclass)."""
    import datetime

    pre_sim = datetime.datetime(2020, 1, 1)
    rt = ms.Runtime(seed=7)

    async def main():
        import datetime as dt

        now = dt.datetime.now()
        assert isinstance(now, dt.datetime)
        assert isinstance(now, dt.date)  # the classic serializer dispatch
        assert isinstance(pre_sim, dt.datetime)
        assert isinstance(pre_sim, dt.date)
        assert issubclass(dt.datetime, dt.date)
        assert isinstance(dt.date.today(), dt.date)
        assert not isinstance(dt.date.today(), dt.datetime)

    rt.block_on(main())


def test_interpose_restored_outside_sim():
    import random
    import time as stdtime

    rt = ms.Runtime(seed=4)

    async def main():
        pass

    rt.block_on(main())
    # outside the sim the real functions are back
    assert stdtime.time() > 1_700_000_000  # actual wall clock (>2023)
    random.seed(99)
    x = random.random()
    random.seed(99)
    assert random.random() == x


def test_thread_spawn_blocked_in_sim():
    rt = ms.Runtime(seed=5)

    async def main():
        import threading

        t = threading.Thread(target=lambda: None)
        with pytest.raises(RuntimeError, match="deterministic"):
            t.start()

    rt.block_on(main())


def test_check_determinism_passes_for_deterministic_workload():
    async def workload():
        import random

        total = 0.0
        for _ in range(10):
            await ms.sleep(random.uniform(0.001, 0.1))
            total += random.random()
        return total

    ms.Runtime.check_determinism(42, workload)


def test_check_determinism_catches_wall_clock_leak():
    state = {"runs": 0}

    async def workload():
        state["runs"] += 1
        # leak real nondeterminism into the control flow on the 2nd run
        n = 3 if state["runs"] == 1 else 5
        for _ in range(n):
            ms.rand.random()

    with pytest.raises(NondeterminismError):
        ms.Runtime.check_determinism(7, workload)


def test_buggify_default_off_and_distribution():
    rt = ms.Runtime(seed=8)

    async def main():
        assert not ms.buggify.is_enabled()
        assert not ms.buggify.buggify()
        ms.buggify.enable()
        hits = sum(ms.buggify.buggify() for _ in range(2000))
        # 25% nominal (ref buggify.rs:8-20)
        assert 400 < hits < 600
        ms.buggify.disable()
        assert not ms.buggify.buggify()

    rt.block_on(main())


def test_buggify_enabled_scope_restores_and_nests():
    """The context-manager gate: scoped enable (with optional prob
    override) restores the prior state on exit — including across
    nesting and exceptions — so buggified sections never leak."""
    rt = ms.Runtime(seed=9)

    async def main():
        assert not ms.buggify.is_enabled()
        with ms.buggify.enabled():
            assert ms.buggify.is_enabled()
            # re-entrant: the inner scope's prob override unwinds to the
            # outer scope's view, then fully off at the end
            with ms.buggify.enabled(prob=1.0):
                assert ms.buggify.buggify()  # fires always at prob=1
            assert ms.buggify.is_enabled()
            hits = sum(ms.buggify.buggify() for _ in range(2000))
            assert 400 < hits < 600  # back on the 25% default
        assert not ms.buggify.is_enabled()
        assert not ms.buggify.buggify()
        # exception-safe: the gate state survives a raising scope
        try:
            with ms.buggify.enabled(prob=1.0):
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert not ms.buggify.is_enabled()
        assert ms.current_handle().rng.buggify_prob == 0.25

    rt.block_on(main())


def test_seed_is_exposed():
    rt = ms.Runtime(seed=31337)
    assert rt.seed == 31337

    async def main():
        return ms.current_handle().seed

    assert rt.block_on(main()) == 31337


def test_cpu_count_reports_node_cores():
    """os.cpu_count inside a sim task = the node's configured cores
    (ref sched_getaffinity/sysconf interposition, task/mod.rs:707-760)."""
    import os

    import madsim_tpu as ms

    rt = ms.Runtime(seed=5)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("big").cores(16).build()

        async def probe():
            return os.cpu_count()

        assert await node.spawn(probe()) == 16
        assert os.cpu_count() == 1  # main node default

    rt.block_on(main())
    assert isinstance(os.cpu_count(), int)  # restored outside the sim
