"""Test environment: force JAX onto CPU with 8 virtual devices so sharding
tests run without TPU hardware (the driver separately dry-runs multichip).

The helper is loaded by file path — NOT via ``import madsim_tpu`` — so no
package ``__init__`` code (which could some day import jax) runs before the
environment is forced. ``apply_in_process`` additionally covers machines
whose sitecustomize imports jax at interpreter startup, before conftest.
"""

import importlib.util
import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

_spec = importlib.util.spec_from_file_location(
    "_cpu_mesh_env", os.path.join(_repo, "madsim_tpu", "_cpu_mesh_env.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)

_mod.force_cpu_mesh_env(os.environ, 8)
_mod.apply_in_process()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight end-to-end tests excluded from the tier-1 "
        "budgeted run (-m 'not slow'); `make test`/`make stest` and the "
        "matching smoke gates still cover them",
    )
