"""Test environment: force JAX onto CPU with 8 virtual devices so sharding
tests run without TPU hardware (the driver separately dry-runs multichip).

Must run before any ``import jax`` in test modules — pytest imports conftest
first, so setting the env here is sufficient.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
