"""Genuine gRPC wire interop: the framework's real mode speaking actual
gRPC (HTTP/2 + protobuf via the installed grpcio) in BOTH directions —
a stock grpcio client calling a madsim-served greeter, and a madsim
typed client calling a stock grpcio server. The analogue of the
reference's std mode BEING real tonic (madsim-tonic/src/lib.rs:1-8;
madsim-tonic-build/src/prost.rs:599-680 emits real tonic codegen), where
the same app binary interoperates with any gRPC ecosystem peer.

The "stock" sides below use grpcio's standard multicallable /
``method_handlers_generic_handler`` APIs with the protogen-compiled real
protobuf messages — exactly what grpcio's generated stubs expand to
(grpcio-tools is not in this image to generate them)."""

import os
import tempfile

import pytest

grpcio = pytest.importorskip("grpc")

from grpc import aio as grpc_aio  # noqa: E402

from madsim_tpu import real  # noqa: E402
from madsim_tpu.grpc import protogen  # noqa: E402
from madsim_tpu.real import grpc  # noqa: E402

PROTO = """
syntax = "proto3";
package interopwire;

message HelloRequest { string name = 1; }
message HelloReply { string message = 1; }

service Greeter {
  rpc SayHello (HelloRequest) returns (HelloReply);
  rpc LotsOfReplies (HelloRequest) returns (stream HelloReply);
  rpc LotsOfGreetings (stream HelloRequest) returns (HelloReply);
  rpc BidiHello (stream HelloRequest) returns (stream HelloReply);
}

// acronym method names do not survive a snake->camel round trip
// (GetTPUInfo -> get_tpu_info -> GetTpuInfo), so the wire tier must use
// the literal descriptor names
service Acronym {
  rpc GetTPUInfo (HelloRequest) returns (HelloReply);
}
"""

_pkg_cache = {}


def _pkg():
    """Compile once per process (protobuf's descriptor pool can't hold
    two versions of one file)."""
    if "pkg" not in _pkg_cache:
        d = tempfile.mkdtemp(prefix="interop_wire_proto")
        path = os.path.join(d, "interopwire.proto")
        with open(path, "w") as f:
            f.write(PROTO)
        _pkg_cache["pkg"] = protogen.compile_protos(path)
    return _pkg_cache["pkg"]


def _greeter_cls(pkg):
    HelloReply = pkg.messages["interopwire.HelloReply"]

    @pkg.implement("interopwire.Greeter")
    class Greeter:
        async def say_hello(self, request):
            msg = request.message
            if msg.name == "error":
                raise grpc.Status.invalid_argument("invalid name: error")
            if msg.name == "slow":
                await real.sleep(5.0)
            return HelloReply(message=f"Hello {msg.name}!")

        async def lots_of_replies(self, request):
            for i in range(3):
                yield HelloReply(message=f"{i}: Hello {request.message.name}!")

        async def lots_of_greetings(self, stream):
            names = [m.name async for m in stream]
            return HelloReply(message=f"Hello {', '.join(names)}!")

        async def bidi_hello(self, stream):
            async for m in stream:
                yield HelloReply(message=f"Hello {m.name}!")

    return Greeter


async def _start_wire_greeter(pkg):
    """madsim real-mode greeter on a real gRPC port; (task, 'host:port')."""
    router = grpc.GrpcioServer.builder().add_service(_greeter_cls(pkg)())
    task = real.spawn(router.serve(("127.0.0.1", 0)))
    while router.bound_addr is None:
        if task.done():
            task.result()
        await real.sleep(0.005)
    host, port = router.bound_addr
    return task, f"{host}:{port}"


def test_stock_grpcio_client_calls_madsim_server():
    """Direction A: a STOCK grpcio client (plain multicallables over
    grpc.aio.insecure_channel) calls the madsim-served greeter — all four
    call shapes plus status-code mapping."""
    pkg = _pkg()
    HelloRequest = pkg.messages["interopwire.HelloRequest"]
    HelloReply = pkg.messages["interopwire.HelloReply"]

    async def main():
        task, addr = await _start_wire_greeter(pkg)
        async with grpc_aio.insecure_channel(addr) as ch:
            # unary
            say_hello = ch.unary_unary(
                "/interopwire.Greeter/SayHello",
                request_serializer=HelloRequest.SerializeToString,
                response_deserializer=HelloReply.FromString,
            )
            reply = await say_hello(HelloRequest(name="world"))
            assert reply.message == "Hello world!"

            # handler Status -> real wire status code
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await say_hello(HelloRequest(name="error"))
            assert e.value.code() == grpcio.StatusCode.INVALID_ARGUMENT
            assert "invalid name" in e.value.details()

            # server streaming
            lots = ch.unary_stream(
                "/interopwire.Greeter/LotsOfReplies",
                request_serializer=HelloRequest.SerializeToString,
                response_deserializer=HelloReply.FromString,
            )
            got = [r.message async for r in lots(HelloRequest(name="s"))]
            assert got == ["0: Hello s!", "1: Hello s!", "2: Hello s!"]

            # client streaming
            greetings = ch.stream_unary(
                "/interopwire.Greeter/LotsOfGreetings",
                request_serializer=HelloRequest.SerializeToString,
                response_deserializer=HelloReply.FromString,
            )
            reply = await greetings(
                iter([HelloRequest(name="a"), HelloRequest(name="b")])
            )
            assert reply.message == "Hello a, b!"

            # bidi
            bidi = ch.stream_stream(
                "/interopwire.Greeter/BidiHello",
                request_serializer=HelloRequest.SerializeToString,
                response_deserializer=HelloReply.FromString,
            )
            call = bidi(iter([HelloRequest(name="x"), HelloRequest(name="y")]))
            got = [r.message async for r in call]
            assert got == ["Hello x!", "Hello y!"]

            # unknown method -> UNIMPLEMENTED from the generic router
            nope = ch.unary_unary(
                "/interopwire.Greeter/Nope",
                request_serializer=HelloRequest.SerializeToString,
                response_deserializer=HelloReply.FromString,
            )
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await nope(HelloRequest(name="?"))
            assert e.value.code() == grpcio.StatusCode.UNIMPLEMENTED

            # client-set deadline enforced against a slow handler
            with pytest.raises(grpc_aio.AioRpcError) as e:
                await say_hello(HelloRequest(name="slow"), timeout=0.2)
            assert e.value.code() == grpcio.StatusCode.DEADLINE_EXCEEDED
        task.abort()

    real.Runtime().block_on(main())


def _stock_server_handler(pkg):
    """A STOCK grpcio server implementation of the greeter: plain
    method_handlers_generic_handler, no madsim code on this side."""
    HelloReply = pkg.messages["interopwire.HelloReply"]
    HelloRequest = pkg.messages["interopwire.HelloRequest"]

    async def say_hello(request, context):
        if request.name == "error":
            await context.abort(
                grpcio.StatusCode.FAILED_PRECONDITION, "stock server says no"
            )
        return HelloReply(message=f"Stock hello {request.name}!")

    async def lots_of_replies(request, context):
        for i in range(2):
            yield HelloReply(message=f"{i}: stock {request.name}")

    async def lots_of_greetings(request_iterator, context):
        names = [m.name async for m in request_iterator]
        return HelloReply(message=f"Stock hello {'+'.join(names)}!")

    async def bidi_hello(request_iterator, context):
        async for m in request_iterator:
            yield HelloReply(message=f"stock {m.name}")

    ser = HelloReply.SerializeToString
    deser = HelloRequest.FromString
    return grpcio.method_handlers_generic_handler(
        "interopwire.Greeter",
        {
            "SayHello": grpcio.unary_unary_rpc_method_handler(
                say_hello, request_deserializer=deser, response_serializer=ser
            ),
            "LotsOfReplies": grpcio.unary_stream_rpc_method_handler(
                lots_of_replies, request_deserializer=deser,
                response_serializer=ser,
            ),
            "LotsOfGreetings": grpcio.stream_unary_rpc_method_handler(
                lots_of_greetings, request_deserializer=deser,
                response_serializer=ser,
            ),
            "BidiHello": grpcio.stream_stream_rpc_method_handler(
                bidi_hello, request_deserializer=deser,
                response_serializer=ser,
            ),
        },
    )


def test_madsim_client_calls_stock_grpcio_server():
    """Direction B: the madsim typed client (pkg.stub + GrpcioServiceClient)
    calls a STOCK grpcio server — all four call shapes, status mapping,
    interceptor, and deadline semantics."""
    pkg = _pkg()
    HelloRequest = pkg.messages["interopwire.HelloRequest"]

    async def main():
        server = grpc_aio.server()
        server.add_generic_rpc_handlers((_stock_server_handler(pkg),))
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()

        channel = grpc.GrpcioChannel(f"127.0.0.1:{port}")
        client = grpc.GrpcioServiceClient(pkg.stub("interopwire.Greeter"), channel)

        # unary
        reply = await client.say_hello(HelloRequest(name="world"))
        assert reply.into_inner().message == "Stock hello world!"

        # wire status -> this framework's Status with the mapped code
        with pytest.raises(grpc.Status) as e:
            await client.say_hello(HelloRequest(name="error"))
        assert e.value.code == grpc.Code.FAILED_PRECONDITION
        assert "stock server" in e.value.message

        # server streaming
        stream = await client.lots_of_replies(HelloRequest(name="s"))
        got = [r.message async for r in stream]
        assert got == ["0: stock s", "1: stock s"]

        # client streaming
        reply = await client.lots_of_greetings(
            [HelloRequest(name="a"), HelloRequest(name="b")]
        )
        assert reply.into_inner().message == "Stock hello a+b!"

        # bidi
        stream = await client.bidi_hello(
            [HelloRequest(name="x"), HelloRequest(name="y")]
        )
        got = [r.message async for r in stream]
        assert got == ["stock x", "stock y"]

        # interceptor sees the outgoing request (same surface as sim mode)
        seen = []

        def icept(req):
            seen.append(req.message.name)
            return req

        iclient = grpc.GrpcioServiceClient(
            pkg.stub("interopwire.Greeter"), channel, icept
        )
        reply = await iclient.say_hello(HelloRequest(name="icept"))
        assert reply.into_inner().message == "Stock hello icept!"
        assert seen == ["icept"]

        # nobody listening -> UNAVAILABLE as this framework's Status
        dead = grpc.GrpcioChannel("127.0.0.1:1")
        dclient = grpc.GrpcioServiceClient(pkg.stub("interopwire.Greeter"), dead)
        with pytest.raises(grpc.Status) as e:
            await dclient.say_hello(grpc.Request(HelloRequest(name="x"),
                                                 timeout=1.0))
        assert e.value.code in (grpc.Code.UNAVAILABLE, grpc.Code.DEADLINE_EXCEEDED)
        await dead.close()

        await channel.close()
        await server.stop(None)

    real.Runtime().block_on(main())


def test_madsim_client_to_madsim_grpcio_server_round_trip():
    """Self-interop over the genuine wire: madsim typed client <-> madsim
    GrpcioServer, with the grpc-timeout surface mapping to a real wire
    deadline."""
    pkg = _pkg()
    HelloRequest = pkg.messages["interopwire.HelloRequest"]

    async def main():
        task, addr = await _start_wire_greeter(pkg)
        channel = grpc.GrpcioChannel(addr)
        client = grpc.GrpcioServiceClient(pkg.stub("interopwire.Greeter"), channel)

        reply = await client.say_hello(HelloRequest(name="wire"))
        assert reply.into_inner().message == "Hello wire!"

        stream = await client.lots_of_replies(HelloRequest(name="w"))
        assert len([r async for r in stream]) == 3

        # Request timeout surface -> wire deadline -> mapped Status
        with pytest.raises(grpc.Status) as e:
            await client.say_hello(
                grpc.Request(HelloRequest(name="slow"), timeout=0.2)
            )
        assert e.value.code == grpc.Code.DEADLINE_EXCEEDED

        await channel.close()
        task.abort()

    real.Runtime().block_on(main())


def test_acronym_method_names_use_literal_wire_path():
    """The wire path segment is the LITERAL proto method name, both on the
    typed client and in server-side routing — a stock peer calling
    /interopwire.Acronym/GetTPUInfo must reach the handler, and the typed
    client must emit that exact path (camel() would produce GetTpuInfo)."""
    pkg = _pkg()
    HelloRequest = pkg.messages["interopwire.HelloRequest"]
    HelloReply = pkg.messages["interopwire.HelloReply"]

    @pkg.implement("interopwire.Acronym")
    class Acronym:
        async def get_tpu_info(self, request):
            return HelloReply(message=f"tpu: {request.message.name}")

    async def main():
        router = grpc.GrpcioServer.builder().add_service(Acronym())
        task = real.spawn(router.serve(("127.0.0.1", 0)))
        while router.bound_addr is None:
            await real.sleep(0.005)
        host, port = router.bound_addr
        addr = f"{host}:{port}"

        # typed client path uses the literal descriptor name
        channel = grpc.GrpcioChannel(addr)
        client = grpc.GrpcioServiceClient(pkg.stub("interopwire.Acronym"), channel)
        assert client._path("get_tpu_info") == "/interopwire.Acronym/GetTPUInfo"
        reply = await client.get_tpu_info(HelloRequest(name="v5e"))
        assert reply.into_inner().message == "tpu: v5e"
        await channel.close()

        # a stock client routing by the literal name reaches the handler
        async with grpc_aio.insecure_channel(addr) as ch:
            mc = ch.unary_unary(
                "/interopwire.Acronym/GetTPUInfo",
                request_serializer=HelloRequest.SerializeToString,
                response_deserializer=HelloReply.FromString,
            )
            reply = await mc(HelloRequest(name="stock"))
            assert reply.message == "tpu: stock"
        task.abort()

    real.Runtime().block_on(main())


def test_stream_call_setup_failure_surfaces_at_await():
    """server_streaming against a dead peer raises Status AT THE AWAIT
    (like the sim and framed tiers), not at the first message read."""
    pkg = _pkg()
    HelloRequest = pkg.messages["interopwire.HelloRequest"]

    async def main():
        dead = grpc.GrpcioChannel("127.0.0.1:1")
        client = grpc.GrpcioServiceClient(pkg.stub("interopwire.Greeter"), dead)
        with pytest.raises(grpc.Status) as e:
            await client.lots_of_replies(
                grpc.Request(HelloRequest(name="x"), timeout=1.0)
            )
        assert e.value.code in (grpc.Code.UNAVAILABLE, grpc.Code.DEADLINE_EXCEEDED)
        await dead.close()

    real.Runtime().block_on(main())


def test_wire_server_crash_mid_stream_then_recovery():
    """The tonic-example server_crash scenario over genuine wire
    (ref tonic-example/tests/test.rs:234-278): killing the server
    mid-stream surfaces a transport-level Status on the client's next
    read, calls to the dead address fail with UNAVAILABLE, and a
    restarted server serves the same service class again."""
    pkg = _pkg()
    HelloRequest = pkg.messages["interopwire.HelloRequest"]
    HelloReply = pkg.messages["interopwire.HelloReply"]

    @pkg.implement("interopwire.Greeter")
    class SlowGreeter:
        async def say_hello(self, request):
            return HelloReply(message=f"Hello {request.message.name}!")

        async def lots_of_replies(self, request):
            for i in range(100):
                yield HelloReply(message=str(i))
                await real.sleep(0.05)

        async def lots_of_greetings(self, stream):
            return HelloReply(message="n/a")

        async def bidi_hello(self, stream):
            if False:
                yield

    async def _serve():
        router = grpc.GrpcioServer.builder().add_service(SlowGreeter())
        task = real.spawn(router.serve(("127.0.0.1", 0)))
        while router.bound_addr is None:
            if task.done():
                task.result()
            await real.sleep(0.005)
        host, port = router.bound_addr
        return task, f"{host}:{port}"

    async def main():
        task, addr = await _serve()
        channel = grpc.GrpcioChannel(addr)
        client = grpc.GrpcioServiceClient(pkg.stub("interopwire.Greeter"), channel)

        stream = await client.lots_of_replies(HelloRequest(name="s"))
        first = await stream.message()
        assert first.message == "0"
        task.abort()  # kill the server mid-stream
        await real.sleep(0.1)
        with pytest.raises(grpc.Status):
            while True:
                m = await stream.message()
                if m is None:  # a clean EOF would hide the crash
                    raise AssertionError("stream ended cleanly past a crash")

        # the dead address refuses further calls with a transport Status
        with pytest.raises(grpc.Status) as e:
            await client.say_hello(
                grpc.Request(HelloRequest(name="x"), timeout=1.0)
            )
        assert e.value.code in (grpc.Code.UNAVAILABLE, grpc.Code.DEADLINE_EXCEEDED)
        await channel.close()

        # restart: the same service class serves again on a fresh port
        task2, addr2 = await _serve()
        channel2 = grpc.GrpcioChannel(addr2)
        client2 = grpc.GrpcioServiceClient(pkg.stub("interopwire.Greeter"), channel2)
        reply = await client2.say_hello(HelloRequest(name="back"))
        assert reply.into_inner().message == "Hello back!"
        await channel2.close()
        task2.abort()

    real.Runtime().block_on(main())


def test_grpcio_tier_rejects_schemaless_services():
    """Hand-decorated @service classes carry no protobuf schema; the wire
    tier refuses them by name instead of failing downstream."""

    @grpc.service("x.NoProto")
    class NoProto:
        @grpc.unary
        async def hi(self, request):
            return None

    with pytest.raises(TypeError, match="proto-derived"):
        grpc.GrpcioServer.builder().add_service(NoProto())
