"""Native simcore tests: lazy g++ build, schedule-equivalence of the C++
timer heap with the Python heapq path, and bit-exact jax.random
compatibility of the C++ threefry2x32."""

import os
import subprocess

import pytest

from madsim_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="g++ unavailable or native build failed"
)


def test_timer_heap_min_order_with_fifo_ties():
    h = native.TimerHeap()
    h.push(50, 1)
    h.push(10, 2)
    h.push(10, 3)  # same deadline: FIFO by insertion
    h.push(30, 4)
    assert len(h) == 4
    assert h.peek() == (10, 2)
    assert [h.pop() for _ in range(4)] == [(10, 2), (10, 3), (30, 4), (50, 1)]
    assert h.pop() is None


def test_ready_queue_swap_remove():
    q = native.ReadyQueue()
    for i in range(5):
        q.push(100 + i)
    # swap-remove semantics: removing idx 1 moves the last element into it
    assert q.swap_remove(1) == 101
    assert len(q) == 4
    assert q.swap_remove(1) == 104
    assert sorted(q.swap_remove(0) for _ in range(3)) == [100, 102, 103]


def test_threefry_matches_jax():
    """The native threefry must reproduce the exact (seed, ctr) → draws
    stream of engine/rng.py's event_bits — jax fold_in + partitionable
    random bits — without importing JAX."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    for seed in (0, 1, 42, 2**31):
        key = jax.random.key(seed)
        kdata = np.asarray(jax.random.key_data(key), dtype=np.uint32)
        for ctr in (0, 1, 7, 123456):
            expect = np.asarray(
                jax.random.bits(jax.random.fold_in(key, ctr), (5,), dtype=jnp.uint32)
            )
            k2 = native.fold_in(int(kdata[0]), int(kdata[1]), ctr)
            got = native.random_bits(k2[0], k2[1], 5)
            assert [int(x) for x in expect] == got, (seed, ctr)


def test_native_timer_queue_schedule_identical():
    """A full simulation under MADSIM_NATIVE=1 must produce byte-identical
    output to the default backend (the swap is schedule-transparent)."""
    script = (
        "import sys; sys.path.insert(0, '/root/repo');"
        "from examples.raft_host import run_seed;"
        "s = run_seed(123, sim_seconds=2.0);"
        "print(s['leaders_elected'], s['violations'], s['msgs'])"
    )
    outs = []
    for env_extra in ({}, {"MADSIM_NATIVE": "1"}):
        env = dict(os.environ, **env_extra)
        r = subprocess.run(
            ["python", "-c", script], capture_output=True, text=True, env=env,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1]


# ---------------------------------------------------------------- simloop


def _run_seed_digest_script() -> str:
    """Run one seed and print its full observable result tuple."""
    return (
        "import sys; sys.path.insert(0, '/root/repo');"
        "from examples.raft_host import run_seed;"
        "s = run_seed(123, sim_seconds=2.0);"
        "print(s['leaders_elected'], s['violations'], s['msgs'], s['elections'])"
    )


def test_simloop_builds():
    assert native.simloop() is not None


def test_simloop_schedule_transparent():
    """The compiled executor core (default) must produce byte-identical
    schedules to the pure-Python loop (MADSIM_NO_NATIVE=1) and to the
    older ctypes backend (MADSIM_NATIVE=1)."""
    script = _run_seed_digest_script()
    outs = []
    for env_extra in ({}, {"MADSIM_NO_NATIVE": "1"}, {"MADSIM_NATIVE": "1"}):
        env = dict(os.environ, **env_extra)
        env.pop("MADSIM_TEST_CHECK_DETERMINISM", None)
        r = subprocess.run(
            ["python", "-c", script], capture_output=True, text=True, env=env,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1] == outs[2]


def test_simloop_draw_stream_identical():
    """Draw-for-draw RNG equality (not just end results): the C loop's
    direct buffer consumption must leave _draw_count and the digest log
    exactly where the Python loop leaves them."""
    script = (
        "import sys\n"
        "sys.path.insert(0, '/root/repo')\n"
        "import madsim_tpu as ms\n"
        "async def main():\n"
        "    for _ in range(50):\n"
        "        await ms.sleep(0.01)\n"
        "        ms.rand.gen_range(0, 1000)\n"
        "rt = ms.Runtime(seed=7)\n"
        "rt.block_on(main())\n"
        "print(rt.rng._draw_count, rt.rng.next_u64())\n"
    )
    outs = []
    for env_extra in ({}, {"MADSIM_NO_NATIVE": "1"}):
        env = dict(os.environ, **env_extra)
        r = subprocess.run(
            ["python", "-c", script], capture_output=True, text=True, env=env,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1]


def test_simloop_mid_drain_enable_log_identical():
    """enable_log() called from INSIDE a running task must capture the
    same digest log natively as pure-Python: the C loop re-reads the
    log/check gate per draw site, not once per drain."""
    script = (
        "import sys\n"
        "sys.path.insert(0, '/root/repo')\n"
        "import madsim_tpu as ms\n"
        "from madsim_tpu import context\n"
        "async def main():\n"
        "    for _ in range(5):\n"
        "        await ms.sleep(0.01)\n"
        "        ms.rand.gen_range(0, 1000)\n"
        "    context.current_handle().rng.enable_log()\n"
        "    for _ in range(5):\n"
        "        await ms.sleep(0.01)\n"
        "        ms.rand.gen_range(0, 1000)\n"
        "rt = ms.Runtime(seed=11)\n"
        "rt.block_on(main())\n"
        "log = rt.rng.take_log()\n"
        "print(len(log), sum(log) & (2**64 - 1))\n"
    )
    outs = []
    for env_extra in ({}, {"MADSIM_NO_NATIVE": "1"}):
        env = dict(os.environ, **env_extra)
        r = subprocess.run(
            ["python", "-c", script], capture_output=True, text=True, env=env,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1]
    # and the mid-drain log actually captured draws (gate took effect)
    assert int(outs[0].split()[0]) > 0


def test_simloop_check_determinism_still_works():
    """Determinism log/check mode routes draws through the Python
    next_u64 (the C loop's gate), so check-determinism still passes."""
    from madsim_tpu import Builder

    async def wl():
        import madsim_tpu as ms

        for _ in range(10):
            await ms.sleep(0.01)
            ms.rand.gen_range(0, 10)

    Builder(seed=3, count=2, check_determinism=True).run(wl)


def test_simloop_mid_sim_time_limit_change_honored():
    """set_time_limit from inside the sim must behave identically on the
    compiled and pure-Python loops (the C loop re-reads the limit each
    iteration instead of snapshotting it)."""
    script = (
        "import sys; sys.path.insert(0, '/root/repo');"
        "import madsim_tpu as ms;"
        "from madsim_tpu.task import TimeLimitError\n"
        "rt = ms.Runtime(seed=5)\n"
        "async def main():\n"
        "    rt.set_time_limit(0.25)\n"
        "    await ms.sleep(100.0)\n"
        "try:\n"
        "    rt.block_on(main())\n"
        "    print('no-error')\n"
        "except TimeLimitError as e:\n"
        "    print(str(e))\n"
    )
    outs = []
    for env_extra in ({}, {"MADSIM_NO_NATIVE": "1"}):
        env = dict(os.environ, **env_extra)
        r = subprocess.run(
            ["python", "-c", script], capture_output=True, text=True, env=env,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1]
    assert "time limit exceeded" in outs[0]


def test_gc_threshold_restored_across_threads():
    """Concurrent block_on calls must not leak the relaxed GC threshold
    (refcounted raise/restore in runtime.py)."""
    import gc
    import threading

    import madsim_tpu as ms

    base = gc.get_threshold()

    def run(seed):
        rt = ms.Runtime(seed=seed)

        async def m():
            for _ in range(20):
                await ms.sleep(0.01)

        rt.block_on(m())

    ts = [threading.Thread(target=run, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert gc.get_threshold() == base
