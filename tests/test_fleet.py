"""The crash-safe fleet tier (explore/store.py + explore/orchestrator.py).

The contracts under test (docs/fleet.md): store records survive torn
writes and bit-flips (quarantined, counted, never fatal), the merged
view is a pure function of the union of valid records (min-combine —
worker-count- and crash-schedule-invariant bytes), the lease protocol
grants exactly once under races and reclaims expired leases without
resurrecting zombies, the unit plan regenerates identically in any
process, and the end-to-end loop — leased units fed into one stream,
triage + shrink per unit, regression-gate replay — produces
byte-identical merged reports across a clean run and a
dead-worker-reclaim run. The multi-process kill -9 drill lives in
scripts/fleet_smoke.py (``make fleet-smoke``).
"""

import json
import multiprocessing as mp
import os
import time

import pytest

from madsim_tpu import obs
from madsim_tpu.explore.store import (
    KIND_BUG,
    KIND_CAND,
    CorpusStore,
    canonical_bytes,
    payload_sha,
)

_P1 = {"fingerprint": "raft:f1:k2:n0", "seed": 7, "unit": 0}
_P2 = {"fingerprint": "raft:f1:k2:n0", "seed": 3, "unit": 2}


# -- record layer -----------------------------------------------------------


def test_store_roundtrip_and_stats(tmp_path):
    st = CorpusStore(str(tmp_path), worker="w0")
    st.append(KIND_BUG, "fp-a", _P1)
    st.append(KIND_CAND, "000000/00", {"unit": 0, "violations": 2})
    st.close()
    records, stats = CorpusStore(str(tmp_path), worker="r").read_records()
    assert stats == (2, 0, 0)
    assert [r["kind"] for r in records] == [KIND_BUG, KIND_CAND]
    assert records[0]["payload"] == _P1
    assert records[0]["sha"] == payload_sha(_P1)


def test_merged_min_combine_is_partition_invariant(tmp_path):
    # the same three records split over different worker logs (and with
    # a duplicate from a re-run batch) merge to identical bytes
    recs = [
        (KIND_BUG, "fp-a", _P2),
        (KIND_BUG, "fp-a", _P1),  # duplicate key: min canonical wins
        (KIND_BUG, "fp-b", {"fingerprint": "x", "seed": 1}),
        (KIND_CAND, "000000/00", {"unit": 0}),
    ]
    partitions = [
        [(0, 4)],
        [(0, 1), (1, 4)],
        [(0, 2), (2, 4)],
        [(0, 3), (0, 4)],  # overlap: the second worker re-ran everything
    ]
    views = []
    for split in partitions:
        root = str(tmp_path / f"s{len(views)}")
        for wi, (lo, hi) in enumerate(split):
            w = CorpusStore(root, worker=f"w{wi}")
            for kind, key, payload in recs[lo:hi]:
                w.append(kind, key, payload)
            w.close()
        views.append(CorpusStore(root, worker="r").merged())
    assert all(v == views[0] for v in views)
    # min-combine: _P2's canonical bytes sort below _P1's (seed 3 < 7)
    assert views[0][(KIND_BUG, "fp-a")] == _P2
    assert canonical_bytes(_P2) < canonical_bytes(_P1)


def test_store_torn_final_line_every_offset(tmp_path):
    st = CorpusStore(str(tmp_path), worker="w0")
    st.append(KIND_BUG, "fp-a", _P1)
    st.append(KIND_BUG, "fp-b", _P2)
    st.close()
    data = open(st._log_path, "rb").read()
    last_start = data.rstrip(b"\n").rfind(b"\n") + 1
    for off in range(len(data) - last_start + 1):
        with open(st._log_path, "wb") as f:
            f.write(data[: last_start + off])
        records, stats = CorpusStore(str(tmp_path), worker="r").read_records()
        whole = off >= len(data) - last_start - 1  # newline-only cuts parse
        assert len(records) == (2 if whole else 1)
        assert stats.quarantined == 0
        assert stats.truncated_logs == (0 if whole or off == 0 else 1)


def test_bitflip_quarantined_with_counter(tmp_path):
    st = CorpusStore(str(tmp_path), worker="w0")
    st.append(KIND_BUG, "fp-a", _P1)
    st.append(KIND_BUG, "fp-b", _P2)
    st.close()
    # flip one payload bit in the FIRST record: sha mismatch, interior
    data = open(st._log_path, "rb").read()
    i = data.index(b'"seed": 7')
    data = data[:i] + b'"seed": 8' + data[i + 9 :]
    open(st._log_path, "wb").write(data)
    t = obs.Telemetry()
    reader = CorpusStore(str(tmp_path), worker="r", telemetry=t)
    records, stats = reader.read_records()
    assert stats == (1, 1, 0)  # the clean record survives
    assert records[0]["payload"] == _P2
    assert t.registry.get("fleet_store_quarantined_total") == 1
    qdir = os.path.join(str(tmp_path), "quarantine")
    (qfile,) = os.listdir(qdir)
    (qrec,) = [json.loads(x) for x in open(os.path.join(qdir, qfile))]
    assert qrec["why"] == "sha mismatch" and '"seed": 8' in qrec["line"]
    # reading again quarantines again but never raises, and merged()
    # still returns the valid view
    assert reader.merged() == {(KIND_BUG, "fp-b"): _P2}


def test_malformed_interior_line_quarantined(tmp_path):
    st = CorpusStore(str(tmp_path), worker="w0")
    st.append(KIND_BUG, "fp-a", _P1)
    st.close()
    with open(st._log_path, "r+") as f:
        body = f.read()
        f.seek(0)
        f.write('{"kind": "bug", "key": "torn-by-a-dead\n' + body)
    records, stats = CorpusStore(str(tmp_path), worker="r").read_records()
    assert stats == (1, 1, 0)
    assert records[0]["payload"] == _P1


def test_duplicate_fingerprint_from_concurrent_workers(tmp_path):
    # two workers hit the same failure class; merged() keeps exactly one
    # deterministic representative regardless of append order
    for a, b in ((_P1, _P2), (_P2, _P1)):
        root = str(tmp_path / f"o{a['seed']}")
        w1 = CorpusStore(root, worker="w1")
        w1.append(KIND_BUG, a["fingerprint"], a)
        w1.close()
        w2 = CorpusStore(root, worker="w2")
        w2.append(KIND_BUG, b["fingerprint"], b)
        w2.close()
        merged = CorpusStore(root, worker="r").merged()
        assert merged == {(KIND_BUG, _P1["fingerprint"]): _P2}


# -- lease protocol ---------------------------------------------------------


def test_lease_expiry_and_reclaim_after_worker_death(tmp_path):
    dead = CorpusStore(str(tmp_path), worker="dead", ttl_s=100)
    lease = dead.try_lease(3)
    assert lease is not None
    # a live holder blocks the grant...
    peer = CorpusStore(str(tmp_path), worker="peer", ttl_s=100)
    assert peer.try_lease(3) is None
    # ...until the holder stops renewing past the TTL (simulated death:
    # backdate the lease mtime instead of sleeping out a real TTL)
    old = time.time() - 1000
    os.utime(lease.path, (old, old))
    t = obs.Telemetry()
    peer2 = CorpusStore(str(tmp_path), worker="peer2", ttl_s=100, telemetry=t)
    re = peer2.try_lease(3)
    assert re is not None and re.worker == "peer2"
    assert t.registry.get("fleet_lease_reclaimed_total") == 1
    # the zombie's renewal must report the lease LOST, not resurrect it
    assert dead.renew(lease) is False


def test_heartbeat_renewal_keeps_slow_worker_alive(tmp_path):
    slow = CorpusStore(str(tmp_path), worker="slow", ttl_s=0.25)
    vulture = CorpusStore(str(tmp_path), worker="vulture", ttl_s=0.25)
    lease = slow.try_lease(0)
    assert lease is not None
    for _ in range(4):
        time.sleep(0.1)
        assert slow.renew(lease) is True
        assert vulture.try_lease(0) is None  # never expires while renewed
    slow.mark_done(0)
    slow.release(lease)
    assert vulture.try_lease(0) is None  # done, not leasable


def test_done_unit_never_leased(tmp_path):
    st = CorpusStore(str(tmp_path), worker="w")
    lease = st.try_lease(1)
    st.mark_done(1)
    st.release(lease)
    assert st.is_done(1)
    assert st.try_lease(1) is None
    assert st.next_lease(2) is not None  # unit 0 still open
    st.mark_done(0)
    assert st.next_lease(2) is None
    assert st.all_done(2)


def _race_worker(root, name, barrier, q):
    st = CorpusStore(root, worker=name, ttl_s=60)
    barrier.wait()
    got = []
    for unit in range(4):
        lease = st.try_lease(unit)
        if lease is not None:
            got.append(unit)
    q.put((name, got))


def test_double_grant_impossible_under_racing_processes(tmp_path):
    ctx = mp.get_context("spawn")
    barrier = ctx.Barrier(2)
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_race_worker, args=(str(tmp_path), f"p{i}", barrier, q)
        )
        for i in range(2)
    ]
    for p in procs:
        p.start()
    results = dict(q.get(timeout=60) for _ in procs)
    for p in procs:
        p.join(timeout=60)
        assert p.exitcode == 0
    # every unit granted exactly once across both racing processes
    grants = results["p0"] + results["p1"]
    assert sorted(grants) == [0, 1, 2, 3]


# -- unit plan --------------------------------------------------------------


def test_plan_unit_deterministic_and_unit_local():
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.explore import CampaignConfig, plan_unit

    base = FaultSpec(crashes=1, crash_window_ns=400_000_000)
    ccfg = CampaignConfig(batch=3, campaign_seed=11)
    u2 = plan_unit(base, ccfg, 2)
    assert len(u2) == 3
    assert plan_unit(base, ccfg, 2) == u2  # regenerates identically
    assert plan_unit(base, ccfg, 0)[0] == base  # unit 0 leads with base
    assert plan_unit(base, ccfg, 3) != u2  # unit-local rng streams
    assert plan_unit(base, ccfg._replace(campaign_seed=12), 2) != u2


# -- end to end: leased stream, reclaim invariance, regression gate ---------


@pytest.mark.slow  # ~60 s of sweeps; `make fleet-smoke` drills this
# same loop harder (separate processes, real kill) in `make stest`
def test_fleet_end_to_end_reclaim_invariance_and_gate(tmp_path):
    from madsim_tpu.engine.faults import FaultSpec
    from madsim_tpu.explore import (
        CampaignConfig,
        amnesia_raft_target,
        merged_report,
        regression_gate,
        run_worker,
    )

    target = amnesia_raft_target(
        time_limit_ns=1_500_000_000, max_steps=15_000, hist_slots=0
    )
    base = FaultSpec(
        crashes=3, crash_window_ns=1_200_000_000,
        restart_lo_ns=50_000_000, restart_hi_ns=300_000_000,
    )
    ccfg = CampaignConfig(
        seeds_per_round=16, batch=2, chunk_size=16,
        campaign_seed=7, max_recorded_seeds=4,
    )
    units = 2

    root_a = str(tmp_path / "a")
    res_a = run_worker(
        target, base, ccfg, CorpusStore(root_a, worker="solo"), units
    )
    assert res_a["units"] == list(range(units))
    rep_a = merged_report(CorpusStore(root_a, worker="ra"))
    assert rep_a.count('"kind": "cand"') == units * ccfg.batch
    assert res_a["fingerprints"], "config found no bugs; gate untested"

    # a worker died mid-unit: stale unexpired-looking lease backdated to
    # expiry, torn half-record on its log — the next worker quarantines
    # nothing (torn tails are dropped), reclaims, and re-runs everything
    # to byte-identical merged bytes
    root_b = str(tmp_path / "b")
    dead = CorpusStore(root_b, worker="dead")
    lease = dead.try_lease(0)
    with open(dead._log_path, "a") as f:
        f.write('{"kind": "cand", "key": "000000/00", "payl')
    old = time.time() - 1000
    os.utime(lease.path, (old, old))
    res_b = run_worker(
        target, base, ccfg, CorpusStore(root_b, worker="live"), units,
        skip_gate=True,
    )
    assert res_b["units"] == list(range(units))
    rep_b = merged_report(CorpusStore(root_b, worker="rb"))
    assert rep_b == rep_a

    # the regression gate replays every stored bug bit-exactly
    gate = regression_gate(CorpusStore(root_a, worker="g"), target)
    assert gate["ok"], gate["mismatches"]
    assert gate["checked"] >= 1
