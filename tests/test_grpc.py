"""gRPC shim tests — port of the reference's flagship integration suite
(tonic-example/tests/test.rs, 408 lines): multi-node cluster with all four
streaming modes, client crash/restart loops, server crash mid-stream,
unimplemented fallback, interceptors, request timeout; plus balance_list.
"""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/examples")

import madsim_tpu as ms
from madsim_tpu import grpc
from greeter import Greeter, HelloReply, HelloRequest, serve

SERVER = "10.0.0.1"
ADDR = f"{SERVER}:50051"


def cluster(h, n_clients=1):
    """1 server + n client nodes with distinct IPs (ref test.rs:22-40)."""
    server = h.create_node().name("server").ip(SERVER).init(lambda: serve(ADDR)).build()
    clients = [
        h.create_node().name(f"client-{i}").ip(f"10.0.0.{i + 2}").build()
        for i in range(n_clients)
    ]
    return server, clients


async def connect():
    channel = await grpc.Endpoint.from_static(f"http://{ADDR}").connect()
    return grpc.ServiceClient(Greeter, channel)


def test_all_streaming_modes():
    rt = ms.Runtime(seed=10)

    async def main():
        h = ms.current_handle()
        _server, (client,) = cluster(h)
        await ms.sleep(0.1)

        async def run():
            c = await connect()
            # unary (test.rs:44-56)
            r = await c.say_hello(HelloRequest(name="world"))
            assert r.into_inner().message == "Hello world!"
            # unary error path
            with pytest.raises(grpc.Status) as e:
                await c.say_hello(HelloRequest(name="error"))
            assert e.value.code == grpc.Code.INVALID_ARGUMENT
            # server streaming (test.rs:58-76)
            stream = await c.lots_of_replies(HelloRequest(name="s"))
            msgs = [m.message async for m in stream]
            assert msgs == ["0: Hello s!", "1: Hello s!", "2: Hello s!"]
            # client streaming (test.rs:78-94)
            r = await c.lots_of_greetings(
                [HelloRequest(name="a"), HelloRequest(name="b")]
            )
            assert r.into_inner().message == "Hello a, b!"
            # bidi streaming (test.rs:96-119)
            stream = await c.bidi_hello([HelloRequest(name=x) for x in "xy"])
            msgs = [m.message async for m in stream]
            assert msgs == ["Hello x!", "Hello y!"]

        await client.spawn(run())

    rt.block_on(main())


def test_client_crash_loop():
    """Kill/restart a calling client 10 times; the server must keep
    serving (ref test.rs:155-202)."""
    rt = ms.Runtime(seed=11)

    async def main():
        h = ms.current_handle()
        server, _ = cluster(h, n_clients=0)

        def client_init():
            async def run():
                c = await connect()
                while True:
                    await c.say_hello(HelloRequest(name="w"))
                    await ms.sleep(0.05)

            return run()

        node = (
            h.create_node().name("crashy").ip("10.0.0.9").init(client_init).build()
        )
        await ms.sleep(0.2)
        for _ in range(10):
            await ms.sleep(ms.rand.uniform(0.05, 0.3))
            h.kill(node)
            await ms.sleep(ms.rand.uniform(0.01, 0.1))
            h.restart(node)
        # server still healthy:
        probe = h.create_node().name("probe").ip("10.0.0.8").build()

        async def check():
            c = await connect()
            r = await c.say_hello(HelloRequest(name="alive"))
            assert r.into_inner().message == "Hello alive!"

        await probe.spawn(check())

    rt.block_on(main())


def test_server_crash_mid_stream():
    """Kill the server mid-stream: in-flight stream errors Unavailable;
    after restart calls succeed (ref test.rs:234-278)."""
    rt = ms.Runtime(seed=12)

    async def main():
        h = ms.current_handle()
        server, (client,) = cluster(h)
        await ms.sleep(0.1)

        async def run():
            c = await connect()
            stream = await c.lots_of_replies(HelloRequest(name="s"))
            first = await stream.message()
            assert first.message == "0: Hello s!"
            h.kill(server)
            with pytest.raises(grpc.Status) as e:
                while await stream.message() is not None:
                    pass
            assert e.value.code == grpc.Code.UNAVAILABLE
            # new call also fails while down
            with pytest.raises((grpc.Status, OSError)):
                await c.say_hello(HelloRequest(name="down"))
            h.restart(server)
            await ms.sleep(0.2)
            r = await c.say_hello(HelloRequest(name="back"))
            assert r.into_inner().message == "Hello back!"

        await client.spawn(run())

    rt.block_on(main())


def test_unimplemented_service():
    """Unknown service/method → UNIMPLEMENTED (ref test.rs:281-318)."""
    rt = ms.Runtime(seed=13)

    @grpc.service("other.Unknown")
    class Unknown:
        @grpc.unary
        async def nope(self, request):
            return None

    async def main():
        h = ms.current_handle()
        _server, (client,) = cluster(h)
        await ms.sleep(0.1)

        async def run():
            channel = await grpc.Endpoint.from_static(f"http://{ADDR}").connect()
            c = grpc.ServiceClient(Unknown, channel)
            with pytest.raises(grpc.Status) as e:
                await c.nope(HelloRequest(name="x"))
            assert e.value.code == grpc.Code.UNIMPLEMENTED

        await client.spawn(run())

    rt.block_on(main())


def test_interceptor():
    """Client interceptor can mutate metadata and reject requests
    (ref test.rs:321-360; sim.rs:94-101)."""
    rt = ms.Runtime(seed=14)

    @grpc.service("helloworld.Echo")
    class Echo:
        @grpc.unary
        async def echo_meta(self, request: grpc.Request):
            return HelloReply(message=request.metadata.get("x-token", ""))

    async def main():
        h = ms.current_handle()
        h.create_node().name("server").ip(SERVER).init(
            lambda: grpc.Server.builder().add_service(Echo()).serve(ADDR)
        ).build()
        client = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            channel = await grpc.Endpoint.from_static(f"http://{ADDR}").connect()

            def add_token(req: grpc.Request) -> grpc.Request:
                req.metadata["x-token"] = "secret"
                return req

            c = grpc.ServiceClient.with_interceptor(Echo, channel, add_token)
            r = await c.echo_meta(HelloRequest(name="x"))
            assert r.into_inner().message == "secret"

            def reject(req: grpc.Request) -> grpc.Request:
                raise grpc.Status.permission_denied("no token")

            c2 = grpc.ServiceClient.with_interceptor(Echo, channel, reject)
            with pytest.raises(grpc.Status) as e:
                await c2.echo_meta(HelloRequest(name="x"))
            assert e.value.code == grpc.Code.PERMISSION_DENIED

        await client.spawn(run())

    rt.block_on(main())


def test_request_timeout():
    """grpc-timeout: a slow handler trips the client deadline with
    CANCELLED "Timeout expired" (ref test.rs:363-408)."""
    rt = ms.Runtime(seed=15)

    async def main():
        h = ms.current_handle()
        _server, (client,) = cluster(h)
        await ms.sleep(0.1)

        async def run():
            c = await connect()
            req = grpc.Request(HelloRequest(name="slow", delay_s=10.0), timeout=1.0)
            with pytest.raises(grpc.Status) as e:
                await c.say_hello(req)
            assert e.value.code == grpc.Code.CANCELLED
            assert "Timeout expired" in e.value.message
            # channel-level default timeout (Endpoint::timeout)
            channel = (
                await grpc.Endpoint.from_static(f"http://{ADDR}").timeout(0.5).connect()
            )
            c2 = grpc.ServiceClient(Greeter, channel)
            with pytest.raises(grpc.Status):
                await c2.say_hello(HelloRequest(name="slow", delay_s=10.0))

        await client.spawn(run())

    rt.block_on(main())


@grpc.service("helloworld.WhoAmI")
class WhoAmI:
    """Identifies which balanced backend served a call."""

    def __init__(self, tag: str = "?"):
        self.tag = tag

    @grpc.unary
    async def who(self, request):
        return HelloReply(message=self.tag)


def tagged_cluster(h, ips):
    """One WhoAmI server per ip, tagged s0, s1, ... (balance tests)."""
    for i, ip in enumerate(ips):
        h.create_node().name(f"s{i}").ip(ip).init(
            lambda i=i, ip=ip: grpc.Server.builder()
            .add_service(WhoAmI(tag=f"s{i}"))
            .serve(f"{ip}:50051")
        ).build()


def test_balance_list_round_robin_random():
    """balance_list spreads calls over endpoints at random
    (ref transport/channel.rs:294-307)."""
    rt = ms.Runtime(seed=16)

    async def main():
        h = ms.current_handle()
        tagged_cluster(h, ["10.0.1.1", "10.0.1.2", "10.0.1.3"])
        client = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            channel = grpc.Channel.balance_list(
                [grpc.Endpoint.from_static(f"http://10.0.1.{j}:50051") for j in (1, 2, 3)]
            )
            c = grpc.ServiceClient(WhoAmI, channel)
            seen = set()
            for _ in range(30):
                r = await c.who(HelloRequest(name="x"))
                seen.add(r.into_inner().message)
            assert seen == {"s0", "s1", "s2"}

        await client.spawn(run())

    rt.block_on(main())


def test_determinism_of_grpc_workload():
    """Same seed ⇒ identical RNG log for a gRPC-heavy workload."""

    def workload():
        async def main():
            h = ms.current_handle()
            _server, (client,) = cluster(h)
            await ms.sleep(0.1)

            async def run():
                c = await connect()
                for _ in range(5):
                    await c.say_hello(HelloRequest(name="d"))

            await client.spawn(run())

        return main()

    ms.Runtime.check_determinism(77, workload)


def test_invalid_address():
    """Connecting to an address nobody serves fails with an error, not a
    hang (ref test.rs:141-152)."""
    rt = ms.Runtime(seed=77)

    async def main():
        h = ms.current_handle()
        client = h.create_node().name("client").ip("10.0.0.2").build()

        async def run():
            ep = grpc.Endpoint.from_static(f"http://{ADDR}").connect_timeout(1.0)
            with pytest.raises(grpc.Status):
                await ep.connect()

        await client.spawn(run())

    rt.block_on(main())


def test_client_drops_response_stream():
    """Dropping a server-streaming response mid-stream must not wedge the
    server: it keeps serving (ref test.rs:205-232)."""
    rt = ms.Runtime(seed=78)

    async def main():
        h = ms.current_handle()
        _server, (client,) = cluster(h)
        await ms.sleep(1.0)

        async def run():
            c = await connect()
            stream = await c.lots_of_replies(HelloRequest(name="Tonic"))
            first = await stream.__anext__()
            assert first.message == "0: Hello Tonic!"
            # drop the response stream mid-flight: the server's next
            # send hits BrokenPipeError and must shut the stream down
            stream.close()
            await ms.sleep(10.0)
            # the server survives and a fresh call succeeds
            r = await c.say_hello(HelloRequest(name="Tonic"))
            assert r.into_inner().message == "Hello Tonic!"

        await client.spawn(run())

    rt.block_on(main())


def test_balance_channel_dynamic_endpoints():
    """balance_channel: endpoints inserted/removed at runtime via
    Change items steer subsequent calls (ref transport/channel.rs:335-359
    tower-discover semantics); an empty set is Unavailable."""
    rt = ms.Runtime(seed=79)

    async def main():
        h = ms.current_handle()
        tagged_cluster(h, ["10.0.1.1", "10.0.1.2"])
        client = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            channel, tx = grpc.Channel.balance_channel()
            c = grpc.ServiceClient(WhoAmI, channel)
            # empty endpoint set: Unavailable, not a hang
            with pytest.raises(grpc.Status) as e:
                await c.who(HelloRequest(name="x"))
            assert e.value.code == grpc.Code.UNAVAILABLE
            await tx.send(
                grpc.Change.insert("a", grpc.Endpoint.from_static("http://10.0.1.1:50051"))
            )
            await tx.send(
                grpc.Change.insert("b", grpc.Endpoint.from_static("http://10.0.1.2:50051"))
            )
            seen = set()
            for _ in range(20):
                seen.add((await c.who(HelloRequest(name="x"))).into_inner().message)
            assert seen == {"s0", "s1"}
            # remove one backend: traffic converges on the survivor
            await tx.send(grpc.Change.remove("a"))
            for _ in range(10):
                r = await c.who(HelloRequest(name="x"))
                assert r.into_inner().message == "s1"

        await client.spawn(run())

    rt.block_on(main())
