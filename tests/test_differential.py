"""Host↔device differential validation (madsim_tpu/explore/differential.py).

The contract under test: one FaultSpec drives the device raft model and
the host raft example over a matched (spec, seed) grid; outcome
distributions agree within tolerances; BOTH tiers' recorded election
histories check against ONE sequential spec (oracle.ElectionSpec) with
a verdict that agrees exactly with each tier's own online violation
latch; and the report is deterministic. The full 200-seed gate runs as
`make differential-smoke` — these tests exercise the machinery on small
grids.
"""

import json
import sys

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/examples")

import raft_host

from madsim_tpu import explore, replay
from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.faults import FaultSpec
from madsim_tpu.explore.differential import (
    DifferentialConfig,
    device_outcomes,
    gate_specs,
    host_outcomes,
    run_differential,
)
from madsim_tpu.models import raft
from madsim_tpu.oracle import ElectionSpec, check_history
from madsim_tpu.oracle.history import OP_ELECT, PH_INVOKE, Op


def _elect(client: int, term: int, node: int, at: int) -> Op:
    return Op(
        client=client, op=OP_ELECT, key=term, inp=node, out=0,
        invoke_ns=at, complete_ns=-1, opid=term,
    )


def test_election_spec_structural():
    """At most one leader per term — enforced structurally (election
    rows are open ops, which the WGL search may omit)."""
    from madsim_tpu.oracle.history import History

    ok = History(seed=0, ops=(
        _elect(0, 1, 0, 10), _elect(1, 2, 1, 20), _elect(0, 3, 0, 30),
    ), overflow=False, rows=3)
    assert check_history(ok, ElectionSpec()).ok
    bad = History(seed=0, ops=(
        _elect(0, 1, 0, 10), _elect(1, 1, 1, 20),
    ), overflow=False, rows=2)
    res = check_history(bad, ElectionSpec())
    assert not res.ok and "two leaders" in res.reason
    assert res.bad_index == 1


def test_device_raft_history_agrees_with_online_latch():
    """The device record hook: every lane's decoded election history is
    rejected by ElectionSpec exactly when the online election-safety
    latch fired (the amnesia sweep has both kinds of seeds)."""
    base, _ = replay.amnesia_raft_config()
    cfg = base._replace(hist_slots=64, history=64)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(
        raft.workload(cfg), ecfg, jnp.arange(96, dtype=jnp.int64)
    )
    violation = np.asarray(final.wstate.violation)
    assert violation.any(), "amnesia sweep found no violations"
    assert not violation.all()
    from madsim_tpu.oracle import decode_sweep

    spec = ElectionSpec()
    for lane, hist in enumerate(decode_sweep(final)):
        assert all(op.op == OP_ELECT for op in hist.ops)
        assert len(hist.ops) == int(np.asarray(final.wstate.elections)[lane])
        assert (not check_history(hist, spec).ok) == bool(violation[lane]), lane


def test_host_raft_emits_checkable_history():
    out = raft_host.run_seed(3, n=3, crashes=1, sim_seconds=1.5)
    hist = out["history"]
    assert len(hist.ops) == out["leaders_elected"] > 0
    assert all(op.op == OP_ELECT and op.inp == op.client for op in hist.ops)
    assert (not check_history(hist, ElectionSpec()).ok) == (
        out["violations"] > 0
    )


def test_differential_grid_passes_and_is_deterministic(tmp_path):
    """A small matched grid: the tolerance verdict holds, histories
    agree with latches on both tiers, and two in-process runs emit
    byte-identical reports."""
    dcfg = DifferentialConfig(seeds=16, sim_seconds=1.5)
    spec = gate_specs()[0]
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    r1 = run_differential([spec], dcfg, report_path=str(a))
    r2 = run_differential([spec], dcfg, report_path=str(b))
    assert a.read_bytes() == b.read_bytes()
    rec = r1["specs"][0]
    assert rec["device"]["hist_mismatch_seeds"] == 0
    assert rec["host"]["hist_mismatch_seeds"] == 0
    assert rec["device"]["hist_overflow_seeds"] == 0
    assert rec["device"]["elected_seeds"] + rec["device"]["no_leader_seeds"] == 16
    assert r1["pass"] == rec["pass"]
    # the report round-trips as canonical JSON
    assert json.loads(a.read_text()) == r1


def test_differential_outcomes_respond_to_the_fault_environment():
    """Both tiers obey the one compiled schedule: a literal full-mesh
    partition (FixedFaults — identical on both tiers for every seed)
    suppresses elections while clogged. The device horizon ends before
    the heal, so every seed stays leaderless; the host run extends one
    second past the heal (run_seed_with_plan's observation window), so
    it elects — but every recorded election lands AFTER the mesh
    unclogs."""
    from madsim_tpu.engine.faults import FixedFaults

    heal_ns = 1_500_000_000
    fixed = FixedFaults(events=(
        (10_000_000, "partition", 0),
        (10_000_001, "partition", 1),
        (10_000_002, "partition", 2),
        (heal_ns, "heal", 0),
        (heal_ns + 1, "heal", 1),
        (heal_ns + 2, "heal", 2),
    ))
    dcfg = DifferentialConfig(seeds=8, sim_seconds=1.0)
    dev = device_outcomes(fixed, dcfg)
    assert dev.no_leader_seeds == 8, dev
    for seed in range(3):
        out = raft_host.run_seed_with_spec(seed, fixed, seed, n=3, sim_seconds=1.0)
        assert out["leaders_elected"] > 0
        assert all(op.invoke_ns >= heal_ns for op in out["history"].ops)
    assert explore.run_differential is run_differential  # package export
    assert host_outcomes  # exercised by the grid test above
