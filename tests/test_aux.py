"""Aux subsystems: tokio façade, tracing (sim-identity logs + chrome
trace), and engine sweep checkpoint/resume."""

import json
import logging

import jax.numpy as jnp
import numpy as np
import pytest

import madsim_tpu as ms
from madsim_tpu import tokio, tracing
from madsim_tpu.engine import checkpoint
from madsim_tpu.engine import core as ecore
from madsim_tpu.models import raft


# -- tokio façade -----------------------------------------------------------


def test_tokio_runtime_aborts_spawned_on_shutdown():
    rt = ms.Runtime(seed=70)

    async def main():
        trt = tokio.runtime.Builder.new_multi_thread().enable_all().build()
        progress = []

        async def worker():
            try:
                while True:
                    await tokio.time.sleep(0.01)
                    progress.append(1)
            finally:
                progress.append("dropped")

        trt.spawn(worker())
        await ms.sleep(0.1)
        assert len(progress) > 3
        trt.shutdown()
        await ms.sleep(0.1)
        assert progress[-1] == "dropped"
        n_after = len(progress)
        await ms.sleep(0.1)
        assert len(progress) == n_after  # really stopped
        with pytest.raises(RuntimeError, match="shut down"):
            trt.spawn(worker())

    rt.block_on(main())


def test_tokio_block_on_is_an_error_in_sim():
    rt = ms.Runtime(seed=71)

    async def main():
        trt = tokio.runtime.Builder().build()
        with pytest.raises(RuntimeError, match="block_on"):
            trt.block_on(None)

    rt.block_on(main())


def test_tokio_reexports_surface():
    # the façade exposes the tokio module layout (lib.rs:38-50)
    assert tokio.sync.channel and tokio.sync.oneshot and tokio.sync.Notify
    assert tokio.time.sleep and tokio.net.Endpoint and tokio.task.spawn


# -- tracing ----------------------------------------------------------------

def test_log_records_carry_sim_identity(caplog):
    rt = ms.Runtime(seed=72)
    logger = logging.getLogger("test.tracing")

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("worker-node").build()

        async def work():
            await ms.sleep(0.5)
            logger.info("hello from the node")

        with caplog.at_level(logging.INFO, logger="test.tracing"):
            caplog.handler.addFilter(tracing.SimContextFilter())
            await node.spawn(work())

    rt.block_on(main())
    rec = next(r for r in caplog.records if "hello" in r.message)
    assert rec.node == "worker-node"
    assert float(rec.sim_time) >= 0.5


def test_chrome_trace_export(tmp_path):
    rt = ms.Runtime(seed=73)
    tracer = tracing.Tracer().install(rt)

    async def main():
        h = ms.current_handle()
        node = h.create_node().name("traced").build()

        async def work():
            for _ in range(3):
                await ms.sleep(0.1)

        await node.spawn(work())

    rt.block_on(main())
    path = tmp_path / "trace.json"
    tracer.save(str(path))
    data = json.loads(path.read_text())
    events = data["traceEvents"]
    polls = [e for e in events if e.get("cat") == "poll"]
    assert len(polls) > 3
    names = {e["args"]["name"] for e in events if e.get("ph") == "M"}
    assert "traced" in names
    # virtual-time timestamps are monotone non-decreasing
    ts = [e["ts"] for e in polls]
    assert ts == sorted(ts)


# -- engine checkpoint/resume ----------------------------------------------


def test_sweep_checkpoint_resume_bit_exact(tmp_path):
    """Pause a sweep mid-flight, save, restore, resume: identical to an
    uninterrupted run."""
    cfg = raft.RaftConfig(num_nodes=3, crashes=1)
    ecfg = raft.engine_config(cfg, queue_capacity=32,
                              time_limit_ns=1_000_000_000, max_steps=8_000)
    wl = raft.workload(cfg)
    seeds = jnp.arange(8, dtype=jnp.int64)

    full = ecore.run_sweep(wl, ecfg, seeds)

    # run ~100 steps by hand, checkpoint, restore, resume
    state = ecore.init_sweep(wl, ecfg, seeds)
    import jax

    stepper = jax.jit(lambda s: ecore.step_batch(wl, ecfg, s))
    for _ in range(100):
        state = stepper(state)
    path = str(tmp_path / "sweep.npz")
    checkpoint.save_sweep(state, path)

    like = ecore.init_sweep(wl, ecfg, seeds)
    restored = checkpoint.load_sweep(path, like)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        pass  # structural restore is validated by resume equality below

    resumed = checkpoint.resume_sweep(wl, ecfg, restored)
    assert jnp.array_equal(resumed.ctr, full.ctr)
    assert jnp.array_equal(resumed.now_ns, full.now_ns)
    assert jnp.array_equal(resumed.wstate.elections, full.wstate.elections)
    assert jnp.array_equal(resumed.wstate.violation, full.wstate.violation)


def test_checkpoint_version_mismatch_raises(tmp_path):
    import numpy as np
    import pytest

    cfg = raft.RaftConfig(num_nodes=3)
    ecfg = raft.engine_config(cfg, queue_capacity=32)
    wl = raft.workload(cfg)
    state = ecore.init_sweep(wl, ecfg, jnp.arange(2, dtype=jnp.int64))
    path = str(tmp_path / "old.npz")
    checkpoint.save_sweep(state, path)
    # rewrite with a stale version stamp
    data = dict(np.load(path))
    data["__version__"] = np.asarray(1)
    np.savez_compressed(path, **data)
    with pytest.raises(ValueError, match="version mismatch"):
        checkpoint.load_sweep(path, state)


def test_cond_interval_validated():
    import pytest

    cfg = raft.RaftConfig(num_nodes=3)
    ecfg = raft.engine_config(cfg)._replace(cond_interval=0)
    wl = raft.workload(cfg)
    with pytest.raises(ValueError, match="cond_interval"):
        ecore.init_sweep(wl, ecfg, jnp.arange(2, dtype=jnp.int64))


def test_resumable_chunked_sweep(tmp_path, monkeypatch):
    """Interrupted pod-scale sweeps resume at chunk granularity: completed
    chunks load from their summary files (zero device work), totals match
    an uninterrupted whole-batch run, and a directory from a different
    sweep is rejected instead of silently merged."""
    import madsim_tpu.engine.core as ecore_mod
    from madsim_tpu.engine import checkpoint, core as ecore
    from madsim_tpu.models import raft

    cfg = raft.RaftConfig(num_nodes=3, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=500_000_000, max_steps=4_000)
    wl = raft.workload(cfg)
    seeds = jnp.arange(22, dtype=jnp.int64)  # 8+8+6: ragged final chunk
    d = str(tmp_path / "ckpts")

    totals = checkpoint.run_sweep_chunked_resumable(
        wl, ecfg, seeds, raft.sweep_summary, d, chunk_size=8
    )
    # ground truth: one whole-batch run (additive keys sum per chunk)
    whole = raft.sweep_summary(ecore.run_sweep(wl, ecfg, seeds))
    assert totals["events_total"] == whole["events_total"]
    assert totals["violations"] == whole["violations"]
    assert totals["queue_high_water"] == whole["queue_high_water"]

    # restart: every chunk must load from disk — no sweep may run
    def boom(*a, **k):
        raise AssertionError("run_sweep called on a fully-checkpointed sweep")

    monkeypatch.setattr(ecore_mod, "run_sweep", boom)
    resumed = checkpoint.run_sweep_chunked_resumable(
        wl, ecfg, seeds, raft.sweep_summary, d, chunk_size=8
    )
    assert resumed == totals
    monkeypatch.undo()

    # partial restart: drop one chunk file, only that chunk re-runs
    files = sorted(p for p in (tmp_path / "ckpts").iterdir() if p.suffix == ".json")
    assert len(files) == 3
    files[1].unlink()
    again = checkpoint.run_sweep_chunked_resumable(
        wl, ecfg, seeds, raft.sweep_summary, d, chunk_size=8
    )
    assert again == totals

    # foreign-sweep guards: different seeds, and same seeds under a
    # different engine config — both must refuse the stale directory
    with pytest.raises(ValueError, match="different sweep"):
        checkpoint.run_sweep_chunked_resumable(
            wl, ecfg, seeds + 1000, raft.sweep_summary, d, chunk_size=8
        )
    other = raft.engine_config(cfg, time_limit_ns=900_000_000, max_steps=4_000)
    with pytest.raises(ValueError, match="different sweep"):
        checkpoint.run_sweep_chunked_resumable(
            wl, other, seeds, raft.sweep_summary, d, chunk_size=8
        )
    with pytest.raises(ValueError, match="chunk_size"):
        checkpoint.run_sweep_chunked_resumable(
            wl, ecfg, seeds, raft.sweep_summary, d, chunk_size=-1
        )

    # a non-contiguous seed vector sharing a chunk's endpoints must not
    # reuse that chunk's summary (guard hashes the full seed array)
    shuffled = np.asarray(seeds).copy()
    shuffled[1], shuffled[2] = shuffled[2], shuffled[1]
    with pytest.raises(ValueError, match="different sweep"):
        checkpoint.run_sweep_chunked_resumable(
            wl,
            ecfg,
            jnp.asarray(shuffled),
            raft.sweep_summary,
            d,
            chunk_size=8,
        )

    # a pre-sha legacy record (endpoints + fingerprint only) still loads
    legacy = json.loads(files[0].read_text())
    del legacy["seeds_sha256"]
    files[0].write_text(json.dumps(legacy))
    assert (
        checkpoint.run_sweep_chunked_resumable(
            wl, ecfg, seeds, raft.sweep_summary, d, chunk_size=8
        )
        == totals
    )


def test_resumable_sweep_survives_layout_only_config_changes(
    tmp_path, monkeypatch
):
    """legacy_queue (and cond_interval) select equivalent layouts whose
    schedules are bit-identical (test_engine.py::
    test_legacy_queue_layout_bit_identical), so a checkpoint directory
    written under one layout must resume — all chunks from disk, zero
    device work — under the other."""
    import madsim_tpu.engine.core as ecore_mod

    cfg = raft.RaftConfig(num_nodes=3, crashes=1)
    ecfg = raft.engine_config(cfg, time_limit_ns=500_000_000, max_steps=4_000)
    wl = raft.workload(cfg)
    seeds = jnp.arange(8, dtype=jnp.int64)
    d = str(tmp_path / "ckpts")

    totals = checkpoint.run_sweep_chunked_resumable(
        wl, ecfg, seeds, raft.sweep_summary, d, chunk_size=8
    )

    def boom(*a, **k):
        raise AssertionError("layout-only change must not re-run the sweep")

    monkeypatch.setattr(ecore_mod, "run_sweep", boom)
    for other in (
        ecfg._replace(legacy_queue=1),
        ecfg._replace(cond_interval=32),
    ):
        resumed = checkpoint.run_sweep_chunked_resumable(
            wl, other, seeds, raft.sweep_summary, d, chunk_size=8
        )
        assert resumed == totals
    monkeypatch.undo()

    # a SEMANTIC config change must still be refused
    with pytest.raises(ValueError, match="different sweep"):
        checkpoint.run_sweep_chunked_resumable(
            wl,
            ecfg._replace(time_limit_ns=900_000_000),
            seeds,
            raft.sweep_summary,
            d,
            chunk_size=8,
        )
