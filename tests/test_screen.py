"""On-device history screening + the pipelined checked-sweep driver.

Covers the round's throughput contract bottom-up: the per-spec screens
on hand-written row planes against the WGL checker's verdicts (every
checker-rejected history must be flagged; provably-clean ones must
not), SWEEP-level conservatism on the seeded-bug models (screen-flagged
seeds ⊇ checker-violating seeds for `bug_stale_read` etcd and amnesia
raft) with the false-positive rate on clean sweeps bounded <5%, the
limit-masked chunk summary (one compiled program for every ragged tail),
the occupancy instrumentation (`state_bytes_per_seed` /
`pick_chunk_size`), and the pipelined driver's determinism story:
screened == naive, pool sizes byte-equal, chunk-checkpoint resume and
mid-chunk (format v7 `inflight`) resume bit-identical.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import replay
from madsim_tpu.engine import checkpoint as eckpt
from madsim_tpu.engine import core as ecore
from madsim_tpu.models import etcd, kafka, raft
from madsim_tpu.models._common import merge_summaries
from madsim_tpu.oracle import (
    ElectionSpec,
    KVSpec,
    LogSpec,
    check_history,
    violating_seeds,
)
from madsim_tpu.oracle.history import (
    OP_ELECT,
    OP_FETCH,
    OP_GET,
    OP_PRODUCE,
    OP_PUT,
    PH_INVOKE,
    PH_OK,
    canonical_bytes_from_rows,
    decode_rows,
    history_canonical_bytes,
    history_from_canon,
)
from madsim_tpu.oracle.screen import (
    checked_sweep,
    history_host_work,
    kv_window_suspect,
    screen_history,
    screen_sweep,
)

SEEDS = jnp.arange(48, dtype=jnp.int64)

ETCD_CLEAN = etcd.EtcdConfig(hist_slots=256)
ETCD_BUG = etcd.EtcdConfig(hist_slots=256, bug_stale_read=True)


def _ecfg(cfg, **kw):
    kw.setdefault("time_limit_ns", 2_000_000_000)
    kw.setdefault("max_steps", 20_000)
    return etcd.engine_config(cfg, **kw)


def _rows(*items, slots=16):
    """Raw history planes from (client, op, phase, key, val, opid, t)
    tuples — MUST be listed in time order (the engine appends rows in
    dispatch order, which is what the screens assume)."""
    rec = np.zeros((slots, 5), np.int32)
    ts = np.zeros((slots,), np.int64)
    for i, (c, op, ph, k, v, oid, t) in enumerate(items):
        rec[i] = (c, op * 2 + ph, k, v, oid)
        ts[i] = t
    return rec, ts, len(items)


def _agrees(spec, *items, slots=16):
    """(screen suspect?, checker rejects?) for one hand-written history,
    asserting the conservatism direction: rejected => suspect."""
    rec, ts, n = _rows(*items, slots=slots)
    suspect = screen_history(rec, ts, n, spec)
    verdict = check_history(decode_rows(rec, ts, n, False), spec)
    assert suspect or verdict.ok, (
        f"screen cleared a history the checker rejects: {verdict.reason}"
    )
    return suspect, verdict.ok


# -- the KV screen on hand-written histories ---------------------------------


def test_kv_screen_flags_stale_read():
    suspect, ok = _agrees(
        KVSpec(),
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (0, OP_PUT, PH_OK, 3, 5, 0, 100),
        (0, OP_PUT, PH_INVOKE, 3, 7, 1, 150),
        (0, OP_PUT, PH_OK, 3, 7, 1, 250),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 300),
        (1, OP_GET, PH_OK, 3, 5, 0, 400),  # stale: 7 committed first
    )
    assert suspect and not ok


def test_kv_screen_clears_concurrent_read():
    """A read overlapping the put may see either value — linearizable,
    and the screen must not flag it (it is exactly the case a naive
    'latest committed value' latch would false-positive on)."""
    suspect, ok = _agrees(
        KVSpec(),
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 10),
        (1, OP_GET, PH_OK, 3, -1, 0, 50),  # before the put lands
        (0, OP_PUT, PH_OK, 3, 5, 0, 100),
        (1, OP_GET, PH_INVOKE, 3, 0, 1, 160),
        (1, OP_GET, PH_OK, 3, 5, 1, 200),
    )
    assert ok and not suspect


def test_kv_screen_flags_read_flipflop():
    """Two writes concurrent with EACH OTHER, later reads disagreeing on
    their order — no write pair is 'definitely fresher', so only the
    read-as-evidence condition can catch it (and must)."""
    suspect, ok = _agrees(
        KVSpec(),
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (1, OP_PUT, PH_INVOKE, 3, 7, 0, 5),
        (0, OP_PUT, PH_OK, 3, 5, 0, 100),
        (1, OP_PUT, PH_OK, 3, 7, 0, 110),
        (0, OP_GET, PH_INVOKE, 3, 0, 1, 200),
        (0, OP_GET, PH_OK, 3, 7, 1, 300),  # observed 7...
        (1, OP_GET, PH_INVOKE, 3, 0, 1, 400),
        (1, OP_GET, PH_OK, 3, 5, 1, 500),  # ...then 5 again: impossible
    )
    assert suspect and not ok


def test_kv_screen_flags_phantom_and_absent():
    s1, ok1 = _agrees(
        KVSpec(),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 10),
        (1, OP_GET, PH_OK, 3, 42, 0, 20),  # nobody ever wrote 42
    )
    assert s1 and not ok1
    s2, ok2 = _agrees(
        KVSpec(),
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (0, OP_PUT, PH_OK, 3, 5, 0, 100),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 200),
        (1, OP_GET, PH_OK, 3, -1, 0, 300),  # ABSENT after a commit
    )
    assert s2 and not ok2


def test_kv_screen_clears_open_put_observed():
    """A PUT whose ack was lost may still have taken effect; a later
    read observing it is linearizable and must not be flagged."""
    suspect, ok = _agrees(
        KVSpec(),
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),  # never completes
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 300),
        (1, OP_GET, PH_OK, 3, 5, 0, 400),
    )
    assert ok and not suspect


# -- the log screen ----------------------------------------------------------


def test_log_screen_flags_overread_and_gap():
    s1, ok1 = _agrees(
        LogSpec(),
        (0, OP_PRODUCE, PH_INVOKE, 0, 0, 0, 0),
        (0, OP_PRODUCE, PH_OK, 0, 1, 0, 50),
        (4, OP_FETCH, PH_INVOKE, 0, 0, 0, 100),
        (4, OP_FETCH, PH_OK, 0, 3, 0, 200),  # 3 records, 1 produce
    )
    assert s1 and not ok1
    s2, ok2 = _agrees(
        LogSpec(),
        (0, OP_PRODUCE, PH_INVOKE, 0, 0, 0, 0),
        (0, OP_PRODUCE, PH_OK, 0, 1, 0, 50),
        (0, OP_PRODUCE, PH_INVOKE, 0, 1, 1, 60),
        (0, OP_PRODUCE, PH_OK, 0, 2, 1, 110),
        (4, OP_FETCH, PH_INVOKE, 0, 0, 0, 120),
        (4, OP_FETCH, PH_OK, 0, 1, 0, 200),
        (4, OP_FETCH, PH_INVOKE, 0, 2, 1, 300),
        (4, OP_FETCH, PH_OK, 0, 1, 1, 400),  # skipped offset 1
    )
    assert s2 and not ok2


def test_log_screen_clears_contiguous_fetches():
    suspect, ok = _agrees(
        LogSpec(),
        (0, OP_PRODUCE, PH_INVOKE, 0, 0, 0, 0),
        (0, OP_PRODUCE, PH_OK, 0, 1, 0, 50),
        (0, OP_PRODUCE, PH_INVOKE, 0, 1, 1, 60),
        (0, OP_PRODUCE, PH_OK, 0, 2, 1, 110),
        (4, OP_FETCH, PH_INVOKE, 0, 0, 0, 120),
        (4, OP_FETCH, PH_OK, 0, 1, 0, 200),
        (4, OP_FETCH, PH_INVOKE, 0, 1, 1, 300),
        (4, OP_FETCH, PH_OK, 0, 1, 1, 400),
    )
    assert ok and not suspect


# -- the election screen (precise) -------------------------------------------


def test_election_screen_matches_structural_exactly():
    rec, ts, n = _rows(
        (1, OP_ELECT, PH_INVOKE, 1, 1, 0, 0),
        (2, OP_ELECT, PH_INVOKE, 2, 2, 1, 100),
        (1, OP_ELECT, PH_INVOKE, 3, 1, 2, 200),
    )
    assert not screen_history(rec, ts, n, ElectionSpec())
    rec, ts, n = _rows(
        (1, OP_ELECT, PH_INVOKE, 1, 1, 0, 0),
        (2, OP_ELECT, PH_INVOKE, 1, 2, 1, 100),  # term 1, second winner
    )
    assert screen_history(rec, ts, n, ElectionSpec())


# -- sweep-level conservatism: the acceptance contract ----------------------


@pytest.fixture(scope="module")
def etcd_bug_final():
    return ecore.run_sweep(etcd.workload(ETCD_BUG), _ecfg(ETCD_BUG), SEEDS)


def test_screen_conservative_on_etcd_stale_bug(etcd_bug_final):
    """Screen-flagged seeds ⊇ WGL-violating seeds on the seeded-bug
    sweep, and the screened checker returns the identical violation set
    at a fraction of the decode+search cost."""
    final = etcd_bug_final
    full = violating_seeds(final, KVSpec())
    assert full.size >= 1, "bug sweep fixture found no violations"
    mask = np.asarray(screen_sweep(final, KVSpec()))
    suspects = set(np.asarray(final.seed)[mask].tolist())
    assert set(full.tolist()) <= suspects
    np.testing.assert_array_equal(
        violating_seeds(final, KVSpec(), screen=True), full
    )


def test_screen_conservative_on_amnesia_raft():
    """Same contract on the raft election histories — here the screen
    is exactly the structural invariant, so flagged == violating."""
    cfg, _ = replay.amnesia_raft_config()
    cfg = cfg._replace(hist_slots=64)
    ecfg = raft.engine_config(
        cfg, time_limit_ns=3_000_000_000, max_steps=30_000
    )
    final = ecore.run_sweep(raft.workload(cfg), ecfg, SEEDS)
    full = violating_seeds(final, ElectionSpec())
    assert full.size >= 1, "amnesia sweep fixture found no violations"
    mask = np.asarray(screen_sweep(final, ElectionSpec()))
    np.testing.assert_array_equal(np.asarray(final.seed)[mask], full)
    np.testing.assert_array_equal(
        violating_seeds(final, ElectionSpec(), screen=True), full
    )


def test_screen_false_positive_rate_bounded_on_clean_sweeps():
    """<5% suspects on clean sweeps — the bound that makes screening a
    real throughput win (a screen that cries wolf re-serializes the
    checker). The bundled screens are near-exact, so the observed rate
    is typically zero; 5% is the contract, not the expectation."""
    efinal = ecore.run_sweep(
        etcd.workload(ETCD_CLEAN), _ecfg(ETCD_CLEAN), SEEDS
    )
    emask = np.asarray(screen_sweep(efinal, KVSpec()))
    assert violating_seeds(efinal, KVSpec(), screen=True).size == 0
    assert emask.mean() < 0.05, f"etcd FP rate {emask.mean():.2%}"
    kcfg = kafka.KafkaConfig(hist_slots=512)
    kecfg = kafka.engine_config(
        kcfg, time_limit_ns=2_000_000_000, max_steps=20_000
    )
    kfinal = ecore.run_sweep(kafka.workload(kcfg), kecfg, SEEDS)
    kmask = np.asarray(screen_sweep(kfinal, LogSpec()))
    assert violating_seeds(kfinal, LogSpec(), screen=True).size == 0
    assert kmask.mean() < 0.05, f"kafka FP rate {kmask.mean():.2%}"


def test_screen_handles_overflowed_prefix(etcd_bug_final):
    """An overflowed buffer screens its valid prefix — same rows the
    checker checks, so conservatism survives truncation."""
    tiny = ETCD_BUG._replace(hist_slots=24)
    final = ecore.run_sweep(etcd.workload(tiny), _ecfg(tiny), SEEDS)
    assert np.asarray(final.hist_overflow).any(), "fixture must overflow"
    full = violating_seeds(final, KVSpec())
    mask = np.asarray(screen_sweep(final, KVSpec()))
    assert set(full.tolist()) <= set(np.asarray(final.seed)[mask].tolist())


# -- the limit-masked summary & occupancy instrumentation --------------------


def test_limit_summary_equals_trimmed_summary(etcd_bug_final):
    final = etcd_bug_final
    trimmed = ecore._concat_finals(30, final)
    assert etcd.sweep_summary(final, limit=30) == etcd.sweep_summary(trimmed)
    assert etcd.sweep_summary.supports_limit
    # raft too (scripts/sweep_million.py's ragged-tail path)
    cfg = raft.RaftConfig(num_nodes=3)
    recfg = raft.engine_config(cfg, time_limit_ns=500_000_000)
    rfinal = ecore.run_sweep(
        raft.workload(cfg), recfg, jnp.arange(8, dtype=jnp.int64)
    )
    assert raft.sweep_summary(rfinal, limit=5) == raft.sweep_summary(
        ecore._concat_finals(5, rfinal)
    )


def test_state_bytes_and_chunk_autopick():
    wl0 = etcd.workload(ETCD_CLEAN._replace(hist_slots=0))
    wl256 = etcd.workload(ETCD_CLEAN)
    ecfg = _ecfg(ETCD_CLEAN)
    b0 = ecore.state_bytes_per_seed(wl0, ecfg)
    b256 = ecore.state_bytes_per_seed(wl256, ecfg)
    # the history plane is 256 rows x (5 x int32 + int64) per seed
    assert b256 - b0 == 256 * (5 * 4 + 8)
    # auto-pick: power of two, in range, monotone in the carry size,
    # and an explicit budget caps it
    c0 = ecore.pick_chunk_size(wl0, ecfg)
    c256 = ecore.pick_chunk_size(wl256, ecfg)
    assert c0 & (c0 - 1) == 0 and 1024 <= c0 <= 65536
    assert c256 <= c0
    assert ecore.pick_chunk_size(wl256, ecfg, budget_bytes=1) == 1024
    assert (
        ecore.pick_chunk_size(wl256, ecfg, budget_bytes=1 << 62) == 65536
    )


def test_run_sweep_chunked_auto_matches_explicit():
    seeds = jnp.arange(12, dtype=jnp.int64)
    cfg = raft.RaftConfig(num_nodes=3)
    recfg = raft.engine_config(cfg, time_limit_ns=500_000_000)
    wl = raft.workload(cfg)
    auto = ecore.run_sweep_chunked(wl, recfg, seeds)
    explicit = ecore.run_sweep_chunked(wl, recfg, seeds, chunk_size=12)
    for a, b in zip((auto.ctr, auto.now_ns), (explicit.ctr, explicit.now_ns)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- the pipelined driver ----------------------------------------------------


def _serial_checked(wl, ecfg, seeds, spec, chunk_size):
    """Reference totals: per-chunk sweep + summary + decode-everything
    checking, merged in chunk order — what the pipeline must equal."""
    from madsim_tpu.oracle import check_histories, decode_sweep
    from madsim_tpu.oracle.history import history_canonical_bytes

    totals = {}
    seeds = np.asarray(seeds)
    for lo in range(0, len(seeds), chunk_size):
        chunk = jnp.asarray(seeds[lo : lo + chunk_size])
        pad = chunk_size - int(chunk.shape[0]) if len(seeds) > chunk_size else 0
        final = ecore.run_sweep(
            wl, ecfg, ecore._pad_seeds(chunk, pad) if pad else chunk
        )
        if pad:
            final = ecore._concat_finals(int(chunk.shape[0]), final)
        s = etcd.sweep_summary(final)
        hists = decode_sweep(final)
        bad = [
            int(h.seed)
            for h, r in zip(hists, check_histories(hists, spec))
            if not r.ok
        ]
        s.update(
            {
                "hist_screened": len(hists),
                "hist_suspects": len(hists),
                "hist_unique": len(
                    {history_canonical_bytes(h) for h in hists}
                ),
                "hist_violations": len(bad),
                "hist_undecided": 0,
                "budget_exceeded": 0,
                "hist_violating_seeds": bad[:32],
            }
        )
        merge_summaries(totals, s)
    # the driver caps the MERGED sample at the same per-chunk bound —
    # the composition that makes the list chunking-invariant (and
    # therefore mesh-size-invariant, docs/multichip.md)
    totals["hist_violating_seeds"] = totals["hist_violating_seeds"][:32]
    return totals


def test_pipelined_checked_sweep_matches_serial_and_pool_sizes(
    etcd_bug_final,
):
    """The determinism triangle: screened+pipelined == naive serial
    (conservatism makes the skip invisible), and the pool size never
    changes a byte. Ragged total on purpose (40 = 2x16 + 8)."""
    del etcd_bug_final  # ordering hint only: reuse the compiled sweep
    wl, ecfg = etcd.workload(ETCD_BUG), _ecfg(ETCD_BUG)
    seeds = jnp.arange(40, dtype=jnp.int64)
    spec = etcd.history_spec()
    serial = _serial_checked(wl, ecfg, seeds, spec, 16)
    piped = checked_sweep(
        wl, ecfg, seeds, spec, etcd.sweep_summary, chunk_size=16
    )
    pooled = checked_sweep(
        wl, ecfg, seeds, spec, etcd.sweep_summary, chunk_size=16, workers=2
    )
    naive = checked_sweep(
        wl, ecfg, seeds, spec, etcd.sweep_summary, chunk_size=16,
        screen=False,
    )
    assert pooled == piped
    # suspect/unique counts depend on the screen setting (they count
    # checked lanes, and the naive path checks every lane); everything
    # verdict-bearing must agree
    drop = lambda d: {
        k: v for k, v in d.items()
        if k not in ("hist_suspects", "hist_unique")
    }
    assert drop(naive) == drop(piped)
    assert serial == naive
    assert piped["hist_violations"] >= 1
    assert piped["hist_suspects"] <= piped["hist_screened"]
    assert piped["hist_unique"] <= piped["hist_suspects"]


def test_campaign_screened_history_target():
    """A coverage + history target routes its device screen through the
    pipeline's screen= hook (not the host phase, which would serialize
    behind the next chunk's sweep) and its host phase consumes the
    precomputed suspect mask — record determinism and violating-seed
    equality with a direct screened check prove the plumbing."""
    from madsim_tpu.explore.campaign import CampaignConfig, run_campaign
    from madsim_tpu.explore.targets import Target

    cfg, _ = replay.amnesia_raft_config()
    cfg = cfg._replace(hist_slots=64)
    spec = raft.history_spec()

    def build(faults):
        c = cfg._replace(faults=faults)
        return raft.workload(c), raft.engine_config(
            c, time_limit_ns=3_000_000_000, max_steps=30_000
        )

    target = Target(
        name="raft-amnesia-hist",
        build=build,
        summarize=raft.sweep_summary,
        num_nodes=cfg.num_nodes,
        fault_kind=raft.K_FAULT,
        node_of=lambda kind, pay: int(pay[0]),
        violating=lambda final: violating_seeds(final, spec, screen=True),
        hist_spec=spec,
    )
    from madsim_tpu.engine.faults import FaultSpec

    ccfg = CampaignConfig(rounds=2, seeds_per_round=24, chunk_size=8)
    bland = FaultSpec(
        crashes=3, crash_window_ns=2_000_000_000,
        restart_lo_ns=50_000_000, restart_hi_ns=300_000_000,
    )
    r1 = run_campaign(target, bland, ccfg)
    r2 = run_campaign(target, bland, ccfg)
    assert r1.records == r2.records
    # the pipeline's screened verdicts == a direct screened check
    wl, ecfg = build(bland)
    final = ecore.run_sweep(wl, ecfg, jnp.arange(24, dtype=jnp.int64))
    direct = violating_seeds(final, spec, screen=True)
    assert r1.records[0]["violating_seeds"] == [int(s) for s in direct[:8]]


def test_pipelined_ckpt_resume_is_bit_identical(tmp_path):
    wl, ecfg = etcd.workload(ETCD_BUG), _ecfg(ETCD_BUG)
    seeds = jnp.arange(40, dtype=jnp.int64)
    spec = etcd.history_spec()
    straight = checked_sweep(
        wl, ecfg, seeds, spec, etcd.sweep_summary, chunk_size=16
    )
    d = str(tmp_path / "ck")
    partial = checked_sweep(
        wl, ecfg, seeds, spec, etcd.sweep_summary, chunk_size=16,
        ckpt_dir=d, stop_after=1,
    )
    assert partial["seeds"] == 16
    assert len(os.listdir(d)) == 1
    resumed = checked_sweep(
        wl, ecfg, seeds, spec, etcd.sweep_summary, chunk_size=16,
        ckpt_dir=d,
    )
    assert resumed == straight
    # a foreign directory (different seeds) must refuse, not merge
    with pytest.raises(ValueError, match="different sweep"):
        checked_sweep(
            wl, ecfg, jnp.arange(100, 140, dtype=jnp.int64), spec,
            etcd.sweep_summary, chunk_size=16, ckpt_dir=d,
        )


def test_inflight_checkpoint_resume_is_bit_identical(tmp_path):
    """The recovery_e2e satellite: interrupt mid-chunk, checkpoint with
    v7 inflight metadata, restore, resume with overlap enabled — the
    merged checked-sweep report is bit-identical."""
    wl = etcd.workload(ETCD_BUG)
    full = _ecfg(ETCD_BUG)
    short = _ecfg(ETCD_BUG, max_steps=300)
    seeds = jnp.arange(32, dtype=jnp.int64)
    spec = etcd.history_spec()
    straight = checked_sweep(
        wl, full, seeds, spec, etcd.sweep_summary, chunk_size=16
    )
    partial = ecore.run_sweep(wl, short, seeds[:16])
    path = str(tmp_path / "mid.npz")
    eckpt.save_sweep(partial, path, inflight={"lo": 0, "k": 16})
    restored = eckpt.load_sweep(path, like=partial)
    inflight = eckpt.load_inflight(path)
    assert inflight == {"lo": 0, "k": 16}
    resumed = checked_sweep(
        wl, full, seeds, spec, etcd.sweep_summary, chunk_size=16,
        resume_from=(restored, inflight),
    )
    assert resumed == straight
    # a snapshot of the WRONG chunk's seeds must refuse
    with pytest.raises(ValueError, match="resume_from"):
        checked_sweep(
            wl, full, jnp.arange(100, 132, dtype=jnp.int64), spec,
            etcd.sweep_summary, chunk_size=16,
            resume_from=(restored, inflight),
        )
    # ...and a plain snapshot carries no inflight metadata
    plain = str(tmp_path / "plain.npz")
    eckpt.save_sweep(partial, plain)
    assert eckpt.load_inflight(plain) is None


# -- device-side canonical decode (docs/oracle.md "Device-side checking") ----


def _canon_device(rec, ts, n):
    """Run the jitted canonical-decode kernel on one hand-written lane."""
    from madsim_tpu.oracle.history import _canon_kernel

    canon, n_ops, breach = _canon_kernel()(
        jnp.asarray(rec)[None],
        jnp.asarray(ts)[None],
        jnp.asarray([n], jnp.int32),
    )
    return np.asarray(canon)[0], int(n_ops[0]), bool(breach[0])


_CANON_FIXTURES = {
    "stale_read": (
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (0, OP_PUT, PH_OK, 3, 5, 0, 100),
        (0, OP_PUT, PH_INVOKE, 3, 7, 1, 150),
        (0, OP_PUT, PH_OK, 3, 7, 1, 250),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 300),
        (1, OP_GET, PH_OK, 3, 5, 0, 400),
    ),
    "open_ops": (
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),  # ack lost: stays open
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 300),
        (1, OP_GET, PH_OK, 3, 5, 0, 400),
        (2, OP_GET, PH_INVOKE, 3, 0, 0, 500),  # open at buffer end
    ),
    "tied_times": (
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 10),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 10),  # tie with the put invoke
        (0, OP_PUT, PH_OK, 3, 5, 0, 20),
        (1, OP_GET, PH_OK, 3, 5, 0, 20),  # tie with the put ok
    ),
    "reinvoked_opid": (
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (0, OP_PUT, PH_INVOKE, 3, 6, 0, 100),  # same opid re-invoked
        (0, OP_PUT, PH_OK, 3, 6, 0, 200),  # pairs with the LATER invoke
        (1, OP_GET, PH_INVOKE, 4, 0, 0, 250),
        (1, OP_GET, PH_OK, 4, -1, 0, 300),
    ),
}


@pytest.mark.parametrize("name", sorted(_CANON_FIXTURES))
@pytest.mark.parametrize("overflow", [False, True])
def test_canon_kernel_bytes_match_host(name, overflow):
    """The tentpole byte contract on hand-written lanes: the device
    kernel's canonical rows encode to EXACTLY the host decode's bytes —
    ties, open ops, re-invoked opids, and the overflow header included —
    and the rank-rebuilt history gets the same checker verdict."""
    rec, ts, n = _rows(*_CANON_FIXTURES[name])
    rows_dev, n_ops, breach = _canon_device(rec, ts, n)
    assert not breach
    dev = canonical_bytes_from_rows(rows_dev, n_ops, n, overflow)
    hist = decode_rows(rec, ts, n, overflow)
    assert dev == history_canonical_bytes(hist)
    rebuilt = history_from_canon(rows_dev, n_ops, overflow, n)
    assert (
        check_history(rebuilt, KVSpec()).ok
        == check_history(hist, KVSpec()).ok
    )


def test_canon_kernel_flags_record_breach():
    """An OK row with no matching invoke (or a mismatched one) is a
    record-hook contract breach — the kernel must refuse (flag), not
    emit rows the host path would raise on."""
    rec, ts, n = _rows(
        (1, OP_GET, PH_OK, 3, 5, 0, 100),  # orphan: no invoke row
    )
    _, _, breach = _canon_device(rec, ts, n)
    assert breach
    rec, ts, n = _rows(
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (0, OP_GET, PH_OK, 4, 5, 0, 100),  # same (client, opid), wrong op+key
    )
    _, _, breach = _canon_device(rec, ts, n)
    assert breach


# -- the bounded-window KV screen --------------------------------------------


def test_kv_window_budget_forces_suspect():
    """Conservatism when the contention window overflows: a perfectly
    linearizable pileup of overlapping same-key ops must screen clean
    under the default window and SUSPECT under a window it exceeds —
    the fallback that keeps the bounded screen sound at any depth."""
    items = [
        (0, OP_PUT, PH_INVOKE, 3, 5, 0, 0),
        (1, OP_GET, PH_INVOKE, 3, 0, 0, 10),
        (2, OP_GET, PH_INVOKE, 3, 0, 0, 20),
        (0, OP_PUT, PH_OK, 3, 5, 0, 100),
        (1, OP_GET, PH_OK, 3, 5, 0, 110),
        (2, OP_GET, PH_OK, 3, 5, 0, 120),
    ]
    rec, ts, n = _rows(*items)
    assert check_history(decode_rows(rec, ts, n, False), KVSpec()).ok
    assert not bool(kv_window_suspect(rec, ts, n))
    assert bool(kv_window_suspect(rec, ts, n, window=1))


def test_kv_window_screen_reduces_suspects(etcd_bug_final):
    """The acceptance pin: on the seeded-bug sweep the exact-in-window
    screen flags strictly FEWER lanes than it screens (the old
    value-staleness heuristic's margin is gone), while conservatism
    holds (test_screen_conservative_on_etcd_stale_bug)."""
    mask = np.asarray(screen_sweep(etcd_bug_final, KVSpec()))
    assert mask.any()
    assert int(mask.sum()) < int(mask.size)


# -- the incremental host-work protocol --------------------------------------


def test_host_work_incremental_and_device_decode_equal(etcd_bug_final):
    """One pipeline, three consumptions — legacy sync call, explicit
    submit/poll/drain, and the device-decode path — must produce the
    IDENTICAL report dict (the byte contract behind every driver)."""
    final = etcd_bug_final
    S = int(np.asarray(final.seed).size)
    mask = np.asarray(screen_sweep(final, KVSpec()))
    sus = mask & (np.arange(S) < 8)  # cap the WGL cost: <=8 lanes
    assert sus.any()
    seeds = np.asarray(final.seed)
    kw = dict(lo=0, n=S, seeds=seeds, suspect=sus, summary={})
    sync = history_host_work(KVSpec())(final, **kw)
    hw = history_host_work(KVSpec())
    hw.submit(final, **kw)
    finished = []
    while not finished:
        finished = hw.poll(0.0)  # starved budget still progresses
    assert finished == [(0, sync)]
    assert hw.drain() == []
    dev = history_host_work(KVSpec(), device_decode=True)(final, **kw)
    assert dev == sync
    assert sync["hist_suspects"] == int(sus.sum())
    assert sync["budget_exceeded"] == 0


def test_budget_exceeded_surfaces(etcd_bug_final):
    """A starved WGL state budget must be VISIBLE, not silent: the
    report's budget_exceeded counts the undecided searches, undecided
    lanes are never reported as violations, and violating_seeds exposes
    the same honesty through its stats out-param."""
    final = etcd_bug_final
    S = int(np.asarray(final.seed).size)
    mask = np.asarray(screen_sweep(final, KVSpec()))
    sus = mask & (np.arange(S) < 8)
    report = history_host_work(KVSpec(), max_states=1)(
        final, lo=0, n=S, seeds=np.asarray(final.seed), suspect=sus,
        summary={},
    )
    assert report["budget_exceeded"] >= 1
    assert report["hist_undecided"] >= 1
    assert report["hist_violations"] == 0
    stats: dict = {}
    out = violating_seeds(
        final, KVSpec(), max_states=1, screen=lambda _f: sus, stats=stats
    )
    assert out.size == 0
    assert stats["checked"] == int(sus.sum())
    assert stats["budget_exceeded"] >= 1
