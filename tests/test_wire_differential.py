"""Differential fuzz for the wire tiers: a seeded random operation
sequence is applied BOTH through the genuine protocol (stock gRPC / HTTP
clients against the wire servers) and directly to a mirrored in-process
service instance; observable state and per-op results must agree at
every step. Catches adapter bugs (encoding, range conventions, status
mapping) that example-based tests miss."""

import random

import pytest

grpcio = pytest.importorskip("grpc")
aiohttp = pytest.importorskip("aiohttp")

from grpc import aio as grpc_aio  # noqa: E402

from madsim_tpu import real  # noqa: E402
from madsim_tpu.etcd import wire as etcd_wire  # noqa: E402
from madsim_tpu.etcd.service import (  # noqa: E402
    DeleteOptions,
    EtcdService,
    GetOptions,
    PutOptions,
)
from madsim_tpu.s3 import wire as s3_wire  # noqa: E402
from madsim_tpu.s3.service import S3Error, S3Service  # noqa: E402

KEYS = [f"k{i:02d}".encode() for i in range(12)]
VALS = [f"v{i}".encode() for i in range(6)]
OPS = 150


def test_etcd_wire_differential_fuzz():
    """put/delete/range/from-key/prefix ops through the wire match a
    mirrored EtcdService op for op (revision, kvs, counts)."""
    rng = random.Random(2024)
    mirror = EtcdService()

    async def main():
        server = etcd_wire.WireServer()
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            if task.done():
                task.result()  # surface bind failures instead of hanging
            await real.sleep(0.005)
        host, port = server.bound_addr
        m = {n.rsplit(".", 1)[-1]: c
             for n, c in etcd_wire.wire_pkg().messages.items()}
        async with grpc_aio.insecure_channel(f"{host}:{port}") as ch:
            put = ch.unary_unary(
                "/etcdserverpb.KV/Put",
                request_serializer=m["PutRequest"].SerializeToString,
                response_deserializer=m["PutResponse"].FromString,
            )
            rng_mc = ch.unary_unary(
                "/etcdserverpb.KV/Range",
                request_serializer=m["RangeRequest"].SerializeToString,
                response_deserializer=m["RangeResponse"].FromString,
            )
            dele = ch.unary_unary(
                "/etcdserverpb.KV/DeleteRange",
                request_serializer=m["DeleteRangeRequest"].SerializeToString,
                response_deserializer=m["DeleteRangeResponse"].FromString,
            )

            for step in range(OPS):
                op = rng.choice(["put", "put", "put", "delete", "range",
                                 "range_all", "from_key"])
                key = rng.choice(KEYS)
                if op == "put":
                    val = rng.choice(VALS)
                    r = await put(m["PutRequest"](key=key, value=val))
                    rev, _prev = mirror.put(key, val, PutOptions())
                    assert r.header.revision == rev, step
                elif op == "delete":
                    end = rng.choice([b"", key + b"\xff"])
                    r = await dele(m["DeleteRangeRequest"](key=key,
                                                           range_end=end))
                    _rev, deleted, _ = mirror.delete(
                        key, DeleteOptions(range_end=end or None)
                    )
                    assert r.deleted == deleted, step
                elif op == "range":
                    r = await rng_mc(m["RangeRequest"](key=key))
                    _rev, items, count = mirror.get(key, GetOptions())
                    assert r.count == count, step
                    assert [kv.value for kv in r.kvs] == [
                        kv.value for kv in items
                    ], step
                elif op == "range_all":
                    r = await rng_mc(m["RangeRequest"](key=b"a",
                                                       range_end=b"z"))
                    _rev, items, count = mirror.get(
                        b"a", GetOptions(range_end=b"z")
                    )
                    assert [(kv.key, kv.value, kv.mod_revision)
                            for kv in r.kvs] == [
                        (kv.key, kv.value, kv.mod_revision) for kv in items
                    ], step
                else:  # from_key
                    r = await rng_mc(m["RangeRequest"](key=key,
                                                       range_end=b"\x00"))
                    _rev, items, count = mirror.get(
                        key, GetOptions(from_key=True)
                    )
                    assert [kv.key for kv in r.kvs] == [
                        kv.key for kv in items
                    ], step

            # final state identical key for key
            r = await rng_mc(m["RangeRequest"](key=b"\x00", range_end=b"\x00"))
            final_wire = {kv.key: (kv.value, kv.mod_revision, kv.version)
                          for kv in r.kvs}
            final_mirror = {
                k: (kv.value, kv.mod_revision, kv.version)
                for k, kv in mirror.kv.items()
            }
            assert final_wire == final_mirror
        task.abort()

    real.Runtime().block_on(main())


def test_s3_wire_differential_fuzz():
    """put/get/delete/list through the REST wire match a mirrored
    S3Service op for op (etags, bodies, listings, error codes)."""
    rng = random.Random(7)
    mirror = S3Service()
    mirror.create_bucket("fz")

    async def main():
        server = s3_wire.WireServer()
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            if task.done():
                task.result()  # surface bind failures instead of hanging
            await real.sleep(0.005)
        host, port = server.bound_addr
        base = f"http://{host}:{port}"
        async with aiohttp.ClientSession() as http:
            assert (await http.put(f"{base}/fz")).status == 200

            for step in range(OPS):
                op = rng.choice(["put", "put", "get", "delete", "list"])
                key = rng.choice(KEYS).decode()
                if op == "put":
                    body = rng.choice(VALS) * rng.randrange(1, 4)
                    r = await http.put(f"{base}/fz/{key}", data=body)
                    etag = mirror.put_object("fz", key, body, 0)
                    assert r.status == 200 and r.headers["ETag"] == etag, step
                elif op == "get":
                    r = await http.get(f"{base}/fz/{key}")
                    try:
                        obj = mirror.get_object("fz", key)
                        assert r.status == 200, step
                        assert await r.read() == obj.body, step
                    except S3Error:
                        assert r.status == 404, step
                elif op == "delete":
                    r = await http.delete(f"{base}/fz/{key}")
                    mirror.delete_object("fz", key)
                    assert r.status == 204, step
                else:  # list
                    r = await http.get(f"{base}/fz?list-type=2&prefix=k")
                    contents, _tok, _trunc = mirror.list_objects_v2(
                        "fz", "k", None, 1000
                    )
                    text = await r.text()
                    for k, _size, etag in contents:
                        assert f"<Key>{k}</Key>" in text, step
                    assert text.count("<Contents>") == len(contents), step
        task.abort()

    real.Runtime().block_on(main())
