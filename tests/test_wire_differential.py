"""Differential fuzz for the wire tiers: a seeded random operation
sequence is applied BOTH through the genuine protocol (stock gRPC / HTTP
/ Kafka-binary clients against the wire servers) and directly to a
mirrored in-process service instance; observable state and per-op
results must agree at every step. Catches adapter bugs (encoding, range
conventions, status mapping) that example-based tests miss.

The kafka legs live at the bottom and are dependency-free (the probe
client is vendored); the etcd/s3 legs need grpcio + aiohttp."""

import asyncio
import random

import pytest

from madsim_tpu import real
from madsim_tpu.etcd import wire as etcd_wire
from madsim_tpu.etcd.service import (
    DeleteOptions,
    EtcdService,
    GetOptions,
    PutOptions,
)
from madsim_tpu.kafka import fuzz as kfuzz
from madsim_tpu.kafka.probe import LoopbackTransport, ProbeClient, RealTransport
from madsim_tpu.kafka.wire import KafkaWire, WireServer
from madsim_tpu.s3 import wire as s3_wire
from madsim_tpu.s3.service import S3Error, S3Service

# per-leg guards, NOT module-level importorskip: the kafka legs below
# are dependency-free (vendored probe client) and must still collect
# where grpcio/aiohttp are absent
try:
    import grpc as grpcio
    from grpc import aio as grpc_aio
except ImportError:  # pragma: no cover - environment-dependent
    grpcio = grpc_aio = None
try:
    import aiohttp
except ImportError:  # pragma: no cover - environment-dependent
    aiohttp = None

needs_grpcio = pytest.mark.skipif(grpcio is None, reason="grpcio not installed")
needs_aiohttp = pytest.mark.skipif(aiohttp is None, reason="aiohttp not installed")

KEYS = [f"k{i:02d}".encode() for i in range(12)]
VALS = [f"v{i}".encode() for i in range(6)]
OPS = 150


@needs_grpcio
def test_etcd_wire_differential_fuzz():
    """put/delete/range/from-key/prefix ops through the wire match a
    mirrored EtcdService op for op (revision, kvs, counts)."""
    rng = random.Random(2024)
    mirror = EtcdService()

    async def main():
        server = etcd_wire.WireServer()
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            if task.done():
                task.result()  # surface bind failures instead of hanging
            await real.sleep(0.005)
        host, port = server.bound_addr
        m = {n.rsplit(".", 1)[-1]: c
             for n, c in etcd_wire.wire_pkg().messages.items()}
        async with grpc_aio.insecure_channel(f"{host}:{port}") as ch:
            put = ch.unary_unary(
                "/etcdserverpb.KV/Put",
                request_serializer=m["PutRequest"].SerializeToString,
                response_deserializer=m["PutResponse"].FromString,
            )
            rng_mc = ch.unary_unary(
                "/etcdserverpb.KV/Range",
                request_serializer=m["RangeRequest"].SerializeToString,
                response_deserializer=m["RangeResponse"].FromString,
            )
            dele = ch.unary_unary(
                "/etcdserverpb.KV/DeleteRange",
                request_serializer=m["DeleteRangeRequest"].SerializeToString,
                response_deserializer=m["DeleteRangeResponse"].FromString,
            )

            for step in range(OPS):
                op = rng.choice(["put", "put", "put", "delete", "range",
                                 "range_all", "from_key"])
                key = rng.choice(KEYS)
                if op == "put":
                    val = rng.choice(VALS)
                    r = await put(m["PutRequest"](key=key, value=val))
                    rev, _prev = mirror.put(key, val, PutOptions())
                    assert r.header.revision == rev, step
                elif op == "delete":
                    end = rng.choice([b"", key + b"\xff"])
                    r = await dele(m["DeleteRangeRequest"](key=key,
                                                           range_end=end))
                    _rev, deleted, _ = mirror.delete(
                        key, DeleteOptions(range_end=end or None)
                    )
                    assert r.deleted == deleted, step
                elif op == "range":
                    r = await rng_mc(m["RangeRequest"](key=key))
                    _rev, items, count = mirror.get(key, GetOptions())
                    assert r.count == count, step
                    assert [kv.value for kv in r.kvs] == [
                        kv.value for kv in items
                    ], step
                elif op == "range_all":
                    r = await rng_mc(m["RangeRequest"](key=b"a",
                                                       range_end=b"z"))
                    _rev, items, count = mirror.get(
                        b"a", GetOptions(range_end=b"z")
                    )
                    assert [(kv.key, kv.value, kv.mod_revision)
                            for kv in r.kvs] == [
                        (kv.key, kv.value, kv.mod_revision) for kv in items
                    ], step
                else:  # from_key
                    r = await rng_mc(m["RangeRequest"](key=key,
                                                       range_end=b"\x00"))
                    _rev, items, count = mirror.get(
                        key, GetOptions(from_key=True)
                    )
                    assert [kv.key for kv in r.kvs] == [
                        kv.key for kv in items
                    ], step

            # final state identical key for key
            r = await rng_mc(m["RangeRequest"](key=b"\x00", range_end=b"\x00"))
            final_wire = {kv.key: (kv.value, kv.mod_revision, kv.version)
                          for kv in r.kvs}
            final_mirror = {
                k: (kv.value, kv.mod_revision, kv.version)
                for k, kv in mirror.kv.items()
            }
            assert final_wire == final_mirror
        task.abort()

    real.Runtime().block_on(main())


@needs_aiohttp
def test_s3_wire_differential_fuzz():
    """put/get/delete/list through the REST wire match a mirrored
    S3Service op for op (etags, bodies, listings, error codes)."""
    rng = random.Random(7)
    mirror = S3Service()
    mirror.create_bucket("fz")

    async def main():
        server = s3_wire.WireServer()
        task = real.spawn(server.serve(("127.0.0.1", 0)))
        while server.bound_addr is None:
            if task.done():
                task.result()  # surface bind failures instead of hanging
            await real.sleep(0.005)
        host, port = server.bound_addr
        base = f"http://{host}:{port}"
        async with aiohttp.ClientSession() as http:
            assert (await http.put(f"{base}/fz")).status == 200

            for step in range(OPS):
                op = rng.choice(["put", "put", "get", "delete", "list"])
                key = rng.choice(KEYS).decode()
                if op == "put":
                    body = rng.choice(VALS) * rng.randrange(1, 4)
                    r = await http.put(f"{base}/fz/{key}", data=body)
                    etag = mirror.put_object("fz", key, body, 0)
                    assert r.status == 200 and r.headers["ETag"] == etag, step
                elif op == "get":
                    r = await http.get(f"{base}/fz/{key}")
                    try:
                        obj = mirror.get_object("fz", key)
                        assert r.status == 200, step
                        assert await r.read() == obj.body, step
                    except S3Error:
                        assert r.status == 404, step
                elif op == "delete":
                    r = await http.delete(f"{base}/fz/{key}")
                    mirror.delete_object("fz", key)
                    assert r.status == 204, step
                else:  # list
                    r = await http.get(f"{base}/fz?list-type=2&prefix=k")
                    contents, _tok, _trunc = mirror.list_objects_v2(
                        "fz", "k", None, 1000
                    )
                    text = await r.text()
                    for k, _size, etag in contents:
                        assert f"<Key>{k}</Key>" in text, step
                    assert text.count("<Contents>") == len(contents), step
        task.abort()

    real.Runtime().block_on(main())


# -- kafka ------------------------------------------------------------------


def test_kafka_wire_differential_fuzz_loopback():
    """50 seeds of the kafka op mix (produce/fetch/list-offsets + group
    join/heartbeat/commit/offset-fetch, mid-run rebalance, late leave)
    through the full wire codec in loopback, versions drawn per seed
    from the advertised matrix, vs the mirrored in-process broker."""

    async def main():
        digests = {}
        for seed in range(50):
            client = ProbeClient(LoopbackTransport(KafkaWire()))
            digests[seed] = await kfuzz.fuzz_seed(seed, client, ops=40)
        # the digest is a pure function of the seed: rerun two seeds
        for seed in (0, 17):
            client = ProbeClient(LoopbackTransport(KafkaWire()))
            assert await kfuzz.fuzz_seed(seed, client, ops=40) == digests[seed]

    asyncio.run(main())


def test_kafka_wire_differential_fuzz_real_tcp():
    """A slice of the same fuzz over genuine TCP framing — the transport
    (frame reassembly, persistent connections) joins the differential."""
    from madsim_tpu import real

    async def main():
        for seed in (1, 2, 3, 4, 5):
            server = WireServer()
            task = real.spawn(server.serve(("127.0.0.1", 0)))
            while server.bound_addr is None:
                if task.done():
                    task.result()
                await real.sleep(0.005)
            client = ProbeClient(
                await RealTransport.connect(server.bound_addr)
            )
            loop_client = ProbeClient(LoopbackTransport(KafkaWire()))
            tcp_digest = await kfuzz.fuzz_seed(seed, client, ops=30)
            # transport must not change a single compared byte
            assert tcp_digest == await kfuzz.fuzz_seed(seed, loop_client, ops=30)
            client.close()
            task.abort()

    real.Runtime().block_on(main())
