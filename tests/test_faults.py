"""Unified cross-tier fault campaigns (engine/faults.py + madsim_tpu/faults.py).

The contract under test: ONE ``FaultSpec`` compiles to the IDENTICAL
``(time_ns, action, victim)`` schedule on both tiers — the device tier
injects it into a lockstep sweep's event queues, the host tier applies it
to live nodes via ``Handle.kill/restart`` and the ``NetSim`` fault
surface — and the shared in-loop interpreter (``FaultState`` +
``on_event``) composes overlapping windows exactly.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/examples")

import raft_host

import madsim_tpu as ms
from madsim_tpu import faults as hfaults
from madsim_tpu import replay
from madsim_tpu.engine import core as ecore
from madsim_tpu.engine import faults as efaults
from madsim_tpu.engine import net as enet
from madsim_tpu.models import etcd, kafka, raft

# every category enabled, all windows well inside the sim horizon
FULL_SPEC = efaults.FaultSpec(
    crashes=2,
    crash_window_ns=1_500_000_000,
    restart_lo_ns=100_000_000,
    restart_hi_ns=400_000_000,
    partitions=2,
    part_window_ns=1_500_000_000,
    part_lo_ns=200_000_000,
    part_hi_ns=600_000_000,
    spikes=1,
    spike_window_ns=1_500_000_000,
    spike_dur_lo_ns=200_000_000,
    spike_dur_hi_ns=500_000_000,
    losses=1,
    loss_window_ns=1_500_000_000,
    loss_dur_lo_ns=200_000_000,
    loss_dur_hi_ns=500_000_000,
    pauses=1,
    pause_window_ns=1_500_000_000,
    pause_lo_ns=100_000_000,
    pause_hi_ns=300_000_000,
)


# -- the differential: device schedule == host schedule ----------------------


def test_device_and_host_compile_identical_schedules():
    """The acceptance gate: for one (spec, seed), the fault events a
    device-tier raft sweep actually dispatches (recovered from a traced
    replay, exact scheduled deadlines from the payloads) are byte-equal
    to the host compiler's schedule — through the engine's queue, vmap
    dispatch, and payload round-trip."""
    cfg = raft.RaftConfig(num_nodes=4, commands=0, faults=FULL_SPEC)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    wl = raft.workload(cfg)
    for seed in (0, 7, 93):
        _, trace = ecore.run_traced(wl, ecfg, seed)
        device = replay.extract_fault_schedule(trace, raft.K_FAULT)
        host = hfaults.compile_host(FULL_SPEC, cfg.num_nodes, seed)
        assert device == host, (seed, device, host)
        assert len(device) == efaults.num_events(FULL_SPEC)


def test_kafka_and_etcd_share_the_same_compiler():
    """The schedule is model-independent: for the same (spec, seed, N)
    the kafka and etcd workloads inject the identical schedule."""
    spec = FULL_SPEC._replace(crash_group=(0, 1), part_group=(1, -1))
    kcfg = kafka.KafkaConfig(num_producers=1, num_consumers=1, faults=spec)
    eccfg = etcd.EtcdConfig(num_clients=2, faults=spec)
    assert kcfg.num_nodes == eccfg.num_nodes == 3
    kecfg = kafka.engine_config(kcfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    eecfg = etcd.engine_config(eccfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    seed = 5
    _, kt = ecore.run_traced(kafka.workload(kcfg), kecfg, seed)
    _, et = ecore.run_traced(etcd.workload(eccfg), eecfg, seed)
    ks = replay.extract_fault_schedule(kt, kafka.K_FAULT)
    es = replay.extract_fault_schedule(et, etcd.K_FAULT)
    host = hfaults.compile_host(spec, 3, seed)
    assert ks == es == host


def test_schedule_respects_windows_and_groups():
    spec = FULL_SPEC._replace(crash_group=(1, 3), part_group=(0, 2))
    for seed in range(16):
        sched = hfaults.compile_host(spec, 4, seed)
        by_action = {}
        for t, action, v in sched:
            by_action.setdefault(action, []).append((t, v))
        for on, off, window, group in (
            ("crash", "restart", spec.crash_window_ns, (1, 3)),
            ("partition", "heal", spec.part_window_ns, (0, 2)),
            ("pause", "resume", spec.pause_window_ns, (0, 4)),
        ):
            assert len(by_action[on]) == len(by_action[off])
            for t, v in by_action[on]:
                assert 0 <= t < window
                assert group[0] <= v < group[1]
        # bursts are network-wide: victim is always 0
        assert all(v == 0 for _, v in by_action["spike_on"])
        assert all(v == 0 for _, v in by_action["loss_on"])


def test_compile_host_is_deterministic_and_seed_sensitive():
    a = hfaults.compile_host(FULL_SPEC, 4, 42)
    b = hfaults.compile_host(FULL_SPEC, 4, 42)
    c = hfaults.compile_host(FULL_SPEC, 4, 43)
    assert a == b
    assert a != c


# -- the shared in-loop interpreter ------------------------------------------


def _apply(spec, base, links, f, action, victim):
    links, f, _edges = efaults.on_event(
        spec, base, links, f, jnp.int32(action), jnp.int32(victim)
    )
    return links, f


def test_partition_refcount_composes():
    """Overlapping partition windows of one victim: the first heal must
    not reopen the second window's clog."""
    base = efaults.NetBase(1_000_000, 10_000_000, 0)
    links = enet.make(3)
    f = efaults.init_state(3)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_PART, 1)
    assert bool(links.clog[1, 0]) and bool(links.clog[0, 1])
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_PART, 1)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_HEAL, 1)
    assert bool(links.clog[1, 0]), "still inside the second window"
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_HEAL, 1)
    assert not bool(links.clog.any())
    assert int(f.part_in_cnt[1]) == 0 and int(f.part_out_cnt[1]) == 0


def test_burst_overrides_and_restores_base_values():
    base = efaults.NetBase(1_000_000, 10_000_000, 7)
    links = enet.make(3, loss_q32=7)
    f = efaults.init_state(3)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_SPIKE_ON, 0)
    assert int(links.lat_lo_ns) == FULL_SPEC.spike_lat_lo_ns
    assert int(links.lat_hi_ns) == FULL_SPEC.spike_lat_hi_ns
    # nested burst: the inner off must not restore early
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_SPIKE_ON, 0)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_SPIKE_OFF, 0)
    assert int(links.lat_lo_ns) == FULL_SPEC.spike_lat_lo_ns
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_SPIKE_OFF, 0)
    assert int(links.lat_lo_ns) == base.lat_lo_ns
    assert int(links.lat_hi_ns) == base.lat_hi_ns
    # loss burst the same way
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_LOSS_ON, 0)
    assert int(links.loss_q32) == FULL_SPEC.burst_loss_q32
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_LOSS_OFF, 0)
    assert int(links.loss_q32) == base.loss_q32


def test_crash_and_pause_masks():
    base = efaults.NetBase(1_000_000, 10_000_000, 0)
    links = enet.make(3)
    f = efaults.init_state(3)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_CRASH, 2)
    assert not bool(f.alive[2]) and bool(f.alive[0])
    assert not bool(efaults.up(f)[2])
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_RESTART, 2)
    assert bool(efaults.up(f)[2])
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_PAUSE, 0)
    assert bool(f.alive[0]) and not bool(efaults.up(f)[0])
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_RESUME, 0)
    assert bool(efaults.up(f)[0])


def test_crash_pause_interaction_matches_host_supervisor():
    """Overlapping crash and pause windows on one victim must resolve the
    way apply_schedule does: a kill clears the pause (the node's tasks
    are gone — restart revives it RUNNING), and pausing/resuming a dead
    node is a no-op."""
    base = efaults.NetBase(1_000_000, 10_000_000, 0)
    links = enet.make(3)
    f = efaults.init_state(3)
    # pause(1), crash(1), restart(1): the restarted node must be up even
    # though its resume has not fired yet
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_PAUSE, 1)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_CRASH, 1)
    assert not bool(f.paused[1]), "kill clears the pause"
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_RESTART, 1)
    assert bool(efaults.up(f)[1]), "restarted node revives running"
    # the stale resume is now a harmless no-op
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_RESUME, 1)
    assert bool(efaults.up(f)[1])
    # pausing a dead node is a no-op: after restart it is up
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_CRASH, 2)
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_PAUSE, 2)
    assert not bool(f.paused[2])
    links, f = _apply(FULL_SPEC, base, links, f, efaults.F_RESTART, 2)
    assert bool(efaults.up(f)[2])


def test_resumed_raft_leader_rearms_heartbeats():
    """A paused-then-resumed LEADER keeps its role, so resume must re-arm
    its heartbeat chain (the pause's lepoch bump killed it) — the host
    tier's Handle.resume lets the leader's tasks heartbeat on, and a
    leader with neither timer chain would sit mute until deposed."""
    cfg = raft.RaftConfig(num_nodes=3, commands=0)
    wl = raft.workload(cfg)
    w, _ = wl.init(jax.random.key(0))
    w = w._replace(
        role=w.role.at[0].set(2),  # LEADER
        fstate=w.fstate._replace(paused=w.fstate.paused.at[0].set(True)),
    )
    rand = jnp.zeros((wl.num_rand,), jnp.uint32)
    pay = jnp.zeros((wl.payload_slots,), jnp.int32)
    pay = pay.at[0].set(efaults.F_RESUME)  # victim defaults to node 0
    w2, emits = wl.handle(w, jnp.int64(1_000), jnp.int32(raft.K_FAULT), pay, rand)
    assert bool(efaults.up(w2.fstate)[0])
    fired = {
        int(k)
        for k, en in zip(np.asarray(emits.kinds), np.asarray(emits.enables))
        if en
    }
    assert raft.K_HEARTBEAT in fired, "resumed leader must re-enter heartbeats"
    assert raft.K_ELECTION not in fired, "leaders never hold election timers"
    # a resumed non-leader re-enters the election chain instead
    w3 = w._replace(role=w.role.at[0].set(0))
    _, emits2 = wl.handle(w3, jnp.int64(1_000), jnp.int32(raft.K_FAULT), pay, rand)
    fired2 = {
        int(k)
        for k, en in zip(np.asarray(emits2.kinds), np.asarray(emits2.enables))
        if en
    }
    assert raft.K_ELECTION in fired2 and raft.K_HEARTBEAT not in fired2


def test_group_validation():
    import pytest

    with pytest.raises(ValueError, match="group"):
        efaults.schedule_events(
            efaults.FaultSpec(crashes=1, crash_group=(3, 2)), 4,
            jax.random.key(0),
        )
    with pytest.raises(ValueError, match="payload slots"):
        efaults.compile_device(
            efaults.FaultSpec(crashes=1), 3, jax.random.key(0), 3, 2
        )


# -- full campaigns through the sweep engine ---------------------------------


def test_raft_campaign_sweep_stays_safe_and_deterministic():
    """A full campaign (crashes + partitions + bursts + pauses) over a
    raft sweep: checkers stay quiet, faults demonstrably perturb
    schedules, and traced replay parity holds."""
    base_cfg = raft.RaftConfig(num_nodes=4, commands=4, crashes=0)
    cfg = base_cfg._replace(faults=FULL_SPEC)
    ecfg = raft.engine_config(
        cfg, queue_capacity=160, time_limit_ns=3_000_000_000, max_steps=30_000
    )
    seeds = jnp.arange(48, dtype=jnp.int64)
    quiet = ecore.run_sweep(
        raft.workload(base_cfg._replace(faults=efaults.FaultSpec())), ecfg, seeds
    )
    stormy = ecore.run_sweep(raft.workload(cfg), ecfg, seeds)
    s = raft.sweep_summary(stormy)
    assert s["violations"] == 0, s
    assert s["overflow_seeds"] == 0
    frac_changed = np.mean(np.asarray(quiet.ctr) != np.asarray(stormy.ctr))
    assert frac_changed > 0.5, frac_changed
    single, _ = ecore.run_traced(raft.workload(cfg), ecfg, 11)
    assert int(single.ctr) == int(stormy.ctr[11])


def test_one_spec_drives_both_tiers_end_to_end():
    """The acceptance scenario: ONE FaultSpec instance drives a device
    raft sweep (finding amnesia violations) AND a host-tier raft run
    under the same compiled schedule — which reproduces the violation."""
    spec = efaults.FaultSpec(
        crashes=3,
        crash_window_ns=2_000_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    cfg = raft.RaftConfig(
        num_nodes=3, commands=0, volatile_state=True, faults=spec
    )
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(
        raft.workload(cfg), ecfg, jnp.arange(160, dtype=jnp.int64)
    )
    vio = replay.violation_seeds(final)
    assert vio.size > 0, "amnesia campaign found no violations"
    # the host tier explores its own schedules under the compiled fault
    # environment, so scan a few violation seeds x host seeds (exactly
    # like the trace-driven pipeline in tests/test_replay.py)
    result = None
    for campaign_seed in vio[:4]:
        result = replay.replay_on_host(
            lambda hs, _p: raft_host.run_seed_with_spec(
                hs, spec, int(campaign_seed), n=cfg.num_nodes, sim_seconds=3.0
            ),
            plan=[],  # unused: the spec compiles the schedule directly
            host_seeds=range(10),
        )
        if result is not None:
            break
    assert result is not None, "violation did not reproduce on the host tier"
    assert result["violations"] > 0


def test_host_supervisor_applies_partitions_and_bursts():
    """apply_schedule drives the NetSim fault surface: partitions clog
    and heal with refcounts, bursts override and restore the config, and
    pause/resume edge-gate correctly."""
    from madsim_tpu.net import NetSim

    spec = FULL_SPEC
    schedule = [
        (100_000_000, "partition", 1),
        (150_000_000, "partition", 1),
        (200_000_000, "spike_on", 0),
        (250_000_000, "loss_on", 0),
        (300_000_000, "heal", 1),
        (400_000_000, "spike_off", 0),
        (450_000_000, "loss_off", 0),
        (500_000_000, "heal", 1),
        (600_000_000, "pause", 0),
        (700_000_000, "resume", 0),
        (800_000_000, "crash", 1),
        (900_000_000, "restart", 1),
    ]
    observed = {}

    async def main():
        h = ms.current_handle()
        ns = h.simulator(NetSim)
        nodes = [h.create_node().name(f"n{i}").build() for i in range(2)]
        base_latency = ns.config.net.send_latency

        async def probe():
            await ms.sleep(0.35)  # inside partition #2 + both bursts
            observed["clogged_mid"] = ns.network.is_clogged(
                nodes[1].id, nodes[0].id
            )
            observed["lat_mid"] = ns.config.net.send_latency
            observed["loss_mid"] = ns.config.net.packet_loss_rate

        ms.spawn(probe())
        await hfaults.apply_schedule(schedule, nodes, spec=spec)
        observed["clogged_end"] = ns.network.is_clogged(nodes[1].id, nodes[0].id)
        observed["lat_end"] = ns.config.net.send_latency
        observed["loss_end"] = ns.config.net.packet_loss_rate
        observed["base_latency"] = base_latency

    ms.Runtime(seed=1).block_on(main())
    assert observed["clogged_mid"], "heal #1 must not reopen window #2"
    assert observed["lat_mid"] == (
        spec.spike_lat_lo_ns / 1e9,
        spec.spike_lat_hi_ns / 1e9,
    )
    assert observed["loss_mid"] == spec.burst_loss_q32 / 2**32
    assert not observed["clogged_end"]
    assert observed["lat_end"] == observed["base_latency"]
    assert observed["loss_end"] == 0.0


# -- gray failures: asymmetric partitions, slow disks, power fail, skew ------

# every gray family enabled, windows inside the sim horizon
GRAY_SPEC = efaults.FaultSpec(
    crashes=1,
    crash_window_ns=1_000_000_000,
    restart_lo_ns=100_000_000,
    restart_hi_ns=400_000_000,
    aparts=2,
    apart_window_ns=1_200_000_000,
    apart_lo_ns=200_000_000,
    apart_hi_ns=600_000_000,
    fsync_stalls=1,
    fsync_window_ns=1_200_000_000,
    fsync_lo_ns=300_000_000,
    fsync_hi_ns=800_000_000,
    power_fails=1,
    power_window_ns=1_500_000_000,
    power_lo_ns=50_000_000,
    power_hi_ns=300_000_000,
    skews=1,
    skew_window_ns=1_200_000_000,
    skew_lo_ns=300_000_000,
    skew_hi_ns=800_000_000,
)


def test_gray_schedule_identical_on_both_tiers():
    """The gray grammar compiles to the identical (time, action, victim)
    schedule on both tiers — through the device engine's queue and
    dispatch, exactly like the clean families."""
    cfg = raft.RaftConfig(num_nodes=4, commands=0, faults=GRAY_SPEC)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    wl = raft.workload(cfg)
    for seed in (0, 11):
        _, trace = ecore.run_traced(wl, ecfg, seed)
        device = replay.extract_fault_schedule(trace, raft.K_FAULT)
        host = hfaults.compile_host(GRAY_SPEC, cfg.num_nodes, seed)
        assert device == host, (seed, device, host)
        assert len(device) == efaults.num_events(GRAY_SPEC)


def test_gray_schedule_families_windows_and_directions():
    dirs = set()
    for seed in range(16):
        sched = hfaults.compile_host(GRAY_SPEC, 4, seed)
        acts = {}
        for t, a, v in sched:
            acts.setdefault(a, []).append((t, v))
        # the asymmetric category draws a direction per window
        n_apart = sum(len(acts.get(a, [])) for a in ("part_in", "part_out"))
        assert n_apart == GRAY_SPEC.aparts
        dirs.update(a for a in ("part_in", "part_out") if a in acts)
        # every heal matches its window's direction and victim
        for on, off in (("part_in", "heal_in"), ("part_out", "heal_out")):
            assert sorted(v for _, v in acts.get(on, [])) == sorted(
                v for _, v in acts.get(off, [])
            )
            for t, _ in acts.get(on, []):
                assert 0 <= t < GRAY_SPEC.apart_window_ns
        assert len(acts["fsync_stall"]) == len(acts["fsync_ok"]) == 1
        assert len(acts["power_fail"]) == 1
        # power fail's off action IS restart (shared with crash storms)
        assert len(acts["restart"]) == GRAY_SPEC.crashes + GRAY_SPEC.power_fails
        assert len(acts["skew_on"]) == len(acts["skew_off"]) == 1
    assert dirs == {"part_in", "part_out"}, "both directions must occur"


def test_asymmetric_partition_clogs_one_direction():
    base = efaults.NetBase(1_000_000, 10_000_000, 0)
    links = enet.make(3)
    f = efaults.init_state(3)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_PART_IN, 1)
    assert bool(links.clog[0, 1]) and bool(links.clog[2, 1]), "inbound clogged"
    assert not bool(links.clog[1, 0]) and not bool(links.clog[1, 2]), (
        "outbound must stay open"
    )
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_HEAL_IN, 1)
    assert not bool(links.clog.any())
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_PART_OUT, 1)
    assert bool(links.clog[1, 0]) and not bool(links.clog[0, 1])
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_HEAL_OUT, 1)
    assert not bool(links.clog.any())


def test_overlapping_symmetric_and_asymmetric_partitions():
    """The satellite-6 regression: a symmetric heal must not un-clog a
    direction an overlapping asymmetric window still holds — neither on
    the same victim nor on a link cell shared with another victim."""
    base = efaults.NetBase(1_000_000, 10_000_000, 0)
    links = enet.make(3)
    f = efaults.init_state(3)
    # same victim: partition(1) + part_in(1), then heal(1)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_PART, 1)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_PART_IN, 1)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_HEAL, 1)
    assert bool(links.clog[0, 1]), "inbound still held by the asym window"
    assert not bool(links.clog[1, 0]), "outbound healed"
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_HEAL_IN, 1)
    assert not bool(links.clog.any())
    # different victims sharing a cell: node 0's out-clog holds [0, 1]
    # across node 1's symmetric heal
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_PART_OUT, 0)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_PART, 1)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_HEAL, 1)
    assert bool(links.clog[0, 1]), "cell still held by node 0's out window"
    assert not bool(links.clog[2, 1]) and not bool(links.clog[1, 2])
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_HEAL_OUT, 0)
    assert not bool(links.clog.any())
    assert int(f.part_in_cnt.sum()) == 0 and int(f.part_out_cnt.sum()) == 0


def test_fsync_and_skew_refcounts_compose():
    base = efaults.NetBase(1_000_000, 10_000_000, 0)
    links = enet.make(3)
    f = efaults.init_state(3)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_FSYNC_STALL, 2)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_FSYNC_STALL, 2)
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_FSYNC_OK, 2)
    assert bool(efaults.stalled(f)[2]), "still inside the second window"
    links, f = _apply(GRAY_SPEC, base, links, f, efaults.F_FSYNC_OK, 2)
    assert not bool(efaults.stalled(f).any())
    spec = GRAY_SPEC._replace(skew_num=2, skew_den=1)
    links, f = _apply(spec, base, links, f, efaults.F_SKEW_ON, 0)
    assert int(efaults.skewed_delay(spec, f, 0, 100)) == 200
    assert int(efaults.skewed_delay(spec, f, 1, 100)) == 100, "other nodes unskewed"
    links, f = _apply(spec, base, links, f, efaults.F_SKEW_OFF, 0)
    assert int(efaults.skewed_delay(spec, f, 0, 100)) == 100


def test_power_fail_drops_unsynced_raft_writes():
    """The device durability plane: a log entry appended while the
    node's disk is stalled is NOT durable — power fail (or crash) rolls
    the log back to the synced frontier; the same append on an
    unstalled node survives its crash."""
    # a spec WITH a stall window: the durability shadow is statically
    # gated on the spec (raft._shadow_nodes) — stall-free specs allocate
    # no shadow and keep the pre-gray crash semantics for free
    spec = efaults.FaultSpec(fsync_stalls=1)
    cfg = raft.RaftConfig(num_nodes=3, commands=0, faults=spec)
    wl = raft.workload(cfg)
    w, _ = wl.init(jax.random.key(0))
    w = w._replace(
        role=w.role.at[0].set(2).at[1].set(2),  # both nodes LEADER
        fstate=w.fstate._replace(fsync_cnt=w.fstate.fsync_cnt.at[0].set(1)),
    )
    rand = jnp.zeros((wl.num_rand,), jnp.uint32)

    def cmd(w, target):
        pay = jnp.zeros((wl.payload_slots,), jnp.int32).at[0].set(target)
        w2, _ = wl.handle(w, jnp.int64(1_000), jnp.int32(raft.K_CMD), pay, rand)
        return w2

    def fault(w, action, victim):
        pay = (
            jnp.zeros((wl.payload_slots,), jnp.int32)
            .at[0].set(action)
            .at[1].set(victim)
        )
        w2, _ = wl.handle(w, jnp.int64(2_000), jnp.int32(raft.K_FAULT), pay, rand)
        return w2

    w = cmd(cmd(w, 0), 1)  # one entry appended on each leader
    assert int(w.log_len[0]) == 1 and int(w.log_len[1]) == 1
    assert int(w.dur_log_len[0]) == 0, "stalled node: append not durable"
    assert int(w.dur_log_len[1]) == 1, "unstalled node synced immediately"
    w = fault(w, efaults.F_POWER_FAIL, 0)
    w = fault(w, efaults.F_CRASH, 1)
    assert int(w.log_len[0]) == 0, "unsynced entry dropped on power fail"
    assert int(w.log_len[1]) == 1, "synced entry survives the crash"
    # the disk catches up when the window closes: later appends persist
    w = fault(w, efaults.F_RESTART, 0)
    w = fault(w, efaults.F_FSYNC_OK, 0)
    w = cmd(w._replace(role=w.role.at[0].set(2)), 0)
    assert int(w.dur_log_len[0]) == 1
    w = fault(w, efaults.F_CRASH, 0)
    assert int(w.log_len[0]) == 1
    # stall-free specs allocate no shadow planes at all (static gating)
    plain = raft.workload(raft.RaftConfig(num_nodes=3, commands=0))
    w0, _ = plain.init(jax.random.key(0))
    assert w0.dur_term.shape == (0,)
    assert w0.dur_log_term.shape == (0, cfg.log_cap)


def test_skewed_node_arms_stretched_timers():
    """Clock skew on the device tier: a skewed victim's revival timer
    arms at the stretched deadline (timer arming runs on the node's own
    slow clock). The spec must draw skew windows — skew-free specs gate
    ``skewed_delay`` off statically (``efaults.can_skew``)."""
    spec = efaults.FaultSpec(skews=1, skew_num=2, skew_den=1)
    cfg = raft.RaftConfig(num_nodes=3, commands=0, faults=spec)
    wl = raft.workload(cfg)
    w, _ = wl.init(jax.random.key(0))
    rand = jnp.zeros((wl.num_rand,), jnp.uint32)  # bounded(0, lo, hi) == lo
    pay = jnp.zeros((wl.payload_slots,), jnp.int32)
    pay = pay.at[0].set(efaults.F_RESUME)  # victim 0
    now = 5_000
    for skewed in (False, True):
        w0 = w._replace(
            fstate=w.fstate._replace(
                paused=w.fstate.paused.at[0].set(True),
                skew_cnt=w.fstate.skew_cnt.at[0].set(1 if skewed else 0),
            )
        )
        _, emits = wl.handle(w0, jnp.int64(now), jnp.int32(raft.K_FAULT), pay, rand)
        times = {
            int(t)
            for t, k, en in zip(
                np.asarray(emits.times), np.asarray(emits.kinds),
                np.asarray(emits.enables),
            )
            if en and k == raft.K_ELECTION
        }
        factor = 2 if skewed else 1
        assert times == {now + factor * cfg.election_lo_ns}, (skewed, times)


def test_host_supervisor_applies_gray_actions():
    """apply_schedule drives the directional NetSim clogs and the
    TimeHandle skew registry with the same refcount semantics as the
    device interpreter."""
    from madsim_tpu.net import NetSim
    from madsim_tpu.runtime import _node_id

    schedule = [
        (100_000_000, "part_in", 1),
        (150_000_000, "partition", 1),
        (200_000_000, "skew_on", 0),
        (300_000_000, "heal", 1),  # out heals; in still held by part_in
        (400_000_000, "heal_in", 1),
        (500_000_000, "skew_off", 0),
    ]
    observed = {}

    async def main():
        h = ms.current_handle()
        ns = h.simulator(NetSim)
        nodes = [h.create_node().name(f"n{i}").build() for i in range(2)]

        async def probe():
            await ms.sleep(0.25)  # inside part_in + partition + skew
            observed["in_mid"] = ns.network.is_clogged(nodes[0].id, nodes[1].id)
            observed["out_mid"] = ns.network.is_clogged(nodes[1].id, nodes[0].id)
            observed["skew_mid"] = h.time.node_skew_of(_node_id(nodes[0]))
            await ms.sleep(0.1)  # after the symmetric heal
            observed["in_after_heal"] = ns.network.is_clogged(
                nodes[0].id, nodes[1].id
            )
            observed["out_after_heal"] = ns.network.is_clogged(
                nodes[1].id, nodes[0].id
            )

        ms.spawn(probe())
        await hfaults.apply_schedule(schedule, nodes, spec=GRAY_SPEC)
        observed["in_end"] = ns.network.is_clogged(nodes[0].id, nodes[1].id)
        observed["skew_end"] = h.time.node_skew_of(_node_id(nodes[0]))

    ms.Runtime(seed=1).block_on(main())
    assert observed["in_mid"] and observed["out_mid"]
    assert observed["skew_mid"] == (GRAY_SPEC.skew_num, GRAY_SPEC.skew_den)
    assert observed["in_after_heal"], "heal must not un-clog the asym window"
    assert not observed["out_after_heal"]
    assert not observed["in_end"]
    assert observed["skew_end"] == (1, 1)


def test_gray_campaign_sweep_is_deterministic_and_perturbs():
    """A full gray campaign through the sweep engine: replay parity
    holds and the gray faults demonstrably perturb schedules."""
    base_cfg = raft.RaftConfig(num_nodes=4, commands=4, crashes=0)
    cfg = base_cfg._replace(faults=GRAY_SPEC)
    ecfg = raft.engine_config(
        cfg, queue_capacity=160, time_limit_ns=3_000_000_000, max_steps=30_000
    )
    seeds = jnp.arange(48, dtype=jnp.int64)
    quiet = ecore.run_sweep(
        raft.workload(base_cfg._replace(faults=efaults.FaultSpec())), ecfg, seeds
    )
    gray = ecore.run_sweep(raft.workload(cfg), ecfg, seeds)
    s = raft.sweep_summary(gray)
    assert s["overflow_seeds"] == 0
    frac_changed = np.mean(np.asarray(quiet.ctr) != np.asarray(gray.ctr))
    assert frac_changed > 0.5, frac_changed
    single, _ = ecore.run_traced(raft.workload(cfg), ecfg, 17)
    assert int(single.ctr) == int(gray.ctr[17])


def test_etcd_campaign_server_crash_gates_processing():
    """Beyond the legacy partition-only etcd faults: a server-crash
    campaign compiles for the etcd model too — requests sent into the
    crash window go unanswered, the run stays violation-free."""
    spec = efaults.FaultSpec(
        crashes=1,
        crash_window_ns=1_000_000_000,
        restart_lo_ns=200_000_000,
        restart_hi_ns=600_000_000,
        crash_group=(0, 1),
    )
    cfg = etcd.EtcdConfig(faults=spec)
    ecfg = etcd.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(etcd.workload(cfg), ecfg, jnp.arange(32, dtype=jnp.int64))
    s = etcd.sweep_summary(final)
    assert s["violations"] == 0, s
    assert s["puts"] > 0 and s["gets"] > 0
    # requests outnumber replies: the dead-server window swallowed some
    assert s["msgs_sent"] > s["msgs_delivered"]
