"""etcd sim tests — port of madsim-etcd-client/tests/test.rs (314 lines):
kv/txn flows, lease TTL expiry on simulated time (a 60 s sleep is instant),
election campaign/proclaim/observe/resign, request-too-large, timeout
injection, and dump/load snapshot-restore.
"""

import pytest

import madsim_tpu as ms
from madsim_tpu import etcd
from madsim_tpu.etcd import (
    Compare,
    CompareOp,
    DeleteOptions,
    GetOptions,
    PutOptions,
    SimServer,
    Txn,
    TxnOp,
)
from madsim_tpu.grpc import Code, Status

ADDR = "10.0.0.1:2379"


def with_cluster(seed, client_fn, timeout_rate=0.0):
    rt = ms.Runtime(seed=seed)

    async def main():
        h = ms.current_handle()
        h.create_node().name("etcd").ip("10.0.0.1").init(
            lambda: SimServer.builder().timeout_rate(timeout_rate).serve(ADDR)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)
        return await node.spawn(client_fn())

    return rt.block_on(main())


def test_kv_put_get_delete_prefix():
    async def run():
        client = await etcd.Client.connect([ADDR])
        kv = client.kv_client()
        await kv.put("hello", "world", None)
        resp = await kv.get("hello", None)
        assert resp.kvs()[0].value_str() == "world"
        assert resp.count() == 1
        # versions/revisions advance
        r1 = (await kv.put("hello", "world2", None)).header().revision()
        resp = await kv.get("hello", None)
        assert resp.kvs()[0].mod_revision == r1
        assert resp.kvs()[0].version == 2
        # prefix range
        await kv.put("key/a", "1", None)
        await kv.put("key/b", "2", None)
        resp = await kv.get("key/", GetOptions().with_prefix())
        assert [k.key_str() for k in resp.kvs()] == ["key/a", "key/b"]
        # delete with prefix
        dresp = await kv.delete("key/", DeleteOptions().with_prefix())
        assert dresp.deleted() == 2
        assert (await kv.get("key/", GetOptions().with_prefix())).count() == 0

    with_cluster(21, run)


def test_txn_compare_and_ops():
    async def run():
        client = await etcd.Client.connect([ADDR])
        kv = client.kv_client()
        await kv.put("k", "v1", None)
        # success branch
        resp = await kv.txn(
            Txn()
            .when([Compare.value("k", CompareOp.EQUAL, "v1")])
            .and_then([TxnOp.put("k", "v2", None), TxnOp.get("k", None)])
            .or_else([TxnOp.put("k", "wrong", None)])
        )
        assert resp.succeeded()
        # failure branch + nested txn (recursive — service.rs txn)
        resp = await kv.txn(
            Txn()
            .when([Compare.value("k", CompareOp.EQUAL, "v1")])
            .and_then([TxnOp.put("k", "nope", None)])
            .or_else([TxnOp.txn(Txn().and_then([TxnOp.put("k", "v3", None)]))])
        )
        assert not resp.succeeded()
        assert (await kv.get("k", None)).kvs()[0].value_str() == "v3"

    with_cluster(22, run)


def test_lease_expiry_on_sim_time():
    """Lease TTL runs on virtual seconds — sleeping 61 s is instant in
    wall time (ref tests/test.rs:96-120)."""

    async def run():
        client = await etcd.Client.connect([ADDR])
        lease = client.lease_client()
        kv = client.kv_client()
        granted = await lease.grant(60)
        lid = granted.id()
        await kv.put("leased", "v", PutOptions().with_lease(lid))
        assert (await kv.get("leased", None)).count() == 1
        # keep alive halfway: lease survives past the original deadline
        await ms.sleep(30)
        await lease.keep_alive(lid)
        await ms.sleep(40)
        assert (await kv.get("leased", None)).count() == 1
        ttl = await lease.time_to_live(lid)
        assert ttl.granted_ttl() == 60
        # stop keeping alive: expiry deletes the attached key
        await ms.sleep(61)
        assert (await kv.get("leased", None)).count() == 0
        with pytest.raises(Status) as e:
            await lease.time_to_live(lid)
        assert e.value.code == Code.NOT_FOUND

    with_cluster(23, run)


def test_lease_revoke_deletes_keys():
    async def run():
        client = await etcd.Client.connect([ADDR])
        lease, kv = client.lease_client(), client.kv_client()
        lid = (await lease.grant(600)).id()
        await kv.put("a", "1", PutOptions().with_lease(lid))
        await kv.put("b", "2", PutOptions().with_lease(lid))
        assert (await lease.leases()) == [lid]
        await lease.revoke(lid)
        assert (await kv.get("a", None)).count() == 0
        assert (await kv.get("b", None)).count() == 0

    with_cluster(24, run)


def test_election_campaign_observe_resign():
    """Two campaigners: first wins immediately; on resign the second
    takes over (ref tests/test.rs election flow)."""

    async def run():
        client = await etcd.Client.connect([ADDR])
        lease = client.lease_client()
        el = client.election_client()
        l1 = (await lease.grant(600)).id()
        l2 = (await lease.grant(600)).id()

        c1 = await el.campaign("mayor", "alice", l1)
        assert (await el.leader("mayor")).kv().value_str() == "alice"

        # second campaigner blocks; run it as a task
        async def second():
            c2 = await el.campaign("mayor", "bob", l2)
            return c2

        t2 = ms.spawn(second())
        await ms.sleep(1)
        assert not t2.done()
        # proclaim updates the leader value
        await el.proclaim("alice-2", c1.leader())
        assert (await el.leader("mayor")).kv().value_str() == "alice-2"
        # observe sees changes
        obs = await el.observe("mayor")
        first = await obs.next()
        assert first.value.decode() in ("alice-2", "bob")
        # resign → bob elected
        await el.resign(c1.leader())
        c2 = await t2
        assert c2.leader().key().startswith(b"mayor/")
        assert (await el.leader("mayor")).kv().value_str() == "bob"
        obs.cancel()

    with_cluster(25, run)


def test_request_too_large():
    """1.5 MiB request cap (service.rs:36; ref tests/test.rs:9-40)."""

    async def run():
        client = await etcd.Client.connect([ADDR])
        kv = client.kv_client()
        with pytest.raises(Status) as e:
            await kv.put("big", b"x" * (2 * 1024 * 1024), None)
        assert e.value.code == Code.INVALID_ARGUMENT
        assert "too large" in e.value.message

    with_cluster(26, run)


def test_timeout_rate_injection():
    """timeout_rate=1.0: every request hangs 5-15 virtual seconds then
    fails Unavailable (server.rs:20-25, service.rs:165-176)."""

    async def run():
        client = await etcd.Client.connect([ADDR])
        t0 = ms.time.elapsed()
        with pytest.raises(Status) as e:
            await client.kv_client().put("k", "v", None)
        assert e.value.code == Code.UNAVAILABLE
        assert 5.0 <= ms.time.elapsed() - t0 <= 16.0

    with_cluster(27, run, timeout_rate=1.0)


def test_dump_load_snapshot_restore():
    """State dump/load round-trip (service.rs:160-163, sim.rs:70-77)."""
    rt = ms.Runtime(seed=28)

    async def main():
        h = ms.current_handle()
        h.create_node().name("etcd1").ip("10.0.0.1").init(
            lambda: SimServer.builder().serve(ADDR)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            client = await etcd.Client.connect([ADDR])
            kv = client.kv_client()
            lid = (await client.lease_client().grant(300)).id()
            await kv.put("persist", "me", PutOptions().with_lease(lid))
            await kv.put("also", "this", None)
            dump = await client.dump()
            # a fresh server restored from the dump serves the same state
            h2 = ms.current_handle()
            h2.create_node().name("etcd2").ip("10.0.0.3").init(
                lambda: SimServer.builder().load(dump).serve("10.0.0.3:2379")
            ).build()
            await ms.sleep(0.1)
            c2 = await etcd.Client.connect(["10.0.0.3:2379"])
            resp = await c2.kv_client().get("persist", None)
            assert resp.kvs()[0].value_str() == "me"
            assert resp.kvs()[0].lease == lid
            assert (await c2.kv_client().get("also", None)).count() == 1

        await node.spawn(run())

    rt.block_on(main())


def test_watch_prefix_stream():
    async def run():
        client = await etcd.Client.connect([ADDR])
        stream = await client.watch_client().watch("w/", prefix=True)
        kv = client.kv_client()

        async def writer():
            await kv.put("w/1", "a", None)
            await kv.put("other", "x", None)
            await kv.put("w/2", "b", None)
            await kv.delete("w/1", None)

        ms.spawn(writer())
        e1 = await stream.next()
        assert e1.type == etcd.EventType.PUT and e1.kv.key == b"w/1"
        e2 = await stream.next()
        assert e2.kv.key == b"w/2"
        e3 = await stream.next()
        assert e3.type == etcd.EventType.DELETE and e3.kv.key == b"w/1"
        stream.cancel()

    with_cluster(29, run)


def test_etcd_determinism():
    def workload():
        async def main():
            h = ms.current_handle()
            h.create_node().name("etcd").ip("10.0.0.1").init(
                lambda: SimServer.builder().serve(ADDR)
            ).build()
            node = h.create_node().name("client").ip("10.0.0.2").build()
            await ms.sleep(0.1)

            async def run():
                client = await etcd.Client.connect([ADDR])
                for i in range(5):
                    await client.kv_client().put(f"k{i}", f"v{i}", None)
                assert (await client.kv_client().get(
                    "k", GetOptions().with_prefix())).count() == 5

            await node.spawn(run())

        return main()

    ms.Runtime.check_determinism(31, workload)


def test_maintenance_status():
    """maintenance_client().status() reports server state
    (ref tests/test.rs:240-263)."""

    async def run():
        client = await etcd.Client.connect([ADDR])
        kv = client.kv_client()
        await kv.put("sk", "sv", None)
        status = await client.maintenance_client().status()
        assert status is not None

    with_cluster(97, run)
