"""The self-steering scheduler's pure-host layer (explore/steer.py).

The contracts under test (docs/steering.md): family keying partitions
the envelope stably, mutation-chain candidates regenerate
bit-identically anywhere (mask-confined, salt-namespaced), the UCB
bandit's decisions are a pure function of the absorbed outcome prefix
(cold order, exploit, deterministic tie-break), kill/escalate
transitions fire exactly at their thresholds (and never orphan the
last live family), uniform mode is the matched round-robin with NO
transitions, the fleet's stats fold is partition-invariant, and a
replayed decision sequence emits byte-identical trace lines. Nothing
here touches a device: the real sweeps live in scripts/steer_demo.py
(``make steer-smoke``) and the check_determinism.sh steering leg.
"""

import json

from madsim_tpu.engine.faults import FaultSpec
from madsim_tpu.explore.campaign import _COUNT_FIELDS, CampaignConfig
from madsim_tpu.explore.steer import (
    BanditScheduler,
    SteerConfig,
    _jfields,
    family_candidate,
    family_key,
    family_of,
    family_universe,
    fold_family_stats,
    plan_unit_steered,
)

_CRASHY = FaultSpec(crashes=2, crash_window_ns=1_500_000_000)


# -- family keying ----------------------------------------------------------


def test_family_of_is_the_active_category_bitmask():
    assert family_of(FaultSpec()) == 0
    assert family_of(_CRASHY) == 0x001  # crashes = bit 0
    assert family_of(FaultSpec(partitions=1)) == 0x002
    assert family_of(FaultSpec(crashes=1, partitions=2, skews=3)) == 0x103
    # windows/durations never affect the family
    assert family_of(
        _CRASHY._replace(crash_window_ns=1)
    ) == family_of(_CRASHY)


def test_family_key_is_fixed_width_sortable_hex():
    assert family_key(0x001) == "001"
    assert family_key(0x1FF) == "1ff"
    keys = [family_key(m) for m in range(0x200)]
    assert keys == sorted(keys)


def test_family_universe_crashes_base_is_17_families():
    # 9 singles + the base (already the crashes single) + base|each
    # other single = 17 sorted, deduped masks — the mostly-dud universe
    # the A/B runs on (docs/steering.md)
    uni = family_universe(_CRASHY)
    assert len(uni) == 17
    assert uni == tuple(sorted(uni))
    assert 0x001 in uni and 0x003 in uni and 0x101 in uni
    singles = {1 << i for i in range(len(_COUNT_FIELDS))}
    assert singles <= set(uni)


def test_family_universe_empty_base_is_the_singles():
    assert family_universe(FaultSpec()) == tuple(
        1 << i for i in range(len(_COUNT_FIELDS))
    )


# -- mutation-chain candidates ----------------------------------------------


def test_family_candidate_lineage0_is_the_masked_base():
    spec = family_candidate(_CRASHY, 0x001, 7, 0)
    assert spec == _CRASHY
    # off-mask categories are forced quiet, on-mask active
    spec = family_candidate(_CRASHY, 0x002, 7, 0)
    assert spec.crashes == 0 and spec.partitions >= 1


def test_family_candidate_is_pure_and_chains_move():
    a = family_candidate(_CRASHY, 0x003, 7, 3)
    b = family_candidate(_CRASHY, 0x003, 7, 3)
    assert a == b
    chain = [family_candidate(_CRASHY, 0x003, 7, i) for i in range(4)]
    assert all(x != y for x, y in zip(chain, chain[1:]))
    # confinement holds along the whole chain
    for spec in chain:
        assert family_of(spec) == 0x003
    # a different campaign seed is a different chain
    assert family_candidate(_CRASHY, 0x003, 8, 3) != a


def test_family_candidate_single_category_chains_still_move():
    # mutations hitting off-mask fields no-op after re-masking; the
    # bounded retry must keep even 1-bit-mask chains moving
    chain = [family_candidate(_CRASHY, 0x002, 7, i) for i in range(3)]
    assert all(x != y for x, y in zip(chain, chain[1:]))


def test_family_candidate_salt_namespaces_and_offsets_chains():
    # a salted chain starts one mutation deep: lineage 0 is NOT the
    # masked base, and two salts diverge at every lineage — fleet units
    # of one generation sweep distinct specs
    base0 = family_candidate(_CRASHY, 0x001, 7, 0)
    s1 = family_candidate(_CRASHY, 0x001, 7, 0, salt=1)
    s2 = family_candidate(_CRASHY, 0x001, 7, 0, salt=2)
    assert s1 != base0 and s2 != base0 and s1 != s2
    assert family_candidate(_CRASHY, 0x001, 7, 0, salt=1) == s1


# -- the bandit -------------------------------------------------------------

_UNI = (0x001, 0x002, 0x004)


def _sched(scfg=None, universe=_UNI, **kw):
    scfg = scfg or SteerConfig()
    kw.setdefault("seeds_per_play", 16)
    kw.setdefault("budget_lo", 100)
    kw.setdefault("budget_hi", 200)
    return BanditScheduler(universe, scfg, **kw)


def _barren(events=1000):
    return {"events": events, "new_bits": 0, "vio": 0, "fresh": 0, "dup": 0}


def test_cold_plays_cover_the_universe_in_mask_order():
    s = _sched()
    recs = [s.decide() for _ in range(3)]
    assert [r["why"] for r in recs] == ["cold", "cold", "cold"]
    assert [r["family"] for r in recs] == ["001", "002", "004"]
    assert [r["i"] for r in recs] == [0, 1, 2]
    assert all(r["seeds"] == 16 and r["budget"] == 100 for r in recs)


def test_ucb_exploits_the_rewarding_family():
    s = _sched(SteerConfig(kill_plays=99))
    order = [s.decide()["family"] for _ in range(3)]
    assert order == ["001", "002", "004"]
    # 001 pays out; the others are barren at the same event cost
    s.absorb(0x001, {"events": 1000, "new_bits": 40, "vio": 0,
                     "fresh": 1, "dup": 0})
    s.absorb(0x002, _barren())
    s.absorb(0x004, _barren())
    rec = s.decide()
    assert rec["why"] == "ucb"
    assert rec["family"] == "001"
    assert rec["score_micro"] > 0


def test_uniform_is_round_robin_with_no_transitions():
    s = _sched(SteerConfig(scheduler="uniform", kill_plays=1))
    fams = []
    for _ in range(6):
        rec = s.decide()
        fams.append(rec["family"])
        assert rec["why"] == "uniform"
        # violations everywhere: uniform must neither kill nor escalate
        s.absorb(int(rec["family"], 16),
                 {"events": 10, "new_bits": 0, "vio": 3,
                  "fresh": 0, "dup": 3})
    assert fams == ["001", "002", "004"] * 2
    assert not s.killed and not s.escalated
    assert all(r["kind"] in ("decide", "outcome") for r in s.trace)


def test_barren_family_is_killed_at_kill_plays():
    s = _sched(SteerConfig(kill_plays=2))
    for _ in range(3):
        s.decide()
    s.absorb(0x001, _barren())
    assert not s.killed  # one barren play < kill_plays
    s.decide()
    s.absorb(0x001, _barren())
    assert s.killed == {0x001: "barren"}
    kills = [r for r in s.trace if r["kind"] == "kill"]
    assert kills == [{"kind": "kill", "family": "001",
                      "why": "barren", "at": 1}]
    # killed families leave the pick rotation
    assert 0x001 not in {int(s.decide()["family"], 16) for _ in range(4)}


def test_dup_saturated_family_is_killed():
    s = _sched(SteerConfig(kill_plays=2, kill_dup_rate_pct=90))
    for _ in range(3):
        s.decide()
    # a rich first play (one fresh fingerprint, nine dups) leaves the
    # family at a 90% dedup hit rate but NOT barren; the next all-dup
    # play makes it stuck (barren >= 1) with the rate still saturated —
    # the dup-saturated kill, distinct from the barren one (which would
    # need kill_plays consecutive empty plays)
    s.absorb(0x001, {"events": 10, "new_bits": 0, "vio": 10,
                     "fresh": 1, "dup": 9})
    assert not s.killed
    s.decide()
    s.absorb(0x001, {"events": 10, "new_bits": 0, "vio": 2,
                     "fresh": 0, "dup": 2})
    assert s.killed.get(0x001) == "dup-saturated"


def test_last_live_family_is_never_killed():
    s = _sched(SteerConfig(kill_plays=1), universe=(0x001,))
    for _ in range(5):
        s.decide()
        s.absorb(0x001, _barren())
    assert not s.killed


def test_first_violation_escalates_seeds_and_budget():
    s = _sched(SteerConfig(escalate_seeds=4, kill_plays=99))
    for _ in range(3):
        s.decide()
    s.absorb(0x002, {"events": 10, "new_bits": 5, "vio": 1,
                     "fresh": 1, "dup": 0})
    assert s.escalated == [0x002]
    esc = [r for r in s.trace if r["kind"] == "escalate"]
    assert esc == [{"kind": "escalate", "family": "002", "at": 0}]
    # a second violation in the same family does NOT re-escalate
    s.absorb(0x001, {"events": 10, "new_bits": 0, "vio": 2,
                     "fresh": 1, "dup": 1})
    assert s.escalated == [0x002, 0x001]
    # the hot family's next decision gets 4x seeds + the long budget
    while True:
        rec = s.decide()
        if rec["family"] == "002":
            break
        s.absorb(int(rec["family"], 16), _barren())
    assert rec["hot"] and rec["seeds"] == 64 and rec["budget"] == 200


def test_replayed_decision_sequence_is_byte_identical():
    def drill():
        s = _sched(SteerConfig(kill_plays=2, escalate_seeds=3))
        outcomes = {
            "001": {"events": 900, "new_bits": 12, "vio": 1,
                    "fresh": 1, "dup": 0},
            "002": _barren(),
            "004": {"events": 1100, "new_bits": 2, "vio": 0,
                    "fresh": 0, "dup": 0},
        }
        for _ in range(2):
            s.decide()
        for _ in range(8):
            rec = s.decide()
            s.absorb(int(rec["family"], 16), outcomes[rec["family"]])
        return s.trace_lines()

    a, b = drill(), drill()
    assert a == b
    # every trace line is deterministic JSON: sorted keys, no wall times
    for ln in a.splitlines():
        rec = json.loads(ln)
        assert list(rec) == sorted(rec)
        assert "ts" not in rec and "wall" not in rec


def test_scheduler_rejects_bad_config():
    import pytest

    with pytest.raises(ValueError):
        _sched(universe=())
    with pytest.raises(ValueError):
        _sched(SteerConfig(scheduler="greedy"))


# -- the fleet fold + steered unit plan -------------------------------------


def _cand(unit, cand, fam, cov, vio=0, seeds=(), events=500):
    return (
        f"{unit:06d}/{cand:02d}",
        {
            "unit": unit, "cand": cand, "family": fam,
            "coverage_map": cov, "violations": vio,
            "violating_seeds": list(seeds), "events_total": events,
        },
    )


def _bug(unit, cand, fp):
    return (fp, {"unit": unit, "cand": cand, "fingerprint": fp})


def test_fold_family_stats_counts_and_dedups():
    cands = [
        _cand(0, 0, "001", [0b0011], vio=2, seeds=[3, 9]),
        _cand(0, 1, "002", [0b0100]),
        _cand(1, 0, "001", [0b0011], vio=1, seeds=[5]),  # no new bits
    ]
    bugs = [
        _bug(0, 0, "raft:f1:k2:n1"),
        _bug(1, 0, "raft:f1:k2:n1"),  # dup of the first
    ]
    stats = fold_family_stats(cands, bugs)
    st = stats[0x001]
    assert st["plays"] == 2 and st["events"] == 1000
    assert st["new_bits"] == 2  # only the first 001 candidate's bits
    assert st["vio"] == 3
    assert st["fresh"] == 1 and st["dup"] == 2
    assert st["barren"] == 1  # the second 001 play earned nothing
    assert stats[0x002]["new_bits"] == 1
    assert stats[0x002]["barren"] == 0


def test_fold_family_stats_is_input_order_invariant():
    cands = [
        _cand(0, 0, "001", [0b01], vio=1, seeds=[3]),
        _cand(0, 1, "002", [0b10]),
        _cand(1, 0, "004", [0b11]),
    ]
    bugs = [_bug(0, 0, "fp-a"), _bug(1, 0, "fp-b")]
    fwd = fold_family_stats(cands, bugs)
    rev = fold_family_stats(cands[::-1], bugs[::-1])
    assert fwd == rev


def test_fold_family_stats_skips_unsteered_records():
    key, payload = _cand(0, 0, "001", [1])
    del payload["family"]
    assert fold_family_stats([(key, payload)], []) == {}


def test_plan_unit_steered_is_deterministic_and_unit_salted():
    ccfg = CampaignConfig(seeds_per_round=16, campaign_seed=7, batch=3)
    scfg = SteerConfig(families=_UNI)
    stats = {0x001: dict(plays=2, events=1000, new_bits=30, vio=1,
                         fresh=1, dup=0, barren=0)}
    p2 = plan_unit_steered(_CRASHY, ccfg, scfg, 2, stats)
    p2b = plan_unit_steered(_CRASHY, ccfg, scfg, 2, dict(stats))
    assert p2 == p2b  # any worker plans the unit identically
    assert len(p2) == 3
    # a generation peer picks from the same primed stats but sweeps
    # DISTINCT candidates (unit-salted chains)
    p3 = plan_unit_steered(_CRASHY, ccfg, scfg, 3, stats)
    assert [m for m, _ in p2] == [m for m, _ in p3]
    assert all(a != b for (_, a), (_, b) in zip(p2, p3))


def test_plan_unit_steered_primes_escalation_and_kills():
    ccfg = CampaignConfig(seeds_per_round=16, campaign_seed=7, batch=4)
    scfg = SteerConfig(families=_UNI, kill_plays=1)
    stats = {
        0x001: dict(plays=1, events=500, new_bits=0, vio=0,
                    fresh=0, dup=0, barren=1),  # killable on arrival
        0x002: dict(plays=1, events=500, new_bits=9, vio=2,
                    fresh=1, dup=0, barren=0),  # hot on arrival
    }
    plan = plan_unit_steered(_CRASHY, ccfg, scfg, 0, stats)
    masks = [m for m, _ in plan]
    assert 0x001 not in masks  # barren family killed before planning
    assert 0x002 in masks  # the hot family keeps earning compute


# -- journal mirroring ------------------------------------------------------


def test_jfields_moves_kind_to_step():
    rec = {"kind": "decide", "i": 4, "family": "001"}
    out = _jfields(rec)
    assert out == {"step": "decide", "i": 4, "family": "001"}
    assert rec["kind"] == "decide"  # input untouched
