"""S3 device workload: healthy sweeps are quiet, the ack-before-durable
bug is caught at a reported seed, and traced CPU replay matches the sweep.

Fourth workload on the shared engine substrate (after Raft, Kafka, etcd):
an object store with the full multipart lifecycle and crash-abort of
staged uploads (ref service model:
madsim-aws-sdk-s3/src/server/service.rs:204-346).
"""

import jax
import jax.numpy as jnp
import numpy as np

from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.rng import prob_to_q32
from madsim_tpu.models import s3

CFG = s3.S3Config()
ECFG = s3.engine_config(CFG, time_limit_ns=3_000_000_000, max_steps=30_000)

BUG_CFG = CFG._replace(bug_ack_before_durable=True, crashes=2)
BUG_ECFG = s3.engine_config(BUG_CFG, time_limit_ns=3_000_000_000, max_steps=30_000)


def test_healthy_sweep_quiet_and_progresses():
    final = ecore.run_sweep(s3.workload(CFG), ECFG, jnp.arange(256, dtype=jnp.int64))
    s = s3.sweep_summary(final)
    assert s["violations"] == 0, s
    assert s["ack_loss_seeds"] == 0 and s["regress_seeds"] == 0
    # every op family actually ran: singles, and the multipart lifecycle
    assert s["puts"] > 0 and s["gets"] > 0 and s["dels"] > 0
    assert s["creates"] > 0 and s["parts"] > 0 and s["completes"] > 0
    # crash-abort of staged uploads was exercised (NoSuchUpload restarts)
    assert s["upload_restarts"] > 0
    assert s["crashes"] > 0
    # bounded structures stayed bounded
    assert s["overflow_seeds"] == 0
    assert s["queue_high_water"] <= ECFG.queue_capacity
    # sent counts attempts, delivered counts link-test passes
    assert s["msgs_sent"] >= s["msgs_delivered"] > 0


def test_durability_and_version_invariants_in_correct_mode():
    final = ecore.run_sweep(s3.workload(CFG), ECFG, jnp.arange(128, dtype=jnp.int64))
    w = final.wstate
    ver_com = np.asarray(w.ver_com)
    ver_dur = np.asarray(w.ver_dur)
    len_com = np.asarray(w.len_com)
    # the durable tier never leads the committed tier
    assert (ver_dur <= ver_com).all()
    # correct mode: every acked version is durable (the S3 contract)
    assert (np.asarray(w.last_acked_ver) <= ver_dur).all()
    # committed lengths are absent (-1), a put (1..max), or an assembled
    # multipart (P * part_len) — never a torn intermediate
    mp_len = CFG.parts_per_upload * CFG.part_len
    ok = (
        (len_com == -1)
        | ((len_com >= 1) & (len_com <= CFG.max_put_len))
        | (len_com == mp_len)
    )
    assert ok.all()


def test_ack_before_durable_bug_is_caught():
    """The deliberate bug (ack at processing, durability at flush) +
    server crash = acknowledged-object loss; the online checker must
    latch it at some seed and the seed must be reported for replay."""
    final = ecore.run_sweep(
        s3.workload(BUG_CFG), BUG_ECFG, jnp.arange(512, dtype=jnp.int64)
    )
    s = s3.sweep_summary(final)
    assert s["ack_loss_seeds"] > 0, f"checker failed to catch the bug: {s}"
    bad = np.asarray(final.seed)[np.asarray(final.wstate.vio_ack_loss)]
    assert bad.size > 0
    # every violating seed reproduces under single-seed traced replay on CPU
    seed = int(bad[0])
    with jax.default_device(jax.devices("cpu")[0]):
        replayed, _trace = ecore.run_traced(s3.workload(BUG_CFG), BUG_ECFG, seed)
    assert bool(replayed.wstate.vio_ack_loss)


def test_correct_mode_never_loses_acked_under_same_faults():
    """Same fault plan as the bug test, correct synchronous durability:
    the checker stays quiet (the bug is in the policy, not the checker)."""
    cfg = BUG_CFG._replace(bug_ack_before_durable=False)
    final = ecore.run_sweep(
        s3.workload(cfg),
        s3.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000),
        jnp.arange(512, dtype=jnp.int64),
    )
    s = s3.sweep_summary(final)
    assert s["violations"] == 0, s
    assert s["crashes"] > 0  # faults really fired


def test_traced_replay_matches_sweep():
    """Bit-exact cross-check: run_traced on a few seeds reproduces the
    sweep's per-seed terminal state exactly (the CPU-replay contract)."""
    wl = s3.workload(CFG)
    seeds = jnp.arange(6, dtype=jnp.int64)
    final = ecore.run_sweep(wl, ECFG, seeds)
    for i in range(6):
        single, _ = ecore.run_traced(wl, ECFG, int(seeds[i]))
        assert int(single.ctr) == int(final.ctr[i])
        assert int(single.now_ns) == int(final.now_ns[i])
        assert int(single.wstate.completes) == int(final.wstate.completes[i])
        assert int(single.wstate.gets) == int(final.wstate.gets[i])
        assert bool(single.wstate.violation) == bool(final.wstate.violation[i])


def test_clients_finish_their_op_budget_under_loss():
    """Retry-until-ack liveness: under 30% packet loss with no crashes,
    clients still complete (nearly) their whole op budget — a lost
    request, response, or part ack must never wedge a client."""
    cfg = CFG._replace(loss_q32=prob_to_q32(0.30), crashes=0)
    ecfg = s3.engine_config(cfg, time_limit_ns=6_000_000_000, max_steps=60_000)
    final = ecore.run_sweep(s3.workload(cfg), ecfg, jnp.arange(64, dtype=jnp.int64))
    ops_done = np.asarray(final.wstate.ops_done)  # [S, NC]
    assert ops_done.mean() > 0.8 * cfg.ops_per_client, ops_done.mean()
    assert s3.sweep_summary(final)["violations"] == 0


def test_different_seeds_diverge():
    final = ecore.run_sweep(s3.workload(CFG), ECFG, jnp.arange(32, dtype=jnp.int64))
    assert len(np.unique(np.asarray(final.ctr))) > 1
