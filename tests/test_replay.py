"""Cross-tier replay pipeline: TPU sweep → violation seed → bit-exact CPU
trace → fault-plan extraction → host-tier reproduction in user code.

This is SURVEY.md §7 stage 5's acceptance: a failure found by the batched
device engine must be actionable on the host tier, where the workload is
ordinary async Python a debugger can step through. The demo bug is the
host example's real amnesia flaw (in-memory state lost on restart →
double vote in the same term), mirrored on the device by
``RaftConfig.volatile_state``.
"""

import sys

import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/examples")

import raft_host

from madsim_tpu import replay
from madsim_tpu.engine import core as ecore
from madsim_tpu.models import raft

CFG, ECFG = replay.amnesia_raft_config()


def _sweep(n_seeds=160):
    return ecore.run_sweep(raft.workload(CFG), ECFG, jnp.arange(n_seeds, dtype=jnp.int64))


def test_sweep_to_host_replay_end_to_end():
    # 1. the sweep flags violation seeds (deterministic: 50, 93, 136, ...)
    final = _sweep()
    vio = replay.violation_seeds(final)
    assert vio.size > 0, "amnesia sweep found no violations"

    seed = int(vio[1]) if vio.size > 1 else int(vio[0])
    # 2. single-seed CPU trace confirms the violation bit-exactly
    single, trace = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    assert bool(single.wstate.violation)

    # 3. the recorded fault schedule is well-formed — and identical to
    # what compiling the spec directly yields (the trace hop adds no
    # drift: exact deadlines survive the payload round-trip)
    plan = replay.extract_fault_schedule(trace, raft.K_FAULT)
    assert len(plan) == 2 * CFG.crashes
    assert {a for _, a, _ in plan} == {"crash", "restart"}
    assert all(0 <= node < CFG.num_nodes for _, _, node in plan)
    from madsim_tpu import faults as hfaults

    assert plan == hfaults.compile_host(
        raft.fault_spec(CFG), CFG.num_nodes, seed
    )

    # 4. the same fault schedule breaks the host-tier user code: the
    # supervisor kills/restarts at the recorded virtual times and the
    # example's own election-safety check records the double-vote
    result = replay.replay_on_host(
        lambda hs, p: raft_host.run_seed_with_plan(hs, p, n=CFG.num_nodes,
                                                   sim_seconds=3.0),
        plan,
        host_seeds=range(10),
    )
    assert result is not None, "violation did not reproduce on the host tier"
    assert result["violations"] > 0
    assert result["leaders_elected"] > 0


def test_fault_plan_extraction_is_deterministic():
    seed = 93
    _, t1 = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    _, t2 = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    p1 = replay.extract_fault_schedule(t1, raft.K_FAULT)
    p2 = replay.extract_fault_schedule(t2, raft.K_FAULT)
    assert p1 == p2 and len(p1) == 2 * CFG.crashes


def test_durable_state_config_stays_quiet():
    """Control: with real durable-state semantics the same fault pressure
    produces no violations (the bug is the amnesia, not the checker)."""
    cfg = CFG._replace(volatile_state=False)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(raft.workload(cfg), ecfg, jnp.arange(160, dtype=jnp.int64))
    assert raft.sweep_summary(final)["violations"] == 0
