"""Cross-tier replay pipeline: TPU sweep → violation seed → bit-exact CPU
trace → fault-plan extraction → host-tier reproduction in user code.

This is SURVEY.md §7 stage 5's acceptance: a failure found by the batched
device engine must be actionable on the host tier, where the workload is
ordinary async Python a debugger can step through. The demo bug is the
host example's real amnesia flaw (in-memory state lost on restart →
double vote in the same term), mirrored on the device by
``RaftConfig.volatile_state``.
"""

import sys

import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/examples")

import raft_host

from madsim_tpu import replay
from madsim_tpu.engine import core as ecore
from madsim_tpu.models import raft

CFG, ECFG = replay.amnesia_raft_config()


def _sweep(n_seeds=160):
    return ecore.run_sweep(raft.workload(CFG), ECFG, jnp.arange(n_seeds, dtype=jnp.int64))


def test_sweep_to_host_replay_end_to_end():
    # 1. the sweep flags violation seeds (deterministic: 6, 16, 46, ...)
    final = _sweep()
    vio = replay.violation_seeds(final)
    assert vio.size > 0, "amnesia sweep found no violations"

    seed = int(vio[1]) if vio.size > 1 else int(vio[0])
    # 2. single-seed CPU trace confirms the violation bit-exactly
    single, trace = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    assert bool(single.wstate.violation)

    # 3. the recorded fault schedule is well-formed — and identical to
    # what compiling the spec directly yields (the trace hop adds no
    # drift: exact deadlines survive the payload round-trip)
    plan = replay.extract_fault_schedule(trace, raft.K_FAULT)
    assert len(plan) == 2 * CFG.crashes
    assert {a for _, a, _ in plan} == {"crash", "restart"}
    assert all(0 <= node < CFG.num_nodes for _, _, node in plan)
    from madsim_tpu import faults as hfaults

    assert plan == hfaults.compile_host(
        raft.fault_spec(CFG), CFG.num_nodes, seed
    )

    # 4. the same fault schedule breaks the host-tier user code: the
    # supervisor kills/restarts at the recorded virtual times and the
    # example's own election-safety check records the double-vote
    result = replay.replay_on_host(
        lambda hs, p: raft_host.run_seed_with_plan(hs, p, n=CFG.num_nodes,
                                                   sim_seconds=3.0),
        plan,
        host_seeds=range(10),
    )
    assert result is not None, "violation did not reproduce on the host tier"
    assert result["violations"] > 0
    assert result["leaders_elected"] > 0


def test_fault_plan_extraction_is_deterministic():
    seed = 93
    _, t1 = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    _, t2 = ecore.run_traced(raft.workload(CFG), ECFG, seed)
    p1 = replay.extract_fault_schedule(t1, raft.K_FAULT)
    p2 = replay.extract_fault_schedule(t2, raft.K_FAULT)
    assert p1 == p2 and len(p1) == 2 * CFG.crashes


def test_fault_schedule_horizon_clipping_is_a_strict_prefix():
    """The documented divergence, asserted: a spec whose windows reach
    past ``time_limit_ns`` yields a traced device schedule that is a
    STRICT time-prefix of ``compile_host``'s — an event drawn at or past
    the horizon appears on the host list but never fires in the trace
    (docs/faults.md "sizing caveat")."""
    from madsim_tpu import faults as hfaults
    from madsim_tpu.engine import faults as efaults
    from madsim_tpu.models import raft as raft_mod

    limit = int(ECFG.time_limit_ns)
    spec = efaults.FaultSpec(
        crashes=2,
        crash_window_ns=2 * limit,  # draws straddle the horizon
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    cfg = CFG._replace(faults=spec)
    ecfg = raft_mod.engine_config(cfg, time_limit_ns=limit, max_steps=30_000)

    # pinned deterministic scan: the first seed whose host schedule has
    # events on both sides of the horizon, none inside a +-1 us guard
    # band (the engine's accumulated 50-100 ns dispatch jitter decides
    # borderline events; the band keeps the assertion jitter-proof)
    for seed in range(32):
        host = hfaults.compile_host(spec, cfg.num_nodes, seed)
        before = [e for e in host if e[0] < limit - 1_000_000]
        after = [e for e in host if e[0] > limit + 1_000_000]
        if before and after and len(before) + len(after) == len(host):
            break
    else:
        raise AssertionError("no straddling seed in the pinned range")

    _, trace = ecore.run_traced(raft_mod.workload(cfg), ecfg, seed)
    device = replay.extract_fault_schedule(trace, raft_mod.K_FAULT)
    assert device == host[: len(device)], "not a prefix of the host schedule"
    assert len(device) < len(host), "horizon clipping did not drop anything"
    assert device == before, "device fired exactly the pre-horizon events"


def test_durable_state_config_stays_quiet():
    """Control: with real durable-state semantics the same fault pressure
    produces no violations (the bug is the amnesia, not the checker)."""
    cfg = CFG._replace(volatile_state=False)
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    final = ecore.run_sweep(raft.workload(cfg), ecfg, jnp.arange(160, dtype=jnp.int64))
    assert raft.sweep_summary(final)["violations"] == 0
