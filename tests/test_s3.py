"""S3 sim tests — the madsim-aws-sdk-s3 operation matrix: object CRUD,
list-v2 pagination, multipart upload lifecycle, bucket lifecycle config,
error codes, and determinism."""

import pytest

import madsim_tpu as ms
from madsim_tpu import s3
from madsim_tpu.s3 import (
    ByteStream,
    CompletedMultipartUpload,
    CompletedPart,
    Delete,
    ObjectIdentifier,
)

ADDR = "10.0.0.1:9000"


def with_server(seed, client_fn):
    rt = ms.Runtime(seed=seed)

    async def main():
        h = ms.current_handle()
        h.create_node().name("s3").ip("10.0.0.1").init(
            lambda: s3.SimServer().serve(ADDR)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)
        return await node.spawn(client_fn())

    return rt.block_on(main())


def test_object_crud_and_head():
    async def run():
        c = s3.Client.from_addr(ADDR)
        await c.create_bucket().bucket("b").send()
        put = await c.put_object().bucket("b").key("k").body(b"hello").send()
        assert put.e_tag().startswith('"')
        got = await c.get_object().bucket("b").key("k").send()
        assert (await got.body.collect()).into_bytes() == b"hello"
        assert got.e_tag() == put.e_tag()
        head = await c.head_object().bucket("b").key("k").send()
        assert head.content_length() == 5
        assert head.e_tag() == put.e_tag()
        await c.delete_object().bucket("b").key("k").send()
        with pytest.raises(s3.S3Error) as e:
            await c.get_object().bucket("b").key("k").send()
        assert e.value.code == "NoSuchKey"

    with_server(61, run)


def test_error_codes():
    async def run():
        c = s3.Client.from_addr(ADDR)
        with pytest.raises(s3.S3Error) as e:
            await c.put_object().bucket("nope").key("k").body(b"x").send()
        assert e.value.code == "NoSuchBucket"
        await c.create_bucket().bucket("b").send()
        with pytest.raises(s3.S3Error) as e:
            await c.create_bucket().bucket("b").send()
        assert e.value.code == "BucketAlreadyExists"
        await c.put_object().bucket("b").key("k").body(b"x").send()
        with pytest.raises(s3.S3Error) as e:
            await c.delete_bucket().bucket("b").send()
        assert e.value.code == "BucketNotEmpty"

    with_server(62, run)


def test_list_objects_v2_pagination():
    async def run():
        c = s3.Client.from_addr(ADDR)
        await c.create_bucket().bucket("b").send()
        for i in range(7):
            await c.put_object().bucket("b").key(f"logs/{i}").body(b"x" * i).send()
        await c.put_object().bucket("b").key("other").body(b"y").send()
        out = await (
            c.list_objects_v2().bucket("b").prefix("logs/").max_keys(3).send()
        )
        assert [o.key() for o in out.contents()] == ["logs/0", "logs/1", "logs/2"]
        assert out.is_truncated()
        out2 = await (
            c.list_objects_v2()
            .bucket("b")
            .prefix("logs/")
            .max_keys(10)
            .continuation_token(out.next_continuation_token())
            .send()
        )
        assert [o.key() for o in out2.contents()] == [f"logs/{i}" for i in range(3, 7)]
        assert not out2.is_truncated()
        # delete_objects batch
        delete = Delete.builder()
        for i in range(7):
            delete.objects(ObjectIdentifier.builder().key(f"logs/{i}").build())
        out3 = await c.delete_objects().bucket("b").delete(delete.build()).send()
        assert len(out3.deleted()) == 7
        assert (await c.list_objects_v2().bucket("b").prefix("").send()).key_count() == 1

    with_server(63, run)


def test_multipart_upload_lifecycle():
    async def run():
        c = s3.Client.from_addr(ADDR)
        await c.create_bucket().bucket("b").send()
        up = await c.create_multipart_upload().bucket("b").key("big").send()
        uid = up.upload_id()
        etags = {}
        for n, chunk in [(1, b"aaa"), (2, b"bbb"), (3, b"ccc")]:
            part = await (
                c.upload_part()
                .bucket("b")
                .key("big")
                .upload_id(uid)
                .part_number(n)
                .body(ByteStream.from_static(chunk))
                .send()
            )
            etags[n] = part.e_tag()
        mp = CompletedMultipartUpload.builder()
        for n in (1, 2, 3):
            mp.parts(CompletedPart.builder().part_number(n).e_tag(etags[n]).build())
        await (
            c.complete_multipart_upload()
            .bucket("b")
            .key("big")
            .upload_id(uid)
            .multipart_upload(mp.build())
            .send()
        )
        got = await c.get_object().bucket("b").key("big").send()
        assert (await got.body.collect()).into_bytes() == b"aaabbbccc"
        # completed upload id is gone
        with pytest.raises(s3.S3Error) as e:
            await c.abort_multipart_upload().bucket("b").upload_id(uid).send()
        assert e.value.code == "NoSuchUpload"
        # abort path
        up2 = await c.create_multipart_upload().bucket("b").key("gone").send()
        await c.abort_multipart_upload().bucket("b").upload_id(up2.upload_id()).send()
        with pytest.raises(s3.S3Error):
            await c.get_object().bucket("b").key("gone").send()

    with_server(64, run)


def test_bucket_lifecycle_configuration():
    async def run():
        c = s3.Client.from_addr(ADDR)
        await c.create_bucket().bucket("b").send()
        with pytest.raises(s3.S3Error) as e:
            await c.get_bucket_lifecycle_configuration().bucket("b").send()
        assert e.value.code == "NoSuchLifecycleConfiguration"
        rules = [{"id": "expire-logs", "prefix": "logs/", "days": 30}]
        await (
            c.put_bucket_lifecycle_configuration()
            .bucket("b")
            .lifecycle_configuration(rules)
            .send()
        )
        out = await c.get_bucket_lifecycle_configuration().bucket("b").send()
        assert out.rules() == rules

    with_server(65, run)


def test_s3_determinism():
    def workload():
        async def main():
            h = ms.current_handle()
            h.create_node().name("s3").ip("10.0.0.1").init(
                lambda: s3.SimServer().serve(ADDR)
            ).build()
            node = h.create_node().name("client").ip("10.0.0.2").build()
            await ms.sleep(0.1)

            async def run():
                c = s3.Client.from_addr(ADDR)
                await c.create_bucket().bucket("b").send()
                for i in range(5):
                    await c.put_object().bucket("b").key(f"k{i}").body(b"v").send()
                out = await c.list_objects_v2().bucket("b").prefix("k").send()
                assert out.key_count() == 5

            await node.spawn(run())

        return main()

    ms.Runtime.check_determinism(66, workload)
