"""Kafka device workload: healthy sweeps are quiet, the ack-before-durable
bug is caught at a reported seed, and traced CPU replay matches the sweep.

This is the engine-generalization suite (BASELINE.md config #4): the same
queue/RNG/net substrate as the Raft model driving a completely different
actor topology (broker + producers + consumers with crash/restart).
"""

import jax
import jax.numpy as jnp
import numpy as np

from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.rng import prob_to_q32
from madsim_tpu.models import kafka

CFG = kafka.KafkaConfig()
ECFG = kafka.engine_config(CFG, time_limit_ns=3_000_000_000, max_steps=30_000)

BUG_CFG = CFG._replace(bug_ack_on_append=True, crashes=2)
BUG_ECFG = kafka.engine_config(BUG_CFG, time_limit_ns=3_000_000_000, max_steps=30_000)


def test_healthy_sweep_quiet_and_progresses():
    final = ecore.run_sweep(kafka.workload(CFG), ECFG, jnp.arange(256, dtype=jnp.int64))
    s = kafka.sweep_summary(final)
    assert s["violations"] == 0, s
    assert s["ack_loss_seeds"] == 0 and s["watermark_seeds"] == 0
    # real traffic flowed: appends, acks, and consumed records
    assert s["appended"] > 0 and s["acked"] > 0 and s["fetched"] > 0
    assert s["flushes"] > 0
    # the fault plan actually fired crashes across the batch
    assert s["crashes"] > 0
    # bounded structures stayed bounded
    assert s["overflow_seeds"] == 0 and s["log_overflow_seeds"] == 0
    assert s["queue_high_water"] <= ECFG.queue_capacity
    # sent counts attempts, delivered counts link-test passes
    assert s["msgs_sent"] >= s["msgs_delivered"] > 0


def test_consumers_only_see_durable_contiguous_stream():
    final = ecore.run_sweep(kafka.workload(CFG), ECFG, jnp.arange(128, dtype=jnp.int64))
    w = final.wstate
    # consumer offsets never pass the durable watermark of their partition
    cons_off = np.asarray(w.cons_off)  # [S, NC]
    flushed = np.asarray(w.flushed)  # [S, P]
    for c in range(CFG.num_consumers):
        part = c % CFG.partitions
        assert (cons_off[:, c] <= flushed[:, part]).all()
    # watermark sanity held everywhere
    assert (flushed <= np.asarray(w.log_len)).all()


def test_ack_before_durable_bug_is_caught():
    """The deliberate bug (ack on append) + broker crash = acked-message
    loss; the online checker must latch it at some seed and the seed must
    be reported for replay."""
    final = ecore.run_sweep(
        kafka.workload(BUG_CFG), BUG_ECFG, jnp.arange(512, dtype=jnp.int64)
    )
    s = kafka.sweep_summary(final)
    assert s["ack_loss_seeds"] > 0, f"checker failed to catch the bug: {s}"
    bad = np.asarray(final.seed)[np.asarray(final.wstate.vio_ack_loss)]
    assert bad.size > 0
    # every violating seed reproduces under single-seed traced replay on CPU
    seed = int(bad[0])
    with jax.default_device(jax.devices("cpu")[0]):
        replayed, _trace = ecore.run_traced(kafka.workload(BUG_CFG), BUG_ECFG, seed)
    assert bool(replayed.wstate.vio_ack_loss)


def test_correct_mode_never_loses_acked_under_same_faults():
    """Same fault plan as the bug test, correct ack-at-flush policy: the
    checker stays quiet (the bug is in the policy, not the checker)."""
    cfg = BUG_CFG._replace(bug_ack_on_append=False)
    final = ecore.run_sweep(
        kafka.workload(cfg), kafka.engine_config(cfg, time_limit_ns=3_000_000_000,
                                                 max_steps=30_000),
        jnp.arange(512, dtype=jnp.int64),
    )
    s = kafka.sweep_summary(final)
    assert s["violations"] == 0, s
    assert s["crashes"] > 0  # faults really fired


def test_traced_replay_matches_sweep():
    """Bit-exact cross-check: run_traced on a few seeds reproduces the
    sweep's per-seed terminal state exactly (the CPU-replay contract)."""
    wl = kafka.workload(CFG)
    seeds = jnp.arange(6, dtype=jnp.int64)
    final = ecore.run_sweep(wl, ECFG, seeds)
    for i in range(6):
        single, _ = ecore.run_traced(wl, ECFG, int(seeds[i]))
        assert int(single.ctr) == int(final.ctr[i])
        assert int(single.now_ns) == int(final.now_ns[i])
        assert int(single.wstate.appended) == int(final.wstate.appended[i])
        assert int(single.wstate.fetched) == int(final.wstate.fetched[i])
        assert bool(single.wstate.violation) == bool(final.wstate.violation[i])


def test_lost_acks_are_resent_on_duplicate_produce():
    """A lost flush-ack must not stall the producer forever: the broker
    re-sends its cumulative ack when a duplicate produce of an already-
    acked seq arrives. Under 30% loss, producers still finish their whole
    send plan (without the re-ack they stall at the first lost ack)."""
    cfg = CFG._replace(loss_q32=prob_to_q32(0.30), crashes=0)
    ecfg = kafka.engine_config(cfg, time_limit_ns=4_000_000_000, max_steps=40_000)
    final = ecore.run_sweep(kafka.workload(cfg), ecfg, jnp.arange(64, dtype=jnp.int64))
    next_seq = np.asarray(final.wstate.next_seq)  # [S, NP]
    # nearly all producers reach the end of their plan; a stall bug drags
    # the mean toward 1/loss ≈ 3
    assert next_seq.mean() > 0.8 * cfg.msgs_per_producer, next_seq.mean()
    assert kafka.sweep_summary(final)["violations"] == 0


def test_different_seeds_diverge():
    final = ecore.run_sweep(kafka.workload(CFG), ECFG, jnp.arange(32, dtype=jnp.int64))
    # schedule randomization: event counts differ across seeds
    assert len(np.unique(np.asarray(final.ctr))) > 1
