"""The persistent streaming sweep service (engine/stream.py).

The contracts under test (docs/streaming.md): per-seed summaries
bit-identical to the chunked pipelined driver on every bundled model,
report bytes invariant to the refill schedule, interrupt/resume through
a v9 stream snapshot bit-identical to the uninterrupted run, and a
warmed multi-candidate stream (spec-as-data lanes of different
FaultParams in one pool) performing ZERO XLA compilations. Plus the
canonical-history dedup key the streaming checked sweep's WGL stage
relies on (oracle/history.history_canonical_bytes).
"""

import os
import tempfile

import jax.numpy as jnp
import numpy as np

from madsim_tpu.engine import core as ecore
from madsim_tpu.engine.checkpoint import run_sweep_pipelined
from madsim_tpu.engine.compiles import count_compiles
from madsim_tpu.engine.stream import stream_sweep
from madsim_tpu.models import etcd, kafka, raft

_SEEDS = 24
_KW = dict(time_limit_ns=500_000_000, max_steps=4_000)


def _etcd():
    cfg = etcd.EtcdConfig(hist_slots=64, bug_stale_read=True)
    return etcd.workload(cfg), etcd.engine_config(cfg, **_KW), etcd.sweep_summary


def _cases():
    rcfg = raft.RaftConfig(num_nodes=3)
    kcfg = kafka.KafkaConfig()
    return (
        (raft.workload(rcfg), raft.engine_config(rcfg, **_KW),
         raft.sweep_summary),
        _etcd(),
        (kafka.workload(kcfg), kafka.engine_config(kcfg, **_KW),
         kafka.sweep_summary),
    )


def test_stream_matches_chunked_raft_etcd_kafka():
    seeds = jnp.arange(_SEEDS, dtype=jnp.int64)
    for wl, ecfg, summarize in _cases():
        chunked = run_sweep_pipelined(wl, ecfg, seeds, summarize, chunk_size=8)
        streamed = stream_sweep(
            wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8,
            round_steps=128,
        )
        assert streamed == chunked


def test_refill_schedule_invariance():
    wl, ecfg, summarize = _etcd()
    seeds = jnp.arange(_SEEDS, dtype=jnp.int64)
    base = stream_sweep(
        wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8, round_steps=128
    )
    for perm_seed in (0, 3):
        order = np.random.default_rng(perm_seed).permutation(_SEEDS)
        assert (
            stream_sweep(
                wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8,
                round_steps=128, queue_order=order,
            )
            == base
        )


def test_interrupt_resume_v9_bit_identity():
    wl, ecfg, summarize = _etcd()
    seeds = jnp.arange(_SEEDS, dtype=jnp.int64)
    full = stream_sweep(
        wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8, round_steps=64
    )
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "stream.npz")
        stream_sweep(
            wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8,
            round_steps=64, ckpt_path=path, stop_after_rounds=1,
        )
        assert os.path.exists(path)
        resumed = stream_sweep(
            wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8,
            round_steps=64, resume_from=path,
        )
    assert resumed == full


def test_warmed_multicandidate_stream_zero_compiles():
    # lanes of DIFFERENT candidates share one pool: K specs x s seeds
    # feed the refill queue, and a warmed stream over fresh candidates
    # compiles nothing — the spec-as-data streaming contract
    from madsim_tpu.engine import faults as efaults

    base = efaults.FaultSpec(
        crashes=1, crash_window_ns=400_000_000,
        restart_lo_ns=50_000_000, restart_hi_ns=100_000_000,
    )
    env = efaults.campaign_envelope(base, mutation_cap=2)
    cfg = raft.RaftConfig(num_nodes=3, faults=env)
    wl, ecfg = raft.workload(cfg), raft.engine_config(cfg, **_KW)
    s = 8

    def grid(specs):
        seeds = np.tile(np.arange(s, dtype=np.int64), len(specs))
        params = efaults.grid_params(
            [efaults.spec_to_params(sp, env, cfg.num_nodes) for sp in specs],
            s,
        )
        return stream_sweep(
            wl, ecfg, seeds, raft.sweep_summary, params=params,
            chunk_size=s, pool_size=2 * s, round_steps=128,
        )

    cands = [base, base._replace(crashes=2), base._replace(partitions=1)]
    grid(cands[:3])  # warm
    with count_compiles() as c:
        got = grid([cands[1], cands[2], base._replace(crashes=0)])
    assert c.count == 0, f"{c.count} XLA compilations in a warmed stream"
    assert got["events_total"] > 0


def test_canonical_bytes_dedup_key():
    # the WGL dedup key: seed-free and invariant to absolute timestamps
    # (dense time-rank), but sensitive to everything the checker reads
    from madsim_tpu.oracle.history import (
        History,
        Op,
        history_bytes,
        history_canonical_bytes,
    )

    def hist(seed, t0):
        ops = (
            Op(client=0, op=0, key=1, inp=7, out=7,
               invoke_ns=t0, complete_ns=t0 + 10, opid=0),
            Op(client=1, op=1, key=1, inp=0, out=7,
               invoke_ns=t0 + 5, complete_ns=-1, opid=0),
        )
        return History(seed=seed, ops=ops, overflow=False, rows=4)

    a, b = hist(3, 1_000), hist(9, 50_000)
    assert history_bytes(a) != history_bytes(b)
    assert history_canonical_bytes(a) == history_canonical_bytes(b)
    # a changed verdict-relevant field must change the key
    c = hist(3, 1_000)
    c = c._replace(ops=(c.ops[0]._replace(out=8),) + c.ops[1:])
    assert history_canonical_bytes(c) != history_canonical_bytes(a)


def test_feed_segments_bit_identical_to_one_shot():
    # the fleet feed hook: a stream fed its queue in segments mid-flight
    # produces the same report bytes as the one-shot queue (and thus as
    # the chunked driver, by the stream contract)
    wl, ecfg, summarize = _cases()[0]
    seeds = jnp.arange(_SEEDS, dtype=jnp.int64)
    one = stream_sweep(
        wl, ecfg, seeds, summarize, chunk_size=8, pool_size=8,
        round_steps=128,
    )
    segs = [np.arange(8, 16, dtype=np.int64), np.arange(16, 24, dtype=np.int64)]
    fed = stream_sweep(
        wl, ecfg, jnp.arange(8, dtype=jnp.int64), summarize,
        chunk_size=8, pool_size=8, round_steps=128,
        feed=lambda: {"seeds": segs.pop(0)} if segs else None,
    )
    assert fed == one
    assert not segs  # both segments were actually consumed


def test_feed_guards():
    import pytest

    wl, ecfg, summarize = _cases()[0]
    seeds = jnp.arange(8, dtype=jnp.int64)
    nothing = lambda: None  # noqa: E731
    with pytest.raises(ValueError, match="queue_order"):
        stream_sweep(
            wl, ecfg, seeds, summarize, chunk_size=8, feed=nothing,
            queue_order=np.arange(8)[::-1],
        )
    with pytest.raises(ValueError, match="checkpointing"):
        stream_sweep(
            wl, ecfg, seeds, summarize, chunk_size=8, feed=nothing,
            ckpt_path="/tmp/nope.npz", stop_after_rounds=1,
        )
    with pytest.raises(ValueError, match="multiple of"):
        stream_sweep(
            wl, ecfg, jnp.arange(7, dtype=jnp.int64), summarize,
            chunk_size=8, feed=nothing,
        )
    with pytest.raises(ValueError, match="multiple of"):
        stream_sweep(
            wl, ecfg, seeds, summarize, chunk_size=8,
            feed=iter([{"seeds": np.arange(8, 11, dtype=np.int64)}]).__next__,
        )
