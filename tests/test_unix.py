"""Unix domain socket tests — shaped like the TCP battery in
tests/test_net.py. The reference's unix sockets are all ``todo!()``
(madsim/src/sim/net/unix/); this suite covers the implemented simulation:
node-local path namespaces, stream echo/EOF/refused, datagram delivery,
bind conflicts, kill cleanup, and schedule determinism."""

import pytest

import madsim_tpu as ms
from madsim_tpu.net import UnixDatagram, UnixListener, UnixStream


def test_unix_stream_echo():
    rt = ms.Runtime(seed=21)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()

        async def server():
            listener = await UnixListener.bind("/tmp/echo.sock")
            stream, peer = await listener.accept()
            assert peer == ""  # anonymous client, like the OS
            data = await stream.read_exact(5)
            await stream.write_all_flush(b"echo:" + data)

        async def client():
            await ms.sleep(0.1)
            stream = await UnixStream.connect("/tmp/echo.sock")
            assert stream.peer_addr() == "/tmp/echo.sock"
            await stream.write_all_flush(b"hello")
            return await stream.read_exact(10)

        n1.spawn(server())
        assert await n1.spawn(client()) == b"echo:hello"

    rt.block_on(main())


def test_unix_stream_eof_on_shutdown():
    rt = ms.Runtime(seed=22)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()

        async def server():
            listener = await UnixListener.bind("/run/x.sock")
            stream, _ = await listener.accept()
            await stream.write_all_flush(b"bye")
            stream.shutdown()

        async def client():
            await ms.sleep(0.1)
            stream = await UnixStream.connect("/run/x.sock")
            assert await stream.read_exact(3) == b"bye"
            assert await stream.read(10) == b""  # EOF

        n1.spawn(server())
        await n1.spawn(client())

    rt.block_on(main())


def test_unix_connect_refused_without_listener():
    rt = ms.Runtime(seed=23)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()

        async def client():
            with pytest.raises(ConnectionRefusedError):
                await UnixStream.connect("/no/such.sock")

        await n1.spawn(client())

    rt.block_on(main())


def test_unix_paths_are_node_local():
    """Two nodes bind the SAME path without conflict, and a connect on one
    node never reaches the other node's listener."""
    rt = ms.Runtime(seed=24)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()
        n2 = h.create_node().name("n2").build()

        async def serve(reply: bytes):
            listener = await UnixListener.bind("/svc.sock")
            stream, _ = await listener.accept()
            await stream.write_all_flush(reply)

        async def ask():
            await ms.sleep(0.1)
            stream = await UnixStream.connect("/svc.sock")
            return await stream.read_exact(2)

        n1.spawn(serve(b"N1"))
        n2.spawn(serve(b"N2"))
        r1 = n1.spawn(ask())
        r2 = n2.spawn(ask())
        assert await r1 == b"N1"
        assert await r2 == b"N2"

    rt.block_on(main())


def test_unix_bind_conflict_and_close_frees_path():
    rt = ms.Runtime(seed=25)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()

        async def wl():
            listener = await UnixListener.bind("/one.sock")
            with pytest.raises(OSError, match="already in use"):
                await UnixListener.bind("/one.sock")
            with pytest.raises(OSError, match="already in use"):
                await UnixDatagram.bind("/one.sock")  # shared namespace
            listener.close()
            listener2 = await UnixListener.bind("/one.sock")  # freed
            listener2.close()

        await n1.spawn(wl())

    rt.block_on(main())


def test_unix_kill_clears_namespace_and_breaks_streams():
    """Node kill drops the node's unix bindings (restart can rebind) and
    breaks its live pipes, like TCP."""
    rt = ms.Runtime(seed=26)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()

        async def bind_and_hold():
            await UnixListener.bind("/held.sock")
            await ms.sleep(100)

        n1.spawn(bind_and_hold())
        await ms.sleep(0.5)
        h.kill(n1.id)
        h.restart(n1.id)

        async def rebind():
            listener = await UnixListener.bind("/held.sock")  # no conflict
            listener.close()

        await n1.spawn(rebind())

    rt.block_on(main())


def test_unix_datagram_send_recv():
    rt = ms.Runtime(seed=27)

    async def main():
        h = ms.current_handle()
        n1 = h.create_node().name("n1").build()

        async def wl():
            a = await UnixDatagram.bind("/a.sock")
            b = await UnixDatagram.bind("/b.sock")
            assert a.local_addr() == "/a.sock"

            assert await a.send_to(b"ping", "/b.sock") == 4
            data, src = await b.recv_from()
            assert (data, src) == (b"ping", "/a.sock")

            # connected mode
            b.connect("/a.sock")
            await b.send(b"pong")
            assert await a.recv() == b"pong"

            # unbound sender: can send, shows empty source
            ub = UnixDatagram.unbound()
            await ub.send_to(b"anon", "/a.sock")
            data, src = await a.recv_from()
            assert (data, src) == (b"anon", "")

            # missing destination errors (kernel semantics, unlike UDP)
            with pytest.raises(ConnectionRefusedError):
                await a.send_to(b"x", "/missing.sock")
            # unconnected send errors
            with pytest.raises(OSError, match="not connected"):
                await a.send(b"x")
            a.close()
            b.close()

        await n1.spawn(wl())

    rt.block_on(main())


def test_unix_deterministic_across_runs():
    """Same seed => identical interleaving of unix traffic."""

    def run(seed: int):
        rt = ms.Runtime(seed=seed)
        log = []

        async def main():
            h = ms.current_handle()
            n1 = h.create_node().name("n1").build()

            async def server():
                listener = await UnixListener.bind("/d.sock")
                for _ in range(3):
                    stream, _ = await listener.accept()
                    data = await stream.read_exact(2)
                    log.append(("srv", data, ms.current_handle().time.now_ns))
                    await stream.write_all_flush(data.upper())

            async def client(tag: bytes):
                await ms.sleep(0.01)
                stream = await UnixStream.connect("/d.sock")
                await stream.write_all_flush(tag)
                log.append((tag, await stream.read_exact(2)))

            n1.spawn(server())
            await ms.join(
                n1.spawn(client(b"c1")),
                n1.spawn(client(b"c2")),
                n1.spawn(client(b"c3")),
            )

        rt.block_on(main())
        return log

    assert run(42) == run(42)
    assert run(42) != run(43) or True  # different seeds may differ

    rt = None  # noqa: F841
