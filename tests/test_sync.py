"""Sync primitive tests (tokio-sync surface, kept native in this build)."""

import pytest

import madsim_tpu as ms
from madsim_tpu import sync


def run(seed, main_fn):
    return ms.Runtime(seed=seed).block_on(main_fn())


def test_oneshot():
    async def main():
        tx, rx = sync.oneshot()

        async def sender():
            await ms.sleep(0.01)
            tx.send(42)

        ms.spawn(sender())
        assert await rx == 42

    run(1, main)


def test_mpsc_unbounded():
    async def main():
        tx, rx = sync.unbounded_channel()

        async def producer():
            for i in range(5):
                tx.send_nowait(i)
                await ms.sleep(0.001)
            tx.close()

        ms.spawn(producer())
        got = []
        while True:
            v = await rx.recv()
            if v is None:
                break
            got.append(v)
        assert got == [0, 1, 2, 3, 4]

    run(2, main)


def test_mpsc_bounded_backpressure():
    async def main():
        tx, rx = sync.channel(2)
        sent = []

        async def producer():
            for i in range(6):
                await tx.send(i)
                sent.append(i)
            tx.close()

        ms.spawn(producer())
        await ms.sleep(0.01)
        assert len(sent) <= 3  # capacity 2 (+1 in flight at most)
        got = []
        while True:
            v = await rx.recv()
            if v is None:
                break
            got.append(v)
        assert got == list(range(6))

    run(3, main)


def test_watch():
    async def main():
        tx, rx = sync.watch("init")
        seen = []

        async def watcher():
            while True:
                await rx.changed()
                v = rx.borrow_and_update()
                seen.append(v)
                if v == "done":
                    return

        h = ms.spawn(watcher())
        await ms.sleep(0.01)
        tx.send("a")
        await ms.sleep(0.01)
        tx.send("done")
        await h
        assert seen == ["a", "done"]

    run(4, main)


def test_broadcast():
    async def main():
        tx, rx1 = sync.broadcast(16)
        rx2 = tx.subscribe()
        tx.send("x")
        tx.send("y")
        assert await rx1.recv() == "x"
        assert await rx2.recv() == "x"
        assert await rx1.recv() == "y"
        assert await rx2.recv() == "y"

    run(5, main)


def test_broadcast_lagged():
    async def main():
        tx, rx = sync.broadcast(2)
        for i in range(5):
            tx.send(i)
        with pytest.raises(sync.LaggedError):
            await rx.recv()
        assert await rx.recv() == 3

    run(6, main)


def test_notify():
    async def main():
        n = sync.Notify()
        woke = []

        async def waiter():
            await n.notified()
            woke.append(1)

        ms.spawn(waiter())
        await ms.sleep(0.01)
        n.notify_one()
        await ms.sleep(0.01)
        assert woke == [1]
        # permit stored when no waiter
        n.notify_one()
        await n.notified()  # consumes stored permit without blocking

    run(7, main)


def test_mutex_exclusive():
    async def main():
        m = sync.Mutex()
        log = []

        async def critical(name):
            async with m:
                log.append(f"{name}-in")
                await ms.sleep(0.01)
                log.append(f"{name}-out")

        hs = [ms.spawn(critical(i)) for i in range(3)]
        for h in hs:
            await h
        # no interleaving inside the critical section
        for i in range(0, 6, 2):
            assert log[i].endswith("-in")
            assert log[i + 1].split("-")[0] == log[i].split("-")[0]

    run(8, main)


def test_rwlock():
    async def main():
        lock = sync.RwLock()
        r1 = await lock.read()
        r2 = await lock.read()  # concurrent readers ok
        r1.release()
        r2.release()
        w = await lock.write()
        w.release()

    run(9, main)


def test_semaphore():
    async def main():
        sem = sync.Semaphore(2)
        g1 = await sem.acquire()
        g2 = await sem.acquire()
        assert sem.try_acquire() is None
        g1.release()
        assert sem.try_acquire() is not None
        g2.release()

    run(10, main)


def test_barrier():
    async def main():
        b = sync.Barrier(3)
        results = []

        async def party(i):
            leader = await b.wait()
            results.append((i, leader))

        hs = [ms.spawn(party(i)) for i in range(3)]
        for h in hs:
            await h
        assert len(results) == 3
        assert sum(1 for _, leader in results if leader) == 1

    run(11, main)
