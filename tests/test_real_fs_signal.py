"""Real-mode fs + signal twins and the tokio io/process surfaces: the sim
API shapes over actual files, OS signals, asyncio streams, and real
subprocesses (ref madsim/src/std/fs.rs; madsim-tokio/src/lib.rs:38-50
keeps real fs/io/process alongside the sim)."""

import os
import signal as os_signal

import pytest

from madsim_tpu import real, tokio


def test_real_fs_file_surface(tmp_path):
    """The sim File surface (positional I/O, set_len, sync_all, metadata)
    over a real file."""
    path = str(tmp_path / "data.bin")

    async def main():
        f = await real.fs.File.create(path)
        await f.write_all(b"hello ")
        await f.write_all(b"world")          # append semantics
        await f.write_all_at(b"HELLO", 0)    # positional overwrite
        await f.sync_all()
        assert await f.read_at(5, 6) == b"world"
        assert await f.read_all() == b"HELLO world"
        meta = await f.metadata()
        assert meta.len() == 11 and meta.is_file()
        await f.set_len(5)
        assert await f.read_all() == b"HELLO"
        await f.set_len(8)                   # extend zero-fills
        assert await f.read_all() == b"HELLO\x00\x00\x00"
        f.close()
        with pytest.raises(ValueError):
            await f.read_all()

        # open_or_create keeps existing contents; open on missing raises
        f2 = await real.fs.File.open_or_create(path)
        assert (await f2.read_all()).startswith(b"HELLO")
        f2.close()
        with pytest.raises(FileNotFoundError):
            await real.fs.File.open(str(tmp_path / "missing"))

    real.Runtime().block_on(main())


def test_real_fs_module_helpers(tmp_path):
    path = str(tmp_path / "blob")

    async def main():
        await real.fs.write(path, b"abc123")
        assert await real.fs.read(path) == b"abc123"
        assert (await real.fs.metadata(path)).len() == 6
        await real.fs.remove_file(path, durable=True)
        assert not os.path.exists(path)
        with pytest.raises(FileNotFoundError):
            await real.fs.read(path)

    real.Runtime().block_on(main())


def test_real_signal_ctrl_c_waits_for_sigint():
    """ctrl_c resolves on a real SIGINT and restores the previous handler
    afterwards (no KeyboardInterrupt leaks into the test process)."""

    async def main():
        async def fire():
            await real.sleep(0.05)
            os.kill(os.getpid(), os_signal.SIGINT)

        task = real.spawn(fire())
        await real.timeout(5.0, real.signal.ctrl_c())
        await task

    real.Runtime().block_on(main())
    # handler restored: a default-action probe would now raise in Python's
    # default handler, so just check the asyncio handler is gone
    assert os_signal.getsignal(os_signal.SIGINT) is os_signal.default_int_handler


def test_real_signal_wakes_all_concurrent_waiters():
    """Multiple tasks awaiting ctrl_c all resolve on ONE signal — the sim
    twin wakes every waiter (signal.py ctrl_c_waiters), so real mode must
    too; a per-waiter handler would strand all but the last."""

    async def main():
        woke = []

        async def waiter(tag):
            await real.signal.ctrl_c()
            woke.append(tag)

        t1 = real.spawn(waiter("a"))
        t2 = real.spawn(waiter("b"))
        t3 = real.spawn(waiter("c"))
        await real.sleep(0.05)
        os.kill(os.getpid(), os_signal.SIGINT)
        await real.timeout(5.0, t1)
        await real.timeout(5.0, t2)
        await real.timeout(5.0, t3)
        assert sorted(woke) == ["a", "b", "c"]

    real.Runtime().block_on(main())
    assert os_signal.getsignal(os_signal.SIGINT) is os_signal.default_int_handler


def test_real_signal_dead_waiter_cannot_strand_live_waiters():
    """A waiter future bound to a closed loop (its Runtime was abandoned
    without cancellation) must not break _on_sigint for the remaining
    live waiters (ADVICE.md finding): the dead future is skipped, every
    live waiter still wakes."""
    import asyncio

    from madsim_tpu.real import signal as rsignal

    # fabricate the dead waiter: a future from a loop that is now closed
    dead_loop = asyncio.new_event_loop()
    dead_fut = dead_loop.create_future()
    dead_loop.close()
    rsignal._waiters.append(dead_fut)
    try:

        async def main():
            woke = []

            async def waiter(tag):
                await real.signal.ctrl_c()
                woke.append(tag)

            t1 = real.spawn(waiter("a"))
            t2 = real.spawn(waiter("b"))
            await real.sleep(0.05)
            os.kill(os.getpid(), os_signal.SIGINT)
            await real.timeout(5.0, t1)
            await real.timeout(5.0, t2)
            assert sorted(woke) == ["a", "b"]

        real.Runtime().block_on(main())
        assert not dead_fut.done()  # skipped, not resolved
    finally:
        if dead_fut in rsignal._waiters:
            rsignal._waiters.remove(dead_fut)


def test_tokio_process_command_surface():
    """tokio::process::Command analogue over real subprocesses."""

    async def main():
        out = await tokio.process.Command("echo").arg("hi").output()
        assert out.status.success() and out.status.code() == 0
        assert out.stdout == b"hi\n" and out.stderr == b""

        st = await tokio.process.Command("sh").args(["-c", "exit 3"]).status()
        assert not st.success() and st.code() == 3

        # env + cwd builders
        out = await (
            tokio.process.Command("sh")
            .args(["-c", "echo $MADSIM_T:$PWD"])
            .env("MADSIM_T", "v")
            .current_dir("/tmp")
            .output()
        )
        assert out.stdout == b"v:/tmp\n"

        # spawn gives the Child analogue
        child = await tokio.process.Command("sleep").arg("10").spawn()
        child.kill()
        assert await child.wait() != 0

    real.Runtime().block_on(main())


def test_tokio_io_streams_and_copy():
    """tokio::io analogue: real asyncio server/connection plus copy()."""

    async def main():
        async def echo(reader, writer):
            await tokio.io.copy(reader, writer)
            writer.close()

        server = await tokio.io.start_server(echo, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await tokio.io.open_connection("127.0.0.1", port)
        writer.write(b"ping" * 1000)
        writer.write_eof()
        await writer.drain()
        assert await reader.read(-1) == b"ping" * 1000
        writer.close()
        server.close()

        # in-memory duplex pipe
        a, b = await tokio.io.duplex()
        a.write(b"x1")
        b.write(b"y2")
        assert await b.read(2) == b"x1"
        assert await a.read(2) == b"y2"
        a.close()
        assert await b.read(1) == b""

    real.Runtime().block_on(main())


def test_tokio_io_fails_loudly_inside_the_sim():
    """Inside the simulator there is no asyncio loop: real-IO surfaces
    raise instead of silently breaking determinism."""
    import madsim_tpu as ms

    async def wl():
        with pytest.raises(RuntimeError):
            await tokio.process.Command("echo").arg("x").output()

    ms.Runtime(seed=1).block_on(wl())
