"""Kafka sim tests — port of madsim-rdkafka/tests/test.rs (176 lines):
broker node + admin + producers + consumers over sim DNS, plus fetch
budgets, watermarks, offsets-for-times, seek, and broker crash/restart.
"""

import pytest

import madsim_tpu as ms
from madsim_tpu.kafka import (
    AdminClient,
    BaseConsumer,
    BaseProducer,
    BaseRecord,
    ClientConfig,
    FutureProducer,
    KafkaError,
    NewTopic,
    SimBroker,
    StreamConsumer,
    TopicPartitionList,
)
from madsim_tpu.net import NetSim
from madsim_tpu.plugin import simulator

BROKER = "10.0.0.1:9092"


def with_broker(seed, client_fn):
    rt = ms.Runtime(seed=seed)

    async def main():
        h = ms.current_handle()
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve(BROKER)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)
        return await node.spawn(client_fn())

    return rt.block_on(main())


def cfg() -> ClientConfig:
    return ClientConfig().set("bootstrap.servers", BROKER)


def test_produce_consume_round_robin():
    async def run():
        admin = await cfg().create(AdminClient)
        errs = await admin.create_topics([NewTopic.new("t", 3)])
        assert errs == [None]
        # duplicate create reports an error string
        errs = await admin.create_topics([NewTopic.new("t", 3)])
        assert errs[0] is not None

        producer = await cfg().create(FutureProducer)
        parts = set()
        for i in range(6):
            partition, offset = await producer.send(
                BaseRecord.to("t").with_payload(f"m{i}")
            )
            parts.add(partition)
        # keyless produce round-robins over all 3 partitions (broker.rs:80-101)
        assert parts == {0, 1, 2}

        consumer = await cfg().create(BaseConsumer)
        await consumer.subscribe(["t"])
        got = set()
        for _ in range(6):
            msg = await consumer.poll(1.0)
            assert msg is not None
            got.add(msg.payload.decode())
        assert got == {f"m{i}" for i in range(6)}
        assert await consumer.poll(0.1) is None

    with_broker(41, run)


def test_keyed_produce_is_sticky():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 4)])
        producer = await cfg().create(FutureProducer)
        parts = {
            (await producer.send(BaseRecord.to("t").with_key("k1").with_payload(str(i))))[0]
            for i in range(5)
        }
        assert len(parts) == 1  # same key → same partition

    with_broker(42, run)


def test_base_producer_buffers_until_flush():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 1)])
        producer = await cfg().create(BaseProducer)
        consumer = await cfg().create(BaseConsumer)
        await consumer.subscribe(["t"])
        producer.send(BaseRecord.to("t").with_payload("a"))
        producer.send(BaseRecord.to("t").with_payload("b"))
        assert producer.in_flight_count() == 2
        assert await consumer.poll(0.1) is None  # nothing until flush
        await producer.flush()
        assert (await consumer.poll(1.0)).payload == b"a"
        assert (await consumer.poll(1.0)).payload == b"b"

    with_broker(43, run)


def test_watermarks_seek_offsets_for_times():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 1)])
        producer = await cfg().create(FutureProducer)
        t_mid = None
        for i in range(5):
            if i == 3:
                await ms.sleep(5)
                t_mid = int(ms.time.now() * 1000)
            await producer.send(BaseRecord.to("t").with_payload(f"m{i}"))
        consumer = await cfg().create(BaseConsumer)
        lo, hi = await consumer.fetch_watermarks("t", 0)
        assert (lo, hi) == (0, 5)
        # offsets_for_times finds the first message at/after t_mid
        tpl = TopicPartitionList().add_partition_offset("t", 0, t_mid)
        [(_, _, off)] = await consumer.offsets_for_times(tpl)
        assert off == 3
        # assign + seek replays from there
        await consumer.assign(TopicPartitionList().add_partition("t", 0))
        consumer.seek("t", 0, off)
        assert (await consumer.poll(1.0)).payload == b"m3"

    with_broker(44, run)


def test_fetch_byte_budget():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 1)])
        producer = await cfg().create(FutureProducer)
        for i in range(10):
            await producer.send(BaseRecord.to("t").with_payload(b"x" * 100))
        # max.partition.fetch.bytes of 250 → ~3 messages per fetch round
        consumer = await (
            cfg().set("max.partition.fetch.bytes", 250).create(BaseConsumer)
        )
        await consumer.subscribe(["t"])
        for _ in range(10):
            assert (await consumer.poll(1.0)) is not None
        assert await consumer.poll(0.05) is None

    with_broker(45, run)


def test_stream_consumer_and_linger():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 2)])
        consumer = await cfg().create(StreamConsumer)
        await consumer.subscribe(["t"])

        async def produce_later():
            producer = await (cfg().set("linger.ms", 50).create(FutureProducer))
            await ms.sleep(1.0)
            await producer.send(BaseRecord.to("t").with_payload("late"))

        ms.spawn(produce_later())
        t0 = ms.time.elapsed()
        msg = await consumer.recv()
        assert msg.payload == b"late"
        assert ms.time.elapsed() - t0 >= 1.0  # waited on virtual time

    with_broker(46, run)


def test_broker_crash_restart():
    rt = ms.Runtime(seed=47)

    async def main():
        h = ms.current_handle()
        broker = h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve(BROKER)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            admin = await cfg().create(AdminClient)
            await admin.create_topics([NewTopic.new("t", 1)])
            producer = await cfg().create(FutureProducer)
            await producer.send(BaseRecord.to("t").with_payload("pre"))
            h.kill(broker)
            with pytest.raises(KafkaError):
                await producer.send(BaseRecord.to("t").with_payload("down"))
            h.restart(broker)
            await ms.sleep(0.2)
            # broker state is volatile (fresh on restart, like the ref sim)
            with pytest.raises(KafkaError, match="unknown topic"):
                await producer.send(BaseRecord.to("t").with_payload("post"))
            await admin.create_topics([NewTopic.new("t", 1)])
            partition, offset = await producer.send(
                BaseRecord.to("t").with_payload("post")
            )
            assert (partition, offset) == (0, 0)

        await node.spawn(run())

    rt.block_on(main())


def test_two_producers_two_consumers_topology():
    """The reference's flagship topology (tests/test.rs:21-100): admin +
    2 producers + 2 consumers on separate nodes over sim DNS."""
    rt = ms.Runtime(seed=48)

    async def main():
        h = ms.current_handle()
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve(BROKER)
        ).build()
        await ms.sleep(0.1)
        simulator(NetSim).add_dns_record("kafka-broker", "10.0.0.1")
        dns_cfg = ClientConfig().set("bootstrap.servers", "kafka-broker:9092")

        admin_node = h.create_node().name("admin").ip("10.0.0.2").build()

        async def setup():
            admin = await dns_cfg.create(AdminClient)
            assert (await admin.create_topics([NewTopic.new("events", 4)])) == [None]

        await admin_node.spawn(setup())

        results = []

        def producer_init(tag):
            def make():
                async def run():
                    p = await dns_cfg.create(FutureProducer)
                    for i in range(10):
                        await p.send(
                            BaseRecord.to("events").with_payload(f"{tag}-{i}")
                        )
                        await ms.sleep(0.01)

                return run()

            return make

        h.create_node().name("p1").ip("10.0.0.3").init(producer_init("p1")).build()
        h.create_node().name("p2").ip("10.0.0.4").init(producer_init("p2")).build()

        async def consume(partitions):
            c = await dns_cfg.create(BaseConsumer)
            tpl = TopicPartitionList()
            for p in partitions:
                tpl.add_partition("events", p)
            await c.assign(tpl)
            while True:
                msg = await c.poll(2.0)
                if msg is None:
                    return
                results.append(msg.payload.decode())

        c1 = h.create_node().name("c1").ip("10.0.0.5").build()
        c2 = h.create_node().name("c2").ip("10.0.0.6").build()
        t1 = c1.spawn(consume([0, 1]))
        t2 = c2.spawn(consume([2, 3]))
        await t1
        await t2
        assert sorted(results) == sorted(
            [f"p{j}-{i}" for j in (1, 2) for i in range(10)]
        )

    rt.block_on(main())


def test_kafka_determinism():
    def workload():
        async def main():
            h = ms.current_handle()
            h.create_node().name("broker").ip("10.0.0.1").init(
                lambda: SimBroker().serve(BROKER)
            ).build()
            node = h.create_node().name("client").ip("10.0.0.2").build()
            await ms.sleep(0.1)

            async def run():
                admin = await cfg().create(AdminClient)
                await admin.create_topics([NewTopic.new("t", 2)])
                producer = await cfg().create(FutureProducer)
                for i in range(8):
                    await producer.send(BaseRecord.to("t").with_payload(f"m{i}"))
                consumer = await cfg().create(BaseConsumer)
                await consumer.subscribe(["t"])
                n = 0
                while await consumer.poll(0.2) is not None:
                    n += 1
                assert n == 8

            await node.spawn(run())

        return main()

    ms.Runtime.check_determinism(49, workload)


# -- consumer groups (beyond the reference: its sim has no groups) ----------


def gcfg(group: str, auto: bool = True) -> ClientConfig:
    c = cfg().set("group.id", group)
    if not auto:
        c.set("enable.auto.commit", "false")
    return c


def test_group_splits_partitions_across_members():
    """Two members of one group range-split 4 partitions 2/2 and together
    consume every message exactly once."""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g1", 4)])
        producer = await cfg().create(FutureProducer)
        for i in range(12):
            await producer.send(BaseRecord.to("g1").with_payload(f"m{i}"))

        a = await gcfg("grp").create(BaseConsumer)
        b = await gcfg("grp").create(BaseConsumer)
        await a.subscribe(["g1"])
        await b.subscribe(["g1"])
        # b's join bumped the generation; a adopts it at next poll
        got_a, got_b = [], []
        for _ in range(24):
            m = await a.poll(timeout_s=0.1)
            if m:
                got_a.append(m.payload.decode())
            m = await b.poll(timeout_s=0.1)
            if m:
                got_b.append(m.payload.decode())
        assert len(a._assignments) == 2 and len(b._assignments) == 2
        assert {x.partition for x in a._assignments}.isdisjoint(
            {x.partition for x in b._assignments}
        )
        assert sorted(got_a + got_b) == sorted(f"m{i}" for i in range(12))

    with_broker(900, run)


def test_group_rebalance_on_join_and_leave():
    """A lone member holds all partitions; a joiner halves them; a leave
    returns them (eager rebalance via generation bump on heartbeat)."""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g2", 4)])
        a = await gcfg("grp2").create(BaseConsumer)
        await a.subscribe(["g2"])
        assert len(a._assignments) == 4

        b = await gcfg("grp2").create(BaseConsumer)
        await b.subscribe(["g2"])
        await a.poll(timeout_s=0.05)  # observe the new generation
        assert len(a._assignments) == 2 and len(b._assignments) == 2

        await b.unsubscribe()
        await a.poll(timeout_s=0.05)
        assert len(a._assignments) == 4

    with_broker(901, run)


def test_group_commit_and_resume():
    """Committed offsets survive a member's departure: a successor in the
    same group resumes where the predecessor committed, not from the log
    start; a fresh group still starts from the beginning."""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g3", 1)])
        producer = await cfg().create(FutureProducer)
        for i in range(6):
            await producer.send(BaseRecord.to("g3").with_payload(f"m{i}"))

        first = await gcfg("grp3", auto=False).create(BaseConsumer)
        await first.subscribe(["g3"])
        for _ in range(3):
            m = await first.poll(timeout_s=0.5)
            assert m is not None
        await first.commit()
        await first.unsubscribe()

        second = await gcfg("grp3", auto=False).create(BaseConsumer)
        await second.subscribe(["g3"])
        m = await second.poll(timeout_s=0.5)
        assert m is not None and m.payload == b"m3"  # resumed, no replay

        fresh = await gcfg("other", auto=False).create(BaseConsumer)
        await fresh.subscribe(["g3"])
        m = await fresh.poll(timeout_s=0.5)
        assert m is not None and m.payload == b"m0"  # new group: log start

    with_broker(902, run)


def test_group_auto_commit_on_unsubscribe():
    """enable.auto.commit (the default) commits positions when the member
    leaves, so a successor resumes without an explicit commit()."""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g4", 1)])
        producer = await cfg().create(FutureProducer)
        for i in range(4):
            await producer.send(BaseRecord.to("g4").with_payload(f"m{i}"))

        first = await gcfg("grp4").create(BaseConsumer)
        await first.subscribe(["g4"])
        for _ in range(2):
            assert await first.poll(timeout_s=0.5) is not None
        await first.unsubscribe()  # auto-commits

        second = await gcfg("grp4").create(BaseConsumer)
        await second.subscribe(["g4"])
        m = await second.poll(timeout_s=0.5)
        assert m is not None and m.payload == b"m2"

    with_broker(903, run)


def test_group_rebalance_commits_consumed_before_revoke():
    """Commit-on-revoke: with auto-commit on (default), a member commits
    consumed positions before adopting a new assignment, even though the
    5 s auto-commit interval never elapsed — so a rebalance where the old
    owner heartbeats before the new owner fetches re-delivers nothing.
    (The window cannot be fully closed under the eager protocol: a new
    member fetching BEFORE the old owner's next poll still re-delivers
    the uncommitted tail — at-least-once, as in Kafka itself.)"""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g6", 1)])
        producer = await cfg().create(FutureProducer)
        for i in range(6):
            await producer.send(BaseRecord.to("g6").with_payload(f"m{i}"))

        a = await gcfg("grp6").create(BaseConsumer)
        await a.subscribe(["g6"])
        seen = []
        for _ in range(3):
            m = await a.poll(timeout_s=0.5)
            seen.append(m.payload.decode())
        assert seen == ["m0", "m1", "m2"]

        b = await gcfg("grp6").create(BaseConsumer)
        await b.subscribe(["g6"])  # generation bump; a must commit first
        got = []
        for _ in range(10):
            for c in (a, b):
                m = await c.poll(timeout_s=0.05)
                if m:
                    got.append(m.payload.decode())
        # the single partition landed on exactly one member, which resumed
        # at the committed position — m0-m2 never re-delivered
        assert got == ["m3", "m4", "m5"]

    with_broker(904, run)


def test_group_commit_generation_fencing():
    """A zombie member — holding an assignment a rebalance it never
    observed has revoked — cannot roll the group's committed offsets
    backward: the broker rejects its stale-generation commit with
    ILLEGAL_GENERATION, and the new owner's commit survives."""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g8", 1)])
        producer = await cfg().create(FutureProducer)
        for i in range(6):
            await producer.send(BaseRecord.to("g8").with_payload(f"m{i}"))

        zombie = await gcfg("grp8", auto=False).create(BaseConsumer)
        await zombie.subscribe(["g8"])  # generation 1, owns the partition
        for _ in range(3):
            m = await zombie.poll(timeout_s=0.5)
            assert m is not None
        await zombie.commit()  # current generation: accepted (offset 3)

        other = await gcfg("grp8", auto=False).create(BaseConsumer)
        await other.subscribe(["g8"])  # rebalance: generation 2

        # the zombie — which never observed generation 2 — may not
        # commit: unfenced, a delayed/stale commit here could roll the
        # offset backward past a newer owner's progress
        with pytest.raises(KafkaError, match="ILLEGAL_GENERATION"):
            await zombie.commit()
        tpl = TopicPartitionList().add_partition("g8", 0)
        committed = await other.committed(tpl)
        assert committed[0][2] == 3  # the fenced commit changed nothing

        # once the member observes the current generation (an empty poll
        # heartbeats and adopts it), its commits are accepted again
        while await zombie.poll(timeout_s=0.3) is not None:
            pass
        await zombie.commit()
        committed = await other.committed(tpl)
        assert committed[0][2] == 6  # all six consumed + committed at gen 2

    with_broker(906, run)


def test_group_ops_on_unknown_group_error():
    """commit/committed/heartbeat against a group nobody ever joined must
    error by name, not silently materialize an empty group."""

    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("g7", 1)])
        c = await gcfg("nojoin", auto=False).create(BaseConsumer)
        # no subscribe -> the group never exists broker-side
        tpl = TopicPartitionList().add_partition("g7", 0)
        with pytest.raises(KafkaError, match="unknown group"):
            await c.committed(tpl)

    with_broker(905, run)


def test_group_determinism():
    """Same seed => identical group consumption interleaving."""

    def run_once(seed):
        async def run():
            admin = await cfg().create(AdminClient)
            await admin.create_topics([NewTopic.new("g5", 3)])
            producer = await cfg().create(FutureProducer)
            for i in range(9):
                await producer.send(BaseRecord.to("g5").with_payload(f"m{i}"))
            a = await gcfg("grp5").create(BaseConsumer)
            b = await gcfg("grp5").create(BaseConsumer)
            await a.subscribe(["g5"])
            await b.subscribe(["g5"])
            log = []
            for _ in range(18):
                m = await a.poll(timeout_s=0.1)
                if m:
                    log.append(("a", m.partition, m.offset))
                m = await b.poll(timeout_s=0.1)
                if m:
                    log.append(("b", m.partition, m.offset))
            return log

        return with_broker(seed, run)

    assert run_once(77) == run_once(77)
