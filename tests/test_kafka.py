"""Kafka sim tests — port of madsim-rdkafka/tests/test.rs (176 lines):
broker node + admin + producers + consumers over sim DNS, plus fetch
budgets, watermarks, offsets-for-times, seek, and broker crash/restart.
"""

import pytest

import madsim_tpu as ms
from madsim_tpu.kafka import (
    AdminClient,
    BaseConsumer,
    BaseProducer,
    BaseRecord,
    ClientConfig,
    FutureProducer,
    KafkaError,
    NewTopic,
    SimBroker,
    StreamConsumer,
    TopicPartitionList,
)
from madsim_tpu.net import NetSim
from madsim_tpu.plugin import simulator

BROKER = "10.0.0.1:9092"


def with_broker(seed, client_fn):
    rt = ms.Runtime(seed=seed)

    async def main():
        h = ms.current_handle()
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve(BROKER)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)
        return await node.spawn(client_fn())

    return rt.block_on(main())


def cfg() -> ClientConfig:
    return ClientConfig().set("bootstrap.servers", BROKER)


def test_produce_consume_round_robin():
    async def run():
        admin = await cfg().create(AdminClient)
        errs = await admin.create_topics([NewTopic.new("t", 3)])
        assert errs == [None]
        # duplicate create reports an error string
        errs = await admin.create_topics([NewTopic.new("t", 3)])
        assert errs[0] is not None

        producer = await cfg().create(FutureProducer)
        parts = set()
        for i in range(6):
            partition, offset = await producer.send(
                BaseRecord.to("t").with_payload(f"m{i}")
            )
            parts.add(partition)
        # keyless produce round-robins over all 3 partitions (broker.rs:80-101)
        assert parts == {0, 1, 2}

        consumer = await cfg().create(BaseConsumer)
        await consumer.subscribe(["t"])
        got = set()
        for _ in range(6):
            msg = await consumer.poll(1.0)
            assert msg is not None
            got.add(msg.payload.decode())
        assert got == {f"m{i}" for i in range(6)}
        assert await consumer.poll(0.1) is None

    with_broker(41, run)


def test_keyed_produce_is_sticky():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 4)])
        producer = await cfg().create(FutureProducer)
        parts = {
            (await producer.send(BaseRecord.to("t").with_key("k1").with_payload(str(i))))[0]
            for i in range(5)
        }
        assert len(parts) == 1  # same key → same partition

    with_broker(42, run)


def test_base_producer_buffers_until_flush():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 1)])
        producer = await cfg().create(BaseProducer)
        consumer = await cfg().create(BaseConsumer)
        await consumer.subscribe(["t"])
        producer.send(BaseRecord.to("t").with_payload("a"))
        producer.send(BaseRecord.to("t").with_payload("b"))
        assert producer.in_flight_count() == 2
        assert await consumer.poll(0.1) is None  # nothing until flush
        await producer.flush()
        assert (await consumer.poll(1.0)).payload == b"a"
        assert (await consumer.poll(1.0)).payload == b"b"

    with_broker(43, run)


def test_watermarks_seek_offsets_for_times():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 1)])
        producer = await cfg().create(FutureProducer)
        t_mid = None
        for i in range(5):
            if i == 3:
                await ms.sleep(5)
                t_mid = int(ms.time.now() * 1000)
            await producer.send(BaseRecord.to("t").with_payload(f"m{i}"))
        consumer = await cfg().create(BaseConsumer)
        lo, hi = await consumer.fetch_watermarks("t", 0)
        assert (lo, hi) == (0, 5)
        # offsets_for_times finds the first message at/after t_mid
        tpl = TopicPartitionList().add_partition_offset("t", 0, t_mid)
        [(_, _, off)] = await consumer.offsets_for_times(tpl)
        assert off == 3
        # assign + seek replays from there
        await consumer.assign(TopicPartitionList().add_partition("t", 0))
        consumer.seek("t", 0, off)
        assert (await consumer.poll(1.0)).payload == b"m3"

    with_broker(44, run)


def test_fetch_byte_budget():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 1)])
        producer = await cfg().create(FutureProducer)
        for i in range(10):
            await producer.send(BaseRecord.to("t").with_payload(b"x" * 100))
        # max.partition.fetch.bytes of 250 → ~3 messages per fetch round
        consumer = await (
            cfg().set("max.partition.fetch.bytes", 250).create(BaseConsumer)
        )
        await consumer.subscribe(["t"])
        for _ in range(10):
            assert (await consumer.poll(1.0)) is not None
        assert await consumer.poll(0.05) is None

    with_broker(45, run)


def test_stream_consumer_and_linger():
    async def run():
        admin = await cfg().create(AdminClient)
        await admin.create_topics([NewTopic.new("t", 2)])
        consumer = await cfg().create(StreamConsumer)
        await consumer.subscribe(["t"])

        async def produce_later():
            producer = await (cfg().set("linger.ms", 50).create(FutureProducer))
            await ms.sleep(1.0)
            await producer.send(BaseRecord.to("t").with_payload("late"))

        ms.spawn(produce_later())
        t0 = ms.time.elapsed()
        msg = await consumer.recv()
        assert msg.payload == b"late"
        assert ms.time.elapsed() - t0 >= 1.0  # waited on virtual time

    with_broker(46, run)


def test_broker_crash_restart():
    rt = ms.Runtime(seed=47)

    async def main():
        h = ms.current_handle()
        broker = h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve(BROKER)
        ).build()
        node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.sleep(0.1)

        async def run():
            admin = await cfg().create(AdminClient)
            await admin.create_topics([NewTopic.new("t", 1)])
            producer = await cfg().create(FutureProducer)
            await producer.send(BaseRecord.to("t").with_payload("pre"))
            h.kill(broker)
            with pytest.raises(KafkaError):
                await producer.send(BaseRecord.to("t").with_payload("down"))
            h.restart(broker)
            await ms.sleep(0.2)
            # broker state is volatile (fresh on restart, like the ref sim)
            with pytest.raises(KafkaError, match="unknown topic"):
                await producer.send(BaseRecord.to("t").with_payload("post"))
            await admin.create_topics([NewTopic.new("t", 1)])
            partition, offset = await producer.send(
                BaseRecord.to("t").with_payload("post")
            )
            assert (partition, offset) == (0, 0)

        await node.spawn(run())

    rt.block_on(main())


def test_two_producers_two_consumers_topology():
    """The reference's flagship topology (tests/test.rs:21-100): admin +
    2 producers + 2 consumers on separate nodes over sim DNS."""
    rt = ms.Runtime(seed=48)

    async def main():
        h = ms.current_handle()
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve(BROKER)
        ).build()
        await ms.sleep(0.1)
        simulator(NetSim).add_dns_record("kafka-broker", "10.0.0.1")
        dns_cfg = ClientConfig().set("bootstrap.servers", "kafka-broker:9092")

        admin_node = h.create_node().name("admin").ip("10.0.0.2").build()

        async def setup():
            admin = await dns_cfg.create(AdminClient)
            assert (await admin.create_topics([NewTopic.new("events", 4)])) == [None]

        await admin_node.spawn(setup())

        results = []

        def producer_init(tag):
            def make():
                async def run():
                    p = await dns_cfg.create(FutureProducer)
                    for i in range(10):
                        await p.send(
                            BaseRecord.to("events").with_payload(f"{tag}-{i}")
                        )
                        await ms.sleep(0.01)

                return run()

            return make

        h.create_node().name("p1").ip("10.0.0.3").init(producer_init("p1")).build()
        h.create_node().name("p2").ip("10.0.0.4").init(producer_init("p2")).build()

        async def consume(partitions):
            c = await dns_cfg.create(BaseConsumer)
            tpl = TopicPartitionList()
            for p in partitions:
                tpl.add_partition("events", p)
            await c.assign(tpl)
            while True:
                msg = await c.poll(2.0)
                if msg is None:
                    return
                results.append(msg.payload.decode())

        c1 = h.create_node().name("c1").ip("10.0.0.5").build()
        c2 = h.create_node().name("c2").ip("10.0.0.6").build()
        t1 = c1.spawn(consume([0, 1]))
        t2 = c2.spawn(consume([2, 3]))
        await t1
        await t2
        assert sorted(results) == sorted(
            [f"p{j}-{i}" for j in (1, 2) for i in range(10)]
        )

    rt.block_on(main())


def test_kafka_determinism():
    def workload():
        async def main():
            h = ms.current_handle()
            h.create_node().name("broker").ip("10.0.0.1").init(
                lambda: SimBroker().serve(BROKER)
            ).build()
            node = h.create_node().name("client").ip("10.0.0.2").build()
            await ms.sleep(0.1)

            async def run():
                admin = await cfg().create(AdminClient)
                await admin.create_topics([NewTopic.new("t", 2)])
                producer = await cfg().create(FutureProducer)
                for i in range(8):
                    await producer.send(BaseRecord.to("t").with_payload(f"m{i}"))
                consumer = await cfg().create(BaseConsumer)
                await consumer.subscribe(["t"])
                n = 0
                while await consumer.poll(0.2) is not None:
                    n += 1
                assert n == 8

            await node.spawn(run())

        return main()

    ms.Runtime.check_determinism(49, workload)
