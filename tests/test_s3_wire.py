"""S3 REST wire tests: a STOCK HTTP client (aiohttp) driving the
framework's S3Service over the real S3 protocol (madsim_tpu/s3/wire.py)
— path-style REST, XML bodies, S3 status codes and headers. The analogue
of madsim-aws-sdk-s3's std mode speaking actual S3 REST."""

import xml.etree.ElementTree as ET

import pytest

aiohttp = pytest.importorskip("aiohttp")

from madsim_tpu import real  # noqa: E402
from madsim_tpu.s3 import wire  # noqa: E402


async def _start():
    server = wire.WireServer()
    task = real.spawn(server.serve(("127.0.0.1", 0)))
    while server.bound_addr is None:
        if task.done():
            task.result()
        await real.sleep(0.005)
    host, port = server.bound_addr
    return server, task, f"http://{host}:{port}"


def test_s3_wire_object_lifecycle():
    async def main():
        server, task, base = await _start()
        async with aiohttp.ClientSession() as http:
            # create bucket; duplicate conflicts with the S3 error shape
            assert (await http.put(f"{base}/b1")).status == 200
            r = await http.put(f"{base}/b1")
            assert r.status == 409
            assert "<Code>BucketAlreadyExists</Code>" in await r.text()

            # put / get / head with ETag + Content-Length
            r = await http.put(f"{base}/b1/dir/hello.txt", data=b"payload")
            assert r.status == 200 and r.headers["ETag"].startswith('"')
            etag = r.headers["ETag"]

            r = await http.get(f"{base}/b1/dir/hello.txt")
            assert r.status == 200 and await r.read() == b"payload"
            assert r.headers["ETag"] == etag
            assert r.headers["Content-Length"] == "7"
            assert "GMT" in r.headers["Last-Modified"]

            r = await http.head(f"{base}/b1/dir/hello.txt")
            assert r.status == 200 and r.headers["Content-Length"] == "7"

            # missing key: 404 with the S3 XML error code
            r = await http.get(f"{base}/b1/nope")
            assert r.status == 404
            assert "<Code>NoSuchKey</Code>" in await r.text()

            # delete is idempotent (204 both times)
            assert (await http.delete(f"{base}/b1/dir/hello.txt")).status == 204
            assert (await http.delete(f"{base}/b1/dir/hello.txt")).status == 204

            # empty bucket deletes; missing bucket is NoSuchBucket
            assert (await http.delete(f"{base}/b1")).status == 204
            r = await http.get(f"{base}/b1/any")
            assert r.status == 404
            assert "<Code>NoSuchBucket</Code>" in await r.text()
        server.close()
        task.abort()

    real.Runtime().block_on(main())


def test_s3_wire_list_objects_v2_pagination():
    async def main():
        server, task, base = await _start()
        async with aiohttp.ClientSession() as http:
            await http.put(f"{base}/data")
            for i in range(5):
                await http.put(f"{base}/data/logs/{i:02d}", data=b"x" * i)
            await http.put(f"{base}/data/other", data=b"y")

            # prefix + max-keys paging via NextContinuationToken
            seen = []
            token = None
            while True:
                url = f"{base}/data?list-type=2&prefix=logs/&max-keys=2"
                if token:
                    url += f"&continuation-token={token}"
                r = await http.get(url)
                assert r.status == 200
                root = ET.fromstring(await r.text())
                page = [c.findtext("Key") for c in root.iter("Contents")]
                seen.extend(page)
                if root.findtext("IsTruncated") != "true":
                    break
                token = root.findtext("NextContinuationToken")
            assert seen == [f"logs/{i:02d}" for i in range(5)]

            # batch delete via the POST ?delete XML document
            doc = (
                "<Delete>"
                + "".join(
                    f"<Object><Key>logs/{i:02d}</Key></Object>" for i in range(5)
                )
                + "</Delete>"
            )
            r = await http.post(f"{base}/data?delete", data=doc.encode())
            assert r.status == 200
            assert (await r.text()).count("<Deleted>") == 5

            # list buckets XML at the service root
            r = await http.get(f"{base}/")
            assert "<Name>data</Name>" in await r.text()
        server.close()
        task.abort()

    real.Runtime().block_on(main())


def test_s3_wire_head_bucket_and_copy_object():
    """HeadBucket (existence probe every SDK issues) and CopyObject via
    the x-amz-copy-source header with its XML result."""
    async def main():
        server, task, base = await _start()
        async with aiohttp.ClientSession() as http:
            assert (await http.head(f"{base}/missing")).status == 404
            await http.put(f"{base}/src")
            assert (await http.head(f"{base}/src")).status == 200

            r = await http.put(f"{base}/src/orig", data=b"copy me")
            etag = r.headers["ETag"]

            await http.put(f"{base}/dst")
            r = await http.put(
                f"{base}/dst/copied",
                headers={"x-amz-copy-source": "/src/orig"},
            )
            assert r.status == 200
            text = await r.text()
            assert "<CopyObjectResult>" in text and etag in text

            r = await http.get(f"{base}/dst/copied")
            assert await r.read() == b"copy me"
            assert r.headers["ETag"] == etag  # content-addressed

            # missing source surfaces the S3 error
            r = await http.put(
                f"{base}/dst/bad",
                headers={"x-amz-copy-source": "/src/nope"},
            )
            assert r.status == 404
            assert "<Code>NoSuchKey</Code>" in await r.text()
        server.close()
        task.abort()

    real.Runtime().block_on(main())


def test_s3_wire_multipart_upload():
    async def main():
        server, task, base = await _start()
        async with aiohttp.ClientSession() as http:
            await http.put(f"{base}/mp")

            # initiate -> UploadId from the XML result
            r = await http.post(f"{base}/mp/big.bin?uploads")
            assert r.status == 200
            upload_id = ET.fromstring(await r.text()).findtext("UploadId")
            assert upload_id

            # upload parts (out of order on the wire; completed in order)
            for n, chunk in ((2, b"BBBB"), (1, b"AAAA"), (3, b"CC")):
                r = await http.put(
                    f"{base}/mp/big.bin?partNumber={n}&uploadId={upload_id}",
                    data=chunk,
                )
                assert r.status == 200 and r.headers["ETag"]

            doc = (
                "<CompleteMultipartUpload>"
                "<Part><PartNumber>1</PartNumber></Part>"
                "<Part><PartNumber>2</PartNumber></Part>"
                "<Part><PartNumber>3</PartNumber></Part>"
                "</CompleteMultipartUpload>"
            )
            r = await http.post(
                f"{base}/mp/big.bin?uploadId={upload_id}", data=doc.encode()
            )
            assert r.status == 200
            assert "<ETag>" in await r.text()
            r = await http.get(f"{base}/mp/big.bin")
            assert await r.read() == b"AAAABBBBCC"

            # completing again: the upload is gone
            r = await http.post(
                f"{base}/mp/big.bin?uploadId={upload_id}", data=doc.encode()
            )
            assert r.status == 404
            assert "<Code>NoSuchUpload</Code>" in await r.text()

            # abort path
            r = await http.post(f"{base}/mp/tmp.bin?uploads")
            up2 = ET.fromstring(await r.text()).findtext("UploadId")
            r = await http.delete(f"{base}/mp/tmp.bin?uploadId={up2}")
            assert r.status == 204

            # UploadPartCopy: a part sourced from an existing object
            await http.put(f"{base}/mp/src.bin", data=b"SOURCE")
            r = await http.post(f"{base}/mp/joined.bin?uploads")
            up3 = ET.fromstring(await r.text()).findtext("UploadId")
            r = await http.put(
                f"{base}/mp/joined.bin?partNumber=1&uploadId={up3}",
                headers={"x-amz-copy-source": "/mp/src.bin"},
            )
            assert r.status == 200
            assert "<CopyPartResult>" in await r.text()
            r = await http.put(
                f"{base}/mp/joined.bin?partNumber=2&uploadId={up3}",
                data=b"+TAIL",
            )
            doc2 = (
                "<CompleteMultipartUpload>"
                "<Part><PartNumber>1</PartNumber></Part>"
                "<Part><PartNumber>2</PartNumber></Part>"
                "</CompleteMultipartUpload>"
            )
            await http.post(f"{base}/mp/joined.bin?uploadId={up3}",
                            data=doc2.encode())
            r = await http.get(f"{base}/mp/joined.bin")
            assert await r.read() == b"SOURCE+TAIL"
        server.close()
        task.abort()

    real.Runtime().block_on(main())
