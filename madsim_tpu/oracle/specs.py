"""Sequential specifications the linearizability checker runs against.

A spec answers one question: *is this operation's observed result legal
as the next atomic step of the datatype?* The checker (oracle/check.py)
searches over linearization orders; the spec supplies the datatype's
sequential semantics through three methods:

- ``init() -> state`` — the initial abstract state. States must be
  **hashable** (the WGL search memoizes on ``(linearized-set, state)``).
- ``apply(state, op) -> (ok, state2)`` — attempt ``op`` as the next
  atomic step. For a completed op, ``ok`` demands the observed result
  matches; an open op (no completion recorded) has no observation to
  contradict, so ``ok`` is True and only the state effect applies.
- ``partition_of(op) -> key`` — linearizability is compositional over
  independent objects (the Herlihy–Wing locality theorem), so the
  checker verifies each partition's subhistory independently — the
  difference between exponential-in-history and exponential-in-
  per-key-contention.

``structural(ops)`` is an optional pre-pass for invariants that are
per-client and order-based rather than value-based (kafka's
committed-offset monotonicity) — cheap, and failures there skip the
search entirely.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .history import OP_DEL, OP_ELECT, OP_FETCH, OP_GET, OP_PRODUCE, OP_PUT, Op

ABSENT = -1  # the value-column encoding of "key not present"


class Spec:
    """Base sequential spec; subclasses override the three methods.

    ``name`` is identity, not decoration: the device-side screen
    (oracle/screen.py) dispatches its conservative first pass on it, so
    a subclass that reuses a bundled name inherits that screen's
    conservatism assumptions — a spec with *stricter* semantics than
    its namesake must pick a fresh name (and go unscreened) rather than
    risk the screen clearing seeds its checker would reject."""

    name = "spec"

    def init(self):
        raise NotImplementedError

    def apply(self, state, op: Op):
        raise NotImplementedError

    def partition_of(self, op: Op) -> int:
        return 0

    def structural(self, ops: Sequence[Op]) -> Optional[Tuple[int, str]]:
        """Order-based pre-check; return ``(op index, reason)`` on breach."""
        return None

    def partition(self, ops: Sequence[Op]) -> Dict[int, List[Tuple[int, Op]]]:
        """Group ops by partition key, keeping each op's global index."""
        parts: Dict[int, List[Tuple[int, Op]]] = {}
        for i, op in enumerate(ops):
            parts.setdefault(self.partition_of(op), []).append((i, op))
        return parts


class KVSpec(Spec):
    """A map of independent int registers — the etcd KV sequential spec.

    Per-key state is the register value (``ABSENT`` when unset). PUT
    writes, GET must observe exactly the current value, DEL (the etcd
    model's internal lease-expiry deletions, recorded as server ops with
    invoke == complete) unsets. One key = one partition, so the search
    only ever weighs genuinely-concurrent ops on the same key.
    """

    name = "kv"

    def init(self):
        return ABSENT

    def apply(self, state, op: Op):
        if op.op == OP_PUT:
            return True, op.inp
        if op.op == OP_DEL:
            return True, ABSENT
        if op.op == OP_GET:
            ok = (not op.complete) or op.out == state
            return ok, state
        return False, state

    def partition_of(self, op: Op) -> int:
        return op.key


class LogSpec(Spec):
    """Per-partition ordered log — the kafka sequential spec.

    Per-partition state is the number of appended records. PRODUCE
    appends one record (retries are separate invokes and separate
    appends — the device broker does not dedupe); a completed
    FETCH(offset) that served ``out`` records requires ``offset + out``
    records to already exist — a broker serving records no linearized
    produce could have appended is the violation.

    ``structural`` adds committed-offset monotonicity: the device client
    only records a fetch completion when the response matched its
    position, so each consumer's completed fetches must advance its
    offset contiguously — ``offset[i+1] == offset[i] + served[i]`` in
    completion order, never backwards.
    """

    name = "log"

    def init(self):
        return 0

    def apply(self, state, op: Op):
        if op.op == OP_PRODUCE:
            return True, state + 1
        if op.op == OP_FETCH:
            ok = (not op.complete) or (op.inp + op.out <= state)
            return ok, state
        return False, state

    def partition_of(self, op: Op) -> int:
        return op.key

    def structural(self, ops: Sequence[Op]) -> Optional[Tuple[int, str]]:
        pos: Dict[Tuple[int, int], int] = {}  # (client, partition) -> offset
        done = [
            (op.complete_ns, i, op)
            for i, op in enumerate(ops)
            if op.op == OP_FETCH and op.complete
        ]
        for _, i, op in sorted(done):
            expect = pos.get((op.client, op.key), 0)
            if op.inp != expect:
                return i, (
                    f"consumer {op.client} offset broke contiguity on "
                    f"partition {op.key}: fetched at {op.inp}, committed "
                    f"offset was {expect}"
                )
            pos[(op.client, op.key)] = op.inp + op.out
        return None


class S3Spec(Spec):
    """Per-object last-writer-wins register — the S3 sequential spec
    (the one model that had none; ROADMAP item 4).

    S3's data plane is a flat namespace of whole-object registers:
    PutObject replaces the value atomically (multipart upload included —
    its parts become visible only at CompleteMultipartUpload, which is
    the single atomic write the history records), GetObject observes
    exactly the current value, DeleteObject unsets it and a subsequent
    GET must observe absence. Recorded with the KV op vocabulary
    (OP_PUT/OP_GET/OP_DEL) but under its own name: values are content
    *fingerprints* (a 63-bit digest of the object body), ``ABSENT``
    encodes both "404" and "never written". One object key = one
    partition (S3 promises nothing across keys).

    What distinguishes it from ``KVSpec`` semantically is the failure
    envelope the load rig leans on: a completed GET with ``out ==
    ABSENT`` after any successful PUT of that key is only legal if a
    DELETE (or nothing) linearizes between them — torn multipart
    visibility or a lost PUT under an fsync stall shows up as exactly
    that inconsistency.
    """

    name = "s3"

    def init(self):
        return ABSENT

    def apply(self, state, op: Op):
        if op.op == OP_PUT:
            return True, op.inp
        if op.op == OP_DEL:
            return True, ABSENT
        if op.op == OP_GET:
            ok = (not op.complete) or op.out == state
            return ok, state
        return False, state

    def partition_of(self, op: Op) -> int:
        return op.key


class ElectionSpec(Spec):
    """Raft election safety as a sequential spec: at most one leader per
    term.

    Election histories are invoke-only (an ``OP_ELECT`` row per won
    election, key = term, inp = winner node; no client observes a
    completion), and the WGL search treats open ops as *optional* —
    omittable — so the invariant lives entirely in ``structural``: two
    OP_ELECT rows for one term naming different nodes is the breach. The
    device raft model records these rows through its ``record`` hook and
    the host example through ``HostRecorder``, which is what lets the
    differential harness (explore/differential.py) check both tiers
    against this one spec.
    """

    name = "election"

    def init(self):
        return ABSENT

    def apply(self, state, op: Op):
        # open ops carry no observation; the state tracks the term's
        # winner for completeness but structural() is the real check
        if op.op == OP_ELECT:
            return True, op.inp
        return False, state

    def partition_of(self, op: Op) -> int:
        return op.key  # the term

    def structural(self, ops: Sequence[Op]) -> Optional[Tuple[int, str]]:
        winner: Dict[int, int] = {}  # term -> node
        for i, op in enumerate(ops):
            if op.op != OP_ELECT:
                continue
            prev = winner.get(op.key)
            if prev is not None and prev != op.inp:
                return i, (
                    f"two leaders elected in term {op.key}: node {prev} "
                    f"and node {op.inp}"
                )
            winner[op.key] = op.inp
        return None
