"""WGL-style linearizability checking over decoded operation histories.

The checker is the Wing & Gong search as refined by Lowe and Porcupine:
depth-first over partial linearizations, where a pending op is a legal
next step iff (a) its invocation precedes the earliest completion among
pending ops — no completed op is illegally reordered past it — and (b)
the sequential spec accepts its observed result from the current
abstract state. Visited ``(linearized-set, state)`` pairs are memoized
(the trick that makes the search practical: many interleavings reach the
same set with the same state), and the spec's key partitioning keeps the
exponent at per-key contention instead of history length.

Open ops (invoked, never completed — a lost response) are *optional*:
they may be linearized anywhere after their invocation or omitted
entirely, exactly the Jepsen ``:info`` treatment. A PUT whose ack was
lost but whose value a later read observed is thereby explained; one
that never took effect is dropped.

The search is exponential in the worst case, so a ``max_states`` budget
bounds it; an exhausted budget returns ``decided=False`` and counts as
clean (the oracle never reports a violation it has not proven).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .history import History, Op
from .specs import Spec

_INF = 1 << 62


class CheckResult(NamedTuple):
    """Outcome of checking one history against one spec."""

    ok: bool  # linearizable (or undecided within budget)
    decided: bool  # False iff the state budget ran out first
    bad_index: int  # global index (into history.ops) of the first bad op
    bad_op: Optional[Op]
    reason: str
    states: int  # memoized states explored across all partitions


def _linearizable(
    ops: Sequence[Op], spec: Spec, max_states: int
) -> Tuple[bool, bool, int]:
    """One partition's WGL search: (ok, decided, states explored)."""
    n = len(ops)
    if n == 0:
        return True, True, 0
    invs = [op.invoke_ns for op in ops]
    rets = [op.complete_ns if op.complete else _INF for op in ops]
    complete_mask = 0
    for i, op in enumerate(ops):
        if op.complete:
            complete_mask |= 1 << i
    init = spec.init()
    seen = {(0, init)}
    stack: List[Tuple[int, object]] = [(0, init)]
    while stack:
        mask, state = stack.pop()
        if mask & complete_mask == complete_mask:
            return True, True, len(seen)
        pending = [i for i in range(n) if not (mask >> i) & 1]
        first_ret = min(rets[i] for i in pending)
        for i in pending:
            if invs[i] > first_ret:
                continue  # a completed op returned before this invoked
            ok, state2 = spec.apply(state, ops[i])
            if not ok:
                continue
            key = (mask | (1 << i), state2)
            if key not in seen:
                if len(seen) >= max_states:
                    return True, False, len(seen)
                seen.add(key)
                stack.append(key)
    return False, True, len(seen)


def _first_bad_in_partition(
    ops: Sequence[Op], spec: Spec, max_states: int
) -> int:
    """Per-PARTITION prefix scan (all ``ops`` must share one partition):
    length of the shortest non-linearizable prefix, or -1."""
    for k in range(1, len(ops) + 1):
        ok, decided, _ = _linearizable(ops[:k], spec, max_states)
        if decided and not ok:
            return k
    return -1


def first_bad_prefix(
    ops: Sequence[Op], spec: Spec, max_states: int = 200_000
) -> int:
    """Length of the shortest non-linearizable prefix of ``ops`` (in
    invoke order), or -1 if every prefix checks out. The last op of that
    prefix is the one the failure fingerprint anchors on: the earliest
    operation whose observation the sequential spec cannot explain.

    Partition-aware like ``check_history`` (each key's subhistory is
    checked independently; the returned length ends at the earliest bad
    op across partitions), so a linearizable multi-key history is never
    falsely rejected by cross-key state mixing."""
    first = -1
    parts = spec.partition(ops)
    for key in sorted(parts):
        indexed = parts[key]
        k = _first_bad_in_partition(
            [op for _, op in indexed], spec, max_states
        )
        if k > 0:
            j = indexed[k - 1][0] + 1
            first = j if first < 0 else min(first, j)
    return first


def check_history(
    hist: History, spec: Spec, max_states: int = 200_000
) -> CheckResult:
    """Check one decoded history against a sequential spec.

    Runs the spec's structural pre-pass, then the WGL search per
    partition (each key's subhistory is independent — Herlihy–Wing
    locality). On failure the result pins the first bad op: the earliest
    op, across failing partitions, ending a non-linearizable prefix."""
    ops = hist.ops
    s = spec.structural(ops)
    if s is not None:
        i, reason = s
        return CheckResult(
            ok=False, decided=True, bad_index=i, bad_op=ops[i],
            reason=reason, states=0,
        )
    states = 0
    decided = True
    bad: List[Tuple[int, int]] = []  # (invoke_ns, global index)
    parts = spec.partition(ops)
    for key in sorted(parts):
        indexed = parts[key]
        sub = [op for _, op in indexed]
        ok, dec, n = _linearizable(sub, spec, max_states)
        states += n
        decided = decided and dec
        if dec and not ok:
            k = _first_bad_in_partition(sub, spec, max_states)
            j = indexed[k - 1][0] if k > 0 else indexed[-1][0]
            bad.append((ops[j].invoke_ns, j))
    if not bad:
        return CheckResult(
            ok=True, decided=decided, bad_index=-1, bad_op=None,
            reason="" if decided else "state budget exhausted (undecided)",
            states=states,
        )
    _, j = min(bad)
    op = ops[j]
    return CheckResult(
        ok=False, decided=True, bad_index=j, bad_op=op,
        reason=f"no linearization explains {op.describe()}",
        states=states,
    )


def _check_job(args) -> CheckResult:
    """Top-level worker body (must pickle) for ``check_histories``."""
    hist, spec, max_states = args
    return check_history(hist, spec, max_states=max_states)


def check_histories(
    hists: Sequence[History],
    spec: Spec,
    max_states: int = 200_000,
    workers: int = 0,
) -> List[CheckResult]:
    """Check a batch of histories, optionally fanned over a process
    pool — the host half of the screened checked-sweep pipeline
    (oracle/screen.py + engine/checkpoint.run_sweep_pipelined).

    Determinism contract: results are returned in input order and each
    verdict is a pure function of ``(history, spec, max_states)``, so
    the worker count can only change wall-clock, never a byte of any
    downstream report (scripts/check_determinism.sh gates this).
    ``workers <= 1`` checks inline; the pool workers are clean
    interpreters (forkserver/spawn — never a fork of THIS process,
    whose JAX runtime threads make mid-pipeline forks deadlock-prone)
    importing only the numpy-side checker modules, and the pool is
    created once per worker count. Falls back to inline checking where
    no multiprocessing context is available."""
    hists = list(hists)
    if workers and workers > 1 and len(hists) > 1:
        ex = _pool(workers)
        if ex is not None:
            from concurrent.futures.process import BrokenProcessPool

            jobs = [(h, spec, max_states) for h in hists]
            try:
                return list(
                    ex.map(
                        _check_job,
                        jobs,
                        chunksize=max(1, len(jobs) // (workers * 4)),
                    )
                )
            except BrokenProcessPool:
                # a worker died (OOM on a pathological history, OS
                # kill): the executor is permanently broken, so evict
                # it — the NEXT call re-forks a fresh pool — and check
                # this batch inline (same results: pure per-history
                # function) instead of failing the remaining chunks
                _POOLS.pop(workers, None)
                ex.shutdown(wait=False, cancel_futures=True)
    return [check_history(h, spec, max_states=max_states) for h in hists]


def _pool(workers: int):
    """Process pool for ``check_histories``, cached per worker count —
    a checked sweep calls in once per chunk, and re-spawning a pool per
    chunk would cost more than small chunks' checking. NOT the fork
    context: by the time the pipeline's host phase runs, this process
    carries live JAX dispatch threads, and forking a multithreaded
    process can deadlock the child inside a held lock — a hung (not
    dead) worker never breaks the pool, so the whole sweep would block.
    forkserver (preferred: its server is a clean single-threaded
    process that forks cheap workers) or spawn both start workers as
    fresh interpreters importing only the numpy-side checker modules —
    a one-time ~0.3 s/worker tax the persistent pool amortizes.
    Returns None where neither context exists (callers check inline)."""
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor

    ex = _POOLS.get(workers)
    if ex is None and workers not in _POOLS:
        ctx = None
        for method in ("forkserver", "spawn"):
            try:
                ctx = mp.get_context(method)
                break
            except ValueError:
                continue
        ex = (
            ProcessPoolExecutor(max_workers=workers, mp_context=ctx)
            if ctx is not None
            else None
        )
        _POOLS[workers] = ex
    return ex


_POOLS: dict = {}


def violating_seeds(
    final,
    spec: Spec,
    max_states: int = 200_000,
    screen=None,
    workers: int = 0,
    stats: Optional[dict] = None,
) -> np.ndarray:
    """Seeds of a finished sweep whose decoded history the checker
    rejects — the history oracle's counterpart of
    ``replay.violation_seeds`` (model-latched flags). Overflowed
    histories are checked on their valid prefix (the buffer never
    wraps), so a reported seed is always a proven violation.

    ``screen=True`` runs the device-side first pass (oracle/screen.py)
    and decodes + checks only the suspect lanes — identical results by
    the screen's conservatism contract, at a fraction of the host cost
    (raises for a spec with no device screen); ``screen="auto"`` does
    the same but quietly degrades to checking every lane for unscreened
    specs; a callable screens with ``screen(final) -> bool[S]``.
    ``workers`` fans the checker over a process pool
    (``check_histories``).

    ``stats`` (a dict, mutated in place) receives
    ``{"checked": lanes handed to the checker, "budget_exceeded":
    lanes whose WGL search exhausted max_states}`` — undecided lanes
    are reported as non-violating (the checker is sound, not complete
    under a finite budget), so callers wanting the honest picture
    surface this count next to the seed list."""
    from .history import decode_lanes, decode_sweep

    if screen == "auto":
        from .screen import screen_for

        screen = screen_for(spec) is not None
    if screen is None or screen is False:
        hists = decode_sweep(final)
    else:
        from .screen import screen_sweep

        mask = (
            screen(final)
            if callable(screen)
            else screen_sweep(final, spec)
        )
        hists = decode_lanes(final, np.nonzero(np.asarray(mask))[0])
    results = check_histories(
        hists, spec, max_states=max_states, workers=workers
    )
    if stats is not None:
        stats["checked"] = len(hists)
        stats["budget_exceeded"] = sum(
            1 for r in results if not r.decided
        )
    out = [h.seed for h, r in zip(hists, results) if not r.ok]
    return np.asarray(out, dtype=np.int64)
