"""History oracle: generic consistency checking for sweep workloads.

Every oracle the sweep stack had before this package was a hand-coded,
model-specific invariant latch (`raft.viol_kind`, etcd's online revision
and lease checks) — a bug that does not trip a pre-written probe is
invisible. The Jepsen-style alternative is generic: record the
client-observed **operation history** and check it against the
datatype's **sequential specification** (Wing & Gong linearizability;
the Porcupine/WGL checker family). Three pieces:

- ``history`` — decode the engine's per-seed op-record ring buffer
  (``EngineState.hist_*``, written in-step by ``Workload.record``) into
  paired invoke/complete operations, plus a thin client-shim for
  recording host-tier histories in the same format;
- ``specs`` — pluggable sequential specs (KV register for etcd,
  per-partition ordered log for kafka);
- ``check`` — a WGL-style linearizability search with memoized state
  hashing, per-key partitioning, and first-bad-prefix location;
- ``screen`` — a conservative device-side first pass (imported lazily:
  it is the one jax-dependent module here) that flags suspect seeds as
  masked reductions over the SoA history plane, so the WGL search runs
  only where it might find something (``checked_sweep`` is the
  pipelined sweep+screen+check driver).

See docs/oracle.md for the record-hook contract and complexity caveats.
"""

from .check import (
    CheckResult,
    check_histories,
    check_history,
    first_bad_prefix,
    violating_seeds,
)
from .history import (
    OP_NAMES,
    History,
    HostRecorder,
    Op,
    decode_lanes,
    decode_seed,
    decode_sweep,
    history_bytes,
)
from .specs import ElectionSpec, KVSpec, LogSpec, S3Spec

__all__ = [
    "CheckResult",
    "check_histories",
    "check_history",
    "first_bad_prefix",
    "violating_seeds",
    "OP_NAMES",
    "History",
    "HostRecorder",
    "Op",
    "decode_lanes",
    "decode_seed",
    "decode_sweep",
    "history_bytes",
    "ElectionSpec",
    "KVSpec",
    "LogSpec",
    "S3Spec",
]
