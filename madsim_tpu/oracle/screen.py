"""Vectorized on-device history screening — the oracle's first pass.

The WGL checker (oracle/check.py) is per-seed host Python: decode ~a
hundred rows, search linearizations. At 100k+ seeds the checker, not the
engine, is the wall-clock bound of a checked sweep. This module moves a
conservative first pass onto the device: per-key quick-checks computed
as masked reductions over the SoA history plane (``EngineState.hist_*``)
of a finished chunk, yielding one bool per seed — *suspect* or
*provably boring*. Full decoding + WGL search then runs only on the
suspect lanes.

The contract is CONSERVATISM: the suspect set must be a superset of the
seeds the full checker would reject, so skipping the clean lanes never
hides a violation. Each screen is therefore built from conditions of
the form "flag unless this observation is provably explainable":

- ``kv`` (etcd register spec): EXACT within a contention window — the
  screen decides single-key register linearizability outright
  (``kv_window_suspect``: value clusters, a writes-before-reads
  2-cycle test, and an absent-read pass — see its docstring for the
  argument) and falls back to "suspect" only when some key's op
  contention exceeds ``KV_WINDOW`` concurrent ops, so a flagged lane
  is either a real violation or an over-budget window. Duplicate
  written values, re-invoked opids and DEL rows defeat the
  value-identity reasoning, so their mere presence flags the seed (the
  bundled etcd model records none of them).
- ``log`` (kafka ordered-log spec): a completed FETCH at offset ``o``
  serving ``n`` records is flagged when fewer than ``o + n`` PRODUCE
  invocations preceded its completion, or when it breaks per-consumer
  offset contiguity (the exact structural pre-check of
  ``specs.LogSpec``, which appends OK rows in completion order).
- ``election`` (raft): two ELECT rows naming different winners for one
  term — exactly ``specs.ElectionSpec.structural``, so this screen is
  precise (no false positives, no misses).

Unknown op kinds, DEL rows, and OK rows with no recorded invoke flag
the seed wholesale: a row the screen cannot reason about must not be
silently trusted. Overflowed histories screen their valid prefix — the
same prefix the checker checks (the buffer never wraps).

What the screen can NOT do is *prove* a violation: a flagged seed is a
candidate, and only the WGL search's verdict counts. The false-positive
rate on clean sweeps is bounded by construction (most conditions are
exact necessary-condition checks; tests/test_screen.py pins it <5%),
which is what makes screening a throughput win rather than a shortcut.

Everything here is jittable JAX over int32/int64 planes — [H, H]
pairwise masks reduced per seed, vmapped over lanes in blocks — so the
screen of a 16k-seed chunk is one device program, enqueued right behind
the chunk's sweep (engine/checkpoint.run_sweep_pipelined overlaps the
host-side checking of chunk N with the device sweep of chunk N+1).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .history import (
    OP_ELECT,
    OP_FETCH,
    OP_GET,
    OP_PRODUCE,
    OP_PUT,
    PH_INVOKE,
    PH_OK,
)
from .specs import ABSENT

# int64 sentinels: "no such time" below/above any virtual timestamp
_T_NEG = jnp.int64(-(1 << 62))
_T_INF = jnp.int64(1 << 62)
_I32_MIN = jnp.int32(-(1 << 31))


def _cols(rec, t, n):
    """Split one seed's raw rows into masked columns."""
    H = rec.shape[0]
    idx = jnp.arange(H, dtype=jnp.int32)
    valid = idx < jnp.asarray(n, jnp.int32)
    client, code, key, val, opid = (rec[:, i] for i in range(5))
    op, ph = code // 2, code % 2
    return idx, valid, client, op, ph, key, val, opid, jnp.asarray(t)


def _invoke_join(idx, valid, client, op, ph, opid, t):
    """For every OK row, the time of its invoke row (and the pair mask).

    The decoder pairs an OK row with the LATEST earlier matching invoke
    (kafka produce retries re-invoke one opid), so the join takes the
    max time over candidates. Rows with no match get ``_T_NEG`` —
    callers flag those (an OK without an invoke is a contract breach the
    decoder would raise on)."""
    pair = (
        (valid & (ph == PH_OK))[:, None]
        & (valid & (ph == PH_INVOKE))[None, :]
        & (client[:, None] == client[None, :])
        & (op[:, None] == op[None, :])
        & (opid[:, None] == opid[None, :])
        & (idx[None, :] < idx[:, None])
    )
    inv_t = jnp.max(jnp.where(pair, t[None, :], _T_NEG), axis=1)
    return inv_t, pair


def kv_suspect(rec, t, n) -> jnp.ndarray:
    """One seed's suspect bit under the KV register spec (etcd) — the
    ORIGINAL necessary-condition screen, superseded as the registered
    ``kv`` screen by the exact ``kv_window_suspect`` (kept for
    comparison: tests pin that the new screen's suspect set is a
    subset of this one's on clean sweeps and still ⊇ the checker's
    rejections)."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    inv_t, _ = _invoke_join(idx, valid, client, op, ph, opid, t)

    put_inv = valid & (op == OP_PUT) & (ph == PH_INVOKE)
    put_ok = valid & (op == OP_PUT) & (ph == PH_OK)
    get_ok = valid & (op == OP_GET) & (ph == PH_OK)
    obs_ok = put_ok | get_ok

    # rows the value-identity reasoning cannot cover flag the seed
    unscreenable = jnp.any(valid & ~((op == OP_PUT) | (op == OP_GET)))
    orphan = jnp.any((valid & (ph == PH_OK)) & (inv_t == _T_NEG))

    same_key = key[:, None] == key[None, :]

    # two distinct PUT invokes of one (key, value): value identity no
    # longer names a unique write — flag (values are random 31-bit
    # draws in the bundled model, so this is vanishingly rare)
    dup = jnp.any(
        put_inv[:, None]
        & put_inv[None, :]
        & same_key
        & (val[:, None] == val[None, :])
        & (idx[:, None] < idx[None, :])
    )

    # commit time of the unique PUT that wrote (key_i, out_i); an
    # unacked (open) write commits "never" — nothing can be proven to
    # follow it, so the freshness conditions below stay quiet
    wrote = put_ok[None, :] & same_key & (val[:, None] == val[None, :])
    cmp_v = jnp.where(
        jnp.any(wrote, axis=1),
        jnp.max(jnp.where(wrote, t[None, :], _T_NEG), axis=1),
        _T_INF,
    )

    ti = inv_t  # a GET-OK row's invoke time
    tc = t  # ... and its completion time (the row's own stamp)

    # ABSENT read after some PUT on the key definitely committed (the
    # recorded keys are never deleted — DEL rows flag above)
    bad_absent = (val == ABSENT) & jnp.any(
        put_ok[None, :] & same_key & (t[None, :] < ti[:, None]), axis=1
    )
    # observed value that no PUT even invoked before the read returned
    no_writer = (val != ABSENT) & ~jnp.any(
        put_inv[None, :]
        & same_key
        & (val[:, None] == val[None, :])
        & (t[None, :] <= tc[:, None]),
        axis=1,
    )
    # a fresher observation: some completed op on the key observed or
    # wrote a DIFFERENT value, began after this read's value committed,
    # and finished before this read began — in every linearization that
    # op sits between the read's write and the read, so the read is
    # provably stale (unique values; duplicates flag above)
    fresher = (val != ABSENT) & jnp.any(
        obs_ok[None, :]
        & same_key
        & (val[:, None] != val[None, :])
        & (t[None, :] < ti[:, None])
        & (inv_t[None, :] > cmp_v[:, None]),
        axis=1,
    )
    bad = get_ok & (bad_absent | no_writer | fresher)
    return jnp.any(bad) | dup | unscreenable | orphan


# contention budget of the exact kv screen: a key whose concurrent-op
# depth ever exceeds this many ops falls back to "suspect" (the [H, H]
# mask cost is paid regardless — the budget bounds the CLAIM, keeping
# the exactness argument checkable, not the compute)
KV_WINDOW = 32


def kv_window_suspect(rec, t, n, window: int = KV_WINDOW) -> jnp.ndarray:
    """One seed's suspect bit under the KV register spec — EXACT within
    a per-key contention window (the device-side linearizability
    decision; docs/oracle.md "Device-side checking").

    For a unique-value register history (duplicates/re-invokes flag
    wholesale below), group ops into value clusters ``C_v = {the PUT
    writing v} ∪ {completed GETs reading v}`` with ``m_v`` = earliest
    completion among completed cluster ops (∞ if none) and ``s_v`` =
    latest invoke in the cluster. The history is linearizable iff

    (A) every completed non-ABSENT read's value has a PUT invoked no
        later than the read completes (else no linearization can place
        the write before the read);
    (B) no completed non-ABSENT observation on a key completes strictly
        before an ABSENT read of that key invokes (else some write is
        forced before the read);
    (C) no two clusters on one key 2-cycle: ``¬∃ u ≠ v: m_u < s_v ∧
        m_v < s_u`` (an op of u completing before an op of v invokes
        forces u's write before v's in EVERY linearization — edge
        u→v; any cycle in that threshold digraph contains a 2-cycle,
        and acyclicity yields a valid linearization by topological
        order: ABSENT reads first, then each cluster's write followed
        by its reads).

    Necessity of each condition is immediate; sufficiency is the
    threshold-digraph construction, with open writes that have readers
    placed at their block's start and open ops without observers
    omitted (the checker's optional-op semantics). Ties use strict
    ``<`` exactly where the WGL search does (a pending op may
    linearize before a completion at the same instant). This closes
    the old ``kv_suspect`` conservatism gap (concurrent-write
    flip-flops whose 2-cycle no single fresher-observation witnesses)
    AND eliminates its false positives: a clean lane under budget is
    *proven* clean, a flagged lane is a violation — unless the per-key
    concurrent-op depth exceeded ``window``, the wholesale budget
    fallback that keeps the claim honest without unbounded reasoning."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    inv_t, pair = _invoke_join(idx, valid, client, op, ph, opid, t)

    put_inv = valid & (op == OP_PUT) & (ph == PH_INVOKE)
    put_ok = valid & (op == OP_PUT) & (ph == PH_OK)
    get_ok = valid & (op == OP_GET) & (ph == PH_OK)
    obs_ok = put_ok | get_ok
    ok_row = valid & (ph == PH_OK)
    inv_row = valid & (ph == PH_INVOKE)

    # wholesale flags: rows the value-identity argument cannot cover
    unscreenable = jnp.any(valid & ~((op == OP_PUT) | (op == OP_GET)))
    orphan = jnp.any(ok_row & (inv_t == _T_NEG))
    same_client = client[:, None] == client[None, :]
    same_opid = opid[:, None] == opid[None, :]
    reinvoke = jnp.any(
        inv_row[:, None]
        & inv_row[None, :]
        & same_client
        & same_opid
        & (idx[:, None] < idx[None, :])
    )

    same_key = key[:, None] == key[None, :]
    same_val = val[:, None] == val[None, :]
    dup = jnp.any(
        put_inv[:, None]
        & put_inv[None, :]
        & same_key
        & same_val
        & (idx[:, None] < idx[None, :])
    )

    # an invoke row with a later matching OK row completed (re-invokes
    # flag above, so "any match" is exact here)
    claimed = jnp.any(pair, axis=0)
    open_inv = inv_row & ~claimed
    open_put = put_inv & ~claimed

    # (A) — also catches a read completing before its write invokes
    no_writer = (val != ABSENT) & ~jnp.any(
        put_inv[None, :] & same_key & same_val & (t[None, :] <= t[:, None]),
        axis=1,
    )
    bad_a = get_ok & no_writer

    # (B) — GET-OK evidence included (the old screen's bad_absent only
    # saw PUT-OK rows and missed read-witnessed writes)
    bad_b = (
        get_ok
        & (val == ABSENT)
        & jnp.any(
            obs_ok[None, :]
            & same_key
            & (val[None, :] != ABSENT)
            & (t[None, :] < inv_t[:, None]),
            axis=1,
        )
    )

    # (C) — cluster rows: completed observations of v plus open PUT
    # invokes of v. m is per-CLUSTER (min completed-observation time,
    # shared by every member row); s is per-ROW (its own invoke) — the
    # pairwise ∃ decouples, so ∃ rows (r, q): m_r < s_q ∧ m_q < s_r
    # iff the cluster-level 2-cycle ∃ u, v: m_u < s_v ∧ m_v < s_u
    rep = (obs_ok | open_put) & (val != ABSENT)
    memb = obs_ok[None, :] & same_key & same_val
    m = jnp.min(jnp.where(memb, t[None, :], _T_INF), axis=1)
    start = jnp.where(obs_ok, inv_t, t)
    cyc = jnp.any(
        rep[:, None]
        & rep[None, :]
        & same_key
        & ~same_val
        & (m[:, None] < start[None, :])
        & (m[None, :] < start[:, None])
    )

    # window budget: per-key concurrent-op depth at each op's invoke
    # (completed ops span [invoke, completion]; open ops never end)
    o_mask = ok_row | open_inv
    o_start = jnp.where(ok_row, inv_t, t)
    o_end = jnp.where(ok_row, t, _T_INF)
    depth = jnp.sum(
        (
            o_mask[:, None]
            & o_mask[None, :]
            & same_key
            & (o_start[None, :] <= o_start[:, None])
            & (o_start[:, None] <= o_end[None, :])
        ).astype(jnp.int32),
        axis=1,
    )
    over_budget = jnp.any(o_mask & (depth > jnp.int32(window)))

    return (
        jnp.any(bad_a | bad_b)
        | cyc
        | over_budget
        | dup
        | reinvoke
        | unscreenable
        | orphan
    )


def log_suspect(rec, t, n) -> jnp.ndarray:
    """One seed's suspect bit under the ordered-log spec (kafka)."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    inv_t, pair = _invoke_join(idx, valid, client, op, ph, opid, t)

    prod_inv = valid & (op == OP_PRODUCE) & (ph == PH_INVOKE)
    fetch_ok = valid & (op == OP_FETCH) & (ph == PH_OK)

    unscreenable = jnp.any(valid & ~((op == OP_PRODUCE) | (op == OP_FETCH)))
    orphan = jnp.any(fetch_ok & (inv_t == _T_NEG))

    same_key = key[:, None] == key[None, :]

    # each fetch's offset rides on its (latest matching) invoke row
    jlast = jnp.max(jnp.where(pair, idx[None, :], jnp.int32(-1)), axis=1)
    onehot = pair & (idx[None, :] == jlast[:, None])
    off = jnp.max(jnp.where(onehot, val[None, :], _I32_MIN), axis=1)
    served = val  # a FETCH-OK row's val column is the records served

    # overread: serving past every append that could precede it — each
    # PRODUCE op (retries included: the spec counts them as separate
    # appends) invoked before this fetch completed may linearize first
    navail = jnp.sum(
        (prod_inv[None, :] & same_key & (t[None, :] <= t[:, None])).astype(
            jnp.int32
        ),
        axis=1,
    )
    overread = fetch_ok & (off + served > navail)

    # per-consumer committed-offset contiguity, in completion order (OK
    # rows append at completion, so row order IS completion order) —
    # exactly specs.LogSpec.structural
    prevm = (
        fetch_ok[:, None]
        & fetch_ok[None, :]
        & same_key
        & (client[:, None] == client[None, :])
        & (idx[None, :] < idx[:, None])
    )
    jprev = jnp.max(jnp.where(prevm, idx[None, :], jnp.int32(-1)), axis=1)
    sel_prev = prevm & (idx[None, :] == jprev[:, None])
    prev_off = jnp.max(jnp.where(sel_prev, off[None, :], _I32_MIN), axis=1)
    prev_served = jnp.max(jnp.where(sel_prev, val[None, :], _I32_MIN), axis=1)
    expect = jnp.where(jprev >= 0, prev_off + prev_served, jnp.int32(0))
    gap = fetch_ok & (off != expect)

    return jnp.any(overread | gap) | unscreenable | orphan


def election_suspect(rec, t, n) -> jnp.ndarray:
    """One seed's suspect bit under the election spec (raft) — precise:
    two ELECT rows naming different winners for one term, exactly
    ``specs.ElectionSpec.structural``."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    elect = valid & (op == OP_ELECT) & (ph == PH_INVOKE)
    unscreenable = jnp.any(valid & ~(op == OP_ELECT))
    split = jnp.any(
        elect[:, None]
        & elect[None, :]
        & (key[:, None] == key[None, :])
        & (val[:, None] != val[None, :])
    )
    return split | unscreenable


_SCREENS = {
    "kv": kv_window_suspect,
    "log": log_suspect,
    "election": election_suspect,
}


def screen_for(spec) -> Optional[Callable]:
    """The per-seed screen function for a sequential spec, by its
    ``name`` — or None when no screen exists (callers must then treat
    every seed as suspect)."""
    return _SCREENS.get(getattr(spec, "name", None))


@lru_cache(maxsize=None)
def _batched(name: str):
    return jax.jit(jax.vmap(_SCREENS[name]))


@lru_cache(maxsize=None)
def _batched_sharded(name: str, mesh, block: int):
    """The per-seed screen shard_map'd over the mesh's seed axis: each
    device screens its LOCAL lanes (in ``block``-lane sub-batches, same
    [block, H, H] working-set bound as the unsharded path), so a chunk's
    screen program runs distributed right behind its sharded sweep with
    no cross-device traffic at all — the suspect mask stays sharded
    like the history planes it reduces. Cached per (spec, mesh, block):
    a fresh shard_map wrapper per chunk would retrace every call."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import SEED_AXIS, shard_map_compat

    f = jax.vmap(_SCREENS[name])

    def local(rec, t, n):
        s = rec.shape[0]
        if s <= block:
            return f(rec, t, n)
        return jnp.concatenate(
            [
                f(rec[lo : lo + block], t[lo : lo + block], n[lo : lo + block])
                for lo in range(0, s, block)
            ]
        )

    return jax.jit(
        shard_map_compat(
            local, mesh, in_specs=P(SEED_AXIS), out_specs=P(SEED_AXIS)
        )
    )


def screen_history(rec, t, n, spec) -> bool:
    """Screen ONE seed's raw history rows (tests and replay tooling)."""
    fn = screen_for(spec)
    if fn is None:
        raise ValueError(f"no device screen for spec {spec.name!r}")
    return bool(
        fn(jnp.asarray(rec, jnp.int32), jnp.asarray(t, jnp.int64), int(n))
    )


def screen_sweep(final, spec, block: int = 1024, mesh=None) -> jnp.ndarray:
    """Suspect mask (bool[S], device array) for a finished batched sweep.

    ``block`` bounds the [block, H, H] pairwise-mask working set per
    launched program (H = hist_slots; 1024 lanes x 256 rows is ~67 MB of
    bool mask per term). The mask is NOT materialized to host — callers
    enqueue this right after the chunk's sweep and ``np.asarray`` it
    later, from the overlapped host phase.

    ``mesh`` runs the screen shard_map'd over the mesh's seed axis
    (``final`` sharded by ``parallel.run_sweep_sharded``; the batch must
    divide the mesh) — same bits per seed, distributed like the sweep
    that produced the planes."""
    fn = screen_for(spec)
    if fn is None:
        raise ValueError(
            f"no device screen for spec {getattr(spec, 'name', spec)!r}; "
            "pass screen=False (check every lane) instead"
        )
    S = int(final.seed.shape[0])
    if final.hist_rec.shape[1] == 0:
        # no recording plane: nothing to screen, nothing to check —
        # consistent with the checker accepting every empty history
        return jnp.zeros((S,), bool)
    if mesh is not None:
        return _batched_sharded(spec.name, mesh, block)(
            final.hist_rec, final.hist_t, final.hist_len
        )
    f = _batched(spec.name)
    if S <= block:
        return f(final.hist_rec, final.hist_t, final.hist_len)
    outs = [
        f(
            final.hist_rec[lo : lo + block],
            final.hist_t[lo : lo + block],
            final.hist_len[lo : lo + block],
        )
        for lo in range(0, S, block)
    ]
    return jnp.concatenate(outs)


class _HostWork:
    """The host phase of a screened checked sweep: decode the suspect
    lanes, dedup on canonical bytes, fan the WGL checker over a process
    pool, and fold the verdicts into per-chunk report dicts.

    Two consumption protocols over ONE pipeline:

    - **Sync** (``host_work(final, lo=..., ...)`` — the legacy callable
      shape every driver already speaks): submit + drain, returning the
      chunk's report dict.
    - **Incremental** (``submit`` / ``poll`` / ``drain`` — drivers that
      see ``incremental = True`` may use it, e.g.
      ``engine.stream.stream_sweep``): ``submit`` runs the cheap decode
      + dedup immediately and queues the WGL work; ``poll(seconds=...)``
      burns at most roughly that budget of checking (always making
      progress when work is pending) and returns the reports of chunks
      that FINISHED, as ``(lo, dict)`` in submission order; ``drain``
      finishes everything. The device thereby never stalls on the
      checker: unfinished verdict work carries across rounds and the
      driver merges reports strictly in submission order.

    Suspect lanes are deduplicated before checking: identical histories
    across seeds are common under coarse faults, and the WGL verdict
    depends only on the seed-free, time-rank canonical encoding
    (``history.history_canonical_bytes`` — an order-isomorphism on the
    timestamps the checker reads only through comparisons). One
    representative per equivalence class (first occurrence, lane order)
    is checked; its verdict fans back to every member, and the report
    carries the class count as ``hist_unique``.

    ``device_decode=True`` sources the canonical rows from the on-device
    decode kernel (``history.canon_sweep``) instead of per-row host
    Python: one fixed-shape jitted program derives every lane's paired +
    rank-encoded rows, the host gathers just the suspect rows and hashes
    them, and only dedup REPRESENTATIVES are materialized as ``History``
    objects (from the canonical rows themselves — rank times, same WGL
    verdict). The two paths produce bit-identical canonical bytes (the
    kernel's contract, gated by scripts/check_determinism.sh) and hence
    bit-identical reports; lanes whose rows breach the record-hook
    contract fall back to the host decoder, which raises the diagnostic.

    Determinism contract: every report dict is a pure function of its
    chunk's history planes — worker count, poll cadence and decode path
    change wall-clock only, never a byte (results are ordered by lane,
    dedup keys on content, each verdict is a pure function of one
    history, and checking is sliced in submission order).
    ``telemetry`` (``obs.Telemetry`` or None) records the suspect rate,
    the canonical-dedup ratio, WGL pool utilization, check wall time
    and budget exhaustion per chunk — out-of-band, never a report
    byte."""

    incremental = True

    def __init__(
        self, spec, max_states, workers, max_recorded, telemetry,
        device_decode,
    ):
        from collections import deque

        self._spec = spec
        self._max_states = max_states
        self._workers = workers
        self._max_recorded = max_recorded
        self._telemetry = telemetry
        self._device_decode = device_decode
        # WGL slice granularity: big enough to keep a pool's workers
        # busy per slice, small enough that a poll budget is respected
        # within ~one slice. Scheduling-only — never affects a report
        self._step = max(8, 4 * max(1, workers))
        self._q: deque = deque()

    def __call__(self, final, *, lo, n, seeds, suspect, summary):
        self.submit(
            final, lo=lo, n=n, seeds=seeds, suspect=suspect,
            summary=summary,
        )
        return self.drain()[-1][1]

    def submit(self, final, *, lo, n, seeds, suspect, summary) -> None:
        """Decode + dedup one chunk now; queue its WGL work."""
        import hashlib
        import time as _time

        from .history import (
            canon_sweep,
            canonical_bytes_from_rows,
            decode_lanes,
            history_canonical_bytes,
            history_from_canon,
        )

        del seeds, summary
        t0 = _time.perf_counter()
        n = int(n)
        if suspect is None:
            lanes = np.arange(n)
        else:
            lanes = np.nonzero(np.asarray(suspect)[:n])[0]
        rep: dict = {}  # canonical hash -> index into reps
        reps: list = []
        keys: list = []
        lane_seeds: list = []
        if self._device_decode and int(final.hist_rec.shape[1]) > 0:
            canon, n_ops, breach = canon_sweep(final)
            total = int(final.seed.shape[0])
            if lanes.size and lanes.size * 4 <= total:
                # sparse selection: gather device-side (decode_lanes'
                # transfer-sizing rule), positions then index the gather
                planes = (
                    canon[lanes], n_ops[lanes], breach[lanes],
                    final.hist_len[lanes], final.hist_overflow[lanes],
                    final.seed[lanes],
                )
                pos = np.arange(lanes.size)
            else:
                planes = (
                    canon, n_ops, breach, final.hist_len,
                    final.hist_overflow, final.seed,
                )
                pos = lanes
            rows_c, nops_h, br_h, len_h, ov_h, seed_h = (
                np.asarray(p) for p in planes
            )
            for j, p in enumerate(pos):
                if br_h[p]:
                    # record-hook contract breach: the host decoder
                    # raises the real diagnostic for this lane
                    decode_lanes(final, [int(lanes[j])])
                    raise RuntimeError(
                        f"device canonical decode flagged lane "
                        f"{int(lanes[j])} but the host decoder "
                        "accepted it"
                    )
                keys.append(
                    hashlib.sha256(
                        canonical_bytes_from_rows(
                            rows_c[p], nops_h[p], len_h[p], ov_h[p]
                        )
                    ).digest()
                )
                lane_seeds.append(int(seed_h[p]))
                if keys[-1] not in rep:
                    rep[keys[-1]] = len(reps)
                    reps.append(
                        history_from_canon(
                            rows_c[p], nops_h[p], ov_h[p], len_h[p],
                            seed=lane_seeds[-1],
                        )
                    )
        else:
            hists = decode_lanes(final, lanes)
            for h in hists:
                k = hashlib.sha256(history_canonical_bytes(h)).digest()
                keys.append(k)
                lane_seeds.append(int(h.seed))
                if k not in rep:
                    rep[k] = len(reps)
                    reps.append(h)
        if self._telemetry is not None:
            self._telemetry.count("oracle_screened_total", n)
            self._telemetry.count("oracle_suspects_total", int(lanes.size))
            self._telemetry.count("oracle_unique_total", len(reps))
            self._telemetry.gauge(
                "oracle_suspect_rate", lanes.size / max(n, 1),
                help="suspect lanes / screened lanes, last chunk",
            )
            if lanes.size:
                self._telemetry.gauge(
                    "oracle_dedup_ratio", len(reps) / lanes.size,
                    help="unique canonical histories / suspects "
                    "(lower = more dedup wins)",
                )
        self._q.append(
            {
                "lo": lo, "n": n, "suspects": int(lanes.size),
                "keys": keys, "seeds": lane_seeds, "rep": rep,
                "reps": reps, "results": [], "next": 0,
                "host_s": _time.perf_counter() - t0,
            }
        )

    def poll(self, seconds: Optional[float] = None) -> list:
        """Run queued WGL work for roughly ``seconds`` (None = until
        empty); returns ``(lo, report_dict)`` for every chunk that
        finished, in submission order. Always makes progress when work
        is pending (at least one slice per call), so a starved budget
        degrades to trickling, never to deadlock. The budget shapes
        SCHEDULING only: verdicts are computed in submission order
        regardless, so the stream of returned reports — and every byte
        in them — is invariant to the poll cadence."""
        import time as _time

        from .check import check_histories

        out = []
        deadline = (
            None if seconds is None else _time.perf_counter() + seconds
        )
        sliced = False
        while self._q:
            e = self._q[0]
            reps = e["reps"]
            while e["next"] < len(reps):
                if (
                    deadline is not None
                    and sliced
                    and _time.perf_counter() >= deadline
                ):
                    return out
                j = min(len(reps), e["next"] + self._step)
                tc = _time.perf_counter()
                e["results"].extend(
                    check_histories(
                        reps[e["next"]: j], self._spec,
                        max_states=self._max_states,
                        workers=self._workers,
                    )
                )
                e["host_s"] += _time.perf_counter() - tc
                e["next"] = j
                sliced = True
            out.append((e["lo"], self._finalize(e)))
            self._q.popleft()
        return out

    def drain(self) -> list:
        """Finish ALL queued work; ``(lo, report_dict)`` in submission
        order."""
        return self.poll(None)

    def _finalize(self, e: dict) -> dict:
        rep_results = e["results"]
        results = [rep_results[e["rep"][k]] for k in e["keys"]]
        bad = [s for s, r in zip(e["seeds"], results) if not r.ok]
        undecided = sum(1 for r in results if not r.decided)
        # distinct WGL searches that hit max_states (vs hist_undecided,
        # which counts the lanes those verdicts fanned out to)
        exhausted = sum(1 for r in rep_results if not r.decided)
        reps, workers = e["reps"], self._workers
        if self._telemetry is not None:
            if bad:
                self._telemetry.count("oracle_violations_total", len(bad))
            if exhausted:
                self._telemetry.count(
                    "oracle_budget_exceeded_total", exhausted,
                    help="WGL searches that exhausted max_states "
                    "(verdict undecided, fails clean)",
                )
            if workers > 0 and reps:
                # load-balance proxy: busy slots / pool slots over the
                # batch's -(-len // workers) waves
                waves = -(-len(reps) // workers)
                self._telemetry.gauge(
                    "oracle_pool_utilization",
                    len(reps) / (workers * waves),
                    help="checked histories / (workers x waves), "
                    "last chunk",
                )
            self._telemetry.observe(
                "oracle_check_seconds", e["host_s"],
                help="decode+dedup+WGL check per chunk",
            )
        return {
            "hist_screened": e["n"],
            "hist_suspects": e["suspects"],
            "hist_unique": len(reps),
            "hist_violations": len(bad),
            "hist_undecided": int(undecided),
            "budget_exceeded": int(exhausted),
            "hist_violating_seeds": bad[: self._max_recorded],
        }


def history_host_work(
    spec,
    max_states: int = 200_000,
    workers: int = 0,
    max_recorded: int = 32,
    telemetry=None,
    device_decode: bool = False,
) -> Callable:
    """Build the ``host_work`` for a screened checked sweep — a
    ``_HostWork``: callable with the legacy per-chunk signature (every
    driver's sync path), and exposing ``submit``/``poll``/``drain`` for
    drivers that interleave checking with device rounds (see the class
    docstring for both protocols and the determinism contract)."""
    return _HostWork(
        spec, max_states, workers, max_recorded, telemetry, device_decode
    )


def checked_sweep(
    workload,
    cfg,
    seeds,
    spec,
    summarize,
    chunk_size: Optional[int] = None,
    workers: int = 0,
    max_states: int = 200_000,
    screen: bool = True,
    ckpt_dir: Optional[str] = None,
    stop_after: Optional[int] = None,
    resume_from=None,
    mesh=None,
    chunk_per_device: Optional[int] = None,
    max_recorded: int = 32,
    on_chunk=None,
    driver: str = "chunked",
    telemetry=None,
    device_decode: bool = False,
) -> dict:
    """End-to-end checked sweep: pipelined chunked sweep + on-device
    screening + process-pool WGL checking, merged into one summary dict.

    This is the optimized quantity BENCH reports as ``checked_sweep``:
    seeds/s through simulation AND history validation. ``screen=False``
    degrades to decode-and-check-every-seed (the naive baseline).
    Results are bit-identical across ``screen`` settings whenever the
    screen is conservative, and across ``workers`` always.
    ``chunk_size=None`` (the default) auto-picks the occupancy knee
    from the workload's measured loop-carry footprint, matching
    ``engine.core.run_sweep_chunked``.

    ``mesh`` routes the whole pipeline through the sharded driver
    (``parallel.run_sweep_sharded_pipelined``): sweep, screen and
    summary run sharded over the mesh, per-device chunks sized
    ``chunk_per_device`` (``core.pick_chunk_size`` when omitted; an
    explicit ``chunk_size`` stays GLOBAL and overrides). The summary
    dict is byte-identical across mesh sizes: every count is an exact
    integer reduction merged in seed order, and the
    ``hist_violating_seeds`` sample composes chunking-invariantly —
    each chunk records at most ``max_recorded`` violators (lane order)
    and the merged list is capped to the same bound, so a prefix kept
    per chunk can never change the global first-``max_recorded`` set.

    ``driver="stream"`` routes the sweep through the persistent lane
    pool (``engine.stream.stream_sweep``, docs/streaming.md): the screen
    runs once per retirement cohort on the whole pool, and the flushed
    reports are byte-identical to this function's chunked output —
    same virtual chunk boundaries, same merge order. The stream driver
    keeps its own checkpoint semantics (``stream_sweep(ckpt_path=...)``),
    so the chunk-granule ``ckpt_dir``/``stop_after``/``resume_from``
    arguments are rejected here.

    ``device_decode=True`` sources canonical history rows from the
    on-device decode kernel instead of per-row host Python
    (``history_host_work``) — bit-identical reports either way, gated
    by the determinism suite's decode leg."""
    from ..engine.checkpoint import run_sweep_pipelined

    if driver not in ("chunked", "stream"):
        raise ValueError(f"unknown driver {driver!r}")
    screen_fn = None
    if screen:
        if screen_for(spec) is None:
            raise ValueError(
                f"spec {spec.name!r} has no device screen; pass "
                "screen=False to check every lane"
            )
        screen_fn = lambda final: screen_sweep(final, spec, mesh=mesh)  # noqa: E731
    host_work = history_host_work(
        spec, max_states=max_states, workers=workers,
        max_recorded=max_recorded, telemetry=telemetry,
        device_decode=device_decode,
    )
    if driver == "stream":
        from ..engine.core import pick_chunk_size
        from ..engine.stream import stream_sweep

        if ckpt_dir is not None or stop_after is not None or resume_from:
            raise ValueError(
                "driver='stream' manages its own snapshots — use "
                "engine.stream.stream_sweep(ckpt_path=...) directly for "
                "interrupt/resume"
            )
        if chunk_size is None:
            if mesh is not None:
                n_dev = int(mesh.devices.size)
                cpd = (
                    pick_chunk_size(workload, cfg)
                    if chunk_per_device is None
                    else chunk_per_device
                )
                chunk_size = cpd * n_dev
            else:
                chunk_size = pick_chunk_size(workload, cfg)
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            chunk_size = -(-chunk_size // n_dev) * n_dev
        totals = stream_sweep(
            workload, cfg, seeds, summarize,
            chunk_size=chunk_size, host_work=host_work,
            screen=screen_fn, mesh=mesh, on_chunk=on_chunk,
            telemetry=telemetry,
        )
    elif mesh is not None:
        from ..parallel.mesh import run_sweep_sharded_pipelined

        totals = run_sweep_sharded_pipelined(
            workload, cfg, seeds, summarize,
            mesh=mesh, host_work=host_work, screen=screen_fn,
            chunk_per_device=chunk_per_device, chunk_size=chunk_size,
            ckpt_dir=ckpt_dir, stop_after=stop_after,
            resume_from=resume_from, on_chunk=on_chunk,
            telemetry=telemetry,
        )
    else:
        if chunk_size is None:
            from ..engine.core import pick_chunk_size

            chunk_size = pick_chunk_size(workload, cfg)
        totals = run_sweep_pipelined(
            workload,
            cfg,
            seeds,
            summarize,
            host_work=host_work,
            screen=screen_fn,
            chunk_size=chunk_size,
            ckpt_dir=ckpt_dir,
            stop_after=stop_after,
            resume_from=resume_from,
            on_chunk=on_chunk,
            telemetry=telemetry,
        )
    if "hist_violating_seeds" in totals:
        totals["hist_violating_seeds"] = totals["hist_violating_seeds"][
            :max_recorded
        ]
    return totals
