"""Vectorized on-device history screening — the oracle's first pass.

The WGL checker (oracle/check.py) is per-seed host Python: decode ~a
hundred rows, search linearizations. At 100k+ seeds the checker, not the
engine, is the wall-clock bound of a checked sweep. This module moves a
conservative first pass onto the device: per-key quick-checks computed
as masked reductions over the SoA history plane (``EngineState.hist_*``)
of a finished chunk, yielding one bool per seed — *suspect* or
*provably boring*. Full decoding + WGL search then runs only on the
suspect lanes.

The contract is CONSERVATISM: the suspect set must be a superset of the
seeds the full checker would reject, so skipping the clean lanes never
hides a violation. Each screen is therefore built from conditions of
the form "flag unless this observation is provably explainable":

- ``kv`` (etcd register spec): a completed GET is flagged when it read
  ABSENT after some PUT on its key definitely committed, when no PUT of
  the observed value was even invoked before the read returned, or when
  a *fresher* observation exists — some op completed before the read
  began whose invoke followed the commit of the read's value (a
  definitely-newer committed write, or an earlier read that already
  observed a newer value — the latter catches value flip-flops that no
  write pair alone can witness). Duplicate written values and DEL rows
  defeat the value-identity reasoning, so their mere presence flags the
  seed (the bundled etcd model records neither).
- ``log`` (kafka ordered-log spec): a completed FETCH at offset ``o``
  serving ``n`` records is flagged when fewer than ``o + n`` PRODUCE
  invocations preceded its completion, or when it breaks per-consumer
  offset contiguity (the exact structural pre-check of
  ``specs.LogSpec``, which appends OK rows in completion order).
- ``election`` (raft): two ELECT rows naming different winners for one
  term — exactly ``specs.ElectionSpec.structural``, so this screen is
  precise (no false positives, no misses).

Unknown op kinds, DEL rows, and OK rows with no recorded invoke flag
the seed wholesale: a row the screen cannot reason about must not be
silently trusted. Overflowed histories screen their valid prefix — the
same prefix the checker checks (the buffer never wraps).

What the screen can NOT do is *prove* a violation: a flagged seed is a
candidate, and only the WGL search's verdict counts. The false-positive
rate on clean sweeps is bounded by construction (most conditions are
exact necessary-condition checks; tests/test_screen.py pins it <5%),
which is what makes screening a throughput win rather than a shortcut.

Everything here is jittable JAX over int32/int64 planes — [H, H]
pairwise masks reduced per seed, vmapped over lanes in blocks — so the
screen of a 16k-seed chunk is one device program, enqueued right behind
the chunk's sweep (engine/checkpoint.run_sweep_pipelined overlaps the
host-side checking of chunk N with the device sweep of chunk N+1).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .history import (
    OP_ELECT,
    OP_FETCH,
    OP_GET,
    OP_PRODUCE,
    OP_PUT,
    PH_INVOKE,
    PH_OK,
)
from .specs import ABSENT

# int64 sentinels: "no such time" below/above any virtual timestamp
_T_NEG = jnp.int64(-(1 << 62))
_T_INF = jnp.int64(1 << 62)
_I32_MIN = jnp.int32(-(1 << 31))


def _cols(rec, t, n):
    """Split one seed's raw rows into masked columns."""
    H = rec.shape[0]
    idx = jnp.arange(H, dtype=jnp.int32)
    valid = idx < jnp.asarray(n, jnp.int32)
    client, code, key, val, opid = (rec[:, i] for i in range(5))
    op, ph = code // 2, code % 2
    return idx, valid, client, op, ph, key, val, opid, jnp.asarray(t)


def _invoke_join(idx, valid, client, op, ph, opid, t):
    """For every OK row, the time of its invoke row (and the pair mask).

    The decoder pairs an OK row with the LATEST earlier matching invoke
    (kafka produce retries re-invoke one opid), so the join takes the
    max time over candidates. Rows with no match get ``_T_NEG`` —
    callers flag those (an OK without an invoke is a contract breach the
    decoder would raise on)."""
    pair = (
        (valid & (ph == PH_OK))[:, None]
        & (valid & (ph == PH_INVOKE))[None, :]
        & (client[:, None] == client[None, :])
        & (op[:, None] == op[None, :])
        & (opid[:, None] == opid[None, :])
        & (idx[None, :] < idx[:, None])
    )
    inv_t = jnp.max(jnp.where(pair, t[None, :], _T_NEG), axis=1)
    return inv_t, pair


def kv_suspect(rec, t, n) -> jnp.ndarray:
    """One seed's suspect bit under the KV register spec (etcd)."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    inv_t, _ = _invoke_join(idx, valid, client, op, ph, opid, t)

    put_inv = valid & (op == OP_PUT) & (ph == PH_INVOKE)
    put_ok = valid & (op == OP_PUT) & (ph == PH_OK)
    get_ok = valid & (op == OP_GET) & (ph == PH_OK)
    obs_ok = put_ok | get_ok

    # rows the value-identity reasoning cannot cover flag the seed
    unscreenable = jnp.any(valid & ~((op == OP_PUT) | (op == OP_GET)))
    orphan = jnp.any((valid & (ph == PH_OK)) & (inv_t == _T_NEG))

    same_key = key[:, None] == key[None, :]

    # two distinct PUT invokes of one (key, value): value identity no
    # longer names a unique write — flag (values are random 31-bit
    # draws in the bundled model, so this is vanishingly rare)
    dup = jnp.any(
        put_inv[:, None]
        & put_inv[None, :]
        & same_key
        & (val[:, None] == val[None, :])
        & (idx[:, None] < idx[None, :])
    )

    # commit time of the unique PUT that wrote (key_i, out_i); an
    # unacked (open) write commits "never" — nothing can be proven to
    # follow it, so the freshness conditions below stay quiet
    wrote = put_ok[None, :] & same_key & (val[:, None] == val[None, :])
    cmp_v = jnp.where(
        jnp.any(wrote, axis=1),
        jnp.max(jnp.where(wrote, t[None, :], _T_NEG), axis=1),
        _T_INF,
    )

    ti = inv_t  # a GET-OK row's invoke time
    tc = t  # ... and its completion time (the row's own stamp)

    # ABSENT read after some PUT on the key definitely committed (the
    # recorded keys are never deleted — DEL rows flag above)
    bad_absent = (val == ABSENT) & jnp.any(
        put_ok[None, :] & same_key & (t[None, :] < ti[:, None]), axis=1
    )
    # observed value that no PUT even invoked before the read returned
    no_writer = (val != ABSENT) & ~jnp.any(
        put_inv[None, :]
        & same_key
        & (val[:, None] == val[None, :])
        & (t[None, :] <= tc[:, None]),
        axis=1,
    )
    # a fresher observation: some completed op on the key observed or
    # wrote a DIFFERENT value, began after this read's value committed,
    # and finished before this read began — in every linearization that
    # op sits between the read's write and the read, so the read is
    # provably stale (unique values; duplicates flag above)
    fresher = (val != ABSENT) & jnp.any(
        obs_ok[None, :]
        & same_key
        & (val[:, None] != val[None, :])
        & (t[None, :] < ti[:, None])
        & (inv_t[None, :] > cmp_v[:, None]),
        axis=1,
    )
    bad = get_ok & (bad_absent | no_writer | fresher)
    return jnp.any(bad) | dup | unscreenable | orphan


def log_suspect(rec, t, n) -> jnp.ndarray:
    """One seed's suspect bit under the ordered-log spec (kafka)."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    inv_t, pair = _invoke_join(idx, valid, client, op, ph, opid, t)

    prod_inv = valid & (op == OP_PRODUCE) & (ph == PH_INVOKE)
    fetch_ok = valid & (op == OP_FETCH) & (ph == PH_OK)

    unscreenable = jnp.any(valid & ~((op == OP_PRODUCE) | (op == OP_FETCH)))
    orphan = jnp.any(fetch_ok & (inv_t == _T_NEG))

    same_key = key[:, None] == key[None, :]

    # each fetch's offset rides on its (latest matching) invoke row
    jlast = jnp.max(jnp.where(pair, idx[None, :], jnp.int32(-1)), axis=1)
    onehot = pair & (idx[None, :] == jlast[:, None])
    off = jnp.max(jnp.where(onehot, val[None, :], _I32_MIN), axis=1)
    served = val  # a FETCH-OK row's val column is the records served

    # overread: serving past every append that could precede it — each
    # PRODUCE op (retries included: the spec counts them as separate
    # appends) invoked before this fetch completed may linearize first
    navail = jnp.sum(
        (prod_inv[None, :] & same_key & (t[None, :] <= t[:, None])).astype(
            jnp.int32
        ),
        axis=1,
    )
    overread = fetch_ok & (off + served > navail)

    # per-consumer committed-offset contiguity, in completion order (OK
    # rows append at completion, so row order IS completion order) —
    # exactly specs.LogSpec.structural
    prevm = (
        fetch_ok[:, None]
        & fetch_ok[None, :]
        & same_key
        & (client[:, None] == client[None, :])
        & (idx[None, :] < idx[:, None])
    )
    jprev = jnp.max(jnp.where(prevm, idx[None, :], jnp.int32(-1)), axis=1)
    sel_prev = prevm & (idx[None, :] == jprev[:, None])
    prev_off = jnp.max(jnp.where(sel_prev, off[None, :], _I32_MIN), axis=1)
    prev_served = jnp.max(jnp.where(sel_prev, val[None, :], _I32_MIN), axis=1)
    expect = jnp.where(jprev >= 0, prev_off + prev_served, jnp.int32(0))
    gap = fetch_ok & (off != expect)

    return jnp.any(overread | gap) | unscreenable | orphan


def election_suspect(rec, t, n) -> jnp.ndarray:
    """One seed's suspect bit under the election spec (raft) — precise:
    two ELECT rows naming different winners for one term, exactly
    ``specs.ElectionSpec.structural``."""
    idx, valid, client, op, ph, key, val, opid, t = _cols(rec, t, n)
    elect = valid & (op == OP_ELECT) & (ph == PH_INVOKE)
    unscreenable = jnp.any(valid & ~(op == OP_ELECT))
    split = jnp.any(
        elect[:, None]
        & elect[None, :]
        & (key[:, None] == key[None, :])
        & (val[:, None] != val[None, :])
    )
    return split | unscreenable


_SCREENS = {
    "kv": kv_suspect,
    "log": log_suspect,
    "election": election_suspect,
}


def screen_for(spec) -> Optional[Callable]:
    """The per-seed screen function for a sequential spec, by its
    ``name`` — or None when no screen exists (callers must then treat
    every seed as suspect)."""
    return _SCREENS.get(getattr(spec, "name", None))


@lru_cache(maxsize=None)
def _batched(name: str):
    return jax.jit(jax.vmap(_SCREENS[name]))


@lru_cache(maxsize=None)
def _batched_sharded(name: str, mesh, block: int):
    """The per-seed screen shard_map'd over the mesh's seed axis: each
    device screens its LOCAL lanes (in ``block``-lane sub-batches, same
    [block, H, H] working-set bound as the unsharded path), so a chunk's
    screen program runs distributed right behind its sharded sweep with
    no cross-device traffic at all — the suspect mask stays sharded
    like the history planes it reduces. Cached per (spec, mesh, block):
    a fresh shard_map wrapper per chunk would retrace every call."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import SEED_AXIS, shard_map_compat

    f = jax.vmap(_SCREENS[name])

    def local(rec, t, n):
        s = rec.shape[0]
        if s <= block:
            return f(rec, t, n)
        return jnp.concatenate(
            [
                f(rec[lo : lo + block], t[lo : lo + block], n[lo : lo + block])
                for lo in range(0, s, block)
            ]
        )

    return jax.jit(
        shard_map_compat(
            local, mesh, in_specs=P(SEED_AXIS), out_specs=P(SEED_AXIS)
        )
    )


def screen_history(rec, t, n, spec) -> bool:
    """Screen ONE seed's raw history rows (tests and replay tooling)."""
    fn = screen_for(spec)
    if fn is None:
        raise ValueError(f"no device screen for spec {spec.name!r}")
    return bool(
        fn(jnp.asarray(rec, jnp.int32), jnp.asarray(t, jnp.int64), int(n))
    )


def screen_sweep(final, spec, block: int = 1024, mesh=None) -> jnp.ndarray:
    """Suspect mask (bool[S], device array) for a finished batched sweep.

    ``block`` bounds the [block, H, H] pairwise-mask working set per
    launched program (H = hist_slots; 1024 lanes x 256 rows is ~67 MB of
    bool mask per term). The mask is NOT materialized to host — callers
    enqueue this right after the chunk's sweep and ``np.asarray`` it
    later, from the overlapped host phase.

    ``mesh`` runs the screen shard_map'd over the mesh's seed axis
    (``final`` sharded by ``parallel.run_sweep_sharded``; the batch must
    divide the mesh) — same bits per seed, distributed like the sweep
    that produced the planes."""
    fn = screen_for(spec)
    if fn is None:
        raise ValueError(
            f"no device screen for spec {getattr(spec, 'name', spec)!r}; "
            "pass screen=False (check every lane) instead"
        )
    S = int(final.seed.shape[0])
    if final.hist_rec.shape[1] == 0:
        # no recording plane: nothing to screen, nothing to check —
        # consistent with the checker accepting every empty history
        return jnp.zeros((S,), bool)
    if mesh is not None:
        return _batched_sharded(spec.name, mesh, block)(
            final.hist_rec, final.hist_t, final.hist_len
        )
    f = _batched(spec.name)
    if S <= block:
        return f(final.hist_rec, final.hist_t, final.hist_len)
    outs = [
        f(
            final.hist_rec[lo : lo + block],
            final.hist_t[lo : lo + block],
            final.hist_len[lo : lo + block],
        )
        for lo in range(0, S, block)
    ]
    return jnp.concatenate(outs)


def history_host_work(
    spec,
    max_states: int = 200_000,
    workers: int = 0,
    max_recorded: int = 32,
    telemetry=None,
) -> Callable:
    """Build the ``host_work`` callback for a screened checked sweep
    (engine/checkpoint.run_sweep_pipelined): decode the suspect lanes,
    fan the WGL checker over a process pool, and fold the verdicts into
    the chunk summary.

    Suspect lanes are deduplicated before checking: identical histories
    across seeds are common under coarse faults, and the WGL verdict
    depends only on the seed-free, time-rank canonical encoding
    (``history.history_canonical_bytes`` — an order-isomorphism on the
    timestamps the checker reads only through comparisons). One
    representative per equivalence class (first occurrence, lane order)
    is checked; its verdict fans back to every member, and the report
    carries the class count as ``hist_unique``.

    Determinism contract: the returned dict is a pure function of the
    chunk's history planes — worker count changes wall-clock only, never
    a byte of the report (results are ordered by lane, dedup keys on
    content, and each verdict is a pure function of one history).
    ``telemetry`` (``obs.Telemetry`` or None) records the suspect rate,
    the canonical-dedup ratio, WGL pool utilization and check wall time
    per chunk — out-of-band, never a byte of the returned dict."""
    import hashlib
    import time as _time

    from .check import check_histories
    from .history import decode_lanes, history_canonical_bytes

    def host_work(final, *, lo, n, seeds, suspect, summary):
        del lo, seeds, summary
        if telemetry is not None:
            t_check = _time.perf_counter()
        if suspect is None:
            lanes = np.arange(n)
        else:
            lanes = np.nonzero(np.asarray(suspect)[:n])[0]
        hists = decode_lanes(final, lanes)
        keys = [
            hashlib.sha256(history_canonical_bytes(h)).digest()
            for h in hists
        ]
        rep: dict = {}  # canonical hash -> index into reps
        reps = []
        for h, k in zip(hists, keys):
            if k not in rep:
                rep[k] = len(reps)
                reps.append(h)
        rep_results = check_histories(
            reps, spec, max_states=max_states, workers=workers
        )
        results = [rep_results[rep[k]] for k in keys]
        bad = [int(h.seed) for h, r in zip(hists, results) if not r.ok]
        undecided = sum(1 for r in results if not r.decided)
        if telemetry is not None:
            telemetry.count("oracle_screened_total", int(n))
            telemetry.count("oracle_suspects_total", int(lanes.size))
            telemetry.count("oracle_unique_total", len(reps))
            if bad:
                telemetry.count("oracle_violations_total", len(bad))
            telemetry.gauge(
                "oracle_suspect_rate", lanes.size / max(n, 1),
                help="suspect lanes / screened lanes, last chunk",
            )
            if lanes.size:
                telemetry.gauge(
                    "oracle_dedup_ratio", len(reps) / lanes.size,
                    help="unique canonical histories / suspects "
                    "(lower = more dedup wins)",
                )
            if workers > 0 and reps:
                # load-balance proxy: busy slots / pool slots over the
                # batch's -(-len // workers) waves
                waves = -(-len(reps) // workers)
                telemetry.gauge(
                    "oracle_pool_utilization",
                    len(reps) / (workers * waves),
                    help="checked histories / (workers x waves), "
                    "last chunk",
                )
            telemetry.observe(
                "oracle_check_seconds", _time.perf_counter() - t_check,
                help="decode+dedup+WGL check per chunk",
            )
        return {
            "hist_screened": int(n),
            "hist_suspects": int(lanes.size),
            "hist_unique": len(reps),
            "hist_violations": len(bad),
            "hist_undecided": int(undecided),
            "hist_violating_seeds": bad[:max_recorded],
        }

    return host_work


def checked_sweep(
    workload,
    cfg,
    seeds,
    spec,
    summarize,
    chunk_size: Optional[int] = None,
    workers: int = 0,
    max_states: int = 200_000,
    screen: bool = True,
    ckpt_dir: Optional[str] = None,
    stop_after: Optional[int] = None,
    resume_from=None,
    mesh=None,
    chunk_per_device: Optional[int] = None,
    max_recorded: int = 32,
    on_chunk=None,
    driver: str = "chunked",
    telemetry=None,
) -> dict:
    """End-to-end checked sweep: pipelined chunked sweep + on-device
    screening + process-pool WGL checking, merged into one summary dict.

    This is the optimized quantity BENCH reports as ``checked_sweep``:
    seeds/s through simulation AND history validation. ``screen=False``
    degrades to decode-and-check-every-seed (the naive baseline).
    Results are bit-identical across ``screen`` settings whenever the
    screen is conservative, and across ``workers`` always.
    ``chunk_size=None`` (the default) auto-picks the occupancy knee
    from the workload's measured loop-carry footprint, matching
    ``engine.core.run_sweep_chunked``.

    ``mesh`` routes the whole pipeline through the sharded driver
    (``parallel.run_sweep_sharded_pipelined``): sweep, screen and
    summary run sharded over the mesh, per-device chunks sized
    ``chunk_per_device`` (``core.pick_chunk_size`` when omitted; an
    explicit ``chunk_size`` stays GLOBAL and overrides). The summary
    dict is byte-identical across mesh sizes: every count is an exact
    integer reduction merged in seed order, and the
    ``hist_violating_seeds`` sample composes chunking-invariantly —
    each chunk records at most ``max_recorded`` violators (lane order)
    and the merged list is capped to the same bound, so a prefix kept
    per chunk can never change the global first-``max_recorded`` set.

    ``driver="stream"`` routes the sweep through the persistent lane
    pool (``engine.stream.stream_sweep``, docs/streaming.md): the screen
    runs once per retirement cohort on the whole pool, and the flushed
    reports are byte-identical to this function's chunked output —
    same virtual chunk boundaries, same merge order. The stream driver
    keeps its own checkpoint semantics (``stream_sweep(ckpt_path=...)``),
    so the chunk-granule ``ckpt_dir``/``stop_after``/``resume_from``
    arguments are rejected here."""
    from ..engine.checkpoint import run_sweep_pipelined

    if driver not in ("chunked", "stream"):
        raise ValueError(f"unknown driver {driver!r}")
    screen_fn = None
    if screen:
        if screen_for(spec) is None:
            raise ValueError(
                f"spec {spec.name!r} has no device screen; pass "
                "screen=False to check every lane"
            )
        screen_fn = lambda final: screen_sweep(final, spec, mesh=mesh)  # noqa: E731
    host_work = history_host_work(
        spec, max_states=max_states, workers=workers,
        max_recorded=max_recorded, telemetry=telemetry,
    )
    if driver == "stream":
        from ..engine.core import pick_chunk_size
        from ..engine.stream import stream_sweep

        if ckpt_dir is not None or stop_after is not None or resume_from:
            raise ValueError(
                "driver='stream' manages its own snapshots — use "
                "engine.stream.stream_sweep(ckpt_path=...) directly for "
                "interrupt/resume"
            )
        if chunk_size is None:
            if mesh is not None:
                n_dev = int(mesh.devices.size)
                cpd = (
                    pick_chunk_size(workload, cfg)
                    if chunk_per_device is None
                    else chunk_per_device
                )
                chunk_size = cpd * n_dev
            else:
                chunk_size = pick_chunk_size(workload, cfg)
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            chunk_size = -(-chunk_size // n_dev) * n_dev
        totals = stream_sweep(
            workload, cfg, seeds, summarize,
            chunk_size=chunk_size, host_work=host_work,
            screen=screen_fn, mesh=mesh, on_chunk=on_chunk,
            telemetry=telemetry,
        )
    elif mesh is not None:
        from ..parallel.mesh import run_sweep_sharded_pipelined

        totals = run_sweep_sharded_pipelined(
            workload, cfg, seeds, summarize,
            mesh=mesh, host_work=host_work, screen=screen_fn,
            chunk_per_device=chunk_per_device, chunk_size=chunk_size,
            ckpt_dir=ckpt_dir, stop_after=stop_after,
            resume_from=resume_from, on_chunk=on_chunk,
            telemetry=telemetry,
        )
    else:
        if chunk_size is None:
            from ..engine.core import pick_chunk_size

            chunk_size = pick_chunk_size(workload, cfg)
        totals = run_sweep_pipelined(
            workload,
            cfg,
            seeds,
            summarize,
            host_work=host_work,
            screen=screen_fn,
            chunk_size=chunk_size,
            ckpt_dir=ckpt_dir,
            stop_after=stop_after,
            resume_from=resume_from,
            on_chunk=on_chunk,
            telemetry=telemetry,
        )
    if "hist_violating_seeds" in totals:
        totals["hist_violating_seeds"] = totals["hist_violating_seeds"][
            :max_recorded
        ]
    return totals
