"""Operation histories: decode device ring buffers, record host-tier runs.

The device engine appends one fixed-width record per dispatched event
that the workload's ``record`` hook elects (engine/core.py): five int32
columns ``(client, code, key, val, opid)`` plus an engine-stamped int64
virtual time. ``code`` packs an op kind and a phase —
``code = op * 2 + phase`` — so one client-visible operation is TWO rows
(its invoke at send time, its completion at response-delivery time),
matched by ``(client, opid)``. One row per event is exactly what the
engine's one-masked-write-per-step discipline can afford, and the
invoke/ok pairing is the Jepsen history shape the checker wants anyway.

``decode_seed`` turns a finished ``EngineState`` lane back into ``Op``
records; ``history_bytes`` is the canonical byte encoding the
determinism gate diffs (same ``(spec, seed)`` on the sweep path and on
the bit-exact CPU ``run_traced`` replay path must produce identical
bytes). ``HostRecorder`` is the thin client-shim for the host tier: wrap
each client call in ``invoke``/``complete`` and the host run yields the
same ``History`` structure, checkable by the same specs.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Tuple

import numpy as np

# op kinds (the row's code column is ``op * 2 + phase``)
OP_PUT = 0  # key := inp; out echoes inp
OP_GET = 1  # read key; out = value or -1 (absent)
OP_DEL = 2  # delete key (internal ops record invoke == complete)
OP_PRODUCE = 3  # append inp (seq) to log/partition key; out = ack frontier
OP_FETCH = 4  # read from offset inp of partition key; out = records served
OP_ELECT = 5  # node inp won leadership of term key (invoke-only: no
#               client observes a completion — ElectionSpec is structural)

OP_NAMES = ("put", "get", "del", "produce", "fetch", "elect")

PH_INVOKE = 0
PH_OK = 1


def code_of(op: int, phase: int) -> int:
    """The row code the record hooks write: ``op * 2 + phase``."""
    return op * 2 + phase


class Op(NamedTuple):
    """One client-observed operation, paired from its invoke/ok rows."""

    client: int
    op: int  # OP_*
    key: int  # key (KV) or partition (log)
    inp: int  # invoke argument: PUT value / produce seq / fetch offset
    out: int  # completion result (meaningless while ``complete_ns < 0``)
    invoke_ns: int
    complete_ns: int  # -1 = never completed (open op — may have happened)
    opid: int

    @property
    def complete(self) -> bool:
        return self.complete_ns >= 0

    def describe(self) -> str:
        done = f"-> {self.out} @{self.complete_ns}" if self.complete else "-> ?"
        return (
            f"c{self.client} {OP_NAMES[self.op]}(k={self.key}, {self.inp}) "
            f"@{self.invoke_ns} {done}"
        )


class History(NamedTuple):
    """A decoded per-seed operation history."""

    seed: int
    ops: Tuple[Op, ...]  # invoke order (== record-append order)
    overflow: bool  # buffer filled up: ops is a valid strict prefix
    rows: int  # raw rows consumed


def _pair_rows(rec: np.ndarray, t: np.ndarray, n: int) -> Tuple[Op, ...]:
    """Pair invoke/ok rows by (client, opid) into ``Op`` records.

    Rows are appended in dispatch order, so an op's invoke row always
    precedes its ok row; an ok row with no recorded invoke means the
    decoder and the workload's record hook disagree — that is a bug, not
    a data condition, so it raises."""
    ops: List[List] = []
    open_ops = {}  # (client, opid) -> index into ops
    for i in range(n):
        client, code, key, val, opid = (int(v) for v in rec[i])
        op, phase = code // 2, code % 2
        when = int(t[i])
        if phase == PH_INVOKE:
            open_ops[(client, opid)] = len(ops)
            ops.append([client, op, key, val, 0, when, -1, opid])
        else:
            j = open_ops.pop((client, opid), None)
            if j is None:
                raise ValueError(
                    f"history row {i} completes op (client={client}, "
                    f"opid={opid}) with no recorded invoke — record-hook "
                    "contract breach"
                )
            if ops[j][1] != op or ops[j][2] != key:
                raise ValueError(
                    f"history row {i} completes (client={client}, "
                    f"opid={opid}) with mismatched op/key "
                    f"({op}/{key} vs {ops[j][1]}/{ops[j][2]})"
                )
            ops[j][4] = val
            ops[j][6] = when
    return tuple(Op(*o) for o in ops)


def decode_rows(
    rec, t, length, overflow, seed: int = -1
) -> History:
    """Decode one seed's raw history arrays (any source) into a History."""
    rec = np.asarray(rec)
    t = np.asarray(t)
    n = int(length)
    return History(
        seed=int(seed),
        ops=_pair_rows(rec, t, n),
        overflow=bool(overflow),
        rows=n,
    )


def decode_seed(final, lane: Optional[int] = None) -> History:
    """Decode the history buffer of a finished ``EngineState``.

    ``final`` is unbatched (``run_traced``'s final state) when ``lane``
    is None, else a batched sweep state indexed by ``lane``."""
    if lane is None:
        return decode_rows(
            final.hist_rec, final.hist_t, final.hist_len,
            final.hist_overflow, seed=int(final.seed),
        )
    return decode_rows(
        np.asarray(final.hist_rec)[lane],
        np.asarray(final.hist_t)[lane],
        np.asarray(final.hist_len)[lane],
        np.asarray(final.hist_overflow)[lane],
        seed=int(np.asarray(final.seed)[lane]),
    )


def decode_lanes(final, lanes) -> List[History]:
    """Decode SELECTED lanes of a batched sweep state — the screened
    path's batch decoder (oracle/screen.py): the device planes come off
    the device once, then only the suspect lanes pay the per-row Python
    decode. ``lanes`` is any integer sequence; order is preserved.

    The device->host transfer is sized to the selection, not the chunk:
    an empty selection never touches the device (the clean-sweep common
    case), and a sparse one (< a quarter of the lanes — the screened
    case) gathers the suspect rows device-side first, so a 16k-lane
    chunk with a handful of suspects moves kilobytes, not the whole
    ~100 MB plane, through a possibly-tunneled link."""
    lanes = [int(lane) for lane in lanes]
    if not lanes:
        return []
    n_total = int(final.seed.shape[0])
    if len(lanes) * 4 <= n_total:
        idx = np.asarray(lanes)
        planes = (
            final.hist_rec[idx], final.hist_t[idx],
            final.hist_len[idx], final.hist_overflow[idx],
            final.seed[idx],
        )
        sel = range(len(lanes))
    else:
        planes = (
            final.hist_rec, final.hist_t, final.hist_len,
            final.hist_overflow, final.seed,
        )
        sel = lanes
    rec, t, length, ov, seeds = (np.asarray(p) for p in planes)
    return [
        decode_rows(rec[i], t[i], length[i], ov[i], seed=int(seeds[i]))
        for i in sel
    ]


def decode_sweep(final) -> List[History]:
    """Decode every lane of a batched sweep state (host-side loop; pull
    the arrays off the device once, not per lane)."""
    return decode_lanes(final, range(int(final.seed.shape[0])))


def history_bytes(hist: History) -> bytes:
    """Canonical byte encoding of a decoded history.

    The determinism contract (docs/oracle.md): the same ``(spec, seed)``
    decoded from a device sweep lane and from a bit-exact CPU
    ``run_traced`` replay — or from two separate processes — must
    produce identical bytes. No wall times, no paths, no float repr."""
    lines = [f"seed={hist.seed} rows={hist.rows} overflow={int(hist.overflow)}"]
    lines += [
        f"c={o.client} op={OP_NAMES[o.op]} key={o.key} in={o.inp} "
        f"out={o.out if o.complete else '?'} "
        f"t=[{o.invoke_ns},{o.complete_ns}] id={o.opid}"
        for o in hist.ops
    ]
    return ("\n".join(lines) + "\n").encode()


_CANON_MAGIC = b"MTHC1\n"  # canonical-row format tag (docs/oracle.md)
_CANON_COLS = 8  # (client, op, key, inp, out, rank_inv, rank_comp, opid)


def canonical_bytes_from_rows(rows, n_ops, raw_rows, overflow) -> bytes:
    """Assemble the canonical byte encoding from fixed-width rows.

    ``rows`` is ``int32[*, 8]`` in invoke order — columns ``(client,
    op, key, inp, out-or-0-while-open, invoke rank, complete rank or
    -1, opid)`` — of which the first ``n_ops`` are encoded after a
    magic tag and an ``(raw_rows, overflow)`` int32 header, all
    little-endian. Both producers — the host path
    (``history_canonical_bytes``) and the device kernel
    (``canon_sweep``) — funnel through here, so their byte-identity
    contract reduces to row-array equality."""
    n = int(n_ops)
    head = np.asarray([int(raw_rows), int(bool(overflow))], dtype="<i4")
    body = np.ascontiguousarray(
        np.asarray(rows, dtype=np.int32)[:n], dtype="<i4"
    )
    return _CANON_MAGIC + head.tobytes() + body.tobytes()


def canonical_rows(hist: History) -> np.ndarray:
    """Host-side canonical rows (``int32[n_ops, 8]``) of a decoded
    history: each op's fields with its times replaced by their dense
    rank over the history's distinct valid times (open completions stay
    ``-1``; an open op's ``out`` is pinned to 0, which is what
    ``_pair_rows`` stores for a never-completed op anyway)."""
    ts = sorted(
        {
            t
            for o in hist.ops
            for t in (o.invoke_ns, o.complete_ns)
            if t >= 0
        }
    )
    rank = {t: i for i, t in enumerate(ts)}
    return np.asarray(
        [
            (
                o.client, o.op, o.key, o.inp,
                o.out if o.complete else 0,
                rank[o.invoke_ns],
                rank[o.complete_ns] if o.complete else -1,
                o.opid,
            )
            for o in hist.ops
        ],
        dtype=np.int32,
    ).reshape(len(hist.ops), _CANON_COLS)


def history_canonical_bytes(hist: History) -> bytes:
    """Seed-free, time-rank canonical encoding — the dedup key for WGL
    checking (oracle/screen.history_host_work).

    Two histories that differ only in seed and in the absolute values of
    their timestamps (but agree on every op field and on the relative
    order of all invoke/complete times) get identical bytes. The WGL
    search and every structural pre-pass read timestamps only through
    comparisons, so replacing each distinct time by its dense rank is an
    order-isomorphism that preserves the checker's verdict exactly —
    one representative verdict is valid for the whole equivalence class.
    Open ops keep their ``-1`` completion sentinel. The encoding is the
    fixed-width binary of ``canonical_bytes_from_rows`` so the on-device
    decode kernel (``canon_sweep``) can produce it without any host-side
    re-derivation. Unlike ``history_bytes`` this is NOT the
    determinism-gate encoding: it deliberately erases the seed and the
    absolute clock."""
    return canonical_bytes_from_rows(
        canonical_rows(hist), len(hist.ops), hist.rows, hist.overflow
    )


def history_from_canon(
    rows, n_ops, overflow, raw_rows, seed: int = -1
) -> History:
    """Rebuild a checkable ``History`` from canonical fixed-width rows,
    using each op's dense time ranks AS its times. Ranks are an
    order-isomorphism of the original clock, and the WGL checker and
    every structural pre-pass read times only through comparisons, so
    the verdict on the rebuilt history equals the verdict on the
    host-decoded one — the device-decode path checks THIS history and
    no report byte can tell the difference."""
    n = int(n_ops)
    r = np.asarray(rows)
    ops = tuple(
        Op(
            client=int(r[i, 0]), op=int(r[i, 1]), key=int(r[i, 2]),
            inp=int(r[i, 3]), out=int(r[i, 4]),
            invoke_ns=int(r[i, 5]), complete_ns=int(r[i, 6]),
            opid=int(r[i, 7]),
        )
        for i in range(n)
    )
    return History(
        seed=int(seed), ops=ops, overflow=bool(overflow),
        rows=int(raw_rows),
    )


_CANON_KERNEL = None


def _canon_kernel():
    """Build (once) the jitted, vmapped per-lane canonical-decode
    kernel. jax is imported lazily so the checker's pool workers —
    clean interpreters that import this module (oracle/check.py) —
    stay numpy-only."""
    global _CANON_KERNEL
    if _CANON_KERNEL is None:
        import jax
        import jax.numpy as jnp

        def lane(rec, t, n):
            H = rec.shape[0]
            idx = jnp.arange(H, dtype=jnp.int32)
            valid = idx < n
            client, code, key, val, opid = (rec[:, c] for c in range(5))
            op, ph = code // 2, code % 2
            inv = valid & (ph == PH_INVOKE)
            okm = valid & (ph == PH_OK)
            # pairing: an ok row k matches the LATEST invoke row i of
            # the same (client, opid) with prev_ok(k) < i < k, where
            # prev_ok(k) is k's latest earlier ok sibling — exactly the
            # overwrite-on-reinvoke / pop-on-ok dict semantics of
            # ``_pair_rows``. No match (or an op/key mismatch against
            # the matched invoke) is the record-hook contract breach
            # ``_pair_rows`` raises on; the kernel can't raise, so it
            # flags the lane and the caller falls back to the host
            # decoder for the real error
            same = (client[:, None] == client[None, :]) & (
                opid[:, None] == opid[None, :]
            )
            earlier = idx[None, :] < idx[:, None]
            neg = jnp.int32(-1)
            prev_ok = jnp.max(
                jnp.where(same & earlier & okm[None, :], idx[None, :], neg),
                axis=1,
            )
            cand = (
                same
                & earlier
                & inv[None, :]
                & (idx[None, :] > prev_ok[:, None])
            )
            match = jnp.max(jnp.where(cand, idx[None, :], neg), axis=1)
            m = jnp.clip(match, 0, H - 1)
            mism = (op[m] != op) | (key[m] != key)
            breach = jnp.any(okm & ((match < 0) | mism))
            # dense time rank: a row's rank = number of distinct valid
            # times strictly below its own. Exact under ties (only the
            # first row of a tie group counts as distinct); device
            # lanes have strictly increasing t so rank == row index,
            # but host-recorded planes may tie
            first = valid & ~jnp.any(
                (t[None, :] == t[:, None]) & earlier & valid[None, :],
                axis=1,
            )
            rank = jnp.sum(
                first[None, :] & (t[None, :] < t[:, None]), axis=1
            ).astype(jnp.int32)
            # assembly: invoke k is op number slot[k]; scatter invoke
            # rows whole, then patch (out, rank_comp) at the matched
            # slots — targets are disjoint (two ok rows can't match one
            # invoke: the second's prev_ok bound excludes it). Masked
            # rows scatter into the extra row H, sliced off
            slot = jnp.cumsum(inv.astype(jnp.int32)) - 1
            n_ops = jnp.sum(inv.astype(jnp.int32))
            dump = jnp.int32(H)
            inv_rows = jnp.stack(
                [
                    client, op, key, val,
                    jnp.zeros_like(val), rank,
                    jnp.full_like(val, -1), opid,
                ],
                axis=1,
            ).astype(jnp.int32)
            canon = jnp.zeros((H + 1, _CANON_COLS), dtype=jnp.int32)
            canon = canon.at[jnp.where(inv, slot, dump)].set(inv_rows)
            ok_tgt = jnp.where(okm & (match >= 0), slot[m], dump)
            canon = canon.at[ok_tgt, 4].set(val)
            canon = canon.at[ok_tgt, 6].set(rank)
            return canon[:H], n_ops, breach

        _CANON_KERNEL = jax.jit(jax.vmap(lane))
    return _CANON_KERNEL


def canon_sweep(final):
    """On-device canonical decode of EVERY lane of a finished sweep
    state: ``(canon int32[S, H, 8], n_ops int32[S], breach bool[S])``.

    ``canon[s, :n_ops[s]]`` are lane ``s``'s canonical rows — the same
    rows ``canonical_rows(decode_seed(final, s))`` derives on the host,
    by the pairing/rank arguments in ``_canon_kernel`` — so
    ``canonical_bytes_from_rows`` over a device row block equals
    ``history_canonical_bytes`` over the host-decoded lane bit-exactly.
    One fixed-shape jitted call covers the whole chunk (no per-lane
    recompiles); callers gather just the lanes they need off the device
    afterwards. ``breach[s]`` marks a record-hook contract breach
    (orphan ok / op-key mismatch) on lane ``s``: those rows are
    unusable and the caller must route that lane through the host
    decoder, which raises the diagnostic."""
    return _canon_kernel()(final.hist_rec, final.hist_t, final.hist_len)


class HostRecorder:
    """Thin client-shim recording host-tier operation histories.

    The host tier runs arbitrary async Python under the same virtual
    clock; wrapping each client call in ``invoke``/``complete`` yields
    the same ``History`` structure the device decoder produces, so one
    checker serves both tiers::

        rec = HostRecorder()
        opid = rec.invoke(client=0, op=OP_PUT, key=3, inp=42)
        resp = await kv.put(b"k3", b"42")
        rec.complete(client=0, opid=opid, out=42)
        check_history(rec.history(), KVSpec())

    Times default to the running simulation's virtual clock
    (``madsim_tpu.time``); pass ``clock`` (a ``() -> int`` of
    nanoseconds) to record outside a sim context. NOTE: two engines
    cannot share one RNG stream, so a host history for a ``(spec,
    seed)`` is *not* byte-comparable to the device history — byte
    identity is the contract between the device sweep and its CPU
    ``run_traced`` replay; host histories share only the format and the
    checker (docs/oracle.md).
    """

    def __init__(self, clock: Optional[Callable[[], int]] = None):
        if clock is None:
            def clock() -> int:
                from ..context import current_handle

                return int(current_handle().time.now_ns)

        self._clock = clock
        self._rows: List[Tuple[int, int, int, int, int, int]] = []
        self._next_opid = {}
        self._open = {}  # (client, opid) -> (invoke code, key)

    def invoke(self, client: int, op: int, key: int, inp: int) -> int:
        """Record an op's invocation; returns the opid to complete with."""
        opid = self._next_opid.get(client, 0)
        self._next_opid[client] = opid + 1
        code = code_of(op, PH_INVOKE)
        self._open[(client, opid)] = (code, key)
        self._rows.append((client, code, key, inp, opid, self._clock()))
        return opid

    def complete(self, client: int, opid: int, out: int) -> None:
        """Record an op's completion (skip for ops that never returned).
        Completing an unknown or already-completed op raises HERE, at
        the offending call, not later from the decoder."""
        # the op/key columns are reconstructed from the invoke entry, so
        # completion needs only the identity and the result
        entry = self._open.pop((client, opid), None)
        if entry is None:
            raise ValueError(
                f"complete() for unknown or already-completed "
                f"(client={client}, opid={opid})"
            )
        code, key = entry
        self._rows.append((client, code + 1, key, out, opid, self._clock()))

    def history(self, seed: int = -1) -> History:
        rec = np.asarray(
            [r[:5] for r in self._rows], dtype=np.int32
        ).reshape(len(self._rows), 5)
        t = np.asarray([r[5] for r in self._rows], dtype=np.int64)
        return decode_rows(rec, t, len(self._rows), False, seed=seed)
