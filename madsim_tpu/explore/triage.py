"""Failure triage: bucket violating seeds by failure fingerprint.

A campaign can flag thousands of red seeds; most are the same bug hit
through different schedules. Triage re-runs each seed through
``engine.run_traced`` (bit-exact on CPU) and reduces it to a
**fingerprint** — the violation flavor bitmask the workload's ``probe``
latched, plus the signature of the FIRST event that latched it (event
kind + victim node). Seeds sharing a fingerprint are one failure class;
the explore report and the shrinker work per class, not per seed.

The fingerprint deliberately excludes times, steps and seeds: those vary
per schedule even when the failure mechanism is identical. What it keeps
is where the detector tripped (flavor) and what the tripping event was
(kind, node) — stable across reruns by determinism, and stable across
seeds of the same bug in practice.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from ..engine import core as ecore
from .targets import Target


class Failure(NamedTuple):
    """One triaged violating seed."""

    seed: int
    flavor: int  # probe bitmask at the first violating event
    step: int  # index of that event in the dispatch order
    time_ns: int  # virtual time of that event
    kind: int  # event kind dispatched at that step
    node: int  # victim node of that event (target.node_of)
    fingerprint: str  # the dedupe key: name:flavor:kind:node


def triage_seed(target: Target, faults, seed: int) -> Optional[Failure]:
    """Re-run one seed traced and locate its first violating event.

    Returns None when the seed does not violate under ``faults`` (the
    workload's probe never leaves zero) — the caller's signal that a
    candidate schedule no longer reproduces."""
    workload, ecfg = target.build(faults)
    if workload.probe is None:
        raise ValueError(
            f"target {target.name!r} workload defines no probe; triage "
            "needs the per-step violation flavor run_traced records"
        )
    _, trace = ecore.run_traced(workload, ecfg, seed)
    fired = np.asarray(trace["fired"])
    probe = np.asarray(trace["probe"])
    hits = np.nonzero(fired & (probe != 0))[0]
    if hits.size == 0:
        return None
    i = int(hits[0])
    flavor = int(probe[i])
    kind = int(np.asarray(trace["kind"])[i])
    node = target.node_of(kind, np.asarray(trace["pay"])[i])
    return Failure(
        seed=int(seed),
        flavor=flavor,
        step=i,
        time_ns=int(np.asarray(trace["time_ns"])[i]),
        kind=kind,
        node=node,
        fingerprint=f"{target.name}:f{flavor}:k{kind}:n{node}",
    )


def triage(
    target: Target, faults, seeds: Sequence[int]
) -> Dict[str, List[Failure]]:
    """Triage a batch of violating seeds into fingerprint buckets.

    Returns ``{fingerprint: [Failure, ...]}`` with each bucket's seeds in
    input order; seeds that do not violate are dropped (a campaign's
    violating-seed list can only shrink under re-verification, never
    grow)."""
    buckets: Dict[str, List[Failure]] = {}
    for seed in seeds:
        f = triage_seed(target, faults, seed)
        if f is not None:
            buckets.setdefault(f.fingerprint, []).append(f)
    return buckets


def fingerprint_counts(buckets: Dict[str, List[Failure]]) -> Dict[str, int]:
    """``{fingerprint: seed count}`` — the triage headline."""
    return {fp: len(fails) for fp, fails in sorted(buckets.items())}
