"""Failure triage: bucket violating seeds by failure fingerprint.

A campaign can flag thousands of red seeds; most are the same bug hit
through different schedules. Triage re-runs each seed through
``engine.run_traced`` (bit-exact on CPU) and reduces it to a
**fingerprint** — the violation flavor bitmask the workload's ``probe``
latched, plus the signature of the FIRST event that latched it (event
kind + victim node). Seeds sharing a fingerprint are one failure class;
the explore report and the shrinker work per class, not per seed.

The fingerprint deliberately excludes times, steps and seeds: those vary
per schedule even when the failure mechanism is identical. What it keeps
is where the detector tripped (flavor) and what the tripping event was
(kind, node) — stable across reruns by determinism, and stable across
seeds of the same bug in practice.

A second fingerprint flavor rides on the history oracle
(madsim_tpu/oracle): with ``history=True`` a seed is re-run traced, its
recorded operation history decoded, and the failure keyed on the op
ending the **first non-linearizable prefix** — no model-specific probe
needed, just a ``Target.hist_spec``. History fingerprints keep the op
kind and drop keys/clients (those vary per schedule like times do).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from ..engine import core as ecore
from .targets import Target

# Failure.flavor value marking a history-oracle failure (probe bitmask
# flavors are non-negative)
HISTORY_FLAVOR = -1


class Failure(NamedTuple):
    """One triaged violating seed."""

    seed: int
    flavor: int  # probe bitmask at the first violating event
    step: int  # index of that event in the dispatch order
    time_ns: int  # virtual time of that event
    kind: int  # event kind dispatched at that step
    node: int  # victim node of that event (target.node_of)
    fingerprint: str  # the dedupe key: name:flavor:kind:node


def _triage_history(
    target: Target, workload, ecfg, seed: int, params=None
) -> Optional[Failure]:
    """History-oracle triage: decode the seed's recorded op history and
    fingerprint the op that ends the first non-linearizable prefix.
    ``step`` is that op's index in the decoded history (not a dispatch
    step), ``kind`` its op code, ``node`` its client.

    A one-lane ``run_sweep`` replaces ``run_traced`` here: history
    triage reads only the final state's history buffer, which the two
    paths fill bit-identically (the byte contract tests/test_oracle.py
    pins), and the sweep neither materializes the max_steps-sized trace
    arrays nor runs past the seed's completion — this is the inner loop
    of ``shrink(history=True)``, one replay per ddmin candidate."""
    import jax.numpy as jnp

    from ..oracle import check_history, decode_seed
    from ..oracle.history import OP_NAMES

    if target.hist_spec is None:
        raise ValueError(
            f"target {target.name!r} declares no hist_spec; history "
            "triage needs the sequential spec to check decoded ops against"
        )
    if workload.record is None or workload.hist_slots == 0:
        raise ValueError(
            f"target {target.name!r} workload records no op history "
            "(Workload.record/hist_slots); there is nothing to check"
        )
    if params is not None:
        from ..engine.faults import tile_params

        params = tile_params(params, 1)
    final = ecore.run_sweep(
        workload, ecfg, jnp.asarray([seed], jnp.int64), params=params
    )
    result = check_history(decode_seed(final, 0), target.hist_spec)
    if result.ok:
        return None
    op = result.bad_op
    return Failure(
        seed=int(seed),
        flavor=HISTORY_FLAVOR,
        step=result.bad_index,
        time_ns=op.invoke_ns,
        kind=op.op,
        node=op.client,
        fingerprint=f"{target.name}:history:{OP_NAMES[op.op]}",
    )


def triage_seed(
    target: Target, faults, seed: int, history: bool = False, params=None
) -> Optional[Failure]:
    """Re-run one seed traced and locate its first violating event.

    Returns None when the seed does not violate under ``faults`` (the
    workload's probe never leaves zero — or, with ``history=True``, the
    decoded op history checks linearizable) — the caller's signal that a
    candidate schedule no longer reproduces.

    ``faults`` may be a ``FaultEnvelope`` with the concrete candidate in
    ``params`` (engine/faults.py spec-as-data): the replay is
    bit-identical to the static-spec path, but every candidate of the
    envelope's width reuses ONE compiled traced program — the shrinker's
    ddmin loop replays dozens of schedules for one compile instead of
    one compile each."""
    workload, ecfg = target.build(faults)
    if history:
        return _triage_history(target, workload, ecfg, seed, params=params)
    if workload.probe is None:
        raise ValueError(
            f"target {target.name!r} workload defines no probe; triage "
            "needs the per-step violation flavor run_traced records"
        )
    _, trace = ecore.run_traced(workload, ecfg, seed, params=params)
    fired = np.asarray(trace["fired"])
    probe = np.asarray(trace["probe"])
    hits = np.nonzero(fired & (probe != 0))[0]
    if hits.size == 0:
        return None
    i = int(hits[0])
    flavor = int(probe[i])
    kind = int(np.asarray(trace["kind"])[i])
    node = target.node_of(kind, np.asarray(trace["pay"])[i])
    return Failure(
        seed=int(seed),
        flavor=flavor,
        step=i,
        time_ns=int(np.asarray(trace["time_ns"])[i]),
        kind=kind,
        node=node,
        fingerprint=f"{target.name}:f{flavor}:k{kind}:n{node}",
    )


def triage(
    target: Target, faults, seeds: Sequence[int], history: bool = False
) -> Dict[str, List[Failure]]:
    """Triage a batch of violating seeds into fingerprint buckets.

    Returns ``{fingerprint: [Failure, ...]}`` with each bucket's seeds in
    input order; seeds that do not violate are dropped (a campaign's
    violating-seed list can only shrink under re-verification, never
    grow). ``history=True`` routes every seed through the history
    oracle instead of the model probe."""
    buckets: Dict[str, List[Failure]] = {}
    for seed in seeds:
        f = triage_seed(target, faults, seed, history=history)
        if f is not None:
            buckets.setdefault(f.fingerprint, []).append(f)
    return buckets


def fingerprint_counts(buckets: Dict[str, List[Failure]]) -> Dict[str, int]:
    """``{fingerprint: seed count}`` — the triage headline."""
    return {fp: len(fails) for fp, fails in sorted(buckets.items())}
