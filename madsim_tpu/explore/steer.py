"""The self-steering scheduler: coverage-guided compute allocation.

MadSim-style DST spends its device-seconds on a uniform grid; this
module closes ROADMAP item 2 by letting the fleet *reallocate its own
compute* toward the envelope regions still producing novel failures —
between the corpus store/orchestrator and the streaming service, as a
pure queue policy over ``stream_sweep``'s ``feed=`` hook (zero
recompiles: the lane pool never drains, candidates ride in as
spec-as-data ``FaultParams`` rows).

The pieces:

- **Families** partition the campaign envelope: a family is the bitmask
  of active fault-category count fields (``campaign._COUNT_FIELDS`` —
  crashes, partitions, ..., skews), and a candidate is a point of that
  region reached by a *mutation lineage* — a seeded mutation chain
  confined to the family's mask. ``family_candidate(base, mask, seed,
  lineage)`` regenerates any chain element bit-identically anywhere
  (the rng key derives from the campaign seed through ``rand.mix64``,
  the GlobalRng module's splitmix64 finalizer — one seed, one chain).
- **The bandit** (:class:`BanditScheduler`) is UCB1 over families,
  scored by novel-coverage-bits-per-device-second. UCB over Thompson on
  purpose: the argmax needs no sampling key, so every decision is a
  pure function of the absorbed outcomes plus the campaign seed —
  nothing to replay but the arithmetic. "Device-seconds" are the
  deterministic proxy ``events_total`` (wall clocks are out-of-band by
  the repo-wide contract and may never influence a decision); a fresh
  triage fingerprint is worth ``fp_bits`` coverage bits so the bandit
  mines violation-bearing regions, not just coverage frontier.
- **Early-kill**: a family whose fingerprint-dedup hit rate saturates
  (``kill_dup_rate_pct``) or that stays barren (no new bits, no fresh
  fingerprints) for ``kill_plays`` consecutive plays is removed from
  the universe — its remaining budget flows to live families. The last
  live family is never killed.
- **Escalation**: a family's first violation marks it hot — later
  candidates get ``escalate_seeds`` x the seeds and the long step
  budget (``budget_hi_steps``), the "longer horizon, more luck" knob
  the stream's per-lane ``budgets=`` machinery makes free.

Determinism contract (the hard constraint): every decision is a pure
function of (campaign seed, config, absorbed outcome prefix). Outcomes
absorb strictly in submission order (the stream flushes virtual chunks
in submission order no matter the refill schedule), and decision ``i``
sees exactly the outcomes of candidates ``0..i-1-pipeline`` — the
pipeline depth is part of the config, so a replayed campaign makes
bit-identical decisions and writes byte-identical reports AND decision
traces (``scripts/check_determinism.sh`` steering leg: 2 processes x
telemetry {on,off}). The trace carries no wall times; scores are
recorded as integer micros. The same records mirror out-of-band into
the run journal as ``steer_round`` events (docs/observability.md).

``run_steered`` is the whole loop; ``CampaignConfig.scheduler="bandit"``
routes ``explore.run_campaign`` here, and ``scheduler="uniform"`` in a
:class:`SteerConfig` turns the identical loop into the matched
round-robin grid — the A/B baseline (``scripts/steer_demo.py``,
``bench.py --steering``). See docs/steering.md.
"""

from __future__ import annotations

import json
import math
import random
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..rand import mix64
from .campaign import (
    _COUNT_FIELDS,
    CampaignConfig,
    CampaignResult,
    mutate_spec,
    spec_to_dict,
    target_envelope,
)
from .targets import Target

_M64 = (1 << 64) - 1


# ---------------------------------------------------------------------------
# family keying


def family_of(spec) -> int:
    """The family bitmask of a ``FaultSpec``: bit ``i`` set iff count
    field ``_COUNT_FIELDS[i]`` is active (> 0). Pure structure — two
    specs differing only in windows/durations/rates share a family."""
    mask = 0
    for i, f in enumerate(_COUNT_FIELDS):
        if getattr(spec, f) > 0:
            mask |= 1 << i
    return mask


def family_key(mask: int) -> str:
    """The stable 3-hex-digit record key of a family bitmask (9
    category bits fit 0x000..0x1ff; fixed width keeps keys sortable)."""
    return f"{mask:03x}"


def family_universe(base_spec) -> Tuple[int, ...]:
    """The default family universe for a base spec: the base's own
    family, every single-category family, and the base joined with each
    other category — sorted, deduped. Single-category duds are the
    point: a uniform grid pays for them forever, the bandit kills them."""
    base = family_of(base_spec)
    masks = {1 << i for i in range(len(_COUNT_FIELDS))}
    if base:
        masks.add(base)
        masks.update(base | (1 << i) for i in range(len(_COUNT_FIELDS)))
    return tuple(sorted(masks))


def _mask_spec(spec, mask: int):
    """Confine ``spec`` to family ``mask``: off-mask count fields drop
    to 0, on-mask fields rise to at least 1 (a family member exercises
    every category its mask names)."""
    updates = {}
    for i, f in enumerate(_COUNT_FIELDS):
        v = getattr(spec, f)
        if not mask & (1 << i):
            if v:
                updates[f] = 0
        elif v == 0:
            updates[f] = 1
    return spec._replace(**updates) if updates else spec


def _chain_rng(campaign_seed: int, mask: int, salt: int) -> random.Random:
    """The mutation-chain rng for one ``(family, salt)`` lineage —
    derived from the campaign seed through ``rand.mix64`` (the GlobalRng
    module's splitmix64 finalizer), so family chains are independent
    streams of ONE explicitly seeded key."""
    k = mix64(campaign_seed & _M64)
    k = mix64(k ^ mask)
    k = mix64(k ^ (salt & _M64))
    return random.Random(k)


def family_candidate(
    base_spec,
    mask: int,
    campaign_seed: int,
    lineage: int,
    mutations_hi: int = 2,
    salt: int = 0,
):
    """Candidate ``lineage`` of family ``mask``'s mutation chain: the
    masked base for lineage 0, then seeded ``mutate_spec`` rounds
    re-confined to the mask — a pure function of ``(base, mask,
    campaign_seed, lineage, salt)``, regenerable bit-identically by any
    process. ``salt`` namespaces independent chains (e.g. per fleet
    unit); a salted chain starts one mutation deep, so two units of one
    generation that pick the same ``(family, lineage)`` still sweep
    DISTINCT candidates (lineage 0 of the unsalted chain is the masked
    base itself — the bland starting point a solo campaign wants)."""
    rng = _chain_rng(campaign_seed, mask, salt)
    cur = _mask_spec(base_spec, mask)
    for _ in range(lineage + (1 if salt else 0)):
        # a draw whose mutations all hit off-mask fields no-ops after
        # re-masking; retry (bounded, deterministic) so chain elements
        # actually move even under single-category masks
        for _try in range(8):
            nxt = _mask_spec(mutate_spec(cur, rng, mutations_hi), mask)
            if nxt != cur:
                break
        cur = nxt
    return cur


# ---------------------------------------------------------------------------
# the bandit


class SteerConfig(NamedTuple):
    """Static scheduler parameters (hashable; the report header records
    them, so compare steered reports only across runs of one config).

    All knobs are integers on purpose — the config travels through
    JSON report headers and the determinism gates byte-diff those."""

    scheduler: str = "bandit"  # "bandit" | "uniform" (the A/B switch)
    families: Tuple[int, ...] = ()  # () = family_universe(base_spec)
    ucb_c_milli: int = 1400  # exploration constant x 1e-3
    fp_bits: int = 64  # coverage-bit value of one fresh fingerprint
    kill_plays: int = 3  # plays before a family may be killed
    kill_dup_rate_pct: int = 90  # dedup-hit-rate saturation threshold
    escalate_seeds: int = 2  # seeds multiplier for hot families
    budget_lo_steps: int = 0  # per-lane step budget (0 = cfg.max_steps)
    budget_hi_steps: int = 0  # escalated budget (0 = cfg.max_steps)
    pipeline: int = 2  # decisions in flight ahead of their outcomes
    budget_events: int = 0  # total device-event budget (0 = rounds-capped)
    gen_units: int = 2  # fleet: units per planning generation


def _stats0() -> dict:
    return {
        "plays": 0,  # absorbed outcomes
        "events": 0,  # deterministic device-second proxy spent
        "new_bits": 0,  # novel coverage bits earned
        "vio": 0,  # violating seeds observed (recorded sample)
        "fresh": 0,  # first-seen triage fingerprints
        "dup": 0,  # recorded violating seeds with a known fingerprint
        "barren": 0,  # consecutive plays with no new bits, no fresh fps
    }


class BanditScheduler:
    """Deterministic UCB1 compute allocator over candidate families.

    ``decide()`` emits the next decision record; ``absorb()`` folds one
    outcome (in submission order) and applies the kill/escalate
    transitions. Every record appended to ``trace`` is deterministic
    bytes — no wall times, scores as integer micros. ``scheduler=
    "uniform"`` degrades the same object to the matched round-robin
    grid (no kills, no escalation, fixed seeds/budget) so the A/B
    differs in POLICY only."""

    def __init__(
        self,
        universe: Sequence[int],
        scfg: SteerConfig,
        *,
        seeds_per_play: int,
        budget_lo: int,
        budget_hi: int,
    ):
        if not universe:
            raise ValueError("family universe is empty")
        if scfg.scheduler not in ("bandit", "uniform"):
            raise ValueError(f"unknown scheduler {scfg.scheduler!r}")
        self.universe: Tuple[int, ...] = tuple(universe)
        self.scfg = scfg
        self.seeds_per_play = int(seeds_per_play)
        self.budget_lo = int(budget_lo)
        self.budget_hi = int(budget_hi)
        self.stats: Dict[int, dict] = {m: _stats0() for m in self.universe}
        self.decided: Dict[int, int] = {m: 0 for m in self.universe}
        self.killed: Dict[int, str] = {}  # mask -> reason
        self.escalated: List[int] = []
        self.trace: List[dict] = []
        self.t = 0  # decisions emitted
        self.absorbed = 0  # outcomes folded
        self.spent_events = 0

    # -- scoring ----------------------------------------------------------

    def alive(self) -> List[int]:
        return [m for m in self.universe if m not in self.killed]

    def _reward(self, st: dict) -> float:
        """Novel coverage bits (fresh fingerprints count ``fp_bits``
        each) per device event — the deterministic bits-per-device-
        second signal."""
        value = st["new_bits"] + self.scfg.fp_bits * st["fresh"]
        return value / max(st["events"], 1)

    def _score(self, mask: int, total_plays: int, r_bar: float) -> float:
        st = self.stats[mask]
        # in-flight decisions count toward the arm's pull count, so the
        # pipelined loop spreads cold exploration instead of double-
        # committing to one family before its first outcome lands
        n = max(self.decided[mask], 1)
        c = self.scfg.ucb_c_milli / 1000.0
        bonus = c * max(r_bar, 1e-12) * math.sqrt(
            math.log(max(total_plays, 2)) / max(n, 1)
        )
        return self._reward(st) + bonus

    def _pick(self) -> Tuple[int, str]:
        alive = self.alive()
        if self.scfg.scheduler == "uniform":
            return alive[self.t % len(alive)], "uniform"
        cold = [m for m in alive if self.decided[m] == 0]
        if cold:
            return cold[0], "cold"
        total_plays = sum(self.decided[m] for m in alive)
        tot = _stats0()
        for m in alive:
            st = self.stats[m]
            tot["events"] += st["events"]
            tot["new_bits"] += st["new_bits"]
            tot["fresh"] += st["fresh"]
        r_bar = self._reward(tot)
        # max score, ties broken by fewest decisions then mask order —
        # a total order, so the argmax is deterministic
        best = min(
            alive,
            key=lambda m: (-self._score(m, total_plays, r_bar),
                           self.decided[m], m),
        )
        return best, "ucb"

    # -- the two verbs ----------------------------------------------------

    def decide(self) -> dict:
        """Emit decision ``t``: which family to sweep next, with how
        many seeds and what per-lane step budget. Pure function of the
        absorbed outcome prefix + config."""
        mask, why = self._pick()
        hot = mask in self.escalated
        seeds = self.seeds_per_play * (
            self.scfg.escalate_seeds if hot else 1
        )
        st = self.stats[mask]
        total_plays = sum(self.decided[m] for m in self.alive())
        tot = _stats0()
        for m in self.alive():
            s2 = self.stats[m]
            tot["events"] += s2["events"]
            tot["new_bits"] += s2["new_bits"]
            tot["fresh"] += s2["fresh"]
        score = (
            0.0
            if why != "ucb"
            else self._score(mask, total_plays, self._reward(tot))
        )
        rec = {
            "kind": "decide",
            "i": self.t,
            "family": family_key(mask),
            "lineage": self.decided[mask],
            "why": why,
            "hot": hot,
            "seen": self.absorbed,
            "seeds": seeds,
            "budget": self.budget_hi if hot else self.budget_lo,
            "score_micro": int(round(score * 1e6)),
            "plays": st["plays"],
            "alive": len(self.alive()),
        }
        self.decided[mask] += 1
        self.t += 1
        self.trace.append(rec)
        return rec

    def absorb(self, mask: int, outcome: dict) -> dict:
        """Fold candidate outcome ``absorbed`` (submission order):
        ``{"events", "new_bits", "vio", "fresh", "dup"}`` — all
        byte-deterministic sweep products — then run the kill/escalate
        transitions. Returns the outcome trace record."""
        st = self.stats[mask]
        st["plays"] += 1
        st["events"] += int(outcome["events"])
        st["new_bits"] += int(outcome["new_bits"])
        st["vio"] += int(outcome["vio"])
        st["fresh"] += int(outcome["fresh"])
        st["dup"] += int(outcome["dup"])
        if outcome["new_bits"] or outcome["fresh"]:
            st["barren"] = 0
        else:
            st["barren"] += 1
        self.spent_events += int(outcome["events"])
        self.absorbed += 1
        rec = {
            "kind": "outcome",
            "i": self.absorbed - 1,
            "family": family_key(mask),
            "events": int(outcome["events"]),
            "new_bits": int(outcome["new_bits"]),
            "vio": int(outcome["vio"]),
            "fresh": int(outcome["fresh"]),
            "dup": int(outcome["dup"]),
            "spent_events": self.spent_events,
        }
        self.trace.append(rec)
        if self.scfg.scheduler == "uniform":
            return rec
        if mask not in self.escalated and st["vio"] > 0:
            # first violation: the family is near a bug — escalate its
            # horizon and seed allocation from the NEXT decision on
            self.escalated.append(mask)
            self.trace.append(
                {
                    "kind": "escalate",
                    "family": family_key(mask),
                    "at": self.absorbed - 1,
                }
            )
        self._maybe_kill(mask, st)
        return rec

    def _maybe_kill(self, mask: int, st: dict) -> None:
        if mask in self.killed or len(self.alive()) <= 1:
            return
        if st["plays"] < self.scfg.kill_plays:
            return
        reason = None
        recorded = st["fresh"] + st["dup"]
        if (
            recorded
            and st["barren"] >= 1
            and 100 * st["dup"] >= self.scfg.kill_dup_rate_pct * recorded
        ):
            reason = "dup-saturated"
        elif st["barren"] >= self.scfg.kill_plays:
            reason = "barren"
        if reason is not None:
            self.killed[mask] = reason
            self.trace.append(
                {
                    "kind": "kill",
                    "family": family_key(mask),
                    "why": reason,
                    "at": self.absorbed - 1,
                }
            )

    def trace_lines(self) -> str:
        """The decision trace as deterministic JSONL bytes (sorted
        keys, no wall times) — what the determinism gate byte-diffs and
        what mirrors into the journal as ``steer_round`` events."""
        return (
            "\n".join(json.dumps(r, sort_keys=True) for r in self.trace)
            + "\n"
        )


# ---------------------------------------------------------------------------
# per-family stats from stored fleet records (the orchestrator's view)


def fold_family_stats(
    cands: Sequence[Tuple[str, dict]],
    bugs: Sequence[Tuple[str, dict]],
) -> Dict[int, dict]:
    """Per-family stats from a merged store view — the pure function of
    the record set ``plan_unit_steered`` consults, so ANY worker
    computes identical stats from identical completed units.

    ``cands``/``bugs`` are ``(key, payload)`` pairs as ``merged()``
    yields them; the fold runs in sorted key order with the same
    coverage accounting as ``orchestrator.merged_report``, so the
    new-bits attribution is partition-invariant. Fingerprint dedup uses
    the bug records' ``(unit, cand)`` attribution: a fingerprint is
    fresh at its first fold-order appearance. Recorded violating seeds
    beyond the fresh count approximate the dup hits (per-seed
    fingerprints are not stored; the approximation is deterministic,
    which is what matters here)."""
    fps_at: Dict[Tuple[int, int], List[str]] = {}
    # sort by KEY alone: payloads are dicts (unorderable), and equal
    # keys would otherwise make the fold order compare them
    for _key, p in sorted(bugs, key=lambda kp: kp[0]):
        fps_at.setdefault((int(p["unit"]), int(p["cand"])), []).append(
            p["fingerprint"]
        )
    stats: Dict[int, dict] = {}
    seen_fps: set = set()
    global_map: List[int] = []
    for key, p in sorted(cands, key=lambda kp: kp[0]):
        fam = p.get("family")
        if fam is None:
            continue  # records from an unsteered plan carry no family
        mask = int(fam, 16)
        st = stats.setdefault(mask, _stats0())
        cand_map = [int(w) for w in p.get("coverage_map", [])]
        if len(global_map) < len(cand_map):
            global_map += [0] * (len(cand_map) - len(global_map))
        new_bits = sum(
            (c & ~g).bit_count() for c, g in zip(cand_map, global_map)
        )
        global_map = [g | c for g, c in zip(global_map, cand_map)]
        fresh = 0
        for fp in fps_at.get((int(p["unit"]), int(p["cand"])), []):
            if fp not in seen_fps:
                seen_fps.add(fp)
                fresh += 1
        recorded = len(p.get("violating_seeds", []))
        st["plays"] += 1
        st["events"] += int(p.get("events_total", 0))
        st["new_bits"] += new_bits
        st["vio"] += int(p.get("violations", 0))
        st["fresh"] += fresh
        st["dup"] += max(0, recorded - fresh)
        if new_bits or fresh:
            st["barren"] = 0
        else:
            st["barren"] += 1
    return stats


def plan_unit_steered(
    base_spec,
    ccfg: CampaignConfig,
    scfg: SteerConfig,
    unit: int,
    stats: Dict[int, dict],
) -> List[Tuple[int, object]]:
    """Unit ``unit``'s steered candidates: ``ccfg.batch`` ``(mask,
    spec)`` pairs chosen by a bandit primed with ``stats`` (the merged
    store's per-family view over COMPLETED generations — every worker
    that plans this unit holds the identical view, so the plan is
    partition-invariant like the uniform ``plan_unit``). Candidate
    lineages are salted by the unit, so distinct units of one
    generation explore distinct chain elements of the same families."""
    universe = scfg.families or family_universe(base_spec)
    sched = BanditScheduler(
        universe, scfg,
        seeds_per_play=ccfg.seeds_per_round,
        budget_lo=scfg.budget_lo_steps,
        budget_hi=scfg.budget_hi_steps,
    )
    for mask in universe:
        st = stats.get(mask)
        if st is None:
            continue
        sched.stats[mask] = dict(st)
        sched.decided[mask] = st["plays"]
        if st["vio"] > 0:
            sched.escalated.append(mask)
        sched._maybe_kill(mask, sched.stats[mask])
    sched.absorbed = sum(st["plays"] for st in stats.values())
    out: List[Tuple[int, object]] = []
    per_family: Dict[int, int] = {}
    for _j in range(max(1, ccfg.batch)):
        rec = sched.decide()
        mask = int(rec["family"], 16)
        lineage = per_family.get(mask, 0)
        per_family[mask] = lineage + 1
        out.append(
            (
                mask,
                family_candidate(
                    base_spec, mask, ccfg.campaign_seed, lineage,
                    ccfg.mutations_hi, salt=unit + 1,
                ),
            )
        )
    return out


def _jfields(rec: dict) -> dict:
    """A trace record as journal-event fields: the trace's ``kind``
    (decide/outcome) moves to ``step`` — the journal writer owns the
    ``kind`` key (it becomes ``steer_round``)."""
    out = dict(rec)
    out["step"] = out.pop("kind")
    return out


# ---------------------------------------------------------------------------
# the steered campaign loop


class SteerResult(NamedTuple):
    """``run_steered``'s product — a superset of ``CampaignResult``."""

    corpus: List[object]
    records: List[dict]
    failures: List[Tuple[object, int]]
    coverage_map: List[int]
    decisions: List[dict]  # the deterministic decision trace
    fingerprints: List[str]  # sorted distinct triage fingerprints
    spent_events: int

    def campaign_result(self) -> CampaignResult:
        return CampaignResult(
            corpus=self.corpus,
            records=self.records,
            failures=self.failures,
            coverage_map=self.coverage_map,
        )


def run_steered(
    target: Target,
    base_spec,
    ccfg: CampaignConfig = CampaignConfig(),
    scfg: Optional[SteerConfig] = None,
    *,
    history: bool = False,
    report_path: Optional[str] = None,
    trace_path: Optional[str] = None,
    mesh=None,
    telemetry=None,
) -> SteerResult:
    """The steered campaign: ONE ``stream_sweep`` whose ``feed=`` queue
    the bandit fills, decision by decision, until ``ccfg.rounds``
    decisions or ``scfg.budget_events`` device events are spent.

    Pipeline discipline (the determinism contract): ``scfg.pipeline``
    decisions are primed cold; afterwards exactly one new decision is
    made per absorbed outcome, inside the submission-order ``on_chunk``
    flush — so decision ``i`` sees outcomes ``0..i-1-pipeline`` no
    matter how lanes retire or refill, and a replay is bit-identical.
    The stream polls ``feed`` whenever lanes run dry: a decided segment
    is handed over if one is ready, else the pool drains, flushes (which
    decides more), and polls again — occupancy may dip, bytes may not.

    Escalated candidates feed ``escalate_seeds`` chunk-sized segments
    and the ``budget_hi_steps`` per-lane budget; an escalated family's
    undispatched items also jump the queue through the stream's
    ``reprioritize`` hook (a zero-recompile reorder — dispatch order
    changes, report bytes cannot, by the stream's submission-order
    flush contract).

    Returns a :class:`SteerResult`; ``report_path`` writes the
    campaign-style JSONL report, ``trace_path`` the decision-trace
    JSONL — both deterministic bytes for one ``(ccfg, scfg)``."""
    import time as _time

    from ..engine.faults import spec_to_params, tile_params
    from ..engine.stream import stream_sweep
    from ..models._common import coverage_bit_count, merge_summaries
    from .triage import triage_seed

    if scfg is None:
        scfg = SteerConfig()
    envelope = target_envelope(target, base_spec)
    workload, ecfg = target.build(envelope)
    if workload.cover is None or workload.cover_bits == 0:
        raise ValueError(
            f"target {target.name!r} workload defines no coverage signal "
            "(Workload.cover/cover_bits); steering needs the reward"
        )
    # ``history=True`` routes triage through the history oracle (the
    # run_worker convention) — required for targets whose violations
    # only the WGL checker sees (etcd's stale reads latch nothing)
    hist_triage = history
    s0 = ccfg.seeds_per_round
    budget_lo = min(scfg.budget_lo_steps or ecfg.max_steps, ecfg.max_steps)
    budget_hi = min(scfg.budget_hi_steps or ecfg.max_steps, ecfg.max_steps)
    universe = scfg.families or family_universe(base_spec)
    sched = BanditScheduler(
        universe, scfg,
        seeds_per_play=s0, budget_lo=budget_lo, budget_hi=budget_hi,
    )
    t0_wall = _time.perf_counter()

    # mirrors sweep_candidate_grid: device screen per retirement cohort,
    # WGL checker over the suspects in the overlapped host phase
    screen_fn = None
    if target.hist_spec is not None:
        from ..oracle.screen import screen_for, screen_sweep

        if screen_for(target.hist_spec) is not None:
            def screen_fn(final):
                return screen_sweep(final, target.hist_spec, mesh=mesh)

    def host_work(final, *, lo, n, seeds, suspect, summary) -> dict:
        del lo, n, seeds
        if suspect is not None:
            from ..oracle.check import violating_seeds

            vio = violating_seeds(
                final, target.hist_spec, screen=lambda _f: suspect,
                workers=ccfg.check_workers,
            )
        else:
            vio = np.asarray(target.violating(final))
        out = {
            "violating_seeds": [int(x) for x in vio[: ccfg.max_recorded_seeds]]
        }
        if "violations" not in summary:
            out["violations"] = int(vio.size)
        return out

    # decided-but-unfed segments, in decision order; chunk bookkeeping
    ready: List[dict] = []
    chunk_owner: Dict[int, int] = {}  # chunk lo -> decision index
    cand: List[dict] = []  # per decision: spec/mask/chunks/partial
    item_prio: List[int] = []  # per queue item: 0 = escalated (jump queue)
    next_item = 0
    corpus: List[object] = []
    records: List[dict] = []
    failures: List[Tuple[object, int]] = []
    seen_failures: set = set()
    global_map: List[int] = []
    seen_fps: set = set()
    first_bug_recorded = False

    def can_decide() -> bool:
        if sched.t >= ccfg.rounds:
            return False
        if scfg.budget_events and sched.spent_events >= scfg.budget_events:
            return False
        return True

    def push_decision() -> None:
        nonlocal next_item
        rec = sched.decide()
        mask = int(rec["family"], 16)
        spec = family_candidate(
            base_spec, mask, ccfg.campaign_seed, rec["lineage"],
            ccfg.mutations_hi,
        )
        m = rec["seeds"]
        for t in range(m // s0):
            chunk_owner[next_item + t * s0] = rec["i"]
        cand.append(
            {
                "rec": rec,
                "mask": mask,
                "spec": spec,
                "chunks": m // s0,
                "landed": 0,
                "partial": {},
            }
        )
        item_prio.extend([0 if rec["hot"] else 1] * m)
        next_item += m
        ready.append(
            {
                "seeds": np.arange(
                    ccfg.seed0, ccfg.seed0 + m, dtype=np.int64
                ),
                "params": tile_params(
                    spec_to_params(spec, envelope, target.num_nodes), m
                ),
                "budgets": np.full(m, rec["budget"], np.int32),
            }
        )
        if telemetry is not None:
            telemetry.count("steer_decisions_total", help="bandit decisions")
            telemetry.gauge(
                "steer_families_alive", len(sched.alive()),
                help="families not yet early-killed",
            )
            telemetry.event("steer_round", **_jfields(rec))

    def absorb(j: int) -> None:
        """Candidate ``j``'s chunks all flushed: score the outcome,
        fold it into the bandit, and decide the next candidate."""
        nonlocal global_map, first_bug_recorded
        c = cand[j]
        summary: dict = {}
        for t in sorted(c["partial"]):
            merge_summaries(summary, c["partial"][t])
        c["partial"] = None
        spec, mask, rec = c["spec"], c["mask"], c["rec"]
        cand_map = [int(w) for w in summary.get("coverage_map", [])]
        if len(global_map) < len(cand_map):
            global_map += [0] * (len(cand_map) - len(global_map))
        new_bits = sum(
            (cw & ~g).bit_count() for cw, g in zip(cand_map, global_map)
        )
        retained = j == 0 or new_bits > 0
        if retained:
            corpus.append(spec)
            global_map = [g | cw for g, cw in zip(global_map, cand_map)]
        all_vio = summary.get("violating_seeds", [])
        # the device latch undercounts targets whose violations only the
        # history checker sees (etcd stale reads): take the max of the
        # two deterministic signals
        vio_n = max(int(summary.get("violations", 0)), len(all_vio))
        vio = all_vio[: ccfg.max_recorded_seeds]
        fresh_fps: List[str] = []
        dup = 0
        for seed in vio:
            f = triage_seed(
                target, envelope, int(seed), history=hist_triage,
                params=spec_to_params(spec, envelope, target.num_nodes),
            )
            if f is None:
                continue
            if f.fingerprint in seen_fps:
                dup += 1
            else:
                seen_fps.add(f.fingerprint)
                fresh_fps.append(f.fingerprint)
            if (spec, int(seed)) not in seen_failures:
                seen_failures.add((spec, int(seed)))
                failures.append((spec, int(seed)))
        events = int(summary.get("events_total", 0)) or rec["seeds"]
        out = sched.absorb(
            mask,
            {
                "events": events,
                "new_bits": new_bits,
                "vio": vio_n,
                "fresh": len(fresh_fps),
                "dup": dup,
            },
        )
        records.append(
            {
                "round": j,
                "family": family_key(mask),
                "lineage": rec["lineage"],
                "spec": spec_to_dict(spec),
                "seeds": [ccfg.seed0, ccfg.seed0 + rec["seeds"]],
                "budget": rec["budget"],
                "violations": vio_n,
                "violating_seeds": [int(x) for x in vio],
                "coverage_bits": coverage_bit_count(cand_map),
                "new_bits": new_bits,
                "coverage_total_bits": coverage_bit_count(global_map),
                "retained": retained,
                "events_total": int(summary.get("events_total", 0)),
                "fresh_fingerprints": fresh_fps,
                "dup_fingerprints": dup,
            }
        )
        if telemetry is not None:
            telemetry.count("steer_outcomes_total", help="outcomes absorbed")
            telemetry.gauge(
                "steer_spent_events", sched.spent_events,
                help="deterministic device-event budget spent",
            )
            if fresh_fps:
                telemetry.count(
                    "steer_fresh_fingerprints_total", len(fresh_fps),
                    help="first-seen triage fingerprints",
                )
            if dup:
                telemetry.count(
                    "steer_dup_fingerprints_total", dup,
                    help="recorded violating seeds with a known fingerprint",
                )
            if sched.killed:
                telemetry.gauge(
                    "steer_kills_total", len(sched.killed),
                    help="families early-killed",
                )
            if sched.escalated:
                telemetry.gauge(
                    "steer_escalations_total", len(sched.escalated),
                    help="families escalated after a first violation",
                )
            if failures and not first_bug_recorded:
                first_bug_recorded = True
                telemetry.gauge(
                    "steer_time_to_first_bug_seconds",
                    _time.perf_counter() - t0_wall,
                    help="wall time from steered-campaign start to first "
                    "failure (out-of-band; decisions never read it)",
                )
            telemetry.event("steer_round", **_jfields(out))
        if can_decide():
            push_decision()

    def on_chunk(*, lo, k, summary):  # noqa: ANN001 - stream contract
        del k
        j = chunk_owner.pop(lo)
        c = cand[j]
        c["partial"][lo] = summary
        c["landed"] += 1
        if c["landed"] == c["chunks"]:
            absorb(j)

    def feed() -> Optional[dict]:
        if not ready:
            return None
        return ready.pop(0)

    def reprioritize(tail: np.ndarray) -> Optional[np.ndarray]:
        prio = np.asarray(item_prio, np.int64)[tail]
        if prio.size < 2 or (prio == prio[0]).all():
            return None
        if telemetry is not None:
            telemetry.count(
                "steer_reorders_total",
                help="escalated families jumped the dispatch queue",
            )
        return tail[np.argsort(prio, kind="stable")]

    for _ in range(max(1, scfg.pipeline)):
        if can_decide():
            push_decision()

    if ready:
        first = ready
        ready = []
        init = {
            "seeds": np.concatenate([seg["seeds"] for seg in first]),
            "params": None,
            "budgets": np.concatenate([seg["budgets"] for seg in first]),
        }
        import jax

        init["params"] = jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
            *[seg["params"] for seg in first],
        )
        stream_sweep(
            workload, ecfg, init["seeds"], target.summarize,
            params=init["params"], budgets=init["budgets"],
            chunk_size=s0,
            pool_size=s0 * max(1, scfg.pipeline),
            host_work=host_work, screen=screen_fn, mesh=mesh,
            on_chunk=on_chunk, feed=feed, reprioritize=reprioritize,
            telemetry=telemetry,
        )

    header = {
        "campaign": ccfg._asdict(),
        "steer": scfg._asdict(),
        "target": target.name,
        "base_spec": spec_to_dict(base_spec),
    }
    if report_path is not None:
        with open(report_path, "w") as f:
            f.write(json.dumps(header, sort_keys=True) + "\n")
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
    if trace_path is not None:
        with open(trace_path, "w") as f:
            f.write(json.dumps(header, sort_keys=True) + "\n")
            f.write(sched.trace_lines())

    return SteerResult(
        corpus=corpus,
        records=records,
        failures=failures,
        coverage_map=global_map,
        decisions=list(sched.trace),
        fingerprints=sorted(seen_fps),
        spent_events=sched.spent_events,
    )
