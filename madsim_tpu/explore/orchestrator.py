"""The fleet orchestrator: leased candidate batches over a shared store.

Ties the three fleet pieces together (docs/fleet.md):

- **work plan**: the campaign's candidate stream is cut into *units* of
  ``CampaignConfig.batch`` candidates each, generated unit-locally —
  ``plan_unit(base, ccfg, u)`` seeds its own ``random.Random`` from
  ``(campaign_seed, u)`` and chains mutations inside the unit only, so
  ANY worker can regenerate ANY unit's candidates bit-identically with
  no cross-unit state. (This is the fleet-mode trade, the same one
  ``CampaignConfig.batch`` already makes: candidates draw from the base
  spec, not from a live corpus — adaptive parent selection would make
  the plan depend on completion order and break partition invariance.)
- **leased execution**: a worker leases units from the shared
  :class:`~.store.CorpusStore` and feeds each leased unit's
  ``(candidate x seed)`` grid into ONE running ``stream_sweep`` through
  its ``feed=`` hook — the unit's lanes enter the warmed pool mid-flight
  at zero recompiles (the envelope covers every mutation the plan can
  generate). Leases heartbeat on every chunk flush; a worker killed
  mid-unit (``kill -9`` mid-append included) stops renewing, its lease
  expires, and any peer reclaims and re-runs the unit — to identical
  record bytes, which the store's min-combine merge absorbs.
- **triage/shrink per unit**: when a unit's candidate summaries land,
  its violating seeds triage through the zero-recompile spec-as-data
  channel, and the FIRST instance of each fingerprint *within the unit*
  shrinks to a minimal ``FixedFaults`` schedule. Deliberately
  unit-pure: a worker never skips a shrink because the store already
  holds the fingerprint — that would make the merged bytes depend on
  work partitioning. Cross-worker dedup happens at merge time, by
  fingerprint key and canonical bytes, where it is deterministic.
- **regression gate**: every stored bug's ``(FixedFaults, seed)``
  replays through :func:`regression_gate` — same fingerprint, same
  canonical-history sha — at worker start, per ``fleet_smoke`` round,
  and in ``make stest``. A found bug can never be silently un-found.

The merged report (:func:`merged_report`) is computed from the merged
store view in unit-key order — a pure function of the union of records,
byte-identical across 1 vs N workers and across kill-and-reclaim runs
(the ``check_determinism.sh`` fleet leg pins this).
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..engine.faults import (
    FaultEnvelope,
    grid_params,
    spec_to_params,
    tile_params,
)
from .campaign import (
    CampaignConfig,
    mutate_spec,
    spec_from_dict,
    spec_to_dict,
    target_envelope,
)
from .shrink import shrink
from .store import KIND_BUG, KIND_CAND, CorpusStore
from .targets import Target
from .triage import triage_seed


def plan_unit(base_spec, ccfg: CampaignConfig, unit: int) -> List[object]:
    """Unit ``unit``'s candidates: ``ccfg.batch`` specs chained by
    mutation from ``base_spec`` under a unit-local rng — any process
    regenerates any unit identically, independent of every other unit.
    Unit 0 leads with the unmutated base (the campaign's round 0).

    This is the UNIFORM plan. With ``ccfg.scheduler="bandit"`` the
    worker loop plans through ``steer.plan_unit_steered`` instead: the
    unit's candidates come from a bandit primed with the merged store's
    per-family stats over COMPLETED planning generations (leasing is
    generation-gated below), so the plan stays a pure function any
    worker computes identically — adaptive, without giving up the
    partition invariance this function's unit-locality buys."""
    rng = random.Random(f"fleet:{ccfg.campaign_seed}:{unit}")
    k = max(1, ccfg.batch)
    specs: List[object] = []
    cur = base_spec
    for j in range(k):
        if unit == 0 and j == 0:
            specs.append(base_spec)
            continue
        cur = mutate_spec(cur, rng, ccfg.mutations_hi)
        specs.append(cur)
    return specs


def _pow2_env(n_events: int) -> FaultEnvelope:
    """The fixed-schedule replay envelope for an ``n_events`` schedule —
    the same width rule as the shrinker, so gate replays share its
    compiled traced program."""
    width = 4
    while width < n_events:
        width *= 2
    return FaultEnvelope(fixed=width)


def _history_sha(target: Target, fixed, seed: int) -> Optional[str]:
    """sha256 of the minimal repro's canonical history (seed-free,
    time-rank canonical — oracle/history.py), through the spec-as-data
    one-lane sweep. None when the target records no history."""
    import jax.numpy as jnp

    from ..engine import core as ecore
    from ..oracle import decode_seed
    from ..oracle.history import history_canonical_bytes

    env = _pow2_env(len(fixed.events))
    workload, ecfg = target.build(env)
    if target.hist_spec is None or workload.hist_slots == 0:
        return None
    final = ecore.run_sweep(
        workload, ecfg, jnp.asarray([seed], jnp.int64),
        params=tile_params(spec_to_params(fixed, env, target.num_nodes), 1),
    )
    return hashlib.sha256(
        history_canonical_bytes(decode_seed(final, 0))
    ).hexdigest()


def run_worker(
    target: Target,
    base_spec,
    ccfg: CampaignConfig,
    store: CorpusStore,
    units: int,
    *,
    history: bool = False,
    shrink_tests: int = 48,
    max_units: Optional[int] = None,
    skip_gate: bool = False,
    telemetry=None,
    steer_cfg=None,
    _crash_after_units: Optional[int] = None,
) -> dict:
    """One fleet worker: lease units, stream them, triage+shrink, store.

    Runs the regression gate first (every stored bug must still replay
    — ``skip_gate`` only for drills), then opens ONE ``stream_sweep``
    whose ``feed`` leases the next available unit whenever lanes run
    dry. Returns ``{"units": [...], "fingerprints": sorted [...],
    "gate": ...}`` for this worker's own share; the authoritative
    cross-worker result is :func:`merged_report` over the store.

    ``max_units`` caps how many units THIS worker leases (the smoke's
    solo-vs-fleet comparison); ``_crash_after_units`` is the crash
    drill: after storing that many units the process dies by
    ``os._exit`` mid-append, leaving a torn record and an unrenewed
    lease behind for a peer to quarantine/reclaim.

    ``ccfg.scheduler="bandit"`` turns on steered planning
    (docs/steering.md): units group into generations of
    ``steer_cfg.gen_units``, a unit only becomes leasable once every
    unit of all earlier generations is DONE, and its candidates come
    from ``steer.plan_unit_steered`` primed with the merged per-family
    stats of those completed generations — identical stats on any
    worker, so the adaptive plan keeps the fleet's partition
    invariance. A worker that finds the current generation fully
    leased elsewhere drains its own in-flight units first, then
    sleep-polls for the barrier (peer crashes resolve through the
    normal lease-expiry reclaim). Steered candidate records additionally
    carry their ``family`` key, which is what the stats fold reads.
    """
    from ..engine.stream import stream_sweep

    steered = ccfg.scheduler == "bandit"
    if steered:
        from .steer import SteerConfig, family_key, fold_family_stats, \
            plan_unit_steered

        scfg = steer_cfg if steer_cfg is not None else SteerConfig()

    gate = None
    if not skip_gate:
        gate = regression_gate(store, target, history=history)
        if not gate["ok"]:
            raise AssertionError(
                f"regression gate failed before work started: "
                f"{gate['mismatches']}"
            )

    envelope = target_envelope(target, base_spec)
    workload, ecfg = target.build(envelope)
    s = ccfg.seeds_per_round
    k = max(1, ccfg.batch)
    seed_range = np.arange(ccfg.seed0, ccfg.seed0 + s, dtype=np.int64)

    # mirrors sweep_candidate_grid: device screen per retirement cohort,
    # WGL checker over the suspects in the overlapped host phase
    screen_fn = None
    if target.hist_spec is not None:
        from ..oracle.screen import screen_for, screen_sweep

        if screen_for(target.hist_spec) is not None:
            def screen_fn(final):
                return screen_sweep(final, target.hist_spec)

    def host_work(final, *, lo, n, seeds, suspect, summary) -> dict:
        del lo, n, seeds
        cstats: dict = {}
        if suspect is not None:
            from ..oracle.check import violating_seeds

            vio = violating_seeds(
                final, target.hist_spec, screen=lambda _f: suspect,
                workers=ccfg.check_workers, stats=cstats,
            )
        else:
            vio = np.asarray(target.violating(final))
        out = {
            "violating_seeds": [int(x) for x in vio[: ccfg.max_recorded_seeds]]
        }
        if "violations" not in summary:
            out["violations"] = int(vio.size)
        # honest-verdict bookkeeping: lanes whose WGL search ran out of
        # state budget count as non-violating above, so the unit summary
        # carries the count (merge_summaries sums it across chunks)
        if cstats.get("budget_exceeded"):
            out["budget_exceeded"] = int(cstats["budget_exceeded"])
            if telemetry is not None:
                telemetry.count(
                    "oracle_budget_exceeded_total",
                    int(cstats["budget_exceeded"]),
                    help="WGL verdicts undecided at max_states",
                )
        return out

    fed: List[Tuple[int, List[object]]] = []  # feed order: (unit, specs)
    leases: Dict[int, object] = {}  # unit -> live Lease
    pending: Dict[int, List[Optional[dict]]] = {}  # unit -> K summaries
    unit_fams: Dict[int, List[int]] = {}  # steered: unit -> family masks
    my_units: List[int] = []
    my_fps: set = set()
    stored = 0  # units finalized by THIS worker (crash-drill counter)

    def heartbeat():
        for unit, lease in list(leases.items()):
            if not store.renew(lease):
                # reclaimed out from under us (we looked dead): the unit
                # is no longer ours to mark, but finishing the compute
                # and appending its records stays harmless — identical
                # bytes, min-combined at merge
                del leases[unit]
        if telemetry is not None and leases:
            telemetry.gauge(
                "fleet_leases_held", len(leases),
                help="units currently leased by this worker",
            )

    def _steer_limit() -> int:
        """The generation barrier: units are leasable only up to the
        end of the first generation containing a not-done unit, so the
        stats a later unit's plan consults are frozen before any worker
        can lease it."""
        g = max(1, scfg.gen_units)
        first_open = units
        for u in range(units):
            if not store.is_done(u):
                first_open = u
                break
        return min(units, (first_open // g + 1) * g)

    def _steer_stats(unit: int) -> dict:
        """Per-family stats over the COMPLETED generations below
        ``unit`` — a pure function of their (immutable, min-combined)
        records, identical on every worker by the generation barrier."""
        cutoff = (unit // max(1, scfg.gen_units)) * max(1, scfg.gen_units)
        if cutoff == 0:
            return {}
        merged = store.merged()
        return fold_family_stats(
            [
                (key, p)
                for (kind, key), p in merged.items()
                if kind == KIND_CAND and int(p["unit"]) < cutoff
            ],
            [
                (key, p)
                for (kind, key), p in merged.items()
                if kind == KIND_BUG and int(p["unit"]) < cutoff
            ],
        )

    def acquire() -> Optional[dict]:
        """Lease the next unit and build its feed segment."""
        if max_units is not None and len(my_units) >= max_units:
            return None
        while True:
            limit = _steer_limit() if steered else units
            lease = store.next_lease(limit)
            if lease is None:
                if steered and limit < units:
                    # the open generation is leased elsewhere and later
                    # units are barrier-gated: drain our own in-flight
                    # units first (their finalize may complete the
                    # generation), else wait for peers / lease expiry
                    if pending:
                        return None
                    if store.all_done(units):
                        return None
                    if telemetry is not None:
                        telemetry.count(
                            "steer_gen_waits_total",
                            help="generation-barrier waits while peers "
                            "finish the open generation",
                        )
                    time.sleep(0.05)
                    heartbeat()
                    continue
                return None
            if lease.unit in pending:
                # our own expired lease came back through the reclaim
                # path: re-hold it, don't feed the unit a second time
                leases[lease.unit] = lease
                continue
            break
        if steered:
            planned = plan_unit_steered(
                base_spec, ccfg, scfg, lease.unit, _steer_stats(lease.unit)
            )
            unit_fams[lease.unit] = [m for m, _ in planned]
            specs = [sp for _, sp in planned]
        else:
            specs = plan_unit(base_spec, ccfg, lease.unit)
        fed.append((lease.unit, specs))
        leases[lease.unit] = lease
        pending[lease.unit] = [None] * k
        my_units.append(lease.unit)
        if telemetry is not None:
            telemetry.event("fleet_lease", unit=lease.unit)
        return {
            "seeds": np.tile(seed_range, k),
            "params": grid_params(
                [
                    spec_to_params(sp, envelope, target.num_nodes)
                    for sp in specs
                ],
                s,
            ),
        }

    def finalize(unit: int, specs: List[object]) -> None:
        """All K summaries for ``unit`` landed: store its candidate
        records and its bugs (first instance per fingerprint WITHIN the
        unit, triaged + shrunk), then retire the lease. A pure function
        of the unit — store content never influences what gets written
        (partition invariance)."""
        nonlocal stored
        summaries = pending.pop(unit)
        fams = unit_fams.pop(unit, None)
        unit_fps: Dict[str, Tuple[int, object, int]] = {}
        for ci, (spec, summary) in enumerate(zip(specs, summaries)):
            vio = summary.get("violating_seeds", [])
            payload = {
                "unit": unit,
                "cand": ci,
                "spec": spec_to_dict(spec),
                "violations": int(summary["violations"]),
                "violating_seeds": [int(x) for x in vio],
                "coverage_map": [
                    int(w) for w in summary.get("coverage_map", [])
                ],
                "events_total": int(summary.get("events_total", 0)),
            }
            if fams is not None:
                # the steered plan's family attribution — what
                # fold_family_stats reads back; uniform payloads stay
                # byte-identical to the pre-steering format
                payload["family"] = family_key(fams[ci])
            store.append(KIND_CAND, f"{unit:06d}/{ci:02d}", payload)
            for seed in vio:
                f = triage_seed(
                    target, envelope, int(seed), history=history,
                    params=spec_to_params(spec, envelope, target.num_nodes),
                )
                if f is not None and f.fingerprint not in unit_fps:
                    unit_fps[f.fingerprint] = (ci, spec, int(seed))
        for fp in sorted(unit_fps):
            ci, spec, seed = unit_fps[fp]
            sr = shrink(
                target, spec, seed, max_tests=shrink_tests, history=history
            )
            payload = {
                "fingerprint": fp,
                "unit": unit,
                "cand": ci,
                "seed": seed,
                "spec": spec_to_dict(spec),
                "fixed": None if sr is None else spec_to_dict(sr.spec),
                "schedule": None
                if sr is None
                else [[t, a, v] for t, a, v in sr.schedule],
                "original_len": None if sr is None else sr.original_len,
                "history_sha": None
                if sr is None
                else _history_sha(target, sr.spec, seed),
            }
            store.append(KIND_BUG, fp, payload)
            my_fps.add(fp)
        stored += 1
        if _crash_after_units is not None and stored >= _crash_after_units:
            # the kill -9 drill: die mid-append — a torn half-record on
            # our log, done marker never written, lease left to expire
            import os as _os

            if store._log_f is None:
                store._log_f = open(store._log_path, "a")
            store._log_f.write('{"kind": "bug", "key": "torn-')
            store._log_f.flush()
            _os.fsync(store._log_f.fileno())
            _os._exit(137)
        store.mark_done(unit)
        lease = leases.pop(unit, None)
        if lease is not None:
            store.release(lease)
        if telemetry is not None:
            telemetry.count("fleet_units_done_total", help="units finalized")
            telemetry.event(
                "fleet_unit_done", unit=unit,
                fingerprints=sorted(unit_fps),
            )

    def on_chunk(*, lo, k: int, summary):  # noqa: ANN001 - stream contract
        heartbeat()
        c = lo // s
        unit, specs = fed[c // max(1, ccfg.batch)]
        pending[unit][c % max(1, ccfg.batch)] = summary
        if all(x is not None for x in pending[unit]):
            finalize(unit, specs)

    def feed() -> Optional[dict]:
        heartbeat()
        return acquire()

    first = acquire()
    if first is not None:
        stream_sweep(
            workload, ecfg, first["seeds"], target.summarize,
            params=first["params"], chunk_size=s,
            pool_size=max(ccfg.chunk_size, s),
            host_work=host_work, screen=screen_fn,
            on_chunk=on_chunk, feed=feed, telemetry=telemetry,
        )
    store.close()
    return {
        "worker": store.worker,
        "units": my_units,
        "fingerprints": sorted(my_fps),
        "gate": gate,
    }


def merged_report(store: CorpusStore) -> str:
    """The byte-deterministic fleet report: one JSONL string computed
    from the merged store view in unit-key order — coverage accounting
    (new_bits / retained / coverage_total_bits) folds at MERGE time, so
    the bytes are identical for any worker count, any lease schedule,
    and any kill-and-reclaim history over the same plan."""
    merged = store.merged()
    cands = sorted(
        (key, p) for (kind, key), p in merged.items() if kind == KIND_CAND
    )
    bugs = sorted(
        (key, p) for (kind, key), p in merged.items() if kind == KIND_BUG
    )
    lines = [
        json.dumps(
            {
                "kind": "fleet_header",
                "cands": len(cands),
                "bugs": len(bugs),
            },
            sort_keys=True,
        )
    ]
    global_map: List[int] = []
    for key, p in cands:
        cand_map = [int(w) for w in p.get("coverage_map", [])]
        if len(global_map) < len(cand_map):
            global_map += [0] * (len(cand_map) - len(global_map))
        new_bits = sum(
            (c & ~g).bit_count() for c, g in zip(cand_map, global_map)
        )
        retained = (key == "000000/00") or new_bits > 0
        if retained:
            global_map = [g | c for g, c in zip(global_map, cand_map)]
        rec = {k: v for k, v in p.items() if k != "coverage_map"}
        rec.update(
            kind="cand",
            key=key,
            new_bits=new_bits,
            retained=retained,
            coverage_total_bits=sum(w.bit_count() for w in global_map),
        )
        lines.append(json.dumps(rec, sort_keys=True))
    for key, p in bugs:
        lines.append(
            json.dumps({**p, "kind": "bug", "key": key}, sort_keys=True)
        )
    return "\n".join(lines) + "\n"


def write_merged(store: CorpusStore, path: str) -> None:
    with open(path, "w") as f:
        f.write(merged_report(store))


def regression_gate(
    store: CorpusStore, target: Target, *, history: bool = False
) -> dict:
    """Replay every stored bug's minimal ``(FixedFaults, seed)`` triple
    bit-exactly: the triage fingerprint must match the stored one, and
    the canonical-history sha (when recorded) must recompute
    identically. Returns ``{"checked", "skipped", "ok", "mismatches"}``
    — a mismatch means a previously found bug would now be silently
    un-found, which is exactly what the gate exists to catch."""
    merged = store.merged()
    bugs = sorted(
        (key, p) for (kind, key), p in merged.items() if kind == KIND_BUG
    )
    checked = skipped = 0
    mismatches: List[dict] = []
    for key, p in bugs:
        if p.get("fixed") is None:
            skipped += 1  # shrink failed at store time; nothing replayable
            continue
        fixed = spec_from_dict(p["fixed"])
        seed = int(p["seed"])
        env = _pow2_env(len(fixed.events))
        f = triage_seed(
            target, env, seed, history=history,
            params=spec_to_params(fixed, env, target.num_nodes),
        )
        checked += 1
        if f is None or f.fingerprint != p["fingerprint"]:
            mismatches.append(
                {
                    "fingerprint": p["fingerprint"],
                    "seed": seed,
                    "got": None if f is None else f.fingerprint,
                    "why": "no longer violates" if f is None
                    else "fingerprint changed",
                }
            )
            continue
        want_sha = p.get("history_sha")
        if want_sha is not None:
            got_sha = _history_sha(target, fixed, seed)
            if got_sha != want_sha:
                mismatches.append(
                    {
                        "fingerprint": p["fingerprint"],
                        "seed": seed,
                        "got": got_sha,
                        "why": "canonical history diverged",
                    }
                )
    return {
        "checked": checked,
        "skipped": skipped,
        "ok": not mismatches,
        "mismatches": mismatches,
    }
