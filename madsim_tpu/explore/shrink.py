"""Schedule shrinking: ddmin a failure down to a minimal replayable triple.

A campaign failure arrives as ``(spec, seed)`` — a fault environment plus
the seed whose schedule broke the workload. The shrinker turns that into
the smallest artifact that still reproduces:

1. extract the seed's *fired* fault schedule from a bit-exact CPU
   ``run_traced`` replay (exact payload-carried deadlines);
2. refit it as a literal ``FixedFaults`` schedule — injecting the same
   events at the same deadlines reproduces the identical trajectory, so
   this step is verified, not assumed;
3. ddmin (Zeller/Hildebrandt delta debugging) over the event list: each
   candidate subset re-verifies by CPU replay through ``triage_seed`` and
   survives iff the SAME failure fingerprint latches — never merely
   "some failure";
4. the result is 1-minimal: removing any single remaining event loses
   the failure (the ddmin guarantee when it terminates normally).

Every reported failure thus lands as a minimal ``(spec, seed, schedule)``
triple that ``scripts/replay_seed.py`` (device tier) and
``madsim_tpu.faults.apply_schedule`` (host tier) consume directly.

``narrow_windows`` is the campaign-side counterpart: clamp a spec's
windows to just cover a shrunk schedule's fire times (and drop categories
that contributed nothing), focusing the NEXT exploration rounds. A
narrowed spec redraws its schedule, so it is not seed-stable — the
``FixedFaults`` triple is the reproducing artifact; the narrowed spec is
a better search start.

Cost model: ddmin candidates replay through the spec-as-data channel
(engine/faults.py): the traced program compiles ONCE per
``FaultEnvelope(fixed=W)`` width — ``W`` is the original schedule
length rounded up to a power of two, so every candidate subset of every
comparably-sized failure shares it — and each candidate rides in as
runtime ``FaultParams`` (bit-identical replay to a static ``FixedFaults``
config; tests/test_fault_params.py). This replaced the
compile-per-candidate cost model that used to dominate shrink
wall-clock (one jit cache entry per distinct candidate config, seconds
each on CPU); ``max_tests`` still bounds the replay count because each
replay costs a real traced run either way.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

from ..engine import core as ecore
from ..engine.faults import FixedFaults
from ..replay import FaultEvent, extract_fault_schedule
from .targets import Target
from .triage import Failure, triage_seed

# (spec count field, window field, schedule "on" action) per category
_CATEGORIES = (
    ("crashes", "crash_window_ns", "crash"),
    ("partitions", "part_window_ns", "partition"),
    ("spikes", "spike_window_ns", "spike_on"),
    ("losses", "loss_window_ns", "loss_on"),
    ("pauses", "pause_window_ns", "pause"),
)


class ShrinkResult(NamedTuple):
    """A minimal, re-verified failure artifact."""

    spec: FixedFaults  # run this (any tier, any seed-stability concern gone)
    seed: int  # the engine seed the workload draws flow from
    schedule: Tuple[FaultEvent, ...]  # == spec.events, time-sorted
    fingerprint: str  # the failure class this still reproduces
    failure: Failure  # triage of the minimal replay
    tests: int  # CPU replays the shrink spent
    original_len: int  # fired-schedule length before shrinking


def to_fixed(spec, events: Sequence[FaultEvent]) -> FixedFaults:
    """Refit a schedule as a literal spec, carrying over the burst and
    clock-skew override values (both spec flavors have them)."""
    return FixedFaults(
        events=tuple(events),
        spike_lat_lo_ns=spec.spike_lat_lo_ns,
        spike_lat_hi_ns=spec.spike_lat_hi_ns,
        burst_loss_q32=spec.burst_loss_q32,
        skew_num=spec.skew_num,
        skew_den=spec.skew_den,
    )


def ddmin(
    events: List[FaultEvent],
    test: Callable[[List[FaultEvent]], bool],
    max_tests: int = 64,
    spent: Optional[Callable[[], int]] = None,
) -> Tuple[List[FaultEvent], int]:
    """Classic ddmin over a fault-event list. ``test`` must hold for
    ``events`` on entry; returns the reduced list (1-minimal unless the
    ``max_tests`` budget ran out first) and the budget consumed.

    ``spent`` overrides the budget meter: pass a callable returning the
    REAL cost so far (e.g. cache-missing replays only) so memoized
    re-tests of an already-tried subset don't burn budget; the default
    meter counts every ``test`` call."""
    n = 2
    calls = 0
    used = spent if spent is not None else lambda: calls
    while len(events) >= 2 and used() < max_tests:
        size = len(events)
        chunk = (size + n - 1) // n
        reduced = False
        for lo in range(0, size, chunk):
            cand = events[:lo] + events[lo + chunk :]
            calls += 1
            if test(cand):
                events = cand
                n = max(n - 1, 2)
                reduced = True
                break
            if used() >= max_tests:
                break
        if not reduced:
            if n >= size:
                break
            n = min(size, 2 * n)
    return events, used()


def shrink(
    target: Target, spec, seed: int, max_tests: int = 64,
    history: bool = False,
) -> Optional[ShrinkResult]:
    """Shrink one ``(spec, seed)`` failure to a minimal verified triple.

    Returns None when the seed does not violate under ``spec``, or when
    the refit literal schedule fails to reproduce the fingerprint (a
    schedule event past the engine horizon would be the usual cause —
    see ``replay.extract_fault_schedule``).

    ``history=True`` re-verifies every ddmin candidate through the
    history oracle (``triage_seed(..., history=True)`` — decode the
    candidate replay's op history, reject iff the linearizability
    checker still rejects with the same fingerprint) instead of the
    model probe; the resulting minimal triple is thus checker-verified,
    not probe-verified."""
    f0 = triage_seed(target, spec, seed, history=history)
    if f0 is None:
        return None
    workload, ecfg = target.build(spec)
    _, trace = ecore.run_traced(workload, ecfg, seed)
    full = extract_fault_schedule(trace, target.fault_kind)

    # spec-as-data replay channel: one traced program per envelope WIDTH
    # (len(full) rounded up to a power of two — candidates are subsets,
    # and comparably-sized failures share the program), each candidate
    # fed in as runtime FaultParams
    from ..engine.faults import FaultEnvelope, spec_to_params

    width = 4
    while width < len(full):
        width *= 2
    env = FaultEnvelope(fixed=width)

    # memoize replays by event tuple: ddmin's regranulation can revisit a
    # subset, and the final verification is always the last accepted
    # test — each replay costs a real traced run (see the module cost
    # note), so none repeats and only real replays burn the max_tests
    # budget
    replayed: dict = {}

    def run(events: List[FaultEvent]) -> Optional[Failure]:
        key = tuple(events)
        if key not in replayed:
            fixed = to_fixed(spec, events)
            replayed[key] = triage_seed(
                target, env, seed, history=history,
                params=spec_to_params(fixed, env, target.num_nodes),
            )
        return replayed[key]

    def reproduces(events: List[FaultEvent]) -> bool:
        f = run(events)
        return f is not None and f.fingerprint == f0.fingerprint

    if not reproduces(full):
        return None
    minimal, _ = ddmin(
        full, reproduces, max_tests=max_tests, spent=lambda: len(replayed)
    )
    fixed = to_fixed(spec, minimal)
    final = run(minimal)  # cached: ddmin only returns verified subsets
    assert final is not None and final.fingerprint == f0.fingerprint
    return ShrinkResult(
        spec=fixed,
        seed=int(seed),
        schedule=fixed.events,
        fingerprint=f0.fingerprint,
        failure=final,
        tests=len(replayed),  # distinct replays actually executed
        original_len=len(full),
    )


def narrow_windows(spec, schedule: Sequence[FaultEvent]):
    """Clamp a ``FaultSpec``'s campaign windows to just cover a (shrunk)
    schedule's fire times; categories that contributed no event drop to
    zero phases. The result redraws (NOT seed-stable — the literal
    ``FixedFaults`` is the reproducing artifact); use it to focus the
    next campaign rounds on the neighborhood that already failed."""
    if isinstance(spec, FixedFaults):
        raise TypeError("narrow_windows narrows FaultSpec campaigns; a "
                        "FixedFaults schedule has no windows to narrow")
    ons = {action: [] for _, _, action in _CATEGORIES}
    for t, action, _ in schedule:
        if action in ons:
            ons[action].append(t)
    updates = {}
    for count_f, window_f, action in _CATEGORIES:
        if not getattr(spec, count_f):
            continue
        times = ons[action]
        if times:
            updates[window_f] = max(times) + 1
        else:
            updates[count_f] = 0
    return spec._replace(**updates)
