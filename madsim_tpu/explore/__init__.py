"""Coverage-guided fault-campaign exploration: find -> triage -> shrink.

The sweep engine can run a million seeds under a declarative ``FaultSpec``
and replay any one bit-exactly — this package *drives* that capacity
toward bugs, the search loop FoundationDB-style simulation testing earns
its keep through (AFL-style corpus guidance; Groce et al., *Swarm
Testing*):

- ``campaign`` — the corpus loop: mutate ``FaultSpec``s via seeded draws,
  sweep each candidate, retain specs that light new coverage bits (the
  engine's per-seed (kind x node x transition) bitmap, folded into the
  chunk summary as ``coverage_map``), and report every violating seed.
- ``triage`` — bucket violating seeds by failure fingerprint (violation
  flavor + first-violation event signature from ``run_traced``; or, with
  ``history=True``, the op ending the first non-linearizable prefix of
  the seed's recorded history — the madsim_tpu/oracle flavor), so
  thousands of red seeds dedupe to a handful of distinct failures.
- ``shrink`` — ddmin-reduce the extracted fault schedule to a minimal
  ``FixedFaults`` literal that still reproduces the same fingerprint
  under bit-exact CPU replay, plus campaign-window narrowing for the
  next exploration round.
- ``targets`` — the model adapters a campaign explores (the canonical
  one: the amnesia Raft config, ``replay.amnesia_raft_config``).
- ``fleet`` — fleet scale: device-count throughput/time-to-first-bug
  curves and million-seed campaigns routed through the sharded
  pipelined driver (``parallel.mesh``; see ``docs/multichip.md``).
- ``store`` / ``orchestrator`` — the crash-safe fleet tier: a shared
  byte-deterministic corpus/bug store (sha-guarded append-only logs,
  quarantine, expiring leases) and the leased-unit worker loop feeding
  ``stream_sweep`` in flight, with the regression-replay gate that
  keeps every stored bug reproducing forever (``docs/fleet.md``).
- ``steer`` — the self-steering scheduler (``docs/steering.md``):
  candidate families (mutation lineage + fault-category bitmask), a
  deterministic UCB bandit allocating device-seconds by
  novel-coverage-bits-per-event, early-kill of dedup-saturated
  families, budget escalation near a first violation — with every
  decision journaled and byte-reproducible
  (``CampaignConfig.scheduler="bandit"``).
- ``differential`` — host↔device differential validation: run the
  device raft model and ``examples/raft_host.py`` over matched
  ``(spec, seed)`` grids (one compiled fault schedule drives both
  tiers), compare outcome distributions within tolerances, and check
  both tiers' recorded election histories against one sequential spec
  (``oracle.specs.ElectionSpec``).

See ``docs/explore.md`` for the full pipeline and guarantees;
``scripts/explore_demo.py`` runs it end to end on the CPU backend.
"""

from .campaign import (  # noqa: F401
    CampaignConfig,
    CampaignResult,
    mutate_spec,
    run_campaign,
    spec_from_dict,
    spec_to_dict,
    sweep_candidate_grid,
    target_envelope,
)
from .fleet import checked_sweep_curve, sharded_campaign  # noqa: F401
from .orchestrator import (  # noqa: F401
    merged_report,
    plan_unit,
    regression_gate,
    run_worker,
    write_merged,
)
from .steer import (  # noqa: F401
    BanditScheduler,
    SteerConfig,
    SteerResult,
    family_candidate,
    family_key,
    family_of,
    family_universe,
    fold_family_stats,
    plan_unit_steered,
    run_steered,
)
from .store import CorpusStore, Lease, ReadStats  # noqa: F401
from .differential import (  # noqa: F401
    DifferentialConfig,
    TierOutcome,
    device_outcomes,
    device_outcomes_grid,
    gate_specs,
    host_outcomes,
    run_differential,
)
from .shrink import ShrinkResult, narrow_windows, shrink  # noqa: F401
from .targets import (  # noqa: F401
    Target,
    amnesia_raft_target,
    etcd_steer_gate,
    stale_etcd_target,
    steer_gate,
)
from .triage import (  # noqa: F401
    HISTORY_FLAVOR,
    Failure,
    fingerprint_counts,
    triage,
    triage_seed,
)
