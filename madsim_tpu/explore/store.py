"""Crash-safe shared corpus/bug store + the fleet lease protocol.

This is the durable half of the fleet orchestrator (docs/fleet.md): a
directory that any number of worker processes on a shared filesystem can
read and write concurrently, survive ``kill -9`` at ANY instruction, and
still merge to one byte-deterministic corpus. Two mechanisms, chosen so
that neither ever needs a lock:

**Records** are append-only, per-worker, sha-guarded JSONL. Each worker
owns exactly one log file (``log/<worker>.jsonl``) — no write ever
contends — and each line is ``{"kind", "key", "payload", "sha"}`` with
``sha`` the SHA-256 of the payload's canonical JSON bytes. Readers
verify every line: a torn/partial FINAL line (a writer killed
mid-append) is normal operating data — the valid prefix is kept and the
tail dropped; a sha mismatch or malformed interior line is QUARANTINED
(copied to ``quarantine/``, skipped, counted — never fatal). The merged
view is a pure function of the union of valid records: duplicates of one
``(kind, key)`` combine by *minimum canonical payload bytes* (after a
record-kind sort key), so re-running a reclaimed batch, double-running a
batch whose lease was stolen from a paused worker, or merging any
partition of the work into any number of logs all converge to the SAME
bytes — worker-count- and crash-schedule-invariant BY CONSTRUCTION.

**Leases** partition the work units. A grant is ``O_CREAT|O_EXCL`` on
``leases/unit_<n>.lease`` — POSIX guarantees exactly one winner, so a
double grant of a live lease is impossible. Liveness is the lease
file's mtime: ``renew`` bumps it with ``os.utime`` (path-based, so a
renewal after a reclaim's rename fails with ENOENT and reports the
lease LOST rather than resurrecting it). A lease whose mtime is older
than the TTL is expired: any worker may reclaim it by *renaming* it
aside (again exactly one winner) and re-granting. ``done/`` markers are
written atomically after a unit's records are durably appended; a
worker that dies mid-unit leaves no marker, so its unit is reclaimed
and re-run — to identical record bytes, which the min-combine merge
absorbs. Leases are therefore a work-partitioning *optimization*;
correctness (determinism, no lost or duplicated results in the merged
view) rests entirely on the record layer.

Telemetry (``obs.Telemetry``, optional) counts grants, renewals,
reclaims, appends and quarantined lines — wall-clock-side only, never a
report byte (the fleet determinism leg byte-diffs merged reports with
and without it implicitly, since the merge never reads metrics).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

# record kinds the orchestrator writes; the store itself is agnostic —
# anything JSON-able dedups by (kind, key)
KIND_CAND = "cand"  # one swept candidate's summary (unit-partitioned)
KIND_BUG = "bug"  # one triaged+shrunk failure class (fingerprint-keyed)


def canonical_bytes(payload) -> bytes:
    """The byte encoding every guard and tie-break hashes/compares:
    sorted keys, no whitespace — any JSON-able payload, one byte string,
    identical across platforms and processes."""
    return json.dumps(
        payload, sort_keys=True, separators=(",", ":")
    ).encode()


def payload_sha(payload) -> str:
    return hashlib.sha256(canonical_bytes(payload)).hexdigest()


class Lease(NamedTuple):
    """A held work-unit lease (see module docstring for the protocol)."""

    unit: int
    path: str
    token: str  # random per-grant identity: survives worker-name reuse
    worker: str


class ReadStats(NamedTuple):
    """What scanning the logs saw (quarantine drills assert on these)."""

    lines: int  # valid records returned
    quarantined: int  # sha-mismatch / malformed interior lines skipped
    truncated_logs: int  # logs ending in a torn partial line


class CorpusStore:
    """One store directory; any number of concurrent worker handles.

    ``worker`` names this handle's own append log (default: a fresh
    pid+random name — two handles never share a log). ``ttl_s`` is the
    lease liveness window: a worker that neither retires its unit nor
    renews within it is presumed dead and its unit reclaimed.
    """

    def __init__(
        self,
        root: str,
        worker: Optional[str] = None,
        *,
        ttl_s: float = 30.0,
        telemetry=None,
    ):
        self.root = root
        self.worker = worker or f"w{os.getpid()}-{os.urandom(3).hex()}"
        if "/" in self.worker or self.worker.startswith("."):
            raise ValueError(f"worker name {self.worker!r} must be a filename")
        self.ttl_s = float(ttl_s)
        self.telemetry = telemetry
        for sub in ("log", "leases", "done", "quarantine"):
            os.makedirs(os.path.join(root, sub), exist_ok=True)
        self._log_path = os.path.join(root, "log", f"{self.worker}.jsonl")
        self._log_f = None

    # -- record layer -------------------------------------------------------

    def append(self, kind: str, key: str, payload) -> None:
        """Append one sha-guarded record to this worker's own log and
        flush+fsync it — after this returns, the record survives
        ``kill -9`` (a kill DURING it leaves at most a torn final line,
        which readers drop)."""
        if self._log_f is None:
            self._log_f = open(self._log_path, "a")
        line = json.dumps(
            {
                "kind": kind,
                "key": key,
                "payload": payload,
                "sha": payload_sha(payload),
            },
            sort_keys=True,
        )
        self._log_f.write(line + "\n")
        self._log_f.flush()
        os.fsync(self._log_f.fileno())
        if self.telemetry is not None:
            self.telemetry.count(
                "fleet_records_appended_total",
                help="records appended to this worker's store log",
            )

    def close(self) -> None:
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None

    def _quarantine(self, log_name: str, raw_line: str, why: str) -> None:
        """Copy one bad line aside (append-only, per source log) so a
        post-mortem can inspect it; the read path just skips it."""
        qpath = os.path.join(self.root, "quarantine", log_name)
        with open(qpath, "a") as f:
            f.write(json.dumps({"why": why, "line": raw_line}) + "\n")
        if self.telemetry is not None:
            self.telemetry.count(
                "fleet_store_quarantined_total",
                help="corrupted store records quarantined (sha mismatch "
                "or malformed interior line)",
            )

    def read_records(self) -> Tuple[List[dict], ReadStats]:
        """Every valid record across every worker log (file order by
        name, line order within a file), plus what the scan saw.

        Never raises on bad data: a torn final line is dropped (the
        writer died mid-append), anything else that fails its sha or its
        JSON parse is quarantined and skipped."""
        records: List[dict] = []
        quarantined = 0
        truncated_logs = 0
        log_dir = os.path.join(self.root, "log")
        for name in sorted(os.listdir(log_dir)):
            if not name.endswith(".jsonl"):
                continue
            with open(os.path.join(log_dir, name)) as f:
                lines = f.read().split("\n")
            for i, raw in enumerate(lines):
                if not raw.strip():
                    continue
                rec = None
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    if i == len(lines) - 1:
                        # torn final line: the valid prefix stands
                        truncated_logs += 1
                        continue
                    self._quarantine(name, raw, "malformed")
                    quarantined += 1
                    continue
                if (
                    not isinstance(rec, dict)
                    or "payload" not in rec
                    or rec.get("sha") != payload_sha(rec["payload"])
                ):
                    self._quarantine(name, raw, "sha mismatch")
                    quarantined += 1
                    continue
                records.append(rec)
        return records, ReadStats(len(records), quarantined, truncated_logs)

    def merged(self) -> Dict[Tuple[str, str], dict]:
        """The deterministic merged view: ``(kind, key) -> payload``,
        duplicates combined by minimum canonical payload bytes — a pure
        function of the SET of valid records, so any partition of the
        work over any number of logs (including partial re-runs from
        reclaimed leases) merges to identical bytes."""
        out: Dict[Tuple[str, str], dict] = {}
        best: Dict[Tuple[str, str], bytes] = {}
        records, _ = self.read_records()
        for rec in records:
            k = (str(rec.get("kind")), str(rec.get("key")))
            b = canonical_bytes(rec["payload"])
            if k not in best or b < best[k]:
                best[k] = b
                out[k] = rec["payload"]
        return out

    # -- lease layer --------------------------------------------------------

    def _lease_path(self, unit: int) -> str:
        return os.path.join(self.root, "leases", f"unit_{unit:06d}.lease")

    def _done_path(self, unit: int) -> str:
        return os.path.join(self.root, "done", f"unit_{unit:06d}.done")

    def is_done(self, unit: int) -> bool:
        return os.path.exists(self._done_path(unit))

    def mark_done(self, unit: int) -> None:
        """Atomic done marker (tmp + rename): written only AFTER the
        unit's records are appended and fsynced, so a crash between the
        two re-runs the unit (harmless: identical record bytes)."""
        path = self._done_path(unit)
        tmp = f"{path}.tmp.{self.worker}"
        with open(tmp, "w") as f:
            json.dump({"unit": unit, "worker": self.worker}, f)
        os.replace(tmp, path)

    def try_lease(self, unit: int) -> Optional[Lease]:
        """One grant attempt: None when the unit is done, currently
        leased and live, or lost the O_EXCL race; a Lease on success.
        An EXPIRED lease (mtime older than ``ttl_s``) is reclaimed
        first — renamed aside (exactly one winner) — then re-granted."""
        if self.is_done(unit):
            return None
        path = self._lease_path(unit)
        try:
            age = time.time() - os.stat(path).st_mtime
        except FileNotFoundError:
            age = None
        if age is not None:
            if age <= self.ttl_s:
                return None
            # expired: exactly one renamer wins the reclaim
            stale = f"{path}.stale.{os.urandom(4).hex()}"
            try:
                os.rename(path, stale)
            except FileNotFoundError:
                return None  # someone else reclaimed (or released) first
            os.unlink(stale)
            if self.telemetry is not None:
                self.telemetry.count(
                    "fleet_lease_reclaimed_total",
                    help="expired leases reclaimed from presumed-dead "
                    "workers",
                )
        token = os.urandom(8).hex()
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return None  # lost the grant race
        with os.fdopen(fd, "w") as f:
            json.dump({"worker": self.worker, "token": token}, f)
            f.flush()
            os.fsync(f.fileno())
        if self.telemetry is not None:
            self.telemetry.count(
                "fleet_lease_granted_total", help="work-unit leases granted"
            )
        return Lease(unit, path, token, self.worker)

    def _owns(self, lease: Lease) -> bool:
        try:
            with open(lease.path) as f:
                rec = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return False
        return rec.get("token") == lease.token

    def renew(self, lease: Lease) -> bool:
        """Heartbeat: bump the lease's mtime. False = the lease was
        reclaimed out from under us (the worker was presumed dead) —
        the caller must treat the unit as no longer theirs. Path-based
        on purpose: after a reclaim's rename there is nothing at
        ``lease.path`` (or a new holder's file with a different token),
        so a zombie's renewal can never resurrect its old lease."""
        if not self._owns(lease):
            return False
        try:
            os.utime(lease.path)
        except FileNotFoundError:
            return False
        if self.telemetry is not None:
            self.telemetry.count(
                "fleet_lease_renewed_total",
                help="lease heartbeat renewals",
            )
        return True

    def release(self, lease: Lease) -> None:
        """Drop a held lease (after ``mark_done``, or on abandon). Only
        removes the file while it is still ours."""
        if self._owns(lease):
            try:
                os.unlink(lease.path)
            except FileNotFoundError:
                pass

    def next_lease(self, units: int) -> Optional[Lease]:
        """Scan units in order and grant the first available one; None
        when every unit is done or live-leased by someone else."""
        for unit in range(units):
            lease = self.try_lease(unit)
            if lease is not None:
                return lease
        return None

    def all_done(self, units: int) -> bool:
        return all(self.is_done(u) for u in range(units))
