"""Host↔device differential validation: the "does the TPU sweep find
what host DST finds?" loop, closed (ROADMAP item 5 / VERDICT "Next
round" #3).

The repo holds two independent implementations of the same workload —
the device Raft model (``models/raft.py``, amnesia mode) and the host
executor's Raft example (``examples/raft_host.py``, ordinary async code
whose in-memory state IS amnesia) — and, since the FaultSpec compiler,
one declarative fault campaign drives both. Jepsen's differential idiom
then applies directly: run BOTH implementations over a matched
``(spec, seed)`` grid — the same compiled fault schedule per seed — and
require

1. **matched outcome distributions**: the per-seed election/no-leader/
   violation rates of the two tiers agree within documented tolerances
   (two engines cannot share an RNG stream, so individual seeds differ;
   the distributions must not);
2. **one sequential spec for both histories**: each tier records its
   elections as an op history (device: the ``record`` hook; host:
   ``oracle.HostRecorder``) and BOTH are checked against
   ``oracle.specs.ElectionSpec`` — per seed, per tier, the checker's
   verdict must agree exactly with that tier's own online violation
   latch (the checker cross-validates the latches, and vice versa);
3. **byte-deterministic reports**: the JSON report carries only integer
   counts and sorted keys — two processes running one grid must emit
   identical bytes (``scripts/check_determinism.sh`` gates this).

Tolerances are in per-mille of the seed count. Defaults are sized from
measured tier gaps (docs/faults.md worked example): election presence
and no-leader rates track within a few percent; violation rates differ
more (the host example polls its election deadline at 10 ms granularity
and has no log, so its double-vote window differs) and get a wider
band. ``scripts/differential_demo.py`` runs the gate grid — ≥200 seeds,
at least one spec per gray-failure family — as ``make
differential-smoke``.
"""

from __future__ import annotations

import json
from typing import List, NamedTuple, Optional, Sequence

import numpy as np

from ..engine import core as ecore
from ..engine.faults import FaultSpec
from ..oracle import ElectionSpec, check_history, decode_sweep
from .campaign import spec_to_dict


class DifferentialConfig(NamedTuple):
    """Grid parameters (hashable, reprs stably)."""

    num_nodes: int = 3
    seeds: int = 200
    seed0: int = 0
    sim_seconds: float = 2.0
    chunk_size: int = 16384
    # device raft sizing: the election-safety ring and the history
    # buffer must cover every election of a seed, else the online latch
    # and the checker see different data (overflows are surfaced in the
    # report and fail the gate)
    history_ring: int = 64
    hist_slots: int = 128
    # per-mille tolerances on |device - host| outcome rates
    tol_elected_pm: int = 100
    tol_no_leader_pm: int = 100
    tol_violation_pm: int = 300


class TierOutcome(NamedTuple):
    """One tier's per-seed outcomes over the grid (integer counts)."""

    elected_seeds: int  # seeds with >= 1 election
    no_leader_seeds: int
    violation_seeds: int  # that tier's own online latch
    elections_total: int
    commits_total: int  # device only (the host example is election-only)
    hist_reject_seeds: int  # seeds whose history fails ElectionSpec
    hist_mismatch_seeds: int  # checker verdict != online latch
    hist_overflow_seeds: int
    # device only: lanes whose event queue overflowed — a truncated lane
    # under-counts outcomes, so any overflow fails the gate
    overflow_seeds: int = 0


def _pm(count: int, total: int) -> int:
    """Integer per-mille — float-free so reports are byte-stable."""
    return 1000 * count // total


def _device_raft_cfg(faults, dcfg: DifferentialConfig):
    """The device raft model of the differential grid (amnesia mode —
    matching the host example's in-memory state), with the fault slot
    open for a concrete spec OR the grid's shared ``FaultEnvelope``."""
    from ..models import raft

    cfg = raft.RaftConfig(
        num_nodes=dcfg.num_nodes,
        commands=0,
        volatile_state=True,
        history=dcfg.history_ring,
        hist_slots=dcfg.hist_slots,
        faults=faults,
    )
    ecfg = raft.engine_config(
        cfg,
        time_limit_ns=int(dcfg.sim_seconds * 1e9),
        max_steps=60_000,
    )
    return raft.workload(cfg), ecfg


def device_outcomes(
    spec, dcfg: DifferentialConfig = DifferentialConfig()
) -> TierOutcome:
    """Sweep the device raft model over the grid and fold per-seed
    outcomes, checking every decoded election history against
    ElectionSpec. One compiled sweep PER SPEC — the reference the grid
    equality test pins (``TierOutcome``s bit-equal to
    ``device_outcomes_grid``, which the gate itself runs: one compile
    for the whole spec set)."""
    workload, ecfg = _device_raft_cfg(spec, dcfg)
    seeds = np.arange(dcfg.seed0, dcfg.seed0 + dcfg.seeds, dtype=np.int64)
    final = ecore.run_sweep_chunked(
        workload, ecfg, seeds, chunk_size=dcfg.chunk_size
    )
    return _fold_device(final, dcfg)


def device_outcomes_grid(
    specs: Sequence, dcfg: DifferentialConfig = DifferentialConfig()
) -> List[TierOutcome]:
    """All specs' device outcomes from ONE compiled sweep program: the
    spec-as-data grid (engine/faults.py). The K specs share a
    ``FaultEnvelope`` jit key, each rides in as per-lane ``FaultParams``
    over its copy of the seed range, and the whole K x seeds grid runs
    as one launch — the differential gate's device half stops being ~4x
    compile-bound for no reason. Per-seed states (and so the folded
    ``TierOutcome`` integers and report bytes) are bit-identical to
    ``device_outcomes`` per spec."""
    from ..engine.core import lane_slice
    from ..engine.faults import campaign_envelope, grid_params, spec_to_params

    env = campaign_envelope(*specs)
    workload, ecfg = _device_raft_cfg(env, dcfg)
    n = dcfg.seeds
    seeds = np.tile(
        np.arange(dcfg.seed0, dcfg.seed0 + n, dtype=np.int64), len(specs)
    )
    params = grid_params(
        [spec_to_params(spec, env, dcfg.num_nodes) for spec in specs], n
    )
    final = ecore.run_sweep_chunked(
        workload, ecfg, seeds,
        chunk_size=max(dcfg.chunk_size, n), params=params,
    )
    return [
        _fold_device(lane_slice(final, n, k * n), dcfg)
        for k in range(len(specs))
    ]


def _fold_device(final, dcfg: DifferentialConfig) -> TierOutcome:
    """Fold one spec's finished lane block into its ``TierOutcome``."""
    elections = np.asarray(final.wstate.elections)
    commits = np.asarray(final.wstate.commits)
    violation = np.asarray(final.wstate.violation)
    # clipped-coverage lanes: the oracle buffer latched hist_overflow OR
    # the online latch's election ring wrapped (it has no latch of its
    # own — more elections than ring slots means the latch may have
    # missed a duplicate term, which would otherwise surface only as a
    # confusing latch/checker mismatch)
    overflow = np.asarray(final.hist_overflow) | (
        elections > dcfg.history_ring
    )
    espec = ElectionSpec()
    rejects = 0
    mismatches = 0
    for lane, hist in enumerate(decode_sweep(final)):
        bad = not check_history(hist, espec).ok
        rejects += bad
        mismatches += bad != bool(violation[lane])
    return TierOutcome(
        elected_seeds=int((elections > 0).sum()),
        no_leader_seeds=int((elections == 0).sum()),
        violation_seeds=int(violation.sum()),
        elections_total=int(elections.sum()),
        commits_total=int(commits.sum()),
        hist_reject_seeds=rejects,
        hist_mismatch_seeds=mismatches,
        hist_overflow_seeds=int(overflow.sum()),
        overflow_seeds=int(np.asarray(final.overflow).sum()),
    )


def host_outcomes(
    spec, dcfg: DifferentialConfig = DifferentialConfig()
) -> TierOutcome:
    """Run the host-tier raft example once per grid seed under the SAME
    compiled fault schedule (``campaign_seed = seed``, so the fault
    environment matches the device lane of that seed by construction)
    and fold the same outcomes, checking each recorded history.

    ``extend=False``: a matched grid needs matched horizons — the host
    run hard-stops at ``sim_seconds`` exactly like the device lane stops
    at ``time_limit_ns``, instead of extending past a schedule that
    outlives the window (the replay pipeline's default)."""
    import sys

    examples = __file__.rsplit("/", 3)[0] + "/examples"
    if examples not in sys.path:
        sys.path.insert(0, examples)
    import raft_host

    espec = ElectionSpec()
    elected = no_leader = violating = total = 0
    rejects = mismatches = 0
    for seed in range(dcfg.seed0, dcfg.seed0 + dcfg.seeds):
        out = raft_host.run_seed_with_spec(
            seed, spec, seed, n=dcfg.num_nodes, sim_seconds=dcfg.sim_seconds,
            extend=False,
        )
        n_elec = out["leaders_elected"]
        total += n_elec
        elected += n_elec > 0
        no_leader += n_elec == 0
        vio = out["violations"] > 0
        violating += vio
        bad = not check_history(out["history"], espec).ok
        rejects += bad
        mismatches += bad != vio
    return TierOutcome(
        elected_seeds=elected,
        no_leader_seeds=no_leader,
        violation_seeds=violating,
        elections_total=total,
        commits_total=0,
        hist_reject_seeds=rejects,
        hist_mismatch_seeds=mismatches,
        hist_overflow_seeds=0,
    )


def compare(
    dev: TierOutcome, host: TierOutcome, dcfg: DifferentialConfig
) -> dict:
    """Tolerance verdict for one spec: rate deltas in per-mille, plus
    the exact history-agreement requirements."""
    n = dcfg.seeds
    deltas = {
        "elected_pm": abs(_pm(dev.elected_seeds, n) - _pm(host.elected_seeds, n)),
        "no_leader_pm": abs(
            _pm(dev.no_leader_seeds, n) - _pm(host.no_leader_seeds, n)
        ),
        "violation_pm": abs(
            _pm(dev.violation_seeds, n) - _pm(host.violation_seeds, n)
        ),
    }
    ok = (
        deltas["elected_pm"] <= dcfg.tol_elected_pm
        and deltas["no_leader_pm"] <= dcfg.tol_no_leader_pm
        and deltas["violation_pm"] <= dcfg.tol_violation_pm
        # the sequential spec must agree with each tier's own latch,
        # seed by seed — and no device lane may have been truncated
        # (clipped history buffer or overflowed event queue)
        and dev.hist_mismatch_seeds == 0
        and host.hist_mismatch_seeds == 0
        and dev.hist_overflow_seeds == 0
        and dev.overflow_seeds == 0
    )
    return {"deltas": deltas, "pass": ok}


def run_differential(
    specs: Sequence,
    dcfg: DifferentialConfig = DifferentialConfig(),
    report_path: Optional[str] = None,
) -> dict:
    """Run the matched grid for every spec; returns (and optionally
    writes, as canonical JSON) the full report. ``report["pass"]`` is
    the gate verdict: every spec's tolerance check held.

    The device half runs as ONE spec-as-data grid
    (``device_outcomes_grid`` — one compile for the whole spec set,
    bit-equal per spec to ``device_outcomes``)."""
    devs = device_outcomes_grid(specs, dcfg)
    records: List[dict] = []
    for spec, dev in zip(specs, devs):
        host = host_outcomes(spec, dcfg)
        verdict = compare(dev, host, dcfg)
        records.append(
            {
                "spec": spec_to_dict(spec),
                "device": dev._asdict(),
                "host": host._asdict(),
                **verdict,
            }
        )
    report = {
        "config": {
            **dcfg._asdict(),
            # floats are kept out of the canonical encoding
            "sim_seconds": None,
            "sim_ns": int(dcfg.sim_seconds * 1e9),
        },
        "grid": [dcfg.seed0, dcfg.seed0 + dcfg.seeds],
        "specs": records,
        "pass": all(r["pass"] for r in records),
    }
    if report_path is not None:
        with open(report_path, "w") as f:
            f.write(json.dumps(report, sort_keys=True) + "\n")
    return report


def gate_specs() -> List[FaultSpec]:
    """The differential gate's spec set: a clean-ish crash-storm
    baseline plus one spec per gray-failure family (asymmetric
    partitions, fsync-stall + power-fail, clock skew) — every window
    well inside the default 2 s horizon so the full fault environment
    transfers to both tiers."""
    return [
        # crash storm (the amnesia baseline both tiers find violations in)
        FaultSpec(
            crashes=3,
            crash_window_ns=1_200_000_000,
            restart_lo_ns=50_000_000,
            restart_hi_ns=300_000_000,
        ),
        # asymmetric partitions: one-directional link loss
        FaultSpec(
            crashes=1,
            crash_window_ns=1_000_000_000,
            restart_lo_ns=50_000_000,
            restart_hi_ns=300_000_000,
            aparts=2,
            apart_window_ns=1_200_000_000,
            apart_lo_ns=200_000_000,
            apart_hi_ns=600_000_000,
        ),
        # slow disks + power loss: crash-without-sync
        FaultSpec(
            fsync_stalls=2,
            fsync_window_ns=1_200_000_000,
            fsync_lo_ns=300_000_000,
            fsync_hi_ns=800_000_000,
            power_fails=2,
            power_window_ns=1_200_000_000,
            power_lo_ns=50_000_000,
            power_hi_ns=300_000_000,
        ),
        # clock skew: a drifting node's timers stretch 1.5x
        FaultSpec(
            crashes=1,
            crash_window_ns=1_000_000_000,
            restart_lo_ns=50_000_000,
            restart_hi_ns=300_000_000,
            skews=2,
            skew_window_ns=1_200_000_000,
            skew_lo_ns=300_000_000,
            skew_hi_ns=900_000_000,
        ),
    ]
