"""Fleet-scale checked sweeps: device-count curves + sharded campaigns.

This is the measurement/driver layer for the production-scale story —
"a million seeds is one unit of work". Two entry points:

- ``checked_sweep_curve``: run ONE fixed-spec checked sweep (sweep +
  on-device screen + WGL checking, ``oracle.screen.checked_sweep``)
  sharded over each requested device count, warm (compiles excluded
  from the timed region — each mesh size compiles its own programs),
  and report aggregate seeds/s, events/s and time-to-first-bug per
  count PLUS the byte-invariance verdict: the merged summary dict must
  be byte-identical across every mesh size (docs/multichip.md).
- ``sharded_campaign``: the full coverage-guided fault campaign
  (``explore.campaign.run_campaign``) routed through the sharded
  pipelined driver — mutation rounds, retain-on-new-bits, history
  screening + checking, per-round JSONL records — with wall-clock,
  throughput and time-to-first-bug instrumentation that stays OUT of
  the report bytes (the JSONL is byte-identical across mesh sizes and
  wall clocks by the campaign determinism contract).

Wall-clock numbers live only in the returned metrics dicts, never in
the byte-compared reports, so the invariance checks stay meaningful.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def _ttfb_hook(t0: float, box: dict):
    """An ``on_chunk`` callback latching the wall time at which the
    first violating seed became KNOWN (its chunk's host phase merged) —
    the time-to-first-bug clock of a checked sweep or campaign."""

    def on_chunk(*, lo, k, summary) -> None:
        del lo, k
        if box.get("ttfb_s") is None and (
            summary.get("violations", 0) > 0
            or summary.get("hist_violations", 0) > 0
            or summary.get("violating_seeds")
            or summary.get("hist_violating_seeds")
        ):
            box["ttfb_s"] = time.perf_counter() - t0
        box["chunks"] = box.get("chunks", 0) + 1

    return on_chunk


def checked_sweep_curve(
    target,
    base_spec,
    device_counts: Sequence[int] = (1, 2, 4, 8),
    seeds_total: int = 4096,
    seed0: int = 0,
    chunk_per_device: int = 512,
    workers: int = 0,
    warm_seeds: Optional[int] = None,
    devices=None,
) -> dict:
    """Aggregate checked-sweep throughput vs device count, one fixed
    fault spec (``target.build(base_spec)``), same seed range at every
    count. Returns per-count metrics plus ``bytes_invariant`` — the
    merged summary JSON must be identical on every mesh size even
    though the chunk boundaries differ (``chunk_per_device × n_dev``).
    """
    from ..oracle.screen import checked_sweep
    from ..parallel.mesh import seed_mesh

    if devices is None:
        devices = jax.devices()
    if len(devices) < max(device_counts):
        raise ValueError(
            f"need {max(device_counts)} devices, have {len(devices)} "
            "(force the CPU host mesh: madsim_tpu._cpu_mesh_env)"
        )
    workload, ecfg = target.build(base_spec)
    spec = target.hist_spec
    if spec is None:
        raise ValueError(f"target {target.name!r} records no history")
    seeds = jnp.arange(seed0, seed0 + seeds_total, dtype=jnp.int64)
    # warm seeds sit far above the measured range (distinct inputs: the
    # tunneled-device memoization caveat of bench.py applies on TPU)
    warm_base = seed0 + (1 << 30)

    points = []
    blobs = []
    for n_dev in device_counts:
        mesh = seed_mesh(devices[:n_dev])
        # compile everything untimed at the exact chunk shapes — one
        # chunk per mesh size suffices (every later chunk reuses the
        # same programs), so small meshes don't re-sweep the whole
        # measured range in warm-up; a ragged seeds_total additionally
        # needs the tail's limit-masked summary program, so the warm
        # batch carries the same tail (one full + one ragged chunk)
        chunk = chunk_per_device * n_dev
        tail = seeds_total % chunk if seeds_total > chunk else 0
        warm = (
            warm_seeds if warm_seeds is not None
            else (chunk + tail if tail else min(seeds_total, chunk))
        )
        checked_sweep(
            workload, ecfg,
            jnp.arange(warm_base, warm_base + warm, dtype=jnp.int64),
            spec, target.summarize, mesh=mesh,
            chunk_per_device=chunk_per_device, workers=workers,
        )
        box: dict = {}
        t0 = time.perf_counter()
        totals = checked_sweep(
            workload, ecfg, seeds, spec, target.summarize, mesh=mesh,
            chunk_per_device=chunk_per_device, workers=workers,
            on_chunk=_ttfb_hook(t0, box),
        )
        wall = time.perf_counter() - t0
        blob = json.dumps(totals, sort_keys=True)
        blobs.append(blob)
        points.append(
            {
                "devices": n_dev,
                "seeds": seeds_total,
                "chunk_per_device": chunk_per_device,
                "wall_s": round(wall, 2),
                "seeds_per_sec": round(seeds_total / wall, 1),
                "events_per_sec": round(totals["events_total"] / wall, 1),
                "time_to_first_bug_s": (
                    round(box["ttfb_s"], 3) if box.get("ttfb_s") else None
                ),
                "suspects": totals.get("hist_suspects", 0),
                "violations": totals.get("hist_violations", 0),
                "chunks": box.get("chunks", 0),
                "report_sha256": hashlib.sha256(blob.encode()).hexdigest(),
            }
        )
    base = points[0]["seeds_per_sec"]
    for p in points:
        p["speedup"] = round(p["seeds_per_sec"] / base, 2)
    return {
        "metric": "sharded_checked_sweep_curve",
        "target": target.name,
        "workers": workers,
        "curve": points,
        "bytes_invariant": all(b == blobs[0] for b in blobs),
    }


def sharded_campaign(
    target,
    base_spec,
    ccfg,
    n_devices: int,
    report_path: Optional[str] = None,
    ckpt_dir: Optional[str] = None,
    devices=None,
) -> dict:
    """One coverage-guided fault campaign through the sharded pipelined
    driver on an ``n_devices`` mesh; returns throughput metrics (the
    campaign's own JSONL report — byte-identical across mesh sizes —
    goes to ``report_path``)."""
    from ..parallel.mesh import seed_mesh
    from .campaign import run_campaign

    if devices is None:
        devices = jax.devices()
    mesh = seed_mesh(devices[:n_devices])
    box: dict = {}
    t0 = time.perf_counter()
    result = run_campaign(
        target, base_spec, ccfg, report_path=report_path,
        ckpt_dir=ckpt_dir, mesh=mesh, on_chunk=_ttfb_hook(t0, box),
    )
    wall = time.perf_counter() - t0
    rounds = len(result.records)
    seeds_swept = rounds * ccfg.seeds_per_round
    events = sum(r["events_total"] for r in result.records)
    out = {
        "metric": "sharded_campaign",
        "target": target.name,
        "devices": n_devices,
        "rounds": rounds,
        "seeds_per_round": ccfg.seeds_per_round,
        "seeds_swept": seeds_swept,
        "wall_s": round(wall, 2),
        "seeds_per_sec": round(seeds_swept / wall, 1),
        "events_per_sec": round(events / wall, 1),
        "events_total": events,
        "violations_total": sum(r["violations"] for r in result.records),
        "distinct_failures": len(result.failures),
        "coverage_total_bits": (
            result.records[-1]["coverage_total_bits"] if result.records else 0
        ),
        "corpus_size": len(result.corpus),
        "time_to_first_bug_s": (
            round(box["ttfb_s"], 3) if box.get("ttfb_s") else None
        ),
    }
    if report_path is not None:
        with open(report_path, "rb") as f:
            out["report_sha256"] = hashlib.sha256(f.read()).hexdigest()
    return out
