"""The corpus-driven campaign loop: coverage-guided FaultSpec search.

AFL's loop lifted onto the sweep engine: keep a corpus of fault specs,
mutate one (seeded draws — the whole campaign is a pure function of
``campaign_seed``), sweep the candidate over a pinned seed range, and
retain it iff the sweep lights coverage bits no earlier candidate
reached. The coverage signal is the engine's per-seed
(kind x node x transition) bitmap, OR-reduced into each chunk summary
(``coverage_map``) — so guidance costs one extra reduction per chunk,
never a second pass over the sweep.

The seed range is the SAME for every candidate on purpose: coverage and
violation differences between rounds are then attributable to the spec
alone (the swarm-testing idiom — vary the fault mix, not the luck).

Violating seeds surface in each round's record; chain them into
``triage`` (dedupe by fingerprint) and ``shrink`` (minimal reproducing
schedule). Long campaigns resume through the existing
``engine/checkpoint.py`` machinery: with ``ckpt_dir`` set, every round's
sweep checkpoints per-chunk summaries, and a restarted campaign (same
config — candidates regenerate identically from the campaign seed) skips
every chunk already on disk.

The JSONL report is deterministic BY CONTRACT: records carry no wall
times or absolute paths, and keys are sorted — two runs of one campaign
seed produce byte-identical reports (``scripts/check_determinism.sh``
gates this).
"""

from __future__ import annotations

import json
import os
import random
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..engine.faults import (
    FaultEnvelope,
    FaultSpec,
    FixedFaults,
    campaign_envelope,
    prob_to_q32,
    spec_to_params,
    tile_params,
)
from ..models._common import coverage_bit_count
from .targets import Target

# mutation clamps: windows/durations stay inside a sane explore envelope
_MIN_NS = 10_000_000  # 10 ms
_MAX_NS = 8_000_000_000  # 8 s
_MAX_PHASES = 6  # per category
# the gray-failure families (aparts/fsync_stalls/power_fails/skews,
# engine/faults.py) are first-class mutation targets: coverage-guided
# search explores one-directional partitions, crash-without-sync and
# clock drift the same way it explores clean crashes
_COUNT_FIELDS = (
    "crashes", "partitions", "spikes", "losses", "pauses",
    "aparts", "fsync_stalls", "power_fails", "skews",
)
_WINDOW_FIELDS = (
    "crash_window_ns",
    "part_window_ns",
    "spike_window_ns",
    "loss_window_ns",
    "pause_window_ns",
    "apart_window_ns",
    "fsync_window_ns",
    "power_window_ns",
    "skew_window_ns",
)
_DUR_FIELDS = (
    ("restart_lo_ns", "restart_hi_ns"),
    ("part_lo_ns", "part_hi_ns"),
    ("spike_dur_lo_ns", "spike_dur_hi_ns"),
    ("loss_dur_lo_ns", "loss_dur_hi_ns"),
    ("pause_lo_ns", "pause_hi_ns"),
    ("apart_lo_ns", "apart_hi_ns"),
    ("fsync_lo_ns", "fsync_hi_ns"),
    ("power_lo_ns", "power_hi_ns"),
    ("skew_lo_ns", "skew_hi_ns"),
)
# scale factors as exact integer ratios (float scaling would make the
# mutated spec depend on platform rounding)
_SCALES = ((1, 2), (2, 3), (3, 2), (2, 1))


class CampaignConfig(NamedTuple):
    """Static campaign parameters (hashable, reprs stably).

    ``check_workers`` fans the history checker of a screened target over
    a process pool (``oracle.check.check_histories``) — wall-clock only,
    never a report byte, so it is safe to vary per machine. The report
    HEADER records the whole config, so compare reports only across runs
    of one config (the determinism gates do)."""

    rounds: int = 12
    seeds_per_round: int = 256
    seed0: int = 0  # the pinned sweep seed range is [seed0, seed0 + n)
    campaign_seed: int = 0  # drives parent choice + mutations
    chunk_size: int = 16384
    mutations_hi: int = 2  # 1..hi mutations per candidate
    stop_after_failures: int = 0  # stop once this many seeds violate (0 = never)
    max_recorded_seeds: int = 8  # violating seeds listed per round record
    check_workers: int = 0  # process-pool size for history checking
    # candidates swept per device launch (spec-as-data only): batch > 1
    # generates that many candidates from the CURRENT corpus snapshot
    # and sweeps them as ONE (candidate x seed) grid — retention still
    # applies in candidate order, but parents within a block are drawn
    # before the block's results land, so batch changes the (still
    # deterministic) campaign trajectory; batch=1 is the exact serial
    # semantics the byte-identity gates pin
    batch: int = 1
    # compute-allocation policy: "uniform" is this module's classic
    # corpus loop; "bandit" routes run_campaign through the
    # self-steering scheduler (explore/steer.py, docs/steering.md) —
    # family-partitioned candidates, UCB allocation, early-kill,
    # budget escalation, and a journaled deterministic decision trace
    scheduler: str = "uniform"


class CampaignResult(NamedTuple):
    corpus: List[object]  # retained specs, oldest first (corpus[0] = base)
    records: List[dict]  # one per executed round (the JSONL lines)
    failures: List[Tuple[object, int]]  # (spec, violating seed), dedup order
    coverage_map: List[int]  # global union bitmap words


def _clamp_ns(v: int) -> int:
    return max(_MIN_NS, min(_MAX_NS, int(v)))


def _scale(rng: random.Random, v: int) -> int:
    num, den = rng.choice(_SCALES)
    return v * num // den


def mutate_spec(
    spec: FaultSpec, rng: random.Random, mutations_hi: int = 2
) -> FaultSpec:
    """One candidate: 1..``mutations_hi`` seeded mutations of ``spec``.

    Mutations are the swarm-testing moves the issue names — add/drop a
    storm or partition phase, widen/narrow a campaign window, scale
    restart/burst durations and rates — all integer arithmetic, so a
    mutated spec is identical across platforms for one rng state."""
    for _ in range(rng.randint(1, max(1, mutations_hi))):
        # weighted op choice: phase-count changes are the coarse knob
        # that opens whole fault categories, so they get extra weight
        op = rng.choice(
            ("add", "add", "add", "drop", "window", "window", "dur", "rate")
        )
        if op == "add":
            f = rng.choice(_COUNT_FIELDS)
            spec = spec._replace(**{f: min(getattr(spec, f) + 1, _MAX_PHASES)})
        elif op == "drop":
            live = [f for f in _COUNT_FIELDS if getattr(spec, f) > 0]
            if live:
                f = rng.choice(live)
                spec = spec._replace(**{f: getattr(spec, f) - 1})
        elif op == "window":
            f = rng.choice(_WINDOW_FIELDS)
            spec = spec._replace(**{f: _clamp_ns(_scale(rng, getattr(spec, f)))})
        elif op == "dur":
            lo_f, hi_f = rng.choice(_DUR_FIELDS)
            num, den = rng.choice(_SCALES)
            lo = _clamp_ns(getattr(spec, lo_f) * num // den)
            hi = _clamp_ns(getattr(spec, hi_f) * num // den)
            spec = spec._replace(**{lo_f: lo, hi_f: max(hi, lo + 1)})
        else:  # rate: burst loss probability / spike latency range
            if rng.random() < 0.5:
                q = _scale(rng, spec.burst_loss_q32)
                spec = spec._replace(
                    burst_loss_q32=max(
                        prob_to_q32(0.05), min(prob_to_q32(0.95), q)
                    )
                )
            else:
                num, den = rng.choice(_SCALES)
                lo = _clamp_ns(spec.spike_lat_lo_ns * num // den)
                hi = _clamp_ns(spec.spike_lat_hi_ns * num // den)
                spec = spec._replace(
                    spike_lat_lo_ns=lo, spike_lat_hi_ns=max(hi, lo + 1)
                )
    return spec


def spec_to_dict(spec) -> dict:
    """JSON-stable encoding of a ``FaultSpec`` or ``FixedFaults``."""
    if isinstance(spec, FixedFaults):
        return {
            "type": "FixedFaults",
            "events": [[t, a, v] for t, a, v in spec.events],
            "spike_lat_lo_ns": spec.spike_lat_lo_ns,
            "spike_lat_hi_ns": spec.spike_lat_hi_ns,
            "burst_loss_q32": spec.burst_loss_q32,
            "skew_num": spec.skew_num,
            "skew_den": spec.skew_den,
        }
    d = {"type": "FaultSpec"}
    for f, v in zip(spec._fields, spec):
        d[f] = list(v) if isinstance(v, tuple) else v
    return d


def spec_from_dict(d: dict):
    """Inverse of ``spec_to_dict`` (report lines back to runnable specs)."""
    d = dict(d)
    kind = d.pop("type")
    if kind == "FixedFaults":
        defaults = FixedFaults()
        return FixedFaults(
            events=tuple((int(t), str(a), int(v)) for t, a, v in d["events"]),
            spike_lat_lo_ns=int(d["spike_lat_lo_ns"]),
            spike_lat_hi_ns=int(d["spike_lat_hi_ns"]),
            burst_loss_q32=int(d["burst_loss_q32"]),
            # .get: report lines written before the gray grammar lack them
            skew_num=int(d.get("skew_num", defaults.skew_num)),
            skew_den=int(d.get("skew_den", defaults.skew_den)),
        )
    if kind != "FaultSpec":
        raise ValueError(f"unknown spec encoding {kind!r}")
    return FaultSpec(
        **{f: tuple(v) if isinstance(v, list) else v for f, v in d.items()}
    )


def target_envelope(target: Target, *specs, fixed: int = 0) -> FaultEnvelope:
    """The campaign envelope for ``target``: covers every given spec
    plus the mutator's ``_MAX_PHASES`` clamp, so every candidate any
    campaign round can generate fits ONE compiled sweep program
    (docs/explore.md "The campaign envelope")."""
    return campaign_envelope(*specs, mutation_cap=_MAX_PHASES, fixed=fixed)


def _candidate_params(target: Target, spec, envelope: FaultEnvelope, lanes: int):
    """Per-lane FaultParams for one candidate over a ``lanes``-seed
    range (host numpy — validation is eager, tracing sees arrays)."""
    return tile_params(
        spec_to_params(spec, envelope, target.num_nodes), lanes
    )


def _sweep_candidate(
    target: Target,
    spec,
    ccfg: CampaignConfig,
    round_dir: Optional[str],
    mesh=None,
    on_chunk=None,
    envelope: Optional[FaultEnvelope] = None,
    telemetry=None,
) -> dict:
    """Run one candidate's sweep over the pinned seed range; returns the
    merged summary dict (coverage_map + violating_seeds included).
    ``mesh`` shards the whole pipeline (sweep, screen, summary) over the
    device mesh; the summary bytes are mesh-size-invariant.

    With ``envelope`` the candidate rides in as spec-as-data: the
    workload is built from the ENVELOPE (the jit cache key) and the
    concrete spec becomes per-lane ``FaultParams``, so every candidate
    after the first reuses the one compiled sweep/summary pipeline —
    the compile-per-candidate tax this module used to pay per round is
    gone. The summary bytes are identical either way (the padded
    schedule derivation is bit-exact; tests/test_fault_params.py)."""
    if envelope is None:
        workload, ecfg = target.build(spec)
        params = None
    else:
        workload, ecfg = target.build(envelope)
        params = _candidate_params(
            target, spec, envelope, ccfg.seeds_per_round
        )
    if workload.cover is None or workload.cover_bits == 0:
        raise ValueError(
            f"target {target.name!r} workload defines no coverage signal "
            "(Workload.cover/cover_bits); without it the campaign loop "
            "degenerates to unguided mutation of the base spec"
        )
    seeds = np.arange(
        ccfg.seed0, ccfg.seed0 + ccfg.seeds_per_round, dtype=np.int64
    )
    # never let the chunk granule exceed the round budget: the chunk
    # drivers pad a ragged chunk to the full chunk_size for program
    # reuse, which would blow a 128-seed explore round up to a
    # 16k-lane sweep
    chunk_size = min(ccfg.chunk_size, ccfg.seeds_per_round)

    # history targets hand the pipeline their device screen so it is
    # enqueued right behind each chunk's sweep; launching it from the
    # host phase instead would queue it behind the NEXT chunk's sweep
    # on the single device stream and serialize the whole pipeline
    screen_fn = None
    if target.hist_spec is not None:
        from ..oracle.screen import screen_for, screen_sweep

        if screen_for(target.hist_spec) is not None:
            def screen_fn(final):
                return screen_sweep(final, target.hist_spec, mesh=mesh)

    def host_work(final, *, lo, n, seeds, suspect, summary) -> dict:
        # the expensive half — checking may run the WGL search per
        # suspect lane — runs in the pipeline's overlapped host phase,
        # concurrent with the device sweep of the next chunk
        del lo, n, seeds
        if suspect is not None:
            # consume the mask the device phase already computed
            # (identical seeds to target.violating, by conservatism)
            from ..oracle.check import violating_seeds

            vio = violating_seeds(
                final, target.hist_spec, screen=lambda _f: suspect,
                workers=ccfg.check_workers,
            )
        else:
            vio = np.asarray(target.violating(final))
        out = {
            "violating_seeds": [int(x) for x in vio[: ccfg.max_recorded_seeds]]
        }
        if "violations" not in summary:
            # the uncapped truth, so the round record never under-reports
            # for a target whose summary lacks the key (sums per chunk)
            out["violations"] = int(vio.size)
        return out

    # one driver for both legs: with round_dir the per-chunk summaries
    # checkpoint (a restarted campaign regenerates the same candidate —
    # pure function of campaign_seed — and skips finished chunks);
    # without it the pipeline still overlaps checking with sweeping.
    # A mesh lifts the same pipeline onto all devices — sharded sweep +
    # screen + summary, identical report bytes on any mesh size.
    if mesh is not None:
        from ..parallel.mesh import run_sweep_sharded_pipelined

        return run_sweep_sharded_pipelined(
            workload, ecfg, seeds, target.summarize, mesh=mesh,
            host_work=host_work, screen=screen_fn, chunk_size=chunk_size,
            ckpt_dir=round_dir, on_chunk=on_chunk, params=params,
            telemetry=telemetry,
        )
    from ..engine.checkpoint import run_sweep_pipelined

    return run_sweep_pipelined(
        workload, ecfg, seeds, target.summarize, host_work=host_work,
        screen=screen_fn, chunk_size=chunk_size, ckpt_dir=round_dir,
        on_chunk=on_chunk, params=params, telemetry=telemetry,
    )


def sweep_candidate_grid(
    target: Target,
    specs: Sequence,
    ccfg: CampaignConfig,
    envelope: FaultEnvelope,
    mesh=None,
    telemetry=None,
) -> List[dict]:
    """Sweep K candidates as ONE (candidate x seed) device grid and
    return each candidate's summary dict — identical values to K calls
    of ``_sweep_candidate`` over the same pinned seed range.

    This is the batched half of the spec-as-data tentpole, run through
    the persistent streaming service (``engine.stream.stream_sweep``,
    docs/streaming.md): the K * seeds_per_round (candidate x seed) work
    items feed the lane pool's refill queue instead of chunk boundaries
    — a candidate whose seeds all finish early releases its lanes to
    the next candidate mid-flight, so the pool stays at constant
    occupancy across the whole grid. The virtual chunk granule is ONE
    candidate (``chunk_size=seeds_per_round``), so each flushed chunk
    summary IS that candidate's summary — identical values to K calls
    of ``_sweep_candidate``, and refill-schedule-invariant by the stream
    contract. One compiled round/refill/summary program serves every
    candidate: a warmed grid runs with ZERO XLA compilations regardless
    of K."""
    from ..engine.faults import grid_params
    from ..engine.stream import stream_sweep

    workload, ecfg = target.build(envelope)
    if workload.cover is None or workload.cover_bits == 0:
        raise ValueError(
            f"target {target.name!r} workload defines no coverage signal "
            "(Workload.cover/cover_bits); without it the campaign loop "
            "degenerates to unguided mutation of the base spec"
        )
    s = ccfg.seeds_per_round
    k = len(specs)
    seeds = np.tile(
        np.arange(ccfg.seed0, ccfg.seed0 + s, dtype=np.int64), k
    )
    params = grid_params(
        [spec_to_params(spec, envelope, target.num_nodes) for spec in specs],
        s,
    )
    multiple = 1 if mesh is None else int(mesh.devices.size)
    # the pool holds the same working set the chunked grid ran — the
    # occupancy-knee granule, rounded up to mesh divisibility like every
    # other sharded driver (stream_sweep caps it to the total)
    pool = -(-max(ccfg.chunk_size, s) // multiple) * multiple

    # the serial pipeline's screen/host-work machinery, per candidate
    # chunk: the device screen (run once per retirement cohort) clears
    # the boring lanes and the WGL checker fans the suspects over the
    # process pool — mirrors _sweep_candidate exactly, which is what
    # keeps grid summaries byte-equal to serial rounds
    screen_fn = None
    if target.hist_spec is not None:
        from ..oracle.screen import screen_for, screen_sweep

        if screen_for(target.hist_spec) is not None:
            def screen_fn(final):
                return screen_sweep(final, target.hist_spec, mesh=mesh)

    def host_work(final, *, lo, n, seeds, suspect, summary) -> dict:
        del lo, n, seeds
        if suspect is not None:
            from ..oracle.check import violating_seeds

            vio = violating_seeds(
                final, target.hist_spec, screen=lambda _f: suspect,
                workers=ccfg.check_workers,
            )
        else:
            vio = np.asarray(target.violating(final))
        out = {
            "violating_seeds": [int(x) for x in vio[: ccfg.max_recorded_seeds]]
        }
        if "violations" not in summary:
            out["violations"] = int(vio.size)
        return out

    summaries: List[dict] = []
    stream_sweep(
        workload, ecfg, seeds, target.summarize,
        params=params, chunk_size=s, pool_size=pool,
        host_work=host_work, screen=screen_fn, mesh=mesh,
        on_chunk=lambda *, lo, k, summary: summaries.append(summary),
        telemetry=telemetry,
    )
    return summaries


def run_campaign(
    target: Target,
    base_spec: FaultSpec,
    ccfg: CampaignConfig = CampaignConfig(),
    report_path: Optional[str] = None,
    ckpt_dir: Optional[str] = None,
    mesh=None,
    on_chunk=None,
    telemetry=None,
    steer_cfg=None,
    trace_path: Optional[str] = None,
    history: bool = False,
) -> CampaignResult:
    """Drive the find loop: ``rounds`` candidates from ``base_spec``.

    Round 0 sweeps the base spec itself (the bland starting point);
    every later round mutates a uniformly drawn corpus parent. A
    candidate joins the corpus iff its sweep lit coverage bits the
    global union lacked. Stops early once ``stop_after_failures``
    violating seeds have surfaced (0 = run every round).

    ``report_path`` writes one JSONL record per executed round (plus a
    header) — deterministic bytes per campaign seed. ``ckpt_dir`` makes
    each round's sweep preemption-safe via per-chunk summary checkpoints
    (``engine/checkpoint.py``).

    ``mesh`` runs every round's checked sweep sharded over the device
    mesh (``parallel.run_sweep_sharded_pipelined``) — the million-seed
    configuration: per-round seed ranges in the tens of thousands, the
    whole campaign one unit of work spanning all chips, and the JSONL
    report BYTE-IDENTICAL to the same campaign on any other mesh size
    (docs/multichip.md). ``on_chunk(lo=, k=, summary=)`` fires per
    merged chunk (time-to-first-violation instrumentation).

    Spec-as-data is the only sweep path (the pre-refactor
    compile-per-candidate path and its ``MADSIM_CAMPAIGN_LEGACY``
    toggle are gone): the campaign envelope (``target_envelope``) is
    derived ONCE from the base spec + mutator clamps, the workload
    compiles once for the envelope shape, and every candidate rides in
    as per-lane ``FaultParams`` — a warmed campaign runs its remaining
    rounds with zero XLA compilations (``make explore-smoke``
    counter-asserts this).
    ``ccfg.batch > 1`` additionally sweeps that many candidates per
    device launch as one (candidate x seed) grid
    (``sweep_candidate_grid``); grid blocks skip per-round sweep
    checkpointing and per-chunk ``on_chunk`` callbacks (``ckpt_dir``
    and ``on_chunk`` apply to serial rounds only — a grid block is one
    launch, not a chunk stream).

    ``telemetry`` (``obs.Telemetry`` or None) rides through to every
    round's sweep driver and adds the campaign view: candidates/s,
    corpus size and global coverage-bit gauges, unique-vs-duplicate
    failure counters (the dedup hit rate), time-to-first-bug, and one
    journal record per round. Strictly OUT-OF-BAND — the JSONL report
    bytes are identical with telemetry on or off (the determinism gate
    runs both ways).

    ``ccfg.scheduler="bandit"`` hands the whole loop to the
    self-steering scheduler (``explore.steer.run_steered``,
    docs/steering.md): family-partitioned candidates, UCB compute
    allocation, early-kill and budget escalation, with the decision
    trace written to ``trace_path`` (deterministic bytes) and mirrored
    into the journal as ``steer_round`` events. ``steer_cfg`` (a
    ``steer.SteerConfig``) tunes the policy, ``history=True`` routes
    the steered loop's in-flight triage through the history oracle
    (required for targets whose violations only the WGL checker sees);
    ``ckpt_dir``/``on_chunk`` apply to the classic uniform loop only."""
    import time as _time

    if ccfg.scheduler not in ("uniform", "bandit"):
        raise ValueError(f"unknown scheduler {ccfg.scheduler!r}")
    if ccfg.scheduler == "bandit":
        from .steer import run_steered

        return run_steered(
            target, base_spec, ccfg, steer_cfg, history=history,
            report_path=report_path, trace_path=trace_path,
            mesh=mesh, telemetry=telemetry,
        ).campaign_result()

    rng = random.Random(ccfg.campaign_seed)
    corpus: List[object] = []
    records: List[dict] = []
    failures: List[Tuple[object, int]] = []
    seen_failures = set()
    global_map: List[int] = []
    t0_wall = _time.perf_counter()
    vio_seen = vio_unique = 0  # dedup-hit-rate inputs (telemetry only)
    first_bug_recorded = False

    header = {
        "campaign": ccfg._asdict(),
        "target": target.name,
        "base_spec": spec_to_dict(base_spec),
    }

    envelope = target_envelope(target, base_spec)

    def gen(r: int):
        """Candidate r: the base spec for round 0, a seeded mutation of
        a drawn corpus parent after. In batch mode the block's
        candidates draw against the corpus SNAPSHOT — retention from
        earlier rounds of the block hasn't landed, so both the parent
        draws and the rng stream diverge from the serial trajectory
        (deterministically; see ``CampaignConfig.batch``)."""
        if r == 0:
            return None, base_spec
        parent = rng.randrange(len(corpus)) if corpus else None
        return parent, mutate_spec(
            corpus[parent] if parent is not None else base_spec,
            rng,
            ccfg.mutations_hi,
        )

    def absorb(r: int, parent, spec, summary: dict) -> bool:
        """Fold one candidate's summary into corpus/coverage/records;
        True = the failure budget is spent (stop the campaign)."""
        nonlocal global_map, vio_seen, vio_unique, first_bug_recorded
        cand_map = [int(w) for w in summary.get("coverage_map", [])]
        if len(global_map) < len(cand_map):
            global_map = global_map + [0] * (len(cand_map) - len(global_map))
        new_bits = sum(
            (c & ~g).bit_count() for c, g in zip(cand_map, global_map)
        )
        retained = r == 0 or new_bits > 0
        if retained:
            corpus.append(spec)
            global_map = [g | c for g, c in zip(global_map, cand_map)]

        vio = summary.get("violating_seeds", [])[: ccfg.max_recorded_seeds]
        fresh = 0
        for seed in vio:
            key = (spec, seed)
            if key not in seen_failures:
                seen_failures.add(key)
                failures.append((spec, seed))
                fresh += 1

        records.append(
            {
                "round": r,
                "parent": parent,
                "spec": spec_to_dict(spec),
                "seeds": [ccfg.seed0, ccfg.seed0 + ccfg.seeds_per_round],
                "violations": int(summary["violations"]),
                "violating_seeds": vio,
                "coverage_bits": coverage_bit_count(cand_map),
                "new_bits": new_bits,
                "coverage_total_bits": coverage_bit_count(global_map),
                "retained": retained,
                "events_total": int(summary.get("events_total", 0)),
            }
        )
        if telemetry is not None:
            elapsed = _time.perf_counter() - t0_wall
            vio_seen += len(vio)
            vio_unique += fresh
            telemetry.count(
                "campaign_candidates_total", help="candidates swept"
            )
            telemetry.gauge(
                "campaign_candidates_per_s",
                (r + 1) / max(elapsed, 1e-9),
                help="campaign throughput since start",
            )
            telemetry.gauge(
                "campaign_corpus_size", len(corpus),
                help="retained specs in the corpus",
            )
            telemetry.gauge(
                "campaign_coverage_bits", coverage_bit_count(global_map),
                help="global coverage union population count",
            )
            if fresh:
                telemetry.count(
                    "campaign_failures_total", fresh,
                    help="unique (spec, seed) failures",
                )
            if len(vio) - fresh:
                telemetry.count(
                    "campaign_failure_dupes_total", len(vio) - fresh,
                    help="violating seeds already in the dedup set",
                )
            if vio_seen:
                telemetry.gauge(
                    "campaign_dedup_hit_rate",
                    (vio_seen - vio_unique) / vio_seen,
                    help="fraction of observed failures already known",
                )
            if failures and not first_bug_recorded:
                first_bug_recorded = True
                telemetry.gauge(
                    "campaign_time_to_first_bug_seconds", elapsed,
                    help="wall time from campaign start to first failure",
                )
            telemetry.event(
                "round", round=r, retained=bool(retained),
                new_bits=int(new_bits), violations=len(vio),
                corpus=len(corpus),
            )
        return bool(
            ccfg.stop_after_failures
            and len(failures) >= ccfg.stop_after_failures
        )

    stop = False
    r = 0
    while r < ccfg.rounds and not stop:
        if ccfg.batch > 1:
            block = [
                gen(r + i) for i in range(min(ccfg.batch, ccfg.rounds - r))
            ]
            # a ragged tail block is padded back to the full batch width
            # (repeat the last candidate, discard its extra summaries):
            # the grid's lane count is a jit shape, and a one-off tail
            # shape would pay a fresh sweep compile for nothing
            specs = [spec for _, spec in block]
            specs += [specs[-1]] * (ccfg.batch - len(block))
            summaries = sweep_candidate_grid(
                target, specs, ccfg, envelope, mesh=mesh,
                telemetry=telemetry,
            )[: len(block)]
            for (parent, spec), summary in zip(block, summaries):
                stop = absorb(r, parent, spec, summary)
                r += 1
                if stop:
                    break
        else:
            parent, spec = gen(r)
            round_dir = (
                os.path.join(ckpt_dir, f"round_{r:04d}") if ckpt_dir else None
            )
            summary = _sweep_candidate(
                target, spec, ccfg, round_dir, mesh=mesh, on_chunk=on_chunk,
                envelope=envelope, telemetry=telemetry,
            )
            stop = absorb(r, parent, spec, summary)
            r += 1

    if report_path is not None:
        with open(report_path, "w") as f:
            f.write(json.dumps(header, sort_keys=True) + "\n")
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")

    return CampaignResult(
        corpus=corpus,
        records=records,
        failures=failures,
        coverage_map=global_map,
    )
